"""Self-tests for ``apex_tpu.analysis`` — and the tier-1 rider that
keeps the repo clean.

Layout: per-rule positive/negative fixture pairs (the positives for
APX102/302/401 are the literal pre-fix ADVICE r5 snippets from
bench.py:876, ops/fused_ce_pallas.py:58, and models/gpt.py:447 — the
findings this subsystem exists to scale), engine unit tests (traced
index, axis-registry discovery, baseline), and the repo-wide clean
check ``python -m apex_tpu.analysis apex_tpu bench.py`` rides on.
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from apex_tpu.analysis import (
    DEFAULT_RULES,
    BaselineError,
    analyze_file,
    analyze_paths,
    apply_baseline,
    discover_axis_registry,
    load_baseline,
    write_baseline,
)
from apex_tpu.analysis import sarif
from apex_tpu.analysis.rules_collectives import (
    CollectiveAxisOutsideShardMapNest,
    CollectiveAxisUnboundUnderJit,
    CollectiveOutsideSpmdContext,
    CollectiveTupleAxisUnbound,
    UnknownCollectiveAxis,
)
from apex_tpu.analysis.rules_divergence import (
    TaintedEngineDispatchDivergence,
    TaintedPredicateGuardsCollective,
    TaintedValueShapesCompiledProgram,
)
from apex_tpu.analysis.rules_donation import DonatedBufferReuse
from apex_tpu.analysis.rules_sharding import (
    DonatedShardingMismatch,
    ShardingSpecAxisUnbound,
    ShardingSpecRankMismatch,
)
from apex_tpu.analysis.rules_host_sync import (
    BlockingHostSyncInStepLoop, UnseamedDispatchTiming,
)
from apex_tpu.analysis.rules_inference import KvPoolScatterBypassesSeam
from apex_tpu.analysis.rules_io import NonAtomicCheckpointWrite
from apex_tpu.analysis.rules_resilience import (
    RetryWithoutBackoff, SwallowedExceptionInRecoveryPath,
)
from apex_tpu.analysis.rules_precision import (
    KvCacheReadDtypeMismatch,
    PageTableGatherUnclamped,
    QuantizedSyncStateDtype,
    Fp32ConstantInBf16Path,
    ScratchAccumDtypeMismatch,
    UnclampedTakeAlongAxis,
)
from apex_tpu.analysis.rules_threading import (
    BlockingCallUnderContendedLock,
    LockOrderInversion,
    SharedMutationWithoutLock,
)
from apex_tpu.analysis.rules_tiling import (
    BlockShapeTilingViolation,
    BlockSpecIndexMapArity,
    HardCodedSublaneAlignment,
    VmemFootprintOverBudget,
)
from apex_tpu.analysis.rules_trace import (
    ProcessGlobalEnvMutation,
    TraceTimeHostStateRead,
)

REPO = Path(__file__).resolve().parent.parent
AXES = frozenset({"dp", "pp", "cp", "tp", "dcn"})


def run(src, tmp_path, rules, axes=AXES):
    p = tmp_path / "fixture.py"
    p.write_text(textwrap.dedent(src))
    return analyze_file(str(p), list(rules), set(axes))


def rule_ids(findings):
    return [f.rule for f in findings]


# ------------------------------------------------- APX101 trace-time reads
class TestTraceTimeHostStateRead:
    def test_positive_env_read_via_helper_under_jit(self, tmp_path):
        """The fused_ce.py shape: the env read lives in a helper that a
        jitted function calls — caught through the module call graph."""
        got = run("""
            import os
            import jax

            def _mode():
                return os.environ.get("APEX_TPU_FUSED_CE_PALLAS", "auto")

            @jax.jit
            def f(x):
                if _mode() == "on":
                    return x * 2
                return x
            """, tmp_path, [TraceTimeHostStateRead()])
        assert rule_ids(got) == ["APX101"]
        assert got[0].symbol == "_mode"
        assert "frozen into the first trace" in got[0].message

    def test_positive_clock_in_pallas_kernel_via_partial_alias(self, tmp_path):
        """The fused_ce_pallas shape: kernel bound with functools.partial
        into a local name, then handed to pl.pallas_call."""
        got = run("""
            import functools
            import time
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref, *, nv):
                o_ref[:] = x_ref[:] * time.time()

            def launch(x, nv):
                kernel = functools.partial(_kernel, nv=nv)
                return pl.pallas_call(kernel, grid=(nv,))(x)
            """, tmp_path, [TraceTimeHostStateRead()])
        assert rule_ids(got) == ["APX101"]
        assert "wall clock" in got[0].message

    def test_positive_host_rng_under_defvjp(self, tmp_path):
        got = run("""
            import numpy as np
            import jax

            @jax.custom_vjp
            def op(x):
                return x

            def _fwd(x):
                return x, None

            def _bwd(res, g):
                return (g * np.random.rand(),)

            op.defvjp(_fwd, _bwd)
            """, tmp_path, [TraceTimeHostStateRead()])
        assert rule_ids(got) == ["APX101"]
        assert "host RNG" in got[0].message

    def test_positive_bare_environ_get_and_lambda(self, tmp_path):
        """Blind spots closed in review: the bare-import spelling
        (`from os import environ`) and a hazard inside `jax.jit(lambda
        ...)` (lambdas have no FunctionDef to index)."""
        got = run("""
            from os import environ, getenv

            import jax

            @jax.jit
            def f(x):
                return x if environ.get("FLAG") else -x

            g = jax.jit(lambda x: x if getenv("FLAG") else -x)
            """, tmp_path, [TraceTimeHostStateRead()])
        assert rule_ids(got) == ["APX101", "APX101"]

    def test_positive_lambda_calling_local_helper(self, tmp_path):
        got = run("""
            import os

            import jax

            def _mode():
                return os.environ.get("FLAG", "auto")

            g = jax.jit(lambda x: x * 2 if _mode() == "on" else x)
            """, tmp_path, [TraceTimeHostStateRead()])
        assert rule_ids(got) == ["APX101"]
        assert got[0].symbol == "_mode"

    def test_negative_host_side_read(self, tmp_path):
        """Same reads, no trace context: host-side config code is fine."""
        got = run("""
            import os
            import time

            def pick_backend():
                return os.environ.get("BACKEND", "tpu")

            def stamp():
                return time.time()
            """, tmp_path, [TraceTimeHostStateRead()])
        assert got == []

    def test_negative_module_level_read(self, tmp_path):
        got = run("""
            import os
            import jax

            _FLAG = os.environ.get("FLAG", "1")

            @jax.jit
            def f(x):
                return x + 1
            """, tmp_path, [TraceTimeHostStateRead()])
        assert got == []


# --------------------------------------------- APX102 env-var mutation
class TestProcessGlobalEnvMutation:
    def test_positive_advice_r5_bench_py_876(self, tmp_path):
        """The literal pre-fix bench.py:876 shape (ADVICE r5): flip the
        env var, rerun, restore — invisible to already-traced jits."""
        got = run("""
            import os

            def bench_gpt_fce(bench_gpt, roof):
                os.environ["APEX_TPU_FUSED_CE_PALLAS"] = "0"
                try:
                    r = bench_gpt(12, 768, 12, 1024, 8, roof, fused_ce=True)
                finally:
                    os.environ.pop("APEX_TPU_FUSED_CE_PALLAS", None)
                return r
            """, tmp_path, [ProcessGlobalEnvMutation()])
        assert rule_ids(got) == ["APX102", "APX102"]
        assert "os.environ[...] assignment" in got[0].message
        assert "os.environ.pop" in got[1].message

    def test_negative_module_level_startup_config(self, tmp_path):
        """Startup env config before anything traces is the accepted
        idiom — only mid-process mutation inside functions is flagged."""
        got = run("""
            import os

            os.environ.setdefault("JAX_PLATFORMS", "cpu")
            os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
            """, tmp_path, [ProcessGlobalEnvMutation()])
        assert got == []


# --------------------------------------------- APX103 donated-buffer reuse
class TestDonatedBufferReuse:
    def test_positive_read_after_donate_new_name(self, tmp_path):
        """The classic shape: the step's result is bound to NEW names
        while the stale donated name is read for logging afterwards —
        a no-op on CPU, garbage on TPU (ROADMAP donation/aliasing
        open item)."""
        got = run("""
            import jax

            def make(step_fn):
                return jax.jit(step_fn, donate_argnums=(0, 1))

            step = jax.jit(lambda p, s: (p, s), donate_argnums=(0, 1))

            def train(params, state, norm_of):
                new_params, new_state = step(params, state)
                norm = norm_of(params)
                return new_params, new_state, norm
            """, tmp_path, [DonatedBufferReuse()])
        assert rule_ids(got) == ["APX103"]
        assert "`params` is donated" in got[0].message
        assert "rebound" in got[0].message

    def test_positive_partial_decorator_spelling(self, tmp_path):
        """@partial(jax.jit, donate_argnums=...) defs are tracked by
        their function name (the bench.py step idiom)."""
        got = run("""
            from functools import partial

            import jax

            @partial(jax.jit, donate_argnums=(0,))
            def step(params, grads):
                return params

            def train(params, grads, save):
                out = step(params, grads)
                save(params)
                return out
            """, tmp_path, [DonatedBufferReuse()])
        assert rule_ids(got) == ["APX103"]

    def test_negative_early_return_branches(self, tmp_path):
        """A donating call that is itself a `return` value: nothing
        later in the function can run after it in the same invocation,
        so a read on the sibling branch (the early-return shape) is
        provably safe and must stay silent."""
        got = run("""
            import jax

            step = jax.jit(lambda p, s: (p, s), donate_argnums=(0,))

            def train(params, state, cond, norm_of):
                if cond:
                    return step(params, state)
                return norm_of(params)
            """, tmp_path, [DonatedBufferReuse()])
        assert got == []

    def test_negative_sibling_branch_read(self, tmp_path):
        """Assign-in-branch sibling of the early-return shape: the
        else-arm read can never execute after the if-arm's donating
        call in one invocation — silent."""
        got = run("""
            import jax

            step = jax.jit(lambda p: p, donate_argnums=(0,))

            def train(params, cond, f):
                if cond:
                    out = step(params)
                else:
                    out = f(params)
                return out
            """, tmp_path, [DonatedBufferReuse()])
        assert got == []

    def test_positive_sibling_branch_inside_loop(self, tmp_path):
        """The same two arms under a loop ARE a bug: iteration 1 may
        donate, iteration 2 read the stale name."""
        got = run("""
            import jax

            step = jax.jit(lambda p: p, donate_argnums=(0,))

            def train(params, iters, f):
                for i in range(iters):
                    if i % 2 == 0:
                        out = step(params)
                    else:
                        out = f(params)
                return out
            """, tmp_path, [DonatedBufferReuse()])
        assert rule_ids(got) == ["APX103"]

    def test_positive_read_after_exclusive_branch(self, tmp_path):
        """A read BELOW the if/else is reachable after the donating arm
        ran — the exclusive-branch skip must not silence it."""
        got = run("""
            import jax

            step = jax.jit(lambda p: p, donate_argnums=(0,))

            def train(params, cond, f, g):
                if cond:
                    out = step(params)
                else:
                    out = f(params)
                return g(params)
            """, tmp_path, [DonatedBufferReuse()])
        assert rule_ids(got) == ["APX103"]

    def test_negative_rebound_from_the_call(self, tmp_path):
        """`params, state, loss = step(params, state)` — the safe
        idiom every bench section uses — must stay silent, including
        inside loops (the rebind covers the next iteration's read)."""
        got = run("""
            from functools import partial

            import jax

            @partial(jax.jit, donate_argnums=(0, 1))
            def step(params, state):
                return params, state, 0.0

            def train(params, state, iters):
                params, state, loss = step(params, state)
                for _ in range(iters):
                    params, state, loss = step(params, state)
                return params, loss
            """, tmp_path, [DonatedBufferReuse()])
        assert got == []

    def test_negative_read_before_and_rebind_after(self, tmp_path):
        got = run("""
            import jax

            step = jax.jit(lambda p: p, donate_argnums=(0,))

            def train(params, norm_of):
                norm = norm_of(params)      # read BEFORE donation: fine
                out = step(params)
                params = out                # rebound before any read
                return params, norm
            """, tmp_path, [DonatedBufferReuse()])
        assert got == []

    def test_negative_same_name_in_nested_scope(self, tmp_path):
        """A same-named parameter or local of a NESTED scope after the
        donating call is a different variable, not the donated buffer —
        the read search stops at function/class/lambda boundaries (this
        exact shape was a reproduced false positive)."""
        got = run("""
            import jax

            step = jax.jit(lambda p: p, donate_argnums=(0,))
            params = {"w": 1.0}
            out = step(params)

            def helper(params):
                return params["w"] * 2

            scale = lambda params: params["w"] + 1
            """, tmp_path, [DonatedBufferReuse()])
        assert got == []

    def test_negative_nested_scope_inside_function(self, tmp_path):
        """Same boundary one level down: a helper def nested in the
        donating function reuses the name for its own parameter."""
        got = run("""
            import jax

            step = jax.jit(lambda p: p, donate_argnums=(0,))

            def train(params, sink):
                out = step(params)

                def norm_of(params):
                    return params["w"]

                sink(norm_of(out))
                return out
            """, tmp_path, [DonatedBufferReuse()])
        assert got == []

    def test_negative_computed_argnums_and_star_args(self, tmp_path):
        """Non-literal donate_argnums and *args call sites are trusted
        (the models/gpt.py `donate_argnums=donate` shape)."""
        got = run("""
            import jax

            def make(fn, donate_state):
                donate = (0, 1) if donate_state else ()
                return jax.jit(fn, donate_argnums=donate)

            step = jax.jit(lambda p, s: (p, s), donate_argnums=(0, 1))

            def train(step_args, params):
                out = step(*step_args)
                return out, params
            """, tmp_path, [DonatedBufferReuse()])
        assert got == []


# ------------------------------------------ APX104 non-atomic ckpt write
class TestNonAtomicCheckpointWrite:
    def test_positive_direct_wb_on_checkpoint_path(self, tmp_path):
        """The torn-write shape: a checkpoint-named path opened for a
        direct binary write — an interrupted writer publishes a
        truncated file under the final name."""
        got = run("""
            def save(ckpt_path, blob):
                with open(ckpt_path, "wb") as f:
                    f.write(blob)
            """, tmp_path, [NonAtomicCheckpointWrite()])
        assert rule_ids(got) == ["APX104"]
        assert "atomic_output" in got[0].fix_hint

    def test_positive_checkpointish_function_name(self, tmp_path):
        """The function name marks the write even when the path
        expression itself is opaque."""
        got = run("""
            def write_checkpoint(path, blob):
                f = open(path, mode="wb")
                f.write(blob)
                f.close()
            """, tmp_path, [NonAtomicCheckpointWrite()])
        assert rule_ids(got) == ["APX104"]

    def test_positive_append_and_exclusive_binary_modes(self, tmp_path):
        got = run("""
            def save(ckpt_path, blob):
                with open(ckpt_path, "ab") as f:
                    f.write(blob)
                with open(ckpt_path, "xb") as f:
                    f.write(blob)
            """, tmp_path, [NonAtomicCheckpointWrite()])
        assert rule_ids(got) == ["APX104", "APX104"]

    def test_negative_tmp_staged_write(self, tmp_path):
        """Writing to <path>.tmp then renaming IS the atomic idiom —
        the staging write must stay silent."""
        got = run("""
            import os

            def save(ckpt_path, blob):
                with open(str(ckpt_path) + ".tmp", "wb") as f:
                    f.write(blob)
                os.replace(str(ckpt_path) + ".tmp", ckpt_path)
            """, tmp_path, [NonAtomicCheckpointWrite()])
        assert got == []

    def test_negative_atomic_helper_itself(self, tmp_path):
        """The designated helper (atomic_output / _atomic_* wrappers)
        owns the one sanctioned open."""
        got = run("""
            import contextlib, os

            @contextlib.contextmanager
            def atomic_output(path):
                f = open(str(path) + ".stage", "wb")
                yield f
                f.close()
                os.replace(str(path) + ".stage", path)

            def _atomic_write_checkpoint(path, blob):
                f = open(path, "wb")
                f.write(blob)
            """, tmp_path, [NonAtomicCheckpointWrite()])
        assert got == []

    def test_negative_non_checkpoint_writes_and_reads(self, tmp_path):
        """Binary writes to non-checkpoint paths, text-mode writes, and
        checkpoint READS are out of scope."""
        got = run("""
            def dump_log(log_path, text):
                with open(log_path, "wb") as f:      # not a ckpt path
                    f.write(text)
                with open("sections.jsonl", "a") as f:  # text append
                    f.write("{}")

            def load_checkpoint(ckpt_path):
                with open(ckpt_path, "rb") as f:     # read: fine
                    return f.read()
            """, tmp_path, [NonAtomicCheckpointWrite()])
        assert got == []

    def test_negative_computed_mode_trusted(self, tmp_path):
        got = run("""
            def save(ckpt_path, blob, mode):
                with open(ckpt_path, mode) as f:
                    f.write(blob)
            """, tmp_path, [NonAtomicCheckpointWrite()])
        assert got == []


# ---------------------------------- APX109 swallowed recovery-path except
class TestSwallowedExceptionInRecoveryPath:
    """The silent-swallow pattern PR 10's review kept hand-auditing:
    a do-nothing `except` in resilience/io/inference erases the one
    signal a wedged run's postmortem needs."""

    def _run_scoped(self, src, tmp_path, subdir):
        """Fixture placed under a scoped directory: APX109 keys on the
        path's directory segments (resilience/io/inference), not on the
        file name."""
        d = tmp_path / subdir
        d.mkdir(parents=True, exist_ok=True)
        p = d / "fixture.py"
        p.write_text(textwrap.dedent(src))
        return analyze_file(str(p), [SwallowedExceptionInRecoveryPath()],
                            set(AXES))

    def test_positive_except_pass_in_resilience(self, tmp_path):
        """The motivating shape: a drain error swallowed whole — the
        supervisor restarts on a wedge and nobody ever learns the
        flush failed too."""
        got = self._run_scoped("""
            def drain(checkpointer):
                try:
                    checkpointer.wait_until_finished()
                except OSError:
                    pass
            """, tmp_path, "resilience")
        assert rule_ids(got) == ["APX109"]
        assert "OSError" in got[0].message
        assert "log_structured" in got[0].fix_hint

    def test_positive_bare_except_ellipsis_in_io(self, tmp_path):
        got = self._run_scoped("""
            def read_shard(path):
                try:
                    return open(path, "rb").read()
                except:
                    ...
            """, tmp_path, "io")
        assert rule_ids(got) == ["APX109"]
        assert "bare" in got[0].message

    def test_positive_stray_string_body_in_inference(self, tmp_path):
        """A bare string is not a report — it is a comment that
        evaluates to nothing."""
        got = self._run_scoped("""
            def evict(slot, allocator, pages):
                try:
                    allocator.free(pages)
                except ValueError:
                    "double free: already recycled"
            """, tmp_path, "inference")
        assert rule_ids(got) == ["APX109"]

    def test_negative_logging_metrics_reraise_and_defaults(self, tmp_path):
        """Handlers that report (log_structured, a metrics record), re-
        raise, or return a fallback value are the sanctioned shapes."""
        got = self._run_scoped("""
            import logging

            def recover(step, logger, metrics):
                try:
                    step()
                except OSError as e:
                    log_structured(logger, logging.WARNING,
                                   "step.recovered", error=str(e))
                try:
                    step()
                except ValueError:
                    metrics.inc("apex_bad_steps_total")
                try:
                    step()
                except KeyError:
                    raise
                try:
                    return step()
                except RuntimeError:
                    return None
            """, tmp_path, "resilience")
        assert got == []

    def test_negative_out_of_scope_modules_trusted(self, tmp_path):
        """The same swallow OUTSIDE the recovery-path packages (an
        example script, an op) is not this rule's business."""
        src = """
            def cleanup(path):
                try:
                    path.unlink()
                except OSError:
                    pass
            """
        for subdir in ("examples/gpt", "ops", "observability"):
            assert self._run_scoped(src, tmp_path, subdir) == []


# ------------------------------------------ APX113 retry without backoff
class TestRetryWithoutBackoff:
    """The busy-spin retry: `while True:` swallowing the failure and
    immediately re-attempting hammers the failing dependency exactly
    when it needs room to recover."""

    def _run_scoped(self, src, tmp_path, subdir):
        d = tmp_path / subdir
        d.mkdir(parents=True, exist_ok=True)
        p = d / "fixture.py"
        p.write_text(textwrap.dedent(src))
        return analyze_file(str(p), [RetryWithoutBackoff()], set(AXES))

    def test_positive_hot_retry_in_resilience(self, tmp_path):
        got = self._run_scoped("""
            def reconnect(coordinator, log):
                while True:
                    try:
                        return coordinator.connect()
                    except OSError as e:
                        log.warning("retrying: %s", e)
            """, tmp_path, "resilience")
        assert rule_ids(got) == ["APX113"]
        assert "busy-spin" in got[0].message
        assert "retry_after_s" in got[0].fix_hint

    def test_positive_while_one_in_inference(self, tmp_path):
        """`while 1:` is the same loop; logging between attempts is
        reporting, not pacing."""
        got = self._run_scoped("""
            def resubmit(frontend, request):
                while 1:
                    try:
                        frontend.submit(request)
                        break
                    except Overloaded:
                        continue
            """, tmp_path, "inference")
        assert rule_ids(got) == ["APX113"]

    def test_negative_sleep_between_attempts(self, tmp_path):
        got = self._run_scoped("""
            import time

            def reconnect(coordinator):
                while True:
                    try:
                        return coordinator.connect()
                    except OSError:
                        time.sleep(0.5)
            """, tmp_path, "resilience")
        assert got == []

    def test_negative_backoff_helper_and_timeout_wait(self, tmp_path):
        """The supervisor shape: a crash-loop `_backoff_s` helper or a
        `child.wait(timeout=...)` both pace the loop."""
        got = self._run_scoped("""
            def supervise(child, attempt):
                while True:
                    try:
                        child.wait(timeout=0.2)
                        return child.returncode
                    except TimeoutError:
                        attempt += 1
            """, tmp_path, "resilience")
        assert got == []

    def test_negative_handler_escapes_loop(self, tmp_path):
        """A handler that re-raises / breaks / returns is not a retry
        loop — it gives up instead of spinning."""
        got = self._run_scoped("""
            def drain(sched):
                while True:
                    try:
                        sched.step()
                    except RuntimeError:
                        raise
                while True:
                    try:
                        sched.step()
                    except RuntimeError:
                        break
            """, tmp_path, "io")
        assert got == []

    def test_negative_blocking_dequeue_worker(self, tmp_path):
        """The async-checkpoint worker: the loop parks on a no-arg
        `q.get()` each iteration — not a busy-spin over the failure."""
        got = self._run_scoped("""
            def worker(q, errors):
                while True:
                    try:
                        q.get()()
                    except OSError as e:
                        errors.append(e)
            """, tmp_path, "io")
        assert got == []

    def test_negative_out_of_scope_and_bounded_for(self, tmp_path):
        """Outside resilience/io/inference the loop is not this rule's
        business, and a bounded `for` retry is self-limiting."""
        src = """
            def reconnect(coordinator):
                while True:
                    try:
                        return coordinator.connect()
                    except OSError:
                        pass
            """
        assert self._run_scoped(src, tmp_path, "examples/gpt") == []
        got = self._run_scoped("""
            def reconnect(coordinator):
                for _ in range(3):
                    try:
                        return coordinator.connect()
                    except OSError:
                        pass
            """, tmp_path, "resilience")
        assert got == []


# ------------------------------------------- APX201 unknown collective axis
class TestUnknownCollectiveAxis:
    def test_positive_typo_axis(self, tmp_path):
        got = run("""
            import jax

            def allreduce(x):
                return jax.lax.psum(x, "tq")
            """, tmp_path, [UnknownCollectiveAxis()])
        assert rule_ids(got) == ["APX201"]
        assert "'tq'" in got[0].message

    def test_positive_unknown_in_tuple(self, tmp_path):
        got = run("""
            import jax

            def hier(x):
                return jax.lax.psum(x, ("dcn", "dq"))
            """, tmp_path, [UnknownCollectiveAxis()])
        assert rule_ids(got) == ["APX201"]
        assert "'dq'" in got[0].message

    def test_negative_registered_and_dynamic_axes(self, tmp_path):
        got = run("""
            import jax

            def allreduce(x):
                return jax.lax.psum(x, "tp")

            def generic(x, axis_name):
                return jax.lax.pmean(x, axis_name)

            def hier(x):
                return jax.lax.psum(x, ("dcn", "dp"))
            """, tmp_path, [UnknownCollectiveAxis()])
        assert got == []


# ------------------------------------ APX202 collective without spmd context
class TestCollectiveOutsideSpmdContext:
    def test_positive_no_shard_map_in_sight(self, tmp_path):
        got = run("""
            import jax

            def loss(x):
                return jax.lax.pmean(x, "dp")
            """, tmp_path, [CollectiveOutsideSpmdContext()])
        assert rule_ids(got) == ["APX202"]

    def test_negative_module_binds_the_axis(self, tmp_path):
        got = run("""
            import jax
            from jax.sharding import Mesh, PartitionSpec as P

            def loss(x):
                return jax.lax.pmean(x, "dp")

            def train(mesh, x):
                return jax.shard_map(loss, mesh=mesh,
                                     in_specs=P("dp"), out_specs=P())(x)
            """, tmp_path, [CollectiveOutsideSpmdContext()])
        assert got == []


# ------------------------------ APX203 collective unbound under jit/pjit
class TestCollectiveAxisUnboundUnderJit:
    def test_positive_helper_reached_only_from_jit(self, tmp_path):
        """jit binds no axis names: the psum dies with an unbound-axis
        error on the first real trace — which for TPU-gated code is the
        chip, not the CPU suite."""
        got = run("""
            import jax

            def allreduce(x):
                return jax.lax.psum(x, "dp")

            @jax.jit
            def f(x):
                return allreduce(x)
            """, tmp_path, [CollectiveAxisUnboundUnderJit()])
        assert rule_ids(got) == ["APX203"]
        assert got[0].symbol == "allreduce"
        assert "jit auto-sharding binds no axis names" in got[0].message

    def test_positive_inside_jitted_lambda(self, tmp_path):
        got = run("""
            import jax

            g = jax.jit(lambda x: jax.lax.pmean(x, "tp"))
            """, tmp_path, [CollectiveAxisUnboundUnderJit()])
        assert rule_ids(got) == ["APX203"]

    def test_one_hazard_one_finding_with_apx202(self, tmp_path):
        """Reconciliation: where the dataflow pass HAS a verdict, the
        APX202 module heuristic yields — the full rule set reports
        exactly one finding for the jit-only psum."""
        got = run("""
            import jax

            def allreduce(x):
                return jax.lax.psum(x, "dp")

            @jax.jit
            def f(x):
                return allreduce(x)
            """, tmp_path, DEFAULT_RULES)
        assert rule_ids(got) == ["APX203"]

    def test_negative_shard_map_binds_the_axis(self, tmp_path):
        """The same helper additionally reachable through a shard_map
        whose (statically resolvable) mesh carries the axis: one
        binding path acquits the call site."""
        got = run("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            def allreduce(x):
                return jax.lax.psum(x, "dp")

            @jax.jit
            def f(x):
                return allreduce(x)

            def train(x):
                mesh = Mesh(np.array(jax.devices()), ("dp",))
                return jax.shard_map(allreduce, mesh=mesh,
                                     in_specs=P("dp"), out_specs=P())(x)
            """, tmp_path, [CollectiveAxisUnboundUnderJit(),
                            CollectiveAxisOutsideShardMapNest()])
        assert got == []

    def test_negative_dynamic_axis_name_never_flags(self, tmp_path):
        """Threading the axis as an argument is the RECOMMENDED fix —
        a dynamic axis name must stay silent even on a jit-only path
        (the caller may pass an axis its own shard_map binds)."""
        got = run("""
            import jax

            def generic(x, axis_name):
                return jax.lax.pmean(x, axis_name)

            @jax.jit
            def f(x):
                return generic(x, "dp")
            """, tmp_path, [CollectiveAxisUnboundUnderJit(),
                            CollectiveAxisOutsideShardMapNest(),
                            CollectiveOutsideSpmdContext()])
        assert got == []

    def test_negative_unregistered_axis_is_apx201_territory(self, tmp_path):
        got = run("""
            import jax

            @jax.jit
            def f(x):
                return jax.lax.psum(x, "tq")
            """, tmp_path, [CollectiveAxisUnboundUnderJit(),
                            UnknownCollectiveAxis()])
        assert rule_ids(got) == ["APX201"]

    def test_cross_module_jit_wrapper_feeds_apx203(self, tmp_path):
        """The collective lives in one file, its ONLY traced entry
        point (a jit wrapper) in another: the linked scope pass still
        proves the axis unbound — per-module analysis could not."""
        (tmp_path / "collective_mod.py").write_text(textwrap.dedent("""
            import jax

            def allreduce(x):
                return jax.lax.psum(x, "dp")
            """))
        (tmp_path / "main.py").write_text(textwrap.dedent("""
            import jax
            from collective_mod import allreduce

            @jax.jit
            def step(x):
                return allreduce(x)
            """))
        got = analyze_paths([str(tmp_path)], DEFAULT_RULES,
                            axis_registry=set(AXES), rel_to=str(tmp_path))
        assert [(f.rule, f.path, f.symbol) for f in got] == \
            [("APX203", "collective_mod.py", "allreduce")]


# --------------------------- APX204 collective outside the shard_map nest
class TestCollectiveAxisOutsideShardMapNest:
    def test_positive_nest_binds_only_other_axes(self, tmp_path):
        """Both axes are on the registry (APX201 is blind), but the
        shard_map's resolvable mesh binds only "tp" — the dp collective
        can never bind on this path."""
        got = run("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            def loss(x):
                return jax.lax.pmean(x, "dp")

            def train(x):
                mesh = Mesh(np.array(jax.devices()), ("tp",))
                return jax.shard_map(loss, mesh=mesh, in_specs=P("tp"),
                                     out_specs=P())(x)
            """, tmp_path, [CollectiveAxisOutsideShardMapNest()])
        assert rule_ids(got) == ["APX204"]
        assert "binds only {tp}" in got[0].message

    def test_negative_shadowed_axis_nest_unions(self, tmp_path):
        """The nest case that MUST stay silent: the inner shard_map
        binds only "tp", but the outer one already bound "dp" — axes
        accumulate through the nest, so the dp collective inside the
        inner function is legal."""
        got = run("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            def inner(x):
                return jax.lax.psum(x, "dp")

            def mid(x):
                tp_mesh = Mesh(np.array(jax.devices()), ("tp",))
                return jax.shard_map(inner, mesh=tp_mesh,
                                     in_specs=P("tp"), out_specs=P())(x)

            def train(x):
                dp_mesh = Mesh(np.array(jax.devices()), ("dp",))
                return jax.shard_map(mid, mesh=dp_mesh,
                                     in_specs=P("dp"), out_specs=P())(x)
            """, tmp_path, DEFAULT_RULES)
        assert got == []

    def test_negative_dynamic_mesh_is_unknowable(self, tmp_path):
        """A mesh passed in as a parameter may bind ANY axes — the
        scope records unknown and the rule stays quiet (specs are only
        a lower bound: replicated axes never appear in them)."""
        got = run("""
            import jax
            from jax.sharding import PartitionSpec as P

            def loss(x):
                return jax.lax.pmean(x, "dp")

            def train(mesh, x):
                return jax.shard_map(loss, mesh=mesh, in_specs=P("tp"),
                                     out_specs=P())(x)
            """, tmp_path, DEFAULT_RULES)
        assert got == []

    def test_positive_pmap_binds_one_name(self, tmp_path):
        got = run("""
            import jax

            def loss(x):
                return jax.lax.pmean(x, "dp")

            def train(x):
                return jax.pmap(loss, axis_name="tp")(x)
            """, tmp_path, [CollectiveAxisOutsideShardMapNest()])
        assert rule_ids(got) == ["APX204"]

    def test_negative_lambda_under_binding_shard_map(self, tmp_path):
        got = run("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            def train(x):
                mesh = Mesh(np.array(jax.devices()), ("dp",))
                return jax.shard_map(
                    lambda v: jax.lax.psum(v, "dp"), mesh=mesh,
                    in_specs=P("dp"), out_specs=P())(x)
            """, tmp_path, DEFAULT_RULES)
        assert got == []


# --------------------------- APX205 tuple-of-axes collective with unbound
HIER_AXES_REG = AXES | {"dp_out", "dp_in"}


class TestCollectiveTupleAxisUnbound:
    """APX205: the hierarchical-sync spelling ``psum(x, ("dp_out",
    "dp_in"))`` needs EVERY member bound in the same nest — the scalar
    dataflow rules (203/204) yield tuple spellings here, which judges
    the tuple at once and names exactly the bad members."""

    def test_positive_tuple_under_jit_only(self, tmp_path):
        got = run("""
            import jax

            def hier_mean(x):
                return jax.lax.pmean(x, ("dp_out", "dp_in"))

            @jax.jit
            def f(x):
                return hier_mean(x)
            """, tmp_path, [CollectiveTupleAxisUnbound()],
            axes=HIER_AXES_REG)
        assert rule_ids(got) == ["APX205"]
        assert "'dp_out'" in got[0].message and "'dp_in'" in got[0].message
        assert "jit" in got[0].message

    def test_positive_nest_binds_only_one_member(self, tmp_path):
        """The case neither APX201 nor the scalar rules report as ONE
        hazard: both members are registered, the shard_map binds only
        the inner axis — the tuple collective dies at trace time."""
        got = run("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            def loss(x):
                return jax.lax.pmean(x, ("dp_out", "dp_in"))

            def train(x):
                mesh = Mesh(np.array(jax.devices()), ("dp_in",))
                return jax.shard_map(loss, mesh=mesh, in_specs=P("dp_in"),
                                     out_specs=P())(x)
            """, tmp_path, [CollectiveTupleAxisUnbound()],
            axes=HIER_AXES_REG)
        assert rule_ids(got) == ["APX205"]
        assert "['dp_out']" in got[0].message
        assert "binds only {dp_in}" in got[0].message

    def test_one_hazard_one_finding_full_rule_set(self, tmp_path):
        """Reconciliation with the scalar rules: the full set reports
        exactly ONE finding for a jit-only tuple collective — 203/204
        skip tuple spellings, APX205 owns them."""
        got = run("""
            import jax

            def hier_mean(x):
                return jax.lax.pmean(x, ("dp_out", "dp_in"))

            @jax.jit
            def f(x):
                return hier_mean(x)
            """, tmp_path, DEFAULT_RULES, axes=HIER_AXES_REG)
        assert rule_ids(got) == ["APX205"]

    def test_negative_nest_binds_both_members(self, tmp_path):
        got = run("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            def loss(x):
                return jax.lax.pmean(x, ("dp_out", "dp_in"))

            def train(x):
                mesh = Mesh(np.array(jax.devices()).reshape(2, 2),
                            ("dp_out", "dp_in"))
                return jax.shard_map(loss, mesh=mesh,
                                     in_specs=P(("dp_out", "dp_in")),
                                     out_specs=P())(x)
            """, tmp_path, DEFAULT_RULES, axes=HIER_AXES_REG)
        assert got == []

    def test_negative_dynamic_member_stays_quiet(self, tmp_path):
        """A dynamically-spelled member may be anything the caller's
        nest binds — the whole tuple stays quiet (the threading-as-
        argument pattern the scalar rules also bless)."""
        got = run("""
            import jax

            def generic(x, outer_axis):
                return jax.lax.pmean(x, (outer_axis, "dp_in"))

            @jax.jit
            def f(x):
                return generic(x, "dp_out")
            """, tmp_path, [CollectiveTupleAxisUnbound()],
            axes=HIER_AXES_REG)
        assert got == []

    def test_unregistered_member_stays_apx201s(self, tmp_path):
        """Registry-tier findings stay APX201's (one per unknown
        member, as its own fixtures pin); APX205 names them only as
        context when an unbound REGISTERED member triggers it."""
        got = run("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, PartitionSpec as P

            def loss(x):
                return jax.lax.pmean(x, ("dp_outer_typo", "dp_in"))

            def train(x):
                mesh = Mesh(np.array(jax.devices()), ("tp",))
                return jax.shard_map(loss, mesh=mesh, in_specs=P("tp"),
                                     out_specs=P())(x)
            """, tmp_path,
            [UnknownCollectiveAxis(), CollectiveTupleAxisUnbound()],
            axes=HIER_AXES_REG)
        assert sorted(rule_ids(got)) == ["APX201", "APX205"]
        apx205 = [f for f in got if f.rule == "APX205"][0]
        assert "'dp_in'" in apx205.message
        assert "dp_outer_typo" in apx205.message  # context, not a dup


# ----------------------------- APX206 sharding-annotation axis unbound
class TestShardingSpecAxisUnbound:
    """APX206: the GSPMD tier of the axis family — PartitionSpec axes
    vs the mesh that actually reaches the annotation."""

    def test_positive_typo_against_own_mesh(self, tmp_path):
        """The one-character-typo class on the annotation side: 'dq'
        is not on the NamedSharding's own mesh — raises at annotation
        construction, which for a TPU-gated builder is on the chip."""
        got = run("""
            import jax
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            mesh = Mesh(devs, ("dp", "tp"))
            spec = NamedSharding(mesh, P("dq", None))
            """, tmp_path, [ShardingSpecAxisUnbound()])
        assert rule_ids(got) == ["APX206"]
        assert "'dq'" in got[0].message
        assert "dp, tp" in got[0].message

    def test_positive_stale_mesh_constraint_under_annotated_jit(
            self, tmp_path):
        """The SILENT-replication class (the fixture
        tests/test_lowered_invariants.py::TestShardingRuleProof runs
        live: jit compiles and runs with zero exceptions): the
        with_sharding_constraint's NamedSharding is self-consistent,
        but it was built on a STALE prod mesh — the mesh reaching this
        jit (its in_shardings) binds only 'dp'."""
        got = run("""
            import jax
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            mesh_ci = Mesh(devs, ("dp",))
            mesh_prod = Mesh(devs2, ("dp", "tp"))

            def f(x):
                return jax.lax.with_sharding_constraint(
                    x * 2, NamedSharding(mesh_prod, P(None, "tp")))

            step = jax.jit(f, in_shardings=NamedSharding(mesh_ci, P("dp")))
            """, tmp_path, [ShardingSpecAxisUnbound()])
        assert rule_ids(got) == ["APX206"]
        assert "silently rematerializes" in got[0].message

    def test_positive_bare_spec_constraint_off_the_reaching_mesh(
            self, tmp_path):
        got = run("""
            import jax
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            mesh = Mesh(devs, ("dp",))

            @functools.partial(jax.jit,
                               in_shardings=NamedSharding(mesh, P("dp")))
            def f(x):
                return jax.lax.with_sharding_constraint(x, P("model"))
            """, tmp_path, [ShardingSpecAxisUnbound()])
        assert rule_ids(got) == ["APX206"]
        assert "'model'" in got[0].message

    def test_negative_bound_axes_and_dynamic_meshes(self, tmp_path):
        """Bound axes pass; a mesh (or spec) out of static reach —
        the threading pattern — stays quiet."""
        got = run("""
            import jax
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            mesh = Mesh(devs, ("dp", "tp"))
            ok = NamedSharding(mesh, P("dp", None, "tp"))

            def make(m, spec):
                return NamedSharding(m, spec)

            def f(x):
                return jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, P("dp")))

            step = jax.jit(f, in_shardings=NamedSharding(mesh, P("dp")))
            """, tmp_path, [ShardingSpecAxisUnbound()])
        assert got == []

    def test_negative_unannotated_jit_has_no_mesh_opinion(self, tmp_path):
        """A wsc under a PLAIN jit (no in_shardings) follows the
        ambient device context the analyzer cannot see — quiet."""
        got = run("""
            import jax
            from jax.sharding import PartitionSpec as P

            @jax.jit
            def f(x):
                return jax.lax.with_sharding_constraint(x, P("dp"))
            """, tmp_path, [ShardingSpecAxisUnbound()])
        assert got == []

    def test_rides_default_rules(self, tmp_path):
        got = run("""
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            mesh = Mesh(devs, ("dp", "tp"))
            s = NamedSharding(mesh, P("dq"))
            """, tmp_path, DEFAULT_RULES)
        assert "APX206" in rule_ids(got)


# ------------------------------------ APX207 spec rank vs array rank
class TestShardingSpecRankMismatch:
    def test_positive_constraint_longer_than_creation_rank(self, tmp_path):
        """The refactor wound: the tensor lost a dim, the annotation
        kept it — a trace-time error deferred to the chip for
        TPU-gated paths."""
        got = run("""
            import jax, jax.numpy as jnp
            from jax.sharding import PartitionSpec as P

            x = jnp.zeros((8, 128))
            y = jax.lax.with_sharding_constraint(x, P("dp", None, "tp"))
            """, tmp_path, [ShardingSpecRankMismatch()])
        assert rule_ids(got) == ["APX207"]
        assert "3 dimensions" in got[0].message
        assert "rank 2" in got[0].message

    def test_positive_device_put_and_aliased_dims(self, tmp_path):
        """device_put sites count too, and dims thread through the
        one-hop local lattice (`bn = 8`)."""
        got = run("""
            import jax, jax.numpy as jnp
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            mesh = Mesh(devs, ("dp", "tp"))
            bn = 8
            x = jnp.ones((bn, 128))
            y = jax.device_put(x, NamedSharding(mesh, P("dp", "tp", None)))
            """, tmp_path, [ShardingSpecRankMismatch()])
        assert rule_ids(got) == ["APX207"]

    def test_negative_numpy_random_signature_not_conflated(self, tmp_path):
        """Review finding: np.random.normal(loc, SCALE, size) puts a
        scalar where jax.random.normal puts the shape — claiming the
        array is rank 1 there was a confirmed false positive.  Scalar
        shapes only count for the zeros/ones (position-0) family."""
        got = run("""
            import jax
            import numpy as np
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            mesh = Mesh(devs, ("dp", "tp"))
            x = np.random.normal(0, 1, (8, 128))
            y = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
            """, tmp_path, [ShardingSpecRankMismatch()])
        assert got == []

    def test_negative_shorter_spec_and_unknown_ranks(self, tmp_path):
        """Shorter specs are legal (trailing dims replicate); arrays
        whose rank is out of static reach are trusted."""
        got = run("""
            import jax, jax.numpy as jnp
            from jax.sharding import PartitionSpec as P

            x = jnp.zeros((8, 128, 4))
            ok = jax.lax.with_sharding_constraint(x, P("dp"))
            exact = jax.lax.with_sharding_constraint(x, P("dp", None, "tp"))
            dyn = jax.lax.with_sharding_constraint(load(), P("a", "b", "c"))
            """, tmp_path, [ShardingSpecRankMismatch()])
        assert got == []

    def test_rides_default_rules(self, tmp_path):
        got = run("""
            import jax, jax.numpy as jnp
            from jax.sharding import PartitionSpec as P

            x = jnp.zeros((16,))
            y = jax.lax.with_sharding_constraint(x, P("dp", "tp"))
            """, tmp_path, DEFAULT_RULES)
        assert "APX207" in rule_ids(got)


# -------------------------- APX208 donated in/out sharding mismatch
class TestDonatedShardingMismatch:
    def test_positive_donated_arg_can_never_alias(self, tmp_path):
        """The silent-drop class: in P('dp', None) matches no output
        sharding, so XLA keeps the input AND the output alive — a
        UserWarning nobody reads in CI logs."""
        got = run("""
            import jax
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            mesh = Mesh(devs, ("dp", "tp"))
            step = jax.jit(f, donate_argnums=(0,),
                           in_shardings=(NamedSharding(mesh, P("dp", None)),
                                         NamedSharding(mesh, P())),
                           out_shardings=(NamedSharding(mesh, P(None, "tp")),))
            """, tmp_path, [DonatedShardingMismatch()])
        assert rule_ids(got) == ["APX208"]
        assert "argument 0 is donated" in got[0].message

    def test_positive_partial_jit_decorator_spelling(self, tmp_path):
        """Review finding: the ``@functools.partial(jax.jit, ...)``
        decorator spelling carries the same three kwargs on the
        partial call — the most common step-builder shape must not
        dodge the rule."""
        got = run("""
            import functools

            import jax
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            mesh = Mesh(devs, ("dp", "tp"))

            @functools.partial(
                jax.jit, donate_argnums=(0,),
                in_shardings=(NamedSharding(mesh, P("dp", None)),),
                out_shardings=(NamedSharding(mesh, P(None, "tp")),))
            def step(state):
                return state * 2
            """, tmp_path, [DonatedShardingMismatch()])
        assert rule_ids(got) == ["APX208"]

    def test_negative_matching_modulo_trailing_nones(self, tmp_path):
        """P('dp') and P('dp', None) are the SAME sharding — trailing
        Nones replicate; flagging them was a false positive waiting to
        happen.  Undonated args and unresolvable specs stay quiet."""
        got = run("""
            import jax
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            mesh = Mesh(devs, ("dp", "tp"))
            ok = jax.jit(f, donate_argnums=(0,),
                         in_shardings=(NamedSharding(mesh, P("dp", None)),),
                         out_shardings=(NamedSharding(mesh, P("dp")),))
            free = jax.jit(f, donate_argnums=(0,),
                           in_shardings=(NamedSharding(mesh, P("dp")),),
                           out_shardings=(make_out_spec(),))
            undonated = jax.jit(f,
                                in_shardings=(NamedSharding(mesh, P("dp")),),
                                out_shardings=(NamedSharding(mesh, P("tp")),))
            """, tmp_path, [DonatedShardingMismatch()])
        assert got == []

    def test_negative_no_out_shardings_means_xla_chooses(self, tmp_path):
        got = run("""
            import jax
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            mesh = Mesh(devs, ("dp",))
            step = jax.jit(f, donate_argnums=(0,),
                           in_shardings=(NamedSharding(mesh, P("dp")),))
            """, tmp_path, [DonatedShardingMismatch()])
        assert got == []

    def test_rides_default_rules(self, tmp_path):
        got = run("""
            import jax
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            mesh = Mesh(devs, ("dp", "tp"))
            step = jax.jit(f, donate_argnums=(0,),
                           in_shardings=(NamedSharding(mesh, P("dp")),),
                           out_shardings=(NamedSharding(mesh, P("tp")),))
            """, tmp_path, DEFAULT_RULES)
        assert "APX208" in rule_ids(got)


# ------------------------------- APX303 scratch/accumulator dtype vs dot
class TestScratchAccumDtypeMismatch:
    def test_positive_bf16_scratch_fp32_preferred(self, tmp_path):
        """The hazard class: preferred_element_type asks the MXU for
        fp32 partials, the bf16 scratch re-rounds every accumulation
        step — the precision was paid for and silently discarded."""
        got = run("""
            import jax
            import jax.numpy as jnp
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu

            def _kernel(x_ref, o_ref, acc_ref):
                acc_ref[:] += jax.lax.dot_general(
                    x_ref[:], x_ref[:], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)

            def launch(x, bn, H):
                return pl.pallas_call(
                    _kernel, grid=(4,),
                    in_specs=[pl.BlockSpec((bn, H), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((bn, H), lambda i: (i, 0)),
                    scratch_shapes=[pltpu.VMEM((bn, H), jnp.bfloat16)],
                )(x)
            """, tmp_path, [ScratchAccumDtypeMismatch()])
        assert rule_ids(got) == ["APX303"]
        assert got[0].symbol == "_kernel"
        assert "preferred_element_type=float32" in got[0].message

    def test_positive_dtype_through_lattice_and_repeat_list(self, tmp_path):
        """The dtype rides a local assignment (``acc_dtype = jnp.
        bfloat16``) and the scratch list uses the ``[...] * 2`` repeat
        spelling — both resolved by the dataflow lattice."""
        got = run("""
            import jax
            import jax.numpy as jnp
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu

            acc_dtype = jnp.bfloat16

            def _kernel(x_ref, o_ref, a_ref, b_ref):
                b_ref[:] = b_ref[:] + jax.lax.dot_general(
                    x_ref[:], x_ref[:], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)

            def launch(x, bn, H):
                return pl.pallas_call(
                    _kernel, grid=(4,),
                    in_specs=[pl.BlockSpec((bn, H), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((bn, H), lambda i: (i, 0)),
                    scratch_shapes=[pltpu.VMEM((bn, H), acc_dtype)] * 2,
                )(x)
            """, tmp_path, [ScratchAccumDtypeMismatch()])
        assert rule_ids(got) == ["APX303"]

    def test_positive_local_accumulator(self, tmp_path):
        """The non-Pallas spelling: a bf16 ``jnp.zeros`` accumulator
        fed by fp32-preferred dots in a scan-style loop."""
        got = run("""
            import jax
            import jax.numpy as jnp

            def chunked_matmul(a, b):
                acc = jnp.zeros((128, 128), dtype=jnp.bfloat16)
                for i in range(4):
                    acc += jax.lax.dot_general(
                        a[i], b[i], (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
                return acc
            """, tmp_path, [ScratchAccumDtypeMismatch()])
        assert rule_ids(got) == ["APX303"]
        assert "accumulator `acc`" in got[0].message

    def test_negative_fp32_scratch_fp32_preferred(self, tmp_path):
        """The repo's own fused-CE shape: fp32 scratch, fp32 preferred
        — the contract this rule exists to protect."""
        got = run("""
            import jax
            import jax.numpy as jnp
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu

            def _kernel(x_ref, o_ref, acc_ref):
                acc_ref[:] += jax.lax.dot_general(
                    x_ref[:], x_ref[:], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)

            def launch(x, bn, H):
                return pl.pallas_call(
                    _kernel, grid=(4,),
                    in_specs=[pl.BlockSpec((bn, H), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((bn, H), lambda i: (i, 0)),
                    scratch_shapes=[pltpu.VMEM((bn, H), jnp.float32)],
                )(x)
            """, tmp_path, [ScratchAccumDtypeMismatch()])
        assert got == []

    def test_negative_deliberate_narrow_accumulation(self, tmp_path):
        """bf16 scratch with bf16 preferred is self-consistent: the
        author CHOSE narrow accumulation, nothing is discarded."""
        got = run("""
            import jax
            import jax.numpy as jnp

            def f(a, b):
                acc = jnp.zeros((128, 128), dtype=jnp.bfloat16)
                acc += jax.lax.dot_general(
                    a, b, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.bfloat16)
                return acc
            """, tmp_path, [ScratchAccumDtypeMismatch()])
        assert got == []

    def test_negative_unresolvable_dtype_stays_quiet(self, tmp_path):
        got = run("""
            import jax
            import jax.numpy as jnp

            def f(a, b, out_dtype):
                acc = jnp.zeros((128, 128), dtype=out_dtype)
                acc += jax.lax.dot_general(
                    a, b, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                return acc
            """, tmp_path, [ScratchAccumDtypeMismatch()])
        assert got == []

    def test_conflicting_dtype_names_terminate_and_poison(self, tmp_path):
        """Review finding: two functions reusing one dtype name with
        different values made the old dtype_env fixpoint flip forever
        (the analyzer HUNG on any module reusing the name ``dtype``).
        Now the module layer reads only top-level statements and a
        conflicting name poisons to UNKNOWN — terminates, stays
        quiet."""
        got = run("""
            import jax
            import jax.numpy as jnp

            def a():
                dt = jnp.bfloat16
                return dt

            def b():
                dt = jnp.float32
                return dt

            def f(x, y):
                acc = jnp.zeros((128, 128), dtype=jnp.bfloat16)
                acc += jax.lax.dot_general(
                    x, y, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                return acc
            """, tmp_path, [ScratchAccumDtypeMismatch()])
        assert rule_ids(got) == ["APX303"]  # f still judged; no hang

    def test_dtype_locals_do_not_leak_across_functions(self, tmp_path):
        """Review finding: one function's ``dt = jnp.bfloat16`` must
        not resolve another function's unrelated ``dt`` (a parameter
        there) — the module layer is top-level-only now."""
        got = run("""
            import jax
            import jax.numpy as jnp

            def other():
                dt = jnp.bfloat16
                return dt

            def f(x, y, dt):
                acc = jnp.zeros((128, 128), dtype=dt)
                acc += jax.lax.dot_general(
                    x, y, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                return acc
            """, tmp_path, [ScratchAccumDtypeMismatch()])
        assert got == []

    def test_branch_conflicting_accumulator_dtype_stays_quiet(self, tmp_path):
        """A name carrying fp32 on one branch and bf16 on the other
        must poison, not last-win into a wrong finding."""
        got = run("""
            import jax
            import jax.numpy as jnp

            def f(x, y, wide):
                dt = jnp.float32
                if not wide:
                    dt = jnp.bfloat16
                acc = jnp.zeros((128, 128), dtype=dt)
                acc += jax.lax.dot_general(
                    x, y, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                return acc
            """, tmp_path, [ScratchAccumDtypeMismatch()])
        assert got == []


# ------------------------------- APX107 page-table gathers (decode path)
class TestPageTableGatherUnclamped:
    """The APX401 unclamped-gather family extended to the serving
    path's mutable page indirection: page-table reads and table-valued
    pool indexing must clamp (or choose an explicit mode)."""

    def test_positive_take_through_page_table(self, tmp_path):
        got = run("""
            import jax.numpy as jnp

            def gather_pages(page_table_row, page_ix):
                return jnp.take(page_table_row, page_ix)
            """, tmp_path, [PageTableGatherUnclamped()])
        assert rule_ids(got) == ["APX107"]
        assert "page_table_row" in got[0].message

    def test_positive_table_values_index_the_pool(self, tmp_path):
        """The vLLM-shaped hazard: the table's VALUES address the pool;
        a stale entry wraps into a live sequence's page."""
        got = run("""
            def gather(k_pool, page_tables):
                return k_pool[page_tables]
            """, tmp_path, [PageTableGatherUnclamped()])
        assert rule_ids(got) == ["APX107"]
        assert "LIVE sequence" in got[0].message

    def test_positive_scatter_through_at(self, tmp_path):
        got = run("""
            def write(k_pool, page_tables, slot, k_new):
                return k_pool.at[page_tables, slot].set(k_new)
            """, tmp_path, [PageTableGatherUnclamped()])
        assert rule_ids(got) == ["APX107"]

    def test_negative_clipped_index(self, tmp_path):
        """The kv_cache.py contract shape: indices clipped (directly
        or through a clipped local) are clean."""
        got = run("""
            import jax.numpy as jnp

            def gather_pages(page_table_row, s, P, num_pages):
                page_ix = jnp.clip(s // 4, 0, P - 1)
                rows = jnp.take(page_table_row, page_ix)
                return jnp.clip(rows, 0, num_pages - 1)

            def gather(k_pool, page_table, num_pages):
                pt = jnp.clip(page_table, 0, num_pages - 1)
                return k_pool[pt]
            """, tmp_path, [PageTableGatherUnclamped()])
        assert got == []

    def test_negative_explicit_mode(self, tmp_path):
        got = run("""
            import jax.numpy as jnp

            def gather_pages(page_table_row, ix):
                return jnp.take(page_table_row, ix, mode="clip")
            """, tmp_path, [PageTableGatherUnclamped()])
        assert got == []

    def test_negative_at_scatter_with_explicit_mode(self, tmp_path):
        """``.at[...].set(..., mode=...)`` chose its out-of-bounds
        semantic explicitly — the mode lives on the ENCLOSING set/get
        call, and must acquit like take's mode= does."""
        got = run("""
            def write(k_pool, page_tables, slot, k_new):
                return k_pool.at[page_tables, slot].set(k_new, mode="drop")

            def read(k_pool, page_tables):
                return k_pool.at[page_tables].get(mode="fill", fill_value=0)
            """, tmp_path, [PageTableGatherUnclamped()])
        assert got == []

    def test_negative_non_page_table_names_quiet(self, tmp_path):
        """Ordinary gathers (embedding lookups, host bookkeeping) stay
        out of reach — the rule is scoped to page-table names."""
        got = run("""
            import jax.numpy as jnp

            def embed(table, tokens):
                return jnp.take(table, tokens, axis=0)

            def host_side(slots, i):
                return slots[i]
            """, tmp_path, [PageTableGatherUnclamped()])
        assert got == []


# ----------------------------- APX110 kv/pool scatter bypassing the seam
class TestKvPoolScatterBypassesSeam:
    """The COW-bypass hazard class: ``.at[...].set`` into a pool-named
    buffer whose page index is neither clamped/garbage-routed device
    data nor an allocator-normalized host int — with refcounted shared
    pages, a write the scheduler's COW pass cannot see mutates pages
    other sequences still read."""

    def test_positive_raw_index_scatter(self, tmp_path):
        got = run("""
            def poison(pools, page, slot, val):
                return pools["k"].at[page, slot].set(val)
            """, tmp_path, [KvPoolScatterBypassesSeam()])
        assert rule_ids(got) == ["APX110"]
        assert "copy-on-write" in got[0].message

    def test_positive_arithmetic_on_unrouted_index(self, tmp_path):
        got = run("""
            def poison(k_pool, positions, page_size, val):
                dest = positions // page_size
                return k_pool.at[dest].add(val)
            """, tmp_path, [KvPoolScatterBypassesSeam()])
        assert rule_ids(got) == ["APX110"]

    def test_negative_garbage_routed_seam_shape(self, tmp_path):
        """The write_decode_kv contract shape: dest built from
        where(clip(...), GARBAGE_PAGE) is the seam itself."""
        got = run("""
            import jax.numpy as jnp
            GARBAGE_PAGE = 0

            def write(k_pool, rows, slot, active, num_pages, k_new):
                dest = jnp.where(active,
                                 jnp.clip(rows, 0, num_pages - 1),
                                 GARBAGE_PAGE)
                return k_pool.at[dest, slot].set(k_new)
            """, tmp_path, [KvPoolScatterBypassesSeam()])
        assert got == []

    def test_negative_allocator_host_int(self, tmp_path):
        """copy_page's shape: allocator-issued ids normalized through
        int(...) — including the tuple-assignment spelling."""
        got = run("""
            def copy_page(pools, src, dst):
                src, dst = int(src), int(dst)
                k = pools["k"].at[:, dst].set(pools["k"][:, src])
                return k
            """, tmp_path, [KvPoolScatterBypassesSeam()])
        assert got == []

    def test_negative_non_pool_buffers_quiet(self, tmp_path):
        """Ordinary functional updates (grads, params, stats) stay out
        of reach — the rule is scoped to kv/pool names."""
        got = run("""
            def bump(stats, i, g):
                return stats.at[i].add(g)

            def read_only(pools, page):
                return pools["k"].at[page].get(mode="fill", fill_value=0)
            """, tmp_path, [KvPoolScatterBypassesSeam()])
        assert rule_ids(got) == []

    def test_negative_static_literal_index(self, tmp_path):
        got = run("""
            def reset_garbage(k_pool):
                return k_pool.at[0].set(0.0)
            """, tmp_path, [KvPoolScatterBypassesSeam()])
        assert got == []


# ------------------------------ APX306 kv-cache read dtype (decode path)
class TestKvCacheReadDtypeMismatch:
    """Narrow (bf16) cache storage feeding a wider-accumulator dot
    needs the widen SPELLED at the read."""

    def test_positive_bf16_pool_into_f32_dot(self, tmp_path):
        got = run("""
            import jax
            import jax.numpy as jnp

            def attend(q, i):
                k_cache = jnp.zeros((8, 16, 64), dtype=jnp.bfloat16)
                return jax.lax.dot_general(
                    q, k_cache[i], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
            """, tmp_path, [KvCacheReadDtypeMismatch()])
        assert rule_ids(got) == ["APX306"]
        assert "k_cache" in got[0].message and "bfloat16" in got[0].message

    def test_positive_via_dtype_lattice(self, tmp_path):
        """Storage dtype resolved through a local alias
        (``store = jnp.bfloat16``) — the APX303-style lattice hop."""
        got = run("""
            import jax
            import jax.numpy as jnp

            store = jnp.bfloat16

            def attend(q, pages):
                kv_pool = pages.astype(store)
                return jax.lax.dot_general(
                    q, kv_pool, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
            """, tmp_path, [KvCacheReadDtypeMismatch()])
        assert rule_ids(got) == ["APX306"]

    def test_negative_widened_at_the_read(self, tmp_path):
        """The decode kernels' contract shape: the cache operand is
        astype-widened where it meets the dot."""
        got = run("""
            import jax
            import jax.numpy as jnp

            def attend(q, i):
                k_cache = jnp.zeros((8, 16, 64), dtype=jnp.bfloat16)
                return jax.lax.dot_general(
                    q, k_cache[i].astype(jnp.float32),
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
            """, tmp_path, [KvCacheReadDtypeMismatch()])
        assert got == []

    def test_negative_wide_storage(self, tmp_path):
        got = run("""
            import jax
            import jax.numpy as jnp

            def attend(q, i):
                k_cache = jnp.zeros((8, 16, 64), dtype=jnp.float32)
                return jax.lax.dot_general(
                    q, k_cache[i], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
            """, tmp_path, [KvCacheReadDtypeMismatch()])
        assert got == []

    def test_negative_unresolvable_astype_at_read_stays_quiet(
            self, tmp_path):
        """An explicit cast at the read whose dtype the lattice cannot
        resolve (a parameter, a config attribute) is still the SPELLED
        widen the rule demands — quiet-when-unprovable applies to the
        cast too, not just the buffer."""
        got = run("""
            import jax
            import jax.numpy as jnp

            def attend(q, pages, acc_dtype, i):
                kv_pool = pages.astype(jnp.bfloat16)
                return jax.lax.dot_general(
                    q, kv_pool[i].astype(acc_dtype),
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
            """, tmp_path, [KvCacheReadDtypeMismatch()])
        assert got == []

    def test_negative_unresolvable_dtype_stays_quiet(self, tmp_path):
        """A pool whose dtype the lattice cannot prove (the real
        kernels: the ref's dtype is whatever the caller allocated)
        must not be guessed at."""
        got = run("""
            import jax

            def attend(q, k_pool, i):
                return jax.lax.dot_general(
                    q, k_pool[i], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
            """, tmp_path, [KvCacheReadDtypeMismatch()])
        assert got == []


# ---------------------------------- APX305 quantized-sync state dtypes
class TestQuantizedSyncStateDtype:
    """Scale/residual buffers of the compressed grad-sync idiom —
    scoped to functions that cast to a quantized WIRE dtype, so the
    repo's many ``loss_scale``-style names stay out of reach."""

    def test_positive_narrow_scales(self, tmp_path):
        got = run("""
            import jax
            import jax.numpy as jnp

            def quantized_sync(h, amax_sum):
                scales = (amax_sum / 127.0).astype(jnp.bfloat16)
                q = (h / scales).astype(jnp.int8)
                return jax.lax.psum_scatter(q, "dp", scatter_dimension=0,
                                            tiled=True)
            """, tmp_path, [QuantizedSyncStateDtype()])
        assert rule_ids(got) == ["APX305"]
        assert "scale" in got[0].message and "float32" in got[0].message

    def test_positive_wire_width_residual_via_lattice(self, tmp_path):
        """The residual narrowed to the WIRE dtype (through a dtype
        alias) — the error-feedback information re-rounded away."""
        got = run("""
            import jax.numpy as jnp

            wire = jnp.float8_e4m3fn

            def quantize_with_feedback(h, scales):
                q = (h / scales).astype(wire)
                residual = (h - q.astype(jnp.float32) * scales).astype(wire)
                return q, residual
            """, tmp_path, [QuantizedSyncStateDtype()])
        assert rule_ids(got) == ["APX305"]
        assert "residual" in got[0].message

    def test_negative_contract_shapes(self, tmp_path):
        """fp32 scales + storage-dtype residual (the
        ``_quantized_sync`` contract itself) are clean."""
        got = run("""
            import jax.numpy as jnp

            def quantize_with_feedback(h, scales):
                scales = scales.astype(jnp.float32)
                q = (h / scales).astype(jnp.int8)
                residual = (h - q.astype(jnp.float32) * scales).astype(
                    jnp.bfloat16)
                return q, residual
            """, tmp_path, [QuantizedSyncStateDtype()])
        assert got == []

    def test_negative_wire_cast_in_nested_def_does_not_mark_outer(
            self, tmp_path):
        """The marker is per-function: a nested helper's int8 cast must
        not put the OUTER function's ``loss_scale``-style names in
        APX305's reach."""
        got = run("""
            import jax.numpy as jnp

            def train_step(grads, scaler_state):
                new_scale = scaler_state.loss_scale.astype(jnp.bfloat16)

                def _quantize(x):
                    return x.astype(jnp.int8)

                return _quantize(grads), new_scale
            """, tmp_path, [QuantizedSyncStateDtype()])
        assert got == []

    def test_negative_loss_scale_outside_quantized_code(self, tmp_path):
        """A half-precision ``loss_scale`` in ordinary amp code — no
        wire cast in the function, so APX305 must stay quiet."""
        got = run("""
            import jax.numpy as jnp

            def scale_loss(loss, scaler_state):
                loss_scale = scaler_state.loss_scale.astype(jnp.float16)
                return loss * loss_scale
            """, tmp_path, [QuantizedSyncStateDtype()])
        assert got == []

    def test_negative_unresolvable_dtype_stays_quiet(self, tmp_path):
        """A residual cast to a dynamically-chosen dtype (the engine's
        ``.astype(jnp.dtype(b.dtype))``) is UNKNOWN — no finding."""
        got = run("""
            import jax.numpy as jnp

            def quantize(h, scales, storage_dtype):
                q = (h / scales).astype(jnp.int8)
                residual = (h - q.astype(jnp.float32) * scales).astype(
                    storage_dtype)
                return q, residual
            """, tmp_path, [QuantizedSyncStateDtype()])
        assert got == []


# ----------------------------------------- APX304 VMEM footprint budget
class TestVmemFootprintOverBudget:
    def test_positive_literal_blocks_over_budget(self, tmp_path):
        """2048x1024 fp32 blocks x 3 ≈ 24 MiB — fine in interpret
        mode, a Mosaic allocation failure on the chip."""
        got = run("""
            import jax.numpy as jnp
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu

            def launch(x):
                return pl.pallas_call(
                    _body, grid=(4,),
                    in_specs=[pl.BlockSpec((2048, 1024),
                                           lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((2048, 1024),
                                           lambda i: (i, 0)),
                    scratch_shapes=[pltpu.VMEM((2048, 1024),
                                               jnp.float32)],
                )(x)
            """, tmp_path, [VmemFootprintOverBudget()])
        assert rule_ids(got) == ["APX304"]
        assert got[0].severity == "warning"
        assert "24.0 MiB" in got[0].message

    def test_positive_dims_through_local_aliases(self, tmp_path):
        """``bn = 2048`` resolves through the assignment lattice —
        the spelling real kernels use."""
        got = run("""
            import jax.numpy as jnp
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu

            def launch(x):
                bn = 2048
                hidden = 1024
                spec = pl.BlockSpec((bn, hidden), lambda i: (i, 0))
                return pl.pallas_call(
                    _body, grid=(4,),
                    in_specs=[spec],
                    out_specs=pl.BlockSpec((bn, hidden),
                                           lambda i: (i, 0)),
                    scratch_shapes=[pltpu.VMEM((bn, hidden),
                                               jnp.float32)],
                )(x)
            """, tmp_path, [VmemFootprintOverBudget()])
        assert rule_ids(got) == ["APX304"]

    def test_negative_dynamic_dims_unpriceable(self, tmp_path):
        """Runtime-sized blocks (the repo's ``_ceil_block`` pattern)
        cannot be priced — the rule only speaks on provable sums."""
        got = run("""
            import jax.numpy as jnp
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu

            def launch(x, block_n):
                bn = _ceil_block(x.shape[0], block_n, 8)
                return pl.pallas_call(
                    _body, grid=(4,),
                    in_specs=[pl.BlockSpec((bn, 4096), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((bn, 4096), lambda i: (i, 0)),
                    scratch_shapes=[pltpu.VMEM((bn, 4096), jnp.float32)],
                )(x)
            """, tmp_path, [VmemFootprintOverBudget()])
        assert got == []

    def test_negative_small_blocks_under_budget(self, tmp_path):
        got = run("""
            import jax.numpy as jnp
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu

            def launch(x):
                return pl.pallas_call(
                    _body, grid=(4,),
                    in_specs=[pl.BlockSpec((256, 512), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((256, 512), lambda i: (i, 0)),
                    scratch_shapes=[pltpu.VMEM((256, 128), jnp.float32)],
                )(x)
            """, tmp_path, [VmemFootprintOverBudget()])
        assert got == []

    def test_positive_bwd_score_dots_price_temporaries(self, tmp_path):
        """The backward-kernel class: declared buffers well under
        budget (~2.5 MiB), but two last-dim-contracting dots (the
        s = q·kᵀ / dp = do·vᵀ score pattern) keep four
        (2048 × 1024) f32 temporaries live — 32 MiB the spec sum never
        sees.  The kernel resolves through the functools.partial
        binding idiom."""
        got = run("""
            import functools

            import jax
            from jax.experimental import pallas as pl

            def _bwd_body(q_ref, k_ref, dq_ref, *, scale):
                s = jax.lax.dot_general(
                    q_ref[...], k_ref[...], (((1,), (1,)), ((), ())))
                dp = jax.lax.dot_general(
                    dq_ref[...], k_ref[...], (((1,), (1,)), ((), ())))
                dq_ref[...] = (s * dp) * scale

            def launch(q, k, dq):
                kernel = functools.partial(_bwd_body, scale=0.125)
                return pl.pallas_call(
                    kernel, grid=(4,),
                    in_specs=[pl.BlockSpec((2048, 128), lambda i: (i, 0)),
                              pl.BlockSpec((1024, 128), lambda i: (0, 0))],
                    out_specs=pl.BlockSpec((2048, 128), lambda i: (i, 0)),
                )(q, k, dq)
            """, tmp_path, [VmemFootprintOverBudget()])
        assert rule_ids(got) == ["APX304"]
        assert "4 score-sized f32 kernel temporaries" in got[0].message

    def test_negative_non_score_dots_not_priced(self, tmp_path):
        """pv/dv-style ``(1,)×(0,)`` dots produce block-shaped results
        the specs already price — the same launch stays clean."""
        got = run("""
            import functools

            import jax
            from jax.experimental import pallas as pl

            def _pv_body(p_ref, v_ref, o_ref, *, scale):
                o_ref[...] = jax.lax.dot_general(
                    p_ref[...], v_ref[...],
                    (((1,), (0,)), ((), ()))) * scale

            def launch(p, v, o):
                kernel = functools.partial(_pv_body, scale=0.125)
                return pl.pallas_call(
                    kernel, grid=(4,),
                    in_specs=[pl.BlockSpec((2048, 128), lambda i: (i, 0)),
                              pl.BlockSpec((1024, 128), lambda i: (0, 0))],
                    out_specs=pl.BlockSpec((2048, 128), lambda i: (i, 0)),
                )(p, v, o)
            """, tmp_path, [VmemFootprintOverBudget()])
        assert got == []

    def test_budget_is_configurable(self, tmp_path):
        """The same small kernel flags under a 128 KiB budget — the
        constructor knob the CLI's --vmem-budget-mib drives."""
        got = run("""
            from jax.experimental import pallas as pl

            def launch(x):
                return pl.pallas_call(
                    _body, grid=(4,),
                    in_specs=[pl.BlockSpec((256, 512), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((256, 512), lambda i: (i, 0)),
                )(x)
            """, tmp_path,
            [VmemFootprintOverBudget(budget_bytes=128 * 1024)])
        assert rule_ids(got) == ["APX304"]


# ----------------------------------------------- APX301 BlockSpec tiling
class TestBlockShapeTilingViolation:
    def test_positive_bad_lane_and_sublane(self, tmp_path):
        got = run("""
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu

            def specs(H):
                a = pl.BlockSpec((8, 64), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM)
                b = pl.BlockSpec((7, 128), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM)
                return a, b
            """, tmp_path, [BlockShapeTilingViolation()])
        assert rule_ids(got) == ["APX301", "APX301"]
        assert "lane dim 64" in got[0].message
        assert "sublane dim 7" in got[1].message

    def test_negative_tiled_scalar_column_and_dynamic(self, tmp_path):
        got = run("""
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu

            def specs(bn, H):
                a = pl.BlockSpec((16, 256), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM)
                b = pl.BlockSpec((bn, 1), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM)
                c = pl.BlockSpec((256, H), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM)
                return a, b, c
            """, tmp_path, [BlockShapeTilingViolation()])
        assert got == []


# ------------------------------- APX105 BlockSpec index_map arity vs grid
class TestBlockSpecIndexMapArity:
    def test_positive_arity_mismatch_direct_and_aliased(self, tmp_path):
        """The refactor hazard: a grid grown to rank 3 while the
        lambdas still take 2 ids — both the inline spec and one built
        through a local alias (the flash-kernel idiom)."""
        got = run("""
            import functools
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu

            def kernel(x):
                kv_spec = pl.BlockSpec((1, 128, 64), lambda b, j: (b, j, 0),
                                       memory_space=pltpu.VMEM)
                grid = (4, 8, 2)
                return pl.pallas_call(
                    functools.partial(_body),
                    grid=grid,
                    in_specs=[
                        pl.BlockSpec((1, 128, 64), lambda b, i: (b, i, 0),
                                     memory_space=pltpu.VMEM),
                        kv_spec,
                    ],
                    out_specs=pl.BlockSpec((1, 128, 64),
                                           lambda b, i, j: (b, i, 0),
                                           memory_space=pltpu.VMEM),
                )(x)
            """, tmp_path, [BlockSpecIndexMapArity()])
        assert rule_ids(got) == ["APX105", "APX105"]
        assert "takes 2 argument(s)" in got[0].message
        assert "rank 3" in got[0].message

    def test_shadowed_alias_last_assignment_wins(self, tmp_path):
        """``grid = (4, 8)`` rebound to ``(4, 8, 2)`` before the call:
        the lexically LAST assignment is the one the call sees, so
        rank-3 lambdas are clean and a rank-2 lambda is flagged (the
        reverse-visit-order bug flagged the correct ones instead)."""
        got = run("""
            from jax.experimental import pallas as pl

            def kernel(x):
                grid = (4, 8)
                grid = (4, 8, 2)
                return pl.pallas_call(
                    _body, grid=grid,
                    in_specs=[
                        pl.BlockSpec((8, 128), lambda b, i, j: (b, i, 0)),
                        pl.BlockSpec((8, 128), lambda b, i: (b, i)),
                    ],
                    out_specs=pl.BlockSpec((8, 128),
                                           lambda b, i, j: (b, i, 0)),
                )(x)
            """, tmp_path, [BlockSpecIndexMapArity()])
        assert rule_ids(got) == ["APX105"]
        assert "takes 2 argument(s)" in got[0].message

    def test_positive_int_grid_is_rank_one(self, tmp_path):
        got = run("""
            from jax.experimental import pallas as pl

            def kernel(x):
                return pl.pallas_call(
                    _body, grid=8,
                    in_specs=[pl.BlockSpec((8, 128), lambda i, j: (i, j))],
                    out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                )(x)
            """, tmp_path, [BlockSpecIndexMapArity()])
        assert rule_ids(got) == ["APX105"]

    def test_negative_matching_named_default_and_dynamic(self, tmp_path):
        """Matching lambdas, a named index_map def of the right arity,
        a default index_map, a *args lambda, and a dynamic grid are
        all silent — the rule only speaks when the mismatch is
        provable."""
        got = run("""
            from jax.experimental import pallas as pl

            def imap(b, i, j):
                return (b, i, 0)

            def kernel(x, grid_from_caller):
                inline = pl.BlockSpec((1, 128, 64),
                                      lambda b, i, j: (b, j, 0))
                return pl.pallas_call(
                    _body,
                    grid=(4, 8, 2),
                    in_specs=[
                        inline,
                        pl.BlockSpec((1, 128, 64), imap),
                        pl.BlockSpec((1, 128, 64)),
                        pl.BlockSpec((1, 128, 64), lambda *ids: ids),
                    ],
                    out_specs=pl.BlockSpec((1, 128, 64), index_map=imap),
                )(x) + pl.pallas_call(
                    _body,
                    grid=grid_from_caller,
                    in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                )(x)
            """, tmp_path, [BlockSpecIndexMapArity()])
        assert got == []


# ------------------------------------- APX302 hard-coded sublane alignment
class TestHardCodedSublaneAlignment:
    def test_positive_advice_r5_fused_ce_pallas_58(self, tmp_path):
        """The literal pre-fix fused_ce_pallas.py:58 shape (ADVICE r5):
        ceil-rounding row blocks to fp32's sublane 8 in a kernel whose
        MXU dots run bf16."""
        got = run("""
            import jax.numpy as jnp

            def _ceil_block(n, target, align):
                if n >= target:
                    return target
                return -(-n // align) * align

            def fused_ce_fwd_pallas(x2, embed, t, block_n=256):
                dot_dtype = jnp.bfloat16
                bn = _ceil_block(x2.shape[0], block_n, align=8)
                return bn
            """, tmp_path, [HardCodedSublaneAlignment()])
        assert rule_ids(got) == ["APX302"]
        assert "align=8" in got[0].message

    def test_positive_positional_spelling(self, tmp_path):
        """The same constant passed positionally must not slip through."""
        got = run("""
            import jax.numpy as jnp

            def _ceil_block(n, target, align):
                return -(-n // align) * align

            def launch(x, block_n=256):
                dot_dtype = jnp.bfloat16
                return _ceil_block(x.shape[0], block_n, 8)
            """, tmp_path, [HardCodedSublaneAlignment()])
        assert rule_ids(got) == ["APX302"]

    def test_negative_dtype_derived_alignment(self, tmp_path):
        got = run("""
            import jax.numpy as jnp

            def _sublane(dtype):
                return {4: 8, 2: 16, 1: 32}[jnp.dtype(dtype).itemsize]

            def _ceil_block(n, target, align):
                if n >= target:
                    return target
                return -(-n // align) * align

            def fused_ce_fwd_pallas(x2, embed, t, block_n=256):
                dot_dtype = jnp.bfloat16
                bn = _ceil_block(x2.shape[0], block_n,
                                 align=_sublane(x2.dtype))
                return bn
            """, tmp_path, [HardCodedSublaneAlignment()])
        assert got == []

    def test_negative_fp32_only_module(self, tmp_path):
        """align=8 is correct when no bf16 can reach the kernel."""
        got = run("""
            def _ceil_block(n, target, align):
                return -(-n // align) * align

            def launch(x, block_n=256):
                bn = _ceil_block(x.shape[0], block_n, align=8)
                return bn
            """, tmp_path, [HardCodedSublaneAlignment()])
        assert got == []


# ---------------------------------------- APX401 unclamped take_along_axis
class TestUnclampedTakeAlongAxis:
    def test_positive_advice_r5_gpt_py_447(self, tmp_path):
        """The literal pre-fix gpt.py:447 dense-head shape (ADVICE r5)."""
        got = run("""
            import jax
            import jax.numpy as jnp

            def lm_head_loss(x, embed, targets):
                logits = jnp.matmul(x.astype(jnp.float32),
                                    embed.T.astype(jnp.float32))
                lse = jax.scipy.special.logsumexp(logits, axis=-1)
                tgt = jnp.take_along_axis(
                    logits, targets[..., None], axis=-1)[..., 0]
                return lse - tgt
            """, tmp_path, [UnclampedTakeAlongAxis()])
        assert rule_ids(got) == ["APX401"]

    def test_negative_clamped_through_a_name(self, tmp_path):
        got = run("""
            import jax.numpy as jnp

            def lm_head_loss(logits, targets):
                t_cl = jnp.clip(targets, 0, logits.shape[-1] - 1)
                tgt = jnp.take_along_axis(
                    logits, t_cl[..., None], axis=-1)[..., 0]
                return tgt
            """, tmp_path, [UnclampedTakeAlongAxis()])
        assert got == []

    def test_negative_explicit_mode(self, tmp_path):
        got = run("""
            import jax.numpy as jnp

            def gather(logits, t):
                return jnp.take_along_axis(
                    logits, t[..., None], axis=-1, mode="fill")
            """, tmp_path, [UnclampedTakeAlongAxis()])
        assert got == []


# ------------------------------------------ APX402 fp32 constant in bf16
class TestFp32ConstantInBf16Path:
    def test_positive_materialized_f32_meets_bf16(self, tmp_path):
        got = run("""
            import jax.numpy as jnp

            def scale(x, shape):
                return x.astype(jnp.bfloat16) * jnp.ones(
                    shape, dtype=jnp.float32)
            """, tmp_path, [Fp32ConstantInBf16Path()])
        assert rule_ids(got) == ["APX402"]
        assert "upcasts" in got[0].message

    def test_negative_constant_in_compute_dtype(self, tmp_path):
        got = run("""
            import jax.numpy as jnp

            def scale(x, shape):
                return x.astype(jnp.bfloat16) * jnp.ones(
                    shape, dtype=jnp.bfloat16)
            """, tmp_path, [Fp32ConstantInBf16Path()])
        assert got == []


# ------------------------------------------------------------ engine bits
class TestEngine:
    def test_axis_registry_discovered_from_parallel_state(self, tmp_path):
        ps = tmp_path / "parallel_state.py"
        ps.write_text('WEIRD_AXIS = "zz"\nOTHER = 3\n')
        assert discover_axis_registry([str(tmp_path)]) == {"zz"}

    def test_axis_registry_falls_back_to_defaults(self, tmp_path):
        assert "tp" in discover_axis_registry([str(tmp_path)])

    def test_syntax_error_is_a_finding_not_a_crash(self, tmp_path):
        got = run("def broken(:\n", tmp_path, DEFAULT_RULES)
        assert rule_ids(got) == ["APX000"]

    def test_findings_are_sorted_and_relative(self, tmp_path):
        (tmp_path / "b.py").write_text(
            "import os\n\ndef f():\n    os.environ['X'] = '1'\n")
        (tmp_path / "a.py").write_text(
            "import os\n\ndef f():\n    os.environ['X'] = '1'\n")
        got = analyze_paths([str(tmp_path)], DEFAULT_RULES,
                            axis_registry=set(AXES), rel_to=str(tmp_path))
        assert [f.path for f in got] == ["a.py", "b.py"]


# ------------------------------------- cross-module trace reachability
class TestCrossModuleReachability:
    """The traced-function index was per-module, so a helper whose only
    traced caller lives in ANOTHER module escaped APX101 — the exact
    ROADMAP case: ``fused_ce_pallas._default_dot_dtype``'s env read
    reached from ``fused_ce._fwd``.  ``analyze_paths`` now links the
    indexes through import-resolved calls; single-file
    ``analyze_file`` stays per-module (no imports to resolve)."""

    HELPER = textwrap.dedent("""
        import os

        def helper():
            return os.environ.get("APEX_TPU_X", "auto")
        """)

    def _scan(self, tmp_path):
        return analyze_paths([str(tmp_path)], DEFAULT_RULES,
                             axis_registry=set(AXES),
                             rel_to=str(tmp_path))

    def test_from_import_reached_from_jit(self, tmp_path):
        (tmp_path / "helper_mod.py").write_text(self.HELPER)
        (tmp_path / "main.py").write_text(textwrap.dedent("""
            import jax
            from helper_mod import helper

            @jax.jit
            def f(x):
                if helper() == "on":
                    return x * 2
                return x
            """))
        got = self._scan(tmp_path)
        assert [(f.rule, f.path, f.symbol) for f in got] == \
            [("APX101", "helper_mod.py", "helper")]
        assert "cross-module" in got[0].message or "main" in got[0].message

    def test_function_local_import_and_alias(self, tmp_path):
        """The fused_ce shape: the import lives INSIDE the traced
        closure; and the `import m as alias` dotted-call spelling."""
        (tmp_path / "helper_mod.py").write_text(self.HELPER)
        (tmp_path / "main.py").write_text(textwrap.dedent("""
            import jax
            import helper_mod as hm

            @jax.jit
            def f(x):
                from helper_mod import helper
                return x if helper() else x * hm.helper()
            """))
        got = self._scan(tmp_path)
        assert [(f.rule, f.path) for f in got] == \
            [("APX101", "helper_mod.py")]

    def test_package_relative_import(self, tmp_path):
        """Packages resolve: `from .kernels import helper` inside
        pkg/api.py marks pkg/kernels.py's helper traced."""
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "kernels.py").write_text(self.HELPER)
        (pkg / "api.py").write_text(textwrap.dedent("""
            import jax
            from .kernels import helper

            @jax.jit
            def f(x):
                return x * helper()
            """))
        got = self._scan(tmp_path)
        assert [(f.rule, f.path, f.symbol) for f in got] == \
            [("APX101", str(Path("pkg") / "kernels.py"), "helper")]

    def test_package_init_relative_import(self, tmp_path):
        """Relative imports in a package __init__.py resolve against
        the package ITSELF (python semantics) — review finding: the
        parent-of-module rule resolved one level too shallow and the
        seed was silently dropped."""
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "kernels.py").write_text(self.HELPER)
        (pkg / "__init__.py").write_text(textwrap.dedent("""
            import jax
            from .kernels import helper

            @jax.jit
            def f(x):
                return x * helper()
            """))
        got = self._scan(tmp_path)
        assert [(f.rule, f.path, f.symbol) for f in got] == \
            [("APX101", str(Path("pkg") / "kernels.py"), "helper")]

    def test_colliding_module_names_never_mislink(self, tmp_path):
        """Two bare roots both holding utils.py: the dotted name is
        ambiguous, so NO cross-module seed may land through it (a wrong
        -file APX101 is worse than a missed link)."""
        for d in ("libA", "libB"):
            (tmp_path / d).mkdir()
            (tmp_path / d / "utils.py").write_text(self.HELPER)
        (tmp_path / "libB" / "main.py").write_text(textwrap.dedent("""
            import jax
            from utils import helper

            @jax.jit
            def f(x):
                return x * helper()
            """))
        got = analyze_paths(
            [str(tmp_path / "libA"), str(tmp_path / "libB")],
            DEFAULT_RULES, axis_registry=set(AXES), rel_to=str(tmp_path))
        assert got == []

    def test_untraced_cross_module_call_not_flagged(self, tmp_path):
        """A helper reached only from plain (untraced) code stays
        clean — reachability, not mere import, is the trigger."""
        (tmp_path / "helper_mod.py").write_text(self.HELPER)
        (tmp_path / "main.py").write_text(textwrap.dedent("""
            from helper_mod import helper

            def plain():
                return helper()
            """))
        assert self._scan(tmp_path) == []

    def test_local_binding_shadows_import(self, tmp_path):
        """A module-local def with the imported name wins resolution —
        the other module must not be marked through the shadowed
        name."""
        (tmp_path / "helper_mod.py").write_text(self.HELPER)
        (tmp_path / "main.py").write_text(textwrap.dedent("""
            import jax
            from helper_mod import helper

            def helper():
                return 1

            @jax.jit
            def f(x):
                return x * helper()
            """))
        assert self._scan(tmp_path) == []


# ------------------------------------------------------------- baseline
class TestBaseline:
    def _write(self, tmp_path, entries):
        p = tmp_path / "baseline.json"
        p.write_text(json.dumps({"entries": entries}))
        return str(p)

    def test_suppression_and_stale_reporting(self, tmp_path):
        findings = run("""
            import os

            def f():
                os.environ["X"] = "1"
            """, tmp_path, [ProcessGlobalEnvMutation()])
        entries = load_baseline(self._write(tmp_path, [
            {"rule": "APX102", "path": "fixture.py", "symbol": "f",
             "contains": "os.environ", "justification": "test fixture"},
            {"rule": "APX102", "path": "nonexistent.py",
             "justification": "stale on purpose"},
        ]))
        kept, suppressed, stale = apply_baseline(findings, entries)
        assert kept == []
        assert len(suppressed) == 1
        assert len(stale) == 1 and stale[0].path == "nonexistent.py"

    def test_justification_is_mandatory(self, tmp_path):
        with pytest.raises(BaselineError, match="justification"):
            load_baseline(self._write(tmp_path, [
                {"rule": "APX102", "path": "x.py", "justification": "  "}]))

    def test_missing_fields_rejected(self, tmp_path):
        with pytest.raises(BaselineError, match="missing"):
            load_baseline(self._write(tmp_path, [{"rule": "APX102"}]))

    @pytest.mark.parametrize("placeholder", ["TODO", "todo", "TODO: later"])
    def test_todo_placeholder_rejected(self, tmp_path, placeholder):
        """--update-baseline's placeholder must never LOAD — a refresh
        is mechanical, signing off on it is not."""
        with pytest.raises(BaselineError, match="placeholder"):
            load_baseline(self._write(tmp_path, [
                {"rule": "APX102", "path": "x.py",
                 "justification": placeholder}]))

    def test_todo_allowed_only_for_the_update_path(self, tmp_path):
        entries = load_baseline(self._write(tmp_path, [
            {"rule": "APX102", "path": "x.py", "justification": "TODO"}]),
            allow_todo=True)
        assert len(entries) == 1

    def test_write_baseline_keeps_drops_adds(self, tmp_path):
        """Regeneration semantics: matched entries survive VERBATIM
        (their justifications are reviewed text), stale entries drop,
        new findings land with the rejected TODO placeholder."""
        findings = run("""
            import os

            def f():
                os.environ["X"] = "1"

            def g():
                os.environ.pop("Y", None)
            """, tmp_path, [ProcessGlobalEnvMutation()])
        entries = load_baseline(self._write(tmp_path, [
            {"rule": "APX102", "path": "fixture.py", "symbol": "f",
             "contains": "assignment", "justification": "reviewed: test"},
            {"rule": "APX102", "path": "gone.py",
             "justification": "stale on purpose"},
        ]))
        out = tmp_path / "new_baseline.json"
        kept, dropped, added = write_baseline(str(out), findings, entries)
        assert (kept, dropped, added) == (1, 1, 1)
        data = json.loads(out.read_text())
        justs = [e["justification"] for e in data["entries"]]
        assert justs == ["reviewed: test", "TODO"]
        assert data["entries"][1]["symbol"] == "g"
        # the regenerated file round-trips ONLY through the update path
        with pytest.raises(BaselineError, match="placeholder"):
            load_baseline(str(out))
        reloaded = load_baseline(str(out), allow_todo=True)
        k2, s2, _ = apply_baseline(findings, reloaded)
        assert k2 == [] and len(s2) == 2  # every finding now matched


# ----------------------------------------- CLI: --update-baseline, SARIF
class TestCliUpdateBaselineAndSarif:
    FIXTURE = textwrap.dedent("""
        import os
        import jax

        @jax.jit
        def f(x):
            return x if os.environ.get("FLAG") else -x
        """)

    def _run_cli(self, args, cwd):
        import os as _os

        env = dict(_os.environ, PYTHONPATH=str(REPO))
        return subprocess.run(
            [sys.executable, "-m", "apex_tpu.analysis", *args],
            cwd=str(cwd), env=env, capture_output=True, text=True,
            timeout=600)

    def test_update_baseline_is_mechanical_but_loud(self, tmp_path):
        """The full loop: findings -> --update-baseline exits 0 and
        writes TODO entries -> a normal run REFUSES the file (exit 2)
        -> filling the justification in makes the run clean (exit 0)."""
        (tmp_path / "mod.py").write_text(self.FIXTURE)
        r = self._run_cli(["mod.py"], tmp_path)
        assert r.returncode == 1  # the APX101 finding, unsuppressed

        r = self._run_cli(["mod.py", "--update-baseline"], tmp_path)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "added 1" in r.stderr
        baseline = tmp_path / "analysis_baseline.json"
        data = json.loads(baseline.read_text())
        assert data["entries"][0]["justification"] == "TODO"
        assert data["entries"][0]["rule"] == "APX101"

        r = self._run_cli(["mod.py"], tmp_path)
        assert r.returncode == 2, r.stdout + r.stderr
        assert "placeholder" in r.stderr

        data["entries"][0]["justification"] = "reviewed: test fixture"
        baseline.write_text(json.dumps(data))
        r = self._run_cli(["mod.py"], tmp_path)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "1 baselined" in r.stderr

    def test_sarif_schema_shape(self, tmp_path):
        """--format sarif emits a SARIF 2.1.0 log whose runs/tool/
        driver/rules/results shape CI consumers (GitHub code scanning,
        the VS Code viewer) require; baselined findings carry
        ``suppressions`` instead of disappearing."""
        (tmp_path / "mod.py").write_text(self.FIXTURE)
        (tmp_path / "analysis_baseline.json").write_text(json.dumps({
            "entries": [{"rule": "APX101", "path": "mod.py",
                         "justification": "reviewed: test fixture"}]}))
        r = self._run_cli(["mod.py", "--format", "sarif"], tmp_path)
        assert r.returncode == 0, r.stdout + r.stderr
        log = json.loads(r.stdout)
        assert log["version"] == "2.1.0"
        assert log["$schema"].endswith("sarif-schema-2.1.0.json")
        (run_obj,) = log["runs"]
        driver = run_obj["tool"]["driver"]
        assert driver["name"] == "apex_tpu.analysis"
        rule_d = {d["id"]: d for d in driver["rules"]}
        assert "APX101" in rule_d
        assert rule_d["APX101"]["defaultConfiguration"]["level"] == "error"
        (result,) = run_obj["results"]
        assert result["ruleId"] == "APX101"
        assert result["level"] == "error"
        loc = result["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == "mod.py"
        assert loc["region"]["startLine"] >= 1
        assert loc["region"]["startColumn"] >= 1
        assert result["suppressions"][0]["kind"] == "external"

    def test_update_baseline_bootstraps_an_explicit_path(self, tmp_path):
        """Review finding: --baseline pointing at a not-yet-existing
        file must BOOTSTRAP it under --update-baseline, not die with
        'cannot read baseline' before write_baseline runs."""
        (tmp_path / "mod.py").write_text(self.FIXTURE)
        target = tmp_path / "fresh" "_baseline.json"
        r = self._run_cli(
            ["mod.py", "--baseline", str(target), "--update-baseline"],
            tmp_path)
        assert r.returncode == 0, r.stdout + r.stderr
        assert json.loads(target.read_text())["entries"]
        # a normal run against a MISSING explicit baseline still errors
        r = self._run_cli(
            ["mod.py", "--baseline", str(tmp_path / "nope.json")],
            tmp_path)
        assert r.returncode == 2

    def test_update_baseline_rejects_no_baseline(self, tmp_path):
        """Review finding: the combination would rewrite the file from
        an EMPTY entry list, silently discarding every reviewed
        justification — refuse it."""
        (tmp_path / "mod.py").write_text(self.FIXTURE)
        (tmp_path / "analysis_baseline.json").write_text(json.dumps({
            "entries": [{"rule": "APX101", "path": "mod.py",
                         "justification": "reviewed: keep me"}]}))
        r = self._run_cli(
            ["mod.py", "--update-baseline", "--no-baseline"], tmp_path)
        assert r.returncode == 2
        assert "discard" in r.stderr
        kept = json.loads(
            (tmp_path / "analysis_baseline.json").read_text())
        assert kept["entries"][0]["justification"] == "reviewed: keep me"

    def test_sarif_unsuppressed_finding_has_no_suppressions(self, tmp_path):
        (tmp_path / "mod.py").write_text(self.FIXTURE)
        r = self._run_cli(
            ["mod.py", "--format", "sarif", "--no-baseline"], tmp_path)
        assert r.returncode == 1  # findings still drive the exit code
        log = json.loads(r.stdout)
        (result,) = log["runs"][0]["results"]
        assert "suppressions" not in result

    def test_vmem_budget_flag_reaches_apx304(self, tmp_path):
        (tmp_path / "mod.py").write_text(textwrap.dedent("""
            from jax.experimental import pallas as pl

            def launch(x):
                return pl.pallas_call(
                    _body, grid=(4,),
                    in_specs=[pl.BlockSpec((256, 512), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((256, 512), lambda i: (i, 0)),
                )(x)
            """))
        assert self._run_cli(["mod.py"], tmp_path).returncode == 0
        r = self._run_cli(
            ["mod.py", "--vmem-budget-mib", "0.125"], tmp_path)
        assert r.returncode == 1
        assert "APX304" in r.stdout


# --------------------------------------- APX108 host sync in step loops
class TestBlockingHostSyncInStepLoop:
    """APX108: float()/.item()/np.asarray/f-string of a proven device
    array inside a loop that dispatches a compiled step — the per-step
    sync barrier the observability async-fetch seam exists to remove."""

    def test_positive_float_of_jit_result_in_loop(self, tmp_path):
        got = run("""
            import jax
            step = jax.jit(lambda p: (p, p.sum()))
            def train(params):
                for i in range(10):
                    params, loss = step(params)
                    print(float(loss))
            """, tmp_path, [BlockingHostSyncInStepLoop()])
        assert rule_ids(got) == ["APX108"]
        assert "float()" in got[0].message

    def test_positive_builder_and_run_step_indirection(self, tmp_path):
        """The pre-fix pretrain_gpt shape: the step comes from a
        builder (`step = build_step()`), dispatch goes through a local
        retry wrapper (`run_step`), and the f-string formats the
        wrapper's result — still proven, still flagged."""
        got = run("""
            from apex_tpu.models.gpt import make_train_step

            def main():
                def build_step():
                    return make_train_step(None, None, None)

                step = build_step()

                def run_step(t):
                    return step(t)

                for i in range(8):
                    params, state, loss = run_step(i)
                    print(f"step {i}: loss={loss:.4f}")
            """, tmp_path, [BlockingHostSyncInStepLoop()])
        assert rule_ids(got) == ["APX108"]
        assert "f-string" in got[0].message

    def test_positive_item_and_np_asarray_in_while(self, tmp_path):
        got = run("""
            import jax
            import numpy as np
            f = jax.jit(lambda x: x)
            def loop():
                out = None
                while True:
                    out = f(1)
                    a = out.item()
                    b = np.asarray(out)
            """, tmp_path, [BlockingHostSyncInStepLoop()])
        assert rule_ids(got) == ["APX108", "APX108"]
        assert {".item()" in f.message or "np.asarray" in f.message
                for f in got} == {True}

    def test_positive_attribute_off_device_tuple(self, tmp_path):
        """float(scaler_state.loss_scale): the base name is the step
        result, the attribute read still materializes on host."""
        got = run("""
            from apex_tpu.models.gpt import make_train_step
            step = make_train_step(1, 2, 3)
            def train(p, s, sc, t):
                for i in range(4):
                    p, s, sc, loss = step(p, s, sc, t)
                    print(float(sc.loss_scale))
            """, tmp_path, [BlockingHostSyncInStepLoop()])
        assert rule_ids(got) == ["APX108"]

    def test_negative_conversion_after_loop_and_async_seam(self, tmp_path):
        """The allowed spellings: hand the array to the fetch seam in
        the loop, convert AFTER the loop, format only harvested host
        values."""
        got = run("""
            import jax
            step = jax.jit(lambda p: (p, p))
            def train(params, fetcher):
                loss = None
                for i in range(10):
                    params, loss = step(params)
                    fetcher.put("loss", i, {"loss": loss})
                    for kind, s, tree in fetcher.ready():
                        print(f"step {s}: loss={float(tree['loss']):.4f}")
                print(float(loss))
            """, tmp_path, [BlockingHostSyncInStepLoop()])
        assert got == []

    def test_negative_jnp_asarray_and_non_device_values(self, tmp_path):
        """jnp.asarray stays on device; float() of a plain loop index
        or of an unproven name is not flagged."""
        got = run("""
            import jax
            import jax.numpy as jnp
            step = jax.jit(lambda p: p)
            def train(params, mystery):
                for i in range(10):
                    params = step(params)
                    x = jnp.asarray(params)
                    y = float(i)
                    z = float(mystery)
            """, tmp_path, [BlockingHostSyncInStepLoop()])
        assert got == []

    def test_negative_loop_without_step_dispatch(self, tmp_path):
        """A conversion in a loop that does NOT dispatch a step is not
        a per-step sync barrier (the post-run report loop shape)."""
        got = run("""
            import jax
            step = jax.jit(lambda p: p)
            def report(params):
                out = step(params)
                for i in range(10):
                    print(float(out))
            """, tmp_path, [BlockingHostSyncInStepLoop()])
        assert got == []

    def test_rides_default_rules(self, tmp_path):
        got = run("""
            import jax
            step = jax.jit(lambda p: p)
            def train(p):
                for i in range(4):
                    p = step(p)
                    print(float(p))
            """, tmp_path, DEFAULT_RULES)
        assert "APX108" in rule_ids(got)


# ------------------------------------ APX112 unseamed dispatch timing
class TestUnseamedDispatchTiming:
    """APX112: a wall-clock delta spanning a proven step dispatch with
    no block_until_ready/host-read/async-fetch seam — async dispatch
    makes such timings enqueue measurements, not step times."""

    def test_positive_delta_around_dispatch(self, tmp_path):
        got = run("""
            import time
            import jax
            step = jax.jit(lambda p: p)
            def bench(p):
                t0 = time.perf_counter()
                p = step(p)
                dt = time.perf_counter() - t0
                return dt
            """, tmp_path, [UnseamedDispatchTiming()])
        assert rule_ids(got) == ["APX112"]
        assert "enqueue" in got[0].message

    def test_positive_two_stamp_spelling_and_from_import(self, tmp_path):
        """t1 = perf_counter(); dt = t1 - t0 — the second stamp, not
        the subtraction, is the read that lies."""
        got = run("""
            from time import perf_counter
            from apex_tpu.models.gpt import make_train_step
            step = make_train_step(1, 2, 3)
            def bench(p, s, t, y):
                t0 = perf_counter()
                p, s, loss = step(p, s, t, y)
                t1 = perf_counter()
                print(float(loss))  # AFTER t1: does not unlie it
                dt = t1 - t0
            """, tmp_path, [UnseamedDispatchTiming()])
        assert rule_ids(got) == ["APX112"]

    def test_positive_dispatch_loop_between_stamps(self, tmp_path):
        got = run("""
            import time
            import jax
            step = jax.jit(lambda p: p)
            def bench(p, iters):
                t0 = time.time()
                for _ in range(iters):
                    p = step(p)
                dt = time.time() - t0
            """, tmp_path, [UnseamedDispatchTiming()])
        assert rule_ids(got) == ["APX112"]

    def test_positive_warmup_seam_does_not_acquit_timed_loop(self,
                                                            tmp_path):
        """A seam after the WARMUP dispatch must not acquit the timed
        loop's own (later, unseamed) dispatches."""
        got = run("""
            import time
            import jax
            step = jax.jit(lambda p: p)
            def bench(p, iters):
                t0 = time.perf_counter()
                p = step(p)                 # warmup
                jax.block_until_ready(p)    # seam covers ONLY warmup
                for _ in range(iters):
                    p = step(p)             # the timed dispatches
                dt = time.perf_counter() - t0
            """, tmp_path, [UnseamedDispatchTiming()])
        assert rule_ids(got) == ["APX112"]

    def test_negative_rebound_stamp_is_data_not_timing(self, tmp_path):
        """Reusing a stamp name for NON-clock data invalidates the
        stamp: the later delta is arithmetic, not a dispatch timing —
        flagging it would turn the gate red on clean code."""
        got = run("""
            import time
            import jax
            step = jax.jit(lambda p: p)
            def bench(p, offsets):
                t0 = time.time()
                p = step(p)
                jax.block_until_ready(p)
                warm = time.time() - t0     # properly seamed
                t0 = offsets[0]             # name reused for DATA
                p = step(p)
                shifted = time.time() - t0  # data math, not timing
                return warm, shifted
            """, tmp_path, [UnseamedDispatchTiming()])
        assert got == []

    def test_negative_block_until_ready_seam(self, tmp_path):
        got = run("""
            import time
            import jax
            step = jax.jit(lambda p: p)
            def bench(p, iters):
                t0 = time.perf_counter()
                for _ in range(iters):
                    p = step(p)
                jax.block_until_ready(p)
                dt = time.perf_counter() - t0
            """, tmp_path, [UnseamedDispatchTiming()])
        assert got == []

    def test_negative_host_read_and_local_seam_wrapper(self, tmp_path):
        """float(loss) is a sync; so is calling a local def that wraps
        block_until_ready (the bench.py `block(tree)` idiom)."""
        got = run("""
            import time
            import jax
            step = jax.jit(lambda p: (p, p.sum()))

            def block(tree):
                for x in jax.tree.leaves(tree):
                    jax.block_until_ready(x)

            def bench(p, iters):
                t0 = time.perf_counter()
                p, loss = step(p)
                host = float(loss)
                dt1 = time.perf_counter() - t0
                t2 = time.perf_counter()
                p, loss = step(p)
                block(loss)
                dt2 = time.perf_counter() - t2
            """, tmp_path, [UnseamedDispatchTiming()])
        assert got == []

    def test_negative_no_dispatch_between_stamps(self, tmp_path):
        """Deltas around host work, or taken before the dispatch, and
        unproven callees between stamps are all trusted."""
        got = run("""
            import time
            import jax
            step = jax.jit(lambda p: p)
            def bench(p, mystery):
                t0 = time.time()
                q = mystery(p)
                setup = time.time() - t0
                p = step(p)
                t1 = time.time()
                host_only = sum(range(100))
                dt = time.time() - t1
            """, tmp_path, [UnseamedDispatchTiming()])
        assert got == []

    def test_negative_nonclock_subtraction_names(self, tmp_path):
        got = run("""
            import time
            import jax
            step = jax.jit(lambda p: p)
            def bench(p, a, b):
                t0 = a  # not a clock read
                p = step(p)
                dt = b - t0
            """, tmp_path, [UnseamedDispatchTiming()])
        assert got == []

    def test_rides_default_rules(self, tmp_path):
        got = run("""
            import time
            import jax
            step = jax.jit(lambda p: p)
            def bench(p):
                t0 = time.time()
                p = step(p)
                return time.time() - t0
            """, tmp_path, DEFAULT_RULES)
        assert "APX112" in rule_ids(got)


# ------------------------------------------------- the repo-wide rider
class TestRepoIsClean:
    """The tier-1 rider: the shipped tree stays clean modulo the
    committed baseline, and every baseline entry still bites."""

    def _repo_findings(self):
        paths = [str(REPO / "apex_tpu"), str(REPO / "bench.py"),
                 str(REPO / "examples")]
        return analyze_paths(paths, DEFAULT_RULES, rel_to=str(REPO))

    def test_repo_clean_modulo_baseline(self):
        entries = load_baseline(str(REPO / "analysis_baseline.json"))
        kept, _, stale = apply_baseline(self._repo_findings(), entries)
        assert not kept, "new analyzer findings:\n" + "\n".join(
            f.render() for f in kept)
        assert not stale, "stale baseline entries (fixed code? remove " \
            "them): " + ", ".join(f"{e.rule} {e.path}" for e in stale)

    def test_advice_r5_fixes_are_in_the_tree(self):
        """The three ADVICE r5 findings must stay FIXED (their pre-fix
        shapes are pinned by the fixture tests above): no APX102 left in
        bench.py, no APX302 in the Pallas ops, no APX401 in gpt.py."""
        by_rule = {}
        for f in self._repo_findings():
            by_rule.setdefault(f.rule, []).append(f.path)
        assert "bench.py" not in by_rule.get("APX102", [])
        assert not [p for p in by_rule.get("APX302", [])
                    if p.startswith("apex_tpu/ops/")]
        assert "apex_tpu/models/gpt.py" not in by_rule.get("APX401", [])

    def test_cli_acceptance_command(self):
        """`python -m apex_tpu.analysis apex_tpu bench.py` exits 0."""
        r = subprocess.run(
            [sys.executable, "-m", "apex_tpu.analysis",
             "apex_tpu", "bench.py"],
            cwd=str(REPO), capture_output=True, text=True, timeout=600)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_cli_from_foreign_cwd_finds_baseline(self, tmp_path):
        """The committed baseline must be picked up when the CLI runs
        from another directory with absolute paths (pre-commit hooks,
        CI jobs) — review finding: CWD-relative default dropped it."""
        import os

        env = dict(os.environ, PYTHONPATH=str(REPO))
        r = subprocess.run(
            [sys.executable, "-m", "apex_tpu.analysis",
             str(REPO / "apex_tpu"), str(REPO / "bench.py")],
            cwd=str(tmp_path), env=env, capture_output=True, text=True,
            timeout=600)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "baselined" in r.stderr


# ------------------------------------------------ rule-hygiene meta-lint
class TestRuleHygieneMetaLint:
    """Every registered APX rule must ship documented and fixtured:
    a docs/static_analysis.md table row, and a Test<RuleClass> class
    here with at least one test_positive* and one test_negative*
    method.  The next rule someone lands undocumented or untested
    fails THIS test, not a review comment."""

    def _rule_classes(self):
        return {type(r).__name__: r.rule_id for r in DEFAULT_RULES}

    def test_every_rule_has_a_docs_row(self):
        docs = (REPO / "docs" / "static_analysis.md").read_text()
        import re as _re

        documented = set(_re.findall(r"^\|\s*(APX\d+)\s*\|", docs,
                                     _re.M))
        missing = {rid for rid in self._rule_classes().values()
                   if rid not in documented}
        assert not missing, (
            f"rules with no docs/static_analysis.md table row: "
            f"{sorted(missing)} — add the row (what it catches / why "
            f"it only fails on the chip)")

    def test_every_rule_has_positive_and_negative_fixtures(self):
        import ast as _ast

        tree = _ast.parse(Path(__file__).read_text())
        classes = {
            n.name: [m.name for m in n.body
                     if isinstance(m, _ast.FunctionDef)]
            for n in tree.body if isinstance(n, _ast.ClassDef)
        }
        problems = []
        for cls, rid in self._rule_classes().items():
            test_cls = f"Test{cls}"
            methods = classes.get(test_cls)
            if methods is None:
                problems.append(f"{rid}: no {test_cls} class")
                continue
            if not any(m.startswith("test_positive") for m in methods):
                problems.append(f"{rid}: {test_cls} has no "
                                f"test_positive* fixture")
            if not any(m.startswith("test_negative") for m in methods):
                problems.append(f"{rid}: {test_cls} has no "
                                f"test_negative* fixture")
        assert not problems, "\n".join(problems)


# ------------------------------------------- CLI performance and hygiene
class TestCliPerformanceAndHygiene:
    def test_repo_scan_stays_fast(self):
        """The analyzer rides tier-1 AND pre-commit: the full repo scan
        must stay interactive.  Measured ~9 s CPU on this 1-core box
        WITH the divergence tier (the taint lattice adds its per-module
        event replay and the link_taint cross-module fixpoint — ~1 s
        over the pre-APX209 scan); the 30 s budget is ~3x headroom
        while still catching an accidentally-quadratic rule or
        fixpoint.  CPU time, not wall time: this box's wall-clock
        tests false-fire under CPU contention (the gpt_example
        watchdog class), and the hazard this test guards is
        algorithmic, not scheduling."""
        import time

        paths = [str(REPO / "apex_tpu"), str(REPO / "bench.py")]
        t0 = time.process_time()
        analyze_paths(paths, DEFAULT_RULES, rel_to=str(REPO))
        dt = time.process_time() - t0
        assert dt < 30.0, f"repo scan took {dt:.1f}s CPU (budget 30s)"

    def test_jobs_results_identical(self):
        """--jobs may change wall time, never findings: the parallel
        parse/index pass over a real subtree must produce byte-equal
        findings to the serial one."""
        paths = [str(REPO / "apex_tpu" / "ops"), str(REPO / "bench.py")]
        serial = analyze_paths(paths, DEFAULT_RULES, rel_to=str(REPO))
        parallel = analyze_paths(paths, DEFAULT_RULES, rel_to=str(REPO),
                                 jobs=2)
        assert [f.to_json() for f in serial] \
            == [f.to_json() for f in parallel]

    def test_timing_collects_per_rule_walltime(self):
        timings = {}
        analyze_paths([str(REPO / "apex_tpu" / "analysis")],
                      DEFAULT_RULES, timings=timings)
        assert "<load>" in timings and "<link>" in timings
        ids = {r.rule_id for r in DEFAULT_RULES}
        assert ids <= set(timings), ids - set(timings)
        assert all(v >= 0 for v in timings.values())

    def test_cli_check_baseline_fails_on_stale_entry(self, tmp_path):
        """--check-baseline turns a stale suppression into exit 1 —
        without it the note on stderr scrolls past and the entry rots
        (matching the next unrelated finding that drifts into its
        substring)."""
        import os

        (tmp_path / "mod.py").write_text("import os\n")
        (tmp_path / "analysis_baseline.json").write_text(json.dumps({
            "entries": [{"rule": "APX101", "path": "never.py",
                         "symbol": "*", "contains": "",
                         "justification": "covers deleted code"}]}))
        env = dict(os.environ, PYTHONPATH=str(REPO))
        base = [sys.executable, "-m", "apex_tpu.analysis", "mod.py"]
        clean = subprocess.run(base, cwd=str(tmp_path), env=env,
                               capture_output=True, text=True, timeout=120)
        assert clean.returncode == 0, clean.stderr
        checked = subprocess.run(base + ["--check-baseline"],
                                 cwd=str(tmp_path), env=env,
                                 capture_output=True, text=True,
                                 timeout=120)
        assert checked.returncode == 1
        assert "stale baseline entry" in checked.stderr
        assert "--check-baseline" in checked.stderr

    def test_cli_sarif_failure_prints_human_summary(self, tmp_path):
        """The red-CI-log fix: --format sarif on a failing tree must
        name the findings count and rule ids on stderr, not just dump
        the SARIF document."""
        import os

        (tmp_path / "bad.py").write_text(textwrap.dedent("""
            import jax, os

            @jax.jit
            def f(x):
                return x if os.environ.get("FLAG") else -x
            """))
        env = dict(os.environ, PYTHONPATH=str(REPO))
        r = subprocess.run(
            [sys.executable, "-m", "apex_tpu.analysis", "bad.py",
             "--no-baseline", "--format", "sarif"],
            cwd=str(tmp_path), env=env, capture_output=True, text=True,
            timeout=120)
        assert r.returncode == 1
        assert "APX101" in r.stderr and "finding(s)" in r.stderr
        doc = json.loads(r.stdout)   # the SARIF document stays valid
        assert doc["runs"][0]["results"]

    def test_repo_scan_has_no_stale_baseline_via_cli_flag(self):
        """The repo-level --check-baseline run the CI target uses."""
        r = subprocess.run(
            [sys.executable, "-m", "apex_tpu.analysis", "apex_tpu",
             "bench.py", "--check-baseline"],
            cwd=str(REPO), capture_output=True, text=True, timeout=600)
        assert r.returncode == 0, r.stdout + r.stderr


# --------------------------------------- APX209 rank-gated collective launch
#: the shared scaffolding of the divergence fixtures: a registered-axis
#: collective inside a shard_map step
_STEP_PRELUDE = textwrap.dedent("""
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    def grad_sync(g):
        return jax.lax.psum(g, "dp")

    step = shard_map(grad_sync, mesh=mesh, in_specs=P("dp"),
                     out_specs=P("dp"))
""")


def run_div(src, tmp_path, rules, axes=AXES):
    """``run`` with the shard_map step prelude prepended (both parts
    dedented independently — the fixture bodies sit at test-method
    indentation, the prelude at module level)."""
    return run(_STEP_PRELUDE + textwrap.dedent(src), tmp_path, rules,
               axes)


class TestTaintedPredicateGuardsCollective:
    def test_positive_rank_zero_probe(self, tmp_path):
        """The canonical bug: only rank 0 launches the collective-
        bearing step — its peers block in the psum forever."""
        got = run_div("""
            def maybe_probe(x):
                if jax.process_index() == 0:
                    return step(x)
                return x
            """, tmp_path, [TaintedPredicateGuardsCollective()])
        assert rule_ids(got) == ["APX209"]
        assert "wedges" in got[0].message
        assert "process_index" in got[0].message

    def test_positive_taint_through_partial_and_conditional_join(
            self, tmp_path):
        """The value survives a functools.partial alias AND a
        conditional clean rebind (the branch may not execute, so the
        taint only joins — it never clears)."""
        got = run_div("""
            import functools

            who = functools.partial(jax.process_index)

            def maybe_probe(x, flag):
                r = who()
                if flag:
                    r = 0
                if r == 0:
                    return step(x)
                return x
            """, tmp_path, [TaintedPredicateGuardsCollective()])
        assert rule_ids(got) == ["APX209"]

    def test_negative_both_branches_launch(self, tmp_path):
        """Branching on rank is fine when EVERY path launches the same
        traced step — per-rank logging around a uniform launch."""
        got = run_div("""
            def maybe_probe(x):
                if jax.process_index() == 0:
                    return step(x * 2)
                return step(x)
            """, tmp_path, [TaintedPredicateGuardsCollective()])
        assert got == []

    def test_negative_straight_line_rebind_clears(self, tmp_path):
        """An unconditional clean rebind kills the taint — the value
        the predicate reads no longer depends on the rank."""
        got = run_div("""
            def maybe_probe(x):
                rank = jax.process_index()
                rank = 0
                if rank == 0:
                    return step(x)
                return x
            """, tmp_path, [TaintedPredicateGuardsCollective()])
        assert got == []

    def test_negative_acquitted_by_uniformity_seam(self, tmp_path):
        """A function that routes the decision through the runtime
        uniformity seam has DECLARED the divergence risk — the runtime
        tier owns it from there."""
        got = run_div("""
            from apex_tpu.resilience.uniformity import assert_uniform

            def maybe_probe(x):
                probe = jax.process_index() == 0
                assert_uniform("probe.rank0", bool(probe))
                if probe:
                    return step(x)
                return x
            """, tmp_path, [TaintedPredicateGuardsCollective()])
        assert got == []


# ------------------------------------------- APX210 tainted compiled shapes
class TestTaintedValueShapesCompiledProgram:
    def test_positive_rank_into_jit_static_arg(self, tmp_path):
        got = run("""
            import jax

            def f(x, variant):
                return x * variant

            step = jax.jit(f, static_argnums=(1,))

            def launch(x):
                return step(x, jax.process_index())
            """, tmp_path, [TaintedValueShapesCompiledProgram()])
        assert rule_ids(got) == ["APX210"]
        assert "static argument" in got[0].message

    def test_positive_env_into_mesh_construction(self, tmp_path):
        got = run("""
            import os
            import jax
            from jax.sharding import Mesh

            def build():
                n = int(os.getenv("APEX_DP", "8"))
                return Mesh(jax.devices()[:n], ("dp",))
            """, tmp_path, [TaintedValueShapesCompiledProgram()])
        assert rule_ids(got) == ["APX210"]
        assert "mesh construction" in got[0].message

    def test_positive_env_into_bucket_plan_shape(self, tmp_path):
        got = run("""
            import os
            from apex_tpu.optimizers import bucketing

            def build(treedef, shapes):
                cap = int(os.getenv("APEX_CAP", "0")) or None
                return bucketing.plan_of_shapes(treedef, shapes,
                                                cap_bytes=cap)
            """, tmp_path, [TaintedValueShapesCompiledProgram()])
        assert rule_ids(got) == ["APX210"]
        assert "plan" in got[0].message

    def test_negative_threaded_config_is_clean(self, tmp_path):
        """Parameters are always clean: threading the value IN is the
        blessed pattern the fix hint prescribes."""
        got = run("""
            import jax
            from jax.sharding import Mesh

            def build(n, cap_bytes):
                return Mesh(jax.devices()[:n], ("dp",))

            def launch(step, x, variant):
                return step(x, variant)
            """, tmp_path, [TaintedValueShapesCompiledProgram()])
        assert got == []


# --------------------------------------- APX211 rank-divergent dispatch
class TestTaintedEngineDispatchDivergence:
    def test_positive_env_gated_kernel_impl(self, tmp_path):
        got = run("""
            import os
            import jax

            def n_shards():
                return jax.process_count()

            def forward(x):
                impl = os.getenv("APEX_ATTN", "auto")
                if impl == "pallas":
                    return pallas_attention(x)
                return xla_attention(x)
            """, tmp_path, [TaintedEngineDispatchDivergence()])
        assert rule_ids(got) == ["APX211"]
        assert "divergent SPMD programs" in got[0].message

    def test_negative_module_without_multiprocess_reach(self, tmp_path):
        """No mention of process_count: nothing scopes this module
        into multi-process reachability — single-host env dispatch is
        the supported configuration surface."""
        got = run("""
            import os

            def forward(x):
                impl = os.getenv("APEX_ATTN", "auto")
                if impl == "pallas":
                    return pallas_attention(x)
                return xla_attention(x)
            """, tmp_path, [TaintedEngineDispatchDivergence()])
        assert got == []

    def test_negative_acquitted_by_uniformity_seam(self, tmp_path):
        got = run("""
            import os
            import jax
            from apex_tpu.resilience.uniformity import assert_uniform

            def n_shards():
                return jax.process_count()

            def forward(x):
                impl = os.getenv("APEX_ATTN", "auto")
                assert_uniform("attn.impl", impl)
                if impl == "pallas":
                    return pallas_attention(x)
                return xla_attention(x)
            """, tmp_path, [TaintedEngineDispatchDivergence()])
        assert got == []

    def test_negative_registry_engaged_shape_stays_quiet(self, tmp_path):
        """The fail-fast spelling the repo itself uses: branch on the
        topology, return a constant — no dispatch in the branch."""
        got = run("""
            import jax

            def registry_engaged(forced):
                if jax.process_count() > 1:
                    return False
                return not forced
            """, tmp_path, [TaintedEngineDispatchDivergence()])
        assert got == []


# ------------------------------------------------ taint-lattice edge cases
class TestTaintLatticeEdgeCases:
    """The dataflow semantics the three rules rest on, probed directly
    through rule behavior: event ordering, aliasing, and the
    cross-module fixpoint (including cycles)."""

    def test_shadowed_rebind_inside_nested_function_is_clean(
            self, tmp_path):
        """A parameter shadows an outer tainted name — parameters are
        always clean, even when the caller passes rank in."""
        got = run_div("""
            rank = jax.process_index()

            def probe(rank, x):
                if rank == 0:
                    return step(x)
                return x
            """, tmp_path, [TaintedPredicateGuardsCollective()])
        assert got == []

    def test_outer_tainted_name_reaches_nested_function(self, tmp_path):
        """...but WITHOUT the shadowing parameter, the module-level
        tainted binding flows in through the enclosing scope."""
        got = run_div("""
            rank = jax.process_index()

            def probe(x):
                if rank == 0:
                    return step(x)
                return x
            """, tmp_path, [TaintedPredicateGuardsCollective()])
        assert rule_ids(got) == ["APX209"]

    def test_partial_of_clean_function_is_clean(self, tmp_path):
        got = run_div("""
            import functools

            def fixed():
                return 0

            who = functools.partial(fixed)

            def probe(x):
                if who() == 0:
                    return step(x)
                return x
            """, tmp_path, [TaintedPredicateGuardsCollective()])
        assert got == []

    def test_cross_module_taint_cycle_converges_and_flags(self, tmp_path):
        """Two modules whose taint-returning helpers call ACROSS the
        module boundary in a cycle: the link_taint fixpoint must
        terminate and still carry process_index's taint around the
        loop into the guarded launch."""
        from apex_tpu.analysis import analyze_paths

        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "ident.py").write_text(textwrap.dedent("""
            import jax

            from pkg.roles import role_of

            def rank():
                return jax.process_index()

            def rank_or_role(named):
                if named:
                    return role_of()
                return rank()
            """))
        (pkg / "roles.py").write_text(_STEP_PRELUDE + textwrap.dedent("""
            from pkg.ident import rank_or_role

            def role_of():
                return rank_or_role(False)

            def probe(x):
                if role_of() == 0:
                    return step(x)
                return x
            """))
        got = analyze_paths([str(pkg)],
                            [TaintedPredicateGuardsCollective()], {"dp"})
        assert rule_ids(got) == ["APX209"]
        assert got[0].path.endswith("roles.py")


# ---------------------------------------- CLI: --only-rules / --skip-rules
class TestCliRuleSelection:
    FIXTURE = textwrap.dedent("""
        import os
        import jax

        @jax.jit
        def f(x):
            return x if os.environ.get("FLAG") else -x
        """)

    def _run_cli(self, args, cwd):
        import os as _os

        env = dict(_os.environ, PYTHONPATH=str(REPO))
        return subprocess.run(
            [sys.executable, "-m", "apex_tpu.analysis", *args],
            cwd=str(cwd), env=env, capture_output=True, text=True,
            timeout=600)

    def test_only_rules_scopes_the_run(self, tmp_path):
        (tmp_path / "mod.py").write_text(self.FIXTURE)
        r = self._run_cli(["mod.py", "--no-baseline",
                           "--only-rules", "APX101"], tmp_path)
        assert r.returncode == 1 and "APX101" in r.stdout
        # scoped AWAY from the finding's rule: clean exit
        r = self._run_cli(["mod.py", "--no-baseline",
                           "--only-rules", "APX104"], tmp_path)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_skip_rules_drops_the_finding(self, tmp_path):
        (tmp_path / "mod.py").write_text(self.FIXTURE)
        r = self._run_cli(["mod.py", "--no-baseline",
                           "--skip-rules", "APX101"], tmp_path)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_unknown_rule_id_is_a_usage_error(self, tmp_path):
        (tmp_path / "mod.py").write_text(self.FIXTURE)
        for flag in ("--only-rules", "--skip-rules"):
            r = self._run_cli(["mod.py", flag, "APX999"], tmp_path)
            assert r.returncode == 2
            assert "unknown rule id" in r.stderr

    def test_selecting_everything_away_is_an_error(self, tmp_path):
        (tmp_path / "mod.py").write_text(self.FIXTURE)
        r = self._run_cli(["mod.py", "--only-rules", "APX101",
                           "--skip-rules", "APX101"], tmp_path)
        assert r.returncode == 2
        assert "nothing to run" in r.stderr

    def test_timing_json_artifact_and_family_rollup(self, tmp_path):
        (tmp_path / "mod.py").write_text(self.FIXTURE)
        out = tmp_path / "timing.json"
        r = self._run_cli(["mod.py", "--no-baseline", "--timing",
                           "--timing-json", str(out)], tmp_path)
        assert r.returncode == 1
        timings = json.loads(out.read_text())
        assert "<load>" in timings and "<link>" in timings
        assert "APX101" in timings
        assert "timing: family" in r.stderr
        assert "distributed" in r.stderr


# ----------------------------------------------- SARIF partialFingerprints
class TestSarifPartialFingerprints:
    SRC = textwrap.dedent("""
        import os
        import jax

        @jax.jit
        def f(x):
            return x if os.environ.get("FLAG") else -x
        """)

    def _fingerprints(self, tmp_path, src, name):
        p = tmp_path / name
        p.write_text(src)
        got = analyze_file(str(p), [TraceTimeHostStateRead()], set())
        log = sarif.render(got, [], [TraceTimeHostStateRead()])
        return [(r["partialFingerprints"]["apexContextHash/v1"],
                 r["locations"][0]["physicalLocation"]["region"]
                  ["startLine"]) for r in log["runs"][0]["results"]]

    def test_fingerprint_survives_line_shift(self, tmp_path):
        """The round-trip code scanning depends on: shifting a finding
        down the file (the every-commit event) keeps its fingerprint —
        keying on the line would re-open the alert each time."""
        base = self._fingerprints(tmp_path, self.SRC, "a.py")
        shifted = self._fingerprints(
            tmp_path, "\n# padding\n# padding\n\n" + self.SRC, "a.py")
        (fp1, line1), (fp2, line2) = base[0], shifted[0]
        assert line2 > line1          # the finding really moved
        assert fp1 == fp2             # ...and the identity did not

    def test_distinct_findings_get_distinct_fingerprints(self, tmp_path):
        fps = self._fingerprints(tmp_path, self.SRC + textwrap.dedent("""
            @jax.jit
            def g(x):
                return x if os.environ.get("OTHER") else -x
            """), "b.py")
        assert len(fps) == 2
        assert fps[0][0] != fps[1][0]


# -------------------------------------- APX114 thread-unsafe shared writes
class TestSharedMutationWithoutLock:
    def test_positive_thread_target_mutates_locked_attr(self, tmp_path):
        got = run("""
            import threading

            class Acc:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._tokens = 0
                    threading.Thread(target=self._persist).start()

                def add(self, n):
                    with self._lock:
                        self._tokens += n

                def _persist(self):
                    self._tokens = 0
            """, tmp_path, [SharedMutationWithoutLock()])
        assert rule_ids(got) == ["APX114"]
        assert "_tokens" in got[0].message
        assert "Acc.add" in got[0].message       # the locked other site
        assert "_persist" in got[0].symbol

    def test_positive_prefix_goodput_accountant_shape(self, tmp_path):
        """The literal PR 10 review finding, as a regression fixture:
        the main-thread mutators take ``self._lock``, but ``finalize``
        — reachable from the watchdog's ``on_wedge=`` callback seam,
        i.e. the monitor thread — writes the same accumulators bare.
        The rule must flag the pre-fix spelling forever (the post-fix
        live tree stays clean via TestRepoIsClean)."""
        got = run("""
            import threading

            class StepWatchdog:
                def check(self):
                    pass

            class GoodputAccountant:
                def __init__(self, path):
                    self._lock = threading.RLock()
                    self._path = path
                    self._productive_s = 0.0
                    self._lost_s = 0.0
                    self._events = []

                def record_step(self, seconds):
                    with self._lock:
                        self._productive_s += seconds
                        self._persist()

                def record_loss(self, seconds, why):
                    with self._lock:
                        self._lost_s += seconds
                        self._events.append(why)
                        self._persist()

                def _persist(self):
                    pass

                def finalize(self, why):
                    # pre-fix: no lock — but this runs on the WATCHDOG
                    # thread via on_wedge while record_step runs on main
                    self._lost_s += 1.0
                    self._events.append(why)
                    self._persist()

            def install(acc):
                wd = StepWatchdog()
                wd.on_wedge = lambda info: acc.finalize("wedge")
                threading.Thread(target=wd.check).start()
                return wd
            """, tmp_path, [SharedMutationWithoutLock()])
        assert "APX114" in rule_ids(got)
        assert any("finalize" in f.symbol for f in got)

    def test_positive_prefix_flightrec_dump_shape(self, tmp_path):
        """The PR 14 review finding: ``record_event`` appends to the
        ring under ``self._lock`` on the main thread, while the dump
        path — reached from the watchdog's ``on_wedge`` — drained the
        same ring with NO lock (the dump-vs-checkpoint torn-read/lost-
        event race, fixed by copying under the lock in ``snapshot``)."""
        got = run("""
            import threading

            class FlightRecorder:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._events = []

                def record_event(self, e):
                    with self._lock:
                        self._events.append(e)

                def dump(self, reason):
                    # pre-fix: read+clear outside the lock, on the
                    # watchdog thread, racing main-thread record_event
                    rec = list(self._events)
                    self._events.clear()
                    return rec

            def install(rec, watchdog):
                watchdog.arm(on_wedge=lambda info: rec.dump("wedge"))
            """, tmp_path, [SharedMutationWithoutLock()])
        assert "APX114" in rule_ids(got)
        assert any("dump" in f.symbol for f in got)

    def test_positive_cross_module_thread_target(self, tmp_path):
        """The thread entry lives in ANOTHER module: main.py starts a
        Thread on worker.Acc._persist's bound method via the instance
        it builds — the link_threads fixpoint must carry thread-
        reachability across the import edge."""
        (tmp_path / "worker.py").write_text(textwrap.dedent("""
            import threading

            class Acc:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def add(self):
                    with self._lock:
                        self._n += 1

                def spill(self):
                    self._n = 0
            """))
        (tmp_path / "main.py").write_text(textwrap.dedent("""
            import threading
            from worker import Acc

            def launch():
                acc = Acc()
                threading.Thread(target=acc.spill).start()
            """))
        got = analyze_paths([str(tmp_path / "worker.py"),
                             str(tmp_path / "main.py")],
                            [SharedMutationWithoutLock()], set(AXES))
        assert "APX114" in rule_ids(got)

    def test_negative_all_sites_locked(self, tmp_path):
        got = run("""
            import threading

            class Acc:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._tokens = 0
                    threading.Thread(target=self._persist).start()

                def add(self, n):
                    with self._lock:
                        self._tokens += n

                def _persist(self):
                    with self._lock:
                        self._tokens = 0
            """, tmp_path, [SharedMutationWithoutLock()])
        assert got == []

    def test_negative_no_lock_discipline_declared(self, tmp_path):
        """A class with NO locked site for the attribute is a design
        choice (maybe GIL-atomic, maybe wrong — but there is no
        declared discipline being violated): quiet."""
        got = run("""
            import threading

            class Flag:
                def __init__(self):
                    self.hit = False
                    threading.Thread(target=self._mark).start()

                def _mark(self):
                    self.hit = True
            """, tmp_path, [SharedMutationWithoutLock()])
        assert got == []

    def test_negative_acquitted_by_assert_lock_held(self, tmp_path):
        """The assert_lock_held seam: the mutator's contract is "my
        caller holds the lock", checked at runtime — acquitted."""
        got = run("""
            import threading
            from apex_tpu.resilience.locks import assert_lock_held

            class Acc:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._tokens = 0
                    threading.Thread(target=self._persist).start()

                def add(self, n):
                    with self._lock:
                        self._tokens += n

                def _persist(self):
                    assert_lock_held(self._lock)
                    self._tokens = 0
            """, tmp_path, [SharedMutationWithoutLock()])
        assert got == []

    def test_negative_acquire_release_pairing_counts_as_locked(
            self, tmp_path):
        got = run("""
            import threading

            class Acc:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._tokens = 0
                    threading.Thread(target=self._persist).start()

                def add(self, n):
                    with self._lock:
                        self._tokens += n

                def _persist(self):
                    self._lock.acquire()
                    try:
                        self._tokens = 0
                    finally:
                        self._lock.release()
            """, tmp_path, [SharedMutationWithoutLock()])
        assert got == []

    def test_negative_main_thread_only_class(self, tmp_path):
        """No thread entry anywhere in the module: quiet even with
        asymmetric locking (single-threaded code may lock for re-use
        from threaded callers it does not itself create)."""
        got = run("""
            import threading

            class Acc:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._tokens = 0

                def add(self, n):
                    with self._lock:
                        self._tokens += n

                def reset(self):
                    self._tokens = 0
            """, tmp_path, [SharedMutationWithoutLock()])
        assert got == []


# ------------------------------------------- APX115 lock-order inversions
class TestLockOrderInversion:
    def test_positive_abba_names_both_sites(self, tmp_path):
        got = run("""
            import threading
            A = threading.Lock()
            B = threading.Lock()

            def forward():
                with A:
                    with B:
                        pass

            def backward():
                with B:
                    with A:
                        pass
            """, tmp_path, [LockOrderInversion()])
        assert rule_ids(got) == ["APX115"]
        msg = got[0].message
        assert "`A`" in msg and "`B`" in msg
        assert "backward" in msg or "forward" in msg  # the other site

    def test_positive_inversion_through_helper_call(self, tmp_path):
        """One side never spells both with-statements: it calls a
        module-local helper whose body takes the second lock — the
        acquisition graph must follow the call edge."""
        got = run("""
            import threading

            class Pair:
                def __init__(self):
                    self._alock = threading.Lock()
                    self._block = threading.Lock()

                def _grab_a(self):
                    with self._alock:
                        return 1

                def one(self):
                    with self._block:
                        return self._grab_a()

                def two(self):
                    with self._alock:
                        with self._block:
                            return 2
            """, tmp_path, [LockOrderInversion()])
        assert rule_ids(got) == ["APX115"]

    def test_negative_consistent_order(self, tmp_path):
        got = run("""
            import threading
            A = threading.Lock()
            B = threading.Lock()

            def one():
                with A:
                    with B:
                        pass

            def two():
                with A:
                    with B:
                        pass
            """, tmp_path, [LockOrderInversion()])
        assert got == []

    def test_negative_rlock_reentry_is_not_a_cycle(self, tmp_path):
        got = run("""
            import threading

            class R:
                def __init__(self):
                    self._lock = threading.RLock()

                def outer(self):
                    with self._lock:
                        self.inner()

                def inner(self):
                    with self._lock:
                        pass
            """, tmp_path, [LockOrderInversion()])
        assert got == []


# --------------------------------- APX116 blocking under a contended lock
class TestBlockingCallUnderContendedLock:
    def test_positive_queue_get_under_signal_contended_lock(
            self, tmp_path):
        got = run("""
            import signal
            import threading

            class H:
                def __init__(self, q):
                    self._lock = threading.Lock()
                    self._q = q
                    signal.signal(signal.SIGTERM, self._on_sig)

                def _on_sig(self, signum, frame):
                    with self._lock:
                        pass

                def drain(self):
                    with self._lock:
                        return self._q.get()
            """, tmp_path, [BlockingCallUnderContendedLock()])
        assert rule_ids(got) == ["APX116"]
        assert "_on_sig" in got[0].message
        assert "signal" in got[0].message

    def test_positive_checkpoint_io_under_watchdog_callback_lock(
            self, tmp_path):
        got = run("""
            import threading

            def save_checkpoint(path, state):
                pass

            class Saver:
                def __init__(self, wd):
                    self._lock = threading.Lock()
                    self.state = {}
                    wd.arm(on_wedge=self._note)

                def _note(self, info):
                    with self._lock:
                        self.state["wedged"] = info

                def save(self, path):
                    with self._lock:
                        save_checkpoint(path, self.state)
            """, tmp_path, [BlockingCallUnderContendedLock()])
        assert rule_ids(got) == ["APX116"]

    def test_negative_timeout_bounded_wait(self, tmp_path):
        got = run("""
            import signal
            import threading

            class H:
                def __init__(self, q):
                    self._lock = threading.Lock()
                    self._q = q
                    signal.signal(signal.SIGTERM, self._on_sig)

                def _on_sig(self, signum, frame):
                    with self._lock:
                        pass

                def drain(self):
                    with self._lock:
                        return self._q.get(timeout=5.0)
            """, tmp_path, [BlockingCallUnderContendedLock()])
        assert got == []

    def test_negative_uncontended_lock_is_merely_slow(self, tmp_path):
        """Blocking under a lock NO async path acquires: not a
        deadlock, stays quiet."""
        got = run("""
            import threading

            class H:
                def __init__(self, q):
                    self._lock = threading.Lock()
                    self._q = q

                def drain(self):
                    with self._lock:
                        return self._q.get()
            """, tmp_path, [BlockingCallUnderContendedLock()])
        assert got == []

    def test_negative_dict_get_is_not_blocking(self, tmp_path):
        got = run("""
            import signal
            import threading

            class H:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._d = {}
                    signal.signal(signal.SIGTERM, self._on_sig)

                def _on_sig(self, signum, frame):
                    with self._lock:
                        pass

                def read(self, k):
                    with self._lock:
                        return self._d.get(k)
            """, tmp_path, [BlockingCallUnderContendedLock()])
        assert got == []

    def test_negative_acquitted_by_assert_lock_held(self, tmp_path):
        got = run("""
            import signal
            import threading
            from apex_tpu.resilience.locks import assert_lock_held

            class H:
                def __init__(self, q):
                    self._lock = threading.Lock()
                    self._q = q
                    signal.signal(signal.SIGTERM, self._on_sig)

                def _on_sig(self, signum, frame):
                    with self._lock:
                        pass

                def drain(self):
                    with self._lock:
                        assert_lock_held(self._lock)
                        return self._q.get()
            """, tmp_path, [BlockingCallUnderContendedLock()])
        assert got == []


# ------------------------------------------ concurrency-tier CLI plumbing
class TestConcurrencyTierCli:
    FIXTURE = textwrap.dedent("""
        import threading
        A = threading.Lock()
        B = threading.Lock()

        def one():
            with A:
                with B:
                    pass

        def two():
            with B:
                with A:
                    pass
        """)

    def _run_cli(self, args, cwd):
        import os as _os

        env = dict(_os.environ, PYTHONPATH=str(REPO))
        return subprocess.run(
            [sys.executable, "-m", "apex_tpu.analysis", *args],
            cwd=str(cwd), env=env, capture_output=True, text=True,
            timeout=600)

    def test_only_rules_scopes_to_the_concurrency_tier(self, tmp_path):
        (tmp_path / "mod.py").write_text(self.FIXTURE)
        r = self._run_cli(
            ["mod.py", "--no-baseline",
             "--only-rules", "APX114,APX115,APX116"], tmp_path)
        assert r.returncode == 1 and "APX115" in r.stdout
        r = self._run_cli(["mod.py", "--no-baseline",
                           "--only-rules", "APX101"], tmp_path)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_timing_rollup_has_a_concurrency_family(self, tmp_path):
        (tmp_path / "mod.py").write_text(self.FIXTURE)
        out = tmp_path / "timing.json"
        r = self._run_cli(["mod.py", "--no-baseline", "--timing",
                           "--timing-json", str(out)], tmp_path)
        assert r.returncode == 1
        timings = json.loads(out.read_text())
        for rid in ("APX114", "APX115", "APX116"):
            assert rid in timings
        assert "timing: family concurrency" in r.stderr
        # APX11x must NOT also be double-counted under trace/io
        concurrency = sum(timings[r] for r in
                          ("APX114", "APX115", "APX116"))
        assert concurrency >= 0.0
