"""Self-tests for ``apex_tpu.analysis`` — and the tier-1 rider that
keeps the repo clean.

Layout: per-rule positive/negative fixture pairs (the positives for
APX102/302/401 are the literal pre-fix ADVICE r5 snippets from
bench.py:876, ops/fused_ce_pallas.py:58, and models/gpt.py:447 — the
findings this subsystem exists to scale), engine unit tests (traced
index, axis-registry discovery, baseline), and the repo-wide clean
check ``python -m apex_tpu.analysis apex_tpu bench.py`` rides on.
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from apex_tpu.analysis import (
    DEFAULT_RULES,
    BaselineError,
    analyze_file,
    analyze_paths,
    apply_baseline,
    discover_axis_registry,
    load_baseline,
)
from apex_tpu.analysis.rules_collectives import (
    CollectiveOutsideSpmdContext,
    UnknownCollectiveAxis,
)
from apex_tpu.analysis.rules_donation import DonatedBufferReuse
from apex_tpu.analysis.rules_precision import (
    Fp32ConstantInBf16Path,
    UnclampedTakeAlongAxis,
)
from apex_tpu.analysis.rules_tiling import (
    BlockShapeTilingViolation,
    BlockSpecIndexMapArity,
    HardCodedSublaneAlignment,
)
from apex_tpu.analysis.rules_trace import (
    ProcessGlobalEnvMutation,
    TraceTimeHostStateRead,
)

REPO = Path(__file__).resolve().parent.parent
AXES = frozenset({"dp", "pp", "cp", "tp", "dcn"})


def run(src, tmp_path, rules, axes=AXES):
    p = tmp_path / "fixture.py"
    p.write_text(textwrap.dedent(src))
    return analyze_file(str(p), list(rules), set(axes))


def rule_ids(findings):
    return [f.rule for f in findings]


# ------------------------------------------------- APX101 trace-time reads
class TestTraceTimeHostStateRead:
    def test_positive_env_read_via_helper_under_jit(self, tmp_path):
        """The fused_ce.py shape: the env read lives in a helper that a
        jitted function calls — caught through the module call graph."""
        got = run("""
            import os
            import jax

            def _mode():
                return os.environ.get("APEX_TPU_FUSED_CE_PALLAS", "auto")

            @jax.jit
            def f(x):
                if _mode() == "on":
                    return x * 2
                return x
            """, tmp_path, [TraceTimeHostStateRead()])
        assert rule_ids(got) == ["APX101"]
        assert got[0].symbol == "_mode"
        assert "frozen into the first trace" in got[0].message

    def test_positive_clock_in_pallas_kernel_via_partial_alias(self, tmp_path):
        """The fused_ce_pallas shape: kernel bound with functools.partial
        into a local name, then handed to pl.pallas_call."""
        got = run("""
            import functools
            import time
            from jax.experimental import pallas as pl

            def _kernel(x_ref, o_ref, *, nv):
                o_ref[:] = x_ref[:] * time.time()

            def launch(x, nv):
                kernel = functools.partial(_kernel, nv=nv)
                return pl.pallas_call(kernel, grid=(nv,))(x)
            """, tmp_path, [TraceTimeHostStateRead()])
        assert rule_ids(got) == ["APX101"]
        assert "wall clock" in got[0].message

    def test_positive_host_rng_under_defvjp(self, tmp_path):
        got = run("""
            import numpy as np
            import jax

            @jax.custom_vjp
            def op(x):
                return x

            def _fwd(x):
                return x, None

            def _bwd(res, g):
                return (g * np.random.rand(),)

            op.defvjp(_fwd, _bwd)
            """, tmp_path, [TraceTimeHostStateRead()])
        assert rule_ids(got) == ["APX101"]
        assert "host RNG" in got[0].message

    def test_positive_bare_environ_get_and_lambda(self, tmp_path):
        """Blind spots closed in review: the bare-import spelling
        (`from os import environ`) and a hazard inside `jax.jit(lambda
        ...)` (lambdas have no FunctionDef to index)."""
        got = run("""
            from os import environ, getenv

            import jax

            @jax.jit
            def f(x):
                return x if environ.get("FLAG") else -x

            g = jax.jit(lambda x: x if getenv("FLAG") else -x)
            """, tmp_path, [TraceTimeHostStateRead()])
        assert rule_ids(got) == ["APX101", "APX101"]

    def test_positive_lambda_calling_local_helper(self, tmp_path):
        got = run("""
            import os

            import jax

            def _mode():
                return os.environ.get("FLAG", "auto")

            g = jax.jit(lambda x: x * 2 if _mode() == "on" else x)
            """, tmp_path, [TraceTimeHostStateRead()])
        assert rule_ids(got) == ["APX101"]
        assert got[0].symbol == "_mode"

    def test_negative_host_side_read(self, tmp_path):
        """Same reads, no trace context: host-side config code is fine."""
        got = run("""
            import os
            import time

            def pick_backend():
                return os.environ.get("BACKEND", "tpu")

            def stamp():
                return time.time()
            """, tmp_path, [TraceTimeHostStateRead()])
        assert got == []

    def test_negative_module_level_read(self, tmp_path):
        got = run("""
            import os
            import jax

            _FLAG = os.environ.get("FLAG", "1")

            @jax.jit
            def f(x):
                return x + 1
            """, tmp_path, [TraceTimeHostStateRead()])
        assert got == []


# --------------------------------------------- APX102 env-var mutation
class TestProcessGlobalEnvMutation:
    def test_positive_advice_r5_bench_py_876(self, tmp_path):
        """The literal pre-fix bench.py:876 shape (ADVICE r5): flip the
        env var, rerun, restore — invisible to already-traced jits."""
        got = run("""
            import os

            def bench_gpt_fce(bench_gpt, roof):
                os.environ["APEX_TPU_FUSED_CE_PALLAS"] = "0"
                try:
                    r = bench_gpt(12, 768, 12, 1024, 8, roof, fused_ce=True)
                finally:
                    os.environ.pop("APEX_TPU_FUSED_CE_PALLAS", None)
                return r
            """, tmp_path, [ProcessGlobalEnvMutation()])
        assert rule_ids(got) == ["APX102", "APX102"]
        assert "os.environ[...] assignment" in got[0].message
        assert "os.environ.pop" in got[1].message

    def test_negative_module_level_startup_config(self, tmp_path):
        """Startup env config before anything traces is the accepted
        idiom — only mid-process mutation inside functions is flagged."""
        got = run("""
            import os

            os.environ.setdefault("JAX_PLATFORMS", "cpu")
            os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
            """, tmp_path, [ProcessGlobalEnvMutation()])
        assert got == []


# --------------------------------------------- APX103 donated-buffer reuse
class TestDonatedBufferReuse:
    def test_positive_read_after_donate_new_name(self, tmp_path):
        """The classic shape: the step's result is bound to NEW names
        while the stale donated name is read for logging afterwards —
        a no-op on CPU, garbage on TPU (ROADMAP donation/aliasing
        open item)."""
        got = run("""
            import jax

            def make(step_fn):
                return jax.jit(step_fn, donate_argnums=(0, 1))

            step = jax.jit(lambda p, s: (p, s), donate_argnums=(0, 1))

            def train(params, state, norm_of):
                new_params, new_state = step(params, state)
                norm = norm_of(params)
                return new_params, new_state, norm
            """, tmp_path, [DonatedBufferReuse()])
        assert rule_ids(got) == ["APX103"]
        assert "`params` is donated" in got[0].message
        assert "rebound" in got[0].message

    def test_positive_partial_decorator_spelling(self, tmp_path):
        """@partial(jax.jit, donate_argnums=...) defs are tracked by
        their function name (the bench.py step idiom)."""
        got = run("""
            from functools import partial

            import jax

            @partial(jax.jit, donate_argnums=(0,))
            def step(params, grads):
                return params

            def train(params, grads, save):
                out = step(params, grads)
                save(params)
                return out
            """, tmp_path, [DonatedBufferReuse()])
        assert rule_ids(got) == ["APX103"]

    def test_negative_early_return_branches(self, tmp_path):
        """A donating call that is itself a `return` value: nothing
        later in the function can run after it in the same invocation,
        so a read on the sibling branch (the early-return shape) is
        provably safe and must stay silent."""
        got = run("""
            import jax

            step = jax.jit(lambda p, s: (p, s), donate_argnums=(0,))

            def train(params, state, cond, norm_of):
                if cond:
                    return step(params, state)
                return norm_of(params)
            """, tmp_path, [DonatedBufferReuse()])
        assert got == []

    def test_negative_sibling_branch_read(self, tmp_path):
        """Assign-in-branch sibling of the early-return shape: the
        else-arm read can never execute after the if-arm's donating
        call in one invocation — silent."""
        got = run("""
            import jax

            step = jax.jit(lambda p: p, donate_argnums=(0,))

            def train(params, cond, f):
                if cond:
                    out = step(params)
                else:
                    out = f(params)
                return out
            """, tmp_path, [DonatedBufferReuse()])
        assert got == []

    def test_positive_sibling_branch_inside_loop(self, tmp_path):
        """The same two arms under a loop ARE a bug: iteration 1 may
        donate, iteration 2 read the stale name."""
        got = run("""
            import jax

            step = jax.jit(lambda p: p, donate_argnums=(0,))

            def train(params, iters, f):
                for i in range(iters):
                    if i % 2 == 0:
                        out = step(params)
                    else:
                        out = f(params)
                return out
            """, tmp_path, [DonatedBufferReuse()])
        assert rule_ids(got) == ["APX103"]

    def test_positive_read_after_exclusive_branch(self, tmp_path):
        """A read BELOW the if/else is reachable after the donating arm
        ran — the exclusive-branch skip must not silence it."""
        got = run("""
            import jax

            step = jax.jit(lambda p: p, donate_argnums=(0,))

            def train(params, cond, f, g):
                if cond:
                    out = step(params)
                else:
                    out = f(params)
                return g(params)
            """, tmp_path, [DonatedBufferReuse()])
        assert rule_ids(got) == ["APX103"]

    def test_negative_rebound_from_the_call(self, tmp_path):
        """`params, state, loss = step(params, state)` — the safe
        idiom every bench section uses — must stay silent, including
        inside loops (the rebind covers the next iteration's read)."""
        got = run("""
            from functools import partial

            import jax

            @partial(jax.jit, donate_argnums=(0, 1))
            def step(params, state):
                return params, state, 0.0

            def train(params, state, iters):
                params, state, loss = step(params, state)
                for _ in range(iters):
                    params, state, loss = step(params, state)
                return params, loss
            """, tmp_path, [DonatedBufferReuse()])
        assert got == []

    def test_negative_read_before_and_rebind_after(self, tmp_path):
        got = run("""
            import jax

            step = jax.jit(lambda p: p, donate_argnums=(0,))

            def train(params, norm_of):
                norm = norm_of(params)      # read BEFORE donation: fine
                out = step(params)
                params = out                # rebound before any read
                return params, norm
            """, tmp_path, [DonatedBufferReuse()])
        assert got == []

    def test_negative_same_name_in_nested_scope(self, tmp_path):
        """A same-named parameter or local of a NESTED scope after the
        donating call is a different variable, not the donated buffer —
        the read search stops at function/class/lambda boundaries (this
        exact shape was a reproduced false positive)."""
        got = run("""
            import jax

            step = jax.jit(lambda p: p, donate_argnums=(0,))
            params = {"w": 1.0}
            out = step(params)

            def helper(params):
                return params["w"] * 2

            scale = lambda params: params["w"] + 1
            """, tmp_path, [DonatedBufferReuse()])
        assert got == []

    def test_negative_nested_scope_inside_function(self, tmp_path):
        """Same boundary one level down: a helper def nested in the
        donating function reuses the name for its own parameter."""
        got = run("""
            import jax

            step = jax.jit(lambda p: p, donate_argnums=(0,))

            def train(params, sink):
                out = step(params)

                def norm_of(params):
                    return params["w"]

                sink(norm_of(out))
                return out
            """, tmp_path, [DonatedBufferReuse()])
        assert got == []

    def test_negative_computed_argnums_and_star_args(self, tmp_path):
        """Non-literal donate_argnums and *args call sites are trusted
        (the models/gpt.py `donate_argnums=donate` shape)."""
        got = run("""
            import jax

            def make(fn, donate_state):
                donate = (0, 1) if donate_state else ()
                return jax.jit(fn, donate_argnums=donate)

            step = jax.jit(lambda p, s: (p, s), donate_argnums=(0, 1))

            def train(step_args, params):
                out = step(*step_args)
                return out, params
            """, tmp_path, [DonatedBufferReuse()])
        assert got == []


# ------------------------------------------- APX201 unknown collective axis
class TestUnknownCollectiveAxis:
    def test_positive_typo_axis(self, tmp_path):
        got = run("""
            import jax

            def allreduce(x):
                return jax.lax.psum(x, "tq")
            """, tmp_path, [UnknownCollectiveAxis()])
        assert rule_ids(got) == ["APX201"]
        assert "'tq'" in got[0].message

    def test_positive_unknown_in_tuple(self, tmp_path):
        got = run("""
            import jax

            def hier(x):
                return jax.lax.psum(x, ("dcn", "dq"))
            """, tmp_path, [UnknownCollectiveAxis()])
        assert rule_ids(got) == ["APX201"]
        assert "'dq'" in got[0].message

    def test_negative_registered_and_dynamic_axes(self, tmp_path):
        got = run("""
            import jax

            def allreduce(x):
                return jax.lax.psum(x, "tp")

            def generic(x, axis_name):
                return jax.lax.pmean(x, axis_name)

            def hier(x):
                return jax.lax.psum(x, ("dcn", "dp"))
            """, tmp_path, [UnknownCollectiveAxis()])
        assert got == []


# ------------------------------------ APX202 collective without spmd context
class TestCollectiveOutsideSpmdContext:
    def test_positive_no_shard_map_in_sight(self, tmp_path):
        got = run("""
            import jax

            def loss(x):
                return jax.lax.pmean(x, "dp")
            """, tmp_path, [CollectiveOutsideSpmdContext()])
        assert rule_ids(got) == ["APX202"]

    def test_negative_module_binds_the_axis(self, tmp_path):
        got = run("""
            import jax
            from jax.sharding import Mesh, PartitionSpec as P

            def loss(x):
                return jax.lax.pmean(x, "dp")

            def train(mesh, x):
                return jax.shard_map(loss, mesh=mesh,
                                     in_specs=P("dp"), out_specs=P())(x)
            """, tmp_path, [CollectiveOutsideSpmdContext()])
        assert got == []


# ----------------------------------------------- APX301 BlockSpec tiling
class TestBlockShapeTilingViolation:
    def test_positive_bad_lane_and_sublane(self, tmp_path):
        got = run("""
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu

            def specs(H):
                a = pl.BlockSpec((8, 64), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM)
                b = pl.BlockSpec((7, 128), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM)
                return a, b
            """, tmp_path, [BlockShapeTilingViolation()])
        assert rule_ids(got) == ["APX301", "APX301"]
        assert "lane dim 64" in got[0].message
        assert "sublane dim 7" in got[1].message

    def test_negative_tiled_scalar_column_and_dynamic(self, tmp_path):
        got = run("""
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu

            def specs(bn, H):
                a = pl.BlockSpec((16, 256), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM)
                b = pl.BlockSpec((bn, 1), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM)
                c = pl.BlockSpec((256, H), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM)
                return a, b, c
            """, tmp_path, [BlockShapeTilingViolation()])
        assert got == []


# ------------------------------- APX105 BlockSpec index_map arity vs grid
class TestBlockSpecIndexMapArity:
    def test_positive_arity_mismatch_direct_and_aliased(self, tmp_path):
        """The refactor hazard: a grid grown to rank 3 while the
        lambdas still take 2 ids — both the inline spec and one built
        through a local alias (the flash-kernel idiom)."""
        got = run("""
            import functools
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu

            def kernel(x):
                kv_spec = pl.BlockSpec((1, 128, 64), lambda b, j: (b, j, 0),
                                       memory_space=pltpu.VMEM)
                grid = (4, 8, 2)
                return pl.pallas_call(
                    functools.partial(_body),
                    grid=grid,
                    in_specs=[
                        pl.BlockSpec((1, 128, 64), lambda b, i: (b, i, 0),
                                     memory_space=pltpu.VMEM),
                        kv_spec,
                    ],
                    out_specs=pl.BlockSpec((1, 128, 64),
                                           lambda b, i, j: (b, i, 0),
                                           memory_space=pltpu.VMEM),
                )(x)
            """, tmp_path, [BlockSpecIndexMapArity()])
        assert rule_ids(got) == ["APX105", "APX105"]
        assert "takes 2 argument(s)" in got[0].message
        assert "rank 3" in got[0].message

    def test_shadowed_alias_last_assignment_wins(self, tmp_path):
        """``grid = (4, 8)`` rebound to ``(4, 8, 2)`` before the call:
        the lexically LAST assignment is the one the call sees, so
        rank-3 lambdas are clean and a rank-2 lambda is flagged (the
        reverse-visit-order bug flagged the correct ones instead)."""
        got = run("""
            from jax.experimental import pallas as pl

            def kernel(x):
                grid = (4, 8)
                grid = (4, 8, 2)
                return pl.pallas_call(
                    _body, grid=grid,
                    in_specs=[
                        pl.BlockSpec((8, 128), lambda b, i, j: (b, i, 0)),
                        pl.BlockSpec((8, 128), lambda b, i: (b, i)),
                    ],
                    out_specs=pl.BlockSpec((8, 128),
                                           lambda b, i, j: (b, i, 0)),
                )(x)
            """, tmp_path, [BlockSpecIndexMapArity()])
        assert rule_ids(got) == ["APX105"]
        assert "takes 2 argument(s)" in got[0].message

    def test_positive_int_grid_is_rank_one(self, tmp_path):
        got = run("""
            from jax.experimental import pallas as pl

            def kernel(x):
                return pl.pallas_call(
                    _body, grid=8,
                    in_specs=[pl.BlockSpec((8, 128), lambda i, j: (i, j))],
                    out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                )(x)
            """, tmp_path, [BlockSpecIndexMapArity()])
        assert rule_ids(got) == ["APX105"]

    def test_negative_matching_named_default_and_dynamic(self, tmp_path):
        """Matching lambdas, a named index_map def of the right arity,
        a default index_map, a *args lambda, and a dynamic grid are
        all silent — the rule only speaks when the mismatch is
        provable."""
        got = run("""
            from jax.experimental import pallas as pl

            def imap(b, i, j):
                return (b, i, 0)

            def kernel(x, grid_from_caller):
                inline = pl.BlockSpec((1, 128, 64),
                                      lambda b, i, j: (b, j, 0))
                return pl.pallas_call(
                    _body,
                    grid=(4, 8, 2),
                    in_specs=[
                        inline,
                        pl.BlockSpec((1, 128, 64), imap),
                        pl.BlockSpec((1, 128, 64)),
                        pl.BlockSpec((1, 128, 64), lambda *ids: ids),
                    ],
                    out_specs=pl.BlockSpec((1, 128, 64), index_map=imap),
                )(x) + pl.pallas_call(
                    _body,
                    grid=grid_from_caller,
                    in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
                    out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
                )(x)
            """, tmp_path, [BlockSpecIndexMapArity()])
        assert got == []


# ------------------------------------- APX302 hard-coded sublane alignment
class TestHardCodedSublaneAlignment:
    def test_positive_advice_r5_fused_ce_pallas_58(self, tmp_path):
        """The literal pre-fix fused_ce_pallas.py:58 shape (ADVICE r5):
        ceil-rounding row blocks to fp32's sublane 8 in a kernel whose
        MXU dots run bf16."""
        got = run("""
            import jax.numpy as jnp

            def _ceil_block(n, target, align):
                if n >= target:
                    return target
                return -(-n // align) * align

            def fused_ce_fwd_pallas(x2, embed, t, block_n=256):
                dot_dtype = jnp.bfloat16
                bn = _ceil_block(x2.shape[0], block_n, align=8)
                return bn
            """, tmp_path, [HardCodedSublaneAlignment()])
        assert rule_ids(got) == ["APX302"]
        assert "align=8" in got[0].message

    def test_positive_positional_spelling(self, tmp_path):
        """The same constant passed positionally must not slip through."""
        got = run("""
            import jax.numpy as jnp

            def _ceil_block(n, target, align):
                return -(-n // align) * align

            def launch(x, block_n=256):
                dot_dtype = jnp.bfloat16
                return _ceil_block(x.shape[0], block_n, 8)
            """, tmp_path, [HardCodedSublaneAlignment()])
        assert rule_ids(got) == ["APX302"]

    def test_negative_dtype_derived_alignment(self, tmp_path):
        got = run("""
            import jax.numpy as jnp

            def _sublane(dtype):
                return {4: 8, 2: 16, 1: 32}[jnp.dtype(dtype).itemsize]

            def _ceil_block(n, target, align):
                if n >= target:
                    return target
                return -(-n // align) * align

            def fused_ce_fwd_pallas(x2, embed, t, block_n=256):
                dot_dtype = jnp.bfloat16
                bn = _ceil_block(x2.shape[0], block_n,
                                 align=_sublane(x2.dtype))
                return bn
            """, tmp_path, [HardCodedSublaneAlignment()])
        assert got == []

    def test_negative_fp32_only_module(self, tmp_path):
        """align=8 is correct when no bf16 can reach the kernel."""
        got = run("""
            def _ceil_block(n, target, align):
                return -(-n // align) * align

            def launch(x, block_n=256):
                bn = _ceil_block(x.shape[0], block_n, align=8)
                return bn
            """, tmp_path, [HardCodedSublaneAlignment()])
        assert got == []


# ---------------------------------------- APX401 unclamped take_along_axis
class TestUnclampedTakeAlongAxis:
    def test_positive_advice_r5_gpt_py_447(self, tmp_path):
        """The literal pre-fix gpt.py:447 dense-head shape (ADVICE r5)."""
        got = run("""
            import jax
            import jax.numpy as jnp

            def lm_head_loss(x, embed, targets):
                logits = jnp.matmul(x.astype(jnp.float32),
                                    embed.T.astype(jnp.float32))
                lse = jax.scipy.special.logsumexp(logits, axis=-1)
                tgt = jnp.take_along_axis(
                    logits, targets[..., None], axis=-1)[..., 0]
                return lse - tgt
            """, tmp_path, [UnclampedTakeAlongAxis()])
        assert rule_ids(got) == ["APX401"]

    def test_negative_clamped_through_a_name(self, tmp_path):
        got = run("""
            import jax.numpy as jnp

            def lm_head_loss(logits, targets):
                t_cl = jnp.clip(targets, 0, logits.shape[-1] - 1)
                tgt = jnp.take_along_axis(
                    logits, t_cl[..., None], axis=-1)[..., 0]
                return tgt
            """, tmp_path, [UnclampedTakeAlongAxis()])
        assert got == []

    def test_negative_explicit_mode(self, tmp_path):
        got = run("""
            import jax.numpy as jnp

            def gather(logits, t):
                return jnp.take_along_axis(
                    logits, t[..., None], axis=-1, mode="fill")
            """, tmp_path, [UnclampedTakeAlongAxis()])
        assert got == []


# ------------------------------------------ APX402 fp32 constant in bf16
class TestFp32ConstantInBf16Path:
    def test_positive_materialized_f32_meets_bf16(self, tmp_path):
        got = run("""
            import jax.numpy as jnp

            def scale(x, shape):
                return x.astype(jnp.bfloat16) * jnp.ones(
                    shape, dtype=jnp.float32)
            """, tmp_path, [Fp32ConstantInBf16Path()])
        assert rule_ids(got) == ["APX402"]
        assert "upcasts" in got[0].message

    def test_negative_constant_in_compute_dtype(self, tmp_path):
        got = run("""
            import jax.numpy as jnp

            def scale(x, shape):
                return x.astype(jnp.bfloat16) * jnp.ones(
                    shape, dtype=jnp.bfloat16)
            """, tmp_path, [Fp32ConstantInBf16Path()])
        assert got == []


# ------------------------------------------------------------ engine bits
class TestEngine:
    def test_axis_registry_discovered_from_parallel_state(self, tmp_path):
        ps = tmp_path / "parallel_state.py"
        ps.write_text('WEIRD_AXIS = "zz"\nOTHER = 3\n')
        assert discover_axis_registry([str(tmp_path)]) == {"zz"}

    def test_axis_registry_falls_back_to_defaults(self, tmp_path):
        assert "tp" in discover_axis_registry([str(tmp_path)])

    def test_syntax_error_is_a_finding_not_a_crash(self, tmp_path):
        got = run("def broken(:\n", tmp_path, DEFAULT_RULES)
        assert rule_ids(got) == ["APX000"]

    def test_findings_are_sorted_and_relative(self, tmp_path):
        (tmp_path / "b.py").write_text(
            "import os\n\ndef f():\n    os.environ['X'] = '1'\n")
        (tmp_path / "a.py").write_text(
            "import os\n\ndef f():\n    os.environ['X'] = '1'\n")
        got = analyze_paths([str(tmp_path)], DEFAULT_RULES,
                            axis_registry=set(AXES), rel_to=str(tmp_path))
        assert [f.path for f in got] == ["a.py", "b.py"]


# ------------------------------------- cross-module trace reachability
class TestCrossModuleReachability:
    """The traced-function index was per-module, so a helper whose only
    traced caller lives in ANOTHER module escaped APX101 — the exact
    ROADMAP case: ``fused_ce_pallas._default_dot_dtype``'s env read
    reached from ``fused_ce._fwd``.  ``analyze_paths`` now links the
    indexes through import-resolved calls; single-file
    ``analyze_file`` stays per-module (no imports to resolve)."""

    HELPER = textwrap.dedent("""
        import os

        def helper():
            return os.environ.get("APEX_TPU_X", "auto")
        """)

    def _scan(self, tmp_path):
        return analyze_paths([str(tmp_path)], DEFAULT_RULES,
                             axis_registry=set(AXES),
                             rel_to=str(tmp_path))

    def test_from_import_reached_from_jit(self, tmp_path):
        (tmp_path / "helper_mod.py").write_text(self.HELPER)
        (tmp_path / "main.py").write_text(textwrap.dedent("""
            import jax
            from helper_mod import helper

            @jax.jit
            def f(x):
                if helper() == "on":
                    return x * 2
                return x
            """))
        got = self._scan(tmp_path)
        assert [(f.rule, f.path, f.symbol) for f in got] == \
            [("APX101", "helper_mod.py", "helper")]
        assert "cross-module" in got[0].message or "main" in got[0].message

    def test_function_local_import_and_alias(self, tmp_path):
        """The fused_ce shape: the import lives INSIDE the traced
        closure; and the `import m as alias` dotted-call spelling."""
        (tmp_path / "helper_mod.py").write_text(self.HELPER)
        (tmp_path / "main.py").write_text(textwrap.dedent("""
            import jax
            import helper_mod as hm

            @jax.jit
            def f(x):
                from helper_mod import helper
                return x if helper() else x * hm.helper()
            """))
        got = self._scan(tmp_path)
        assert [(f.rule, f.path) for f in got] == \
            [("APX101", "helper_mod.py")]

    def test_package_relative_import(self, tmp_path):
        """Packages resolve: `from .kernels import helper` inside
        pkg/api.py marks pkg/kernels.py's helper traced."""
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        (pkg / "kernels.py").write_text(self.HELPER)
        (pkg / "api.py").write_text(textwrap.dedent("""
            import jax
            from .kernels import helper

            @jax.jit
            def f(x):
                return x * helper()
            """))
        got = self._scan(tmp_path)
        assert [(f.rule, f.path, f.symbol) for f in got] == \
            [("APX101", str(Path("pkg") / "kernels.py"), "helper")]

    def test_package_init_relative_import(self, tmp_path):
        """Relative imports in a package __init__.py resolve against
        the package ITSELF (python semantics) — review finding: the
        parent-of-module rule resolved one level too shallow and the
        seed was silently dropped."""
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "kernels.py").write_text(self.HELPER)
        (pkg / "__init__.py").write_text(textwrap.dedent("""
            import jax
            from .kernels import helper

            @jax.jit
            def f(x):
                return x * helper()
            """))
        got = self._scan(tmp_path)
        assert [(f.rule, f.path, f.symbol) for f in got] == \
            [("APX101", str(Path("pkg") / "kernels.py"), "helper")]

    def test_colliding_module_names_never_mislink(self, tmp_path):
        """Two bare roots both holding utils.py: the dotted name is
        ambiguous, so NO cross-module seed may land through it (a wrong
        -file APX101 is worse than a missed link)."""
        for d in ("libA", "libB"):
            (tmp_path / d).mkdir()
            (tmp_path / d / "utils.py").write_text(self.HELPER)
        (tmp_path / "libB" / "main.py").write_text(textwrap.dedent("""
            import jax
            from utils import helper

            @jax.jit
            def f(x):
                return x * helper()
            """))
        got = analyze_paths(
            [str(tmp_path / "libA"), str(tmp_path / "libB")],
            DEFAULT_RULES, axis_registry=set(AXES), rel_to=str(tmp_path))
        assert got == []

    def test_untraced_cross_module_call_not_flagged(self, tmp_path):
        """A helper reached only from plain (untraced) code stays
        clean — reachability, not mere import, is the trigger."""
        (tmp_path / "helper_mod.py").write_text(self.HELPER)
        (tmp_path / "main.py").write_text(textwrap.dedent("""
            from helper_mod import helper

            def plain():
                return helper()
            """))
        assert self._scan(tmp_path) == []

    def test_local_binding_shadows_import(self, tmp_path):
        """A module-local def with the imported name wins resolution —
        the other module must not be marked through the shadowed
        name."""
        (tmp_path / "helper_mod.py").write_text(self.HELPER)
        (tmp_path / "main.py").write_text(textwrap.dedent("""
            import jax
            from helper_mod import helper

            def helper():
                return 1

            @jax.jit
            def f(x):
                return x * helper()
            """))
        assert self._scan(tmp_path) == []


# ------------------------------------------------------------- baseline
class TestBaseline:
    def _write(self, tmp_path, entries):
        p = tmp_path / "baseline.json"
        p.write_text(json.dumps({"entries": entries}))
        return str(p)

    def test_suppression_and_stale_reporting(self, tmp_path):
        findings = run("""
            import os

            def f():
                os.environ["X"] = "1"
            """, tmp_path, [ProcessGlobalEnvMutation()])
        entries = load_baseline(self._write(tmp_path, [
            {"rule": "APX102", "path": "fixture.py", "symbol": "f",
             "contains": "os.environ", "justification": "test fixture"},
            {"rule": "APX102", "path": "nonexistent.py",
             "justification": "stale on purpose"},
        ]))
        kept, suppressed, stale = apply_baseline(findings, entries)
        assert kept == []
        assert len(suppressed) == 1
        assert len(stale) == 1 and stale[0].path == "nonexistent.py"

    def test_justification_is_mandatory(self, tmp_path):
        with pytest.raises(BaselineError, match="justification"):
            load_baseline(self._write(tmp_path, [
                {"rule": "APX102", "path": "x.py", "justification": "  "}]))

    def test_missing_fields_rejected(self, tmp_path):
        with pytest.raises(BaselineError, match="missing"):
            load_baseline(self._write(tmp_path, [{"rule": "APX102"}]))


# ------------------------------------------------- the repo-wide rider
class TestRepoIsClean:
    """The tier-1 rider: the shipped tree stays clean modulo the
    committed baseline, and every baseline entry still bites."""

    def _repo_findings(self):
        paths = [str(REPO / "apex_tpu"), str(REPO / "bench.py"),
                 str(REPO / "examples")]
        return analyze_paths(paths, DEFAULT_RULES, rel_to=str(REPO))

    def test_repo_clean_modulo_baseline(self):
        entries = load_baseline(str(REPO / "analysis_baseline.json"))
        kept, _, stale = apply_baseline(self._repo_findings(), entries)
        assert not kept, "new analyzer findings:\n" + "\n".join(
            f.render() for f in kept)
        assert not stale, "stale baseline entries (fixed code? remove " \
            "them): " + ", ".join(f"{e.rule} {e.path}" for e in stale)

    def test_advice_r5_fixes_are_in_the_tree(self):
        """The three ADVICE r5 findings must stay FIXED (their pre-fix
        shapes are pinned by the fixture tests above): no APX102 left in
        bench.py, no APX302 in the Pallas ops, no APX401 in gpt.py."""
        by_rule = {}
        for f in self._repo_findings():
            by_rule.setdefault(f.rule, []).append(f.path)
        assert "bench.py" not in by_rule.get("APX102", [])
        assert not [p for p in by_rule.get("APX302", [])
                    if p.startswith("apex_tpu/ops/")]
        assert "apex_tpu/models/gpt.py" not in by_rule.get("APX401", [])

    def test_cli_acceptance_command(self):
        """`python -m apex_tpu.analysis apex_tpu bench.py` exits 0."""
        r = subprocess.run(
            [sys.executable, "-m", "apex_tpu.analysis",
             "apex_tpu", "bench.py"],
            cwd=str(REPO), capture_output=True, text=True, timeout=600)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_cli_from_foreign_cwd_finds_baseline(self, tmp_path):
        """The committed baseline must be picked up when the CLI runs
        from another directory with absolute paths (pre-commit hooks,
        CI jobs) — review finding: CWD-relative default dropped it."""
        import os

        env = dict(os.environ, PYTHONPATH=str(REPO))
        r = subprocess.run(
            [sys.executable, "-m", "apex_tpu.analysis",
             str(REPO / "apex_tpu"), str(REPO / "bench.py")],
            cwd=str(tmp_path), env=env, capture_output=True, text=True,
            timeout=600)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "baselined" in r.stderr
