"""Pipeline-parallel tests — mirrors the reference's
test_pipeline_parallel_fwd_bwd.py:115-242: the pipelined schedule must
produce *exactly* the same loss and gradients as a single-process run of
the same model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer.pipeline_parallel.schedules import (
    forward_backward_no_pipelining,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    pipelined_apply,
)
from apex_tpu.transformer.pipeline_parallel.schedules.tick_schedule import (
    pipelined_fwd_bwd,
)

PP = 4
L = 8  # total layers, 2 per stage
H = 16
M = 6  # microbatches
MB = 3  # microbatch size


def make_problem(seed=0):
    rng = np.random.RandomState(seed)
    shared = {
        "w_in": jnp.asarray(rng.randn(H, H).astype(np.float32) * 0.3),
        "w_out": jnp.asarray(rng.randn(H).astype(np.float32) * 0.3),
    }
    stages = {
        "w": jnp.asarray(rng.randn(L, H, H).astype(np.float32) * 0.3),
        "b": jnp.asarray(rng.randn(L, H).astype(np.float32) * 0.1),
    }
    x = jnp.asarray(rng.randn(M, MB, H).astype(np.float32))
    y = jnp.asarray(rng.randn(M, MB).astype(np.float32))
    return shared, stages, {"x": x, "y": y}


def pre_fn(shared, mb):
    return jnp.tanh(mb["x"] @ shared["w_in"])


def layer(w, b, h):
    return jnp.tanh(h @ w + b)


def stage_fn(stage_params, h):
    def body(carry, lp):
        return layer(lp["w"], lp["b"], carry), None

    out, _ = jax.lax.scan(body, h, stage_params)
    return out


def post_fn(shared, h, mb):
    pred = h @ shared["w_out"]
    return jnp.mean((pred - mb["y"]) ** 2)


def oracle_loss(shared, stages, batch):
    def one(mb):
        h = pre_fn(shared, mb)
        h = stage_fn(stages, h)
        return post_fn(shared, h, mb)

    losses = jax.vmap(one)(batch)
    return jnp.mean(losses)


class TestPipelinedApply:
    def test_identity_pipeline_routes_data(self, devices8):
        mesh = Mesh(np.array(devices8[:PP]), ("pp",))
        xs = jnp.arange(float(M * 2)).reshape(M, 2)
        dummy = {"s": jnp.zeros((PP,))}

        def stage(params, x):
            return x + 1.0  # each stage adds 1

        def f(params, xs):
            out = pipelined_apply(stage, params, xs, "pp")
            from apex_tpu.transformer.pipeline_parallel.schedules import (
                broadcast_from_last_stage,
            )

            return broadcast_from_last_stage(out, "pp")

        out = jax.shard_map(
            f, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(), check_vma=False
        )({"s": jnp.zeros((PP,))}, xs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(xs) + PP)


class TestPipelineParity:
    """The reference's exact-parity pattern (test_pipeline_parallel_fwd_bwd.py)."""

    @pytest.mark.slow
    def test_loss_matches_oracle(self, devices8):
        shared, stages, batch = make_problem()
        ref = oracle_loss(shared, stages, batch)

        mesh = Mesh(np.array(devices8[:PP]), ("pp",))
        sspec = {"w_in": P(), "w_out": P()}
        stspec = {"w": P("pp", None, None), "b": P("pp", None)}
        bspec = {"x": P(), "y": P()}

        def f(shared, stages, batch):
            loss, _ = forward_backward_pipelining_without_interleaving(
                pre_fn, stage_fn, post_fn, shared, stages, batch,
                forward_only=True, axis_name="pp",
            )
            return loss

        loss = jax.shard_map(
            f, mesh=mesh, in_specs=(sspec, stspec, bspec), out_specs=P(), check_vma=False
        )(shared, stages, batch)
        np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)

    @pytest.mark.slow
    def test_grads_match_oracle(self, devices8):
        shared, stages, batch = make_problem(1)
        ref_loss, (ref_gs, ref_gst) = jax.value_and_grad(oracle_loss, argnums=(0, 1))(
            shared, stages, batch
        )

        mesh = Mesh(np.array(devices8[:PP]), ("pp",))
        sspec = {"w_in": P(), "w_out": P()}
        stspec = {"w": P("pp", None, None), "b": P("pp", None)}
        bspec = {"x": P(), "y": P()}

        def f(shared, stages, batch):
            return forward_backward_pipelining_without_interleaving(
                pre_fn, stage_fn, post_fn, shared, stages, batch, axis_name="pp"
            )

        loss, (g_shared, g_stage) = jax.shard_map(
            f,
            mesh=mesh,
            in_specs=(sspec, stspec, bspec),
            out_specs=((P()), (sspec, stspec)),
            check_vma=False,
        )(shared, stages, batch)

        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        for a, r in zip(jax.tree.leaves(g_shared), jax.tree.leaves(ref_gs)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=1e-4, atol=1e-5)
        for a, r in zip(jax.tree.leaves(g_stage), jax.tree.leaves(ref_gst)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=1e-4, atol=1e-5)


class TestInterleaved:
    """Interleaved schedule parity: vpp chunks must produce the same
    loss/grads as the flat model (reference
    fwd_bwd_pipelining_with_interleaving.py semantics)."""

    @pytest.mark.slow
    def test_interleaved_matches_oracle(self, devices8):
        from apex_tpu.transformer.pipeline_parallel.schedules import (
            forward_backward_pipelining_with_interleaving,
        )

        shared, stages, batch = make_problem(7)
        VPP = 2
        lpc = L // (VPP * PP)

        # execution order is chunk-major (v, s, i); the sharded global
        # layout is stage-major [s][v][i] so P("pp") slices per stage
        def to_stage_major(v):
            return np.asarray(v).reshape(VPP, PP, lpc, *v.shape[1:]).transpose(
                1, 0, *range(2, v.ndim + 2)
            ).reshape(v.shape)

        def from_stage_major(g, like):
            return np.asarray(g).reshape(PP, VPP, lpc, *like.shape[1:]).transpose(
                1, 0, *range(2, like.ndim + 2)
            ).reshape(like.shape)

        sharded_stages = {k: jnp.asarray(to_stage_major(v)) for k, v in stages.items()}

        ref_loss, (ref_gs, ref_gst) = jax.value_and_grad(oracle_loss, argnums=(0, 1))(
            shared, stages, batch
        )

        mesh = Mesh(np.array(devices8[:PP]), ("pp",))
        sspec = {"w_in": P(), "w_out": P()}
        stspec = {"w": P("pp", None, None), "b": P("pp", None)}
        bspec = {"x": P(), "y": P()}

        def f(shared, stages_, batch):
            return forward_backward_pipelining_with_interleaving(
                pre_fn, stage_fn, post_fn, shared, stages_, batch,
                virtual_pipeline_model_parallel_size=VPP, axis_name="pp",
            )

        loss, (g_shared, g_stage) = jax.shard_map(
            f,
            mesh=mesh,
            in_specs=(sspec, stspec, bspec),
            out_specs=(P(), (sspec, stspec)),
            check_vma=False,
        )(shared, sharded_stages, batch)

        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        for a, r in zip(jax.tree.leaves(g_shared), jax.tree.leaves(ref_gs)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=1e-4, atol=1e-5)
        for k in stages:
            g = from_stage_major(g_stage[k], stages[k])
            np.testing.assert_allclose(g, np.asarray(ref_gst[k]), rtol=1e-4, atol=1e-5)

    def test_selector_returns_interleaved(self):
        from apex_tpu.transformer.pipeline_parallel.schedules import (
            forward_backward_pipelining_with_interleaving as interleaved,
        )

        assert get_forward_backward_func(2, 4) is interleaved


class TestNoPipelining:
    @pytest.mark.slow
    def test_matches_oracle(self):
        shared, stages, batch = make_problem(2)

        def step_fn(params, mb):
            h = pre_fn(params["shared"], mb)
            h = stage_fn(params["stages"], h)
            return post_fn(params["shared"], h, mb)

        params = {"shared": shared, "stages": stages}
        losses, grads = forward_backward_no_pipelining(step_fn, batch, params)
        ref_loss, ref_grads = jax.value_and_grad(
            lambda p: oracle_loss(p["shared"], p["stages"], batch)
        )(params)
        np.testing.assert_allclose(float(jnp.mean(losses)), float(ref_loss), rtol=1e-5)
        for a, r in zip(jax.tree.leaves(grads), jax.tree.leaves(ref_grads)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=1e-4, atol=1e-5)

    def test_selector(self):
        from apex_tpu.transformer.pipeline_parallel.schedules import (
            forward_backward_no_pipelining as nop,
            forward_backward_pipelining_without_interleaving as pip,
        )

        assert get_forward_backward_func(None, 1) is nop
        assert get_forward_backward_func(None, 4) is pip


class TestMemoryBound:
    """The 1F1B property: live activation state is O(P), not O(M).

    The round-1 schedule differentiated through the forward tick-scan,
    keeping every microbatch's residuals live (GPipe memory, linear in
    M).  The explicit schedule bounds the activation buffer at
    min(2P-1, M) stage inputs, so the compiled program's largest buffer
    must not grow with M (reference
    fwd_bwd_pipelining_without_interleaving.py:241's reason to exist).
    """

    @pytest.mark.slow
    @pytest.mark.parametrize("vpp", [1, 2])
    def test_peak_buffer_flat_in_microbatches(self, devices8, vpp):
        import re

        # Hin != Hact: a leaked activation buffer is f32[M, MB2, Hact],
        # which can NOT alias the batch input f32[M, MB2, Hin] — the
        # round-2 version used one width and was blind to an xbuf that
        # regressed to n_slots == M slots.
        Hin, Hact, L2, MB2, PP2 = 96, 128, 8, 8, 4

        def pre2(shared, mb):
            return jnp.tanh(mb["x"] @ shared["w_in"])

        def stage2(sp, h):
            out, _ = jax.lax.scan(
                lambda c, lp: (jnp.tanh(c @ lp["w"] + lp["b"]), None), h, sp
            )
            return out

        def post2(shared, h, mb):
            return jnp.mean((h @ shared["w_out"] - mb["y"]) ** 2)

        def offending_buffers(M, vpp=1):
            rng = np.random.RandomState(0)
            shared = {
                "w_in": jnp.asarray(rng.randn(Hin, Hact).astype(np.float32)),
                "w_out": jnp.asarray(rng.randn(Hact).astype(np.float32)),
            }
            stages = {
                "w": jnp.asarray(rng.randn(L2, Hact, Hact).astype(np.float32) * 0.3),
                "b": jnp.zeros((L2, Hact), np.float32),
            }
            batch = {
                "x": jnp.asarray(rng.randn(M, MB2, Hin).astype(np.float32)),
                "y": jnp.asarray(rng.randn(M, MB2).astype(np.float32)),
            }
            mesh = Mesh(np.array(jax.devices()[:PP2]), ("pp",))
            sspec = {"w_in": P(), "w_out": P()}
            stspec = {"w": P("pp", None, None), "b": P("pp", None)}
            bspec = {"x": P(), "y": P()}
            if vpp == 1:
                def run(sh, st, b):
                    return forward_backward_pipelining_without_interleaving(
                        pre2, stage2, post2, sh, st, b, axis_name="pp"
                    )
            else:
                def run(sh, st, b):
                    loss, (g_sh, g_st) = pipelined_fwd_bwd(
                        pre2, stage2, post2, sh, st, b,
                        num_chunks=vpp, axis_name="pp",
                    )
                    g_sh = jax.tree.map(lambda g: jax.lax.psum(g, "pp"), g_sh)
                    return loss, (g_sh, g_st)
            f = jax.jit(
                jax.shard_map(
                    run, mesh=mesh,
                    in_specs=(sspec, stspec, bspec),
                    out_specs=(P(), (sspec, stspec)),
                    check_vma=False,
                )
            )
            txt = f.lower(shared, stages, batch).compile().as_text()
            # the only tensors allowed to scale with M are the microbatch
            # inputs themselves; any other f32 buffer whose leading dim
            # falls in the per-microbatch window [M, M+vpp·P) is a
            # GPipe-style residual leak (T = M+P-1 tick-stacked
            # residuals being the round-1 failure mode). M is chosen so
            # the window can't collide with model dims (L2=8, H=96/128).
            inputs = {(M, MB2, Hin), (M, MB2)}
            offending = set()
            for mo in re.finditer(r"f32\[([0-9,]+)\]", txt):
                dims = tuple(int(d) for d in mo.group(1).split(","))
                if M <= dims[0] < M + vpp * PP2 and dims not in inputs:
                    offending.add(dims)
            return offending

        for M in (24, 48):
            assert not offending_buffers(M, vpp=vpp), (M, vpp, offending_buffers(M, vpp=vpp))
