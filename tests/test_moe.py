"""Expert parallelism (MoE) — beyond-reference capability (SURVEY §2.4
"EP: No").  Correctness model: the ep-sharded layer must match a dense
(all-experts-local) run of the same per-shard token batches, and expert
gradients must arrive complete on the owning device via the all_to_all
transpose."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer.expert_parallel import (
    _top_k_mask,
    load_balancing_loss,
    moe_ffn,
    moe_init,
)

# whole-file e2e/parity workloads: >20 s compiled (quick tier skips)
pytestmark = pytest.mark.slow

EP = 4


def _toy(T=32, H=16, F=32, E=8, seed=0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(T, H).astype(np.float32))
    params = moe_init(jax.random.PRNGKey(seed), H, F, E)
    return x, params


class TestRouterMask:
    def test_capacity_respected_and_slot_priority(self):
        probs = jax.nn.softmax(jnp.asarray(np.random.RandomState(0).randn(16, 4)), -1)
        dispatch, combine, m1 = _top_k_mask(probs, top_k=2, capacity=3)
        # ≤ capacity tokens land in any expert slot column
        per_expert = np.asarray(dispatch.sum(axis=(0, 2)))
        assert (per_expert <= 3 + 1e-6).all()
        # each (expert, slot) position holds at most one token
        per_slot = np.asarray(dispatch.sum(axis=0))
        assert (per_slot <= 1 + 1e-6).all()
        # combine weights only where dispatched
        assert np.asarray(jnp.where(dispatch == 0, combine, 0.0)).max() == 0.0

    def test_no_drops_with_ample_capacity(self):
        probs = jax.nn.softmax(jnp.asarray(np.random.RandomState(1).randn(16, 4)), -1)
        dispatch, _, _ = _top_k_mask(probs, top_k=2, capacity=32)
        assert float(dispatch.sum()) == 16 * 2  # every token in both slots

    def test_aux_loss_uniform_routing_is_one(self):
        # perfectly uniform router → aux = E · E · (1/E)·(1/E) = 1
        probs = jnp.full((64, 8), 1.0 / 8)
        m1 = jax.nn.one_hot(jnp.arange(64) % 8, 8)
        assert np.isclose(float(load_balancing_loss(probs, m1)), 1.0)


class TestDenseMoE:
    def test_top1_matches_manual(self):
        x, params = _toy(E=4)
        out, aux = moe_ffn(x, params, top_k=1, capacity_factor=4.0)
        # manual: every token goes to its argmax expert, weight = prob
        logits = x @ params["router"]
        probs = jax.nn.softmax(logits, -1)
        idx = jnp.argmax(probs, -1)
        ref = []
        for t in range(x.shape[0]):
            e = int(idx[t])
            h = jax.nn.gelu(x[t] @ params["w1"][e].T + params["b1"][e], approximate=True)
            y = h @ params["w2"][e].T + params["b2"][e]
            ref.append(float(probs[t, e]) * y)
        np.testing.assert_allclose(np.asarray(out), np.asarray(jnp.stack(ref)),
                                   rtol=1e-5, atol=1e-5)

    def test_differentiable(self):
        x, params = _toy()
        g = jax.grad(lambda p: jnp.sum(moe_ffn(x, p, top_k=2, capacity_factor=8.0)[0] ** 2))(params)
        assert all(np.isfinite(np.asarray(v)).all() for v in jax.tree.leaves(g))


@pytest.fixture
def ep_mesh(devices8):
    return Mesh(np.array(devices8[:EP]), ("ep",))


class TestExpertParallelMoE:
    def _setup(self, T_total=64, H=16, F=32, E=8, seed=0):
        rng = np.random.RandomState(seed)
        x = jnp.asarray(rng.randn(T_total, H).astype(np.float32))
        params = moe_init(jax.random.PRNGKey(seed), H, F, E)
        return x, params, E

    def test_sharded_matches_dense_per_shard(self, ep_mesh):
        x, params, E = self._setup()
        kw = dict(top_k=2, capacity_factor=float(E))  # ample: no drops

        # oracle: dense per token-shard (same shard-local capacity)
        Tl = x.shape[0] // EP
        ref = jnp.concatenate(
            [moe_ffn(x[i * Tl:(i + 1) * Tl], params, **kw)[0] for i in range(EP)]
        )

        pspecs = {
            "router": P(None, None), "w1": P("ep", None, None), "b1": P("ep", None),
            "w2": P("ep", None, None), "b2": P("ep", None),
        }
        out = jax.shard_map(
            lambda xx, pp: moe_ffn(xx, pp, ep_axis="ep", **kw)[0],
            mesh=ep_mesh, in_specs=(P("ep", None), pspecs),
            out_specs=P("ep", None), check_vma=False,
        )(x, params)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_expert_grads_complete_on_owner(self, ep_mesh):
        x, params, E = self._setup()
        kw = dict(top_k=2, capacity_factor=float(E))
        Tl = x.shape[0] // EP

        def oracle_loss(p):
            outs = [moe_ffn(x[i * Tl:(i + 1) * Tl], p, **kw)[0] for i in range(EP)]
            return jnp.sum(jnp.concatenate(outs) ** 2)

        go = jax.grad(oracle_loss)(params)

        pspecs = {
            "router": P(None, None), "w1": P("ep", None, None), "b1": P("ep", None),
            "w2": P("ep", None, None), "b2": P("ep", None),
        }

        def local_loss_grad(xx, pp):
            return jax.grad(
                lambda p: jnp.sum(moe_ffn(xx, p, ep_axis="ep", **kw)[0] ** 2)
            )(pp)

        g = jax.shard_map(
            local_loss_grad, mesh=ep_mesh, in_specs=(P("ep", None), pspecs),
            out_specs=pspecs, check_vma=False,
        )(x, params)
        # expert grads: complete on the owner — global view equals oracle
        for k in ("w1", "b1", "w2", "b2"):
            np.testing.assert_allclose(np.asarray(g[k]), np.asarray(go[k]),
                                       rtol=1e-4, atol=1e-5)

    def test_router_grads_sum_over_ep(self, ep_mesh):
        x, params, E = self._setup()
        kw = dict(top_k=1, capacity_factor=float(E))
        Tl = x.shape[0] // EP

        def oracle_loss(p):
            outs = [moe_ffn(x[i * Tl:(i + 1) * Tl], p, **kw)[0] for i in range(EP)]
            return jnp.sum(jnp.concatenate(outs) ** 2)

        go = jax.grad(oracle_loss)(params)["router"]

        pspecs = {
            "router": P(None, None), "w1": P("ep", None, None), "b1": P("ep", None),
            "w2": P("ep", None, None), "b2": P("ep", None),
        }

        def local(xx, pp):
            g = jax.grad(
                lambda p: jnp.sum(moe_ffn(xx, p, ep_axis="ep", **kw)[0] ** 2)
            )(pp)
            return jax.lax.psum(g["router"], "ep")

        g = jax.shard_map(
            local, mesh=ep_mesh, in_specs=(P("ep", None), pspecs),
            out_specs=P(None, None), check_vma=False,
        )(x, params)
        np.testing.assert_allclose(np.asarray(g), np.asarray(go), rtol=1e-4, atol=1e-5)


class TestMoEGPT:
    def _cfg(self, **kw):
        from apex_tpu.models.gpt import GPTConfig

        return GPTConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_attention_heads=4,
            max_seq_len=32, compute_dtype=jnp.float32, checkpoint_layers=False,
            moe_num_experts=8, moe_top_k=2, **kw,
        )

    def test_dense_forward_and_loss(self):
        from apex_tpu.models.gpt import gpt_loss, init_params

        cfg = self._cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        assert "moe" in params["layers"] and "fc1" not in params["layers"]
        tokens = jnp.asarray(np.random.RandomState(0).randint(0, 64, (4, 32)))
        loss = gpt_loss(params, tokens, jnp.roll(tokens, -1, 1), cfg)
        assert np.isfinite(float(loss))

    def test_fused_ce_matches_dense_head(self):
        """MoE's (hidden, aux) return threads through the fused head:
        loss and grads match the dense-head config exactly."""
        import dataclasses

        from apex_tpu.models.gpt import gpt_loss, init_params

        cfg = self._cfg(fused_ce=True, fused_ce_chunk=8)
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.RandomState(3)
        tokens = jnp.asarray(rng.randint(0, 64, (4, 32)))
        targets = jnp.roll(tokens, -1, 1)
        dense_cfg = dataclasses.replace(cfg, fused_ce=False)
        ref, ref_g = jax.value_and_grad(gpt_loss)(
            params, tokens, targets, dense_cfg)
        got, got_g = jax.value_and_grad(gpt_loss)(params, tokens, targets, cfg)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
            got_g, ref_g)

    def test_sharded_loss_matches_dense(self, devices8):
        from apex_tpu.models.gpt import GPTConfig, gpt_loss, init_params, make_train_step
        from apex_tpu.optimizers import FusedAdam

        # aux is computed per dp shard (product-of-means ≠ mean-of-products),
        # so compare the CE part only
        cfg = self._cfg(moe_capacity_factor=8.0, moe_aux_coef=0.0)
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.RandomState(1)
        tokens = jnp.asarray(rng.randint(0, 64, (8, 32)))
        targets = jnp.roll(tokens, -1, 1)
        dense = float(gpt_loss(params, tokens, targets, cfg))

        mesh = Mesh(np.array(devices8[:4]).reshape(4, 1), ("dp", "tp"))
        opt = FusedAdam(lr=1e-3)
        step = make_train_step(cfg, opt, mesh)
        state = opt.init(params)
        _, _, loss = step(params, state, tokens, targets)
        np.testing.assert_allclose(float(loss), dense, rtol=1e-5)

    def test_train_step_decreases_loss(self, devices8):
        from apex_tpu.models.gpt import init_params, make_train_step
        from apex_tpu.optimizers import FusedAdam

        cfg = self._cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        mesh = Mesh(np.array(devices8).reshape(4, 2), ("dp", "tp"))
        opt = FusedAdam(lr=1e-2)
        step = make_train_step(cfg, opt, mesh)
        state = opt.init(params)
        rng = np.random.RandomState(2)
        tokens = jnp.asarray(rng.randint(0, 64, (8, 32)))
        targets = jnp.roll(tokens, -1, 1)
        losses = []
        for _ in range(10):
            params, state, loss = step(params, state, tokens, targets)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_moe_rejects_sequence_parallel(self):
        from apex_tpu.models.gpt import gpt_forward, init_params

        cfg = self._cfg(sequence_parallel=True)
        params = init_params(cfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="sequence parallel"):
            gpt_forward(params, jnp.zeros((1, 8), jnp.int32), cfg, axis_name="tp")

    def test_rejects_indivisible_experts(self, devices8):
        from apex_tpu.models.gpt import init_params, make_train_step
        from apex_tpu.optimizers import FusedAdam

        cfg = self._cfg().__class__(**{**self._cfg().__dict__, "moe_num_experts": 6})
        mesh = Mesh(np.array(devices8[:4]).reshape(4, 1), ("dp", "tp"))
        with pytest.raises(ValueError, match="moe_num_experts"):
            make_train_step(cfg, FusedAdam(lr=1e-3), mesh)


class TestMoEPipeline:
    """MoE composed with the pipeline schedule (pp x dp x tp): the aux
    loss rides the tick schedule's aux channel and expert grads stay
    dp-sharded — parity vs the single-device dense-MoE oracle."""

    def test_pp_moe_matches_single_device(self, devices8):
        from apex_tpu.models.gpt import (
            GPTConfig, gpt_loss, init_params, make_pp_train_step,
        )
        from apex_tpu.optimizers import FusedSGD

        # ample capacity: token-drop sets would otherwise differ between
        # the full-batch oracle and the microbatched pipeline grouping
        cfg = GPTConfig(
            vocab_size=64, hidden_size=32, num_layers=4,
            num_attention_heads=4, max_seq_len=16,
            compute_dtype=jnp.float32, checkpoint_layers=False,
            moe_num_experts=4, moe_top_k=2, moe_capacity_factor=4.0,
        )
        mesh = Mesh(np.array(devices8).reshape(2, 2, 2), ("dp", "pp", "tp"))
        params = init_params(cfg, jax.random.PRNGKey(0))
        # SGD: the param delta is linear in the grads, so the comparison
        # tests gradient parity without Adam's rsqrt noise amplification
        opt = FusedSGD(lr=1e-2, momentum=0.0)
        state = opt.init(params)

        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, size=(8, 16)))
        targets = jnp.roll(tokens, -1, axis=1)

        from apex_tpu.optimizers.fused_sgd import SGDState
        from apex_tpu.models.gpt import param_specs as gpt_param_specs

        base_specs = gpt_param_specs(cfg, ep_axis="dp")
        pp_specs = dict(base_specs)
        pp_specs["layers"] = jax.tree.map(
            lambda s: P("pp", *s[1:]), base_specs["layers"],
            is_leaf=lambda s: isinstance(s, P),
        )
        sspec = SGDState(step=P(), momentum_buffer=pp_specs, master=None)
        step = make_pp_train_step(cfg, opt, mesh, num_microbatches=2,
                                  opt_state_spec=sspec)
        new_params, _, loss = step(params, state, tokens, targets)

        ref_loss, ref_grads = jax.value_and_grad(gpt_loss)(params, tokens, targets, cfg)
        ref_params, _ = opt.update(ref_grads, opt.init(params), params)

        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
        for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(new_params),
            jax.tree_util.tree_leaves_with_path(ref_params),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-5,
                err_msg=jax.tree_util.keystr(ka),
            )
