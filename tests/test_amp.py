"""amp tests — mirrors tests/L0/run_amp of the reference (cast checks,
loss-scaler dynamics, update_scale_hysteresis parity)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp


class TestPolicy:
    def test_opt_levels_exist(self):
        for lvl in ("O0", "O1", "O2", "O3"):
            p = amp.get_policy(lvl)
            assert p.opt_level == lvl

    def test_bad_level_raises(self):
        with pytest.raises(ValueError):
            amp.get_policy("O4")

    def test_o2_casts_params_keeps_norms_fp32(self):
        params = {
            "dense": {"kernel": jnp.ones((4, 4), jnp.float32)},
            "batchnorm_0": {"scale": jnp.ones((4,), jnp.float32)},
        }
        pol = amp.get_policy("O2")
        cast = pol.cast_params(params)
        assert cast["dense"]["kernel"].dtype == jnp.bfloat16
        assert cast["batchnorm_0"]["scale"].dtype == jnp.float32

    def test_o3_casts_everything(self):
        params = {"bn": jnp.ones((4,), jnp.float32), "w": jnp.ones((4,), jnp.float32)}
        cast = amp.get_policy("O3").cast_params(params)
        assert cast["bn"].dtype == jnp.bfloat16
        assert cast["w"].dtype == jnp.bfloat16

    def test_o0_noop(self):
        params = {"w": jnp.ones((4,), jnp.float32)}
        cast = amp.get_policy("O0").cast_params(params)
        assert cast["w"].dtype == jnp.float32

    def test_fp16_enables_dynamic_scaling(self):
        p = amp.get_policy("O2", half_dtype=jnp.float16)
        assert p.loss_scale == "dynamic"
        p = amp.get_policy("O2")  # bf16 default
        assert p.loss_scale is None


class TestDynamicLossScaler:
    def test_init(self):
        s = amp.DynamicLossScaler()
        st = s.init()
        assert float(st.loss_scale) == 2.0 ** 16

    def test_backoff_on_overflow(self):
        s = amp.DynamicLossScaler(init_scale=2.0 ** 10)
        st = s.init()
        st = s.update(st, jnp.bool_(False))
        assert float(st.loss_scale) == 2.0 ** 9
        assert int(st.growth_tracker) == 0

    def test_growth_after_interval(self):
        s = amp.DynamicLossScaler(init_scale=4.0, growth_interval=3)
        st = s.init()
        for _ in range(2):
            st = s.update(st, jnp.bool_(True))
            assert float(st.loss_scale) == 4.0
        st = s.update(st, jnp.bool_(True))
        assert float(st.loss_scale) == 8.0
        assert int(st.growth_tracker) == 0

    def test_hysteresis(self):
        # hysteresis=2: first overflow tolerated, second backs off
        s = amp.DynamicLossScaler(init_scale=16.0, hysteresis=2)
        st = s.init()
        st = s.update(st, jnp.bool_(False))
        assert float(st.loss_scale) == 16.0
        st = s.update(st, jnp.bool_(False))
        assert float(st.loss_scale) == 8.0
        # a finite step resets hysteresis
        st = s.update(st, jnp.bool_(True))
        st = s.update(st, jnp.bool_(False))
        assert float(st.loss_scale) == 8.0

    def test_min_scale_floor(self):
        s = amp.DynamicLossScaler(init_scale=2.0)
        st = s.init()
        for _ in range(5):
            st = s.update(st, jnp.bool_(False))
        assert float(st.loss_scale) == 1.0

    def test_unscale_detects_inf(self):
        s = amp.DynamicLossScaler(init_scale=4.0)
        st = s.init()
        grads = {"w": jnp.array([1.0, jnp.inf])}
        out, finite = s.unscale(st, grads)
        assert not bool(finite)
        grads = {"w": jnp.array([1.0, 2.0])}
        out, finite = s.unscale(st, grads)
        assert bool(finite)
        np.testing.assert_allclose(np.asarray(out["w"]), [0.25, 0.5])

    def test_update_is_jittable(self):
        s = amp.DynamicLossScaler()
        st = s.init()
        st = jax.jit(s.update)(st, jnp.bool_(True))
        assert int(st.growth_tracker) == 1

    def test_state_dict_roundtrip(self):
        s = amp.DynamicLossScaler()
        st = s.init()
        st = s.update(st, jnp.bool_(False))
        st2 = s.load_state_dict(s.state_dict(st))
        assert float(st2.loss_scale) == float(st.loss_scale)


class TestValueAndGrad:
    def test_fp16_pipeline_skips_on_overflow(self):
        params = {"w": jnp.array([2.0], jnp.float32)}
        cast_params, a = amp.initialize(params, opt_level="O1", half_dtype=jnp.float16)
        st = a.init_state()

        def loss_fn(p, x):
            return jnp.sum(p["w"] * x)

        vg = amp.value_and_grad(a, loss_fn)
        loss, grads, st, finite = vg(cast_params, st, jnp.array([3.0]))
        assert bool(finite)
        np.testing.assert_allclose(float(loss), 6.0, rtol=1e-3)
        np.testing.assert_allclose(np.asarray(grads["w"]), [3.0], rtol=1e-3)

        # poisoned input → non-finite grads flagged, scale halves
        loss, grads, st2, finite = vg(cast_params, st, jnp.array([jnp.inf]))
        assert not bool(finite)
        assert float(st2.loss_scale) == float(st.loss_scale) / 2

    def test_bf16_no_scaler(self):
        params = {"w": jnp.array([2.0], jnp.float32)}
        cast_params, a = amp.initialize(params, opt_level="O2")
        assert a.scaler is None
        vg = amp.value_and_grad(a, lambda p, x: jnp.sum(p["w"] * x))
        loss, grads, st, finite = vg(cast_params, None, jnp.array([3.0]))
        assert bool(finite)
        assert grads["w"].dtype == jnp.float32


class TestMultiLoss:
    """num_losses parity (reference amp.initialize(num_losses=N)):
    independent scaler states per loss."""

    def test_per_loss_states_round_trip(self):
        from apex_tpu import amp as amp_mod

        params = {"w": jnp.ones((4,))}
        _, a = amp_mod.initialize(params, opt_level="O2", half_dtype=jnp.float16)
        states = a.init_state(num_losses=3)
        assert len(states) == 3
        # scale one loss's state down (simulate overflow on loss 1)
        states[1] = a.update_scaler(states[1], jnp.bool_(False))
        assert float(states[1].loss_scale) < float(states[0].loss_scale)
        d = a.state_dict(states)
        assert set(d) == {"loss_scaler0", "loss_scaler1", "loss_scaler2"}
        back = a.load_state_dict(d)
        assert float(back[1].loss_scale) == float(states[1].loss_scale)
        assert float(back[0].loss_scale) == float(states[0].loss_scale)
