"""Multi-tensor primitive parity tests.

Mirrors the reference's per-kernel L0 suite
(``tests/L0/run_amp/test_multi_tensor_scale.py`` / ``..._axpby`` /
``..._l2norm`` / ``..._unscale_l2norm``): each op checked against a
NumPy oracle over fp32/fp16/bf16 in/out combinations, overflow
(inf/nan) detection included.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.multi_tensor import (
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_norm_blend,
    multi_tensor_scale,
    tree_not_finite,
    tree_where,
)


def _tree(dtype, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "a": jnp.asarray(rng.randn(33, 9).astype(np.float32)).astype(dtype),
        "b": [jnp.asarray(rng.randn(5).astype(np.float32)).astype(dtype)],
    }


IN_OUT = [
    (jnp.float32, jnp.float32),
    (jnp.float16, jnp.float16),
    (jnp.bfloat16, jnp.bfloat16),
    (jnp.float16, jnp.float32),
    (jnp.float32, jnp.float16),
]


class TestScale:
    @pytest.mark.parametrize("in_dtype,out_dtype", IN_OUT)
    def test_matches_numpy(self, in_dtype, out_dtype):
        src = _tree(in_dtype)
        out, found_inf = multi_tensor_scale(src, 0.25, out_dtype=out_dtype)
        assert not bool(found_inf)
        for k in ("a",):
            ref = np.asarray(src[k], np.float32) * 0.25
            assert out[k].dtype == out_dtype
            np.testing.assert_allclose(
                np.asarray(out[k], np.float32), ref.astype(np.dtype(out_dtype)).astype(np.float32),
                rtol=1e-2 if out_dtype == jnp.bfloat16 else 1e-6)

    def test_overflow_sets_flag(self):
        src = _tree(jnp.float32)
        src["a"] = src["a"].at[3, 3].set(jnp.inf)
        _, found_inf = multi_tensor_scale(src, 2.0)
        assert bool(found_inf)
        # fp16 range overflow during the scale also trips it (the
        # reference's unscale-detects-inf contract)
        big = {"x": jnp.full((8,), 60000.0, jnp.float16)}
        _, found_inf = multi_tensor_scale(big, 4.0, out_dtype=jnp.float16)
        assert bool(found_inf)


class TestAxpby:
    @pytest.mark.parametrize("in_dtype,out_dtype", IN_OUT)
    def test_matches_numpy(self, in_dtype, out_dtype):
        x, y = _tree(in_dtype, 1), _tree(in_dtype, 2)
        out, found_inf = multi_tensor_axpby(2.0, x, -0.5, y, out_dtype=out_dtype)
        assert not bool(found_inf)
        ref = 2.0 * np.asarray(x["a"], np.float32) - 0.5 * np.asarray(y["a"], np.float32)
        assert out["a"].dtype == out_dtype
        np.testing.assert_allclose(
            np.asarray(out["a"], np.float32), ref.astype(np.dtype(out_dtype)).astype(np.float32),
            rtol=1e-2 if out_dtype in (jnp.bfloat16, jnp.float16) else 1e-6, atol=1e-3)

    def test_nan_propagates_to_flag(self):
        x, y = _tree(jnp.float32, 1), _tree(jnp.float32, 2)
        y["b"][0] = y["b"][0].at[0].set(jnp.nan)
        _, found_inf = multi_tensor_axpby(1.0, x, 1.0, y)
        assert bool(found_inf)


class TestL2Norm:
    def test_global_matches_numpy(self):
        t = _tree(jnp.float32, 3)
        flat = np.concatenate([np.asarray(t["a"]).ravel(), np.asarray(t["b"][0]).ravel()])
        np.testing.assert_allclose(float(multi_tensor_l2norm(t)), np.linalg.norm(flat), rtol=1e-6)

    def test_per_tensor(self):
        t = _tree(jnp.float32, 4)
        total, per = multi_tensor_l2norm(t, per_tensor=True)
        np.testing.assert_allclose(float(per[0]), np.linalg.norm(np.asarray(t["a"])), rtol=1e-6)
        np.testing.assert_allclose(float(per[1]), np.linalg.norm(np.asarray(t["b"][0])), rtol=1e-6)
        np.testing.assert_allclose(
            float(total), np.sqrt(sum(float(p) ** 2 for p in per)), rtol=1e-6)

    def test_half_inputs_fp32_math(self):
        # fp16 inputs whose squared sum overflows fp16 still produce a
        # finite fp32 norm (the reference computes in MATH_T=fp32)
        t = {"x": jnp.full((4096,), 16.0, jnp.float16)}
        n = multi_tensor_l2norm(t)
        np.testing.assert_allclose(float(n), 16.0 * 64.0, rtol=1e-3)

    def test_empty_tree(self):
        assert float(multi_tensor_l2norm({})) == 0.0


class TestNormBlend:
    def test_l2_blend(self):
        t = {"x": jnp.asarray([3.0, 4.0])}
        old = [jnp.float32(10.0)]
        (out,) = multi_tensor_norm_blend(old, t, 0.5, 2.0, norm_type=2)
        np.testing.assert_allclose(float(out), np.sqrt(0.5 * 100 + 2.0 * 25), rtol=1e-6)

    def test_linf_blend(self):
        t = {"x": jnp.asarray([-7.0, 4.0])}
        (out,) = multi_tensor_norm_blend([jnp.float32(2.0)], t, 0.5, 3.0, norm_type=0)
        np.testing.assert_allclose(float(out), 0.5 * 2.0 + 3.0 * 7.0, rtol=1e-6)

    def test_bad_norm_type(self):
        with pytest.raises(ValueError):
            multi_tensor_norm_blend([jnp.float32(1.0)], {"x": jnp.ones(2)}, 1, 1, norm_type=1)


class TestPredication:
    def test_tree_where_and_not_finite(self):
        a = {"x": jnp.ones(3)}
        b = {"x": jnp.zeros(3)}
        np.testing.assert_array_equal(
            np.asarray(tree_where(jnp.bool_(True), a, b)["x"]), np.ones(3))
        np.testing.assert_array_equal(
            np.asarray(tree_where(jnp.bool_(False), a, b)["x"]), np.zeros(3))
        assert not bool(tree_not_finite(a))
        assert bool(tree_not_finite({"x": jnp.asarray([1.0, jnp.inf])}))
        assert not bool(tree_not_finite({}))

    def test_noop_semantics_under_jit(self):
        """The reference kernel early-exits when noop_flag is set; the XLA
        form predicates the whole update.  Check it composes under jit."""

        @jax.jit
        def step(p, g):
            scaled, found = multi_tensor_scale(g, 0.5)
            new_p, _ = multi_tensor_axpby(1.0, p, -1.0, scaled)
            return tree_where(~found, new_p, p)

        p = {"w": jnp.ones(4)}
        ok = step(p, {"w": jnp.full(4, 0.5)})
        np.testing.assert_allclose(np.asarray(ok["w"]), 0.75)
        bad = step(p, {"w": jnp.asarray([jnp.nan, 0, 0, 0])})
        np.testing.assert_array_equal(np.asarray(bad["w"]), np.ones(4))
