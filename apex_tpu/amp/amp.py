"""The amp decorator/registry API.

Reference: ``apex/amp/amp.py:30-183`` — ``half_function`` /
``float_function`` / ``promote_function`` decorators and
``register_half_function(module, name)`` etc., which monkey-patch
functions into the O1 cast tables.

JAX functions are values, not attributes to patch, so the registry
returns *wrapped* functions instead of mutating modules; the cast
semantics (inputs to half / to fp32 / promote to widest) are identical.
"""

from typing import Callable

import jax
import jax.numpy as jnp

from apex_tpu._autocast_utils import autocast

_HALF = jnp.bfloat16


def set_half_dtype(dtype) -> None:
    """Choose the 'half' dtype used by the decorators (bf16 default)."""
    global _HALF
    _HALF = dtype


def half_function(fn: Callable) -> Callable:
    """Run fn's floating inputs in half precision (reference amp.py:30).

    The half dtype is read at call time, so ``set_half_dtype`` /
    ``amp.init(half_dtype=...)`` affect functions decorated earlier
    (matching the reference, where the dtype lives in global amp state).
    """

    def wrapped(*args, **kwargs):
        return autocast(fn, dtype=_HALF)(*args, **kwargs)

    return wrapped


def float_function(fn: Callable) -> Callable:
    """Run fn's floating inputs in fp32 (reference amp.py:34)."""
    return autocast(fn, dtype=jnp.float32)


def promote_function(fn: Callable) -> Callable:
    """Promote mixed inputs to the widest floating dtype (reference
    amp.py:38 / wrap.py promote)."""

    def _is_float(a):
        return hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)

    def wrapped(*args, **kwargs):
        floats = [a.dtype for a in (*args, *kwargs.values()) if _is_float(a)]
        if not floats:
            return fn(*args, **kwargs)
        widest = jnp.result_type(*floats)
        args = tuple(a.astype(widest) if _is_float(a) else a for a in args)
        kwargs = {
            k: (v.astype(widest) if _is_float(v) else v) for k, v in kwargs.items()
        }
        return fn(*args, **kwargs)

    return wrapped


def register_half_function(module, name: str) -> None:
    """Wrap ``module.name`` in a half cast (reference amp.py:50).  The
    one place apex-style in-place registration is still meaningful —
    user-owned modules."""
    setattr(module, name, half_function(getattr(module, name)))


def register_float_function(module, name: str) -> None:
    setattr(module, name, float_function(getattr(module, name)))


def register_promote_function(module, name: str) -> None:
    setattr(module, name, promote_function(getattr(module, name)))


def init(enabled: bool = True, loss_scale: str = "dynamic", half_dtype=None, **kwargs):
    """Legacy ``amp.init`` entry point (reference apex/amp/amp.py:74).

    The reference patches the torch function tables and returns an
    ``AmpHandle``; here there is no global function table to patch, so
    this configures the decorator half-dtype and returns an O1
    :class:`~apex_tpu.amp.frontend.Amp` whose ``scale_loss`` /
    ``state_dict`` match the old handle surface.  ``enabled=False``
    returns a no-op O0 Amp (reference NoOpHandle).

    Legacy apex ``init`` kwargs with no TPU meaning (``verbose``,
    ``enable_caching``, ``allow_banned``, ...) are accepted and ignored.
    """
    from apex_tpu.amp import frontend

    if half_dtype is not None:
        set_half_dtype(half_dtype)
    known = {"init_scale", "growth_interval", "hysteresis"}
    fwd = {k: v for k, v in kwargs.items() if k in known}
    _, amp = frontend.initialize(
        {}, opt_level="O1" if enabled else "O0",
        half_dtype=half_dtype,
        loss_scale=loss_scale if enabled else None,
        **fwd,
    )
    return amp
