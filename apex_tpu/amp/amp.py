"""The amp decorator/registry API.

Reference: ``apex/amp/amp.py:30-183`` — ``half_function`` /
``float_function`` / ``promote_function`` decorators and
``register_half_function(module, name)`` etc., which monkey-patch
functions into the O1 cast tables.

JAX functions are values, not attributes to patch, so the registry
returns *wrapped* functions instead of mutating modules; the cast
semantics (inputs to half / to fp32 / promote to widest) are identical.
"""

from typing import Callable

import jax
import jax.numpy as jnp

from apex_tpu._autocast_utils import autocast

_HALF = jnp.bfloat16


def set_half_dtype(dtype) -> None:
    """Choose the 'half' dtype used by the decorators (bf16 default)."""
    global _HALF
    _HALF = dtype


def half_function(fn: Callable) -> Callable:
    """Run fn's floating inputs in half precision (reference amp.py:30)."""
    return autocast(fn, dtype=_HALF)


def float_function(fn: Callable) -> Callable:
    """Run fn's floating inputs in fp32 (reference amp.py:34)."""
    return autocast(fn, dtype=jnp.float32)


def promote_function(fn: Callable) -> Callable:
    """Promote mixed inputs to the widest floating dtype (reference
    amp.py:38 / wrap.py promote)."""

    def wrapped(*args, **kwargs):
        floats = [
            a.dtype
            for a in args
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
        ]
        if not floats:
            return fn(*args, **kwargs)
        widest = jnp.result_type(*floats)
        args = tuple(
            a.astype(widest)
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
            else a
            for a in args
        )
        return fn(*args, **kwargs)

    return wrapped


def register_half_function(module, name: str) -> None:
    """Wrap ``module.name`` in a half cast (reference amp.py:50).  The
    one place apex-style in-place registration is still meaningful —
    user-owned modules."""
    setattr(module, name, half_function(getattr(module, name)))


def register_float_function(module, name: str) -> None:
    setattr(module, name, float_function(getattr(module, name)))


def register_promote_function(module, name: str) -> None:
    setattr(module, name, promote_function(getattr(module, name)))
