"""Mixed precision for TPU (reference: ``apex/amp``).

The O0–O3 opt levels map onto functional dtype policies
(:mod:`apex_tpu.amp.policy`), and dynamic loss scaling is fully
device-side (:mod:`apex_tpu.amp.scaler`), following the reference's
capturable/CUDA-graph design (``csrc/update_scale_hysteresis.cu``) which
is the natural XLA semantics.

Export surface mirrors ``apex/amp/__init__.py``: the decorator/registry
API from ``amp.py``, ``scale_loss``/``disable_casts`` from ``handle.py``,
``initialize``/``state_dict``/``load_state_dict`` from ``frontend.py``,
and ``master_params``.
"""

from apex_tpu.amp.amp import (
    float_function,
    half_function,
    init,
    promote_function,
    register_float_function,
    register_half_function,
    register_promote_function,
    set_half_dtype,
)
from apex_tpu.amp.frontend import (
    Amp,
    initialize,
    load_state_dict,
    master_params,
    state_dict,
    value_and_grad,
)
from apex_tpu.amp.handle import disable_casts, scale_loss
from apex_tpu.amp.policy import Policy, get_policy
from apex_tpu.amp.scaler import (
    DynamicLossScaler,
    ScalerState,
    StaticLossScaler,
    all_finite,
)

__all__ = [
    "Amp",
    "initialize",
    "value_and_grad",
    "state_dict",
    "load_state_dict",
    "master_params",
    "scale_loss",
    "disable_casts",
    "init",
    "half_function",
    "float_function",
    "promote_function",
    "register_half_function",
    "register_float_function",
    "register_promote_function",
    "set_half_dtype",
    "Policy",
    "get_policy",
    "DynamicLossScaler",
    "StaticLossScaler",
    "ScalerState",
    "all_finite",
]
