"""Mixed precision for TPU (reference: ``apex/amp``).

The O0–O3 opt levels map onto functional dtype policies
(:mod:`apex_tpu.amp.policy`), and dynamic loss scaling is fully
device-side (:mod:`apex_tpu.amp.scaler`), following the reference's
capturable/CUDA-graph design (``csrc/update_scale_hysteresis.cu``) which
is the natural XLA semantics.
"""

from apex_tpu.amp.frontend import Amp, initialize, value_and_grad
from apex_tpu.amp.policy import Policy, get_policy
from apex_tpu.amp.scaler import (
    DynamicLossScaler,
    ScalerState,
    StaticLossScaler,
    all_finite,
)

__all__ = [
    "Amp",
    "initialize",
    "value_and_grad",
    "Policy",
    "get_policy",
    "DynamicLossScaler",
    "StaticLossScaler",
    "ScalerState",
    "all_finite",
]
