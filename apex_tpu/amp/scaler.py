"""Dynamic loss scaling — fully device-side, jit-compatible.

Reference: ``apex/amp/scaler.py:33-217`` (python ``LossScaler`` with fused
``multi_tensor_scale`` unscale and host-side scale update) and
``csrc/update_scale_hysteresis.cu:5-47`` (the device-side scale-update
kernel used by capturable optimizers).

The CUDA-graphs-era "capturable" design — overflow predicate, unscale, and
scale update all device-resident, optimizer step predicated on the
overflow flag — is the natural fit for XLA, where the whole train step is
one compiled program.  That design is adopted here wholesale:

- ``ScalerState`` is a small pytree (scale, growth_tracker, hysteresis).
- ``unscale`` multiplies grads by ``1/scale`` and returns an
  ``all_finite`` predicate (replaces the noop_flag buffer).
- ``update`` applies the exact hysteresis semantics of
  ``update_scale_hysteresis.cu``: on overflow decrement hysteresis and
  back off only when exhausted; on ``growth_interval`` consecutive good
  steps multiply by ``growth_factor``.
- The *caller* predicates the optimizer step with ``jnp.where`` — see
  :func:`apex_tpu.optimizers.FusedAdam.update`.

No host synchronization ever happens (the reference does a D2H read per
step, ``apex/amp/scaler.py:197-217``).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class ScalerState(NamedTuple):
    loss_scale: jnp.ndarray  # f32 scalar
    growth_tracker: jnp.ndarray  # i32 scalar: consecutive finite steps
    hysteresis: jnp.ndarray  # i32 scalar: remaining tolerated overflows


class DynamicLossScaler:
    """Device-side dynamic loss scaler.

    Defaults mirror ``apex.amp.scaler.LossScaler`` (init 2**16, factor 2,
    window 2000; ``apex/amp/scaler.py:38-60``) plus the hysteresis knob of
    ``update_scale_hysteresis.cu`` (hysteresis=1 reproduces the python
    scaler exactly).
    """

    def __init__(
        self,
        init_scale: float = 2.0 ** 16,
        growth_factor: float = 2.0,
        backoff_factor: float = 0.5,
        growth_interval: int = 2000,
        hysteresis: int = 1,
        min_scale: float = 1.0,
        max_scale: float = 2.0 ** 24,
    ):
        self.init_scale = float(init_scale)
        self.growth_factor = float(growth_factor)
        self.backoff_factor = float(backoff_factor)
        self.growth_interval = int(growth_interval)
        self.init_hysteresis = int(hysteresis)
        self.min_scale = float(min_scale)
        self.max_scale = float(max_scale)

    # ------------------------------------------------------------------ state
    def init(self) -> ScalerState:
        return ScalerState(
            loss_scale=jnp.float32(self.init_scale),
            growth_tracker=jnp.int32(0),
            hysteresis=jnp.int32(self.init_hysteresis),
        )

    # ------------------------------------------------------------------- ops
    def scale(self, state: ScalerState, loss):
        """Scale the loss (do this *before* grad; apex handle.py:113)."""
        return jax.tree.map(lambda l: l * state.loss_scale.astype(l.dtype), loss)

    def unscale(self, state: ScalerState, grads):
        """Unscale grads in fp32 and detect non-finite values.

        Mirrors ``LossScaler.unscale`` (apex/amp/scaler.py:94-119): the
        fp16->fp32 unscale-copy into master grads, with inf/nan detection
        folded into the same pass (multi_tensor_scale's noop_flag).
        Returns ``(unscaled_grads_fp32, all_finite)``.
        """
        inv = 1.0 / state.loss_scale

        def unscale_one(g):
            return g.astype(jnp.float32) * inv

        out = jax.tree.map(unscale_one, grads)
        finite = all_finite(out)
        return out, finite

    def update(self, state: ScalerState, all_finite_flag) -> ScalerState:
        """Exact ``update_scale_hysteresis.cu:5-47`` semantics, branch-free.

        if !all_finite: hysteresis -= 1; if hysteresis <= 0:
            scale = max(scale*backoff, min); growth_tracker = 0
        else: growth_tracker += 1; if growth_tracker == interval:
            scale = min(scale*growth, max); growth_tracker = 0;
            hysteresis reset
        """
        finite = jnp.asarray(all_finite_flag)
        scale, tracker, hyst = state

        # Overflow branch.
        new_hyst_of = hyst - 1
        do_backoff = new_hyst_of <= 0
        scale_of = jnp.where(
            do_backoff,
            jnp.maximum(scale * self.backoff_factor, self.min_scale),
            scale,
        )
        hyst_of = jnp.where(do_backoff, jnp.int32(self.init_hysteresis), new_hyst_of)
        tracker_of = jnp.int32(0)

        # Finite branch.
        new_tracker = tracker + 1
        do_growth = new_tracker >= self.growth_interval
        scale_ok = jnp.where(
            do_growth,
            jnp.minimum(scale * self.growth_factor, self.max_scale),
            scale,
        )
        tracker_ok = jnp.where(do_growth, jnp.int32(0), new_tracker)
        hyst_ok = jnp.int32(self.init_hysteresis)

        return ScalerState(
            loss_scale=jnp.where(finite, scale_ok, scale_of),
            growth_tracker=jnp.where(finite, tracker_ok, tracker_of),
            hysteresis=jnp.where(finite, hyst_ok, hyst_of),
        )

    # ------------------------------------------------------ state_dict parity
    def state_dict(self, state: ScalerState):
        """Reference: apex/amp/frontend.py:365-376 (amp.state_dict)."""
        return {
            "loss_scale": float(state.loss_scale),
            "growth_tracker": int(state.growth_tracker),
            "hysteresis": int(state.hysteresis),
        }

    def load_state_dict(self, d) -> ScalerState:
        return ScalerState(
            loss_scale=jnp.float32(d["loss_scale"]),
            growth_tracker=jnp.int32(d["growth_tracker"]),
            hysteresis=jnp.int32(d.get("hysteresis", self.init_hysteresis)),
        )


class StaticLossScaler:
    """Constant loss scale (``loss_scale=<float>`` opt; apex frontend)."""

    def __init__(self, scale: float = 1.0):
        self._scale = float(scale)

    def init(self) -> ScalerState:
        return ScalerState(jnp.float32(self._scale), jnp.int32(0), jnp.int32(0))

    def scale(self, state, loss):
        return jax.tree.map(lambda l: l * state.loss_scale.astype(l.dtype), loss)

    def unscale(self, state, grads):
        inv = 1.0 / state.loss_scale
        out = jax.tree.map(lambda g: g.astype(jnp.float32) * inv, grads)
        return out, all_finite(out)

    def update(self, state, all_finite_flag):
        return state

    def state_dict(self, state):
        return {"loss_scale": float(state.loss_scale)}

    def load_state_dict(self, d):
        return ScalerState(jnp.float32(d["loss_scale"]), jnp.int32(0), jnp.int32(0))


def all_finite(tree) -> jnp.ndarray:
    """True iff every element of every leaf is finite (no inf/nan).

    The functional replacement for the reference's ``noop_flag``/
    ``_overflow_buf`` (``csrc/multi_tensor_scale_kernel.cu``).
    """
    leaves = [x for x in jax.tree.leaves(tree) if hasattr(x, "dtype")]
    if not leaves:
        return jnp.bool_(True)
    flags = [jnp.all(jnp.isfinite(x)) for x in leaves]
    return jnp.stack(flags).all()
