"""The ``amp.initialize``-style front end, re-imagined functionally.

Reference: ``apex/amp/frontend.py:197-404`` and ``apex/amp/handle.py:16``
(``scale_loss``).  The reference mutates models/optimizers in place and
installs patched ``forward``/``step``.  Here, :func:`initialize` returns a
small immutable :class:`Amp` object plus cast params, and
:func:`value_and_grad` wraps a loss function so one call produces
(loss, grads, new_scaler_state, grads_finite) with all scaling handled —
the moral equivalent of ``with amp.scale_loss(...) as scaled: ...``.
"""

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.amp.policy import Policy, get_policy
from apex_tpu.amp.scaler import DynamicLossScaler, ScalerState, StaticLossScaler, all_finite


class Amp(NamedTuple):
    """Bundle of policy + scaler (static) — safe to close over in jit."""

    policy: Policy
    scaler: Any  # DynamicLossScaler | StaticLossScaler | None

    def init_state(self, num_losses: int = 1):
        """One scaler state, or a list of ``num_losses`` independent
        states (reference ``amp.initialize(num_losses=)``,
        frontend.py:197 — per-loss ``LossScaler`` instances; here the
        scaler is stateless so per-loss *states* suffice, used with
        ``loss_id`` on the loss ops)."""
        if self.scaler is None:
            return None if num_losses == 1 else [None] * num_losses
        if num_losses == 1:
            return self.scaler.init()
        return [self.scaler.init() for _ in range(num_losses)]

    # -------------------------------------------------------------- loss ops
    def scale_loss(self, scaler_state, loss):
        """Functional ``with amp.scale_loss(loss, opt)`` (handle.py:16)."""
        if self.scaler is None:
            return loss
        return self.scaler.scale(scaler_state, loss)

    def unscale_grads(self, scaler_state, grads):
        if self.scaler is None:
            g32 = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
            return g32, all_finite(g32)
        return self.scaler.unscale(scaler_state, grads)

    def update_scaler(self, scaler_state, grads_finite):
        if self.scaler is None:
            return scaler_state
        return self.scaler.update(scaler_state, grads_finite)

    # ----------------------------------------------------- state dict parity
    def state_dict(self, scaler_state):
        """Reference: apex/amp/frontend.py:365-376 (one ``loss_scalerN``
        entry per loss)."""
        if self.scaler is None:
            return {}
        if isinstance(scaler_state, list):
            return {
                f"loss_scaler{i}": self.scaler.state_dict(s)
                for i, s in enumerate(scaler_state)
            }
        return {"loss_scaler0": self.scaler.state_dict(scaler_state)}

    def load_state_dict(self, d):
        if self.scaler is None or not d:
            return None
        if len(d) > 1:
            return [
                self.scaler.load_state_dict(d[f"loss_scaler{i}"])
                for i in range(len(d))
            ]
        return self.scaler.load_state_dict(d["loss_scaler0"])


def initialize(
    params,
    opt_level: str = "O1",
    half_dtype=None,
    loss_scale=None,
    init_scale: float = 2.0 ** 16,
    growth_interval: int = 2000,
    hysteresis: int = 1,
):
    """Build an :class:`Amp` and cast params per the opt level.

    Returns ``(cast_params, amp)``.  Mirrors
    ``amp.initialize(models, optimizers, opt_level=...)``
    (apex/amp/frontend.py:197) with models/optimizers replaced by the
    param pytree (state is the caller's to thread).
    """
    policy = get_policy(opt_level, half_dtype=half_dtype, loss_scale=loss_scale)
    if policy.loss_scale == "dynamic":
        scaler = DynamicLossScaler(
            init_scale=init_scale, growth_interval=growth_interval, hysteresis=hysteresis
        )
    elif policy.loss_scale is None:
        scaler = None
    else:
        scaler = StaticLossScaler(float(policy.loss_scale))
    amp = Amp(policy=policy, scaler=scaler)
    global _last_amp
    _last_amp = amp
    return policy.cast_params(params), amp


# ------------------------------------------------------------------ module API
# The reference keeps a process-global ``_amp_state`` so that
# ``amp.state_dict()`` / ``amp.load_state_dict()`` work without a handle
# (apex/amp/frontend.py:365-404).  We track the last-initialized Amp for
# the same call shape; the scaler *state* stays functional and is passed in.
_last_amp: Optional[Amp] = None


def state_dict(scaler_state, destination=None):
    """Checkpointable amp state (reference frontend.py:365)."""
    if _last_amp is None:
        raise RuntimeError("amp.initialize() has not been called")
    d = _last_amp.state_dict(scaler_state)
    if destination is not None:
        destination.update(d)
        return destination
    return d


def load_state_dict(d):
    """Restore scaler state from :func:`state_dict` (frontend.py:377).

    Returns the restored scaler state (functional — thread it back into
    your train step)."""
    if _last_amp is None:
        raise RuntimeError("amp.initialize() has not been called")
    return _last_amp.load_state_dict(d)


def master_params(opt_state):
    """Iterate fp32 master params out of an optimizer state
    (reference: apex/amp/_amp_state.py ``master_params(optimizer)``).

    Works with any apex_tpu fused-optimizer state carrying a ``master``
    field; falls back to nothing when master weights are disabled."""
    master = getattr(opt_state, "master", None)
    if master is None:
        return
    for leaf in jax.tree.leaves(master):
        yield leaf


def value_and_grad(amp: Amp, loss_fn: Callable, **grad_kwargs):
    """Mixed-precision ``jax.value_and_grad``.

    ``loss_fn(params, *args)`` is differentiated with the loss scaled by
    the current scale; grads come back unscaled in fp32 together with the
    updated scaler state and a finite flag.  The whole train-step pattern
    of reference §3.2 (SURVEY) in one transform::

        loss, grads, scaler_state, finite = amp_vg(params, scaler_state, batch)
        new_params, opt_state = opt.update(grads, opt_state, params, grads_finite=finite)
        scaler_state = amp.update_scaler(scaler_state, finite)
    """

    def scaled_loss_fn(params, scaler_state, *args, **kwargs):
        loss = loss_fn(params, *args, **kwargs)
        return amp.scale_loss(scaler_state, loss)

    vg = jax.value_and_grad(scaled_loss_fn, **grad_kwargs)

    def wrapped(params, scaler_state, *args, **kwargs):
        scaled_loss, grads = vg(params, scaler_state, *args, **kwargs)
        grads, finite = amp.unscale_grads(scaler_state, grads)
        if amp.scaler is not None:
            loss = scaled_loss / scaler_state.loss_scale.astype(scaled_loss.dtype)
            new_state = amp.update_scaler(scaler_state, finite)
        else:
            loss, new_state = scaled_loss, scaler_state
        return loss, grads, new_state, finite

    return wrapped
