"""``scale_loss`` — the context-manager entry point, functionally.

Reference: ``apex/amp/handle.py:16`` — ``with amp.scale_loss(loss,
optimizer) as scaled_loss: scaled_loss.backward()``.

There is no ambient autograd tape to scale into in JAX; the idiomatic
form is :func:`apex_tpu.amp.value_and_grad` (frontend.py), which scales
the loss before differentiation and unscales the grads after.  This
module keeps the name for discovery: ``scale_loss`` returns the scaled
loss for code that threads gradients manually.
"""

from apex_tpu.amp.frontend import Amp


def scale_loss(loss, amp: Amp, scaler_state):
    """Scaled loss (reference handle.py:113 ``loss.float()*loss_scale``).

    Pair with ``amp.unscale_grads(scaler_state, grads)`` after
    ``jax.grad`` — or use :func:`apex_tpu.amp.value_and_grad`, which does
    both around one differentiation.
    """
    return amp.scale_loss(scaler_state, loss)
