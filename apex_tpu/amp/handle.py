"""``scale_loss`` — the context-manager entry point, functionally.

Reference: ``apex/amp/handle.py:16`` — ``with amp.scale_loss(loss,
optimizer) as scaled_loss: scaled_loss.backward()``.

There is no ambient autograd tape to scale into in JAX; the idiomatic
form is :func:`apex_tpu.amp.value_and_grad` (frontend.py), which scales
the loss before differentiation and unscales the grads after.  This
module keeps the name for discovery: ``scale_loss`` returns the scaled
loss for code that threads gradients manually.
"""

import contextlib

from apex_tpu import _autocast_utils
from apex_tpu.amp.frontend import Amp


@contextlib.contextmanager
def disable_casts():
    """Suspend decorator/registry casting inside the block.

    Reference: ``apex/amp/handle.py`` ``disable_casts`` — regions that
    must run in true fp32 (e.g. loss computation) under O1.

    **Trace-time only.** The flag is read when a function is traced, and
    jit caches traces: a jitted function called once *outside* this
    context keeps casting on later calls made inside it (and vice
    versa).  Use it around eager calls or first traces; for a region
    inside an already-jitted step, make the dtype an explicit argument
    (e.g. ``float_function``) instead.
    """
    prev = _autocast_utils._casts_disabled
    _autocast_utils._casts_disabled = True
    try:
        yield
    finally:
        _autocast_utils._casts_disabled = prev


def scale_loss(loss, amp: Amp, scaler_state):
    """Scaled loss (reference handle.py:113 ``loss.float()*loss_scale``).

    Pair with ``amp.unscale_grads(scaler_state, grads)`` after
    ``jax.grad`` — or use :func:`apex_tpu.amp.value_and_grad`, which does
    both around one differentiation.
    """
    return amp.scale_loss(scaler_state, loss)
