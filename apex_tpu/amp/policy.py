"""Mixed-precision dtype policies (the O0–O3 opt levels).

Reference: ``apex/amp/frontend.py:9-193`` — apex expresses mixed precision
as a ``Properties`` object selected by opt level and then *imperatively
patches* torch (function-table monkey-patching for O1, model ``.half()``
for O2/O3).  Patching a function table is non-idiomatic in JAX: everything
is traced, so the policy is instead applied *functionally* — cast params to
the compute dtype at the top of the step, keep an fp32 master copy in the
optimizer, cast outputs back.  The opt-level names, semantics, and defaults
are preserved:

======  ==========================  =======================================
level   reference semantics          apex_tpu semantics
======  ==========================  =======================================
O0      fp32 everything              compute=param=fp32, no loss scale
O1      patch functions to fp16      compute=half (bf16 on TPU), params
        w/ fp32 weights              stay fp32, cast at op boundaries,
                                     dynamic loss scale (fp16 only)
O2      model .half(), fp32 master   params cast to half, fp32 master
        weights, fp32 batchnorm      weights in optimizer, norm layers
                                     fp32, dynamic loss scale (fp16 only)
O3      pure fp16                    compute=param=half, no master weights
======  ==========================  =======================================

On TPU the natural half dtype is **bfloat16**, which needs no loss
scaling; ``half_dtype=jnp.float16`` recovers exact apex semantics
(dynamic scaling on).
"""

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp


def _is_norm_param(path: str) -> bool:
    """Heuristic used by ``keep_batchnorm_fp32`` to identify norm params.

    Mirrors apex's rule of keeping ``_BatchNorm`` modules in fp32
    (``apex/fp16_utils/fp16util.py:60-89``): any param whose pytree path
    mentions a normalization layer stays in fp32.
    """
    p = path.lower()
    return any(k in p for k in ("batchnorm", "bn", "layernorm", "layer_norm", "groupnorm", "norm", "scale_bias"))


@dataclasses.dataclass(frozen=True)
class Policy:
    """A functional mixed-precision policy.

    Attributes mirror ``apex.amp.Properties`` (``apex/amp/frontend.py:9-99``):
    ``cast_model_type`` -> ``param_dtype``, ``patch_torch_functions`` ->
    ``cast_compute``, ``keep_batchnorm_fp32`` -> ``keep_norm_fp32``,
    ``master_weights``, ``loss_scale``.
    """

    opt_level: str
    param_dtype: Optional[Any]  # dtype params are stored/cast to (None = leave)
    compute_dtype: Optional[Any]  # dtype for op inputs (None = leave)
    keep_norm_fp32: bool
    master_weights: bool
    loss_scale: Any  # "dynamic" | float | None
    is_norm_param: Callable[[str], bool] = _is_norm_param

    # ------------------------------------------------------------------ casts
    def _cast_tree(self, tree, dtype, respect_norm: bool):
        if dtype is None:
            return tree

        def cast(path, x):
            if not hasattr(x, "dtype") or not jnp.issubdtype(x.dtype, jnp.floating):
                return x
            if respect_norm and self.keep_norm_fp32 and self.is_norm_param(path):
                return x.astype(jnp.float32)
            return x.astype(dtype)

        flat = jax.tree_util.tree_flatten_with_path(tree)
        leaves = [cast(jax.tree_util.keystr(kp), x) for kp, x in flat[0]]
        return jax.tree_util.tree_unflatten(flat[1], leaves)

    def cast_params(self, params):
        """Cast a param pytree to the storage dtype (O2/O3 ``model.half()``)."""
        return self._cast_tree(params, self.param_dtype, respect_norm=True)

    def cast_to_compute(self, tree):
        """Cast activations/inputs to the compute dtype (O1 patching)."""
        return self._cast_tree(tree, self.compute_dtype, respect_norm=False)

    def cast_to_fp32(self, tree):
        return self._cast_tree(tree, jnp.float32, respect_norm=False)

    @property
    def uses_loss_scaling(self) -> bool:
        return self.loss_scale is not None


def _half(half_dtype):
    return jnp.bfloat16 if half_dtype is None else half_dtype


def get_policy(opt_level: str = "O1", half_dtype=None, loss_scale=None) -> Policy:
    """Build the policy for an opt level (reference: apex/amp/frontend.py:104-193).

    ``half_dtype`` defaults to bfloat16 (TPU-native).  With bfloat16 the
    default loss scale is ``None`` (not needed); with float16 it is
    ``"dynamic"``, matching apex.  An explicit ``loss_scale`` always wins.
    """
    h = _half(half_dtype)
    fp16 = h == jnp.float16
    default_dynamic = "dynamic" if fp16 else None
    if opt_level == "O0":
        pol = Policy("O0", jnp.float32, jnp.float32, False, False, None)
    elif opt_level == "O1":
        pol = Policy("O1", None, h, True, False, loss_scale if loss_scale is not None else default_dynamic)
    elif opt_level == "O2":
        pol = Policy("O2", h, None, True, True, loss_scale if loss_scale is not None else default_dynamic)
    elif opt_level == "O3":
        pol = Policy("O3", h, h, False, False, loss_scale)
    else:
        raise ValueError(f"Unexpected optimization level {opt_level!r} (expected O0/O1/O2/O3)")
    return pol
