"""apex_tpu — a TPU-native training-accelerator library.

A ground-up JAX/XLA/Pallas re-design of the capability surface of NVIDIA
Apex (reference: ``timmoon10/apex``; see ``/root/reference/apex/__init__.py``):

- :mod:`apex_tpu.amp` — mixed precision (O0–O3 dtype policies, device-side
  dynamic loss scaling with hysteresis).  Reference: ``apex/amp``.
- :mod:`apex_tpu.optimizers` — fused optimizers (Adam, LAMB, SGD, NovoGrad,
  Adagrad) with exact reference numerics.  Reference: ``apex/optimizers``.
- :mod:`apex_tpu.normalization` — fused LayerNorm/RMSNorm (Pallas kernels).
  Reference: ``apex/normalization``.
- :mod:`apex_tpu.parallel` — data parallelism (psum-DDP semantics, SyncBN,
  LARC).  Reference: ``apex/parallel``.
- :mod:`apex_tpu.transformer` — Megatron-style tensor/sequence/pipeline
  parallelism over ``jax.sharding.Mesh`` axes.  Reference:
  ``apex/transformer``.
- :mod:`apex_tpu.contrib` — optional extensions (xentropy, clip_grad,
  flash attention, group norm, ...).  Reference: ``apex/contrib``.

Unlike the reference, which accelerates PyTorch via CUDA extensions, this
library is functional-first: state lives in pytrees, transforms compose with
``jax.jit``/``jax.grad``/``jax.shard_map``, and multi-device execution uses
XLA collectives over a device mesh (ICI/DCN) instead of NCCL process groups.
"""

import logging as _logging

import apex_tpu._compat  # noqa: F401 — installs jax version aliases

__version__ = "0.1.0"

from apex_tpu.utils.logging import RankInfoFormatter, get_logger

# Subpackages are imported lazily to keep `import apex_tpu` cheap and to
# avoid importing optional deps at package-import time (mirrors the lazy
# import structure of apex/__init__.py:20-30).
_LAZY_SUBMODULES = (
    "amp",
    "analysis",
    "optimizers",
    "normalization",
    "multi_tensor_apply",
    "fused_dense",
    "mlp",
    "parallel",
    "resilience",
    "transformer",
    "contrib",
    "models",
    "ops",
    "utils",
    "fp16_utils",
)


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        import importlib

        mod = importlib.import_module(f"apex_tpu.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'apex_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_LAZY_SUBMODULES))


def deprecated_warning(msg: str) -> None:
    """Emit a deprecation warning once (reference: apex/__init__.py:61)."""
    import warnings

    warnings.warn(msg, DeprecationWarning, stacklevel=2)
