"""Crash-forensics flight recorder: the last N things the process did.

A wedged collective, a bad-step budget abort, a preemption, a hard
kill — by the time the postmortem starts, the process is gone and the
logs only hold what someone thought to print.  The flight recorder is
the black box: a fixed-size ring of the most recent

- **trace spans** (fed live from the :mod:`~apex_tpu.observability
  .tracing` tracer via a listener — including the still-OPEN span of a
  wedged dispatch),
- **structured events** (fed from ``utils.logging.log_structured``
  whenever a recorder is installed),
- **StepStats windows** (the trainer records each harvested summary),

dumped ATOMICALLY (``io.native.atomic_output`` — a crash mid-dump can
never publish a torn file) when something dies:

| trigger | who calls it |
|---|---|
| watchdog wedge | the driver's ``on_wedge`` hook → :meth:`FlightRecorder.dump` (``"wedge"``) |
| StepGuard budget abort | ``StepGuard.check`` → :func:`dump_active` (``"step_guard_abort"``) |
| preemption notice | ``PreemptionHandler`` → :func:`dump_active` (``"preemption"``) |
| hard kill (137) | nothing runs — the periodically republished :meth:`checkpoint` file IS the dump |
| supervisor-observed child death | the supervisor attaches :func:`latest_dump_path` to its restart/quarantine records |

Reading side: :func:`load_dump` validates the schema and fails loudly
on torn bytes; :func:`latest_dump` scans a directory newest-first and
SKIPS torn/partial files with a structured
``flightrec.torn_dump_skipped`` warning — a half-written dump from the
crash being investigated must not crash the investigation.

Every record carries the correlation ``(run_id, step)``
(:mod:`~apex_tpu.observability.correlation`), so a dump's last span, a
metrics point, and a log line all join on the wedged step.
"""

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from apex_tpu.observability.correlation import step_context

__all__ = [
    "FlightRecorder", "active", "default_dir", "dump_active", "install",
    "latest_dump", "latest_dump_path", "load_dump", "observe_event",
    "uninstall",
]


def default_dir(metrics_dir=None, trace_dir=None) -> Optional[str]:
    """The ONE dir convention writers (drivers) and readers (the
    supervisor's attach-to-restart-record) share: the trace dir when
    tracing is on, else ``<metrics_dir>/flightrec``, else None (memory-
    only recording)."""
    if trace_dir:
        return str(trace_dir)
    if metrics_dir:
        return os.path.join(str(metrics_dir), "flightrec")
    return None

SCHEMA = "apex_tpu_flightrec_v1"

_ACTIVE: Optional["FlightRecorder"] = None


class FlightRecorder:
    """Bounded ring of recent spans/events/stats + atomic dump.

    ``dir_path`` (optional) enables file output: :meth:`checkpoint`
    atomically republishes ``flightrec_<pid>.json`` (call it at the
    telemetry cadence — a hard-killed process leaves its last
    checkpoint as the de-facto dump), and :meth:`dump` writes a final
    reason-stamped ``flightrec_dump_<ms>_<pid>.json``.  Thread-safe:
    the tracer listener and ``log_structured`` feed from any thread.
    """

    def __init__(self, dir_path=None, capacity: int = 512,
                 events_capacity: int = 512, stats_capacity: int = 64,
                 run_id: Optional[str] = None, time_fn=time.time):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.dir = str(dir_path) if dir_path is not None else None
        if self.dir is not None:
            os.makedirs(self.dir, exist_ok=True)
        self.run_id = run_id
        self._time = time_fn
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=int(capacity))
        self._events: deque = deque(maxlen=int(events_capacity))
        self._stats: deque = deque(maxlen=int(stats_capacity))
        self._tracer = None
        self.dumped: List[str] = []
        self.path = (os.path.join(self.dir,
                                  f"flightrec_{os.getpid()}.json")
                     if self.dir is not None else None)

    # ----------------------------------------------------------- feeds
    def attach(self, tracer) -> "FlightRecorder":
        """Subscribe to a :class:`~apex_tpu.observability.tracing
        .Tracer`: every finished span lands in the ring, and dumps
        include the tracer's OPEN spans (the wedged dispatch)."""
        self._tracer = tracer
        tracer.add_listener(self.record_span)
        return self

    def record_span(self, span: Dict[str, Any]) -> None:
        with self._lock:
            self._spans.append(dict(span))

    def record_event(self, event: str, fields: Dict[str, Any]) -> None:
        """One structured event (``log_structured`` feeds this for
        every record while a recorder is installed).  Must never log
        itself — that would recurse through the feed."""
        with self._lock:
            self._events.append({
                "ts": round(float(self._time()), 6), "event": str(event),
                **{k: v for k, v in fields.items()},
            })

    def record_stats(self, step: int, summary: Dict[str, Any]) -> None:
        """One harvested StepStats window summary."""
        with self._lock:
            self._stats.append({
                "ts": round(float(self._time()), 6), "step": int(step),
                **{k: v for k, v in summary.items()},
            })

    # ------------------------------------------------------------ dump
    def snapshot(self, reason: Optional[str] = None, **extra
                 ) -> Dict[str, Any]:
        """The dump payload: rings + the tracer's open spans +
        correlation, JSON-serializable."""
        with self._lock:
            spans = [dict(s) for s in self._spans]
            events = [dict(e) for e in self._events]
            stats = [dict(s) for s in self._stats]
        open_spans: List[dict] = []
        if self._tracer is not None:
            try:
                open_spans = self._tracer.open_spans()
            except Exception:  # noqa: BLE001 — a broken tracer must not
                pass           # rob the dump of the rings it DOES hold
        rec: Dict[str, Any] = {
            "schema": SCHEMA, "pid": os.getpid(),
            "ts": round(float(self._time()), 6),
            "reason": reason, **step_context(),
            "spans": spans, "open_spans": open_spans,
            "events": events, "stats_windows": stats,
        }
        if self.run_id is not None:
            rec["run_id"] = str(self.run_id)
        rec.update(extra)
        return rec

    def _write(self, path: str, rec: Dict[str, Any]) -> None:
        from apex_tpu.io.native import atomic_output

        with atomic_output(path) as f:
            f.write(json.dumps(rec, sort_keys=True, default=str).encode())

    def checkpoint(self) -> Optional[str]:
        """Atomically republish the rolling recording (no reason
        stamp).  Call at the telemetry cadence: a hard kill (exit 137
        runs no handlers) then still leaves the last checkpoint as the
        forensics artifact."""
        if self.path is None:
            return None
        self._write(self.path, self.snapshot(reason=None))
        return self.path

    def dump(self, reason: str, dir_path=None, **extra) -> Optional[str]:
        """Write the final reason-stamped dump
        (``flightrec_dump_<ms>_<pid>.json``) and log its path.  Returns
        the path (None without a directory).  Never raises — the dump
        rides exit paths (watchdog ``on_wedge``, budget abort) whose
        one job is to exit."""
        d = str(dir_path) if dir_path is not None else self.dir
        if d is None:
            return None
        path = os.path.join(
            d, f"flightrec_dump_{int(self._time() * 1000)}"
               f"_{os.getpid()}.json")
        try:
            self._write(path, self.snapshot(reason=reason, **extra))
        except Exception as e:  # noqa: BLE001 — report, never block exit
            _log_warning("flightrec.dump_failed", reason=reason,
                         error=f"{type(e).__name__}: {e}")
            return None
        self.dumped.append(path)
        _log_warning("flightrec.dumped", reason=reason, path=path)
        return path


# ------------------------------------------------------- global recorder
def install(rec: FlightRecorder) -> FlightRecorder:
    """Make ``rec`` the process recorder: ``log_structured`` events and
    the library dump triggers (:func:`dump_active`) route to it."""
    global _ACTIVE
    _ACTIVE = rec
    return rec


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


def active() -> Optional[FlightRecorder]:
    return _ACTIVE


def observe_event(event: str, fields: Dict[str, Any]) -> None:
    """``log_structured``'s feed seam: record into the installed
    recorder, swallow everything — a telemetry failure must never
    change a logging call's control flow."""
    rec = _ACTIVE
    if rec is None:
        return
    try:
        rec.record_event(event, fields)
    except Exception:  # noqa: BLE001 — observers never participate
        pass


def dump_active(reason: str, **extra) -> Optional[str]:
    """Dump the installed recorder (no-op without one) — the library
    trigger seam (``StepGuard.check`` before its budget raise,
    ``PreemptionHandler`` on the notice).  Best-effort by design."""
    rec = _ACTIVE
    if rec is None:
        return None
    try:
        return rec.dump(reason, **extra)
    except Exception:  # noqa: BLE001 — a broken recorder must not turn
        return None    # an orderly abort into a telemetry crash


# ------------------------------------------------------------ read side
def load_dump(path) -> Dict[str, Any]:
    """Parse + validate one dump file; raises ``ValueError`` on torn
    bytes or a wrong schema (callers that scan directories use
    :func:`latest_dump`, which skips torn files loudly)."""
    with open(path, "rb") as f:
        data = f.read()
    try:
        rec = json.loads(data)
    except ValueError as e:
        raise ValueError(
            f"{path} is not a valid flight-recorder dump (torn/partial "
            f"JSON: {e})") from e
    if not isinstance(rec, dict) or rec.get("schema") != SCHEMA:
        raise ValueError(
            f"{path} is not a flight-recorder dump (schema "
            f"{rec.get('schema') if isinstance(rec, dict) else None!r}, "
            f"want {SCHEMA!r})")
    return rec


def latest_dump(dir_path) -> Optional[Tuple[str, Dict[str, Any]]]:
    """Newest readable dump in ``dir_path`` as ``(path, record)``;
    None when the dir holds none.  Reason-stamped ``flightrec_dump_*``
    files outrank the rolling ``flightrec_<pid>.json`` checkpoints of
    the same vintage only by recency — newest mtime wins across both.
    Torn/partial files are SKIPPED with a loud structured warning,
    never raised: the half-written dump belongs to the crash being
    investigated."""
    import glob

    candidates = glob.glob(os.path.join(str(dir_path), "flightrec_*.json"))
    candidates.sort(key=lambda p: (_mtime(p), p), reverse=True)
    for p in candidates:
        try:
            return p, load_dump(p)
        except (OSError, ValueError) as e:
            _log_warning("flightrec.torn_dump_skipped", path=p,
                         error=f"{type(e).__name__}: {e}")
    return None


def latest_dump_path(dir_path) -> Optional[str]:
    """Just the newest readable dump's path (the supervisor's
    attach-to-restart-record call)."""
    if dir_path is None:
        return None
    try:
        hit = latest_dump(dir_path)
    except OSError:
        return None
    return hit[0] if hit is not None else None


def _mtime(p: str) -> float:
    try:
        return os.path.getmtime(p)
    except OSError:
        return 0.0


def _log_warning(event: str, **fields) -> None:
    from apex_tpu.utils.logging import get_logger, log_structured

    log_structured(get_logger("apex_tpu.observability"), logging.WARNING,
                   event, **fields)
