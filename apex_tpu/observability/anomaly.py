"""Anomaly & straggler detection: notice degradation before a human does.

The metrics layer records *what happened*; this module decides *whether
that was normal*.  Rolling median/MAD detectors (robust to the heavy
right tail every latency series has — a mean/stddev detector is blown
by the first outlier it exists to catch) watch the series the rest of
the observability stack already produces:

- **step time** (the trainer loop's iteration cadence — the wedge's
  slow-motion precursor),
- **per-hop sync time** (span durations off the tracer: a slow
  cross-slice hop is a network problem, a slow inner hop a chip),
- **goodput / window throughput** (direction ``low``: a regression is
  a DROP),
- **per-lane TTFT and inter-token latency** (the serving SLO burn,
  split by lane so the best-effort tail can't hide an interactive
  regression),
- **dp-rank stragglers** (cross-sectional: one rank's per-step value
  against the same step's other ranks).

Every detection increments an ``apex_anomaly_<kind>_total`` counter
(labels preserved — the serving counters split by lane) and emits one
structured ``anomaly.detected`` record carrying the value, the rolling
median/MAD, and the robust z-score — which also lands in the flight
recorder's event ring whenever one is installed, so a postmortem dump
SHOWS the degradation ramp that preceded the death.

The detector is deliberately boring: a bounded ``window`` of recent
values, median/MAD over it, alarm when the robust z-score
``|v - median| / (1.4826 * MAD)`` exceeds ``threshold`` in the watched
direction.  A relative floor on the scale (``min_rel_spread``) keeps a
near-constant series (CPU-test step times agree to microseconds) from
alarming on noise, and ``min_points`` keeps the cold start quiet.

Consumption: the supervisor's goodput-adaptive backoff reads the
summary files :meth:`AnomalyMonitor.persist` leaves under the metrics
dir (:func:`recent_alert_count`) — a child that was ramping into
step-time regressions before it died earns a LONGER cool-down than a
clean crash, the same logic as the wedge-repeat lengthening.
"""

import glob
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from apex_tpu.observability import metrics as _metrics
from apex_tpu.observability.correlation import step_context

__all__ = [
    "AnomalyMonitor", "RollingMadDetector", "recent_alert_count",
    "robust_zscore",
]

#: scale factor that makes the MAD a consistent estimator of the
#: standard deviation under normality
MAD_TO_SIGMA = 1.4826


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def robust_zscore(value: float, values: List[float],
                  min_rel_spread: float = 0.05,
                  min_abs_spread: float = 1e-12
                  ) -> Tuple[float, float, float]:
    """``(z, median, mad)`` of ``value`` against ``values`` — the one
    median/MAD expression every detector here uses.  The scale is
    floored at ``min_rel_spread * |median|`` (and an absolute epsilon)
    so a series that agrees to the last microsecond cannot alarm on
    measurement noise."""
    med = _median(values)
    mad = _median([abs(v - med) for v in values])
    scale = max(MAD_TO_SIGMA * mad, min_rel_spread * abs(med),
                min_abs_spread)
    return (value - med) / scale, med, mad


class RollingMadDetector:
    """One series' rolling median/MAD detector.

    ``direction``: ``"high"`` alarms on spikes (latency, step time),
    ``"low"`` on drops (goodput, throughput), ``"both"`` on either.
    The candidate value is scored against the window EXCLUDING itself
    (an outlier must not mask itself), then appended — so a genuine
    level shift alarms for ~window/2 updates and then becomes the new
    normal, which is the wanted behavior for a *detector* (the alert
    count records that the shift happened)."""

    def __init__(self, window: int = 64, threshold: float = 4.0,
                 min_points: int = 16, direction: str = "high",
                 min_rel_spread: float = 0.05):
        if window < 2:
            raise ValueError(f"window must be >= 2, got {window}")
        if direction not in ("high", "low", "both"):
            raise ValueError(
                f"direction must be high/low/both, got {direction!r}")
        if min_points < 2:
            raise ValueError(f"min_points must be >= 2, got {min_points}")
        self.window = int(window)
        self.threshold = float(threshold)
        self.min_points = int(min_points)
        self.direction = direction
        self.min_rel_spread = float(min_rel_spread)
        self._values: deque = deque(maxlen=self.window)
        self.alerts = 0

    def update(self, value: float) -> Optional[Dict[str, float]]:
        """Score ``value``; returns the alert record (``value`` /
        ``median`` / ``mad`` / ``zscore``) when anomalous, else None.
        The value joins the window either way."""
        value = float(value)
        out = None
        if len(self._values) >= self.min_points:
            z, med, mad = robust_zscore(value, list(self._values),
                                        self.min_rel_spread)
            hit = ((self.direction in ("high", "both") and z > self.threshold)
                   or (self.direction in ("low", "both")
                       and -z > self.threshold))
            if hit:
                self.alerts += 1
                out = {"value": value, "median": med, "mad": mad,
                       "zscore": round(z, 3)}
        self._values.append(value)
        return out


#: detector kinds with their watched direction (anything else defaults
#: to "high" — latency-like)
_DIRECTIONS = {
    "step_time": "high",
    "hop_sync_time": "high",
    "ttft": "high",
    "inter_token": "high",
    "goodput": "low",
    "tokens_per_sec": "low",
}


class AnomalyMonitor:
    """Named rolling detectors + the counter/log/flight-recorder fanout.

    One monitor per process (the drivers build one when observability
    is on); series are keyed ``(kind, sorted labels)`` so per-lane and
    per-hop streams are scored independently.  Thread-safe: the serving
    scheduler observes from the serve loop while the watchdog thread
    may force a wedge alert."""

    def __init__(self, threshold: float = 4.0, window: int = 64,
                 min_points: int = 16, max_alerts_kept: int = 256):
        self.threshold = float(threshold)
        self.window = int(window)
        self.min_points = int(min_points)
        self._lock = threading.Lock()
        self._detectors: Dict[Tuple, RollingMadDetector] = {}
        self.alerts: deque = deque(maxlen=int(max_alerts_kept))
        #: TRUE alert totals (the deque above keeps only the most
        #: recent records — counts must not saturate at its length)
        self._counts: Dict[str, int] = {}
        self._label_counts: Dict[Tuple[str, str, str], int] = {}
        #: first-seen label-name tuple per kind — the registry pins a
        #: counter's labelnames at first use, so a later alert with a
        #: different label shape must be conformed or its increment is
        #: silently swallowed by the best-effort module helper
        self._label_schema: Dict[str, Tuple[str, ...]] = {}
        self._schema_warned: set = set()

    # ------------------------------------------------------------ core
    def _detector(self, kind: str, key: Tuple) -> RollingMadDetector:
        with self._lock:
            det = self._detectors.get(key)
            if det is None:
                det = RollingMadDetector(
                    window=self.window, threshold=self.threshold,
                    min_points=self.min_points,
                    direction=_DIRECTIONS.get(kind, "high"))
                self._detectors[key] = det
            return det

    def observe(self, kind: str, value: float,
                **labels) -> Optional[Dict[str, Any]]:
        """Score one sample of series ``(kind, labels)``; on detection
        increment ``apex_anomaly_<kind>_total{labels}``, log one
        structured ``anomaly.detected`` (which feeds any installed
        flight recorder), and return the alert record."""
        key = (kind, tuple(sorted(labels.items())))
        hit = self._detector(kind, key).update(value)
        if hit is None:
            return None
        return self._alert(kind, dict(labels), hit)

    def wedge(self, elapsed_s: float, step=None) -> Dict[str, Any]:
        """A watchdog-adjudicated wedge IS a step-time anomaly — no
        window vote needed (the wedged dispatch never returns, so the
        ordinary ``observe`` would never see it).  Rides the watchdog's
        pre-exit hook; the counter increment and the structured alert
        are what the postmortem greps for."""
        return self._alert("step_time", {}, {
            "value": float(elapsed_s), "median": None, "mad": None,
            "zscore": None, "wedge": True, "step": step,
        })

    def check_stragglers(self, per_rank: Dict[Any, float],
                         kind: str = "rank_step_time",
                         threshold: Optional[float] = None
                         ) -> List[Dict[str, Any]]:
        """Cross-sectional straggler vote: each rank's value against the
        SAME step's other ranks (per-rank StepStats windows, per-rank
        wall times).  Needs >= 3 ranks (with 2 there is no majority to
        deviate from).  Returns the alert records, one per straggler."""
        if len(per_rank) < 3:
            return []
        thr = self.threshold if threshold is None else float(threshold)
        out = []
        for rank, v in sorted(per_rank.items()):
            others = [float(x) for r, x in per_rank.items() if r != rank]
            z, med, mad = robust_zscore(float(v), others)
            if z > thr:
                out.append(self._alert(
                    "straggler", {"rank": str(rank), "series": kind},
                    {"value": float(v), "median": med, "mad": mad,
                     "zscore": round(z, 3)}))
        return out

    # ------------------------------------------------------------ fanout
    def _alert(self, kind: str, labels: Dict[str, Any],
               hit: Dict[str, Any]) -> Dict[str, Any]:
        rec = {"ts": round(time.time(), 3), "kind": kind,
               **step_context(), **labels, **hit}
        with self._lock:
            self.alerts.append(rec)
            self._counts[kind] = self._counts.get(kind, 0) + 1
            for k, v in labels.items():
                key = (kind, k, str(v))
                self._label_counts[key] = self._label_counts.get(key, 0) + 1
            schema = self._label_schema.setdefault(
                kind, tuple(sorted(labels)))
            conform = tuple(sorted(labels)) != schema
            warn_schema = conform and kind not in self._schema_warned
            if warn_schema:
                self._schema_warned.add(kind)
        out_labels = {k: str(v) for k, v in labels.items()}
        if conform:
            # conform to the kind's first-seen shape so the increment
            # COUNTS (missing names filled empty, unknown dropped)
            # instead of being swallowed as a labelnames clash
            out_labels = {k: str(labels.get(k, "")) for k in schema}
            if warn_schema:
                _log(logging.WARNING, "anomaly.label_schema_conformed",
                     kind=kind, expected=list(schema),
                     got=sorted(labels))
        # best-effort by design (the module helpers never raise): a
        # registry clash must not rob the loop of its alert record
        _metrics.inc(f"apex_anomaly_{kind}_total",
                     help=f"anomaly detections on the {kind} series",
                     **out_labels)
        _log(logging.WARNING, "anomaly.detected", **{
            k: v for k, v in rec.items() if k != "ts"})
        return rec

    # ------------------------------------------------------- tracer feed
    def span_listener(self, name_to_kind: Dict[str, str]):
        """A :meth:`~apex_tpu.observability.tracing.Tracer.add_listener`
        hook routing finished-span durations into detectors: exact
        names map directly; a mapping key ending in ``*`` prefix-matches
        (``zero_sync.*`` -> ``hop_sync_time``, labeled by span name)."""
        prefixes = [(k[:-1], v) for k, v in name_to_kind.items()
                    if k.endswith("*")]
        exact = {k: v for k, v in name_to_kind.items()
                 if not k.endswith("*")}

        def feed(span: Dict[str, Any]) -> None:
            name = span.get("name", "")
            kind = exact.get(name)
            if kind is None:
                for pfx, k in prefixes:
                    if name.startswith(pfx):
                        kind = k
                        break
            if kind is None or span.get("ph") != "X":
                return
            # one STABLE label shape per feed (span always, lane empty
            # when the span carries none): optional labels would flip
            # the counter's labelnames between alerts and the registry
            # would swallow every increment after the first shape
            labels = {"span": name,
                      "lane": span.get("attrs", {}).get("lane") or ""}
            self.observe(kind, span.get("dur_us", 0) / 1e6, **labels)

        return feed

    # ------------------------------------------------------ introspection
    def counts(self) -> Dict[str, int]:
        """TRUE alert counts per kind (the bench/driver report column;
        the ``alerts`` deque holds only the most recent records, so
        counts come from dedicated counters that never saturate)."""
        with self._lock:
            return dict(self._counts)

    def counts_by(self, label: str) -> Dict[str, Dict[str, int]]:
        """kind -> {label value -> alerts} (the per-lane serve column;
        true totals, same as :meth:`counts`)."""
        out: Dict[str, Dict[str, int]] = {}
        with self._lock:
            items = list(self._label_counts.items())
        for (kind, name, value), n in items:
            if name == label:
                out.setdefault(kind, {})[value] = n
        return out

    # ------------------------------------------------------- persistence
    def persist(self, dir_path) -> Optional[str]:
        """Atomically publish ``anomaly_<pid>.json`` (counts + recent
        alerts) under ``dir_path`` — what the supervisor's backoff reads
        after a child death (:func:`recent_alert_count`).  Best-effort:
        rides exit paths."""
        if dir_path is None:
            return None
        try:
            from apex_tpu.io.native import atomic_output

            os.makedirs(str(dir_path), exist_ok=True)
            path = os.path.join(str(dir_path), f"anomaly_{os.getpid()}.json")
            with self._lock:
                alerts = list(self.alerts)
            doc = {"schema": "apex_tpu_anomaly_v1",
                   "ts": round(time.time(), 3), "pid": os.getpid(),
                   **step_context(),
                   "counts": self.counts(), "alerts": alerts}
            with atomic_output(path) as f:
                f.write(json.dumps(doc, sort_keys=True,
                                   default=str).encode())
            return path
        except Exception as e:  # noqa: BLE001 — report, never block exit
            _log(logging.WARNING, "anomaly.persist_failed",
                 error=f"{type(e).__name__}: {e}")
            return None


def recent_alert_count(dir_path, max_age_sec: Optional[float] = None,
                       now: Optional[float] = None) -> int:
    """Total alerts across the ``anomaly_*.json`` summaries under
    ``dir_path`` (0 for a missing dir; torn files skipped — they belong
    to the crash being investigated).  ``max_age_sec`` keeps the
    supervisor's backoff from re-lengthening on a week-old record."""
    if dir_path is None:
        return 0
    total = 0
    now = time.time() if now is None else now
    for p in glob.glob(os.path.join(str(dir_path), "anomaly_*.json")):
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict) \
                or doc.get("schema") != "apex_tpu_anomaly_v1":
            continue
        if max_age_sec is not None \
                and now - float(doc.get("ts", 0)) > max_age_sec:
            continue
        total += sum(int(v) for v in (doc.get("counts") or {}).values())
    return total


def _log(level: int, event: str, **fields) -> None:
    from apex_tpu.utils.logging import get_logger, log_structured

    log_structured(get_logger("apex_tpu.observability"), level, event,
                   **fields)
