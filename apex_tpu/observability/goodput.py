"""Goodput and MFU accounting across the elastic run lifecycle.

The resilience stack made runs *survive* restarts, wedges, and
preemptions; this module makes the cost of surviving **measurable** —
the supervisor exit-code table (docs/resilience.md) becomes a wall-time
breakdown:

- Each process records one *session* file under the metrics dir
  (``goodput_*.json``, atomically republished at every heartbeat so a
  hard kill still leaves the last known progress): start/end, the
  attributed segments (``checkpoint``, ``restore``, ``reshard``, …),
  step/token counters, and an exit cause.
- :func:`goodput_report` folds every session into one breakdown whose
  fractions **sum to exactly 1** over the run's wall clock
  (first session start → last session end): ``productive`` is the
  remainder after the attributed buckets, inter-session gaps are
  ``restart``, and a session that died wedged contributes its
  last-progress→death tail to ``wedge`` — so an injected wedged
  collective shows up as a measurable goodput loss, not a log line.

MFU helpers centralize the model-FLOPs formula bench.py has always
used (6N + 12·L·S·H per trained token, no recompute credit; 2N per
decoded token) so the trainer, the serving bench, and the report agree
on the denominator's numerator.
"""

import contextlib
import glob
import json
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = [
    "GoodputAccountant", "decode_flops_per_token", "goodput_report",
    "model_flops_per_step", "model_flops_per_token", "param_count",
    "session_progress",
]

SCHEMA = "apex_tpu_goodput_v1"


# ------------------------------------------------------------- MFU helpers
def param_count(params) -> int:
    import jax

    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


def model_flops_per_token(n_params: int, num_layers: int, seq: int,
                          hidden: int) -> float:
    """Train-step model FLOPs per token: ``6N`` (fwd+bwd matmuls) plus
    the attention term ``12·L·S·H`` — the usual MFU convention (no
    recompute credit), and exactly bench.py's historical formula."""
    return 6.0 * n_params + 12.0 * num_layers * seq * hidden


def model_flops_per_step(n_params: int, num_layers: int, seq: int,
                         hidden: int, batch: int) -> float:
    return model_flops_per_token(n_params, num_layers, seq, hidden) \
        * batch * seq


def decode_flops_per_token(n_params: int) -> float:
    """Serving decode FLOPs per generated token: the forward matmuls
    (``2N``); attention-over-cache is cache-length-dependent and small
    against the matmuls at the page sizes served here."""
    return 2.0 * n_params


# --------------------------------------------------------------- accountant
class GoodputAccountant:
    """One training process's slice of the goodput record.

    Usage (``examples/gpt/pretrain_gpt.py --metrics-dir``)::

        acct = GoodputAccountant(metrics_dir, run_id="gpt")
        with acct.attribute("restore"):
            ...restore checkpoint...
        for step in ...:
            ...train...
            acct.step_done(tokens=batch*seq)
            with acct.attribute("checkpoint"): ...save...
            acct.heartbeat()          # at the telemetry fetch cadence
        acct.finalize("clean")        # or "preempted"; the watchdog's
                                      # on_wedge hook calls finalize("wedge")

    The session file is republished atomically (tmp+rename) at every
    heartbeat/segment/finalize, so a chaos hard-kill (exit 137 — no
    cleanup runs) still leaves the last heartbeat's end time and the
    report attributes the lost tail to ``restart``."""

    def __init__(self, dir_path, run_id: str = "run",
                 time_fn=time.time):
        import threading

        self.dir = str(dir_path)
        os.makedirs(self.dir, exist_ok=True)
        self.run_id = str(run_id)
        self._time = time_fn
        # finalize("wedge") arrives from the WATCHDOG thread while the
        # main thread may be mid-heartbeat — an unserialized concurrent
        # json.dump into the same .tmp would publish torn bytes (or the
        # dump would race a first-time segment-key insert) and the
        # report would silently drop the wedged session.  RLock: the
        # mutators hold it across mutation + _persist
        self._lock = threading.RLock()
        start = float(time_fn())
        self._rec: Dict[str, Any] = {
            "schema": SCHEMA, "run_id": self.run_id,
            "pid": os.getpid(),
            "start": start, "end": start,
            # last_activity: the last moment the session demonstrably
            # did SOMETHING (a step finished, an attributed segment
            # ended) — the wedge tail is end - last_activity
            "last_activity": start,
            "segments": {}, "steps": 0, "tokens": 0,
            "exit_cause": None,
        }
        # "goodput_session_" prefix, NOT bare "goodput_": the aggregate
        # goodput_report.json the example writes into the same dir must
        # never match the session glob (it carries the same schema tag
        # and no "start" — found by the third-resume crash)
        self.path = os.path.join(
            self.dir,
            f"goodput_session_{int(start * 1000)}_{os.getpid()}.json")
        self._persist()

    # ------------------------------------------------------------ recording
    def _persist(self) -> None:
        with self._lock:
            self._rec["end"] = float(self._time())
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self._rec, f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)

    @contextlib.contextmanager
    def attribute(self, cause: str):
        """Attribute the body's wall time to ``cause`` (``checkpoint``,
        ``restore``, ``reshard``, ``drain`` …); everything never
        attributed is productive."""
        t0 = self._time()
        try:
            yield
        finally:
            self.add_segment(cause, float(self._time() - t0))

    def add_segment(self, cause: str, seconds: float) -> None:
        """Attribute an already-measured duration (the non-contextmanager
        spelling of :meth:`attribute`, for code paths that time
        themselves)."""
        if seconds > 0:
            with self._lock:
                seg = self._rec["segments"]
                seg[cause] = seg.get(cause, 0.0) + float(seconds)
                self._rec["last_activity"] = float(self._time())
                self._persist()

    def step_done(self, steps: int = 1, tokens: int = 0) -> None:
        """Record step/token progress (host counters only — no
        persistence; ride :meth:`heartbeat` for that)."""
        with self._lock:
            self._rec["steps"] += int(steps)
            self._rec["tokens"] += int(tokens)
            self._rec["last_activity"] = float(self._time())

    def heartbeat(self) -> None:
        self._persist()

    def finalize(self, exit_cause: str = "clean") -> None:
        """Stamp the exit cause and republish — the watchdog's
        ``on_wedge`` hook calls ``finalize("wedge")`` before
        ``os._exit``, which is what lets the report attribute the
        wedged tail per cause."""
        with self._lock:
            self._rec["exit_cause"] = str(exit_cause)
            self._persist()

    def report(self, **kw) -> Dict[str, Any]:
        """The aggregate report over every session in this dir
        (including this live one, already persisted)."""
        self._persist()
        return goodput_report(self.dir, **kw)


# ------------------------------------------------------------------ report
def _load_sessions(dir_path) -> List[Dict[str, Any]]:
    out = []
    pattern = os.path.join(str(dir_path), "goodput_session_*.json")
    for p in sorted(glob.glob(pattern)):
        try:
            with open(p) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue  # torn session file: skip, never crash the report
        if rec.get("schema") == SCHEMA and "start" in rec \
                and "end" in rec:
            out.append(rec)
    out.sort(key=lambda r: r["start"])
    return out


def session_progress(dir_path) -> int:
    """Total steps recorded across every session file in ``dir_path``
    (0 when the dir is missing/empty) — monotone over a run's life, so
    the supervisor's crash-loop breaker can compare it across restarts:
    a relaunch that adds no steps before dying made NO progress, and K
    of those in a row is a crash loop, not a recoverable fault."""
    return sum(int(r.get("steps", 0)) for r in _load_sessions(dir_path))


def goodput_report(dir_path, flops_per_token: Optional[float] = None,
                   roofline_tflops: Optional[float] = None
                   ) -> Dict[str, Any]:
    """Fold every session record into one goodput breakdown.

    Buckets over ``wall = last session end - first session start``:

    - every explicitly attributed segment cause (``checkpoint``,
      ``restore``, ``reshard``, ``drain``, …), summed across sessions;
    - ``wedge``: for sessions whose ``exit_cause`` is ``"wedge"``, the
      tail from their last recorded progress to their end (the steps
      the wedged collective ate);
    - ``restart``: the gaps between one session's end and the next's
      start (supervisor backoff + process relaunch + jax init; a
      hard-killed session's unpersisted tail lands here too — its
      recorded end IS its last heartbeat);
    - ``productive``: the remainder — so the fractions sum to exactly
      1 by construction.

    With ``flops_per_token`` (see :func:`model_flops_per_token`) the
    report adds achieved model TFLOP/s over *productive* time, and with
    ``roofline_tflops`` the MFU against a measured roofline."""
    sessions = _load_sessions(dir_path)
    if not sessions:
        return {"schema": SCHEMA, "sessions": 0, "wall_secs": 0.0,
                "fractions": {}, "seconds": {}}
    wall = max(r["end"] for r in sessions) - sessions[0]["start"]
    wall = max(wall, 1e-9)
    seconds: Dict[str, float] = {}

    def add(cause, secs):
        if secs > 0:
            seconds[cause] = seconds.get(cause, 0.0) + float(secs)

    for i, rec in enumerate(sessions):
        for cause, secs in rec.get("segments", {}).items():
            add(cause, secs)
        if rec.get("exit_cause") == "wedge":
            add("wedge", rec["end"] - rec.get("last_activity", rec["end"]))
        if i + 1 < len(sessions):
            add("restart", sessions[i + 1]["start"] - rec["end"])
    attributed = sum(seconds.values())
    seconds["productive"] = max(wall - attributed, 0.0)
    fractions = {k: v / wall for k, v in seconds.items()}
    steps = sum(r.get("steps", 0) for r in sessions)
    tokens = sum(r.get("tokens", 0) for r in sessions)
    out: Dict[str, Any] = {
        "schema": SCHEMA,
        "run_id": sessions[-1].get("run_id"),
        "sessions": len(sessions),
        "wall_secs": round(wall, 3),
        "seconds": {k: round(v, 3) for k, v in sorted(seconds.items())},
        # fractions stay full-precision: the productive bucket is the
        # remainder, so they sum to 1 exactly — rounding would break
        # the closure the acceptance contract pins
        "fractions": dict(sorted(fractions.items())),
        "steps": steps,
        "tokens": tokens,
        "exit_causes": [r.get("exit_cause") for r in sessions],
        "wedge_events": sum(1 for r in sessions
                            if r.get("exit_cause") == "wedge"),
    }
    productive = seconds["productive"]
    if tokens and productive > 0:
        out["tokens_per_sec_productive"] = round(tokens / productive, 2)
        out["tokens_per_sec_wall"] = round(tokens / wall, 2)
        if flops_per_token:
            tflops = flops_per_token * tokens / productive / 1e12
            out["model_tflops_productive"] = round(tflops, 3)
            if roofline_tflops:
                out["mfu_vs_measured_roofline"] = round(
                    tflops / roofline_tflops, 4)
    return out
