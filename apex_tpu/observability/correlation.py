"""Step-correlation context: join logs, metrics, and trace spans.

One tiny process-global ``(run_id, step)`` pair, set by the training /
serving loop at its own cadence.  Three consumers read it:

- :func:`apex_tpu.utils.logging.log_structured` merges it into every
  structured event's JSON payload,
- :meth:`apex_tpu.observability.metrics.MetricsRegistry.snapshot_jsonl`
  stamps it onto every metrics point,
- :func:`apex_tpu.utils.profiler.nvtx_range` appends it to the scope
  name (so the range survives into the HLO op metadata and the xprof
  host timeline),

so a wedged-run postmortem can join a log line, a metrics sample, and
an xprof range on exactly ``(run_id, step)``.

Deliberately stdlib-only and import-cycle-free: ``utils.logging`` and
``utils.profiler`` lazy-import this module, and this module imports
nothing from the package.
"""

import re
from typing import Optional

__all__ = ["clear_step_context", "set_step_context", "span_suffix",
           "step_context"]

_RUN_ID: Optional[str] = None
_STEP: Optional[int] = None

#: jax.named_scope names survive into HLO op metadata; keep the suffix
#: to characters every consumer (Mosaic, xprof, trace viewers) accepts
_SAFE = re.compile(r"[^A-Za-z0-9_.-]+")


def set_step_context(run_id: Optional[str] = None,
                     step: Optional[int] = None) -> None:
    """Record the loop's current ``(run_id, step)``.  ``run_id=None``
    keeps the previously set id (the loop usually sets it once and then
    only advances ``step``)."""
    global _RUN_ID, _STEP
    if run_id is not None:
        _RUN_ID = _SAFE.sub("_", str(run_id))
    if step is not None:
        _STEP = int(step)


def clear_step_context() -> None:
    global _RUN_ID, _STEP
    _RUN_ID, _STEP = None, None


def step_context() -> dict:
    """The current correlation fields (empty dict when unset) — callers
    merge this into their own payloads."""
    out = {}
    if _RUN_ID is not None:
        out["run_id"] = _RUN_ID
    if _STEP is not None:
        out["step"] = _STEP
    return out


def span_suffix() -> str:
    """Trace-span spelling of the context (``""`` when unset):
    ``.run_<id>.s<step>`` appended to a ``named_scope`` name."""
    parts = []
    if _RUN_ID is not None:
        parts.append(f"run_{_RUN_ID}")
    if _STEP is not None:
        parts.append(f"s{_STEP}")
    return ("." + ".".join(parts)) if parts else ""
