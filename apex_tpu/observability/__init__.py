"""apex_tpu.observability — metrics, step telemetry, goodput.

The unified telemetry layer (TorchTitan's built-in-metrics pillar,
PAPERS.md arxiv 2410.06511) over three sub-modules:

- :mod:`~apex_tpu.observability.metrics`: process-local rank-aware
  counters/gauges/histograms with labels, a JSONL time-series sidecar
  (the ``log_structured`` greppability contract) and a Prometheus text
  exporter; :class:`MetricsScope` routes the resilience/IO/serving
  retrofit counters (fallback trips, io retries, watchdog wedges,
  preemption drains, queue depth, TTFT) into a caller-owned registry.
- :mod:`~apex_tpu.observability.stepstats`: the :class:`StepStats`
  pytree riding ``make_train_step(telemetry=...)`` — loss, the grad
  norm reused from the fused clip reduction, the finite vote, the
  loss scale, param/update norms — accumulated device-side and fetched
  asynchronously (:class:`AsyncFetcher`; zero ``.item()`` in the hot
  loop — analyzer rule APX108 enforces the seam).
- :mod:`~apex_tpu.observability.goodput`: per-session wall-time
  attribution (checkpoint / restore / restart / wedge vs productive)
  whose report fractions sum to 1 across elastic restarts, plus the
  centralized model-FLOPs/MFU formulas.

See docs/observability.md for the metric name schema, the fetch-cadence
knob, and the goodput attribution table.
"""

from apex_tpu.observability.correlation import (
    clear_step_context, set_step_context, step_context,
)
from apex_tpu.observability.goodput import (
    GoodputAccountant, decode_flops_per_token, goodput_report,
    model_flops_per_step, model_flops_per_token, param_count,
    session_progress,
)
from apex_tpu.observability.metrics import (
    MetricsRegistry, MetricsScope, append_jsonl, get_metrics,
)
from apex_tpu.observability.stepstats import (
    AsyncFetcher, StepStats, StepTelemetry,
)

__all__ = [
    "AsyncFetcher", "GoodputAccountant", "MetricsRegistry", "MetricsScope",
    "StepStats", "StepTelemetry", "append_jsonl", "clear_step_context",
    "decode_flops_per_token", "get_metrics", "goodput_report",
    "model_flops_per_step", "model_flops_per_token", "param_count",
    "session_progress", "set_step_context", "step_context",
]
