"""apex_tpu.observability — metrics, step telemetry, goodput.

The unified telemetry layer (TorchTitan's built-in-metrics pillar,
PAPERS.md arxiv 2410.06511) over three sub-modules:

- :mod:`~apex_tpu.observability.metrics`: process-local rank-aware
  counters/gauges/histograms with labels, a JSONL time-series sidecar
  (the ``log_structured`` greppability contract) and a Prometheus text
  exporter; :class:`MetricsScope` routes the resilience/IO/serving
  retrofit counters (fallback trips, io retries, watchdog wedges,
  preemption drains, queue depth, TTFT) into a caller-owned registry.
- :mod:`~apex_tpu.observability.stepstats`: the :class:`StepStats`
  pytree riding ``make_train_step(telemetry=...)`` — loss, the grad
  norm reused from the fused clip reduction, the finite vote, the
  loss scale, param/update norms — accumulated device-side and fetched
  asynchronously (:class:`AsyncFetcher`; zero ``.item()`` in the hot
  loop — analyzer rule APX108 enforces the seam).
- :mod:`~apex_tpu.observability.goodput`: per-session wall-time
  attribution (checkpoint / restore / restart / wedge vs productive)
  whose report fractions sum to 1 across elastic restarts, plus the
  centralized model-FLOPs/MFU formulas.
- :mod:`~apex_tpu.observability.tracing`: host-side distributed
  tracing — the near-zero-overhead :func:`span` API over the run's
  host phases (data wait, step dispatch, checkpoint, serving
  admission/prefill/decode, supervisor attempts), a bounded in-memory
  ring, and JSONL + Chrome-trace/Perfetto exporters.  Spans wrap
  DISPATCH, never run inside jit: tracing on/off lowers identically
  and loss/params stay bitwise (the lowered + parity pins).
- :mod:`~apex_tpu.observability.flightrec`: the crash-forensics
  flight recorder — a fixed-size ring of recent spans + structured
  events + StepStats windows, dumped atomically on watchdog wedge,
  StepGuard abort, and preemption, so every exit-75/137 leaves a
  self-contained postmortem artifact.
- :mod:`~apex_tpu.observability.anomaly`: rolling median/MAD anomaly
  and straggler detection over step time, per-hop sync time, goodput,
  and per-lane serving latency — ``apex_anomaly_*`` counters plus
  structured alerts the supervisor's backoff consumes.

See docs/observability.md for the metric name schema, the fetch-cadence
knob, the goodput attribution table, the span naming schema, the
flight-recorder dump triggers, and the detector knobs.
"""

from apex_tpu.observability.anomaly import (
    AnomalyMonitor, RollingMadDetector,
)
from apex_tpu.observability.correlation import (
    clear_step_context, set_step_context, step_context,
)
from apex_tpu.observability.flightrec import FlightRecorder
from apex_tpu.observability.goodput import (
    GoodputAccountant, decode_flops_per_token, goodput_report,
    model_flops_per_step, model_flops_per_token, param_count,
    session_progress,
)
from apex_tpu.observability.metrics import (
    MetricsRegistry, MetricsScope, append_jsonl, get_metrics,
)
from apex_tpu.observability.stepstats import (
    AsyncFetcher, StepStats, StepTelemetry,
)
from apex_tpu.observability.tracing import (
    TracedStep, Tracer, TracingScope, new_trace_id, span,
)

__all__ = [
    "AnomalyMonitor", "AsyncFetcher", "FlightRecorder",
    "GoodputAccountant", "MetricsRegistry", "MetricsScope",
    "RollingMadDetector", "StepStats", "StepTelemetry", "TracedStep",
    "Tracer", "TracingScope", "append_jsonl", "clear_step_context",
    "decode_flops_per_token", "get_metrics", "goodput_report",
    "model_flops_per_step", "model_flops_per_token", "new_trace_id",
    "param_count", "session_progress", "set_step_context", "span",
    "step_context",
]
