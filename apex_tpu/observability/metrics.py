"""Process-local, rank-aware metrics registry.

The unified metrics layer the repo's one-off telemetry primitives
(``log_structured`` events, bench sidecar records, per-section JSON)
plug into — TorchTitan's built-in-metrics pillar (PAPERS.md, arxiv
2410.06511) in apex_tpu shape:

- **Counters / gauges / histograms with labels**: plain host-side
  Python objects (a dict update under a lock — safe to call from the
  watchdog/preemption threads), never device work.  Library code
  records through the module helpers (:func:`inc`, :func:`set_gauge`,
  :func:`observe`), which resolve the *current* registry so tests and
  embedded servers can scope their own.
- **JSONL time-series sidecar** (:meth:`MetricsRegistry.snapshot_jsonl`):
  one line per sample per snapshot, append+flush+fsync — the same
  greppability contract as ``utils.logging.log_structured`` and
  bench.py's section sidecar (whose writer now lives here,
  :func:`append_jsonl`).  Every line carries ``ts``, the process
  ``rank``, and the :mod:`~apex_tpu.observability.correlation`
  ``(run_id, step)`` so it joins against logs and xprof ranges.
- **Prometheus text exporter** (:meth:`MetricsRegistry.prometheus_text`):
  the 0.0.4 exposition format (``# HELP``/``# TYPE`` + samples;
  histograms expand to cumulative ``_bucket``/``_sum``/``_count``) for
  scrape-style collection.

Naming schema (see docs/observability.md): ``apex_<subsystem>_<what>``
with Prometheus unit conventions (``_total`` counters, ``_seconds``
histograms) — e.g. ``apex_checkpoint_io_retries_total``,
``apex_serve_ttft_seconds``.
"""

import json
import math
import os
import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from apex_tpu.observability.correlation import step_context

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "MetricsScope",
    "append_jsonl", "get_metrics", "inc", "observe", "set_gauge",
]

#: default latency buckets (seconds): sub-ms decode tokens through
#: multi-minute restarts
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0)


def _rank() -> int:
    """JAX process index, read lazily (metrics work before
    ``jax.distributed.initialize`` and in no-jax contexts)."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:  # noqa: BLE001 — rank is best-effort decoration
        return 0


def _label_key(labelnames: Sequence[str], labels: Dict[str, str]) -> Tuple:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"labels {sorted(labels)} do not match the metric's declared "
            f"label names {sorted(labelnames)}")
    return tuple(str(labels[n]) for n in labelnames)


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str],
                 lock: threading.RLock):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock
        self._children: Dict[Tuple, object] = {}

    def _child(self, labels: Dict[str, str]):
        key = _label_key(self.labelnames, labels)
        with self._lock:
            if key not in self._children:
                self._children[key] = self._new_child()
            return key

    def _read(self, labels: Dict[str, str]) -> float:
        """Non-inserting read: an absent series reads 0.0 WITHOUT
        minting it — a value() probe with a typo'd label must not
        pollute every later export with a permanent zero sample."""
        key = _label_key(self.labelnames, labels)
        with self._lock:
            child = self._children.get(key)
            return 0.0 if child is None else child[0]

    # ------------------------------------------------------------ export
    def samples(self) -> Iterator[Tuple[str, Dict[str, str], float]]:
        """``(sample_name, labels, value)`` triples (histograms expand
        to the cumulative bucket/sum/count series).

        The expansion MATERIALIZES under the lock: ``_expand`` reads
        mutable child state (a histogram's ``counts``/``sum``/
        ``count``), and yielding lazily would interleave those reads
        with a watchdog-thread ``observe`` — a torn scrape where
        ``_bucket`` rows disagree with ``_count`` (APX114's shape,
        caught by this module's two-thread hammer test)."""
        with self._lock:
            out: List[Tuple[str, Dict[str, str], float]] = []
            for key, child in self._children.items():
                labels = dict(zip(self.labelnames, key))
                out.extend(self._expand(labels, child))
        return iter(out)


class Counter(_Metric):
    """Monotonic cumulative count (``_total`` naming convention)."""

    kind = "counter"

    def _new_child(self):
        return [0.0]

    def labels(self, **labels) -> "_BoundCounter":
        return _BoundCounter(self, self._child(labels))

    def inc(self, n: float = 1.0, **labels) -> None:
        self.labels(**labels).inc(n)

    def value(self, **labels) -> float:
        return self._read(labels)

    def _expand(self, labels, child):
        yield (self.name, labels, child[0])


class _BoundCounter:
    def __init__(self, metric: Counter, key: Tuple):
        self._m, self._key = metric, key

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self._m.name} cannot decrease")
        with self._m._lock:
            self._m._children[self._key][0] += float(n)


class Gauge(_Metric):
    """Point-in-time value (set wins; no rate semantics)."""

    kind = "gauge"

    def _new_child(self):
        return [0.0]

    def labels(self, **labels) -> "_BoundGauge":
        return _BoundGauge(self, self._child(labels))

    def set(self, v: float, **labels) -> None:
        self.labels(**labels).set(v)

    def value(self, **labels) -> float:
        return self._read(labels)

    def _expand(self, labels, child):
        yield (self.name, labels, child[0])


class _BoundGauge:
    def __init__(self, metric: Gauge, key: Tuple):
        self._m, self._key = metric, key

    def set(self, v: float) -> None:
        with self._m._lock:
            self._m._children[self._key][0] = float(v)


class _HistState:
    __slots__ = ("counts", "sum", "count", "exemplars")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # +inf bucket
        self.sum = 0.0
        self.count = 0
        #: recent exemplar records ({"value", "ts", **ids}), bounded —
        #: drained by snapshot_jsonl so each appears in ONE snapshot
        self.exemplars: List[dict] = []


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics)."""

    kind = "histogram"

    def __init__(self, name, help, labelnames, lock,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")
        super().__init__(name, help, labelnames, lock)

    def _new_child(self):
        return _HistState(len(self.buckets))

    #: exemplars kept per histogram child between snapshots
    MAX_EXEMPLARS = 16

    def labels(self, **labels) -> "_BoundHistogram":
        return _BoundHistogram(self, self._child(labels))

    def observe(self, v: float, exemplar: Optional[dict] = None,
                **labels) -> None:
        """Record ``v``; ``exemplar`` (e.g. ``{"trace_id": ..., "rid":
        ...}``) attaches join-key identity to this OTHERWISE-ANONYMOUS
        sample — a p99 outlier in the exported series becomes joinable
        to its request's trace spans.  Exemplars ride the JSONL export
        (``<name>_exemplar`` lines, drained per snapshot); the
        Prometheus 0.0.4 text format has no exemplar syntax, so the
        .prom export carries only the histogram itself."""
        self.labels(**labels).observe(v, exemplar=exemplar)

    def drain_exemplars(self) -> List[Tuple[dict, dict]]:
        """``(labels, exemplar)`` pairs recorded since the last drain
        (the JSONL snapshot's feed); clears the rings."""
        with self._lock:
            items = list(self._children.items())
            out = []
            for key, child in items:
                if child.exemplars:
                    labels = dict(zip(self.labelnames, key))
                    out.extend((labels, ex) for ex in child.exemplars)
                    child.exemplars = []
        return out

    def _expand(self, labels, child: _HistState):
        cum = 0
        for le, c in zip(self.buckets, child.counts):
            cum += c
            yield (f"{self.name}_bucket", {**labels, "le": _fmt(le)}, cum)
        yield (f"{self.name}_bucket", {**labels, "le": "+Inf"}, child.count)
        yield (f"{self.name}_sum", labels, child.sum)
        yield (f"{self.name}_count", labels, child.count)


class _BoundHistogram:
    def __init__(self, metric: Histogram, key: Tuple):
        self._m, self._key = metric, key

    def observe(self, v: float, exemplar: Optional[dict] = None) -> None:
        v = float(v)
        m = self._m
        with m._lock:
            st: _HistState = m._children[self._key]
            st.sum += v
            st.count += 1
            if exemplar is not None:
                # recency ring, but the window MAX survives eviction:
                # the p99 outlier is the sample worth joining, and a
                # single end-of-run drain (serve_gpt.py) must still
                # hold it after hundreds of ordinary samples
                exs = st.exemplars
                if len(exs) >= Histogram.MAX_EXEMPLARS:
                    mx = max(range(len(exs)),
                             key=lambda i: exs[i]["value"])
                    del exs[1 if mx == 0 else 0]
                exs.append(
                    {"value": v, "ts": round(time.time(), 3), **exemplar})
            for i, le in enumerate(m.buckets):
                if v <= le:
                    st.counts[i] += 1
                    return
            st.counts[-1] += 1


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    s = repr(float(v))
    return s[:-2] if s.endswith(".0") else s


class MetricsRegistry:
    """One process-local family of named metrics.

    ``counter``/``gauge``/``histogram`` are get-or-create: repeated
    registration with the same kind returns the existing metric (so
    library call sites need no init ceremony), a kind or label clash on
    an existing name fails loudly."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) \
                        or tuple(labelnames) != m.labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind} with labels {m.labelnames}")
                want_buckets = kw.get("buckets")
                # DEFAULT_BUCKETS (by identity) means the caller did not
                # choose bounds — get-or-create, don't compare; explicit
                # differing bounds would silently misfile observations
                if want_buckets is not None \
                        and want_buckets is not DEFAULT_BUCKETS \
                        and tuple(sorted(
                            float(b) for b in want_buckets)) != m.buckets:
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {m.buckets}; re-registering with "
                        f"different bounds would silently misfile "
                        f"observations")
                return m
            m = cls(name, help, labelnames, self._lock, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    # ------------------------------------------------------------ export
    def metrics(self) -> List[_Metric]:
        with self._lock:
            return sorted(self._metrics.values(), key=lambda m: m.name)

    def prometheus_text(self) -> str:
        """Prometheus text exposition (0.0.4): HELP/TYPE headers plus
        every sample, ``rank`` label added to each.  Label values and
        HELP text are escaped per the spec — one un-escaped quote in an
        error-derived label would invalidate the WHOLE scrape.

        The whole exposition is assembled under the registry lock (one
        re-entrant lock shared by every metric), so the scrape is a
        CONSISTENT point-in-time snapshot: a watchdog-thread ``inc``
        or a registry insert mid-scrape waits, instead of mutating the
        dicts this iterates or tearing a histogram mid-expansion."""
        rank = str(_rank())
        out: List[str] = []
        with self._lock:
            for m in self.metrics():
                if m.help:
                    out.append(f"# HELP {m.name} {_esc_help(m.help)}")
                out.append(f"# TYPE {m.name} {m.kind}")
                for name, labels, value in m.samples():
                    lbl = ",".join(
                        f'{k}="{_esc_label(v)}"' for k, v in
                        sorted({**labels, "rank": rank}.items()))
                    out.append(f"{name}{{{lbl}}} {_fmt_val(value)}")
        return "\n".join(out) + "\n"

    def snapshot_jsonl(self, path, **extra) -> int:
        """Append the current value of every sample as one JSONL line
        each — the time-series sidecar.  Lines carry ``ts``, ``rank``,
        the correlation ``(run_id, step)``, and any ``extra`` fields;
        returns the number of lines written.  ONE open/flush/fsync per
        snapshot (not per line): a serving registry's histograms emit
        dozens of lines, and the fetch cadence this rides exists to
        keep host work cheap."""
        ctx = step_context()
        ts = round(time.time(), 3)
        rank = _rank()
        lines = []
        # assemble under the registry lock for a consistent snapshot
        # (concurrent inserts/incs wait); the file write + fsync below
        # happens OUTSIDE it — disk I/O under a lock the watchdog and
        # preemption threads also take is the APX116 drain-deadlock
        # shape this repo's analyzer exists to flag
        with self._lock:
            for m in self.metrics():
                for name, labels, value in m.samples():
                    lines.append(json.dumps({
                        "ts": ts, "rank": rank, **ctx,
                        "metric": name, "type": m.kind,
                        "labels": labels, "value": value, **extra,
                    }, sort_keys=True, default=str))
                if isinstance(m, Histogram):
                    # exemplars: the identity (trace id, request id) of
                    # individual samples — one line each, drained so a
                    # sample's identity rides exactly one snapshot.
                    # This is what makes a p99 outlier in the series
                    # JOINABLE to its request's trace spans.
                    for labels, ex in m.drain_exemplars():
                        lines.append(json.dumps({
                            "ts": ts, "rank": rank, **ctx,
                            "metric": f"{m.name}_exemplar",
                            "type": "exemplar",
                            "labels": labels, **ex, **extra,
                        }, sort_keys=True, default=str))
        if lines:
            with open(path, "a") as f:
                f.write("\n".join(lines) + "\n")
                f.flush()
                os.fsync(f.fileno())
        return len(lines)


def _fmt_val(v: float) -> str:
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def _esc_label(v) -> str:
    """Prometheus 0.0.4 label-value escaping: backslash, quote, LF."""
    return str(v).replace("\\", r"\\").replace('"', r"\"") \
        .replace("\n", r"\n")


def _esc_help(v: str) -> str:
    """HELP-text escaping: backslash and LF."""
    return str(v).replace("\\", r"\\").replace("\n", r"\n")


def append_jsonl(path, obj: dict) -> None:
    """THE append-one-JSON-line writer (append + flush + fsync) —
    shared by the metrics sidecar and bench.py's section sidecar, so a
    process killed mid-run keeps every line that was written."""
    line = json.dumps(obj, sort_keys=True, default=str)
    with open(path, "a") as f:
        f.write(line + "\n")
        f.flush()
        os.fsync(f.fileno())


# ------------------------------------------------------- current registry
_DEFAULT = MetricsRegistry()
_SCOPES: List[MetricsRegistry] = []


def get_metrics() -> MetricsRegistry:
    """The registry library call sites record into: the innermost
    :class:`MetricsScope`'s, else the process default."""
    return _SCOPES[-1] if _SCOPES else _DEFAULT


class MetricsScope:
    """``with MetricsScope(reg):`` — route every module-helper record
    (the resilience/IO/serving retrofits) into ``reg`` for the scope's
    duration.  This is how tests isolate counters and how an embedded
    server owns its own registry without threading one through every
    library signature."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None else MetricsRegistry()

    def __enter__(self) -> MetricsRegistry:
        _SCOPES.append(self.registry)
        return self.registry

    def __exit__(self, *exc):
        _SCOPES.pop()
        return False


# ---------------------------------------------------------- module helpers
#
# The helpers are BEST-EFFORT by design: they are the retrofit seam the
# resilience paths record through (fallback trip, watchdog fire,
# preemption drain, step-guard abort, io retry), and a telemetry
# failure — a registry clash from a caller-owned scope, a torn install
# — must never change THEIR control flow (a metrics error swallowing a
# BadStepBudgetExceeded, or crashing the degrade-once fallback before
# it runs, is strictly worse than a lost sample).  Failures warn once
# per metric name; registry methods used directly stay strict.
_WARNED: set = set()


def _best_effort(fn, name: str) -> None:
    try:
        fn()
    except Exception as e:  # noqa: BLE001 — observers never participate
        if name not in _WARNED:
            _WARNED.add(name)
            import logging

            from apex_tpu.utils.logging import get_logger, log_structured

            log_structured(get_logger("apex_tpu.observability"),
                           logging.WARNING, "metrics.record_failed",
                           metric=name,
                           error=f"{type(e).__name__}: {e}")


def inc(name: str, value: float = 1.0, help: str = "", **labels) -> None:
    """Increment counter ``name`` in the current registry (labels
    create the series on first use).  Best-effort — see above."""
    _best_effort(
        lambda: get_metrics().counter(
            name, help, tuple(sorted(labels))).inc(value, **labels),
        name)


def set_gauge(name: str, value: float, help: str = "", **labels) -> None:
    _best_effort(
        lambda: get_metrics().gauge(
            name, help, tuple(sorted(labels))).set(value, **labels),
        name)


def observe(name: str, value: float, help: str = "",
            buckets: Sequence[float] = DEFAULT_BUCKETS,
            exemplar: Optional[dict] = None, **labels) -> None:
    _best_effort(
        lambda: get_metrics().histogram(
            name, help, tuple(sorted(labels)),
            buckets=buckets).observe(value, exemplar=exemplar, **labels),
        name)
