"""Device-side per-step training telemetry with asynchronous fetch.

The blocking spelling of training telemetry — ``float(loss)`` every
step — costs a full host sync per step (the device drains its dispatch
queue while the host formats a string).  This module is the allowed
spelling (analyzer rule APX108 flags the blocking one):

- :class:`StepStats` is a tiny pytree of device scalars that rides the
  jitted train step exactly like
  :class:`~apex_tpu.resilience.step_guard.GuardState` does: loss
  (last + window sum), the global gradient norm **reused from the
  optimizer's fused clip reduction** (never a new HBM pass — see the
  capture seam below), the all-finite vote, the loss scale, and the
  param/update norms.  Accumulation is branch-free device arithmetic
  fused into the compiled step; the stats buffers are donated.
- :class:`AsyncFetcher` is the host half: the loop hands it device
  arrays (``put``) — it starts a non-blocking device→host copy and the
  loop keeps dispatching; completed copies are harvested later
  (``ready``, non-blocking; ``flush`` blocks, for end of run).  Zero
  ``.item()``/``float()`` of a device array ever runs in the hot loop.

**Capture seam** (:func:`capture`/:func:`offer`): the step builders
wrap the traced step body in ``with capture() as cap:``; the optimizer
engines *offer* interior traced values (the clip's global grad norm,
the agreed all-finite flag) into it at trace time.  This is a
trace-time side channel — it costs nothing at run time and lets the
stats reuse reductions the update already computes instead of re-reading
the gradients.  When no clip is configured the engines fold a local
Σx² into the same grad read (fused by XLA — still no extra pass), so
``grad_norm`` is then the *rank-local* norm on sharded axes; with
``clip_grad_norm`` set it is the exact global norm the clip agreed.

Stats are **observers, never participants**: nothing here feeds back
into the update, so telemetry-on and telemetry-off steps produce
bitwise-identical losses and params (pinned in
tests/test_observability.py) and identical collective counts (pinned
in tests/test_lowered_invariants.py).
"""

import contextlib
import threading
from collections import deque
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["AsyncFetcher", "StepStats", "StepTelemetry", "capture",
           "capturing", "offer"]


class StepStats(NamedTuple):
    """The windowed device-side accumulator (all scalars; donated)."""

    steps: jnp.ndarray          # i32: steps accumulated in this window
    loss_sum: jnp.ndarray       # f32: Σ loss over the window
    loss_last: jnp.ndarray      # f32
    grad_norm_sum: jnp.ndarray  # f32: Σ grad-norm (see module doc)
    grad_norm_last: jnp.ndarray  # f32
    notfinite: jnp.ndarray      # i32: non-finite (skipped) steps in window
    loss_scale: jnp.ndarray     # f32: last loss scale (nan without amp)
    param_norm: jnp.ndarray     # f32: last ||params|| (local shards)
    update_norm: jnp.ndarray    # f32: last ||Δparams|| (local shards)


# ------------------------------------------------------------ capture seam
_CAPTURE: List[Dict[str, Any]] = []


def capturing() -> bool:
    """True while a step builder's telemetry wrapper is tracing — the
    engines use this to fold the (otherwise skipped) Σx² stat into
    their one grad read."""
    return bool(_CAPTURE)


def offer(key: str, value) -> None:
    """Trace-time: expose an interior traced value (``grad_norm``,
    ``all_finite``) to the innermost active :func:`capture`.  No-op —
    one truthiness check — when nothing captures."""
    if _CAPTURE:
        _CAPTURE[-1][key] = value


@contextlib.contextmanager
def capture():
    """``with capture() as cap:`` around a traced step body; ``cap``
    collects everything the interior :func:`offer`'d."""
    cap: Dict[str, Any] = {}
    _CAPTURE.append(cap)
    try:
        yield cap
    finally:
        _CAPTURE.pop()


def offer_local_grad_norm(arrays) -> None:
    """The no-clip grad-norm stat, in ONE place for all three engine
    paths (bucketed prepare, per-leaf dispatch, ZeRO shards): when a
    telemetry wrapper captures and no clip reduction exists to reuse,
    fold a rank-local Σx² over ``arrays`` into the engine's one grad
    read (XLA fuses the reduce with the read — still no extra HBM
    pass) and offer its sqrt.  No-op when nothing captures."""
    if not _CAPTURE:
        return
    offer("grad_norm", jnp.sqrt(sum(
        jnp.sum(jnp.square(jnp.asarray(a).astype(jnp.float32)))
        for a in arrays)))


# ------------------------------------------------------------- device side
class StepTelemetry:
    """Build-time telemetry spec for ``make_train_step(telemetry=...)``.

    ``norms=False`` drops the param/update norm stats (two extra — XLA
    fuses them, but nonzero — elementwise reads of the param trees per
    step); everything else reuses values the step already computes.
    """

    def __init__(self, norms: bool = True):
        self.norms = bool(norms)

    def init(self) -> StepStats:
        """Fresh zeroed window (also what the loop swaps in after each
        fetch — the fetched buffers must NOT ride into the next step:
        they are donated).  Every field gets its OWN buffer: the stats
        ride a donating step, and donating one shared buffer at several
        argument positions is an Execute()-time crash (the
        ``base.make_master`` copy=True lesson)."""
        return StepStats(
            steps=jnp.int32(0),
            loss_sum=jnp.float32(0.0),
            loss_last=jnp.float32(0.0),
            grad_norm_sum=jnp.float32(0.0),
            grad_norm_last=jnp.float32(jnp.nan),
            notfinite=jnp.int32(0),
            loss_scale=jnp.float32(jnp.nan),
            param_norm=jnp.float32(jnp.nan),
            update_norm=jnp.float32(jnp.nan))

    def init_like(self, stats: StepStats) -> StepStats:
        """Fresh zeroed window placed with ``stats``' shardings — what
        the fetch seam swaps in mid-run.  The jit cache keys on input
        shardings, so resetting with uncommitted host scalars would
        retrace the step once per fetch; matching the outgoing window's
        (replicated) placement keeps the steady-state signature — and
        the compiled-variant count — fixed."""
        return jax.tree.map(
            lambda z, old: jax.device_put(z, old.sharding),
            self.init(), stats)

    def accumulate(self, stats: StepStats, *, loss, grad_norm=None,
                   finite=None, loss_scale=None, new_params=None,
                   old_params=None) -> StepStats:
        """One step's device-side accounting (branch-free, traced into
        the step).  ``grad_norm``/``finite`` come from the capture
        seam and may be absent (non-engine optimizers, unguarded
        unscaled steps): absent ``finite`` counts as finite, absent
        ``grad_norm`` freezes the nan placeholder."""
        loss = jnp.asarray(loss, jnp.float32)
        if grad_norm is not None:
            gn = jnp.asarray(grad_norm, jnp.float32)
            gn_sum = stats.grad_norm_sum + gn
        else:
            gn = stats.grad_norm_last
            gn_sum = stats.grad_norm_sum
        bad = (jnp.int32(0) if finite is None else
               jnp.where(jnp.asarray(finite), jnp.int32(0), jnp.int32(1)))
        scale = (stats.loss_scale if loss_scale is None
                 else jnp.asarray(loss_scale, jnp.float32))
        pn, un = stats.param_norm, stats.update_norm
        if self.norms and new_params is not None and old_params is not None:
            psq = sum(jnp.sum(jnp.square(p.astype(jnp.float32)))
                      for p in jax.tree.leaves(new_params))
            usq = sum(jnp.sum(jnp.square(
                n.astype(jnp.float32) - o.astype(jnp.float32)))
                for n, o in zip(jax.tree.leaves(new_params),
                                jax.tree.leaves(old_params)))
            pn, un = jnp.sqrt(psq), jnp.sqrt(usq)
        return StepStats(
            steps=stats.steps + jnp.int32(1),
            loss_sum=stats.loss_sum + loss, loss_last=loss,
            grad_norm_sum=gn_sum, grad_norm_last=gn,
            notfinite=stats.notfinite + bad,
            loss_scale=scale, param_norm=pn, update_norm=un)

    # ---------------------------------------------------------- host side
    @staticmethod
    def summary(stats_np: Dict[str, Any]) -> Dict[str, float]:
        """Harvested window (a ``{field: np scalar}`` dict from
        :class:`AsyncFetcher`) → plain floats for printing/metrics."""
        n = max(int(stats_np["steps"]), 1)
        gn_last = float(stats_np["grad_norm_last"])
        # the window never received a grad norm (non-engine optimizer):
        # grad_norm_sum sat at its 0.0 init — report "unavailable"
        # (nan, matching grad_norm_last), never a fake 0.0 mean
        gn_mean = (float(stats_np["grad_norm_sum"]) / n
                   if np.isfinite(gn_last) else float("nan"))
        out = {
            "steps": int(stats_np["steps"]),
            "loss_mean": float(stats_np["loss_sum"]) / n,
            "loss_last": float(stats_np["loss_last"]),
            "grad_norm_last": gn_last,
            "grad_norm_mean": gn_mean,
            "bad_steps": int(stats_np["notfinite"]),
            "loss_scale": float(stats_np["loss_scale"]),
            "param_norm": float(stats_np["param_norm"]),
            "update_norm": float(stats_np["update_norm"]),
        }
        return out

    @staticmethod
    def emit(registry, stats_np: Dict[str, Any],
             prefix: str = "apex_train") -> Dict[str, float]:
        """Record a harvested window onto a
        :class:`~apex_tpu.observability.metrics.MetricsRegistry`
        (gauges for the point-in-time stats, counters for the
        cumulative ones); returns the summary dict."""
        s = StepTelemetry.summary(stats_np)
        registry.counter(f"{prefix}_steps_total",
                         "train steps accumulated").inc(s["steps"])
        registry.counter(f"{prefix}_bad_steps_total",
                         "non-finite (skipped) steps").inc(s["bad_steps"])
        g = registry.gauge
        # every gauge is isfinite-gated: a skipped overflow step (routine
        # while an fp16 scaler searches down) puts inf in the window's
        # loss_sum — bad_steps_total carries that fact; the loss gauges
        # must keep tracking the real trend, not freeze a dashboard at
        # inf (the summary dict returns the raw values regardless)
        for key, gname, help_ in (
                ("loss_mean", f"{prefix}_loss", "window-mean train loss"),
                ("loss_last", f"{prefix}_loss_last", "last step's loss"),
                ("grad_norm_last", f"{prefix}_grad_norm_last", ""),
                ("grad_norm_mean", f"{prefix}_grad_norm_mean", ""),
                ("loss_scale", f"{prefix}_loss_scale", ""),
                ("param_norm", f"{prefix}_param_norm", ""),
                ("update_norm", f"{prefix}_update_norm", "")):
            if np.isfinite(s[key]):
                g(gname, help_).set(s[key])
        return s


# ------------------------------------------------------------- async fetch
def _start_copy(leaf):
    try:
        leaf.copy_to_host_async()
    except AttributeError:
        pass  # non-jax leaf (plain number): nothing to overlap


def _is_ready(leaf) -> bool:
    fn = getattr(leaf, "is_ready", None)
    return True if fn is None else bool(fn())


class AsyncFetcher:
    """The non-blocking device→host telemetry channel.

    ``put(kind, step, tree)`` starts an async copy of every array leaf
    and queues the entry; ``ready()`` harvests — in FIFO order, so
    printed lines stay step-ordered — every entry whose arrays have
    materialized, WITHOUT blocking (an entry still in flight stops the
    harvest); ``flush()`` blocks for the stragglers (end of run /
    preemption exit, where a sync is correct).  Harvested trees are
    plain numpy.

    The loop must not pass a ``put`` tree onward into a donating step
    call (the stats protocol swaps in fresh
    :meth:`StepTelemetry.init` buffers at each fetch) — the fetcher
    holds the only live reference until harvest.

    **Threading model**: ``put`` and ``ready`` are LOOP-THREAD-ONLY —
    they are the hot path's non-blocking halves, and the step loop is
    the only producer.  ``flush`` may additionally be called from the
    preemption/watchdog exit paths concurrently with the loop: it
    detaches the whole pending queue ATOMICALLY under the internal
    lock (each entry is harvested exactly once, each caller's batch
    stays FIFO) and converts to numpy outside the lock, so a loop-
    thread ``ready`` racing an exit-path ``flush`` never double-
    harvests or drops a window.  ``len()`` is a racy snapshot."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: deque = deque()

    def put(self, kind: str, step: int, tree) -> None:
        jax.tree.map(_start_copy, tree)
        with self._lock:
            self._pending.append((kind, int(step), tree))

    def __len__(self) -> int:
        return len(self._pending)

    def _to_np(self, tree):
        return jax.tree.map(np.asarray, tree)

    def ready(self) -> List[Tuple[str, int, Any]]:
        harvested = []
        while True:
            with self._lock:
                if not self._pending:
                    break
                kind, step, tree = self._pending[0]
                if not all(_is_ready(x)
                           for x in jax.tree.leaves(tree)):
                    break
                self._pending.popleft()
            harvested.append((kind, step, tree))
        return [(k, s, self._to_np(t)) for k, s, t in harvested]

    def flush(self) -> List[Tuple[str, int, Any]]:
        with self._lock:
            drained, self._pending = self._pending, deque()
        return [(kind, step, self._to_np(tree))
                for kind, step, tree in drained]
