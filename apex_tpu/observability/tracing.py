"""Host-side distributed tracing: where did a step's wall time go?

The metrics layer (PR 10) answers *how much* — counters, histograms,
goodput fractions; this module answers *where*: a near-zero-overhead
span API over the host-side phases of a run (data wait, step dispatch,
telemetry harvest, checkpoint save/restore, serving admission → prefill
→ decode, supervisor attempt/backoff), correlated with logs and
metrics through the one ``(run_id, step)`` join key
(:mod:`~apex_tpu.observability.correlation`), and exported two ways:

- **JSONL** (:meth:`Tracer.export_jsonl`): one line per span — the
  ``log_structured`` greppability contract, same fields every other
  sidecar carries (``ts``/``rank``/``run_id``/``step``).
- **Chrome trace-event / Perfetto JSON**
  (:meth:`Tracer.export_chrome`): load the file straight into
  https://ui.perfetto.dev (or ``chrome://tracing``) — spans render per
  thread with their attributes as args.

Design constraints, each load-bearing:

- **Spans wrap DISPATCH, never run inside jit.**  A traced step is the
  SAME compiled program as an untraced one: tracing on/off is pinned
  to identical collective counts, zero extra host transfers, and
  bitwise-identical loss/params (tests/test_lowered_invariants.py::
  TestTracingTrainStep, tests/test_tracing.py).  Because dispatch is
  asynchronous, a dispatch span measures *host* time — queueing a
  step, not running it.  That is exactly what the span name says
  (``train.step.dispatch``); treating it as device step time is the
  lie analyzer rule APX112 exists to flag.  Real step wall time shows
  up as the steady-state dispatch cadence once the device queue
  throttles the host.
- **Near-zero overhead when off.**  :func:`span` with no tracer
  configured returns a no-op singleton — one module-global read, no
  allocation, no lock.
- **Bounded memory.**  The span buffer is a ring (``capacity`` spans,
  oldest dropped, drop count kept): tracing a week-long run costs the
  same memory as tracing a minute.
- **Thread-aware.**  Spans record their thread id and name — the
  watchdog, preemption, and async-checkpoint threads show up as their
  own Perfetto tracks.
- **Crash-forensics ready.**  OPEN spans (started, never finished —
  the wedged dispatch) are tracked and included in exports and in
  :mod:`~apex_tpu.observability.flightrec` dumps, flagged
  ``open=True`` with their elapsed time: the last open span of a
  wedged process names the step that wedged.

Span naming schema (see docs/observability.md for the full table):
``<subsystem>.<phase>`` — ``train.step.dispatch``, ``train.data_wait``,
``train.checkpoint_save``, ``zero_sync.bucket<k>.hop_<axis>``,
``serve.admission_wait``, ``serve.decode_step``, ``serve.request``,
``supervisor.attempt``, ``bench.section.<name>``.
"""

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from apex_tpu.observability.correlation import step_context

__all__ = [
    "TracedStep", "Tracer", "TracingScope", "configure", "disable",
    "emit_sync_plan", "enabled", "export_run", "get_tracer", "instant",
    "new_trace_id", "overlap_fraction", "span",
]

SCHEMA = "apex_tpu_trace_v1"

_TRACER: Optional["Tracer"] = None

_TRACE_IDS = itertools.count()


def new_trace_id() -> str:
    """A process-unique request/trace id (``<pid-hex>-<n-hex>``) —
    what the serving scheduler stamps on every request so a p99
    histogram outlier joins back to its spans.  Monotonic per process:
    two requests can never share one."""
    return f"{os.getpid():x}-{next(_TRACE_IDS):x}"


# --------------------------------------------------------------- span core
class _Span:
    """One in-flight span; records itself into the tracer on exit.
    Also usable as a context manager (the common spelling)."""

    __slots__ = ("_tracer", "name", "attrs", "ts", "_t0", "tid",
                 "thread", "_done")

    def __init__(self, tracer: "Tracer", name: str, attrs: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        # correlation captured at START: the step the span belongs to
        # is the step the loop had set when the phase began
        self.attrs = {**step_context(), **attrs}
        self.ts = time.time()
        self._t0 = time.perf_counter()
        t = threading.current_thread()
        self.tid = t.ident or 0
        self.thread = t.name
        self._done = False
        tracer._opened(self)

    def elapsed(self) -> float:
        return time.perf_counter() - self._t0

    def set(self, **attrs) -> "_Span":
        """Attach attributes mid-span (spec accept counts, result
        sizes)."""
        self.attrs.update(attrs)
        return self

    def end(self, **attrs) -> None:
        if self._done:
            return
        self._done = True
        if attrs:
            self.attrs.update(attrs)
        self._tracer._finished(self, self.elapsed())

    # ------------------------------------------------- context manager
    def __enter__(self) -> "_Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        self.end()
        return False


class _NoopSpan:
    """The disabled-tracing singleton: every operation is a no-op."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self

    def end(self, **attrs):
        pass

    def elapsed(self) -> float:
        return 0.0


_NOOP = _NoopSpan()


class Tracer:
    """Bounded in-memory span buffer + exporters.

    Thread-safe: spans may start/finish on any thread (the watchdog
    fires from its own).  ``capacity`` bounds FINISHED spans (ring —
    oldest dropped, counted in ``dropped``); open spans are tracked in
    a side table so a crash dump can name what never finished."""

    def __init__(self, capacity: int = 4096):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=self.capacity)
        self._open: Dict[int, _Span] = {}
        self._listeners: List[Callable[[dict], None]] = []
        self.started = 0
        self.finished = 0
        self.dropped = 0

    # ----------------------------------------------------------- record
    def span(self, name: str, **attrs) -> _Span:
        """Start a span; ``with tracer.span("x"):`` or keep the handle
        and call ``.end()``."""
        return _Span(self, name, attrs)

    def instant(self, name: str, **attrs) -> None:
        """A zero-duration marker event (Chrome ``i`` phase)."""
        t = threading.current_thread()
        self._record({
            "name": name, "ph": "i", "ts": time.time(), "dur_us": 0,
            "tid": t.ident or 0, "thread": t.name,
            "attrs": {**step_context(), **attrs},
        })

    def emit(self, name: str, start_ts: float, dur_s: float,
             **attrs) -> None:
        """Retro-record a COMPLETED span from its measured endpoints
        (the serving scheduler's admission wait: both timestamps are
        known only at admit time)."""
        t = threading.current_thread()
        self._record({
            "name": name, "ph": "X", "ts": float(start_ts),
            "dur_us": max(int(dur_s * 1e6), 0),
            "tid": t.ident or 0, "thread": t.name,
            "attrs": {**step_context(), **attrs},
        })

    def add_listener(self, fn: Callable[[dict], None]) -> None:
        """Call ``fn(span_dict)`` on every finished span — the flight
        recorder's feed.  Listener errors are swallowed (observers
        never participate)."""
        self._listeners.append(fn)

    # ------------------------------------------------------- internals
    def _opened(self, s: _Span) -> None:
        with self._lock:
            self.started += 1
            self._open[id(s)] = s

    def _finished(self, s: _Span, dur_s: float) -> None:
        with self._lock:
            self._open.pop(id(s), None)
        self._record({
            "name": s.name, "ph": "X", "ts": s.ts,
            "dur_us": max(int(dur_s * 1e6), 0),
            "tid": s.tid, "thread": s.thread, "attrs": dict(s.attrs),
        })

    def _record(self, rec: dict) -> None:
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped += 1
            self._spans.append(rec)
            self.finished += 1
            listeners = tuple(self._listeners)
        for fn in listeners:
            try:
                fn(rec)
            except Exception:  # noqa: BLE001 — observers never participate
                pass

    # ---------------------------------------------------------- export
    def spans(self) -> List[dict]:
        """Finished spans, oldest first (a snapshot copy)."""
        with self._lock:
            return [dict(s) for s in self._spans]

    def open_spans(self) -> List[dict]:
        """Started-but-unfinished spans with their elapsed time so far
        — the wedged dispatch shows up HERE, flagged ``open``."""
        with self._lock:
            live = list(self._open.values())
        return [{
            "name": s.name, "ph": "X", "ts": s.ts,
            "dur_us": max(int(s.elapsed() * 1e6), 0),
            "tid": s.tid, "thread": s.thread,
            "attrs": dict(s.attrs), "open": True,
        } for s in live]

    def export_jsonl(self, path) -> int:
        """One JSON line per span (finished then open), the sidecar
        contract fields (``ts``/``rank``; ``run_id``/``step`` ride the
        span attrs).  One open/flush/fsync for the whole file append.
        Returns lines written."""
        rank = _rank()
        lines = []
        for rec in self.spans() + self.open_spans():
            lines.append(json.dumps({
                "span": rec["name"], "ph": rec["ph"],
                "ts": round(rec["ts"], 6), "dur_us": rec["dur_us"],
                "tid": rec["tid"], "thread": rec["thread"],
                "rank": rank, "open": rec.get("open", False),
                **rec.get("attrs", {}),
            }, sort_keys=True, default=str))
        if lines:
            with open(path, "a") as f:
                f.write("\n".join(lines) + "\n")
                f.flush()
                os.fsync(f.fileno())
        return len(lines)

    def export_chrome(self, path) -> int:
        """Chrome trace-event JSON (Perfetto-loadable), written
        ATOMICALLY (tmp+fsync+rename — a wedge dump must never publish
        a torn trace).  Timestamps are epoch microseconds; each thread
        gets a ``thread_name`` metadata event so watchdog/checkpoint
        threads render as named tracks.  Returns the event count."""
        from apex_tpu.io.native import atomic_output

        pid = os.getpid()
        events = []
        threads = {}
        for rec in self.spans() + self.open_spans():
            threads.setdefault(rec["tid"], rec["thread"])
            args = dict(rec.get("attrs", {}))
            if rec.get("open"):
                args["open"] = True
            events.append({
                "name": rec["name"], "ph": rec["ph"],
                "ts": int(rec["ts"] * 1e6), "dur": rec["dur_us"],
                "pid": pid, "tid": rec["tid"], "args": args,
            })
        for tid, tname in sorted(threads.items()):
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": tid, "args": {"name": tname},
            })
        doc = {"schema": SCHEMA, "displayTimeUnit": "ms",
               "traceEvents": events,
               "otherData": {"rank": _rank(), "dropped": self.dropped}}
        with atomic_output(path) as f:
            f.write(json.dumps(doc).encode())
        return len(events)


def _rank() -> int:
    # the ONE rank resolution (metrics JSONL and span exports join on
    # the rank field — they must never disagree)
    from apex_tpu.observability.metrics import _rank as metrics_rank

    return metrics_rank()


# ------------------------------------------------------- global configure
def configure(capacity: int = 4096,
              tracer: Optional[Tracer] = None) -> Tracer:
    """Install (and return) the process tracer; until this is called
    every :func:`span`/:func:`instant` is a no-op."""
    global _TRACER
    _TRACER = tracer if tracer is not None else Tracer(capacity=capacity)
    return _TRACER


def disable() -> None:
    global _TRACER
    _TRACER = None


def get_tracer() -> Optional[Tracer]:
    return _TRACER


def enabled() -> bool:
    return _TRACER is not None


class TracingScope:
    """``with TracingScope() as tracer:`` — scope a tracer for tests /
    embedded engines (restores the previous one on exit, exactly the
    :class:`~apex_tpu.observability.metrics.MetricsScope` pattern)."""

    def __init__(self, tracer: Optional[Tracer] = None,
                 capacity: int = 4096):
        self.tracer = tracer if tracer is not None \
            else Tracer(capacity=capacity)
        self._prev: Optional[Tracer] = None

    def __enter__(self) -> Tracer:
        global _TRACER
        self._prev = _TRACER
        _TRACER = self.tracer
        return self.tracer

    def __exit__(self, *exc):
        global _TRACER
        _TRACER = self._prev
        return False


def span(name: str, **attrs):
    """Module-level span against the current tracer — THE instrumented
    spelling (``with span("train.data_wait"): ...``).  One global read
    and a no-op singleton when tracing is off."""
    t = _TRACER
    return t.span(name, **attrs) if t is not None else _NOOP


def instant(name: str, **attrs) -> None:
    t = _TRACER
    if t is not None:
        t.instant(name, **attrs)


def export_run(dir_path, run_id, tracer: Optional["Tracer"] = None
               ) -> Optional[Dict[str, Any]]:
    """Export the current trace under ``dir_path`` with THE repo-wide
    artifact convention — ``trace_<run_id>_<pid>.json`` (Perfetto/
    Chrome) plus ``spans_<run_id>_<pid>.jsonl`` (sidecar contract) —
    the one spelling shared by the train/serve drivers, the wedge
    hook, and bench (the e2e forensics test and the docs table both
    glob these names).  Creates ``dir_path`` if missing; returns
    ``{"chrome", "jsonl", "events", "dropped"}``, or None when no
    tracer is installed."""
    t = tracer if tracer is not None else _TRACER
    if t is None:
        return None
    os.makedirs(str(dir_path), exist_ok=True)
    pid = os.getpid()
    chrome = os.path.join(str(dir_path), f"trace_{run_id}_{pid}.json")
    jsonl = os.path.join(str(dir_path), f"spans_{run_id}_{pid}.jsonl")
    n = t.export_chrome(chrome)
    t.export_jsonl(jsonl)
    return {"chrome": chrome, "jsonl": jsonl, "events": n,
            "dropped": t.dropped}


# ----------------------------------------------------- dispatch wrapping
class TracedStep:
    """Wrap a compiled step callable in a DISPATCH span.

    The wrapper lives entirely outside jit: ``lower``/``_cache_size``
    and every other attribute delegate to the wrapped callable, so the
    compiled program — collective counts, host transfers, donation —
    is byte-identical with tracing on or off (the lowered-tier pin),
    and loss/params stay bitwise (the parity pin).  The span measures
    HOST dispatch time (async dispatch returns before the device
    runs); in steady state the device queue throttles dispatch, so the
    span cadence tracks real step time — but a single span is not a
    step-time measurement (analyzer rule APX112's subject)."""

    def __init__(self, fn, name: str = "step.dispatch",
                 attrs: Optional[Dict[str, Any]] = None):
        self._fn = fn
        self._name = str(name)
        self._attrs = dict(attrs or {})

    def __call__(self, *args, **kw):
        t = _TRACER
        if t is None:
            return self._fn(*args, **kw)
        with t.span(self._name, dispatch=True, **self._attrs):
            return self._fn(*args, **kw)

    def __getattr__(self, name):
        return getattr(self._fn, name)


def overlap_fraction(tracer: Optional[Tracer] = None,
                     prefix: str = "zero_sync.bucket") -> float:
    """Span-concurrency of the wire plan against dispatch: the
    fraction of ``prefix``-named instant markers in the tracer's
    buffer whose timestamp falls INSIDE some ``*step.dispatch`` span's
    ``[ts, ts + dur]`` interval.  A marker emitted while a dispatch is
    in flight is a sync whose host-side bookkeeping overlapped the
    step — the host-observable proxy for the compiled step's
    compute/communication overlap (the collectives themselves run on
    device, where per-hop host timing would need forbidden host
    transfers).  ``prefix`` defaults to the ZeRO wire plan's
    ``zero_sync.bucket`` markers (:func:`emit_sync_plan`); ring
    attention's bench section passes ``"ring_attn.hop"`` to measure
    its hop plan against the same dispatch windows.  0.0 with no
    tracer, no markers, or no dispatch spans."""
    tracer = tracer if tracer is not None else _TRACER
    if tracer is None:
        return 0.0
    spans = tracer.spans() + tracer.open_spans()
    windows = [(s["ts"], s["ts"] + s["dur_us"] / 1e6) for s in spans
               if s["name"].endswith("step.dispatch")]
    marks = [s["ts"] for s in spans
             if s["ph"] == "i" and s["name"].startswith(prefix)]
    if not marks or not windows:
        return 0.0
    inside = sum(1 for ts in marks
                 if any(lo <= ts <= hi for lo, hi in windows))
    return inside / len(marks)


def emit_sync_plan(optimizer, tracer: Optional[Tracer] = None) -> dict:
    """Emit one ``zero_sync.bucket<k>.hop_<axis>`` marker per (bucket,
    hop) of a ZeRO optimizer's sync plan, attributes carrying the
    per-hop payload/scale bytes (:meth:`~apex_tpu.contrib.optimizers.
    _zero_engine.ZeroOptimizerBase.sync_plan_hops`).  The markers give
    a trace its wire-plan track; the per-step ``train.step.dispatch``
    span carries the same per-hop totals, so span duration ÷ hop bytes
    bounds the achieved per-hop bandwidth (the sync itself runs inside
    the compiled step — per-hop host timing would need host transfers
    the zero-overhead contract forbids).

    Returns ``{"markers": n, "overlap_fraction": f}``: markers emitted
    this call (0 when tracing is off or the optimizer has no plan) and
    :func:`overlap_fraction` over the tracer's whole buffer — calling
    this inside the step loop (markers land inside the live dispatch
    span) folds the wire plan's dispatch concurrency into the same
    record the bench reports as its ``overlap_fraction`` column."""
    tracer = tracer if tracer is not None else _TRACER
    hops_fn = getattr(optimizer, "sync_plan_hops", None)
    if tracer is None or hops_fn is None:
        return {"markers": 0, "overlap_fraction": 0.0}
    n = 0
    for rec in hops_fn():
        tracer.instant(
            f"zero_sync.bucket{rec['bucket']}.hop_{rec['hop']}", **rec)
        n += 1
    return {"markers": n, "overlap_fraction": overlap_fraction(tracer)}
