"""Megatron-style tensor parallelism over a mesh axis.

Reference: ``apex/transformer/tensor_parallel`` (SURVEY.md §2.1).
"""

from apex_tpu.transformer.tensor_parallel.cross_entropy import vocab_parallel_cross_entropy
from apex_tpu.transformer.tensor_parallel.data import broadcast_data, broadcast_from_rank0
from apex_tpu.transformer.tensor_parallel.grad_accum import (
    accumulate_gradients,
    make_grad_accumulator,
)
from apex_tpu.transformer.tensor_parallel.attributes import (
    TensorParallelAttributes,
    attributes_tree,
    copy_tensor_model_parallel_attributes,
    param_is_not_tensor_parallel_duplicate,
    set_defaults_if_not_set_tensor_model_parallel_attributes,
    set_tensor_model_parallel_attributes,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    column_parallel_linear,
    row_parallel_linear,
    vocab_parallel_embedding,
)
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.memory import (
    MemoryBuffer,
    RingMemBuffer,
    allocate_mem_buff,
    get_mem_buffs,
)
from apex_tpu.transformer.tensor_parallel.random import (
    init_checkpointed_activations_memory_buffer,
    reset_checkpointed_activations_memory_buffer,
    RNGStatesTracker,
    checkpoint,
    get_cuda_rng_tracker,
    get_rng_state_tracker,
    model_parallel_cuda_manual_seed,
    model_parallel_seed,
)
from apex_tpu.transformer.tensor_parallel.utils import (
    VocabUtility,
    split_tensor_along_last_dim,
)

__all__ = [
    "TensorParallelAttributes",
    "attributes_tree",
    "copy_tensor_model_parallel_attributes",
    "param_is_not_tensor_parallel_duplicate",
    "set_defaults_if_not_set_tensor_model_parallel_attributes",
    "set_tensor_model_parallel_attributes",
    "init_checkpointed_activations_memory_buffer",
    "reset_checkpointed_activations_memory_buffer",
    "vocab_parallel_cross_entropy",
    "broadcast_data",
    "broadcast_from_rank0",
    "ColumnParallelLinear",
    "RowParallelLinear",
    "VocabParallelEmbedding",
    "column_parallel_linear",
    "row_parallel_linear",
    "vocab_parallel_embedding",
    "copy_to_tensor_model_parallel_region",
    "gather_from_sequence_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
    "scatter_to_sequence_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "MemoryBuffer",
    "RingMemBuffer",
    "allocate_mem_buff",
    "get_mem_buffs",
    "RNGStatesTracker",
    "checkpoint",
    "get_cuda_rng_tracker",
    "get_rng_state_tracker",
    "model_parallel_cuda_manual_seed",
    "model_parallel_seed",
    "VocabUtility",
    "split_tensor_along_last_dim",
]
