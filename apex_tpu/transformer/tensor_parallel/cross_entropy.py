"""Vocab-parallel cross entropy.

Reference: ``apex/transformer/tensor_parallel/cross_entropy.py:23-132``
(``_VocabParallelCrossEntropy``).  Semantics reproduced exactly,
including the backward (softmax minus smoothed one-hot) via
``jax.custom_vjp`` so no full-vocab gather ever happens:

1. ``pmax`` of logits over the tp axis, subtract.
2. Local gather of the target logit (ids outside this shard's vocab range
   masked to 0), ``psum``.
3. ``psum`` of local sum-exp; ``loss = log(sum_exp) - target_logit``.
4. Label smoothing uses the *partition* vocab size in its coefficient and
   a partition-local mean log-prob, faithfully mirroring the reference
   (cross_entropy.py:78-97 computes ``vocab_size = exp_logits.size(-1)``
   after sharding — a deliberate parity choice here).
"""

from functools import partial

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TENSOR_AXIS


def _fwd_impl(logits, target, label_smoothing, axis_name):
    # 1. global max for stability
    lmax = jax.lax.pmax(jnp.max(logits, axis=-1), axis_name)
    logits = logits - lmax[..., None]

    partition = logits.shape[-1]
    rank = jax.lax.axis_index(axis_name)
    start = rank * partition

    # 2. this shard's copy of the target logit
    local_t = target - start
    mask = (local_t < 0) | (local_t >= partition)
    local_t = jnp.clip(local_t, 0, partition - 1)
    predicted = jnp.take_along_axis(logits, local_t[..., None], axis=-1)[..., 0]
    predicted = jnp.where(mask, 0.0, predicted)
    predicted = jax.lax.psum(predicted, axis_name)

    # 3. global partition function
    exp_logits = jnp.exp(logits)
    sum_exp = jax.lax.psum(jnp.sum(exp_logits, axis=-1), axis_name)
    loss = jnp.log(sum_exp) - predicted

    softmax = exp_logits / sum_exp[..., None]

    if label_smoothing > 0:
        # reference cross_entropy.py:78-97 (partition-local terms)
        assert 1.0 > label_smoothing > 0.0
        smoothing = label_smoothing * partition / (partition - 1)
        log_probs = jnp.log(softmax)
        mean_log_probs = jnp.mean(log_probs, axis=-1)
        loss = (1.0 - smoothing) * loss - smoothing * mean_log_probs

    return loss, (softmax, mask, local_t)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def vocab_parallel_cross_entropy(
    vocab_parallel_logits, target, label_smoothing: float = 0.0, axis_name: str = TENSOR_AXIS
):
    """Per-token CE loss; logits sharded over vocab on ``axis_name``.

    Reference: cross_entropy.py:132 (same signature plus the axis name).
    """
    loss, _ = _fwd_impl(vocab_parallel_logits, target, label_smoothing, axis_name)
    return loss


def _ce_fwd(logits, target, label_smoothing, axis_name):
    loss, res = _fwd_impl(logits, target, label_smoothing, axis_name)
    return loss, res


def _ce_bwd(label_smoothing, axis_name, res, g):
    softmax, mask, local_t = res
    partition = softmax.shape[-1]
    update = (~mask).astype(softmax.dtype)
    onehot = jax.nn.one_hot(local_t, partition, dtype=softmax.dtype) * update[..., None]
    if label_smoothing > 0:
        smoothing = label_smoothing * partition / (partition - 1)
        grad = softmax - (1.0 - smoothing) * onehot - smoothing / partition
    else:
        grad = softmax - onehot
    grad = grad * g[..., None]
    return grad.astype(softmax.dtype), None


vocab_parallel_cross_entropy.defvjp(_ce_fwd, _ce_bwd)
