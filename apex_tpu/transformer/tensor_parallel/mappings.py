"""Autograd-visible collectives for tensor/sequence parallelism.

Reference: ``apex/transformer/tensor_parallel/mappings.py:31-302`` — seven
autograd Functions pairing a forward collective with the Megatron-correct
backward collective, exposed as ``*_region`` helpers.

These run *inside* ``shard_map`` over the mesh of
:mod:`apex_tpu.transformer.parallel_state`; each takes the mesh axis name
(default ``"tp"``).  The forward/backward pairing is expressed with
``jax.custom_vjp``:

====================================  ============  =====================
function                              forward       backward
====================================  ============  =====================
copy_to_tensor_model_parallel_region  identity      psum
reduce_from_..._region                psum          identity
scatter_to_..._region                 split(last)   all_gather(last)
gather_from_..._region                gather(last)  split(last)
scatter_to_sequence_parallel_region   split(first)  all_gather(first)
gather_from_sequence_parallel_region  gather(first) reduce_scatter(first)
reduce_scatter_to_sequence_..._region rs(first)     all_gather(first)
====================================  ============  =====================
"""

from functools import partial

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TENSOR_AXIS


def _split_along(x, axis_name, dim):
    """Keep this rank's slice of dim (reference ``_split``, mappings.py:69)."""
    size = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    chunk = x.shape[dim] // size
    return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=dim)


def _gather_along(x, axis_name, dim):
    """Concatenate across the axis (reference ``_gather``, mappings.py:79)."""
    return jax.lax.all_gather(x, axis_name, axis=dim, tiled=True)


def _reduce_scatter_along(x, axis_name, dim):
    """Reference ``_reduce_scatter`` (mappings.py:122)."""
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=dim, tiled=True)


# ---------------------------------------------------------------- copy
@partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tensor_model_parallel_region(x, axis_name=TENSOR_AXIS):
    """Identity fwd / all-reduce bwd (mappings.py:141 _CopyToModelParallelRegion)."""
    return x


def _copy_fwd(x, axis_name):
    return x, None


def _copy_bwd(axis_name, _, g):
    return (jax.lax.psum(g, axis_name),)


copy_to_tensor_model_parallel_region.defvjp(_copy_fwd, _copy_bwd)


# -------------------------------------------------------------- reduce
@partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tensor_model_parallel_region(x, axis_name=TENSOR_AXIS):
    """All-reduce fwd / identity bwd (mappings.py:158 _ReduceFromModelParallelRegion)."""
    return jax.lax.psum(x, axis_name)


def _reduce_fwd(x, axis_name):
    return jax.lax.psum(x, axis_name), None


def _reduce_bwd(axis_name, _, g):
    return (g,)


reduce_from_tensor_model_parallel_region.defvjp(_reduce_fwd, _reduce_bwd)


# ------------------------------------------------------------- scatter
@partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_tensor_model_parallel_region(x, axis_name=TENSOR_AXIS):
    """Split last dim fwd / gather bwd (mappings.py:175 _ScatterToModelParallelRegion)."""
    return _split_along(x, axis_name, x.ndim - 1)


def _scatter_fwd(x, axis_name):
    return _split_along(x, axis_name, x.ndim - 1), None


def _scatter_bwd(axis_name, _, g):
    return (_gather_along(g, axis_name, g.ndim - 1),)


scatter_to_tensor_model_parallel_region.defvjp(_scatter_fwd, _scatter_bwd)


# -------------------------------------------------------------- gather
@partial(jax.custom_vjp, nondiff_argnums=(1,))
def gather_from_tensor_model_parallel_region(x, axis_name=TENSOR_AXIS):
    """Gather last dim fwd / split bwd (mappings.py:192 _GatherFromModelParallelRegion)."""
    return _gather_along(x, axis_name, x.ndim - 1)


def _gather_fwd(x, axis_name):
    return _gather_along(x, axis_name, x.ndim - 1), None


def _gather_bwd(axis_name, _, g):
    return (_split_along(g, axis_name, g.ndim - 1),)


gather_from_tensor_model_parallel_region.defvjp(_gather_fwd, _gather_bwd)


# ------------------------------------------------- sequence parallelism
@partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_sequence_parallel_region(x, axis_name=TENSOR_AXIS):
    """Split first dim fwd / gather bwd (mappings.py:213 _ScatterToSequenceParallelRegion)."""
    return _split_along(x, axis_name, 0)


def _seq_scatter_fwd(x, axis_name):
    return _split_along(x, axis_name, 0), None


def _seq_scatter_bwd(axis_name, _, g):
    return (_gather_along(g, axis_name, 0),)


scatter_to_sequence_parallel_region.defvjp(_seq_scatter_fwd, _seq_scatter_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_from_sequence_parallel_region(x, axis_name=TENSOR_AXIS, tensor_parallel_output_grad=True):
    """Gather first dim fwd (mappings.py:230 _GatherFromSequenceParallelRegion)
    — the SP entry collective of the TP linears (layers.py:311-324).

    ``tensor_parallel_output_grad`` (reference mappings.py:236-250):
    True (default) = downstream produces rank-PARTIAL cotangents (a TP
    linear) → backward reduce-scatters.  False = downstream cotangent is
    already complete/replicated (e.g. after the psum of the LM-head's
    copy-to-region) → backward just splits.
    """
    return _gather_along(x, axis_name, 0)


def _seq_gather_fwd(x, axis_name, tensor_parallel_output_grad):
    return _gather_along(x, axis_name, 0), None


def _seq_gather_bwd(axis_name, tensor_parallel_output_grad, _, g):
    if tensor_parallel_output_grad:
        return (_reduce_scatter_along(g, axis_name, 0),)
    return (_split_along(g, axis_name, 0),)


gather_from_sequence_parallel_region.defvjp(_seq_gather_fwd, _seq_gather_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_scatter_to_sequence_parallel_region(x, axis_name=TENSOR_AXIS):
    """Reduce-scatter first dim fwd / gather bwd (mappings.py:252
    _ReduceScatterToSequenceParallelRegion) — the SP exit collective of
    RowParallelLinear."""
    return _reduce_scatter_along(x, axis_name, 0)


def _seq_rs_fwd(x, axis_name):
    return _reduce_scatter_along(x, axis_name, 0), None


def _seq_rs_bwd(axis_name, _, g):
    return (_gather_along(g, axis_name, 0),)


reduce_scatter_to_sequence_parallel_region.defvjp(_seq_rs_fwd, _seq_rs_bwd)
