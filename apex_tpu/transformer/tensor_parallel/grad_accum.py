"""Gradient-accumulation fusion: the ``main_grad`` contract.

Reference: ``fused_weight_gradient_mlp_cuda.wgrad_gemm_accum_fp32``
(``csrc/megatron/fused_weight_gradient_dense.cpp:19``) +
``LinearWithGradAccumulationAndAsyncCommunication``
(``apex/transformer/tensor_parallel/layers.py:415-427``): when training
with microbatches, each backward's weight gradient accumulates directly
into one persistent fp32 ``weight.main_grad`` buffer — no per-microbatch
gradient materialization, and fp32 accumulation even when the model runs
in half precision.

TPU form: a ``lax.scan`` over microbatches whose carry IS the fp32
main-grad buffer.  XLA keeps the carry resident and in-place (this is
verified by an HLO regression test: no gradient-sized buffer scales with
the microbatch count), and each microbatch's bf16 wgrad dot fuses with
the accumulate — the same one-buffer behavior the CUDA kernel provides,
without a custom kernel.

Inside the pipeline schedules the identical pattern is built in
(``tick_schedule.py`` grad carries); this module is the standalone,
user-visible form for non-pipelined microbatched training.
"""

from typing import Callable

import jax
import jax.numpy as jnp


def accumulate_gradients(
    loss_fn: Callable,
    params,
    microbatches,
    *args,
    accum_dtype=jnp.float32,
    mean_loss: bool = True,
):
    """Run ``loss_fn(params, microbatch, *args)`` over the leading
    microbatch axis, accumulating gradients into one persistent
    ``accum_dtype`` buffer per parameter (the ``main_grad`` semantics).

    Returns ``(loss, grads)`` — loss averaged over microbatches and
    grads averaged (matching what one large-batch backward would give
    for a mean-reduced loss).

    Works under ``shard_map``: any collectives inside ``loss_fn`` (TP
    mappings, SP gathers) run per microbatch exactly as the reference's
    backward does.
    """
    M = jax.tree.leaves(microbatches)[0].shape[0]
    g0 = jax.tree.map(lambda p: jnp.zeros(p.shape, accum_dtype), params)

    def body(carry, mb):
        loss_sum, g = carry
        loss, gi = jax.value_and_grad(loss_fn)(params, mb, *args)
        g = jax.tree.map(lambda a, b: a + b.astype(accum_dtype), g, gi)
        return (loss_sum + loss.astype(jnp.float32), g), None

    (loss_sum, g), _ = jax.lax.scan(body, (jnp.float32(0.0), g0), microbatches)
    inv = 1.0 / M
    loss = loss_sum * inv if mean_loss else loss_sum
    grads = jax.tree.map(lambda a: a * inv, g) if mean_loss else g
    return loss, grads


def make_grad_accumulator(loss_fn: Callable, **kw):
    """Partial-application convenience:
    ``accum = make_grad_accumulator(loss_fn); loss, g = accum(params, mbs)``."""

    def accum(params, microbatches, *args):
        return accumulate_gradients(loss_fn, params, microbatches, *args, **kw)

    return accum
