"""Data broadcast across the tensor-parallel axis.

Reference: ``apex/transformer/tensor_parallel/data.py:80``
(``broadcast_data``): rank 0 of each TP group broadcasts the batch so all
TP ranks compute on identical data.

On TPU, input pipelines usually feed identical host data to the TP group
already (the sharding of the batch is over ``dp``), so this is a safety
utility: inside ``shard_map`` it replaces every rank's value with tp-rank
0's.
"""

from typing import Any

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TENSOR_AXIS


def broadcast_from_rank0(x, axis_name: str = TENSOR_AXIS):
    """Value of tp rank 0, on every rank (one all_gather + slice; XLA
    lowers this to a broadcast on ICI)."""
    return jax.lax.all_gather(x, axis_name, axis=0)[0]


def broadcast_data(keys, data: dict, datatype=None, axis_name: str = TENSOR_AXIS) -> dict:
    """Reference-parity signature (data.py:80): broadcast ``data[k]`` for
    k in keys from tp rank 0."""
    out = {}
    for k in keys:
        v = jnp.asarray(data[k])
        if datatype is not None:
            v = v.astype(datatype)
        out[k] = broadcast_from_rank0(v, axis_name)
    return out
