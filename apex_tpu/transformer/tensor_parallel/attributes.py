"""Tensor-parallel parameter attributes.

Reference: ``apex/transformer/tensor_parallel/layers.py:70-107`` —
``set_tensor_model_parallel_attributes`` et al. stamp three attributes
(``tensor_model_parallel``, ``partition_dim``, ``partition_stride``)
onto ``torch.nn.Parameter`` objects so downstream code (grad-norm
computation, checkpointing) can tell TP-sharded params from replicated
duplicates (``param_is_not_tensor_parallel_duplicate``,
``layers.py:76``).

JAX arrays are values, not objects — they cannot carry attributes
through transforms.  The TPU-native form is a **spec tree**: a pytree of
:class:`TensorParallelAttributes` mirroring the param tree, built once
at model-construction time and passed alongside params where the
reference would read ``param.tensor_model_parallel``
(:func:`apex_tpu.transformer.pipeline_parallel.utils.calc_params_l2_norm`
accepts one).  The function names and semantics match the reference;
they operate on spec objects / spec trees instead of mutating tensors.
"""

import dataclasses
from typing import Any, Optional

import jax

_MODEL_PARALLEL_ATTRIBUTE_DEFAULTS = {
    "tensor_model_parallel": False,
    "partition_dim": -1,
    "partition_stride": 1,
}


@dataclasses.dataclass
class TensorParallelAttributes:
    """The three reference attributes (layers.py:70-74)."""

    tensor_model_parallel: bool = False
    partition_dim: int = -1
    partition_stride: int = 1


def set_tensor_model_parallel_attributes(
    is_parallel: bool, dim: int, stride: int
) -> TensorParallelAttributes:
    """Build the spec the reference stamps onto a sharded param
    (layers.py:82-89)."""
    return TensorParallelAttributes(
        tensor_model_parallel=is_parallel, partition_dim=dim, partition_stride=stride
    )


def set_defaults_if_not_set_tensor_model_parallel_attributes(
    attrs: Optional[TensorParallelAttributes],
) -> TensorParallelAttributes:
    """None → replicated defaults (layers.py:92-98)."""
    return TensorParallelAttributes() if attrs is None else attrs


def copy_tensor_model_parallel_attributes(
    source: TensorParallelAttributes,
) -> TensorParallelAttributes:
    """Fresh copy of a spec (layers.py:101-107; e.g. when cloning a
    param into a master-weight tree)."""
    return dataclasses.replace(source)


def param_is_not_tensor_parallel_duplicate(
    attrs: Optional[TensorParallelAttributes], tp_rank: int
) -> bool:
    """True if this param should be counted on this tp rank: it is
    TP-sharded (every rank owns a distinct slice) or we are tp rank 0
    (replicated params counted once).  Reference layers.py:76-79."""
    a = set_defaults_if_not_set_tensor_model_parallel_attributes(attrs)
    return a.tensor_model_parallel or tp_rank == 0


def attributes_tree(params: Any, is_parallel_fn) -> Any:
    """Build a spec tree for ``params``: ``is_parallel_fn(path, leaf)``
    returns ``None`` (replicated) or ``(dim, stride)`` for sharded
    leaves."""

    def one(path, leaf):
        r = is_parallel_fn(path, leaf)
        if r is None:
            return TensorParallelAttributes()
        dim, stride = r
        return set_tensor_model_parallel_attributes(True, dim, stride)

    return jax.tree_util.tree_map_with_path(one, params)
