"""Contiguous activation memory buffers.

Reference: ``apex/transformer/tensor_parallel/memory.py`` —
``MemoryBuffer`` (:37), ``RingMemBuffer`` (:138), ``allocate_mem_buff``
(:25) — a preallocated flat tensor that activation-partitioning copies
checkpointed activations into, to avoid allocator fragmentation.

TPU redesign: XLA owns device memory, so the fragmentation problem the
reference solves does not exist under jit — but the *capacity-budgeting*
role does.  The buffer here is a flat ``jnp`` array reused across
``add`` calls: ``add`` packs a flattened tensor at the bump-allocator
cursor (``lax.dynamic_update_slice``) and slices it back out.  This is a
**host-side** compatibility shim: the cursor and arena live in Python
state, so ``add`` must be called outside ``jit`` (it raises on tracers).
Inside jit the idiomatic equivalents are ``jax.checkpoint`` policies
(:mod:`apex_tpu.transformer.tensor_parallel.random`) — XLA already
arena-allocates.  Usage tracking mirrors the reference (sampled at
``get_data``, memory.py:115-120) so code ported from Megatron can
budget identically.
"""

from typing import Dict

import jax.numpy as jnp
from jax import lax

# All allocated buffers, by name (reference memory.py:22 ``_MEM_BUFFS``).
_MEM_BUFFS: Dict[str, "MemoryBuffer"] = {}


def allocate_mem_buff(name: str, numel: int, dtype, track_usage: bool = False):
    """Allocate a named buffer (reference memory.py:25)."""
    if name in _MEM_BUFFS:
        raise AssertionError(f"memory buffer {name} already allocated.")
    _MEM_BUFFS[name] = MemoryBuffer(name, numel, dtype, track_usage)
    return _MEM_BUFFS[name]


def get_mem_buff(name: str):
    """Look up a named buffer (reference memory.py:32)."""
    return _MEM_BUFFS[name]


def get_mem_buffs():
    """All buffers (test/debug helper)."""
    return dict(_MEM_BUFFS)


def reset_mem_buffs():
    _MEM_BUFFS.clear()


class MemoryBuffer:
    """Bump-allocated contiguous buffer (reference memory.py:37).

    ``add(tensor)`` copies the flattened tensor into the arena at the
    current cursor and returns the packed view reshaped to the tensor's
    shape; ``reset()`` rewinds the cursor so the arena is reused next
    microbatch — the exact usage pattern of the reference's
    activation partitioning.
    """

    def __init__(self, name: str, numel: int, dtype, track_usage: bool = False):
        self.name = name
        self.numel = int(numel)
        self.dtype = dtype
        self._data = None  # allocated lazily on first use: a buffer that
        self._start = 0    # is never add()ed to must not pin device memory
        # usage tracking (reference memory.py:70-77,122)
        self.track_usage = track_usage
        self.in_use_value = 0.0
        self.total_value = 0.0

    @property
    def data(self):
        if self._data is None:
            self._data = jnp.zeros((self.numel,), dtype=self.dtype)
        return self._data

    @data.setter
    def data(self, value):
        self._data = value

    def reset(self):
        """Rewind the cursor; arena contents become dead (memory.py:79)."""
        self._start = 0

    def is_in_use(self) -> bool:
        return self._start > 0

    def numel_in_use(self) -> int:
        return self._start

    def add(self, tensor):
        """Pack ``tensor`` into the arena; returns the packed copy
        reshaped to ``tensor.shape`` (reference memory.py:91)."""
        import jax

        if isinstance(tensor, jax.core.Tracer):
            raise TypeError(
                "MemoryBuffer.add called under jit tracing: the arena cursor is "
                "host-side Python state. Use jax.checkpoint policies for in-jit "
                "activation memory management."
            )
        if tensor.dtype != self.dtype:
            raise AssertionError(
                f"Input tensor dtype {tensor.dtype} != buffer dtype {self.dtype}"
            )
        n = tensor.size
        new_start = self._start + n
        if new_start > self.numel:
            raise AssertionError(f"Not enough memory buffer ({self.name})")
        self.data = lax.dynamic_update_slice(
            self.data, tensor.reshape(-1), (self._start,)
        )
        view = lax.dynamic_slice(self.data, (self._start,), (n,)).reshape(tensor.shape)
        self._start = new_start
        return view

    def get_data(self):
        """The live prefix of the arena; usage is sampled here, per
        consumer read, exactly as the reference does (memory.py:115-120)."""
        if self.track_usage:
            self.in_use_value += float(self._start)
            self.total_value += float(self.numel)
        if self._data is None and self._start == 0:
            return jnp.zeros((0,), dtype=self.dtype)  # unused arena: stay lazy
        return self.data[: self._start]

    def print_average_usage(self):
        if not self.track_usage:
            raise AssertionError("You need to enable usage tracking")
        print(
            f" > usage of {self.name} memory buffer: "
            f"{self.in_use_value * 100.0 / max(self.total_value, 1.0):.2f} %"
        )


class RingMemBuffer:
    """Ring of N buffers rotated per call (reference memory.py:138) —
    double-buffering for overlapping microbatches."""

    def __init__(self, name: str, num_buffers: int, numel: int, dtype, track_usage=False):
        self.num_buffers = num_buffers
        self.buffers = [
            allocate_mem_buff(f"{name} {i}", numel, dtype, track_usage)
            for i in range(num_buffers)
        ]
        self._index = -1

    def get_next_buffer(self):
        self._index = (self._index + 1) % self.num_buffers
        buff = self.buffers[self._index]
        buff.reset()
        return buff
