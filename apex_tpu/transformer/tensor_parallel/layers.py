"""Tensor-parallel layers: column/row linear, vocab-parallel embedding.

Reference: ``apex/transformer/tensor_parallel/layers.py`` —
``VocabParallelEmbedding`` (:174), ``LinearWithGradAccumulationAndAsync
Communication`` (:279-437), ``ColumnParallelLinear`` (:460),
``RowParallelLinear`` (:645).

TPU redesign notes:

- The reference's *async grad all-reduce overlapped with wgrad* and the
  *fused wgrad accumulation into main_grad*
  (``fused_weight_gradient_mlp_cuda``) are scheduling tricks for
  torch's eager backward; XLA's latency-hiding scheduler overlaps the
  backward collective with the wgrad dot automatically once they live in
  one jit region, so no user-facing knobs are needed for them.
- Sequence parallelism keeps the reference dataflow exactly: activations
  enter seq-sharded, ``all_gather`` on entry to a column linear
  (backward: ``reduce_scatter``), ``reduce_scatter`` on exit of the row
  linear (backward: ``all_gather``)  (layers.py:311-324,386-413).
- Weight layout follows the reference: column linear holds
  ``(out_local, in)``, row linear holds ``(out, in_local)``; ``y = x W^T``.

Functional forms run inside ``shard_map`` (weights are the *local*
shards); flax module wrappers hold locally-shaped params for use with the
same shard_map pattern.
"""

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TENSOR_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.utils import VocabUtility


def column_parallel_linear(
    x,
    weight,
    bias: Optional[jnp.ndarray] = None,
    *,
    gather_output: bool = True,
    sequence_parallel_enabled: bool = False,
    axis_name: str = TENSOR_AXIS,
):
    """Y = XA^T + b with A sharded over rows (output features).

    Reference: ColumnParallelLinear.forward (layers.py:460+).  ``weight``
    is the local shard ``(out_features/tp, in_features)``.
    """
    if sequence_parallel_enabled:
        # SP: input is seq-sharded; all-gather fwd, reduce-scatter bwd
        x = gather_from_sequence_parallel_region(x, axis_name)
    else:
        # identity fwd, all-reduce bwd
        x = copy_to_tensor_model_parallel_region(x, axis_name)
    y = jnp.matmul(x, weight.T.astype(x.dtype))
    if bias is not None:
        y = y + bias.astype(y.dtype)
    if gather_output:
        if sequence_parallel_enabled:
            raise ValueError("gather_output is incompatible with sequence parallelism")
        y = gather_from_tensor_model_parallel_region(y, axis_name)
    return y


def row_parallel_linear(
    x,
    weight,
    bias: Optional[jnp.ndarray] = None,
    *,
    input_is_parallel: bool = True,
    sequence_parallel_enabled: bool = False,
    axis_name: str = TENSOR_AXIS,
):
    """Y = XA^T + b with A sharded over columns (input features).

    Reference: RowParallelLinear (layers.py:645+).  ``weight`` is the
    local shard ``(out_features, in_features/tp)``.  Bias is added
    *after* the reduction (only once, as in the reference).
    """
    if not input_is_parallel:
        if sequence_parallel_enabled:
            raise ValueError("sequence parallelism requires input_is_parallel")
        x = scatter_to_tensor_model_parallel_region(x, axis_name)
    y = jnp.matmul(x, weight.T.astype(x.dtype))
    if sequence_parallel_enabled:
        y = reduce_scatter_to_sequence_parallel_region(y, axis_name)
    else:
        y = reduce_from_tensor_model_parallel_region(y, axis_name)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y


def vocab_parallel_embedding(
    ids,
    weight,
    *,
    axis_name: str = TENSOR_AXIS,
):
    """Embedding with the vocab dimension sharded over ``tp``.

    Reference: VocabParallelEmbedding.forward (layers.py:174-277): mask
    out-of-range ids, local lookup, zero the masked rows, all-reduce.
    ``weight`` is the local shard ``(vocab/tp, hidden)``.
    """
    per_partition = weight.shape[0]
    rank = jax.lax.axis_index(axis_name)
    start = rank * per_partition
    local = ids - start
    mask = (local < 0) | (local >= per_partition)
    local = jnp.clip(local, 0, per_partition - 1)
    out = jnp.take(weight, local, axis=0)
    out = jnp.where(mask[..., None], jnp.zeros((), out.dtype), out)
    # psum fwd / identity bwd via the custom_vjp mapping — a raw psum's
    # autodiff transpose would double-count the embedding gradient
    # (reference layers.py:270: output_parallel → reduce_from_...).
    return reduce_from_tensor_model_parallel_region(out, axis_name)


# ------------------------------------------------------------ flax modules
import flax.linen as nn


class ColumnParallelLinear(nn.Module):
    """Module form; holds the LOCAL weight shard (use under shard_map).

    ``output_size`` is the GLOBAL output dim; the local param is
    ``output_size // tp_size`` rows (reference layers.py:460 computes
    ``output_size_per_partition`` the same way).
    """

    input_size: int
    output_size: int
    tp_size: int
    use_bias: bool = True
    gather_output: bool = True
    sequence_parallel_enabled: bool = False
    axis_name: str = TENSOR_AXIS
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        out_local = self.output_size // self.tp_size
        w = self.param(
            "weight", nn.initializers.lecun_normal(), (out_local, self.input_size), self.param_dtype
        )
        b = (
            self.param("bias", nn.initializers.zeros, (out_local,), self.param_dtype)
            if self.use_bias
            else None
        )
        return column_parallel_linear(
            x,
            w,
            b,
            gather_output=self.gather_output,
            sequence_parallel_enabled=self.sequence_parallel_enabled,
            axis_name=self.axis_name,
        )


class RowParallelLinear(nn.Module):
    input_size: int
    output_size: int
    tp_size: int
    use_bias: bool = True
    input_is_parallel: bool = True
    sequence_parallel_enabled: bool = False
    axis_name: str = TENSOR_AXIS
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        in_local = self.input_size // self.tp_size
        w = self.param(
            "weight", nn.initializers.lecun_normal(), (self.output_size, in_local), self.param_dtype
        )
        b = (
            self.param("bias", nn.initializers.zeros, (self.output_size,), self.param_dtype)
            if self.use_bias
            else None
        )
        return row_parallel_linear(
            x,
            w,
            b,
            input_is_parallel=self.input_is_parallel,
            sequence_parallel_enabled=self.sequence_parallel_enabled,
            axis_name=self.axis_name,
        )


class VocabParallelEmbedding(nn.Module):
    num_embeddings: int
    embedding_dim: int
    tp_size: int
    axis_name: str = TENSOR_AXIS
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, ids):
        vocab_local = self.num_embeddings // self.tp_size
        w = self.param(
            "weight",
            nn.initializers.normal(stddev=0.02),
            (vocab_local, self.embedding_dim),
            self.param_dtype,
        )
        return vocab_parallel_embedding(ids, w, axis_name=self.axis_name)
