"""Parallel RNG management + activation checkpointing.

Reference: ``apex/transformer/tensor_parallel/random.py`` —
``CudaRNGStatesTracker`` (:124), ``model_parallel_cuda_manual_seed``
(:204), ``CheckpointFunction``/``checkpoint`` (:237,308) with the
``MemoryBuffer`` partitioning option (``memory.py:37``).

TPU redesign: CUDA RNG *state snapshots* become **functional key
derivation**.  JAX keys are values, so the tracker holds named base keys
and every ``fork`` is a pure ``fold_in`` — no state capture/restore, and
checkpoint recompute replays identically by construction (the whole
reason the reference needs the tracker machinery disappears).

Megatron seeding rule (random.py:204-234): the *model-parallel* RNG
differs per tp rank (``seed + 2718 + tp_rank``) so dropout on sharded
activations decorrelates, while the *default* RNG is identical across tp
ranks.  Both are provided here; pass the traced tp rank from inside
shard_map.

Activation checkpointing maps to ``jax.checkpoint`` — recompute in
backward with identical RNG, which is exactly the reference's
CheckpointFunction contract.  The activation-partitioning option
(``distribute_saved_activations``) is an XLA rematerialization/sharding
policy here rather than a manual MemoryBuffer.
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp

_MODEL_PARALLEL_RNG = "model-parallel-rng"

# Reference offsets (random.py:204-220)
_TP_OFFSET = 2718
_PP_OFFSET = 100


class RNGStatesTracker:
    """Named RNG streams (reference CudaRNGStatesTracker, random.py:124).

    Functional: ``fork(name)`` returns a fresh key derived from the named
    base key and an internal counter; no global mutation of randomness
    outside the returned keys.
    """

    def __init__(self):
        self.states_: Dict[str, jnp.ndarray] = {}
        self.counts_: Dict[str, int] = {}

    def reset(self):
        self.states_ = {}
        self.counts_ = {}

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name: str, seed):
        if name in self.states_:
            raise Exception(f"rng state {name} already exists")
        if isinstance(seed, (int,)):
            key = jax.random.PRNGKey(seed)
        else:
            key = seed  # already a key (possibly traced, e.g. folded with tp rank)
        self.states_[name] = key
        self.counts_[name] = 0

    def fork(self, name: str = _MODEL_PARALLEL_RNG):
        """Return the next key from the named stream."""
        if name not in self.states_:
            raise Exception(f"rng state {name} is not added")
        k = jax.random.fold_in(self.states_[name], self.counts_[name])
        self.counts_[name] += 1
        return k


_TRACKER = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    """Reference: get_cuda_rng_tracker (random.py:194)."""
    return _TRACKER


# Reference import-name parity ("cuda" kept so Megatron-style code ports
# with a one-line import change).
get_cuda_rng_tracker = get_rng_state_tracker


def model_parallel_seed(seed: int, tp_rank, pp_rank=0):
    """Derive the two Megatron seeds (reference random.py:204
    model_parallel_cuda_manual_seed).

    Returns ``(data_parallel_key, model_parallel_key)``: the first is
    identical across tp ranks, the second decorrelated per tp/pp rank.
    ``tp_rank``/``pp_rank`` may be traced (``jax.lax.axis_index``).
    """
    base = jax.random.PRNGKey(seed)
    dp_key = base
    mp_key = jax.random.fold_in(jax.random.fold_in(base, _TP_OFFSET + 1), tp_rank)
    if pp_rank is not None:
        mp_key = jax.random.fold_in(mp_key, _PP_OFFSET * 1 + pp_rank)
    return dp_key, mp_key


def model_parallel_cuda_manual_seed(seed: int, tp_rank, pp_rank=0) -> None:
    """API-parity wrapper: installs 'default' and the model-parallel
    stream into the global tracker."""
    _TRACKER.reset()
    dp_key, mp_key = model_parallel_seed(seed, tp_rank, pp_rank)
    _TRACKER.add("default", dp_key)
    _TRACKER.add(_MODEL_PARALLEL_RNG, mp_key)


def checkpoint(function, distribute_saved_activations: bool = False, *args):
    """Activation checkpointing (reference random.py:308).

    ``jax.checkpoint`` recomputes the forward during backward; RNG replay
    is automatic because keys are explicit values.
    ``distribute_saved_activations=True`` additionally offloads nothing on
    TPU — XLA decides placement — the flag is accepted for parity.
    """
    return jax.checkpoint(function)(*args)


# ----------------------------------------------------------------------
# Checkpointed-activations memory buffer (reference random.py:44-88 —
# deprecated there, kept for API parity).  On TPU the buffer is a
# host-side planning object: ``jax.checkpoint`` owns what actually gets
# saved, so the value of this API is the *capacity accounting* (how many
# activation elements a schedule would pin) rather than real storage.
_CHECKPOINTED_BUFFER_NAME = "checkpointed activations"


def _checkpointed_buffer():
    """Single source of truth is the _MEM_BUFFS registry (so
    reset_mem_buffs() and this API can never disagree)."""
    from apex_tpu.transformer.tensor_parallel.memory import get_mem_buffs

    return get_mem_buffs().get(_CHECKPOINTED_BUFFER_NAME)


def init_checkpointed_activations_memory_buffer(
    micro_batch_size,
    max_position_embeddings,
    hidden_size,
    num_layers,
    tensor_model_parallel_size,
    checkpoint_num_layers,
    fp16,
):
    """Reference random.py:48-81; same sizing math (seq·mbs·hidden/tp per
    checkpointed layer)."""
    from apex_tpu.transformer.tensor_parallel.memory import allocate_mem_buff

    per_layer = (
        micro_batch_size * max_position_embeddings * hidden_size
        // tensor_model_parallel_size
    )
    if num_layers % checkpoint_num_layers != 0:
        raise ValueError("number of layers is not divisible by checkpoint-num-layers")
    numel = per_layer * (num_layers // checkpoint_num_layers)
    dtype = jnp.float16 if fp16 else jnp.float32

    if _checkpointed_buffer() is not None:
        raise RuntimeError("checkpointed activations memory buffer is already allocated.")
    return allocate_mem_buff(_CHECKPOINTED_BUFFER_NAME, numel, dtype, track_usage=False)


def reset_checkpointed_activations_memory_buffer():
    """Reference random.py:84-88."""
    buf = _checkpointed_buffer()
    if buf is not None:
        buf.reset()
