"""TP utilities (reference: ``apex/transformer/tensor_parallel/utils.py``)."""

from typing import Sequence, Tuple

import jax.numpy as jnp

from apex_tpu.utils.misc import divide


def split_tensor_along_last_dim(tensor, num_partitions: int):
    """Reference: utils.py:17 — static split into a tuple."""
    last = tensor.shape[-1]
    chunk = divide(last, num_partitions)
    return tuple(
        jnp.take(tensor, jnp.arange(i * chunk, (i + 1) * chunk), axis=-1)
        for i in range(num_partitions)
    )


class VocabUtility:
    """Vocab partition arithmetic (reference: utils.py:46)."""

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(
        per_partition_vocab_size: int, rank, world_size: int
    ) -> Tuple[int, int]:
        index_f = rank * per_partition_vocab_size
        return index_f, index_f + per_partition_vocab_size

    @staticmethod
    def vocab_range_from_global_vocab_size(global_vocab_size: int, rank, world_size: int):
        per_partition = divide(global_vocab_size, world_size)
        return VocabUtility.vocab_range_from_per_partition_vocab_size(
            per_partition, rank, world_size
        )
