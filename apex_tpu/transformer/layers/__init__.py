from apex_tpu.transformer.layers.layer_norm import FastLayerNorm, FusedLayerNorm

__all__ = ["FusedLayerNorm", "FastLayerNorm"]
