"""LayerNorm variants that mark params as sequence-parallel.

Reference: ``apex/transformer/layers/layer_norm.py:26-99`` — subclasses
of the fused norms whose single job is setting
``param.sequence_parallel_enabled = True`` so the Megatron trainer knows
these grads need an extra all-reduce over the TP group when SP is on.

In JAX that marking is metadata on the param pytree: flax's
``nn.with_partitioning``/axis metadata, or simply the treepath-based
helper :func:`sequence_parallel_param_mask` used by
:func:`allreduce_sequence_parallel_grads`.
"""

from typing import Sequence

import jax

import apex_tpu.normalization as _norm
from apex_tpu.transformer.parallel_state import TENSOR_AXIS


class FusedLayerNorm(_norm.FusedLayerNorm):
    """LayerNorm whose params are replicated over TP but live outside the
    TP-sharded linears; with SP enabled their grads must be summed over
    the tp axis (reference layer_norm.py:26)."""

    sequence_parallel_enabled: bool = False


# reference layer_norm.py:73 FastLayerNorm = tuned-hidden-size kernels;
# the Pallas/XLA fused norm covers all sizes
class FastLayerNorm(FusedLayerNorm):
    pass


def sequence_parallel_param_mask(params, norm_keywords: Sequence[str] = ("ln", "norm", "layernorm")):
    """Boolean pytree: True for params that need the SP grad allreduce."""

    def is_sp(path):
        p = path.lower()
        return any(k in p for k in norm_keywords)

    flat = jax.tree_util.tree_flatten_with_path(params)
    leaves = [is_sp(jax.tree_util.keystr(kp)) for kp, _ in flat[0]]
    return jax.tree_util.tree_unflatten(flat[1], leaves)


def allreduce_sequence_parallel_grads(grads, mask, axis_name: str = TENSOR_AXIS):
    """Sum SP-marked grads over the tp axis (the trainer-side loop the
    reference expects; see layer_norm.py:26-99 + Megatron's
    allreduce_sequence_parallel_gradients)."""

    def one(g, m):
        return jax.lax.psum(g, axis_name) if m else g

    return jax.tree.map(one, grads, mask)
