"""Expert parallelism: mixture-of-experts FFN over a mesh axis.

**Beyond the reference**: apex has no MoE/expert parallelism (SURVEY
§2.4 "EP: No").  TPU-native design, GShard/Switch style:

- top-k router with capacity-factor token dropping — everything static
  shapes, so the whole layer jits: dispatch/combine are one-hot einsum
  tensors, never data-dependent gathers;
- experts sharded over a mesh axis (``ep_axis``, usually the ``dp``
  axis — "expert parallelism rides data parallelism"): tokens travel to
  their expert's device and back with two ``jax.lax.all_to_all`` over
  ICI, compute runs as batched per-expert matmuls on the MXU;
- auxiliary load-balancing loss (Switch Transformer eq. 4).

Expert weights are *sharded, not replicated*, over ``ep_axis``: each
device computes full gradients for its own experts (the all-to-all
brings every token routed to them), so data-parallel gradient sync must
SKIP expert parameters — :func:`is_expert_param` tells the train step
which ones.
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def moe_init(key, hidden_size: int, ffn_size: int, num_experts: int,
             layers: Optional[int] = None, std: float = 0.02):
    """Router + expert FFN params.  With ``layers``, adds a leading L dim
    (for scan-over-layers models)."""
    k = jax.random.split(key, 3)
    ld = () if layers is None else (layers,)
    init = lambda kk, *s: jax.random.normal(kk, ld + s, jnp.float32) * std
    return {
        "router": init(k[0], hidden_size, num_experts),
        "w1": init(k[1], num_experts, ffn_size, hidden_size),
        "b1": jnp.zeros(ld + (num_experts, ffn_size)),
        "w2": init(k[2], num_experts, hidden_size, ffn_size) / np.sqrt(2.0),
        "b2": jnp.zeros(ld + (num_experts, hidden_size)),
    }


EXPERT_PARAM_KEYS = ("w1", "b1", "w2", "b2")


def is_expert_param(path_keys) -> bool:
    """True for params sharded over the expert axis (their grads are
    device-local and must not be averaged over dp)."""
    names = [getattr(p, "key", getattr(p, "name", None)) for p in path_keys]
    return any(n in EXPERT_PARAM_KEYS for n in names) and any(
        n in ("moe", "experts") for n in names
    )


def _top_k_mask(probs, top_k: int, capacity: int):
    """Static-shape top-k dispatch with capacity dropping.

    probs: (T, E) f32.  Returns (dispatch (T, E, C) one-hot,
    combine (T, E, C) gate-weighted, aux-loss ingredients).
    Slot priority is GShard's: all slot-0 assignments claim capacity
    before any slot-1 assignment."""
    T, E = probs.shape
    masks = []
    p = probs
    for _ in range(top_k):
        idx = jnp.argmax(p, axis=-1)
        m = jax.nn.one_hot(idx, E, dtype=probs.dtype)
        masks.append(m)
        p = p * (1.0 - m)  # knock out the chosen expert for the next slot

    # capacity accounting, slot-major: (K*T, E) running count per expert
    stacked = jnp.concatenate(masks, axis=0)  # (K*T, E)
    pos = jnp.cumsum(stacked, axis=0) - stacked  # tokens ahead of me
    keep = (pos < capacity).astype(probs.dtype) * stacked
    loc = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=probs.dtype)

    dispatch = (keep[..., None] * loc).reshape(len(masks), T, E, capacity).sum(0)
    gate = (probs[None] * jnp.stack(masks)).sum(0)  # (T, E) chosen probs
    if top_k == 1:
        # Switch Transformer: weight by the raw router prob — the output
        # path is what carries the router gradient for top-1
        weights = gate
    else:
        # GShard: renormalize over the chosen experts
        weights = gate / jnp.maximum(gate.sum(-1, keepdims=True), 1e-9)
    combine = dispatch * weights[..., None]
    return dispatch, combine, masks[0]


def load_balancing_loss(probs, mask1):
    """Switch Transformer aux loss: E · Σ_e f_e · P_e (eq. 4)."""
    E = probs.shape[-1]
    f = jnp.mean(mask1, axis=0)  # fraction of tokens per expert (top-1)
    P = jnp.mean(probs, axis=0)  # mean router prob per expert
    return E * jnp.sum(f * P)


def moe_ffn(
    x,
    params,
    *,
    top_k: int = 2,
    capacity_factor: float = 1.25,
    ep_axis: Optional[str] = None,
    activation=partial(jax.nn.gelu, approximate=True),
):
    """MoE FFN.  x: (..., H) — leading dims are flattened to tokens.

    With ``ep_axis`` (inside shard_map): ``params`` hold the LOCAL
    expert shard (E_local = E/ep on the expert dim) and tokens exchange
    over the axis with all_to_all.  Without: dense (all experts local).

    Returns (out, aux_loss).
    """
    orig_shape = x.shape
    H = orig_shape[-1]
    xf = x.reshape(-1, H)
    T = xf.shape[0]

    ep = 1 if ep_axis is None else jax.lax.axis_size(ep_axis)
    E_local = params["w1"].shape[0]
    E = E_local * ep

    logits = jnp.matmul(xf.astype(jnp.float32), params["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)  # (T, E)

    capacity = max(1, int(np.ceil(top_k * capacity_factor * T / E)))
    dispatch, combine, mask1 = _top_k_mask(probs, top_k, capacity)
    aux = load_balancing_loss(probs, mask1)

    cd = x.dtype
    expert_in = jnp.einsum("tec,th->ech", dispatch.astype(cd), xf)  # (E, C, H)

    if ep_axis is not None:
        # (E, C, H) -> (E_local, ep·C, H): expert-major blocks scatter to
        # their owners, received capacity blocks stack source-major
        expert_in = jax.lax.all_to_all(
            expert_in, ep_axis, split_axis=0, concat_axis=1, tiled=True
        )

    h = jnp.einsum("ech,efh->ecf", expert_in, params["w1"].astype(cd))
    h = activation(h + params["b1"].astype(cd)[:, None, :])
    y = jnp.einsum("ecf,ehf->ech", h, params["w2"].astype(cd))
    y = y + params["b2"].astype(cd)[:, None, :]

    if ep_axis is not None:
        # (E_local, ep·C, H) -> (E, C, H): the exact transpose of the way in
        y = jax.lax.all_to_all(y, ep_axis, split_axis=1, concat_axis=0, tiled=True)

    out = jnp.einsum("tec,ech->th", combine.astype(cd), y)
    return out.reshape(orig_shape), aux.astype(jnp.float32)


def moe_param_specs(ep_axis: Optional[str] = "dp", layers: bool = True):
    """PartitionSpecs for :func:`moe_init` params: experts sharded over
    ``ep_axis`` (None = replicated), router replicated."""
    from jax.sharding import PartitionSpec as P

    ld = (None,) if layers else ()
    return {
        "router": P(*ld, None, None),
        "w1": P(*ld, ep_axis, None, None),
        "b1": P(*ld, ep_axis, None),
        "w2": P(*ld, ep_axis, None, None),
        "b2": P(*ld, ep_axis, None),
    }
