"""Microbatch calculators.

Reference: ``apex/transformer/microbatches.py`` —
``build_num_microbatches_calculator`` (:26),
``ConstantNumMicroBatches`` (:93), ``RampupBatchsizeNumMicroBatches``
(:112).  Pure bookkeeping; behavior preserved exactly.
"""

from typing import List, Optional

from apex_tpu.utils.misc import divide


def build_num_microbatches_calculator(
    rank: int,
    rampup_batch_size: Optional[List[int]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
):
    if rampup_batch_size is None:
        calculator = ConstantNumMicroBatches(
            global_batch_size, micro_batch_size, data_parallel_size
        )
        if rank == 0:
            pass  # reference logs here
    else:
        if len(rampup_batch_size) != 3:
            raise ValueError(
                "expected the following format: --rampup-batch-size <start batch size> "
                "<batch size increment> <ramp-up samples>"
            )
        start, incr, samples = map(int, rampup_batch_size)
        calculator = RampupBatchsizeNumMicroBatches(
            start, incr, samples, global_batch_size, micro_batch_size, data_parallel_size
        )
    return calculator


class NumMicroBatchesCalculator:
    def __init__(self):
        self.num_micro_batches = None
        self.current_global_batch_size = None

    def get(self):
        return self.num_micro_batches

    def get_current_global_batch_size(self):
        return self.current_global_batch_size

    def update(self, consumed_samples, consistency_check):
        pass


class ConstantNumMicroBatches(NumMicroBatchesCalculator):
    def __init__(self, global_batch_size, micro_batch_size, data_parallel_size):
        super().__init__()
        micro_batch_times_dp = micro_batch_size * data_parallel_size
        if global_batch_size % micro_batch_times_dp != 0:
            raise ValueError(
                f"global batch size ({global_batch_size}) is not divisible by "
                f"micro batch size ({micro_batch_size}) times data parallel size "
                f"({data_parallel_size})"
            )
        self.num_micro_batches = global_batch_size // micro_batch_times_dp
        assert self.num_micro_batches >= 1
        self.current_global_batch_size = global_batch_size


class RampupBatchsizeNumMicroBatches(NumMicroBatchesCalculator):
    """Linear batch-size rampup (reference :112-178)."""

    def __init__(
        self,
        start_batch_size,
        batch_size_increment,
        ramup_samples,
        global_batch_size,
        micro_batch_size,
        data_parallel_size,
    ):
        super().__init__()
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = micro_batch_size * data_parallel_size
        assert self.micro_batch_times_data_parallel_size > 0

        assert start_batch_size > 0
        self.start_batch_size = start_batch_size
        assert global_batch_size > 0
        self.global_batch_size = global_batch_size
        diff_batch_size = self.global_batch_size - self.start_batch_size
        assert diff_batch_size >= 0
        assert batch_size_increment > 0
        self.batch_size_increment = batch_size_increment
        assert diff_batch_size % batch_size_increment == 0, (
            "expected global batch size interval ({}) to be divisible by global batch "
            "size increment ({})".format(diff_batch_size, batch_size_increment)
        )

        num_increments = diff_batch_size // self.batch_size_increment
        self.ramup_samples = ramup_samples
        assert self.ramup_samples >= 0
        self.rampup_samples_per_increment = self.ramup_samples / num_increments

        self.update(0, False)

    def update(self, consumed_samples, consistency_check):
        if consumed_samples > self.ramup_samples:
            self.current_global_batch_size = self.global_batch_size
        else:
            steps = int(consumed_samples / self.rampup_samples_per_increment)
            self.current_global_batch_size = (
                self.start_batch_size + steps * self.batch_size_increment
            )
            assert self.current_global_batch_size <= self.global_batch_size
        if consistency_check:
            assert (
                self.current_global_batch_size
                % self.micro_batch_times_data_parallel_size
                == 0
            ), (
                "current global batch size ({}) is not divisible by micro-batch-size "
                "({}) times data parallel size ({})".format(
                    self.current_global_batch_size,
                    self.micro_batch_size,
                    self.data_parallel_size,
                )
            )
        self.num_micro_batches = (
            self.current_global_batch_size // self.micro_batch_times_data_parallel_size
        )
