"""Model-parallel-aware grad scaler.

Reference: ``apex/transformer/amp/grad_scaler.py:21-126`` — a
``torch.cuda.amp.GradScaler`` subclass whose only delta is all-reducing
``found_inf`` over the model-parallel group in ``unscale_`` and
``update`` so every TP/PP rank agrees on skipping a step.

Here: :class:`apex_tpu.amp.DynamicLossScaler` with the finite-flag
combined across the model-parallel mesh axes via ``psum`` of the
not-finite indicator (inside shard_map).
"""

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import DynamicLossScaler, ScalerState
from apex_tpu.transformer.parallel_state import PIPELINE_AXIS, TENSOR_AXIS


def sync_found_inf(grads_finite, axis_names: Sequence[str] = (TENSOR_AXIS, PIPELINE_AXIS)):
    """All ranks agree: finite iff finite on EVERY model-parallel rank
    (reference grad_scaler.py:49,102 MAX-allreduce of found_inf)."""
    not_finite = 1.0 - jnp.asarray(grads_finite).astype(jnp.float32)
    for ax in axis_names:
        not_finite = jax.lax.pmax(not_finite, ax)
    return not_finite == 0.0


class GradScaler(DynamicLossScaler):
    """DynamicLossScaler that syncs the finite flag over model-parallel
    axes before unscale/update decisions."""

    def __init__(self, *args, model_parallel_axes: Sequence[str] = (TENSOR_AXIS,), **kw):
        super().__init__(*args, **kw)
        self.model_parallel_axes = tuple(model_parallel_axes)

    def unscale(self, state: ScalerState, grads):
        out, finite = super().unscale(state, grads)
        return out, sync_found_inf(finite, self.model_parallel_axes)

    def update(self, state: ScalerState, all_finite_flag):
        return super().update(state, all_finite_flag)
