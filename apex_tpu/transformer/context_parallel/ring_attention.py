"""Ring attention over the ``cp`` mesh axis (see package docstring)."""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.ops.attention import NEG_INF
from apex_tpu.transformer.parallel_state import CONTEXT_AXIS


def shard_sequence(x, axis_name: str = CONTEXT_AXIS, seq_axis: int = 2):
    """Take this device's sequence chunk (helper for tests/pipelines)."""
    size = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    chunk = x.shape[seq_axis] // size
    return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=seq_axis)


def unshard_sequence(x, axis_name: str = CONTEXT_AXIS, seq_axis: int = 2):
    return jax.lax.all_gather(x, axis_name, axis=seq_axis, tiled=True)


def _block_attend(q, k, v, scale, causal, q_pos, k_pos):
    """One chunk-vs-chunk blockwise attention returning (acc, m, l) in the
    online-softmax accumulator format (unnormalized)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return acc, m, l


def ring_attention(
    q,
    k,
    v,
    axis_name: str = CONTEXT_AXIS,
    causal: bool = True,
    softmax_scale: Optional[float] = None,
):
    """Exact attention with sequence sharded over ``axis_name``.

    q/k/v: local chunks ``(B, H, S_local, D)`` (global position =
    rank * S_local + i).  Runs cp ring steps; each step rotates k/v one
    neighbor backward around the ring so every device eventually sees
    every chunk.  Differentiable (scan + ppermute transpose is the
    reverse ring — the backward pass is itself a ring).
    """
    cp = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    B, H, S, D = q.shape
    scale = softmax_scale if softmax_scale is not None else 1.0 / np.sqrt(D)
    perm = [(i, (i - 1) % cp) for i in range(cp)]  # chunks flow backward

    qf = q.astype(jnp.float32)
    q_pos = rank * S + jnp.arange(S)

    def step(carry, r):
        kc, vc, m, l, acc = carry
        src = (rank + r) % cp  # whose chunk we hold at step r
        k_pos = src * S + jnp.arange(S)
        a, m_b, l_b = _block_attend(qf, kc, vc, scale, causal, q_pos, k_pos)
        m_new = jnp.maximum(m, m_b)
        c_old = jnp.exp(m - m_new)
        c_b = jnp.exp(m_b - m_new)
        l_new = l * c_old + l_b * c_b
        acc_new = acc * c_old[..., None] + a * c_b[..., None]
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (kc, vc, m_new, l_new, acc_new), None

    m0 = jnp.full((B, H, S), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, S), jnp.float32)
    acc0 = jnp.zeros((B, H, S, D), jnp.float32)
    (_, _, m, l, acc), _ = jax.lax.scan(
        step,
        (k.astype(jnp.float32), v.astype(jnp.float32), m0, l0, acc0),
        jnp.arange(cp),
    )
    l = jnp.maximum(l, 1e-30)
    return (acc / l[..., None]).astype(q.dtype)
