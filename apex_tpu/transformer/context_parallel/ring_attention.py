"""Ring attention over the ``cp`` mesh axis (see package docstring).

Flash-style memory discipline end to end: the forward ring carries the
normalized ``(out, logsumexp)`` pair and merges chunk results with the
online-softmax rule; the backward pass is its own ring (``custom_vjp``)
that recomputes per-chunk-pair scores from the saved ``(q, k, v, out,
lse)`` — no probability matrices are ever saved across steps, so
activation memory is O(S_local) regardless of the global sequence.

Causality across devices reduces each chunk pair to one of three static
cases — fully visible (src < rank), diagonal-triangular (src == rank),
fully masked (src > rank) — selected with ``lax.switch``, so the masked
case costs nothing and the other two run with *static* zero offsets,
which lets the per-pair math dispatch onto the Pallas flash kernels
(:mod:`apex_tpu.ops.flash_attention_pallas`) on TPU.  The ``lax.scan``
composite remains the universal fallback and numerics oracle.
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.ops.attention import NEG_INF, _attend_fwd_scan, flash_bwd_from_lse
from apex_tpu.transformer.parallel_state import CONTEXT_AXIS


def shard_sequence(x, axis_name: str = CONTEXT_AXIS, seq_axis: int = 2):
    """Take this device's sequence chunk (helper for tests/pipelines)."""
    size = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    chunk = x.shape[seq_axis] // size
    return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=seq_axis)


def unshard_sequence(x, axis_name: str = CONTEXT_AXIS, seq_axis: int = 2):
    return jax.lax.all_gather(x, axis_name, axis=seq_axis, tiled=True)


def _use_pallas(q, k, impl: str) -> bool:
    if impl == "scan":
        return False
    if impl == "pallas":
        return True
    from apex_tpu.ops.flash_attention_pallas import pallas_flash_available

    return pallas_flash_available(q, k)


def _chunk_fwd(q, k, v, scale, causal, impl, interpret):
    """(out f32, lse f32 (B,H,S)) for one chunk pair, zero offsets."""
    B, H, S, D = q.shape
    if _use_pallas(q, k, impl):
        from apex_tpu.ops.flash_attention_pallas import flash_fwd_pallas

        out, lse = flash_fwd_pallas(
            q.reshape(B * H, S, D), k.reshape(B * H, k.shape[2], D),
            v.reshape(B * H, v.shape[2], D), scale, causal, 0, 0,
            interpret=interpret, out_dtype=jnp.float32,
        )
        return out.reshape(B, H, S, D), lse.reshape(B, H, S)
    return _attend_fwd_scan(q, k, v, scale, causal, 0, 0, block_k=256)


def _chunk_bwd(q, k, v, do, lse, delta, scale, causal, impl, interpret):
    """Per-chunk-pair flash backward from global (lse, delta); f32 outputs
    so ring accumulation never rounds through bf16."""
    B, H, S, D = q.shape
    if _use_pallas(q, k, impl):
        from apex_tpu.ops.flash_attention_pallas import flash_bwd_pallas

        dq, dk, dv = flash_bwd_pallas(
            q.reshape(B * H, S, D), k.reshape(B * H, k.shape[2], D),
            v.reshape(B * H, v.shape[2], D), None,
            lse.reshape(B * H, S, 1),
            # keep the cross-chunk cotangent f32: the kernel widens v to
            # match rather than rounding do through bf16
            do.reshape(B * H, S, D).astype(jnp.float32),
            scale, causal, 0, 0, interpret=interpret,
            delta=delta.reshape(B * H, S, 1), out_dtype=jnp.float32,
        )
        shp = (B, H, S, D)
        return dq.reshape(shp), dk.reshape(shp), dv.reshape(shp)
    return flash_bwd_from_lse(q, k, v, do, lse, delta, scale, causal)


def _merge(out, lse, out_b, lse_b):
    """Online-softmax merge of two normalized partials."""
    lse_new = jnp.logaddexp(lse, lse_b)
    w_old = jnp.exp(lse - lse_new)[..., None]
    w_b = jnp.exp(lse_b - lse_new)[..., None]
    return out * w_old + out_b * w_b, lse_new


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring(q, k, v, axis_name, causal, scale, impl, interpret):
    out, _ = _ring_fwd_pass(q, k, v, axis_name, causal, scale, impl, interpret)
    return out.astype(q.dtype)


def _ring_fwd_pass(q, k, v, axis_name, causal, scale, impl, interpret):
    cp = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    B, H, S, D = q.shape
    perm = [(i, (i - 1) % cp) for i in range(cp)]  # chunks flow backward

    def full_case(kc, vc):
        return _chunk_fwd(q, kc, vc, scale, False, impl, interpret)

    def diag_case(kc, vc):
        return _chunk_fwd(q, kc, vc, scale, True, impl, interpret)

    def masked_case(kc, vc):
        return (jnp.zeros((B, H, S, D), jnp.float32),
                jnp.full((B, H, S), NEG_INF, jnp.float32))

    def step(carry, r):
        kc, vc, out, lse = carry
        src = (rank + r) % cp  # whose chunk we hold at step r
        if causal:
            # 0: src < rank (full), 1: src == rank (diag), 2: masked
            case = jnp.clip(jnp.sign(src - rank) + 1, 0, 2)
            out_b, lse_b = jax.lax.switch(
                case, (full_case, diag_case, masked_case), kc, vc
            )
        else:
            out_b, lse_b = full_case(kc, vc)
        out, lse = _merge(out, lse, out_b, lse_b)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (kc, vc, out, lse), None

    out0 = jnp.zeros((B, H, S, D), jnp.float32)
    lse0 = jnp.full((B, H, S), NEG_INF, jnp.float32)
    (_, _, out, lse), _ = jax.lax.scan(step, (k, v, out0, lse0), jnp.arange(cp))
    return out, lse


def _ring_vjp_fwd(q, k, v, axis_name, causal, scale, impl, interpret):
    out, lse = _ring_fwd_pass(q, k, v, axis_name, causal, scale, impl, interpret)
    return out.astype(q.dtype), (q, k, v, out, lse)


def _ring_vjp_bwd(axis_name, causal, scale, impl, interpret, res, g):
    """The backward ring: q/do/lse/delta stay home; (k, v, dk, dv)
    travel the ring and arrive home after cp steps with every device's
    contribution accumulated."""
    q, k, v, out, lse = res
    cp = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    B, H, S, D = q.shape
    perm = [(i, (i - 1) % cp) for i in range(cp)]

    do = g.astype(jnp.float32)
    delta = jnp.sum(do * out, axis=-1)  # global-row rowsum(dO·O)

    def full_case(kc, vc):
        return _chunk_bwd(q, kc, vc, do, lse, delta, scale, False, impl, interpret)

    def diag_case(kc, vc):
        return _chunk_bwd(q, kc, vc, do, lse, delta, scale, True, impl, interpret)

    def masked_case(kc, vc):
        z = jnp.zeros((B, H, S, D), jnp.float32)
        return z, z, z

    def step(carry, r):
        kc, vc, dk_acc, dv_acc, dq_acc = carry
        src = (rank + r) % cp
        if causal:
            case = jnp.clip(jnp.sign(src - rank) + 1, 0, 2)
            dq_b, dk_b, dv_b = jax.lax.switch(
                case, (full_case, diag_case, masked_case), kc, vc
            )
        else:
            dq_b, dk_b, dv_b = full_case(kc, vc)
        dq_acc = dq_acc + dq_b
        dk_acc = dk_acc + dk_b
        dv_acc = dv_acc + dv_b
        kc, vc, dk_acc, dv_acc = (
            jax.lax.ppermute(t, axis_name, perm) for t in (kc, vc, dk_acc, dv_acc)
        )
        return (kc, vc, dk_acc, dv_acc, dq_acc), None

    z = jnp.zeros((B, H, S, D), jnp.float32)
    (_, _, dk, dv, dq), _ = jax.lax.scan(
        step, (k, v, z, z, z), jnp.arange(cp)
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def ring_attention(
    q,
    k,
    v,
    axis_name: str = CONTEXT_AXIS,
    causal: bool = True,
    softmax_scale: Optional[float] = None,
    impl: str = "auto",
    interpret: bool = False,
):
    """Exact attention with sequence sharded over ``axis_name``.

    q/k/v: local chunks ``(B, H, S_local, D)`` (global position =
    rank * S_local + i).  Call inside ``shard_map``.  Differentiable:
    the backward pass is its own ring (dk/dv accumulate while circling
    home), so per-device grads of a local loss shard sum to the
    total-loss gradient.

    ``impl``: "pallas" / "scan" / "auto" (Pallas kernels per chunk pair
    on TPU when shapes allow).
    """
    if impl not in ("auto", "pallas", "scan"):
        raise ValueError(f"impl must be 'auto', 'pallas', or 'scan'; got {impl!r}")
    scale = softmax_scale if softmax_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    return _ring(q, k, v, axis_name, causal, scale, impl, interpret)
