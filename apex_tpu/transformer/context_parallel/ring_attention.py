"""Ring attention over the ``cp`` mesh axis (see package docstring).

Flash-style memory discipline end to end: the forward ring carries the
normalized ``(out, logsumexp)`` pair and merges chunk results with the
online-softmax rule; the backward pass is its own ring (``custom_vjp``)
that recomputes per-chunk-pair scores from the saved ``(q, k, v, out,
lse)`` — no probability matrices are ever saved across steps, so
activation memory is O(S_local) regardless of the global sequence.

Causality across devices reduces each chunk pair to one of three static
cases — fully visible (src < rank), diagonal-triangular (src == rank),
fully masked (src > rank) — selected with ``lax.switch``, so the masked
case costs nothing and the other two run with *static* zero offsets,
which lets the per-pair math dispatch onto the Pallas flash kernels
(:mod:`apex_tpu.ops.flash_attention_pallas`) on TPU.  The ``lax.scan``
composite remains the universal fallback and numerics oracle.
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.ops.attention import NEG_INF, _attend_fwd_scan, flash_bwd_from_lse
from apex_tpu.transformer.parallel_state import CONTEXT_AXIS


def shard_sequence(x, axis_name: str = CONTEXT_AXIS, seq_axis: int = 2):
    """Take this device's sequence chunk (helper for tests/pipelines)."""
    size = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    chunk = x.shape[seq_axis] // size
    return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=seq_axis)


def unshard_sequence(x, axis_name: str = CONTEXT_AXIS, seq_axis: int = 2):
    return jax.lax.all_gather(x, axis_name, axis=seq_axis, tiled=True)


def _use_pallas(q, k, impl: str) -> bool:
    if impl == "scan":
        return False
    if impl == "pallas":
        return True
    from apex_tpu.ops.flash_attention_pallas import pallas_flash_available

    return pallas_flash_available(q, k)


def _scan_block_k(S, D, dtype):
    """k-block for the scan-composite chunk path: the chunk shape's
    tuned ``"fwd"`` entry when a sweep installed one (cp runs must not
    ignore measured defaults), else 16 sublane tiles of the dtype —
    256 for bf16, 128 for fp32 — the memory/step tradeoff the old
    hard-coded 256 encoded for bf16 only."""
    from apex_tpu.ops._pallas_tiling import sublane
    from apex_tpu.ops.flash_attention_pallas import tuned_blocks

    tuned = tuned_blocks(S, D, dtype, phase="fwd")
    if tuned is not None:
        return tuned[1]
    return 16 * sublane(dtype)


# Chunk math as one jitted op when the surrounding program runs
# op-by-op under jax.disable_jit() (the pallas path gets this for free
# from pallas_call's own jit): a chunk is the ring's atomic unit — the
# schedule property disable_jit() exists to pin here is the RING-level
# op order, and the jit cache makes every identically-shaped chunk
# reuse one compiled program, deterministic across both schedules.
# Under normal tracing the inline path below is taken — the traced
# program is byte-identical to pre-wrapper builds.
@partial(jax.jit, static_argnums=(3, 4, 5))
def _scan_chunk_fwd_jit(q, k, v, scale, causal, block_k):
    return _attend_fwd_scan(q, k, v, scale, causal, 0, 0, block_k=block_k)


@partial(jax.jit, static_argnums=(6, 7))
def _scan_chunk_bwd_jit(q, k, v, do, lse, delta, scale, causal):
    return flash_bwd_from_lse(q, k, v, do, lse, delta, scale, causal)


def _chunk_fwd(q, k, v, scale, causal, impl, interpret):
    """(out f32, lse f32 (B,H,S)) for one chunk pair, zero offsets."""
    B, H, S, D = q.shape
    if _use_pallas(q, k, impl):
        from apex_tpu.ops.flash_attention_pallas import flash_fwd_pallas

        out, lse = flash_fwd_pallas(
            q.reshape(B * H, S, D), k.reshape(B * H, k.shape[2], D),
            v.reshape(B * H, v.shape[2], D), scale, causal, 0, 0,
            interpret=interpret, out_dtype=jnp.float32,
        )
        return out.reshape(B, H, S, D), lse.reshape(B, H, S)
    block_k = _scan_block_k(S, D, q.dtype)  # resolved OUTSIDE the jit
    if jax.config.jax_disable_jit:
        with jax.disable_jit(False):
            return _scan_chunk_fwd_jit(q, k, v, scale, causal, block_k)
    return _attend_fwd_scan(q, k, v, scale, causal, 0, 0, block_k=block_k)


def _chunk_bwd(q, k, v, do, lse, delta, scale, causal, impl, interpret):
    """Per-chunk-pair flash backward from global (lse, delta); f32 outputs
    so ring accumulation never rounds through bf16."""
    B, H, S, D = q.shape
    if _use_pallas(q, k, impl):
        from apex_tpu.ops.flash_attention_pallas import flash_bwd_pallas

        dq, dk, dv = flash_bwd_pallas(
            q.reshape(B * H, S, D), k.reshape(B * H, k.shape[2], D),
            v.reshape(B * H, v.shape[2], D), None,
            lse.reshape(B * H, S, 1),
            # keep the cross-chunk cotangent f32: the kernel widens v to
            # match rather than rounding do through bf16
            do.reshape(B * H, S, D).astype(jnp.float32),
            scale, causal, 0, 0, interpret=interpret,
            delta=delta.reshape(B * H, S, 1), out_dtype=jnp.float32,
        )
        shp = (B, H, S, D)
        return dq.reshape(shp), dk.reshape(shp), dv.reshape(shp)
    if jax.config.jax_disable_jit:
        with jax.disable_jit(False):
            return _scan_chunk_bwd_jit(q, k, v, do, lse, delta, scale, causal)
    return flash_bwd_from_lse(q, k, v, do, lse, delta, scale, causal)


def _merge(out, lse, out_b, lse_b):
    """Online-softmax merge of two normalized partials."""
    lse_new = jnp.logaddexp(lse, lse_b)
    w_old = jnp.exp(lse - lse_new)[..., None]
    w_b = jnp.exp(lse_b - lse_new)[..., None]
    return out * w_old + out_b * w_b, lse_new


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring(q, k, v, axis_name, causal, scale, impl, interpret, overlap):
    out, _ = _ring_fwd_pass(q, k, v, axis_name, causal, scale, impl,
                            interpret, overlap)
    return out.astype(q.dtype)


def _ring_fwd_pass(q, k, v, axis_name, causal, scale, impl, interpret,
                   overlap=False):
    cp = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    B, H, S, D = q.shape
    perm = [(i, (i - 1) % cp) for i in range(cp)]  # chunks flow backward

    def full_case(kc, vc):
        return _chunk_fwd(q, kc, vc, scale, False, impl, interpret)

    def diag_case(kc, vc):
        return _chunk_fwd(q, kc, vc, scale, True, impl, interpret)

    def masked_case(kc, vc):
        return (jnp.zeros((B, H, S, D), jnp.float32),
                jnp.full((B, H, S), NEG_INF, jnp.float32))

    def chunk(kc, vc, r):
        src = (rank + r) % cp  # whose chunk we hold at step r
        if causal:
            # 0: src < rank (full), 1: src == rank (diag), 2: masked
            case = jnp.clip(jnp.sign(src - rank) + 1, 0, 2)
            return jax.lax.switch(
                case, (full_case, diag_case, masked_case), kc, vc
            )
        return full_case(kc, vc)

    out0 = jnp.zeros((B, H, S, D), jnp.float32)
    lse0 = jnp.full((B, H, S), NEG_INF, jnp.float32)

    if not overlap:
        def step(carry, r):
            kc, vc, out, lse = carry
            out_b, lse_b = chunk(kc, vc, r)
            out, lse = _merge(out, lse, out_b, lse_b)
            kc = jax.lax.ppermute(kc, axis_name, perm)
            vc = jax.lax.ppermute(vc, axis_name, perm)
            return (kc, vc, out, lse), None

        (_, _, out, lse), _ = jax.lax.scan(
            step, (k, v, out0, lse0), jnp.arange(cp))
        return out, lse

    # Overlapped: the ring unrolls (cp is static) and hop r+1's ppermute
    # issues BEFORE chunk r's compute, so XLA's latency-hiding scheduler
    # can run the ICI hop behind the per-chunk flash kernels — the
    # classic double-buffered ring.  The compute consumes the SAME
    # values in the SAME merge order as the scan path (the permute only
    # moves data; r promotes to the same int32 arithmetic), so fp32
    # out/lse are bitwise equal.  The final hop's rotation — whose
    # result the scan discards — is skipped entirely.
    kc, vc, out, lse = k, v, out0, lse0
    for r in range(cp):
        if r + 1 < cp:
            kn = jax.lax.ppermute(kc, axis_name, perm)
            vn = jax.lax.ppermute(vc, axis_name, perm)
        out_b, lse_b = chunk(kc, vc, r)
        out, lse = _merge(out, lse, out_b, lse_b)
        if r + 1 < cp:
            kc, vc = kn, vn
    return out, lse


def _ring_vjp_fwd(q, k, v, axis_name, causal, scale, impl, interpret, overlap):
    out, lse = _ring_fwd_pass(q, k, v, axis_name, causal, scale, impl,
                              interpret, overlap)
    return out.astype(q.dtype), (q, k, v, out, lse)


def _ring_vjp_bwd(axis_name, causal, scale, impl, interpret, overlap, res, g):
    """The backward ring: q/do/lse/delta stay home; (k, v, dk, dv)
    travel the ring and arrive home after cp steps with every device's
    contribution accumulated.  With ``overlap`` the ring unrolls:
    hop r+1's (k, v) rotation issues before chunk r's compute, and the
    dk/dv accumulators rotate AFTER chunk r accumulates into them (a
    data dependency — but their hop is then in flight during chunk
    r+1's compute).  All cp accumulator rotations are required either
    way: each moves the accumulator one hop toward home."""
    q, k, v, out, lse = res
    cp = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    B, H, S, D = q.shape
    perm = [(i, (i - 1) % cp) for i in range(cp)]

    do = g.astype(jnp.float32)
    delta = jnp.sum(do * out, axis=-1)  # global-row rowsum(dO·O)

    def full_case(kc, vc):
        return _chunk_bwd(q, kc, vc, do, lse, delta, scale, False, impl, interpret)

    def diag_case(kc, vc):
        return _chunk_bwd(q, kc, vc, do, lse, delta, scale, True, impl, interpret)

    def masked_case(kc, vc):
        z = jnp.zeros((B, H, S, D), jnp.float32)
        return z, z, z

    def chunk(kc, vc, r):
        src = (rank + r) % cp
        if causal:
            case = jnp.clip(jnp.sign(src - rank) + 1, 0, 2)
            return jax.lax.switch(
                case, (full_case, diag_case, masked_case), kc, vc
            )
        return full_case(kc, vc)

    z = jnp.zeros((B, H, S, D), jnp.float32)

    if not overlap:
        def step(carry, r):
            kc, vc, dk_acc, dv_acc, dq_acc = carry
            dq_b, dk_b, dv_b = chunk(kc, vc, r)
            dq_acc = dq_acc + dq_b
            dk_acc = dk_acc + dk_b
            dv_acc = dv_acc + dv_b
            kc, vc, dk_acc, dv_acc = (
                jax.lax.ppermute(t, axis_name, perm)
                for t in (kc, vc, dk_acc, dv_acc)
            )
            return (kc, vc, dk_acc, dv_acc, dq_acc), None

        (_, _, dk, dv, dq), _ = jax.lax.scan(
            step, (k, v, z, z, z), jnp.arange(cp)
        )
        return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)

    kc, vc = k, v
    dk_acc = dv_acc = dq_acc = z
    for r in range(cp):
        if r + 1 < cp:  # k/v double buffer: next hop rides under chunk r
            kn = jax.lax.ppermute(kc, axis_name, perm)
            vn = jax.lax.ppermute(vc, axis_name, perm)
        dq_b, dk_b, dv_b = chunk(kc, vc, r)
        dq_acc = dq_acc + dq_b
        dk_acc = dk_acc + dk_b
        dv_acc = dv_acc + dv_b
        dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
        if r + 1 < cp:
            kc, vc = kn, vn
    return (dq_acc.astype(q.dtype), dk_acc.astype(k.dtype),
            dv_acc.astype(v.dtype))


_ring.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def ring_attention(
    q,
    k,
    v,
    axis_name: str = CONTEXT_AXIS,
    causal: bool = True,
    softmax_scale: Optional[float] = None,
    impl: str = "auto",
    interpret: bool = False,
    overlap: bool = False,
):
    """Exact attention with sequence sharded over ``axis_name``.

    q/k/v: local chunks ``(B, H, S_local, D)`` (global position =
    rank * S_local + i).  Call inside ``shard_map``.  Differentiable:
    the backward pass is its own ring (dk/dv accumulate while circling
    home), so per-device grads of a local loss shard sum to the
    total-loss gradient.

    ``impl``: "pallas" / "scan" / "auto" (Pallas kernels per chunk pair
    on TPU when shapes allow).

    ``overlap``: unroll the ring and issue hop r+1's ``ppermute``
    before chunk r's compute (fwd AND bwd), double-buffering the
    rotating k/v so the ICI hop hides behind the per-chunk kernels.
    Same chunk order, same merge order, same values — fp32 outputs and
    grads are BITWISE equal to the serial schedule; flip it per run to
    A/B the overlap (default off: the serial ``lax.scan`` compiles a
    cp-independent program body).
    """
    if impl not in ("auto", "pallas", "scan"):
        raise ValueError(f"impl must be 'auto', 'pallas', or 'scan'; got {impl!r}")
    scale = softmax_scale if softmax_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    return _ring(q, k, v, axis_name, causal, scale, impl, interpret,
                 bool(overlap))
