"""Context parallelism: ring attention over a mesh axis.

The reference has **no** sequence-length scaling beyond Megatron SP
(SURVEY §2.4: "CP/ring-attention/Ulysses: No").  Long-context is
first-class here: the sequence is sharded over the ``cp`` mesh axis and
attention runs as a **ring** — each step every device computes blockwise
attention of its local queries against the currently-held k/v chunk,
then rotates k/v one neighbor over ICI with ``ppermute`` — overlapping
the ICI transfer of the next chunk with the current block's matmuls (the
TPU analog of ring-attention's compute/comm overlap).  Partial results
merge with the online-softmax (out, logsumexp) rule, so the math is
exactly full attention.

Causality across devices falls out of global position offsets: chunk j
attending from query chunk i is fully masked when j > i, fully visible
when j < i, and triangular when i == j.
"""

from apex_tpu.transformer.context_parallel.ring_attention import (
    ring_attention,
    shard_sequence,
    unshard_sequence,
)

__all__ = ["ring_attention", "shard_sequence", "unshard_sequence"]
