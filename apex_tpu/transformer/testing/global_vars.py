"""Megatron-style global variables for the test harness.

Reference: ``apex/transformer/testing/global_vars.py`` —
``set_global_variables`` parses args once and installs process-global
args / microbatch calculator / timers / tensorboard writer, read back by
``get_args()`` etc.  Test-harness-only state (the library itself is
functional); kept process-global here for the same reason the reference
does it: Megatron-style training scripts expect these accessors.
"""

from typing import Optional

from apex_tpu.transformer.pipeline_parallel import utils as _pp_utils
from apex_tpu.transformer.testing.arguments import parse_args

_GLOBAL_ARGS = None
_GLOBAL_TENSORBOARD_WRITER = None
_GLOBAL_AUTORESUME = None
_GLOBAL_TIMERS = None


def _ensure_var_is_initialized(var, name):
    if var is None:
        raise AssertionError(f"{name} is not initialized.")


def _ensure_var_is_not_initialized(var, name):
    if var is not None:
        raise AssertionError(f"{name} is already initialized.")


def get_args():
    """Reference: global_vars.py:34."""
    _ensure_var_is_initialized(_GLOBAL_ARGS, "args")
    return _GLOBAL_ARGS


# The calculator lives in pipeline_parallel.utils (the module the
# pipeline schedules read); these accessors delegate so both views agree.
def _calculator():
    calc = _pp_utils._GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _ensure_var_is_initialized(calc, "num microbatches calculator")
    return calc


def get_num_microbatches() -> int:
    return _calculator().get()


def get_current_global_batch_size() -> int:
    return _calculator().get_current_global_batch_size()


def update_num_microbatches(consumed_samples: int, *, consistency_check: bool = True) -> None:
    _calculator().update(consumed_samples, consistency_check)


def get_tensorboard_writer():
    """May be None (reference global_vars.py:69)."""
    return _GLOBAL_TENSORBOARD_WRITER


def get_adlr_autoresume():
    """Always None on TPU — no ADLR cluster (reference global_vars.py:75)."""
    return _GLOBAL_AUTORESUME


def get_timers():
    _ensure_var_is_initialized(_GLOBAL_TIMERS, "timers")
    return _GLOBAL_TIMERS


def set_global_variables(extra_args_provider=None, args_defaults=None,
                         override_args=None, ignore_unknown_args=False,
                         args=None):
    """Parse args and install all globals (reference global_vars.py:87)."""
    global _GLOBAL_ARGS, _GLOBAL_TIMERS
    _ensure_var_is_not_initialized(_GLOBAL_ARGS, "args")
    _GLOBAL_ARGS = parse_args(
        extra_args_provider=extra_args_provider,
        defaults=args_defaults or {},
        override_args=override_args or {},
        ignore_unknown_args=ignore_unknown_args,
        args=args,
    )
    if _GLOBAL_ARGS.micro_batch_size is not None:
        # Install where the pipeline schedules read it (reference
        # global_vars.py:95 builds the one calculator the whole process
        # shares via pipeline_parallel.utils).
        _pp_utils.setup_microbatch_calculator(
            rank=_GLOBAL_ARGS.rank,
            rampup_batch_size=_GLOBAL_ARGS.rampup_batch_size,
            global_batch_size=_GLOBAL_ARGS.global_batch_size,
            micro_batch_size=_GLOBAL_ARGS.micro_batch_size,
            data_parallel_size=_GLOBAL_ARGS.data_parallel_size,
        )
    _GLOBAL_TIMERS = _pp_utils.get_timers()
    return _GLOBAL_ARGS


def destroy_global_vars():
    """Reset for test isolation (no reference analog; their process dies)."""
    global _GLOBAL_ARGS
    global _GLOBAL_TENSORBOARD_WRITER, _GLOBAL_AUTORESUME, _GLOBAL_TIMERS
    _GLOBAL_ARGS = None
    _pp_utils.destroy_num_microbatches_calculator()
    _GLOBAL_TENSORBOARD_WRITER = None
    _GLOBAL_AUTORESUME = None
    _GLOBAL_TIMERS = None
