"""Common distributed-test helpers.

Reference: ``apex/transformer/testing/commons.py`` (toy models,
``fwd_step_func``) and ``distributed_test_base.py:22-96``
(``DistributedTestBase`` spawning NCCL/UCC process groups).

TPU: no processes to spawn — a ``Mesh`` over the virtual CPU devices is
the "cluster".  ``DistributedTestContext`` mirrors the setup/teardown
shape of the reference base class for tests that want parallel_state
initialized.
"""

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from apex_tpu.transformer import parallel_state


def make_mesh(axis_sizes: dict, devices: Optional[Sequence] = None) -> Mesh:
    """Mesh from {"axis": size} in the given order."""
    devs = list(devices) if devices is not None else jax.devices()
    names = tuple(axis_sizes)
    shape = tuple(axis_sizes[n] for n in names)
    n = int(np.prod(shape))
    return Mesh(np.array(devs[:n]).reshape(shape), names)


def smap(mesh, f, in_specs, out_specs):
    """shard_map with check_vma=False (custom_vjp collectives hide
    replication info from the static checker)."""
    return jax.shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False)


class DistributedTestContext:
    """``with DistributedTestContext(tp=2, pp=2): ...`` — initializes and
    tears down parallel_state around a test (the reference's
    setUp/tearDown, distributed_test_base.py:40-77)."""

    def __init__(self, tp: int = 1, pp: int = 1, cp: int = 1, devices=None,
                 slices: int = 1, split_rank=None):
        self.tp, self.pp, self.cp = tp, pp, cp
        self.devices = devices
        self.slices = slices
        self.split_rank = split_rank
        self.mesh = None

    def __enter__(self):
        self.mesh = parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=self.tp,
            pipeline_model_parallel_size_=self.pp,
            context_parallel_size_=self.cp,
            pipeline_model_parallel_split_rank_=self.split_rank,
            devices=self.devices,
            num_distributed_slices_=self.slices,
        )
        return self

    def __exit__(self, *exc):
        parallel_state.destroy_model_parallel()
        return False


def toy_stage_fn(stage_params, x):
    """Stacked tanh layers — the toy pipeline stage used in schedule
    tests (reference commons.py toy models)."""

    def body(carry, lp):
        return jnp.tanh(carry @ lp["w"] + lp["b"]), None

    out, _ = jax.lax.scan(body, x, stage_params)
    return out
