"""Distributed test base classes.

Reference: ``apex/transformer/testing/distributed_test_base.py:22-96`` —
``DistributedTestBase`` spawns ``world_size`` processes with file-store
rendezvous (``MultiProcessTestCase``), with NCCL and UCC subclasses.

TPU redesign: multi-device correctness is tested on one process against
a virtual device mesh (``--xla_force_host_platform_device_count``),
comparing shard_map-parallel runs with a single-device oracle — the same
parallel-vs-oracle pattern the reference uses, minus process spawning
(which tests the transport, not the math; XLA's collectives are the
transport here).  ``world_size``/``DISTRIBUTED_BACKEND`` attributes are
kept so reference-style test bodies port over unchanged.
"""

import unittest

import jax

from apex_tpu.transformer.testing.commons import DistributedTestContext


class DistributedTestBase(unittest.TestCase):
    """Per-test mesh lifecycle (reference distributed_test_base.py:22).

    Subclasses set ``TP``/``PP``/``CP`` (defaults 1) — the analog of the
    reference's world_size carve-up; remaining devices become ``dp``.
    """

    DISTRIBUTED_BACKEND = "xla"
    TP = 1
    PP = 1
    CP = 1

    @property
    def world_size(self) -> int:
        return jax.device_count()

    def setUp(self):
        super().setUp()
        self._ctx = DistributedTestContext(tp=self.TP, pp=self.PP, cp=self.CP)
        self.mesh = self._ctx.__enter__().mesh

    def tearDown(self):
        self._ctx.__exit__(None, None, None)
        super().tearDown()


class XlaDistributedTestBase(DistributedTestBase):
    """Name parity with NcclDistributedTestBase (:80) — XLA collectives
    are the only backend on TPU, so there is exactly one subclass."""

    DISTRIBUTED_BACKEND = "xla"


# The reference parametrizes NCCL vs UCC; both map to XLA here.
NcclDistributedTestBase = XlaDistributedTestBase
UccDistributedTestBase = XlaDistributedTestBase
