"""Test scaffolding (reference: ``apex/transformer/testing``).

The reference ships standalone Megatron GPT/BERT clones
(``standalone_gpt.py``/``standalone_bert.py``) and multiprocess test
bases (``distributed_test_base.py``).  Here the standalone models ARE
the library models, and the distributed base is a mesh helper: JAX's
virtual multi-device CPU platform replaces process spawning.
"""

from apex_tpu.transformer.testing import arguments, global_vars
from apex_tpu.transformer.testing.commons import (
    DistributedTestContext,
    make_mesh,
    smap,
    toy_stage_fn,
)
from apex_tpu.transformer.testing.distributed_test_base import (
    DistributedTestBase,
    NcclDistributedTestBase,
    UccDistributedTestBase,
    XlaDistributedTestBase,
)
from apex_tpu.models import bert as standalone_bert
from apex_tpu.models import gpt as standalone_gpt

__all__ = [
    "arguments",
    "global_vars",
    "DistributedTestContext",
    "DistributedTestBase",
    "XlaDistributedTestBase",
    "NcclDistributedTestBase",
    "UccDistributedTestBase",
    "make_mesh",
    "smap",
    "toy_stage_fn",
    "standalone_gpt",
    "standalone_bert",
]
