"""Megatron-style argument parser for the test/benchmark harness.

Reference: ``apex/transformer/testing/arguments.py`` (977 LoC argparse
clone of Megatron-LM's ``parse_args``).  That parser exists only so the
standalone GPT/BERT test models and the pipeline tests can be configured
the Megatron way; this is the TPU port of the same contract — the core
argument groups, the derived-value logic (ffn size, kv channels,
consistency checks), and the same flag spellings — sized to what the
apex test-suite actually reads rather than all 188 flags.

GPU-only flags that have no TPU meaning (``--no-gradient-accumulation-
fusion``, NCCL/IB toggles, ...) are accepted and ignored so Megatron
launch scripts parse unchanged.
"""

import argparse
import os


def parse_args(extra_args_provider=None, defaults=None, override_args=None,
               ignore_unknown_args=False, args=None):
    """Parse Megatron-style flags (reference arguments.py:30 parse_args).

    ``args`` (list of strings) defaults to an empty list — tests build
    configs programmatically; pass ``sys.argv[1:]`` for CLI use.
    """
    parser = argparse.ArgumentParser(description="apex_tpu arguments",
                                     allow_abbrev=False)
    _add_network_size_args(parser)
    _add_regularization_args(parser)
    _add_training_args(parser)
    _add_learning_rate_args(parser)
    _add_mixed_precision_args(parser)
    _add_distributed_args(parser)
    _add_validation_args(parser)
    _add_data_args(parser)
    _add_logging_args(parser)

    if extra_args_provider is not None:
        parser = extra_args_provider(parser)

    if args is None:
        args = []
    if ignore_unknown_args:
        parsed, _ = parser.parse_known_args(args)
    else:
        parsed = parser.parse_args(args)

    for key, value in (defaults or {}).items():
        if getattr(parsed, key, None) is None:
            setattr(parsed, key, value)
    for key, value in (override_args or {}).items():
        setattr(parsed, key, value)

    return validate_args(parsed)


def validate_args(args):
    """Derived values + consistency checks (reference arguments.py:160)."""
    # world-size bookkeeping: on TPU "rank"/"world size" are device counts.
    if args.world_size is None:
        args.world_size = int(os.environ.get("WORLD_SIZE", "1"))
    model_parallel = (
        args.tensor_model_parallel_size
        * args.pipeline_model_parallel_size
        * getattr(args, "context_parallel_size", 1)
    )
    if args.world_size % model_parallel != 0:
        raise ValueError(
            f"world size {args.world_size} not divisible by tp*pp*cp {model_parallel}"
        )
    args.data_parallel_size = args.world_size // model_parallel
    if args.ffn_hidden_size is None and args.hidden_size is not None:
        args.ffn_hidden_size = 4 * args.hidden_size
    if args.kv_channels is None and args.hidden_size is not None:
        if args.num_attention_heads:
            args.kv_channels = args.hidden_size // args.num_attention_heads
    if args.global_batch_size is None and args.micro_batch_size is not None:
        args.global_batch_size = args.micro_batch_size * args.data_parallel_size
    if args.fp16 and args.bf16:
        raise ValueError("--fp16 and --bf16 are mutually exclusive")
    args.params_dtype = "float32"
    if args.fp16:
        args.params_dtype = "float16"
    if args.bf16:
        args.params_dtype = "bfloat16"
    if args.sequence_parallel and args.tensor_model_parallel_size == 1:
        args.sequence_parallel = False
    if args.virtual_pipeline_model_parallel_size is not None:
        if args.pipeline_model_parallel_size <= 1:
            raise ValueError("virtual pipeline requires pipeline_model_parallel_size > 1")
        if args.num_layers is not None and args.num_layers % (
            args.pipeline_model_parallel_size
            * args.virtual_pipeline_model_parallel_size
        ) != 0:
            raise ValueError("num_layers must divide pp*vpp chunks")
    return args


def _add_network_size_args(parser):
    group = parser.add_argument_group(title="network size")
    group.add_argument("--num-layers", type=int, default=None)
    group.add_argument("--hidden-size", type=int, default=None)
    group.add_argument("--ffn-hidden-size", type=int, default=None)
    group.add_argument("--num-attention-heads", type=int, default=None)
    group.add_argument("--kv-channels", type=int, default=None)
    group.add_argument("--max-position-embeddings", type=int, default=None)
    group.add_argument("--make-vocab-size-divisible-by", type=int, default=128)
    group.add_argument("--layernorm-epsilon", type=float, default=1e-5)
    group.add_argument("--padded-vocab-size", type=int, default=None)
    return parser


def _add_regularization_args(parser):
    group = parser.add_argument_group(title="regularization")
    group.add_argument("--attention-dropout", type=float, default=0.1)
    group.add_argument("--hidden-dropout", type=float, default=0.1)
    group.add_argument("--weight-decay", type=float, default=0.01)
    group.add_argument("--clip-grad", type=float, default=1.0)
    group.add_argument("--adam-beta1", type=float, default=0.9)
    group.add_argument("--adam-beta2", type=float, default=0.999)
    group.add_argument("--adam-eps", type=float, default=1e-8)
    group.add_argument("--sgd-momentum", type=float, default=0.9)
    return parser


def _add_training_args(parser):
    group = parser.add_argument_group(title="training")
    group.add_argument("--micro-batch-size", type=int, default=None)
    group.add_argument("--global-batch-size", type=int, default=None)
    group.add_argument("--rampup-batch-size", nargs="*", default=None)
    group.add_argument("--train-iters", type=int, default=None)
    group.add_argument("--train-samples", type=int, default=None)
    group.add_argument("--log-interval", type=int, default=100)
    group.add_argument("--exit-interval", type=int, default=None)
    group.add_argument("--optimizer", type=str, default="adam",
                       choices=["adam", "sgd", "lamb"])
    group.add_argument("--recompute-activations", action="store_true")
    group.add_argument("--checkpoint-activations", action="store_true")
    group.add_argument("--distribute-saved-activations", action="store_true")
    group.add_argument("--seed", type=int, default=1234)
    # GPU fusion toggles — parsed for parity, TPU fusion is XLA's call.
    group.add_argument("--no-masked-softmax-fusion", action="store_false",
                       dest="masked_softmax_fusion")
    group.add_argument("--no-bias-gelu-fusion", action="store_false",
                       dest="bias_gelu_fusion")
    group.add_argument("--no-bias-dropout-fusion", action="store_false",
                       dest="bias_dropout_fusion")
    group.add_argument("--no-gradient-accumulation-fusion", action="store_false",
                       dest="gradient_accumulation_fusion")
    return parser


def _add_learning_rate_args(parser):
    group = parser.add_argument_group(title="learning rate")
    group.add_argument("--lr", type=float, default=None)
    group.add_argument("--lr-decay-style", type=str, default="linear",
                       choices=["constant", "linear", "cosine"])
    group.add_argument("--lr-decay-iters", type=int, default=None)
    group.add_argument("--lr-warmup-fraction", type=float, default=None)
    group.add_argument("--min-lr", type=float, default=0.0)
    return parser


def _add_mixed_precision_args(parser):
    group = parser.add_argument_group(title="mixed precision")
    group.add_argument("--fp16", action="store_true")
    group.add_argument("--bf16", action="store_true")
    group.add_argument("--loss-scale", type=float, default=None)
    group.add_argument("--initial-loss-scale", type=float, default=2 ** 32)
    group.add_argument("--min-loss-scale", type=float, default=1.0)
    group.add_argument("--loss-scale-window", type=float, default=1000)
    group.add_argument("--hysteresis", type=int, default=2)
    group.add_argument("--accumulate-allreduce-grads-in-fp32", action="store_true")
    return parser


def _add_distributed_args(parser):
    group = parser.add_argument_group(title="distributed")
    group.add_argument("--tensor-model-parallel-size", type=int, default=1)
    group.add_argument("--pipeline-model-parallel-size", type=int, default=1)
    group.add_argument("--pipeline-model-parallel-split-rank", type=int, default=None)
    group.add_argument("--num-layers-per-virtual-pipeline-stage", type=int, default=None)
    group.add_argument("--virtual-pipeline-model-parallel-size", type=int, default=None)
    group.add_argument("--context-parallel-size", type=int, default=1)
    group.add_argument("--sequence-parallel", action="store_true")
    group.add_argument("--world-size", type=int, default=None)
    group.add_argument("--rank", type=int, default=0)
    group.add_argument("--local-rank", type=int, default=0)
    group.add_argument("--distributed-backend", type=str, default="xla",
                       choices=["xla", "nccl", "gloo", "ucc"])
    group.add_argument("--use-cpu-initialization", action="store_true")
    return parser


def _add_validation_args(parser):
    group = parser.add_argument_group(title="validation")
    group.add_argument("--eval-iters", type=int, default=100)
    group.add_argument("--eval-interval", type=int, default=1000)
    return parser


def _add_data_args(parser):
    group = parser.add_argument_group(title="data")
    group.add_argument("--data-path", nargs="*", default=None)
    group.add_argument("--seq-length", type=int, default=None)
    group.add_argument("--encoder-seq-length", type=int, default=None)
    group.add_argument("--decoder-seq-length", type=int, default=None)
    group.add_argument("--vocab-size", type=int, default=None)
    group.add_argument("--num-workers", type=int, default=2)
    group.add_argument("--reset-position-ids", action="store_true")
    group.add_argument("--reset-attention-mask", action="store_true")
    group.add_argument("--eod-mask-loss", action="store_true")
    group.add_argument("--dataloader-type", type=str, default=None,
                       choices=[None, "single", "cyclic"])
    return parser


def _add_logging_args(parser):
    group = parser.add_argument_group(title="logging")
    group.add_argument("--log-params-norm", action="store_true")
    group.add_argument("--log-num-zeros-in-grad", action="store_true")
    group.add_argument("--tensorboard-dir", type=str, default=None)
    group.add_argument("--tensorboard-log-interval", type=int, default=1)
    group.add_argument("--timing-log-level", type=int, default=0, choices=range(3))
    return parser
