"""Utilities shared by ``pipeline_parallel`` and ``tensor_parallel``.

Reference: ``apex/transformer/utils.py`` (``ensure_divisibility`` /
``divide`` / ``split_tensor_into_1d_equal_chunks`` /
``gather_split_1d_tensor``).

TPU note: the reference's split/gather pair exists to stash sequence-
parallel activations as flat per-rank chunks (NCCL ``all_gather`` into a
preallocated buffer).  Here the same contract is expressed with
``jax.shard_map`` collectives over the ``tp`` mesh axis — the split is a
static slice by rank index, the gather is ``jax.lax.all_gather(...,
tiled=True)`` — so both work inside jit on any mesh the caller built via
:mod:`apex_tpu.transformer.parallel_state`.
"""

import jax
import jax.numpy as jnp

from apex_tpu.utils.misc import divide, ensure_divisibility  # noqa: F401 — re-export
from apex_tpu.transformer import parallel_state


def split_tensor_into_1d_equal_chunks(tensor, *, rank=None, world_size=None):
    """This rank's equal 1-D chunk of ``tensor`` (flattened).

    Inside ``shard_map`` pass nothing: rank/world come from the ``tp``
    axis (``jax.lax.axis_index``).  Outside, pass explicit ints.
    """
    if world_size is None:
        world_size = parallel_state.get_tensor_model_parallel_world_size()
    if rank is None:
        rank = parallel_state.get_tensor_model_parallel_rank()
    data = jnp.ravel(tensor)
    ensure_divisibility(data.size, world_size)
    partition = data.size // world_size
    return jax.lax.dynamic_slice(data, (rank * partition,), (partition,))


def gather_split_1d_tensor(tensor, *, axis_name="tp"):
    """Opposite of :func:`split_tensor_into_1d_equal_chunks`: all-gather
    the per-rank 1-D chunks over the tensor-parallel axis.  Must run
    inside ``shard_map`` with ``axis_name`` bound."""
    return jax.lax.all_gather(jnp.ravel(tensor), axis_name, tiled=True)
