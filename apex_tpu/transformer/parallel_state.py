"""Model-parallel state: the mesh/axis registry.

Reference: ``apex/transformer/parallel_state.py`` —
``initialize_model_parallel`` (:155) builds NCCL process groups for
TP/PP/DP (+embedding, amax, ...) and ~50 getters expose ranks/sizes/groups.

TPU-native redesign: process groups become **named axes of one
``jax.sharding.Mesh``**.  Axis order encodes ICI locality — ``tp``
innermost (highest-bandwidth neighbor links, collectives every layer),
then ``cp`` (context/sequence parallelism — a capability beyond the
reference, SURVEY §2.4), then ``pp`` (point-to-point only), ``dp``
outermost (least-frequent collectives; on multi-slice deployments the
``dp`` axis is the one to map onto DCN).  Group membership, sub-group
creation, and rank bookkeeping all disappear: a collective names its axis,
and XLA routes it over ICI.

Rank/size getters are preserved with reference names.  Sizes are static
(mesh shape).  Ranks are meaningful per-device: inside ``shard_map`` they
are ``jax.lax.axis_index`` (traced); outside they are derived from
``jax.process_index`` for the host-local view.
"""

import dataclasses
from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

# Canonical axis names (the TPU equivalents of the reference's groups).
DATA_AXIS = "dp"
PIPELINE_AXIS = "pp"
CONTEXT_AXIS = "cp"
TENSOR_AXIS = "tp"
# Multi-slice deployments add an outermost DCN axis: the analog of the
# reference's hybrid IB/socket group split (parallel_state.py:108-153,
# NUM_GPUS_PER_IB_BLOCK) — data parallelism hierarchically decomposed
# into fast-domain (ICI, "dp") and slow-domain (DCN, "dcn") legs.
DCN_AXIS = "dcn"
# Hierarchical data parallelism (topology-aware two-hop grad sync,
# contrib/optimizers/_hierarchical_sync.py): the dp world split into a
# slow cross-slice outer axis and a fast intra-slice inner axis —
# dp_outer x dp_inner = dp.  Registered here so the analyzer's axis
# registry (discover_axis_registry) knows them like every other axis.
DATA_OUTER_AXIS = "dp_out"
DATA_INNER_AXIS = "dp_in"
#: the canonical three-level dp split, slow to fast — the ``dp_axes=``
#: spelling of a multi-pod deployment (cross-DCN x cross-slice x
#: intra-slice); the two-level spelling is its ``[1:]`` suffix
HIER_DP_AXES = (DCN_AXIS, DATA_OUTER_AXIS, DATA_INNER_AXIS)
AXIS_ORDER = (DATA_AXIS, PIPELINE_AXIS, CONTEXT_AXIS, TENSOR_AXIS)


@dataclasses.dataclass
class _State:
    mesh: Mesh
    tensor_model_parallel_size: int
    pipeline_model_parallel_size: int
    context_parallel_size: int
    data_parallel_size: int
    virtual_pipeline_model_parallel_size: Optional[int]
    pipeline_model_parallel_split_rank: Optional[int]
    num_distributed_slices: int = 1
    # mutable trace-time bookkeeping (mirrors the reference's globals)
    virtual_pipeline_model_parallel_rank: Optional[int] = None
    # static rank overrides installed by set_*_rank (test support);
    # None → getters return the traced axis_index
    tensor_model_parallel_rank_override: Optional[int] = None
    pipeline_model_parallel_rank_override: Optional[int] = None


_STATE: Optional[_State] = None


def initialize_model_parallel(
    tensor_model_parallel_size_: int = 1,
    pipeline_model_parallel_size_: int = 1,
    virtual_pipeline_model_parallel_size_: Optional[int] = None,
    pipeline_model_parallel_split_rank_: Optional[int] = None,
    context_parallel_size_: int = 1,
    devices: Optional[Sequence] = None,
    num_distributed_slices_: int = 1,
) -> Mesh:
    """Build and register the global device mesh.

    Reference: ``parallel_state.initialize_model_parallel``
    (parallel_state.py:155) — argument names kept (trailing underscore and
    all).  ``context_parallel_size_`` is new (ring-attention axis).
    Returns the mesh (also retrievable via :func:`get_mesh`).

    ``num_distributed_slices_`` > 1 adds an outermost ``dcn`` mesh axis
    splitting data parallelism into a cross-slice leg and a within-slice
    leg — the multi-slice topology (model axes stay inside one slice on
    ICI; only the infrequent data-parallel gradient reduction crosses
    DCN).  Collectives over ``("dcn", "dp")`` lower to a hierarchical
    reduce (ICI first, then one transfer per slice over DCN) — the TPU
    form of the reference's IB-block-aware hybrid groups
    (parallel_state.py:108-153).  On real multi-slice hardware pass the
    devices ordered slice-major (``jax.devices()`` already is).
    """
    global _STATE
    devs = list(devices) if devices is not None else jax.devices()
    world = len(devs)
    tp, pp, cp = (
        int(tensor_model_parallel_size_),
        int(pipeline_model_parallel_size_),
        int(context_parallel_size_),
    )
    if world % (tp * pp * cp) != 0:
        raise RuntimeError(
            f"world size ({world}) is not divisible by tp ({tp}) x pp ({pp}) x cp ({cp})"
        )
    dp = world // (tp * pp * cp)
    if virtual_pipeline_model_parallel_size_ is not None and pp < 2:
        raise RuntimeError(
            "pipeline-model-parallel size should be greater than 2 with interleaved schedule"
        )

    slices = int(num_distributed_slices_)
    if slices > 1:
        if dp % slices:
            raise RuntimeError(
                f"data-parallel size ({dp}) not divisible by slices ({slices}): "
                "model axes must fit inside one slice"
            )
        dp_in = dp // slices
        arr = np.array(devs).reshape(slices, dp_in, pp, cp, tp)
        mesh = Mesh(arr, (DCN_AXIS,) + AXIS_ORDER)
        dp = dp_in
    else:
        arr = np.array(devs).reshape(dp, pp, cp, tp)
        mesh = Mesh(arr, AXIS_ORDER)
    _STATE = _State(
        mesh=mesh,
        tensor_model_parallel_size=tp,
        pipeline_model_parallel_size=pp,
        context_parallel_size=cp,
        data_parallel_size=dp,
        virtual_pipeline_model_parallel_size=virtual_pipeline_model_parallel_size_,
        pipeline_model_parallel_split_rank=pipeline_model_parallel_split_rank_,
        num_distributed_slices=slices,
    )
    return mesh


def model_parallel_is_initialized() -> bool:
    """Reference: parallel_state.py:404."""
    return _STATE is not None


def _state() -> _State:
    if _STATE is None:
        raise RuntimeError("model parallel is not initialized (call initialize_model_parallel)")
    return _STATE


def get_mesh() -> Mesh:
    return _state().mesh


def destroy_model_parallel() -> None:
    """Reference: parallel_state.py:761."""
    global _STATE
    _STATE = None


# ------------------------------------------------------------------- sizes
def get_tensor_model_parallel_world_size() -> int:
    return _state().tensor_model_parallel_size


def get_pipeline_model_parallel_world_size() -> int:
    return _state().pipeline_model_parallel_size


def get_context_parallel_world_size() -> int:
    return _state().context_parallel_size


def get_data_parallel_world_size() -> int:
    return _state().data_parallel_size


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _state().virtual_pipeline_model_parallel_size


def get_pipeline_model_parallel_split_rank() -> Optional[int]:
    return _state().pipeline_model_parallel_split_rank


# ------------------------------------------------------------------- groups
class AxisGroup(str):
    """A "process group" handle: the name of a mesh axis.

    Reference groups (``parallel_state.py:444-506``) are NCCL communicators;
    the TPU equivalent is a named mesh axis.  ``AxisGroup`` subclasses
    ``str`` so it can be passed straight to ``jax.lax.psum``/``all_gather``
    etc. as the ``axis_name``.  ``size()`` and ``mesh`` mirror the
    ``torch.distributed`` group API surface.
    """

    members: Optional[tuple] = None

    def __new__(cls, axis: str, size: int, mesh: Mesh, members: Optional[tuple] = None):
        self = super().__new__(cls, axis)
        self._size = size
        self.mesh = mesh
        self.members = members
        return self

    def size(self) -> int:
        return self._size

    def masked_psum(self, x):
        """Sum ``x`` over the group's *members* only.

        Groups with partial membership (``members`` set, e.g. the
        embedding group = first+last pipeline stages) still name a full
        mesh axis, so a bare ``jax.lax.psum(x, group)`` would sum over
        every index on the axis — including non-members.  This helper
        zeroes non-member contributions first.  Members receive the
        member-sum; non-members receive it too (harmless — they hold no
        tied embedding), matching the reference's group-scoped
        all_reduce semantics for ranks in the group.
        """
        import jax
        import jax.numpy as jnp

        axis_extent = dict(zip(self.mesh.axis_names, self.mesh.devices.shape)).get(str(self))
        if self.members is None or len(self.members) == axis_extent:
            return jax.lax.psum(x, str(self))
        idx = jax.lax.axis_index(str(self))
        is_member = jnp.zeros((), bool)
        for m in self.members:
            is_member = is_member | (idx == m)
        zeros = jax.tree.map(lambda t: jnp.where(is_member, t, jnp.zeros_like(t)), x)
        return jax.lax.psum(zeros, str(self))


def get_tensor_model_parallel_group() -> AxisGroup:
    """Reference: parallel_state.py:444 — here, the ``tp`` mesh axis."""
    s = _state()
    return AxisGroup(TENSOR_AXIS, s.tensor_model_parallel_size, s.mesh)


def get_pipeline_model_parallel_group() -> AxisGroup:
    """Reference: parallel_state.py:453 — here, the ``pp`` mesh axis."""
    s = _state()
    return AxisGroup(PIPELINE_AXIS, s.pipeline_model_parallel_size, s.mesh)


def get_context_parallel_group() -> AxisGroup:
    s = _state()
    return AxisGroup(CONTEXT_AXIS, s.context_parallel_size, s.mesh)


def get_data_parallel_group():
    """Reference: parallel_state.py:462 — here, the ``dp`` mesh axis.

    On a multi-slice mesh this is the combined ``(dcn, dp)`` axes: a
    ``psum`` over it is the hierarchical (ICI-then-DCN) gradient
    reduction."""
    s = _state()
    if s.num_distributed_slices > 1:
        return MultiAxisGroup(
            (DCN_AXIS, DATA_AXIS), s.num_distributed_slices * s.data_parallel_size,
            s.mesh,
        )
    return AxisGroup(DATA_AXIS, s.data_parallel_size, s.mesh)


def get_num_distributed_slices() -> int:
    """Multi-slice count (1 = single slice; no reference analog — the
    IB/socket hybrid logic is the closest, parallel_state.py:108)."""
    return _state().num_distributed_slices


class MultiAxisGroup(tuple):
    """A "process group" spanning several mesh axes.

    Subclasses ``tuple`` of axis-name strings so it is accepted verbatim
    as ``axis_name`` by ``jax.lax.psum``-family collectives, like
    :class:`AxisGroup` is for a single axis."""

    def __new__(cls, axes, size: int, mesh: Mesh):
        self = super().__new__(cls, axes)
        self._size = size
        self.mesh = mesh
        return self

    def size(self) -> int:
        return self._size


def get_model_parallel_group() -> MultiAxisGroup:
    """The combined (pp, tp) axes — collectives over every non-dp axis;
    used for found-inf reductions (reference:
    ``transformer/amp/grad_scaler.py``)."""
    s = _state()
    return MultiAxisGroup(
        (PIPELINE_AXIS, TENSOR_AXIS),
        s.pipeline_model_parallel_size * s.tensor_model_parallel_size,
        s.mesh,
    )


def get_embedding_group() -> AxisGroup:
    """First+last pipeline stages (tied embedding grad sync).

    Reference: parallel_state.py:471.  On TPU the tied-embedding gradient
    exchange is a masked ``psum`` over the ``pp`` axis done inside the
    pipeline schedule; ``members`` records which stage indices take part.

    .. warning:: this group has *partial* membership — a bare
       ``jax.lax.psum(x, group)`` sums over every pipeline stage.  Use
       :meth:`AxisGroup.masked_psum` to reduce over members only.
    """
    s = _state()
    members = _embedding_group_members()
    return AxisGroup(PIPELINE_AXIS, len(members), s.mesh, members=members)


def _embedding_group_members() -> tuple:
    """{first, last} stages, plus the first decoder stage when an
    encoder/decoder split is configured (reference :352,:361-366)."""
    s = _state()
    members = {0, s.pipeline_model_parallel_size - 1}
    if (
        s.pipeline_model_parallel_size > 1
        and s.pipeline_model_parallel_split_rank is not None
    ):
        members.add(s.pipeline_model_parallel_split_rank)
    return tuple(sorted(members))


def _position_embedding_group_members() -> tuple:
    """Stage 0, plus the first decoder stage under a split
    (reference :353,:367-372)."""
    s = _state()
    members = {0}
    if (
        s.pipeline_model_parallel_size > 1
        and s.pipeline_model_parallel_split_rank is not None
    ):
        members.add(s.pipeline_model_parallel_split_rank)
    return tuple(sorted(members))


def get_position_embedding_group() -> AxisGroup:
    """Reference: parallel_state.py:480 — stage 0 (plus the split stage
    for encoder/decoder models)."""
    s = _state()
    members = _position_embedding_group_members()
    return AxisGroup(PIPELINE_AXIS, len(members), s.mesh, members=members)


def get_amax_reduction_group() -> AxisGroup:
    """Reference: parallel_state.py:489 — fp8 amax reductions ride tp."""
    s = _state()
    return AxisGroup(TENSOR_AXIS, s.tensor_model_parallel_size, s.mesh)


# ------------------------------------------------------------------- ranks
# Inside shard_map these return traced per-device indices; the reference's
# host-side rank bookkeeping has no other TPU analog.
def get_tensor_model_parallel_rank():
    ov = _STATE.tensor_model_parallel_rank_override if _STATE else None
    return jax.lax.axis_index(TENSOR_AXIS) if ov is None else ov


def get_pipeline_model_parallel_rank():
    ov = _STATE.pipeline_model_parallel_rank_override if _STATE else None
    return jax.lax.axis_index(PIPELINE_AXIS) if ov is None else ov


def get_context_parallel_rank():
    return jax.lax.axis_index(CONTEXT_AXIS)


def get_data_parallel_rank():
    return jax.lax.axis_index(DATA_AXIS)


def get_pipeline_model_parallel_next_rank():
    """Ring-next stage index (reference: parallel_state.py:730).

    On TPU the "rank" is the position on the ``pp`` mesh axis; the p2p
    module turns (rank → rank+1) into a ``ppermute`` permutation, so this
    is mainly for parity/debug inside shard_map."""
    return (jax.lax.axis_index(PIPELINE_AXIS) + 1) % _state().pipeline_model_parallel_size


def get_pipeline_model_parallel_prev_rank():
    """Ring-previous stage index (reference: parallel_state.py:739)."""
    return (jax.lax.axis_index(PIPELINE_AXIS) - 1) % _state().pipeline_model_parallel_size


def get_virtual_pipeline_model_parallel_rank() -> Optional[int]:
    """Trace-time virtual-stage cursor (reference: parallel_state.py:679)."""
    return _state().virtual_pipeline_model_parallel_rank


def set_virtual_pipeline_model_parallel_rank(rank: Optional[int]) -> None:
    _state().virtual_pipeline_model_parallel_rank = rank


# ------------------------------------------------- stage predicates (static)
def is_pipeline_first_stage(ignore_virtual: bool = False, stage: Optional[int] = None):
    """Static form: pass ``stage`` (the pp index of the program being
    built).  Reference: parallel_state.py:508."""
    if not ignore_virtual:
        vpp = _state().virtual_pipeline_model_parallel_size
        if vpp is not None and _state().virtual_pipeline_model_parallel_rank not in (None, 0):
            return False
    if stage is None:
        raise ValueError("pass stage= (static pipeline stage index)")
    return stage == 0


def is_pipeline_last_stage(ignore_virtual: bool = False, stage: Optional[int] = None):
    if not ignore_virtual:
        vpp = _state().virtual_pipeline_model_parallel_size
        if vpp is not None and _state().virtual_pipeline_model_parallel_rank not in (
            None,
            vpp - 1,
        ):
            return False
    if stage is None:
        raise ValueError("pass stage= (static pipeline stage index)")
    return stage == _state().pipeline_model_parallel_size - 1


def get_rank_info() -> str:
    """Debug string (reference: parallel_state.py:421)."""
    if _STATE is None:
        return "model parallel not initialized"
    s = _state()
    return (
        f"tp={s.tensor_model_parallel_size} pp={s.pipeline_model_parallel_size} "
        f"cp={s.context_parallel_size} dp={s.data_parallel_size}"
    )


def is_unitialized() -> bool:
    """Reference parallel_state.py:79 (typo preserved): True when model
    parallel state has not been initialized."""
    return _STATE is None


# ----------------------------------------------- encoder/decoder split
# (T5-style models: stages [0, split) run the encoder, [split, pp) the
#  decoder; reference parallel_state.py:538-575.)
def is_pipeline_stage_before_split(rank: Optional[int] = None, *, stage: Optional[int] = None):
    """True if the given pipeline stage executes encoder block for a
    model with both encoder and decoder (reference :538).  Pass the
    static stage index as either positional ``rank`` (reference name) or
    ``stage=``."""
    s = _state()
    stage = rank if stage is None else stage
    if s.pipeline_model_parallel_size == 1:
        return True
    if stage is None:
        raise ValueError("pass the static pipeline stage index")
    if s.pipeline_model_parallel_split_rank is None:
        return True
    return stage < s.pipeline_model_parallel_split_rank


def is_pipeline_stage_after_split(rank: Optional[int] = None, *, stage: Optional[int] = None):
    """True if the given stage executes decoder block (reference :553)."""
    s = _state()
    stage = rank if stage is None else stage
    if s.pipeline_model_parallel_size == 1:
        return True
    if stage is None:
        raise ValueError("pass the static pipeline stage index")
    if s.pipeline_model_parallel_split_rank is None:
        return True
    return stage >= s.pipeline_model_parallel_split_rank


def is_pipeline_stage_at_split(rank: Optional[int] = None, *, stage: Optional[int] = None):
    """True if the given stage is the last encoder stage (the next one
    is the first decoder stage); reference :568-575."""
    s = _state()
    stage = rank if stage is None else stage
    if s.pipeline_model_parallel_size == 1 or s.pipeline_model_parallel_split_rank is None:
        return False
    if stage is None:
        raise ValueError("pass the static pipeline stage index")
    return (
        is_pipeline_stage_before_split(stage)
        and is_pipeline_stage_after_split(stage + 1)
    )


# ----------------------------------------------- first/last/src ranks
def get_pipeline_model_parallel_first_rank() -> int:
    """Stage index of the first pipeline stage (reference :715 returns
    the global rank; mesh-axis position here)."""
    _state()
    return 0


def get_pipeline_model_parallel_last_rank() -> int:
    """Stage index of the last pipeline stage (reference :722)."""
    return _state().pipeline_model_parallel_size - 1


def get_tensor_model_parallel_src_rank() -> int:
    """Axis position of the broadcast source inside the tp group
    (reference :699 computes the global rank of tp-local-rank 0; on a
    named mesh axis the source is simply index 0)."""
    _state()
    return 0


def get_data_parallel_src_rank() -> int:
    """Axis position of the broadcast source inside the dp group
    (reference :707)."""
    _state()
    return 0


# ----------------------------------------------- group membership (static)
def is_rank_in_embedding_group(ignore_virtual: bool = False, *, stage: Optional[int] = None) -> bool:
    """True if the given static stage takes part in the tied-embedding
    grad sync (first/last stage; reference :504-517 incl. the virtual
    chunk refinement)."""
    s = _state()
    if stage is None:
        raise ValueError("pass stage= (static pipeline stage index)")
    members = _embedding_group_members()
    if stage not in members:
        return False
    if ignore_virtual:
        return True
    if stage == members[0]:
        return is_pipeline_first_stage(stage=stage)
    if stage == members[-1]:
        return is_pipeline_last_stage(stage=stage)
    return True  # the split stage (reference :515-516: plain membership)


def is_rank_in_position_embedding_group(*, stage: Optional[int] = None) -> bool:
    """Stage 0 (plus the split stage for encoder/decoder models) holds
    position embeddings (reference :520, group built at :353,:367-372)."""
    _state()
    if stage is None:
        raise ValueError("pass stage= (static pipeline stage index)")
    return stage in _position_embedding_group_members()


def _relative_position_embedding_members(encoder: bool) -> tuple:
    s = _state()
    P = s.pipeline_model_parallel_size
    split = s.pipeline_model_parallel_split_rank
    if P == 1 or split is None:
        return (0,)  # reference: [ranks[0]] when there is no split
    return tuple(range(0, split)) if encoder else tuple(range(split, P))


def is_rank_in_encoder_relative_position_embedding_group(*, stage: Optional[int] = None) -> bool:
    """Reference :526 — encoder stages share relative-position-embedding
    grads."""
    if stage is None:
        raise ValueError("pass stage= (static pipeline stage index)")
    return stage in _relative_position_embedding_members(True)


def is_rank_in_decoder_relative_position_embedding_group(*, stage: Optional[int] = None) -> bool:
    """Reference :532."""
    if stage is None:
        raise ValueError("pass stage= (static pipeline stage index)")
    return stage in _relative_position_embedding_members(False)


def get_encoder_relative_position_embedding_group() -> AxisGroup:
    """Encoder stages on the ``pp`` axis (reference :~356).  Partial
    membership — reduce with :meth:`AxisGroup.masked_psum`."""
    s = _state()
    members = _relative_position_embedding_members(True)
    return AxisGroup(PIPELINE_AXIS, len(members), s.mesh, members=members)


def get_decoder_relative_position_embedding_group() -> AxisGroup:
    """Decoder stages on the ``pp`` axis."""
    s = _state()
    members = _relative_position_embedding_members(False)
    return AxisGroup(PIPELINE_AXIS, len(members), s.mesh, members=members)


# ----------------------------------------------- test-support setters
# The reference mutates its rank/size globals in tests
# (parallel_state.py:578-759).  Sizes and the split rank are real state
# here; *rank* setters install a static override returned by the
# corresponding getter instead of the traced ``axis_index`` (ranks are
# mesh positions under SPMD — the override exists so host-side test
# code can reason about one stage at a time).
def set_tensor_model_parallel_world_size(world_size: int) -> None:
    _state().tensor_model_parallel_size = int(world_size)


def set_pipeline_model_parallel_world_size(world_size: int) -> None:
    _state().pipeline_model_parallel_size = int(world_size)


def set_virtual_pipeline_model_parallel_world_size(world_size: Optional[int]) -> None:
    _state().virtual_pipeline_model_parallel_size = world_size


def set_pipeline_model_parallel_split_rank(rank: Optional[int]) -> None:
    _state().pipeline_model_parallel_split_rank = rank


def set_tensor_model_parallel_rank(rank: Optional[int]) -> None:
    _state().tensor_model_parallel_rank_override = rank


def set_pipeline_model_parallel_rank(rank: Optional[int]) -> None:
    _state().pipeline_model_parallel_rank_override = rank


# ----------------------------------------------- NCCL plumbing (no-op)
# Reference parallel_state.py:83-153 tunes NCCL transport (IB vs socket
# per group) and builds hybrid process groups.  Interconnect placement
# is *declarative* on TPU: the mesh layout decides which axes ride ICI
# and which cross DCN (``initialize_model_parallel(num_distributed_
# slices_=...)``); there is no transport to configure per group.
def init_nccl_net(group=None) -> None:
    """No TPU meaning (reference :91 warms up NCCL net); kept for API
    parity."""


def set_nccl_socket_envs() -> None:
    """No TPU meaning (reference :83)."""


def set_nccl_ib_envs() -> None:
    """No TPU meaning (reference :88)."""


def new_nccl_socket_group(ranks=None):
    """Not constructible under SPMD: arbitrary-rank process groups are
    replaced by named mesh axes.  Use ``initialize_model_parallel``'s
    mesh shape (and ``num_distributed_slices_`` for the DCN leg)."""
    raise RuntimeError(
        "new_nccl_socket_group: process groups are mesh axes on TPU — "
        "declare the topology via initialize_model_parallel(...)"
    )


def new_nccl_ib_group(ranks=None):
    """See :func:`new_nccl_socket_group`."""
    raise RuntimeError(
        "new_nccl_ib_group: process groups are mesh axes on TPU — "
        "declare the topology via initialize_model_parallel(...)"
    )


def new_process_group(ranks=None, backend=None):
    """See :func:`new_nccl_socket_group` (reference :108-153 picks
    IB/socket per group; DCN-vs-ICI placement is the mesh's job)."""
    raise RuntimeError(
        "new_process_group: process groups are mesh axes on TPU — "
        "declare the topology via initialize_model_parallel(...)"
    )
