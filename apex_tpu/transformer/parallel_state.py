"""Model-parallel state: the mesh/axis registry.

Reference: ``apex/transformer/parallel_state.py`` —
``initialize_model_parallel`` (:155) builds NCCL process groups for
TP/PP/DP (+embedding, amax, ...) and ~50 getters expose ranks/sizes/groups.

TPU-native redesign: process groups become **named axes of one
``jax.sharding.Mesh``**.  Axis order encodes ICI locality — ``tp``
innermost (highest-bandwidth neighbor links, collectives every layer),
then ``cp`` (context/sequence parallelism — a capability beyond the
reference, SURVEY §2.4), then ``pp`` (point-to-point only), ``dp``
outermost (least-frequent collectives; on multi-slice deployments the
``dp`` axis is the one to map onto DCN).  Group membership, sub-group
creation, and rank bookkeeping all disappear: a collective names its axis,
and XLA routes it over ICI.

Rank/size getters are preserved with reference names.  Sizes are static
(mesh shape).  Ranks are meaningful per-device: inside ``shard_map`` they
are ``jax.lax.axis_index`` (traced); outside they are derived from
``jax.process_index`` for the host-local view.
"""

import dataclasses
from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

# Canonical axis names (the TPU equivalents of the reference's groups).
DATA_AXIS = "dp"
PIPELINE_AXIS = "pp"
CONTEXT_AXIS = "cp"
TENSOR_AXIS = "tp"
# Multi-slice deployments add an outermost DCN axis: the analog of the
# reference's hybrid IB/socket group split (parallel_state.py:108-153,
# NUM_GPUS_PER_IB_BLOCK) — data parallelism hierarchically decomposed
# into fast-domain (ICI, "dp") and slow-domain (DCN, "dcn") legs.
DCN_AXIS = "dcn"
AXIS_ORDER = (DATA_AXIS, PIPELINE_AXIS, CONTEXT_AXIS, TENSOR_AXIS)


@dataclasses.dataclass
class _State:
    mesh: Mesh
    tensor_model_parallel_size: int
    pipeline_model_parallel_size: int
    context_parallel_size: int
    data_parallel_size: int
    virtual_pipeline_model_parallel_size: Optional[int]
    pipeline_model_parallel_split_rank: Optional[int]
    num_distributed_slices: int = 1
    # mutable trace-time bookkeeping (mirrors the reference's globals)
    virtual_pipeline_model_parallel_rank: Optional[int] = None


_STATE: Optional[_State] = None


def initialize_model_parallel(
    tensor_model_parallel_size_: int = 1,
    pipeline_model_parallel_size_: int = 1,
    virtual_pipeline_model_parallel_size_: Optional[int] = None,
    pipeline_model_parallel_split_rank_: Optional[int] = None,
    context_parallel_size_: int = 1,
    devices: Optional[Sequence] = None,
    num_distributed_slices_: int = 1,
) -> Mesh:
    """Build and register the global device mesh.

    Reference: ``parallel_state.initialize_model_parallel``
    (parallel_state.py:155) — argument names kept (trailing underscore and
    all).  ``context_parallel_size_`` is new (ring-attention axis).
    Returns the mesh (also retrievable via :func:`get_mesh`).

    ``num_distributed_slices_`` > 1 adds an outermost ``dcn`` mesh axis
    splitting data parallelism into a cross-slice leg and a within-slice
    leg — the multi-slice topology (model axes stay inside one slice on
    ICI; only the infrequent data-parallel gradient reduction crosses
    DCN).  Collectives over ``("dcn", "dp")`` lower to a hierarchical
    reduce (ICI first, then one transfer per slice over DCN) — the TPU
    form of the reference's IB-block-aware hybrid groups
    (parallel_state.py:108-153).  On real multi-slice hardware pass the
    devices ordered slice-major (``jax.devices()`` already is).
    """
    global _STATE
    devs = list(devices) if devices is not None else jax.devices()
    world = len(devs)
    tp, pp, cp = (
        int(tensor_model_parallel_size_),
        int(pipeline_model_parallel_size_),
        int(context_parallel_size_),
    )
    if world % (tp * pp * cp) != 0:
        raise RuntimeError(
            f"world size ({world}) is not divisible by tp ({tp}) x pp ({pp}) x cp ({cp})"
        )
    dp = world // (tp * pp * cp)
    if virtual_pipeline_model_parallel_size_ is not None and pp < 2:
        raise RuntimeError(
            "pipeline-model-parallel size should be greater than 2 with interleaved schedule"
        )

    slices = int(num_distributed_slices_)
    if slices > 1:
        if dp % slices:
            raise RuntimeError(
                f"data-parallel size ({dp}) not divisible by slices ({slices}): "
                "model axes must fit inside one slice"
            )
        dp_in = dp // slices
        arr = np.array(devs).reshape(slices, dp_in, pp, cp, tp)
        mesh = Mesh(arr, (DCN_AXIS,) + AXIS_ORDER)
        dp = dp_in
    else:
        arr = np.array(devs).reshape(dp, pp, cp, tp)
        mesh = Mesh(arr, AXIS_ORDER)
    _STATE = _State(
        mesh=mesh,
        tensor_model_parallel_size=tp,
        pipeline_model_parallel_size=pp,
        context_parallel_size=cp,
        data_parallel_size=dp,
        virtual_pipeline_model_parallel_size=virtual_pipeline_model_parallel_size_,
        pipeline_model_parallel_split_rank=pipeline_model_parallel_split_rank_,
        num_distributed_slices=slices,
    )
    return mesh


def model_parallel_is_initialized() -> bool:
    """Reference: parallel_state.py:404."""
    return _STATE is not None


def _state() -> _State:
    if _STATE is None:
        raise RuntimeError("model parallel is not initialized (call initialize_model_parallel)")
    return _STATE


def get_mesh() -> Mesh:
    return _state().mesh


def destroy_model_parallel() -> None:
    """Reference: parallel_state.py:761."""
    global _STATE
    _STATE = None


# ------------------------------------------------------------------- sizes
def get_tensor_model_parallel_world_size() -> int:
    return _state().tensor_model_parallel_size


def get_pipeline_model_parallel_world_size() -> int:
    return _state().pipeline_model_parallel_size


def get_context_parallel_world_size() -> int:
    return _state().context_parallel_size


def get_data_parallel_world_size() -> int:
    return _state().data_parallel_size


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _state().virtual_pipeline_model_parallel_size


def get_pipeline_model_parallel_split_rank() -> Optional[int]:
    return _state().pipeline_model_parallel_split_rank


# ------------------------------------------------------------------- groups
class AxisGroup(str):
    """A "process group" handle: the name of a mesh axis.

    Reference groups (``parallel_state.py:444-506``) are NCCL communicators;
    the TPU equivalent is a named mesh axis.  ``AxisGroup`` subclasses
    ``str`` so it can be passed straight to ``jax.lax.psum``/``all_gather``
    etc. as the ``axis_name``.  ``size()`` and ``mesh`` mirror the
    ``torch.distributed`` group API surface.
    """

    members: Optional[tuple] = None

    def __new__(cls, axis: str, size: int, mesh: Mesh, members: Optional[tuple] = None):
        self = super().__new__(cls, axis)
        self._size = size
        self.mesh = mesh
        self.members = members
        return self

    def size(self) -> int:
        return self._size

    def masked_psum(self, x):
        """Sum ``x`` over the group's *members* only.

        Groups with partial membership (``members`` set, e.g. the
        embedding group = first+last pipeline stages) still name a full
        mesh axis, so a bare ``jax.lax.psum(x, group)`` would sum over
        every index on the axis — including non-members.  This helper
        zeroes non-member contributions first.  Members receive the
        member-sum; non-members receive it too (harmless — they hold no
        tied embedding), matching the reference's group-scoped
        all_reduce semantics for ranks in the group.
        """
        import jax
        import jax.numpy as jnp

        axis_extent = dict(zip(self.mesh.axis_names, self.mesh.devices.shape)).get(str(self))
        if self.members is None or len(self.members) == axis_extent:
            return jax.lax.psum(x, str(self))
        idx = jax.lax.axis_index(str(self))
        is_member = jnp.zeros((), bool)
        for m in self.members:
            is_member = is_member | (idx == m)
        zeros = jax.tree.map(lambda t: jnp.where(is_member, t, jnp.zeros_like(t)), x)
        return jax.lax.psum(zeros, str(self))


def get_tensor_model_parallel_group() -> AxisGroup:
    """Reference: parallel_state.py:444 — here, the ``tp`` mesh axis."""
    s = _state()
    return AxisGroup(TENSOR_AXIS, s.tensor_model_parallel_size, s.mesh)


def get_pipeline_model_parallel_group() -> AxisGroup:
    """Reference: parallel_state.py:453 — here, the ``pp`` mesh axis."""
    s = _state()
    return AxisGroup(PIPELINE_AXIS, s.pipeline_model_parallel_size, s.mesh)


def get_context_parallel_group() -> AxisGroup:
    s = _state()
    return AxisGroup(CONTEXT_AXIS, s.context_parallel_size, s.mesh)


def get_data_parallel_group():
    """Reference: parallel_state.py:462 — here, the ``dp`` mesh axis.

    On a multi-slice mesh this is the combined ``(dcn, dp)`` axes: a
    ``psum`` over it is the hierarchical (ICI-then-DCN) gradient
    reduction."""
    s = _state()
    if s.num_distributed_slices > 1:
        return MultiAxisGroup(
            (DCN_AXIS, DATA_AXIS), s.num_distributed_slices * s.data_parallel_size,
            s.mesh,
        )
    return AxisGroup(DATA_AXIS, s.data_parallel_size, s.mesh)


def get_num_distributed_slices() -> int:
    """Multi-slice count (1 = single slice; no reference analog — the
    IB/socket hybrid logic is the closest, parallel_state.py:108)."""
    return _state().num_distributed_slices


class MultiAxisGroup(tuple):
    """A "process group" spanning several mesh axes.

    Subclasses ``tuple`` of axis-name strings so it is accepted verbatim
    as ``axis_name`` by ``jax.lax.psum``-family collectives, like
    :class:`AxisGroup` is for a single axis."""

    def __new__(cls, axes, size: int, mesh: Mesh):
        self = super().__new__(cls, axes)
        self._size = size
        self.mesh = mesh
        return self

    def size(self) -> int:
        return self._size


def get_model_parallel_group() -> MultiAxisGroup:
    """The combined (pp, tp) axes — collectives over every non-dp axis;
    used for found-inf reductions (reference:
    ``transformer/amp/grad_scaler.py``)."""
    s = _state()
    return MultiAxisGroup(
        (PIPELINE_AXIS, TENSOR_AXIS),
        s.pipeline_model_parallel_size * s.tensor_model_parallel_size,
        s.mesh,
    )


def get_embedding_group() -> AxisGroup:
    """First+last pipeline stages (tied embedding grad sync).

    Reference: parallel_state.py:471.  On TPU the tied-embedding gradient
    exchange is a masked ``psum`` over the ``pp`` axis done inside the
    pipeline schedule; ``members`` records which stage indices take part.

    .. warning:: this group has *partial* membership — a bare
       ``jax.lax.psum(x, group)`` sums over every pipeline stage.  Use
       :meth:`AxisGroup.masked_psum` to reduce over members only.
    """
    s = _state()
    members = tuple(sorted({0, s.pipeline_model_parallel_size - 1}))
    return AxisGroup(PIPELINE_AXIS, len(members), s.mesh, members=members)


def get_position_embedding_group() -> AxisGroup:
    """Reference: parallel_state.py:480 — stage 0 only (position embeddings)."""
    s = _state()
    return AxisGroup(PIPELINE_AXIS, 1, s.mesh, members=(0,))


def get_amax_reduction_group() -> AxisGroup:
    """Reference: parallel_state.py:489 — fp8 amax reductions ride tp."""
    s = _state()
    return AxisGroup(TENSOR_AXIS, s.tensor_model_parallel_size, s.mesh)


# ------------------------------------------------------------------- ranks
# Inside shard_map these return traced per-device indices; the reference's
# host-side rank bookkeeping has no other TPU analog.
def get_tensor_model_parallel_rank():
    return jax.lax.axis_index(TENSOR_AXIS)


def get_pipeline_model_parallel_rank():
    return jax.lax.axis_index(PIPELINE_AXIS)


def get_context_parallel_rank():
    return jax.lax.axis_index(CONTEXT_AXIS)


def get_data_parallel_rank():
    return jax.lax.axis_index(DATA_AXIS)


def get_pipeline_model_parallel_next_rank():
    """Ring-next stage index (reference: parallel_state.py:730).

    On TPU the "rank" is the position on the ``pp`` mesh axis; the p2p
    module turns (rank → rank+1) into a ``ppermute`` permutation, so this
    is mainly for parity/debug inside shard_map."""
    return (jax.lax.axis_index(PIPELINE_AXIS) + 1) % _state().pipeline_model_parallel_size


def get_pipeline_model_parallel_prev_rank():
    """Ring-previous stage index (reference: parallel_state.py:739)."""
    return (jax.lax.axis_index(PIPELINE_AXIS) - 1) % _state().pipeline_model_parallel_size


def get_virtual_pipeline_model_parallel_rank() -> Optional[int]:
    """Trace-time virtual-stage cursor (reference: parallel_state.py:679)."""
    return _state().virtual_pipeline_model_parallel_rank


def set_virtual_pipeline_model_parallel_rank(rank: Optional[int]) -> None:
    _state().virtual_pipeline_model_parallel_rank = rank


# ------------------------------------------------- stage predicates (static)
def is_pipeline_first_stage(ignore_virtual: bool = False, stage: Optional[int] = None):
    """Static form: pass ``stage`` (the pp index of the program being
    built).  Reference: parallel_state.py:508."""
    if not ignore_virtual:
        vpp = _state().virtual_pipeline_model_parallel_size
        if vpp is not None and _state().virtual_pipeline_model_parallel_rank not in (None, 0):
            return False
    if stage is None:
        raise ValueError("pass stage= (static pipeline stage index)")
    return stage == 0


def is_pipeline_last_stage(ignore_virtual: bool = False, stage: Optional[int] = None):
    if not ignore_virtual:
        vpp = _state().virtual_pipeline_model_parallel_size
        if vpp is not None and _state().virtual_pipeline_model_parallel_rank not in (
            None,
            vpp - 1,
        ):
            return False
    if stage is None:
        raise ValueError("pass stage= (static pipeline stage index)")
    return stage == _state().pipeline_model_parallel_size - 1


def get_rank_info() -> str:
    """Debug string (reference: parallel_state.py:421)."""
    if _STATE is None:
        return "model parallel not initialized"
    s = _state()
    return (
        f"tp={s.tensor_model_parallel_size} pp={s.pipeline_model_parallel_size} "
        f"cp={s.context_parallel_size} dp={s.data_parallel_size}"
    )
