"""Pipeline-parallel utilities.

Reference: ``apex/transformer/pipeline_parallel/utils.py`` —
``setup_microbatch_calculator`` (:58), ``get_kth_microbatch`` (:122),
``_Timers`` (:146 via _timers.py), ``print_rank_0`` (:159),
``calc_params_l2_norm`` (:213), ``report_memory`` (:253),
``get_ltor_masks_and_position_ids`` (:303).
"""

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.multi_tensor import multi_tensor_l2norm
from apex_tpu.transformer.microbatches import build_num_microbatches_calculator
from apex_tpu.utils.logging import get_logger

_GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
_GLOBAL_TIMERS = None


def setup_microbatch_calculator(
    rank: int,
    rampup_batch_size: Optional[List[int]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
) -> None:
    """Reference: utils.py:58."""
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    assert _GLOBAL_NUM_MICROBATCHES_CALCULATOR is None, "num microbatches calculator is already initialized."
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size, data_parallel_size
    )


def _reconfigure_microbatch_calculator(
    rank, rampup_batch_size, global_batch_size, micro_batch_size, data_parallel_size
) -> None:
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size, data_parallel_size
    )


def get_micro_batch_size():
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.micro_batch_size


def get_num_microbatches():
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get()


def get_current_global_batch_size():
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get_current_global_batch_size()


def update_num_microbatches(consumed_samples, consistency_check=True):
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR.update(consumed_samples, consistency_check)


def destroy_num_microbatches_calculator():
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None


def get_kth_microbatch(batch, k: int):
    """Slice microbatch k out of a pytree batch (reference utils.py:122)."""
    if batch is None:
        return batch
    mbs = get_micro_batch_size()
    return jax.tree.map(lambda x: x[k * mbs : (k + 1) * mbs], batch)


def listify_model(model):
    if isinstance(model, list):
        return model
    return [model]


def calc_params_l2_norm(params, bf16: bool = False, attrs=None, tp_rank: int = 0,
                        axis_name=None, tp_axis_name=None):
    """Reference: utils.py:213 — global L2 norm over params (the
    multi_tensor_l2norm kernel).

    ``attrs``: optional spec tree of
    :class:`~apex_tpu.transformer.tensor_parallel.TensorParallelAttributes`
    mirroring ``params``; when given, TP-replicated params are counted
    only on tp rank 0 (the reference filters with
    ``param_is_not_tensor_parallel_duplicate``, utils.py:217-222).

    ``axis_name``: mesh axis (or tuple of axes) the param *views* are
    sharded over.  The reference all-reduces norm² across the
    model-parallel group (utils.py:234-238); here, when called inside
    ``shard_map`` on per-rank shards, pass the axis name(s) and the
    norm² is psum-med the same way.  Without it the result is the norm
    of the LOCAL shard only — callers on sharded views must either pass
    ``axis_name`` or psum the squared result themselves.

    With BOTH ``attrs`` and ``axis_name``: sharded leaves contribute
    from every rank (each owns a distinct slice); TP-replicated leaves
    contribute only where ``lax.axis_index(tp) == 0`` (a traced analog
    of the reference's rank-0-only counting — a static ``tp_rank``
    filter would count them once PER rank and inflate the psum).  The
    dedup weighting applies to the TP axis ONLY — the reference filters
    TP duplicates and then all-reduces over the full mp group
    (utils.py:217-238); a tp-replicated leaf on another listed axis
    (e.g. pp-stage-sharded LN params) is still distinct per rank there
    and must count from every rank of that axis.  ``tp_axis_name``
    selects the dedup axis (default: the first axis of ``axis_name``)."""
    if attrs is not None and axis_name is not None:
        from apex_tpu.transformer.tensor_parallel.attributes import (
            set_defaults_if_not_set_tensor_model_parallel_attributes as _defaults,
        )

        axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
        dedup_ax = tp_axis_name if tp_axis_name is not None else axes[0]
        on_rank0 = (jax.lax.axis_index(dedup_ax) == 0).astype(jnp.float32)
        leaves, treedef = jax.tree.flatten(params)
        attr_leaves = treedef.flatten_up_to(attrs)
        sq = jnp.float32(0.0)
        for p, a in zip(leaves, attr_leaves):
            contrib = jnp.sum(jnp.square(p.astype(jnp.float32)))
            if not _defaults(a).tensor_model_parallel:
                contrib = contrib * on_rank0
            sq = sq + contrib
        return jnp.sqrt(jax.lax.psum(sq, axis_name))
    if attrs is not None:
        from apex_tpu.transformer.tensor_parallel.attributes import (
            param_is_not_tensor_parallel_duplicate,
        )

        # tree.map validates the two trees have the same structure, so a
        # misplaced None in attrs fails loudly instead of misaligning
        keep = jax.tree.map(
            lambda p, a: p if param_is_not_tensor_parallel_duplicate(a, tp_rank) else None,
            params, attrs,
            is_leaf=lambda x: x is None or hasattr(x, "partition_dim"),
        )
        params = [p for p in jax.tree.leaves(keep) if p is not None]
    norm = multi_tensor_l2norm(params)
    if axis_name is not None:
        norm = jnp.sqrt(jax.lax.psum(jnp.square(norm), axis_name))
    return norm


def print_rank_0(message: str) -> None:
    """Reference: utils.py:159 — only process 0 prints."""
    if jax.process_index() == 0:
        print(message, flush=True)


def print_rank_last(message: str) -> None:
    if jax.process_index() == jax.process_count() - 1:
        print(message, flush=True)


def report_memory(name: str) -> None:
    """Reference: utils.py:253 — device memory stats."""
    try:
        stats = jax.local_devices()[0].memory_stats()
        mega = 1024 * 1024
        print_rank_0(
            f"[{name}] memory: {stats.get('bytes_in_use', 0) / mega:.1f}MB in use / "
            f"{stats.get('bytes_limit', 0) / mega:.1f}MB limit"
        )
    except Exception:
        print_rank_0(f"[{name}] memory stats unavailable on this backend")


def get_autoresume():
    """Reference: utils.py:142 — optional hook to an ADLR AutoResume
    session.  No such service exists here; always None (the reference
    also returns its module global, which is never set in apex)."""
    return None


@jax.jit
def _param_stats(t):
    return [(jnp.min(x), jnp.max(x), jnp.linalg.norm(jnp.ravel(x).astype(jnp.float32)))
            for x in jax.tree.leaves(t)]


def print_params_min_max_norm(params, iteration: int) -> None:
    """Reference: utils.py:265 — per-tensor min/max/L2-norm debug dump.

    Functional form: takes the param pytree (the reference walks
    ``optimizer.param_groups``).  One jitted pass computes all stats
    device-side; the host loop only formats."""
    stats = _param_stats(params)
    lines = ["iteration, rank, index, min, max, norm"]
    rank = jax.process_index()
    for index, (mn, mx, nm) in enumerate(stats, 1):
        lines.append(
            f"{iteration:7d}, {rank:4d}, {index:4d}, "
            f"{float(mn):.6E}, {float(mx):.6E}, {float(nm):.6E}"
        )
    print("\n".join(lines), flush=True)


def get_ltor_masks_and_position_ids(
    data,
    eod_token: int,
    reset_position_ids: bool = False,
    reset_attention_mask: bool = False,
    eod_mask_loss: bool = False,
):
    """Left-to-right (causal) masks + position ids (reference utils.py:303).

    The document-reset variants rebuild positions/masks at EOD tokens.
    Returns (attention_mask [b,1,s,s] bool True=masked, loss_mask [b,s],
    position_ids [b,s]).
    """
    b, s = data.shape
    att = ~jnp.tril(jnp.ones((s, s), bool))  # True above diagonal = masked
    attention_mask = jnp.broadcast_to(att, (b, 1, s, s))

    loss_mask = jnp.ones((b, s), jnp.float32)
    if eod_mask_loss:
        loss_mask = jnp.where(data == eod_token, 0.0, loss_mask)

    position_ids = jnp.broadcast_to(jnp.arange(s), (b, s))
    if reset_position_ids or reset_attention_mask:
        # positions restart after each EOD; attention cannot cross EOD
        is_eod = (data == eod_token).astype(jnp.int32)
        doc_id = jnp.cumsum(is_eod, axis=1) - is_eod  # doc index per token
        if reset_position_ids:
            # position = index - index_of_doc_start
            idx = jnp.broadcast_to(jnp.arange(s), (b, s))
            doc_start = jax.vmap(
                lambda d, i: jax.vmap(lambda dd: jnp.min(jnp.where(d == dd, i, s)))(d)
            )(doc_id, idx)
            position_ids = idx - jnp.take_along_axis(doc_start, doc_id, axis=1)
        if reset_attention_mask:
            cross_doc = doc_id[:, :, None] != doc_id[:, None, :]
            attention_mask = attention_mask | cross_doc[:, None, :, :]
    return attention_mask, loss_mask, position_ids


class _Timer:
    """CUDA-sync timers → block_until_ready timers (reference _timers.py:1-40)."""

    def __init__(self, name):
        self.name_ = name
        self.elapsed_ = 0.0
        self.started_ = False
        self.start_time = time.time()

    def start(self):
        assert not self.started_, "timer has already been started"
        (jax.device_put(0.0) + 0).block_until_ready()
        self.start_time = time.time()
        self.started_ = True

    def stop(self):
        assert self.started_, "timer is not started"
        (jax.device_put(0.0) + 0).block_until_ready()
        self.elapsed_ += time.time() - self.start_time
        self.started_ = False

    def reset(self):
        self.elapsed_ = 0.0
        self.started_ = False

    def elapsed(self, reset=True):
        started_ = self.started_
        if self.started_:
            self.stop()
        elapsed_ = self.elapsed_
        if reset:
            self.reset()
        if started_:
            self.start()
        return elapsed_


class _Timers:
    """Named timer group (reference _timers.py:43-83)."""

    def __init__(self):
        self.timers = {}

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def log(self, names, normalizer=1.0, reset=True):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            elapsed_time = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
            string += " | {}: {:.2f}".format(name, elapsed_time)
        print_rank_last(string)


def get_timers():
    global _GLOBAL_TIMERS
    if _GLOBAL_TIMERS is None:
        _GLOBAL_TIMERS = _Timers()
    return _GLOBAL_TIMERS
