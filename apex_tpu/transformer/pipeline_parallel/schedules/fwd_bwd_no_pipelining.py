"""No-pipelining schedule: sequential microbatches with grad accumulation.

Reference: ``schedules/fwd_bwd_no_pipelining.py:23`` — run each
microbatch's forward+backward in turn, accumulating grads, with the loss
divided by the number of microbatches (common.py:305-309).
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp


def forward_backward_no_pipelining(
    forward_step_func: Callable,
    batch,
    model=None,
    *,
    forward_only: bool = False,
    **kwargs,
):
    """``forward_step_func(params, microbatch) -> loss`` (scalar).

    ``batch`` is a pytree whose leaves have a leading microbatch dim
    ``(M, ...)``; ``model`` is the param pytree.  Returns
    ``(per_microbatch_losses, accumulated_grads_or_None)``; each
    microbatch's contribution is scaled by 1/M exactly as the reference
    scales the loss before backward.
    """
    params = model
    leaves = jax.tree.leaves(batch)
    M = leaves[0].shape[0]

    def one(params, mb):
        if forward_only:
            return forward_step_func(params, mb), None
        loss, grads = jax.value_and_grad(forward_step_func)(params, mb)
        return loss, grads

    def body(carry, mb):
        acc = carry
        loss, grads = one(params, mb)
        if grads is not None:
            acc = jax.tree.map(lambda a, g: a + g / M, acc, grads)
        return acc, loss

    if forward_only:
        losses = []
        for i in range(M):
            mb = jax.tree.map(lambda x: x[i], batch)
            losses.append(forward_step_func(params, mb))
        return jnp.stack(losses), None

    acc0 = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
    acc, losses = jax.lax.scan(body, acc0, batch)
    return losses, acc
