"""Pipeline schedule building blocks.

Reference: ``apex/transformer/pipeline_parallel/schedules/common.py`` —
``build_model`` (:30), ``forward_step``/``backward_step`` (:253,:325),
``custom_backward`` (:219).

The TPU-native core is :func:`pipelined_apply`: a ``lax.scan`` over
``num_microbatches + P - 1`` ticks where every tick each stage applies
its local layer chunk and ``ppermute`` shifts activations one stage
forward — the software-pipeline shape of 1F1B's steady state, expressed
as one compiled program.  Differentiating through the scan yields the
backward pipeline automatically (ppermute's transpose is the reverse
shift), replacing the reference's hand-scheduled
warmup/steady/cooldown phases and ``custom_backward``.
"""

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import PIPELINE_AXIS


def pipelined_apply(stage_fn, stage_params, mb_inputs, axis_name: str = PIPELINE_AXIS):
    """Run microbatched inputs through the P-stage pipeline.

    ``mb_inputs``: ``(M, ...)`` microbatch activations fed to stage 0.
    ``stage_fn(stage_params, x) -> y`` is this stage's chunk (same
    activation shape in/out — the transformer block contract of reference
    §3.4, shape ``(seq, mbs, hidden)``).

    Returns ``(M, ...)`` outputs, valid on the LAST stage (zeros
    elsewhere); combine with :func:`broadcast_from_last_stage`.
    """
    P = jax.lax.axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    M = mb_inputs.shape[0]
    T = M + P - 1
    perm = [(i, (i + 1) % P) for i in range(P)]

    zero = jnp.zeros_like(mb_inputs[0])
    out_buf = jnp.zeros_like(mb_inputs)

    def tick(carry, t):
        incoming, out_buf = carry
        m = t - stage  # microbatch index this stage processes at tick t
        x = jnp.where(stage == 0, mb_inputs[jnp.clip(t, 0, M - 1)], incoming)
        y = stage_fn(stage_params, x)
        active = (m >= 0) & (m < M)
        written = jax.lax.dynamic_update_index_in_dim(
            out_buf, y, jnp.clip(m, 0, M - 1), 0
        )
        out_buf = jnp.where(active & (stage == P - 1), written, out_buf)
        incoming = jax.lax.ppermute(y, axis_name, perm)
        return (incoming, out_buf), None

    (_, out_buf), _ = jax.lax.scan(tick, (zero, out_buf), jnp.arange(T))
    return out_buf


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def broadcast_from_last_stage(x, axis_name: str = PIPELINE_AXIS):
    """Last stage's value on every stage; backward routes the cotangent
    to the last stage only (the pp analog of the embedding-group
    broadcast in reference parallel_state.py:50-56)."""
    P = jax.lax.axis_size(axis_name)
    return jax.lax.all_gather(x, axis_name, axis=0)[P - 1]


def _bcast_fwd(x, axis_name):
    P = jax.lax.axis_size(axis_name)
    return jax.lax.all_gather(x, axis_name, axis=0)[P - 1], None


def _bcast_bwd(axis_name, _, g):
    P = jax.lax.axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    return (jnp.where(stage == P - 1, g, jnp.zeros_like(g)),)


broadcast_from_last_stage.defvjp(_bcast_fwd, _bcast_bwd)


def build_model(
    model_provider_func: Callable,
    wrap_with_ddp: bool = True,
    virtual_pipeline_model_parallel_size=None,
    **kwargs,
):
    """Reference: schedules/common.py:30 — builds (a list of) model
    chunks with pre_process/post_process flags per stage.  In the TPU
    design the per-stage split is a *sharding of stacked layer params*
    over the ``pp`` mesh axis, so this returns the provider's result; the
    virtual-chunk list shape is kept for interleaved scheduling."""
    if virtual_pipeline_model_parallel_size is None:
        return [model_provider_func(**kwargs)]
    return [
        model_provider_func(**kwargs)
        for _ in range(virtual_pipeline_model_parallel_size)
    ]
