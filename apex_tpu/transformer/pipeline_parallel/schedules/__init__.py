"""Schedule selector (reference: ``schedules/__init__.py:22``)."""

from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    broadcast_from_last_stage,
    build_model,
    pipelined_apply,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_no_pipelining import (
    forward_backward_no_pipelining,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_without_interleaving import (
    forward_backward_pipelining_without_interleaving,
    make_pipeline_loss_fn,
)


def get_forward_backward_func(virtual_pipeline_model_parallel_size, pipeline_model_parallel_size):
    """Reference: schedules/__init__.py:22 — pick the schedule.

    The interleaved (virtual-pipeline) schedule lowers to the same
    tick-scan machinery with stage chunks; until it lands, requesting it
    raises.
    """
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None:
            raise NotImplementedError(
                "interleaved virtual-pipeline schedule: planned (use "
                "forward_backward_pipelining_without_interleaving)"
            )
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining


__all__ = [
    "get_forward_backward_func",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "make_pipeline_loss_fn",
    "pipelined_apply",
    "broadcast_from_last_stage",
    "build_model",
]
