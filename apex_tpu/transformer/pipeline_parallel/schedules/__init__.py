"""Schedule selector (reference: ``schedules/__init__.py:22``)."""

from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    broadcast_from_last_stage,
    build_model,
    pipelined_apply,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_no_pipelining import (
    forward_backward_no_pipelining,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_without_interleaving import (
    forward_backward_pipelining_without_interleaving,
    make_pipeline_loss_fn,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_with_interleaving import (
    forward_backward_pipelining_with_interleaving,
    interleaved_pipelined_apply,
)
from apex_tpu.transformer.pipeline_parallel.schedules.tick_schedule_encdec import (
    forward_backward_pipelining_encdec,
    pad_stage_layout_encdec,
    unpad_stage_layout_encdec,
)


def get_forward_backward_func(virtual_pipeline_model_parallel_size, pipeline_model_parallel_size):
    """Reference: schedules/__init__.py:22 — pick the schedule."""
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None:
            return forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining


__all__ = [
    "get_forward_backward_func",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "forward_backward_pipelining_with_interleaving",
    "forward_backward_pipelining_encdec",
    "interleaved_pipelined_apply",
    "make_pipeline_loss_fn",
    "pad_stage_layout_encdec",
    "pipelined_apply",
    "broadcast_from_last_stage",
    "build_model",
    "unpad_stage_layout_encdec",
]
