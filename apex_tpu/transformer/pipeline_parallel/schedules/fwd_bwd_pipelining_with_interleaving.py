"""Interleaved (virtual-pipeline) schedule.

Reference: ``schedules/fwd_bwd_pipelining_with_interleaving.py:27`` —
each physical stage owns ``vpp`` non-contiguous layer chunks (stage s
holds chunks s, s+pp, s+2pp, ...) and round-robins microbatches over
chunks to shrink the pipeline bubble from (P-1)/M to (P-1)/(M·vpp).

TPU form: the same explicit fwd+bwd tick schedule as the
non-interleaved case (:func:`~..tick_schedule.pipelined_fwd_bwd`) with
``num_chunks=vpp``: the forward ``ppermute`` ring's wraparound (stage
P-1 → 0) is the cross-chunk hop, so one ring drives all vpp chunks; a
reverse ring carries cotangents.  The dense per-stage slot ordering
(group of P microbatches → chunk-major within the group) gives the
Megatron bubble reduction analytically: total ticks =
vpp·M + (P-1) + (V-1) at 1/vpp per-tick cost → bubble (P-1)/vpp
microbatch-equivalents instead of (P-1).  Live activations are
O(vpp·P), the interleaved schedule's usual memory premium over 1F1B.
"""

from typing import Callable

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import PIPELINE_AXIS
from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    broadcast_from_last_stage,
    pipelined_apply,
)


def interleaved_pipelined_apply(stage_fn, stage_params, mb_inputs, vpp: int, axis_name=PIPELINE_AXIS):
    """Run microbatches through ``vpp`` virtual chunks × P stages.

    ``stage_params``: this stage's layers, leaves shaped
    ``(vpp * layers_per_chunk, ...)`` with chunk v at
    ``leaf[v*lpc:(v+1)*lpc]`` (so a GLOBAL array sharded ``P("pp")`` on
    the layer axis must be ordered stage-major, then chunk, then layer —
    the reference's assignment of chunks s, s+pp, s+2pp to stage s,
    fwd_bwd_pipelining_with_interleaving.py:27).  Global execution order
    is chunk-major: (v=0, s=0..P-1), (v=1, s=0..P-1), ...
    """
    P = jax.lax.axis_size(axis_name)
    perm = [(i, (i + 1) % P) for i in range(P)]

    def chunk_of(v):
        return jax.tree.map(
            lambda l: l.reshape(vpp, l.shape[0] // vpp, *l.shape[1:])[v], stage_params
        )

    outs = mb_inputs
    for v in range(vpp):
        outs = pipelined_apply(stage_fn, chunk_of(v), outs, axis_name)
        if v < vpp - 1:
            # results live on the last stage; rotate them to stage 0 to
            # feed the next virtual chunk (one ppermute — the cross-chunk
            # p2p of the reference's interleaved schedule)
            outs = jax.lax.ppermute(outs, axis_name, perm)
    return outs


def forward_backward_pipelining_with_interleaving(
    pre_fn: Callable,
    stage_fn: Callable,
    post_fn: Callable,
    shared_params,
    stage_params,
    microbatches,
    *,
    virtual_pipeline_model_parallel_size: int = 2,
    forward_only: bool = False,
    axis_name: str = PIPELINE_AXIS,
    stage_has_aux: bool = False,
):
    """Interleaved analog of the non-interleaved fwd_bwd; stage params
    hold ``vpp`` chunks stacked on the layer axis (see
    :func:`interleaved_pipelined_apply` for the layout)."""
    vpp = virtual_pipeline_model_parallel_size

    if forward_only:
        def loss_fn(shared, stages, mbs):
            acts = jax.vmap(lambda mb: pre_fn(shared, mb))(mbs)
            outs = interleaved_pipelined_apply(stage_fn, stages, acts, vpp, axis_name)
            losses = jax.vmap(lambda y, mb: post_fn(shared, y, mb))(outs, mbs)
            return broadcast_from_last_stage(jnp.mean(losses), axis_name)

        return loss_fn(shared_params, stage_params, microbatches), None

    from apex_tpu.transformer.pipeline_parallel.schedules.tick_schedule import (
        pipelined_fwd_bwd,
    )

    loss, (g_shared, g_stage) = pipelined_fwd_bwd(
        pre_fn, stage_fn, post_fn, shared_params, stage_params, microbatches,
        num_chunks=vpp, axis_name=axis_name, stage_has_aux=stage_has_aux,
    )
    g_shared = jax.tree.map(lambda g: jax.lax.psum(g, axis_name), g_shared)
    return loss, (g_shared, g_stage)
