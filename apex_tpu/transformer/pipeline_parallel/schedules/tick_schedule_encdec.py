"""Encoder-decoder (T5-style) 1F1B tick schedule: dual activation
streams across the pipeline split rank.

Reference: ``apex/transformer/pipeline_parallel/schedules/
fwd_bwd_pipelining_without_interleaving.py:50-84`` (``ModelType.
encoder_and_decoder``: ranks before the split carry ONE tensor — the
encoder stream at the encoder sequence length; ranks at/after the split
carry TWO — the decoder stream plus the encoder's final output
forwarded stage-to-stage for cross-attention) and ``schedules/
common.py:85-100`` (``add_encoder``/``add_decoder`` role assignment by
``pipeline_model_parallel_split_rank``).

TPU-native redesign.  The reference routes per-rank control flow: each
rank materializes a different send/recv shape list and runs only its
own role's module.  Under SPMD (one program on every pp rank via
``shard_map``) the same semantics come from three moves:

- **Uniform dual-stream message.**  Every hop ``ppermute``s the PAIR
  ``(a_enc, a_dec)``.  Before the split, ``a_enc`` is the live encoder
  stream and ``a_dec`` rides as zeros; at/after the split, ``a_dec`` is
  the live decoder stream and ``a_enc`` carries the encoder's final
  output — the exact two-tensor protocol of the reference, expressed as
  one static shape so XLA compiles a single program.
- **``lax.cond``-gated roles.**  ``stage < split`` picks the encoder or
  decoder branch per tick.  The predicate depends only on the stage
  index — uniform along tp — so tp collectives inside either branch
  stay in lockstep (the same argument that gates the loss head in
  :mod:`tick_schedule`).  Only the taken branch executes: encoder
  stages never pay for decoder FLOPs or vice versa.
- **Boundary seeding, both directions.**  Stage ``split`` seeds the
  decoder stream from ``pre_dec_fn`` (the decoder embedding) exactly as
  stage 0 seeds the encoder stream from ``pre_enc_fn``; in backward,
  stage ``split`` routes the decoder-input cotangent into the shared
  params via the ``pre_dec_fn`` vjp (cond-gated) while the encoder-
  output cotangent — accumulated through every decoder stage's
  cross-attention — rides the reverse ring into the encoder stages.

Interleaving (vpp > 1) is intentionally unsupported, matching the
reference: its interleaved schedule asserts ``encoder_or_decoder``
only.  Timing/memory are the vpp=1 case of :mod:`tick_schedule`:
warmup P-1 forward ticks, M+?? steady 1F1B ticks, P-1 backward
cooldown, activation buffer of min(2P-1, M) stream PAIRS.

Per-stage parameter layout: SPMD needs every stage to hold the same
pytree structure, so encoder chunks live in a ``(P·lpc_e, ...)``
stacked array (real layers on stages < split, zeros elsewhere) and
decoder chunks mirror that — see :func:`pad_stage_layout_encdec`.  The
zero chunks cost HBM but no FLOPs (their branch never runs); their
grads come back zero, so optimizers keep them at zero (zero params
see zero weight-decay pull).
"""

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import PIPELINE_AXIS
from apex_tpu.transformer.pipeline_parallel.schedules.tick_schedule import (
    _index_tree,
    _mask_add,
)


def pad_stage_layout_encdec(enc_layers, dec_layers, pp: int, split: int):
    """Stack per-side layer trees into the uniform SPMD layout.

    ``enc_layers`` leaves are ``(L_enc, ...)``; returns leaves of shape
    ``(pp·lpc_e, ...)`` with stages ``< split`` holding the real
    chunks (lpc_e = L_enc // split) and later stages zeros — and the
    mirrored layout for ``dec_layers`` (real on stages >= split).
    Shard the results over the pp mesh axis on dim 0."""
    if not (0 < split < pp):
        raise ValueError(f"split must be in (0, {pp}); got {split}")

    def pad(tree, n_layers, first, count, lpc):
        if n_layers % count:
            raise ValueError(
                f"{n_layers} layers do not divide over {count} stages"
            )

        def one(a):
            out = jnp.zeros((pp * lpc, *a.shape[1:]), a.dtype)
            return jax.lax.dynamic_update_slice_in_dim(
                out, a, first * lpc, axis=0
            )

        return jax.tree.map(one, tree)

    L_e = jax.tree.leaves(enc_layers)[0].shape[0]
    L_d = jax.tree.leaves(dec_layers)[0].shape[0]
    lpc_e = L_e // split
    lpc_d = L_d // (pp - split)
    return (
        pad(enc_layers, L_e, 0, split, lpc_e),
        pad(dec_layers, L_d, split, pp - split, lpc_d),
    )


def unpad_stage_layout_encdec(enc_padded, dec_padded, pp: int, split: int):
    """Inverse of :func:`pad_stage_layout_encdec` (e.g. for checkpoints
    interchangeable with the non-pipelined layout)."""

    def cut(tree, first, count):
        def one(a):
            lpc = a.shape[0] // pp
            return jax.lax.dynamic_slice_in_dim(
                a, first * lpc, count * lpc, axis=0
            )

        return jax.tree.map(one, tree)

    return cut(enc_padded, 0, split), cut(dec_padded, split, pp - split)


def pipelined_fwd_bwd_encdec(
    pre_enc_fn: Callable,
    pre_dec_fn: Callable,
    enc_stage_fn: Callable,
    dec_stage_fn: Callable,
    post_fn: Callable,
    shared_params,
    enc_stage_params,
    dec_stage_params,
    microbatches,
    *,
    split: int,
    axis_name: str = PIPELINE_AXIS,
):
    """1F1B fwd+bwd for an encoder-decoder model over the pp axis.

    - ``pre_enc_fn(shared, mb) -> x_enc`` — encoder embedding, stage 0
    - ``pre_dec_fn(shared, mb) -> x_dec`` — decoder embedding, stage
      ``split`` (reference common.py:92: ``pre_process`` is True on
      rank 0 AND rank split)
    - ``enc_stage_fn(enc_chunk, x_enc) -> y_enc``
    - ``dec_stage_fn(dec_chunk, x_dec, enc_out) -> y_dec`` —
      ``enc_out`` is the encoder's final output (cross-attention keys)
    - ``post_fn(shared, y_dec, mb) -> scalar loss`` — stage P-1

    ``enc_stage_params`` / ``dec_stage_params`` are this stage's local
    chunks in the :func:`pad_stage_layout_encdec` layout (zeros on the
    other side's stages).  Returns ``(loss, (shared_grads,
    enc_stage_grads, dec_stage_grads))``; shared grads are LOCAL
    per-stage contributions — psum over the pipeline axis to combine.
    """
    Pp = jax.lax.axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    M = jax.tree.leaves(microbatches)[0].shape[0]

    n_slots = M
    delta = Pp - 1
    S_buf = min(2 * Pp - 1, n_slots)
    inv_m = 1.0 / M
    is_enc = stage < split

    mb0 = _index_tree(microbatches, jnp.int32(0))
    xe_shape = jax.eval_shape(pre_enc_fn, shared_params, mb0)
    xd_shape = jax.eval_shape(pre_dec_fn, shared_params, mb0)
    zero_enc = jnp.zeros(xe_shape.shape, xe_shape.dtype)
    zero_dec = jnp.zeros(xd_shape.shape, xd_shape.dtype)

    perm_fwd = [(i, (i + 1) % Pp) for i in range(Pp)]
    perm_bwd = [(i, (i - 1) % Pp) for i in range(Pp)]

    def stage_pair_fn(chunks, x_pair):
        """The SPMD role dispatch: encoder stages transform the enc
        stream (dec rides zeros); decoder stages pass the enc output
        through untouched and transform the dec stream.  One branch
        executes per stage; vjp of the cond is the cond of the vjps,
        with zero cotangents for the untaken branch's params."""
        enc_chunk, dec_chunk = chunks
        xe, xd = x_pair
        return jax.lax.cond(
            is_enc,
            lambda: (enc_stage_fn(enc_chunk, xe), zero_dec),
            lambda: (xe, dec_stage_fn(dec_chunk, xd, xe)),
        )

    def tick(carry, t, do_fwd, do_bwd, do_post):
        (msg_e, msg_d, cot_e, cot_d, xbuf_e, xbuf_d,
         loss_sum, g_sh, g_enc, g_dec) = carry
        seed_dx = zero_dec

        if do_fwd:
            u = t - stage
            m = jnp.clip(u, 0, M - 1)
            ok = (u >= 0) & (u < n_slots)
            mb = _index_tree(microbatches, m)
            # stream seeds: stage 0 embeds the source, stage `split`
            # embeds the target.  cond-gated (not masked-but-executed):
            # the embedding gather + its tp collective run only on the
            # seeding stage — the predicates are tp-uniform, so the
            # collectives inside the taken branch stay in lockstep
            xe = jax.lax.cond(
                stage == 0,
                lambda: pre_enc_fn(shared_params, mb).astype(msg_e.dtype),
                lambda: msg_e)
            xd = jax.lax.cond(
                stage == split,
                lambda: pre_dec_fn(shared_params, mb).astype(msg_d.dtype),
                lambda: msg_d)
            slot = jnp.clip(u, 0, n_slots - 1) % S_buf
            xbuf_e = jnp.where(
                ok, jax.lax.dynamic_update_index_in_dim(xbuf_e, xe, slot, 0),
                xbuf_e)
            xbuf_d = jnp.where(
                ok, jax.lax.dynamic_update_index_in_dim(xbuf_d, xd, slot, 0),
                xbuf_d)
            ye, yd = stage_pair_fn((enc_stage_params, dec_stage_params),
                                   (xe, xd))
            if do_post:
                last = ok & (stage == Pp - 1)

                def _post(operand):
                    loss_sum, g_sh = operand
                    loss_m, post_vjp = jax.vjp(
                        lambda sh, h: post_fn(sh, h, mb), shared_params, yd
                    )
                    d_sh_post, dy_seed = post_vjp(
                        jnp.asarray(inv_m, loss_m.dtype))
                    g_sh = jax.tree.map(jnp.add, g_sh, d_sh_post)
                    return (loss_sum + loss_m * inv_m, g_sh,
                            dy_seed.astype(zero_dec.dtype))

                loss_sum, g_sh, seed_dx = jax.lax.cond(
                    last, _post,
                    lambda op: (op[0], op[1], zero_dec), (loss_sum, g_sh)
                )
            msg_e = jax.lax.ppermute(ye, axis_name, perm_fwd)
            msg_d = jax.lax.ppermute(yd, axis_name, perm_fwd)

        if do_bwd:
            ub = t - delta - (Pp - 1) + stage
            ok_b = (ub >= 0) & (ub < n_slots)
            m_b = jnp.clip(ub, 0, M - 1)
            slot = jnp.clip(ub, 0, n_slots - 1) % S_buf
            xe_s = jax.lax.dynamic_index_in_dim(xbuf_e, slot, 0,
                                                keepdims=False)
            xd_s = jax.lax.dynamic_index_in_dim(xbuf_d, slot, 0,
                                                keepdims=False)
            last = stage == Pp - 1
            # the last stage's enc-output passthrough feeds nothing
            # downstream (the ring wraps to stage 0's seed), so its
            # cotangent seed is zero; the dec stream seeds from the
            # loss head's vjp
            dye = jnp.where(last, jnp.zeros_like(cot_e), cot_e)
            dyd = jnp.where(last, seed_dx, cot_d)
            _, pair_vjp = jax.vjp(
                stage_pair_fn, (enc_stage_params, dec_stage_params),
                (xe_s, xd_s))
            (d_enc_c, d_dec_c), (dxe, dxd) = pair_vjp((dye, dyd))
            g_enc = _mask_add(g_enc, d_enc_c, ok_b)
            g_dec = _mask_add(g_dec, d_dec_c, ok_b)

            mb = _index_tree(microbatches, m_b)
            # stage 0: encoder-input cotangent -> source embedding grads
            pre_e = ok_b & (stage == 0)

            def _pre_e(g_sh):
                _, vjp = jax.vjp(lambda sh: pre_enc_fn(sh, mb),
                                 shared_params)
                (d_sh,) = vjp(dxe.astype(xe_shape.dtype))
                return jax.tree.map(jnp.add, g_sh, d_sh)

            g_sh = jax.lax.cond(pre_e, _pre_e, lambda g: g, g_sh)
            # stage split: decoder-input cotangent -> target embedding
            # grads (it must NOT ride the ring into the encoder side;
            # encoder stages' zero-output dec branch would ignore it,
            # but the pre_dec vjp is where it belongs)
            pre_d = ok_b & (stage == split)

            def _pre_d(g_sh):
                _, vjp = jax.vjp(lambda sh: pre_dec_fn(sh, mb),
                                 shared_params)
                (d_sh,) = vjp(dxd.astype(xd_shape.dtype))
                return jax.tree.map(jnp.add, g_sh, d_sh)

            g_sh = jax.lax.cond(pre_d, _pre_d, lambda g: g, g_sh)
            cot_e = jax.lax.ppermute(dxe, axis_name, perm_bwd)
            cot_d = jax.lax.ppermute(dxd, axis_name, perm_bwd)

        return (msg_e, msg_d, cot_e, cot_d, xbuf_e, xbuf_d,
                loss_sum, g_sh, g_enc, g_dec), None

    xbuf_e0 = jnp.zeros((S_buf, *xe_shape.shape), xe_shape.dtype)
    xbuf_d0 = jnp.zeros((S_buf, *xd_shape.shape), xd_shape.dtype)
    g_sh0 = jax.tree.map(jnp.zeros_like, shared_params)
    g_enc0 = jax.tree.map(jnp.zeros_like, enc_stage_params)
    g_dec0 = jax.tree.map(jnp.zeros_like, dec_stage_params)
    carry = (zero_enc, zero_dec, zero_enc, zero_dec, xbuf_e0, xbuf_d0,
             jnp.float32(0.0), g_sh0, g_enc0, g_dec0)

    def run(carry, lo, hi, **kw):
        if hi <= lo:
            return carry
        body = partial(tick, **kw)
        carry, _ = jax.lax.scan(
            lambda c, t: body(c, t), carry,
            jnp.arange(lo, hi, dtype=jnp.int32))
        return carry

    steady_end = n_slots + Pp - 1
    carry = run(carry, 0, delta, do_fwd=True, do_bwd=False, do_post=False)
    carry = run(carry, delta, steady_end, do_fwd=True, do_bwd=True,
                do_post=True)
    carry = run(carry, steady_end, steady_end + delta, do_fwd=False,
                do_bwd=True, do_post=False)

    loss_sum, g_sh, g_enc, g_dec = carry[6], carry[7], carry[8], carry[9]
    loss = jax.lax.psum(loss_sum, axis_name)
    return loss, (g_sh, g_enc, g_dec)


def forward_backward_pipelining_encdec(
    pre_enc_fn, pre_dec_fn, enc_stage_fn, dec_stage_fn, post_fn,
    shared_params, enc_stage_params, dec_stage_params, microbatches,
    *, split: int, axis_name: str = PIPELINE_AXIS,
):
    """Run the encoder-decoder 1F1B schedule; returns
    ``(loss, (shared_grads, enc_stage_grads, dec_stage_grads))`` with
    shared-param grads psum'd over the pipeline axis (each contribution
    lives on exactly one stage: source embedding on 0, target embedding
    on ``split``, head on P-1 — the reference's embedding-grad
    allreduce between first/split/last ranks,
    ``apex/transformer/parallel_state.py:316-340`` embedding groups)."""
    loss, (g_sh, g_enc, g_dec) = pipelined_fwd_bwd_encdec(
        pre_enc_fn, pre_dec_fn, enc_stage_fn, dec_stage_fn, post_fn,
        shared_params, enc_stage_params, dec_stage_params, microbatches,
        split=split, axis_name=axis_name,
    )
    g_sh = jax.tree.map(lambda g: jax.lax.psum(g, axis_name), g_sh)
    return loss, (g_sh, g_enc, g_dec)
