"""Explicit fwd+bwd tick schedule: true 1F1B memory behavior on TPU.

Reference: ``apex/transformer/pipeline_parallel/schedules/
fwd_bwd_pipelining_without_interleaving.py:241,344-436`` (warmup =
P-rank-1 forwards, one-forward-one-backward steady state, cooldown) and
``fwd_bwd_pipelining_with_interleaving.py:27`` (virtual chunks).

The round-1 design differentiated through a forward tick-scan, which is
exact but keeps every microbatch's residuals live until the backward
starts — GPipe memory, O(M).  This module schedules the backward
explicitly so live state is O(P) (O(vpp·P) interleaved), independent of
the microbatch count M:

- **Forward stream**: per tick each stage applies its layer chunk and a
  ``ppermute`` ring shifts activations one stage forward.  The ring's
  wraparound (stage P-1 → 0) is exactly the cross-chunk hop of the
  interleaved schedule, so vpp > 1 is the same program.
- **Backward stream**: a second, reverse ``ppermute`` ring carries
  cotangents.  Each stage's backward unit recomputes its forward from
  the *saved stage input* via ``jax.vjp`` (the per-microbatch
  ``jax.checkpoint`` strategy — trade ~f extra FLOPs per unit for not
  storing residuals, the reference's selective-recompute idea,
  reference ``:351-361``).
- **Activation buffer**: a circular buffer of ``min(2·vpp·P - 1,
  n_slots)`` stage inputs.  A microbatch's input is written at its
  forward tick and read at its backward tick ≤ 2·vpp·P - 2 ticks later,
  so the buffer never grows with M — the 1F1B property.
- **Grad accumulation**: parameter gradients accumulate into persistent
  carry buffers across microbatches *inside* the scan — the analog of
  the reference's ``wgrad_gemm_accum_fp32`` accumulating into
  ``main_grad`` (``csrc/megatron/fused_weight_gradient_dense.cpp:19``):
  one resident fp32 buffer, no per-microbatch grad materialization.

**Timing.**  Per-stage forward-slot counter ``u = t - stage`` decodes
mixed-radix ``u = g·V + v·P + r`` (group g of P microbatches, chunk v,
member r; ``V = vpp·P``); microbatch ``m = g·P + r``.  Backward-slot
counter ``u_b = t - (V-1) - (P-1) + stage`` decodes the mirror order
(chunks reversed).  Both streams are *dense*: every stage has forward
work at consecutive ticks [s, s + n_slots) and backward work at
[V-1 + P-1-s, ... + n_slots), so the schedule splits into three
statically-shaped scans:

  A. warmup   — V-1 ticks, forward units only      (cost f each)
  B. steady   — n_slots + P - V ticks, 1F + 1B     (cost f + b each)
  C. cooldown — V-1 ticks, backward units only     (cost b each)

Total = (f+b)·(n_slots + P - 1) ≈ (f+b)·vpp·(M + (P-1)/vpp): the
pipeline bubble is (P-1)/vpp microbatch-equivalents — the reference
1F1B bubble for vpp=1 and the Megatron interleaved bubble reduction for
vpp>1, obtained here from the segment split rather than per-rank
control flow (SPMD stages share one program; a stage with no unit at a
tick computes masked work, and the segment split removes the ticks
where *no* stage has work of that kind).

The pre/post units are ``lax.cond``-gated on their stage predicate
(stage 0 / stage P-1), so the loss head's vocab matmul pair and the
(vocab, H) embedding-grad scatter run only where the reference runs
them (first/last rank, reference ``:305-309``) — not masked-but-
executed on every stage.  The predicates depend only on (stage, tick),
i.e. they are uniform along tp, which keeps tp collectives inside
``pre_fn``/``post_fn`` in lockstep within each branch; pre/post must
not contain pp-axis collectives (they don't: they are per-stage
compute).
"""

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import PIPELINE_AXIS


def _index_tree(tree, i):
    return jax.tree.map(
        lambda a: jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False), tree
    )


def _mask_add(acc, contrib, mask):
    return jax.tree.map(
        lambda a, c: a + jnp.where(mask, c, jnp.zeros_like(c)), acc, contrib
    )


def pipelined_fwd_bwd(
    pre_fn: Callable,
    stage_fn: Callable,
    post_fn: Callable,
    shared_params,
    stage_params,
    microbatches,
    *,
    num_chunks: int = 1,
    axis_name: str = PIPELINE_AXIS,
    stage_has_aux: bool = False,
):
    """One-forward-one-backward pipeline with O(vpp·P) live activations.

    ``stage_params`` leaves are this stage's layers ``(vpp·lpc, ...)``
    with chunk v at ``[v·lpc:(v+1)·lpc]`` (stage-major global layout —
    same contract as the round-1 interleaved schedule).  Returns
    ``(loss, (shared_grads, stage_grads))``; shared grads are LOCAL
    contributions (pre on stage 0, post on stage P-1) — psum over the
    pipeline axis to combine, as the wrapper schedules do.

    ``stage_has_aux``: ``stage_fn`` returns ``(y, aux)`` with a scalar
    auxiliary loss (MoE load balancing, pre-weighted by the caller);
    aux is added to the loss per (stage, microbatch) unit and its
    cotangent (1/M) is seeded into each backward unit's vjp, so expert
    routers train identically to the non-pipelined path.
    """
    Pp = jax.lax.axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    vpp = num_chunks
    V = vpp * Pp
    M = jax.tree.leaves(microbatches)[0].shape[0]

    if vpp == 1:
        n_slots = M  # u == m directly; dense for any M
    else:
        n_slots = -(-M // Pp) * V  # ceil(M/P) groups; padding slots masked
    delta = V - 1  # tick of the first backward (last stage, last chunk, mb 0)
    S_buf = min(2 * V - 1, n_slots)
    inv_m = 1.0 / M

    chunked = jax.tree.map(
        lambda a: a.reshape(vpp, a.shape[0] // vpp, *a.shape[1:]), stage_params
    )

    def chunk_of(v):
        if vpp == 1:
            return stage_params
        return _index_tree(chunked, v)

    def decode_fwd(u):
        """forward-slot counter -> (chunk, microbatch, valid)."""
        if vpp == 1:
            m = u
            v = jnp.int32(0)
        else:
            g, q = jnp.divmod(u, V)
            v, r = jnp.divmod(q, Pp)
            m = g * Pp + r
        ok = (u >= 0) & (u < n_slots) & (m >= 0) & (m < M)
        return v, m, ok

    def decode_bwd(u):
        """backward-slot counter -> (chunk, microbatch, fwd-slot, valid)."""
        if vpp == 1:
            return jnp.int32(0), u, u, (u >= 0) & (u < n_slots)
        g, q = jnp.divmod(u, V)
        vq, r = jnp.divmod(q, Pp)
        v = (vpp - 1) - vq
        m = g * Pp + r
        u_fwd = g * V + v * Pp + r
        ok = (u >= 0) & (u < n_slots) & (m >= 0) & (m < M)
        return v, m, u_fwd, ok

    mb0 = _index_tree(microbatches, jnp.int32(0))
    x_shape = jax.eval_shape(pre_fn, shared_params, mb0)
    zero_act = jnp.zeros(x_shape.shape, x_shape.dtype)

    perm_fwd = [(i, (i + 1) % Pp) for i in range(Pp)]
    perm_bwd = [(i, (i - 1) % Pp) for i in range(Pp)]

    def tick(carry, t, do_fwd, do_bwd, do_post):
        act_msg, cot_msg, xbuf, loss_sum, g_sh, g_st = carry
        seed_dx = zero_act

        if do_fwd:
            u = t - stage
            v, m, ok = decode_fwd(u)
            m_c = jnp.clip(m, 0, M - 1)
            mb = _index_tree(microbatches, m_c)
            # cond-gated like the post head: the embedding gather (+ its
            # tp collective) runs only where the seed is consumed; the
            # predicate is tp-uniform so the collective stays in
            # lockstep within the taken branch
            first_vs = (stage == 0) & (v == 0)
            x = jax.lax.cond(
                first_vs,
                lambda: pre_fn(shared_params, mb).astype(act_msg.dtype),
                lambda: act_msg)
            slot = jnp.clip(u, 0, n_slots - 1) % S_buf
            written = jax.lax.dynamic_update_index_in_dim(xbuf, x, slot, 0)
            xbuf = jnp.where(ok, written, xbuf)
            if stage_has_aux:
                y, aux_v = stage_fn(chunk_of(jnp.clip(v, 0, vpp - 1)), x)
                loss_sum = loss_sum + jnp.where(
                    ok, aux_v.astype(jnp.float32) * inv_m, 0.0
                )
            else:
                y = stage_fn(chunk_of(jnp.clip(v, 0, vpp - 1)), x)
            if do_post:
                # Only stage P-1's last chunk runs the loss head.  The
                # predicate depends on (stage, tick) alone — uniform
                # across tp — so tp collectives inside post_fn stay in
                # lockstep within every cond branch.  Gating the vjp
                # (instead of masking its outputs) keeps the head matmul
                # pair and the (vocab, H)-sized grad accumulation off
                # the other P-1 stages' ticks: at vocab 32k those were
                # the dominant per-tick cost.
                last_vs = ok & (stage == Pp - 1) & (v == vpp - 1)

                def _post(operand):
                    loss_sum, g_sh = operand
                    loss_m, post_vjp = jax.vjp(
                        lambda sh, h: post_fn(sh, h, mb), shared_params, y
                    )
                    d_sh_post, dy_seed = post_vjp(jnp.asarray(inv_m, loss_m.dtype))
                    g_sh = jax.tree.map(jnp.add, g_sh, d_sh_post)
                    return (loss_sum + loss_m * inv_m, g_sh,
                            dy_seed.astype(zero_act.dtype))

                def _skip(operand):
                    loss_sum, g_sh = operand
                    return (loss_sum, g_sh, zero_act)

                loss_sum, g_sh, seed_dx = jax.lax.cond(
                    last_vs, _post, _skip, (loss_sum, g_sh)
                )
            act_msg = jax.lax.ppermute(y, axis_name, perm_fwd)

        if do_bwd:
            ub = t - delta - (Pp - 1) + stage
            vb, mb_i, u_fwd, ok_b = decode_bwd(ub)
            slot = jnp.clip(u_fwd, 0, n_slots - 1) % S_buf
            x_saved = jax.lax.dynamic_index_in_dim(xbuf, slot, 0, keepdims=False)
            dy = jnp.where((stage == Pp - 1) & (vb == vpp - 1), seed_dx, cot_msg)
            vb_c = jnp.clip(vb, 0, vpp - 1)
            _, stage_vjp = jax.vjp(stage_fn, chunk_of(vb_c), x_saved)
            if stage_has_aux:
                aux_seed = jnp.where(ok_b, jnp.float32(inv_m), 0.0)
                d_chunk, dx = stage_vjp((dy, aux_seed))
            else:
                d_chunk, dx = stage_vjp(dy)
            if vpp == 1:
                g_st = _mask_add(g_st, d_chunk, ok_b)
            else:
                cur = _index_tree(g_st, vb_c)
                new = _mask_add(cur, d_chunk, ok_b)
                g_st = jax.tree.map(
                    lambda G, n: jax.lax.dynamic_update_index_in_dim(G, n, vb_c, 0),
                    g_st, new,
                )
            # stage 0, chunk 0: route dx into the embedding/pre params.
            # cond-gated like the post head: the pre vjp scatters into a
            # (vocab, H) embedding-grad buffer, which the other stages
            # must not pay for every tick (predicate is tp-uniform).
            mb = _index_tree(microbatches, jnp.clip(mb_i, 0, M - 1))
            pre_vs = ok_b & (stage == 0) & (vb == 0)

            def _pre(g_sh):
                _, pre_vjp = jax.vjp(lambda sh: pre_fn(sh, mb), shared_params)
                (d_sh_pre,) = pre_vjp(dx.astype(x_shape.dtype))
                return jax.tree.map(jnp.add, g_sh, d_sh_pre)

            g_sh = jax.lax.cond(pre_vs, _pre, lambda g: g, g_sh)
            cot_msg = jax.lax.ppermute(dx, axis_name, perm_bwd)

        return (act_msg, cot_msg, xbuf, loss_sum, g_sh, g_st), None

    xbuf0 = jnp.zeros((S_buf, *x_shape.shape), x_shape.dtype)
    g_sh0 = jax.tree.map(jnp.zeros_like, shared_params)
    g_st0 = jax.tree.map(jnp.zeros_like, chunked if vpp > 1 else stage_params)
    carry = (zero_act, zero_act, xbuf0, jnp.float32(0.0), g_sh0, g_st0)

    def run(carry, lo, hi, **kw):
        if hi <= lo:
            return carry
        body = partial(tick, **kw)
        carry, _ = jax.lax.scan(
            lambda c, t: body(c, t), carry, jnp.arange(lo, hi, dtype=jnp.int32)
        )
        return carry

    steady_end = n_slots + Pp - 1
    # A: warmup (forward only; no microbatch reaches the loss head before
    # tick V-1, so the post vjp is statically skipped)
    carry = run(carry, 0, delta, do_fwd=True, do_bwd=False, do_post=False)
    # B: steady state — one forward and one backward unit per tick
    carry = run(carry, delta, steady_end, do_fwd=True, do_bwd=True, do_post=True)
    # C: cooldown (backward only)
    carry = run(carry, steady_end, steady_end + delta, do_fwd=False, do_bwd=True,
                do_post=False)

    _, _, _, loss_sum, g_sh, g_st = carry
    # loss lives on the last stage (masked zero elsewhere)
    loss = jax.lax.psum(loss_sum, axis_name)
    if vpp > 1:
        g_st = jax.tree.map(
            lambda G, ref: G.reshape(ref.shape), g_st, stage_params
        )
    return loss, (g_sh, g_st)
