"""Pipelined schedule over the ``pp`` mesh axis (true 1F1B).

Reference: ``schedules/fwd_bwd_pipelining_without_interleaving.py:241`` —
warmup (P-rank-1 forwards), 1F1B steady state, cooldown, with p2p
send/recv at every boundary and grad accumulation across microbatches.

TPU-native: the whole schedule is ONE jitted program built from
:func:`~...schedules.tick_schedule.pipelined_fwd_bwd` — three scans
(fwd-only warmup, one-forward-one-backward steady state, bwd-only
cooldown) with a forward activation ring and a reverse cotangent ring
(``ppermute``), and a circular buffer bounding live activations to
O(P) microbatches independent of M — the memory property 1F1B exists
for.  The backward of each microbatch recomputes its stage forward from
the saved stage input (per-microbatch checkpointing, reference
``:351-361``).  ``forward_only`` uses the lighter forward-only scan
(:func:`~...schedules.common.pipelined_apply`).

Model contract (replaces torch's ``model.set_input_tensor``):
- ``pre_fn(shared_params, microbatch) -> activation``   (embedding; stage 0)
- ``stage_fn(stage_params, activation) -> activation``  (this stage's layer chunk)
- ``post_fn(shared_params, activation, microbatch) -> scalar loss`` (head; last stage)

``stage_params`` leaves are sharded over ``pp`` on their leading
(stacked-layer) axis; ``shared_params`` are replicated over ``pp`` and
their grads are psum'd across stages (the reference's
embedding-group allreduce, parallel_state.py:50).
"""

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import PIPELINE_AXIS
from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    broadcast_from_last_stage,
    pipelined_apply,
)


def make_pipeline_loss_fn(
    pre_fn: Callable,
    stage_fn: Callable,
    post_fn: Callable,
    axis_name: str = PIPELINE_AXIS,
):
    """Compose pre/pipeline/post into ``loss_fn(shared, stages, microbatches)``.

    ``microbatches``: pytree with leading (M, ...) dim.  Returns the mean
    over microbatches of ``post_fn``'s scalar.
    """

    def loss_fn(shared_params, stage_params, microbatches):
        acts = jax.vmap(lambda mb: pre_fn(shared_params, mb))(microbatches)
        outs = pipelined_apply(stage_fn, stage_params, acts, axis_name)
        # post/loss on the raw outputs (valid on the LAST stage only), then
        # broadcast the scalar.  This keeps each shared-param contribution
        # on exactly one stage — pre on stage 0, post on stage P-1 — so the
        # cross-stage psum of shared grads counts it once (the reference's
        # first/last-stage embedding-grad allreduce).
        losses = jax.vmap(lambda y, mb: post_fn(shared_params, y, mb))(outs, microbatches)
        return broadcast_from_last_stage(jnp.mean(losses), axis_name)

    return loss_fn


def forward_backward_pipelining_without_interleaving(
    pre_fn: Callable,
    stage_fn: Callable,
    post_fn: Callable,
    shared_params,
    stage_params,
    microbatches,
    *,
    forward_only: bool = False,
    axis_name: str = PIPELINE_AXIS,
    stage_has_aux: bool = False,
):
    """Run the pipelined schedule; returns ``(loss, (shared_grads, stage_grads))``.

    Shared-param grads are psum'd over the pipeline axis (different
    stages own different contributions — reference's embedding-grad
    allreduce between first and last stage).
    """
    if forward_only:
        loss_fn = make_pipeline_loss_fn(pre_fn, stage_fn, post_fn, axis_name)
        return loss_fn(shared_params, stage_params, microbatches), None
    from apex_tpu.transformer.pipeline_parallel.schedules.tick_schedule import (
        pipelined_fwd_bwd,
    )

    loss, (g_shared, g_stage) = pipelined_fwd_bwd(
        pre_fn, stage_fn, post_fn, shared_params, stage_params, microbatches,
        num_chunks=1, axis_name=axis_name, stage_has_aux=stage_has_aux,
    )
    g_shared = jax.tree.map(lambda g: jax.lax.psum(g, axis_name), g_shared)
    return loss, (g_shared, g_stage)
