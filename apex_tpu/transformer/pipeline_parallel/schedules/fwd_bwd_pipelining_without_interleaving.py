"""Pipelined schedule over the ``pp`` mesh axis (the 1F1B equivalent).

Reference: ``schedules/fwd_bwd_pipelining_without_interleaving.py:241`` —
warmup (P-rank-1 forwards), 1F1B steady state, cooldown, with p2p
send/recv at every boundary and grad accumulation across microbatches.

TPU-native: the whole schedule is ONE jitted program built from
:func:`~...schedules.common.pipelined_apply` (scan over ticks +
ppermute).  The forward pipeline is explicit; the backward pipeline is
obtained by differentiation — the transpose of a tick-scan with
forward ppermutes IS the cooldown/steady/warmup backward order, and
XLA's scheduler overlaps the shifted collectives with compute the way
the reference overlaps NCCL with the backward kernels.

Model contract (replaces torch's ``model.set_input_tensor``):
- ``pre_fn(shared_params, microbatch) -> activation``   (embedding; stage 0)
- ``stage_fn(stage_params, activation) -> activation``  (this stage's layer chunk)
- ``post_fn(shared_params, activation, microbatch) -> scalar loss`` (head; last stage)

``stage_params`` leaves are sharded over ``pp`` on their leading
(stacked-layer) axis; ``shared_params`` are replicated over ``pp`` and
their grads are psum'd across stages (the reference's
embedding-group allreduce, parallel_state.py:50).
"""

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import PIPELINE_AXIS
from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    broadcast_from_last_stage,
    pipelined_apply,
)


def make_pipeline_loss_fn(
    pre_fn: Callable,
    stage_fn: Callable,
    post_fn: Callable,
    axis_name: str = PIPELINE_AXIS,
):
    """Compose pre/pipeline/post into ``loss_fn(shared, stages, microbatches)``.

    ``microbatches``: pytree with leading (M, ...) dim.  Returns the mean
    over microbatches of ``post_fn``'s scalar.
    """

    def loss_fn(shared_params, stage_params, microbatches):
        acts = jax.vmap(lambda mb: pre_fn(shared_params, mb))(microbatches)
        outs = pipelined_apply(stage_fn, stage_params, acts, axis_name)
        # post/loss on the raw outputs (valid on the LAST stage only), then
        # broadcast the scalar.  This keeps each shared-param contribution
        # on exactly one stage — pre on stage 0, post on stage P-1 — so the
        # cross-stage psum of shared grads counts it once (the reference's
        # first/last-stage embedding-grad allreduce).
        losses = jax.vmap(lambda y, mb: post_fn(shared_params, y, mb))(outs, microbatches)
        return broadcast_from_last_stage(jnp.mean(losses), axis_name)

    return loss_fn


def forward_backward_pipelining_without_interleaving(
    pre_fn: Callable,
    stage_fn: Callable,
    post_fn: Callable,
    shared_params,
    stage_params,
    microbatches,
    *,
    forward_only: bool = False,
    axis_name: str = PIPELINE_AXIS,
):
    """Run the pipelined schedule; returns ``(loss, (shared_grads, stage_grads))``.

    Shared-param grads are psum'd over the pipeline axis (different
    stages own different contributions — reference's embedding-grad
    allreduce between first and last stage).
    """
    loss_fn = make_pipeline_loss_fn(pre_fn, stage_fn, post_fn, axis_name)
    if forward_only:
        return loss_fn(shared_params, stage_params, microbatches), None
    loss, (g_shared, g_stage) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
        shared_params, stage_params, microbatches
    )
    g_shared = jax.tree.map(lambda g: jax.lax.psum(g, axis_name), g_shared)
    return loss, (g_shared, g_stage)
