"""Pipeline parallelism (reference: ``apex/transformer/pipeline_parallel``)."""

from apex_tpu.transformer.pipeline_parallel import p2p_communication
from apex_tpu.transformer.pipeline_parallel.schedules import (
    build_model,
    forward_backward_no_pipelining,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    pipelined_apply,
)

__all__ = [
    "p2p_communication",
    "get_forward_backward_func",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "pipelined_apply",
    "build_model",
]
