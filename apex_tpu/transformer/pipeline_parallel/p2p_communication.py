"""Pipeline stage-to-stage communication.

Reference: ``apex/transformer/pipeline_parallel/p2p_communication.py`` —
``_communicate`` (:168) with NCCL ``batch_isend_irecv``, shape/dtype
handshakes, scatter-gather optimization, and 9 send/recv wrappers
(:385-690).

TPU-native: stage p2p is ``jax.lax.ppermute`` on the ``pp`` mesh axis —
a collective-permute over ICI neighbor links, which is *exactly* the
hardware pattern the reference builds by hand.  No handshake is needed
(shapes are static under jit); async overlap is XLA's job.  The 9
wrappers reduce to forward/backward shifts; "FutureTensor" disappears
(XLA programs are data-flow graphs already).

These helpers are differentiable; ppermute's autodiff transpose is the
inverse permutation, which is the correct backward-communication
pairing.
"""

import jax

from apex_tpu.transformer.parallel_state import PIPELINE_AXIS


def _ring(axis_name, shift):
    n = jax.lax.axis_size(axis_name)
    return [(i, (i + shift) % n) for i in range(n)]


def send_forward_recv_forward(x, axis_name: str = PIPELINE_AXIS):
    """Shift activations one stage forward (stage s → s+1); the fused
    equivalent of send_forward + recv_forward (reference :385,:410)."""
    return jax.lax.ppermute(x, axis_name, _ring(axis_name, +1))


def send_backward_recv_backward(g, axis_name: str = PIPELINE_AXIS):
    """Shift gradients one stage backward (stage s → s-1) (reference :437,:463)."""
    return jax.lax.ppermute(g, axis_name, _ring(axis_name, -1))


# aliases matching the reference's vocabulary
recv_forward = send_forward_recv_forward
recv_backward = send_backward_recv_backward


def send_forward(x, axis_name: str = PIPELINE_AXIS):
    return send_forward_recv_forward(x, axis_name)


def send_backward(g, axis_name: str = PIPELINE_AXIS):
    return send_backward_recv_backward(g, axis_name)


def send_forward_recv_backward(x, grad, axis_name: str = PIPELINE_AXIS):
    """The 1F1B steady-state exchange (reference :490): send this
    stage's activation forward while sending the cotangent backward, as
    one fused step.  Both ``ppermute``s are issued in the same program
    point so XLA schedules them as a bidirectional neighbor exchange
    over ICI (the pattern the reference builds with one batched
    ``batch_isend_irecv``).

    Returns ``(x_from_prev, grad_from_next)``.
    """
    return (
        jax.lax.ppermute(x, axis_name, _ring(axis_name, +1)),
        jax.lax.ppermute(grad, axis_name, _ring(axis_name, -1)),
    )


def send_backward_recv_forward(grad, x, axis_name: str = PIPELINE_AXIS):
    """Mirror of :func:`send_forward_recv_backward` (reference :521):
    cotangent travels backward, activation forward.  Returns
    ``(grad_from_next, x_from_prev)``."""
    x_prev, g_next = send_forward_recv_backward(x, grad, axis_name)
    return g_next, x_prev
