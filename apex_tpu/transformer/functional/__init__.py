"""Fused functional ops (reference: ``apex/transformer/functional``)."""

from apex_tpu.transformer.functional.fused_softmax import (
    FusedScaleMaskSoftmax,
    generic_scaled_masked_softmax,
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
)

__all__ = [
    "FusedScaleMaskSoftmax",
    "scaled_masked_softmax",
    "scaled_softmax",
    "scaled_upper_triang_masked_softmax",
    "generic_scaled_masked_softmax",
]
