"""Fused scaled/masked softmax family.

Reference: ``apex/transformer/functional/fused_softmax.py`` — four CUDA
kernel wrappers (ScaledUpperTriangMaskedSoftmax :21, ScaledMaskedSoftmax
:71, GenericScaledMaskedSoftmax :106, ScaledSoftmax :133) and the
``FusedScaleMaskSoftmax`` module (:164) whose ``is_kernel_available``
(:222-246) decides kernel vs torch fallback based on dtype/shape/mask.

TPU: scale + mask-fill + row softmax is a single XLA fusion (one VPU pass
over the attention scores), so every variant is "fused" and the
availability heuristics collapse to "always".  Shapes follow the
reference: scores are ``(b, np, sq, sk)``; causal masking uses the upper
triangle; padding masks are boolean with True = masked, filled with
-10000.0 before the softmax (reference kernel semantics).
"""

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer.enums import AttnMaskType

MASK_FILL_VALUE = -10000.0


def _softmax(x, softmax_in_fp32: bool = True):
    dt = x.dtype
    if softmax_in_fp32:
        x = x.astype(jnp.float32)
    out = jax.nn.softmax(x, axis=-1)
    return out.astype(dt)


def scaled_upper_triang_masked_softmax(x, scale: float = 1.0):
    """Causal softmax (reference csrc/megatron/scaled_upper_triang_...).

    Input ``(b, sq, sk)`` or ``(b, np, sq, sk)``; masks j > i.

    The XLA composite IS the fused kernel on TPU: scale + mask + softmax
    compile to one VPU pass, and the backward fuses into its neighbors.
    A hand-written Pallas softmax was measured slower fwd+bwd (5.8 vs
    3.6 ms at B8·H12·S1024 on v5e-lite) precisely because the kernel
    boundary blocks that backward fusion, so it was removed — the
    blessed fused-attention path is flash attention
    (:mod:`apex_tpu.ops.flash_attention_pallas`), which fuses the
    matmuls *around* the softmax, where a kernel actually wins.
    """
    sq, sk = x.shape[-2], x.shape[-1]
    causal = jnp.tril(jnp.ones((sq, sk), bool))
    scores = x * scale
    scores = jnp.where(causal, scores, MASK_FILL_VALUE)
    return _softmax(scores)


def scaled_masked_softmax(x, mask, scale: float = 1.0):
    """Arbitrary-mask softmax (reference csrc/megatron/scaled_masked_...).

    ``mask`` boolean broadcastable to ``x`` with True = masked out.
    """
    scores = x * scale
    if mask is not None:
        scores = jnp.where(mask, MASK_FILL_VALUE, scores)
    return _softmax(scores)


def scaled_softmax(x, scale: float = 1.0):
    """Unmasked scaled softmax (reference csrc/megatron/scaled_softmax.cpp)."""
    return _softmax(x * scale)


# the generic (non-power-of-2) variant is the same computation under XLA
generic_scaled_masked_softmax = scaled_masked_softmax


class FusedScaleMaskSoftmax:
    """Module parity with ``FusedScaleMaskSoftmax`` (fused_softmax.py:164).

    Callable: ``softmax(input, mask)`` with scores ``(b, np, sq, sk)``.
    """

    def __init__(
        self,
        input_in_fp16: bool = False,
        input_in_bf16: bool = True,
        attn_mask_type: AttnMaskType = AttnMaskType.padding,
        scaled_masked_softmax_fusion: bool = True,
        mask_func: Optional[Callable] = None,
        softmax_in_fp32: bool = True,
        scale: Optional[float] = None,
    ):
        if input_in_fp16 and input_in_bf16:
            raise RuntimeError("both fp16 and bf16 flags cannot be active at the same time.")
        if scale is not None and not softmax_in_fp32:
            raise RuntimeError("softmax should be in fp32 when scaled")
        self.attn_mask_type = attn_mask_type
        self.mask_func = mask_func
        self.softmax_in_fp32 = softmax_in_fp32
        self.scale = scale

    def is_kernel_available(self, mask, b, np_, sq, sk) -> bool:
        """Always true on TPU — XLA fuses any shape (reference :222-246
        gates on seqlen ≤ 4096, pow2 batching, dtype)."""
        return True

    def __call__(self, input, mask=None):
        scale = self.scale if self.scale is not None else 1.0
        if self.attn_mask_type == AttnMaskType.causal:
            return scaled_upper_triang_masked_softmax(input, scale)
        if mask is not None and self.mask_func is not None:
            scores = self.mask_func(input * scale, mask)
            return _softmax(scores, self.softmax_in_fp32)
        return scaled_masked_softmax(input, mask, scale)

    @staticmethod
    def get_batch_per_block(sq, sk, b, np_) -> int:
        """Kernel tiling detail with no TPU meaning (reference :271)."""
        return 1
