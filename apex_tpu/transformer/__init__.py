"""Megatron-style model parallelism for TPU (reference: ``apex/transformer``)."""

from apex_tpu.transformer import parallel_state

__all__ = ["parallel_state"]


def __getattr__(name):
    if name in ("tensor_parallel", "pipeline_parallel", "functional", "layers", "amp", "_data", "testing", "enums", "microbatches", "context_parallel", "expert_parallel"):
        import importlib

        mod = importlib.import_module(f"apex_tpu.transformer.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'apex_tpu.transformer' has no attribute {name!r}")
