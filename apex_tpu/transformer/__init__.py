"""Megatron-style model parallelism for TPU (reference: ``apex/transformer``)."""

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.enums import AttnMaskType, AttnType, LayerType, ModelType

__all__ = [
    "amp",
    "functional",
    "parallel_state",
    "pipeline_parallel",
    "tensor_parallel",
    "utils",
    # enums.py
    "LayerType",
    "AttnType",
    "AttnMaskType",
    "ModelType",
]


def __getattr__(name):
    if name in ("tensor_parallel", "pipeline_parallel", "functional", "layers", "amp", "_data", "testing", "enums", "microbatches", "context_parallel", "expert_parallel", "utils"):
        import importlib

        mod = importlib.import_module(f"apex_tpu.transformer.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'apex_tpu.transformer' has no attribute {name!r}")
