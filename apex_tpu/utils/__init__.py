"""Shared utilities: logging, math helpers, profiling ranges."""

from apex_tpu.utils.logging import RankInfoFormatter, get_logger, set_logging_level
from apex_tpu.utils.misc import divide, ensure_divisibility
from apex_tpu.utils.profiler import (
    nvtx_range,
    nvtx_range_pop,
    nvtx_range_push,
    profile,
    start_profile,
    stop_profile,
)

__all__ = [
    "RankInfoFormatter",
    "get_logger",
    "set_logging_level",
    "divide",
    "ensure_divisibility",
    "nvtx_range",
    "nvtx_range_push",
    "nvtx_range_pop",
    "profile",
    "start_profile",
    "stop_profile",
]
