"""Shared utilities: logging, math helpers, pytree helpers."""

from apex_tpu.utils.logging import RankInfoFormatter, get_logger, set_logging_level
from apex_tpu.utils.misc import divide, ensure_divisibility

__all__ = [
    "RankInfoFormatter",
    "get_logger",
    "set_logging_level",
    "divide",
    "ensure_divisibility",
]
