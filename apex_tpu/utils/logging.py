"""Rank-aware logging.

Reference: ``apex/__init__.py:32-43`` (``RankInfoFormatter``) and
``apex/transformer/log_util.py``.  On TPU the "rank" is the JAX process
index plus the local device set, read lazily so logging works before
``jax.distributed.initialize``.
"""

import json
import logging
import sys


def _rank_info() -> str:
    try:
        import jax

        return f"[p{jax.process_index()}/{jax.process_count()}]"
    except Exception:
        return "[p?/?]"


class RankInfoFormatter(logging.Formatter):
    """Prepends JAX process/rank info to every record."""

    def format(self, record):
        record.rank_info = _rank_info()
        return super().format(record)


_FORMAT = "%(asctime)s %(rank_info)s %(name)s %(levelname)s: %(message)s"


def get_logger(name: str = "apex_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(RankInfoFormatter(_FORMAT))
        logger.addHandler(handler)
        logger.propagate = False
    return logger


def set_logging_level(level) -> None:
    """Reference: apex/transformer/log_util.py (set_logging_level)."""
    get_logger().setLevel(level)


def log_structured(logger: logging.Logger, level: int, event: str,
                   **fields) -> None:
    """One-line machine-parseable log record: ``EVENT {json fields}``.

    The resilience runtime (kernel fallback, step guard, preemption)
    reports through this so a wedged-run postmortem can grep one event
    name and get every occurrence with its context as JSON — the same
    greppability contract as bench.py's section sidecar.  When the loop
    set a step-correlation context
    (:func:`apex_tpu.observability.set_step_context`), every record
    additionally carries ``(run_id, step)`` so it joins against metrics
    points and xprof ranges.  When a flight recorder is installed
    (:func:`apex_tpu.observability.flightrec.install`), every record is
    ALSO appended to its bounded event ring — the postmortem dump then
    holds the last N structured events without any per-call-site
    wiring."""
    try:
        from apex_tpu.observability.correlation import step_context

        fields = {**step_context(), **fields}
    except ImportError:  # pragma: no cover — torn installs only
        pass
    try:
        from apex_tpu.observability.flightrec import observe_event

        observe_event(event, fields)  # no-op without an installed recorder
    except ImportError:  # pragma: no cover — torn installs only
        pass
    try:
        payload = json.dumps(fields, sort_keys=True, default=str)
    except (TypeError, ValueError):
        payload = json.dumps({k: repr(v) for k, v in fields.items()},
                             sort_keys=True)
    logger.log(level, "%s %s", event, payload)
