"""Small math/shape helpers (reference: apex/transformer/utils.py)."""


def ensure_divisibility(numerator: int, denominator: int) -> None:
    if numerator % denominator != 0:
        raise ValueError(f"{numerator} is not divisible by {denominator}")


def divide(numerator: int, denominator: int) -> int:
    """Integer division asserting exact divisibility."""
    ensure_divisibility(numerator, denominator)
    return numerator // denominator
