"""Profiling ranges and trace capture.

Reference: NVTX ranges gated by the ``prof`` flag in apex DDP
(``apex/parallel/distributed.py:363-364,406-407,520-521``) plus the
CUDA-synchronized ``_Timers``
(``apex/transformer/pipeline_parallel/_timers.py``; our port lives in
:mod:`apex_tpu.transformer.pipeline_parallel.utils`).

TPU mapping: ``torch.cuda.nvtx.range_push/pop`` becomes a pair of
annotations — ``jax.named_scope`` names the ops in the traced HLO (so
ranges survive compilation and show up in the XLA trace viewer), and
``jax.profiler.TraceAnnotation`` marks the host timeline.  Trace
capture (`nsys` analog) is ``jax.profiler.start_trace`` writing a
TensorBoard-loadable protobuf.
"""

import contextlib
from typing import List, Optional

import jax

__all__ = [
    "nvtx_range",
    "nvtx_range_push",
    "nvtx_range_pop",
    "start_profile",
    "stop_profile",
    "profile",
]

_range_stack: List[object] = []


@contextlib.contextmanager
def nvtx_range(name: str):
    """Named range visible in both the HLO (op metadata) and the host
    trace.  Usable inside traced code (the named_scope part) and out.

    When the loop set a step-correlation context
    (:func:`apex_tpu.observability.set_step_context`), the scope name
    carries a ``.run_<id>.s<step>`` suffix, so an xprof range joins a
    structured log line and a metrics point on ``(run_id, step)``."""
    try:
        from apex_tpu.observability.correlation import span_suffix

        tagged = name + span_suffix()
    except ImportError:  # pragma: no cover — torn installs only
        tagged = name
    with jax.named_scope(tagged), jax.profiler.TraceAnnotation(tagged):
        yield


def nvtx_range_push(name: str) -> None:
    """``torch.cuda.nvtx.range_push`` parity (stack-based form)."""
    cm = nvtx_range(name)
    cm.__enter__()
    _range_stack.append(cm)


def nvtx_range_pop() -> None:
    """``torch.cuda.nvtx.range_pop`` parity."""
    if not _range_stack:
        raise RuntimeError("nvtx_range_pop without a matching push")
    _range_stack.pop().__exit__(None, None, None)


_trace_dir: Optional[str] = None


def start_profile(logdir: str) -> None:
    """Begin a device+host trace (TensorBoard / xprof format)."""
    global _trace_dir
    if _trace_dir is not None:
        raise RuntimeError(f"profile already running (logdir={_trace_dir})")
    jax.profiler.start_trace(logdir)
    _trace_dir = logdir


def stop_profile() -> Optional[str]:
    """End the trace; returns the logdir it was written to."""
    global _trace_dir
    if _trace_dir is None:
        raise RuntimeError("no profile running")
    jax.profiler.stop_trace()
    out, _trace_dir = _trace_dir, None
    return out


@contextlib.contextmanager
def profile(logdir: str):
    """``with profile('/tmp/trace'):`` — capture a trace of the body."""
    start_profile(logdir)
    try:
        yield
    finally:
        stop_profile()
