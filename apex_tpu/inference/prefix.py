"""Prefix sharing: a rolling token-hash trie over pool pages.

N requests carrying the same system prompt should hold ONE physical
copy of its KV pages.  This module is the host-side index that makes
that true: a trie keyed by a rolling hash of page-sized token blocks,
each node owning (one reference on) the pool page that caches exactly
that block's k/v.  Admission matches a new prompt's longest full-page
chain and maps the matched pages straight into the sequence's page
table via :meth:`PageAllocator.share` — the pages are never rewritten
(``write_prompt_kv(start=shared_len)`` masks them out of the prefill
scatter), and chunked prefill skips their compute entirely.

**Why the values are interchangeable**: a transformer layer's k/v at
position ``i`` depend only on tokens ``0..i`` (causal) and the
weights, so two prompts agreeing on their first ``shared_len`` tokens
produce bitwise-identical k/v there (same compiled prefill, same
shapes) — sharing the pages IS the unshared computation, minus the
copies.

**Tail pages and copy-on-write**: a node may also index its chain's
final PARTIAL page (``tail``).  A new prompt whose remainder is a
prefix of the cached tail's tokens shares that page too — but unlike
full pages, the tail sits in the write path (the first generated
token's k/v lands in it), so tail sharing never reduces the
reservation: the admitting sequence still reserves one fresh page as
its COW budget, and the scheduler copies the page
(:func:`apex_tpu.inference.kv_cache.copy_page`) before the first
divergent write.  Full pages live strictly below every write position
and can never need COW — which is what lets them reduce the
reservation and admit strictly more sequences than worst-case
accounting.

The trie holds its OWN reference on every indexed page, so cached
prefixes survive their registering sequence's eviction; under pool
pressure the scheduler calls :meth:`PrefixCache.release` to drop
least-recently-used root chains until enough pages actually RECYCLE
(chains whose every page is still resident-held are skipped — dropping
them frees nothing and destroys the sharing the residents came from).
"""

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from apex_tpu.inference.kv_cache import GARBAGE_PAGE, PageAllocator

__all__ = ["PrefixCache", "PrefixMatch"]

_HASH_SEED = 0x9E3779B97F4A7C15
_MASK64 = (1 << 64) - 1


def _roll(parent: int, block: Tuple[int, ...]) -> int:
    """Rolling hash of one page-sized token block chained onto its
    parent's key (splitmix-style mixing; collisions are verified
    against the stored tokens, never trusted)."""
    h = parent
    for t in block:
        h = (h ^ (int(t) + _HASH_SEED + ((h << 12) & _MASK64)
                  + (h >> 4))) & _MASK64
        h = (h * 0x100000001B3) & _MASK64
    return h


@dataclasses.dataclass
class _Node:
    page: int
    tokens: Tuple[int, ...]
    children: Dict[int, "_Node"] = dataclasses.field(default_factory=dict)
    tail_page: Optional[int] = None
    tail_tokens: Tuple[int, ...] = ()


@dataclasses.dataclass(frozen=True)
class PrefixMatch:
    """An admission plan's sharing component: ``full_pages`` — pool
    pages covering the prompt's leading full page blocks, in order;
    ``tail_page`` — the shared partial page covering the remainder (or
    None); ``shared_len`` — prompt positions covered in total (k/v
    already pooled; prefill starts writing — and chunked prefill
    starts computing — here)."""

    full_pages: Tuple[int, ...]
    tail_page: Optional[int]
    shared_len: int

    @property
    def num_full(self) -> int:
        return len(self.full_pages)


_NO_MATCH = PrefixMatch(full_pages=(), tail_page=None, shared_len=0)


class PrefixCache:
    """The rolling token-hash trie (see module doc).  Owned by the
    scheduler; every indexed page carries one trie reference in the
    shared :class:`PageAllocator`."""

    def __init__(self, allocator: PageAllocator, page_size: int):
        self._alloc = allocator
        self._ps = int(page_size)
        self._roots: Dict[int, _Node] = {}
        #: root key -> LRU stamp (bumped on any match/register through it)
        self._used: Dict[int, int] = {}
        self._clock = 0
        self.stats = {"hits": 0, "misses": 0, "released_pages": 0}

    @property
    def indexed_pages(self) -> int:
        """Pages the trie currently holds a reference on."""
        return sum(self._chain_pages(n) for n in self._roots.values())

    def _chain_pages(self, node: _Node) -> int:
        n = 1 + (1 if node.tail_page is not None else 0)
        return n + sum(self._chain_pages(c) for c in node.children.values())

    def _walk(self, prompt: Sequence[int]):
        """Longest verified chain: yields (key, node) per matched full
        page block."""
        ps = self._ps
        h = _HASH_SEED
        level, node = self._roots, None
        for i in range(len(prompt) // ps):
            block = tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])
            h = _roll(h, block)
            child = level.get(h)
            if child is None or child.tokens != block:
                return  # hash miss, or a collision — treat as miss
            node = child
            yield h, node
            level = node.children

    def match(self, prompt: Sequence[int]) -> PrefixMatch:
        """The longest shareable prefix of ``prompt`` — does NOT take
        references; the scheduler shares exactly what it admits."""
        chain = list(self._walk(prompt))
        if not chain:
            self.stats["misses"] += 1
            return _NO_MATCH
        self._clock += 1
        self._used[chain[0][0]] = self._clock
        self.stats["hits"] += 1
        node = chain[-1][1]
        pages = tuple(n.page for _, n in chain)
        rest = tuple(int(t) for t in prompt[len(pages) * self._ps:])
        # tail share only when the remainder is FULLY covered by the
        # cached tail's tokens: a mid-page divergence would COW at
        # admission, saving nothing
        if rest and node.tail_page is not None \
                and len(rest) <= len(node.tail_tokens) \
                and node.tail_tokens[:len(rest)] == rest:
            return PrefixMatch(full_pages=pages, tail_page=node.tail_page,
                               shared_len=len(pages) * self._ps + len(rest))
        return PrefixMatch(full_pages=pages, tail_page=None,
                           shared_len=len(pages) * self._ps)

    def register(self, prompt: Sequence[int],
                 table_pages: Sequence[int], tail: bool = False) -> int:
        """Index an admitted prompt's pages (call AFTER its prefill
        writes land): every full page block gets a trie node; with
        ``tail=True`` the partial remainder's page becomes the chain's
        tail — only safe once that page is QUIESCED (the owning
        sequence evicted: generation writes into the tail page, so a
        live owner would mutate a trie page).  ``table_pages`` is the
        sequence's page-table prefix in order (shared entries included
        — already-indexed blocks are left untouched).  Returns the net
        number of newly indexed pages (each +1 ref)."""
        ps = self._ps
        added = 0
        h = _HASH_SEED
        level, node = self._roots, None
        root_key = None
        for i in range(len(prompt) // ps):
            block = tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])
            h = _roll(h, block)
            root_key = h if root_key is None else root_key
            child = level.get(h)
            if child is not None and child.tokens != block:
                break  # collision slot — leave the incumbent alone
            if child is None:
                page = int(table_pages[i])
                if page == GARBAGE_PAGE:
                    break  # table shorter than the prompt? stop clean
                child = _Node(page=page, tokens=block)
                self._alloc.share([page])
                level[h] = child
                added += 1
            node, level = child, child.children
        if root_key is not None:
            self._clock += 1
            self._used[root_key] = self._clock
        rest = tuple(int(t) for t in prompt[(len(prompt) // ps) * ps:])
        if tail and node is not None and rest \
                and len(rest) > len(node.tail_tokens):
            page = int(table_pages[len(prompt) // ps])
            if page != GARBAGE_PAGE and page != node.tail_page:
                if node.tail_page is not None:
                    self._alloc.free([node.tail_page])
                    added -= 1
                self._alloc.share([page])
                node.tail_page = page
                node.tail_tokens = rest
                added += 1
        return added

    def release(self, n_pages: int) -> int:
        """Drop least-recently-used ROOT chains until >= ``n_pages``
        pages are actually RECYCLED to the free list (or no droppable
        chain remains).  Chains whose every page is still resident-held
        are skipped entirely: dropping them would free nothing while
        destroying the sharing the residents came from — the one thing
        a pressure-relief pass must never make worse.  Returns pages
        recycled (0 = releasing cannot help; the caller escalates to
        preemption)."""
        freed = 0
        order = sorted(self._roots, key=lambda k: self._used.get(k, 0))
        for key in order:
            if freed >= n_pages:
                break
            if self._recyclable(self._roots[key]) == 0:
                continue  # all pages resident-held — keep the chain
            freed += self._drop(self._roots.pop(key))
            self._used.pop(key, None)
        self.stats["released_pages"] += freed
        return freed

    def _recyclable(self, node: _Node) -> int:
        """Pages in this chain the trie is the LAST holder of — the
        ones :meth:`release` would actually return to the free list."""
        n = 1 if self._alloc.refcount(node.page) == 1 else 0
        if node.tail_page is not None \
                and self._alloc.refcount(node.tail_page) == 1:
            n += 1
        return n + sum(self._recyclable(c)
                       for c in node.children.values())

    def _drop(self, node: _Node) -> int:
        """Decref every page in the chain; count only those whose LAST
        reference this was (they recycled — resident-held pages stay
        alive, they just stop being shareable)."""
        n = 0
        for child in node.children.values():
            n += self._drop(child)
        if node.tail_page is not None:
            if self._alloc.refcount(node.tail_page) == 1:
                n += 1
            self._alloc.free([node.tail_page])
        if self._alloc.refcount(node.page) == 1:
            n += 1
        self._alloc.free([node.page])
        return n
