"""apex_tpu.inference — the serving half of the north star.

A paged-KV decode engine with continuous batching:

- :mod:`~apex_tpu.inference.kv_cache` — fixed-size pages in a
  preallocated pool, per-sequence page tables, a host-side allocator
  with a reserved garbage page for masked writes;
- :mod:`~apex_tpu.inference.decode` — ONE jitted decode step (shared
  transformer blocks via :func:`apex_tpu.models.gpt.forward_decode`,
  paged single-query attention and the fused sampling head from
  :mod:`apex_tpu.ops.decode_attention_pallas` /
  :mod:`apex_tpu.ops.decode_sampling_pallas`) that compiles once and
  serves every cache length and batch occupancy, plus the static-shape
  prompt prefill riding the training forward;
- :mod:`~apex_tpu.inference.scheduler` — FIFO continuous batching:
  admit into freed pages between steps, evict finished sequences,
  degrade-once kernel fallback via :mod:`apex_tpu.resilience`.

See docs/inference.md for the architecture and knob table, and
``examples/gpt/serve_gpt.py`` for the load-generator driver.
"""

from apex_tpu.inference.decode import (
    DecodeConfig, make_decode_step, make_prefill,
)
from apex_tpu.inference.kv_cache import (
    GARBAGE_PAGE, KVCacheConfig, PageAllocator, alloc_pools, pages_needed,
    write_decode_kv, write_prompt_kv,
)
from apex_tpu.inference.scheduler import (
    Completion, ContinuousBatchingScheduler, Request,
)

__all__ = [
    "Completion", "ContinuousBatchingScheduler", "DecodeConfig",
    "GARBAGE_PAGE", "KVCacheConfig", "PageAllocator", "Request",
    "alloc_pools", "make_decode_step", "make_prefill", "pages_needed",
    "write_decode_kv", "write_prompt_kv",
]
