"""apex_tpu.inference — the serving half of the north star.

A paged-KV decode engine with continuous batching:

- :mod:`~apex_tpu.inference.kv_cache` — fixed-size pages in a
  preallocated pool, per-sequence page tables, a host-side allocator
  with a reserved garbage page for masked writes;
- :mod:`~apex_tpu.inference.decode` — ONE jitted decode step (shared
  transformer blocks via :func:`apex_tpu.models.gpt.forward_decode`,
  paged single-query attention and the fused sampling head from
  :mod:`apex_tpu.ops.decode_attention_pallas` /
  :mod:`apex_tpu.ops.decode_sampling_pallas`) that compiles once and
  serves every cache length and batch occupancy, plus the static-shape
  prompt prefill riding the training forward;
- :mod:`~apex_tpu.inference.scheduler` — lane-aware continuous
  batching: FIFO-per-lane admission into freed pages between steps
  (interactive lane preempts best-effort residents through the
  evict→recycle path), chunked prefill interleaved with decode,
  eviction, degrade-once kernel fallback via
  :mod:`apex_tpu.resilience`;
- :mod:`~apex_tpu.inference.spec` — speculative decode: n-gram
  (prompt-lookup) drafting + longest-matching-prefix acceptance over
  the batched verify step (bitwise the non-speculative stream);
- :mod:`~apex_tpu.inference.prefix` — prefix sharing: a refcounted
  rolling token-hash trie deduping identical prompt-prefix pages,
  copy-on-write before the first divergent write;
- :mod:`~apex_tpu.inference.fleet` — the fault-tolerant multi-replica
  frontend: replica health state machine, replay-on-failure from the
  wedge manifest / request journal (greedy streams stay bitwise the
  unkilled run), prefix-affinity routing, and graceful brownout.

See docs/inference.md for the architecture and knob table, and
``examples/gpt/serve_gpt.py`` for the load-generator driver.
"""

from apex_tpu.inference.decode import (
    DecodeConfig, make_decode_step, make_prefill, make_prefill_chunk,
    make_sample_head, make_verify_step,
)
from apex_tpu.inference.kv_cache import (
    GARBAGE_PAGE, KVCacheConfig, PageAllocator, alloc_pools, copy_page,
    pages_needed, write_decode_kv, write_prompt_kv,
)
from apex_tpu.inference.fleet import (
    FleetCompletion, FleetFrontend, LocalReplica, Overloaded, Router,
    RouterConfig,
)
from apex_tpu.inference.prefix import PrefixCache, PrefixMatch
from apex_tpu.inference.scheduler import (
    LANES, Completion, ContinuousBatchingScheduler, ManifestEntry,
    Request,
)
from apex_tpu.inference.spec import NGramProposer, accepted_tokens

__all__ = [
    "Completion", "ContinuousBatchingScheduler", "DecodeConfig",
    "FleetCompletion", "FleetFrontend", "GARBAGE_PAGE", "KVCacheConfig",
    "LANES", "LocalReplica", "ManifestEntry", "NGramProposer",
    "Overloaded", "PageAllocator", "PrefixCache", "PrefixMatch",
    "Request", "Router", "RouterConfig", "accepted_tokens",
    "alloc_pools", "copy_page", "make_decode_step", "make_prefill",
    "make_prefill_chunk", "make_sample_head", "make_verify_step",
    "pages_needed", "write_decode_kv", "write_prompt_kv",
]
