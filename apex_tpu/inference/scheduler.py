"""Continuous-batching scheduler: admit, prefill, decode, evict.

The serving loop's control plane (Orca-style continuous batching): the
jitted decode step always runs at the STATIC ``max_batch`` shape, and
this scheduler fills its slots —

- **admit**: between decode steps, queued requests move into free slots
  FIFO *within their lane*.  Two lanes (``Request.lane``):
  ``interactive`` is admitted strictly FIFO with worst-case page
  reservation (``ceil((prompt + max_new [+ draft]) / page_size)``) so a
  resident sequence can never hit a mid-generation allocation failure;
  ``best_effort`` fills leftover capacity only while the interactive
  queue is empty, and is PREEMPTIBLE — when the interactive head does
  not fit, the youngest best-effort resident is evicted through the
  ordinary evict→recycle path and requeued (continuation: prompt +
  tokens generated so far, remaining budget) at its lane's head.
- **prefill**: an admitted prompt runs through the training forward at
  ONE static padded shape (``DecodeConfig.max_prompt_len``) — or, with
  ``prefill_chunk`` set, as fixed-size CHUNKS through the
  multi-position decode forward, one chunk per scheduler step,
  interleaved with resident streams' decode steps (arbitrary prompt
  lengths, no TTFT spike for the streams).
- **prefix sharing** (``prefix_sharing``): admission matches the
  prompt against the refcounted page trie
  (:mod:`apex_tpu.inference.prefix`); matched full pages map straight
  into the page table (one physical copy, N tables), the prefill write
  window starts past them, and chunked prefill skips their compute.  A
  shared partial TAIL page is copy-on-written
  (:func:`~apex_tpu.inference.kv_cache.copy_page`) before the first
  divergent write, paid from a reserve page allocated at admission —
  COW can never fail mid-generation.
- **decode**: one fused step advances every active slot; inactive
  slots ride along masked.  With ``draft_len`` k > 0 the step is the
  VERIFY step: per slot, an n-gram proposer
  (:class:`~apex_tpu.inference.spec.NGramProposer`) drafts up to k
  tokens, one batched pass scores all k+1 positions, and the host
  accepts the longest matching prefix — the emitted stream is bitwise
  the non-speculative stream (greedy AND sampled: each emission spends
  its own (slot, draw) seed), it just arrives up to k+1 tokens per
  step.
- **evict**: finished sequences free (decref) their pages back to the
  allocator — the next ``step()`` can admit into them — and register
  their quiesced tail page into the prefix trie.

The scheduler is time-agnostic (drivers decide when to ``submit``;
tests replay seeded traces step-by-step, the load-generator example
submits on wall-clock Poisson arrivals) and deterministic: sampling
seeds derive from ``(base_seed, slot, per-slot draw counter)``, and the
draw counter advances MONOTONICALLY across every generation a slot
serves (drain-and-resubmit, preemption re-admission) — it never
resets, so the same trace of submits produces the same tokens and two
generations can never replay one seed.

Kernel resilience: trace-time kernel failures already degrade through
the fallback registry inside the step build; a DEFERRED jit-compile
failure surfaces on the first call, is attributed via
``resilience.fallback.trip_from_exception``, and the steps are rebuilt
once — the fresh trace lowers the XLA reference and the server keeps
serving (the same recovery ``examples/gpt/pretrain_gpt.py`` wires for
training).

Wedge resilience: a ``watchdog=`` (:class:`apex_tpu.resilience
.StepWatchdog`) gets a heartbeat per scheduler step; a decode step that
never returns (dead tunnel, hung collective) fires it — the scheduler's
``on_wedge`` hook logs every queued and in-flight request id
(``serve.step_wedged`` — the requeue manifest for the layer above) and
records ``apex_serve_wedges_total``, then the watchdog drains and exits
75 so a :class:`~apex_tpu.resilience.supervisor.Supervisor` restarts
the server (``serve_gpt.py --supervise --watchdog-secs``).
"""

import dataclasses
import logging
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

import jax.numpy as jnp

from apex_tpu.inference.decode import (
    DecodeConfig, make_decode_step, make_prefill, make_prefill_chunk,
    make_sample_head, make_verify_step,
)
from apex_tpu.inference.kv_cache import (
    GARBAGE_PAGE, PageAllocator, alloc_pools, copy_page, pages_needed,
)
from apex_tpu.inference.prefix import PrefixCache, PrefixMatch
from apex_tpu.inference.spec import NGramProposer, accepted_tokens
from apex_tpu.models.gpt import GPTConfig
from apex_tpu.observability import metrics as _metrics
from apex_tpu.observability import tracing as _tracing
from apex_tpu.resilience.chaos import active_monkey
from apex_tpu.resilience.uniformity import assert_uniform
from apex_tpu.utils.logging import get_logger, log_structured

__all__ = ["LANES", "Completion", "ContinuousBatchingScheduler",
           "ManifestEntry", "Request"]

_logger = get_logger("apex_tpu.inference")

_MASK32 = (1 << 32) - 1

#: admission lanes, in priority order: ``interactive`` requests carry
#: the latency SLO (strict FIFO, worst-case reservation, may preempt);
#: ``best_effort`` fills leftover capacity and is preemptible
LANES = ("interactive", "best_effort")


@dataclasses.dataclass
class Request:
    """One generation request: ``prompt`` token ids, ``max_new_tokens``
    to generate, optional ``eos_id`` early stop, and the admission
    ``lane`` (see :data:`LANES`).  ``trace_id`` is assigned at
    ``submit`` when the caller did not bring one — it is stamped on
    every span and latency-histogram exemplar the request produces, so
    a p99 outlier in ``apex_serve_ttft_seconds`` joins back to this
    request's admission-wait/prefill/decode spans."""

    rid: int
    prompt: List[int]
    max_new_tokens: int
    eos_id: Optional[int] = None
    lane: str = "interactive"
    trace_id: Optional[str] = None


@dataclasses.dataclass
class Completion:
    """A finished request with its wall-clock trace: ``token_times[i]``
    is when ``tokens[i]`` became available (``token_times[0]`` is the
    prefill / time-to-first-token).  ``preemptions`` counts how often a
    best-effort generation was evicted-and-requeued on the way."""

    rid: int
    prompt: List[int]
    tokens: List[int]
    submit_time: float
    finish_time: float
    token_times: List[float]
    lane: str = "interactive"
    preemptions: int = 0
    trace_id: Optional[str] = None


@dataclasses.dataclass
class ManifestEntry:
    """One unfinished request in a :meth:`drain_manifest` snapshot —
    everything a frontend needs to RESUBMIT it elsewhere and splice the
    continuation into the caller's stream: the ORIGINAL prompt (not the
    current continuation leg's), every token already emitted across all
    legs (``emitted`` — the splice point), and the tokens still owed
    (``remaining``).  The replay request is
    ``Request(rid, prompt + emitted, remaining, eos_id, lane,
    trace_id)`` — prefix sharing makes the re-prefill cheap on a
    replica that has served the prompt, and monotonic per-slot draw
    seeds make the resubmission seed-safe."""

    rid: int
    lane: str
    phase: str                     # "queued" | "in_flight"
    prompt: List[int]              # original prompt (all legs)
    emitted: List[int]             # tokens already emitted, in order
    remaining: int                 # new tokens still owed
    eos_id: Optional[int] = None
    trace_id: Optional[str] = None


@dataclasses.dataclass
class _Carry:
    """Cross-preemption continuation state for one rid: the ORIGINAL
    prompt and submit time, plus tokens/times already emitted by
    earlier residency legs."""

    prompt: List[int]
    tokens: List[int]
    times: List[float]
    submit_time: float
    preemptions: int = 0


@dataclasses.dataclass
class _Slot:
    request: Request
    pages: List[int]               # page-table entries, in index order
    generated: List[int]
    token_times: List[float]
    submit_time: float
    admit_seq: int = 0             # admission order (preemption picks max)
    submitted_at: float = 0.0      # true submit wall-time (TTFT base)
    shared_len: int = 0            # prompt positions served by shared pages
    cow_reserve: Optional[int] = None
    chunk_next: Optional[int] = None  # next prompt position to chunk-prefill
    proposer: Optional[NGramProposer] = None


class ContinuousBatchingScheduler:
    """The serve loop's control plane: lane-aware admission into freed
    KV pages between decode steps, static-shape slot management,
    chunked prefill, speculative verify, prefix sharing with COW,
    eviction with refcounted page recycling, deterministic per-slot
    sampling seeds, and degrade-once step rebuild on deferred kernel
    failures (see the module docstring for the full semantics)."""

    def __init__(self, params, config: GPTConfig, dcfg: DecodeConfig,
                 time_fn=time.monotonic, watchdog=None, anomaly=None):
        cache = dcfg.cache
        if config.moe:
            raise NotImplementedError("MoE decode is not wired")
        if dcfg.max_prompt_len > config.max_seq_len \
                and config.position_embedding_type == "learned":
            raise ValueError(
                f"max_prompt_len ({dcfg.max_prompt_len}) exceeds the "
                f"learned position table ({config.max_seq_len})")
        self.params = params
        self.config = config
        self.dcfg = dcfg
        self._time = time_fn
        tp_local_kv = config.kv_heads  # single-process serving: tp=1
        self.pools = alloc_pools(config.num_layers, tp_local_kv,
                                 config.head_dim, cache)
        self.allocator = PageAllocator(cache.num_pages)
        self.prefix: Optional[PrefixCache] = (
            PrefixCache(self.allocator, cache.page_size)
            if dcfg.prefix_sharing else None)
        self.queue: deque = deque()      # interactive lane
        self.be_queue: deque = deque()   # best-effort lane
        B, P = dcfg.max_batch, cache.pages_per_seq
        self._slots: List[Optional[_Slot]] = [None] * B
        self._page_tables = np.zeros((B, P), np.int32)
        self._positions = np.zeros((B,), np.int32)
        self._tokens = np.zeros((B,), np.int32)
        self._active = np.zeros((B,), bool)
        #: per-slot sampling draw counters — MONOTONIC for the life of
        #: the scheduler, across every generation a slot serves (the
        #: determinism contract: no (slot, draw) seed is ever replayed,
        #: even after drain-and-resubmit or preemption re-admission)
        self._draws = np.zeros((B,), np.int64)
        self._admit_counter = 0
        self.completed: List[Completion] = []
        self._carry: Dict[int, _Carry] = {}
        self.stats: Dict[str, int] = {
            "admitted": 0, "evicted": 0, "decode_steps": 0,
            "prefills": 0, "step_rebuilds": 0,
            "preemptions": 0, "chunk_steps": 0, "cow_copies": 0,
            "shared_full_pages": 0, "shared_tail_pages": 0,
            "spec_steps": 0, "spec_emitted": 0,
        }
        self._rebuilt_once = False
        self._draining = False
        # record-only uniformity seam: the serve config shapes every
        # compiled step (static batch/page shapes, lane layout) — in a
        # future multi-host serving topology a per-process difference
        # here is a divergent program, so record it where
        # check_uniform() can compare it across processes by name
        assert_uniform("serve.scheduler_config", {
            "decode": dataclasses.asdict(dcfg),
            "model": dataclasses.asdict(config),
        })
        #: true submit wall-time per queued rid (Completion.submit_time
        #: is the ADMIT time for driver compatibility; the metrics
        #: histograms — admission wait, TTFT — need the real submit)
        self._submit_times: Dict[int, float] = {}
        #: per-lane SLO-burn detection (an
        #: :class:`~apex_tpu.observability.anomaly.AnomalyMonitor`):
        #: every TTFT / inter-token sample is also scored, so a lane
        #: regression raises ``apex_anomaly_ttft_total{lane=}`` and a
        #: structured alert without the driver polling percentiles
        self._anomaly = anomaly
        self._watchdog = watchdog
        self._beaten = False
        if watchdog is not None:
            # chain, don't clobber: the driver may have wired its own
            # pre-exit hook (the trainer's goodput finalize pattern)
            prev = watchdog.on_wedge

            def hook(info, _prev=prev):
                if _prev is not None:
                    _prev(info)
                self._on_wedge(info)

            watchdog.on_wedge = hook
        self._build_steps()

    def drain_manifest(self) -> List["ManifestEntry"]:
        """Snapshot of every unfinished request — queued (both lanes)
        then in-flight, each with the tokens already emitted across all
        its legs — structured for a frontend to resubmit elsewhere and
        SPLICE (emit only ``total[len(already_streamed):]``) rather
        than regenerate.  Non-destructive and lock-free: list() copies
        of the queues/slots make it racy-but-safe from the watchdog
        thread (the decode thread is by definition wedged when it runs
        there), and cheap enough for a frontend to poll per step."""
        out: List[ManifestEntry] = []
        for req in list(self.queue) + list(self.be_queue):
            c = self._carry.get(req.rid)
            out.append(ManifestEntry(
                rid=req.rid, lane=req.lane, phase="queued",
                prompt=list(c.prompt) if c is not None
                else list(req.prompt),
                emitted=list(c.tokens) if c is not None else [],
                remaining=req.max_new_tokens, eos_id=req.eos_id,
                trace_id=req.trace_id))
        for s in list(self._slots):
            if s is None:
                continue
            req = s.request
            c = self._carry.get(req.rid)
            gen = list(s.generated)
            out.append(ManifestEntry(
                rid=req.rid, lane=req.lane, phase="in_flight",
                prompt=list(c.prompt) if c is not None
                else list(req.prompt),
                emitted=(list(c.tokens) if c is not None else []) + gen,
                remaining=req.max_new_tokens - len(gen),
                eos_id=req.eos_id, trace_id=req.trace_id))
        return out

    def _on_wedge(self, info) -> None:
        """Watchdog pre-exit hook: one structured record carrying the
        full :meth:`drain_manifest` — rids, lanes, AND the tokens each
        in-flight request already emitted, so the frontend replaying it
        can resubmit the unfinished tail and splice the continuation
        instead of regenerating from scratch — plus the wedge counter.
        Runs on the watchdog thread; reads of the slot arrays are
        racy-but-safe (the decode thread is by definition wedged)."""
        manifest = self.drain_manifest()
        queued = [m.rid for m in manifest if m.phase == "queued"]
        inflight = [m.rid for m in manifest if m.phase == "in_flight"]
        # EVERY entry, untruncated: this record IS the requeue manifest
        # — a frontend replaying it cannot recover ids (or emitted
        # tokens) a cap dropped.  One long line once per process death
        # is the cheap side of that trade (the wedge exits the process
        # right after this).
        log_structured(
            _logger, logging.ERROR, "serve.step_wedged",
            decode_step=self.stats["decode_steps"],
            queued_rids=queued, inflight_rids=inflight,
            queued=len(queued), inflight=len(inflight),
            manifest=[dataclasses.asdict(m) for m in manifest],
            elapsed_s=info.get("elapsed_s"))
        _metrics.inc("apex_serve_wedges_total",
                     help="decode steps the watchdog declared wedged")

    def _active_trace_ids(self) -> List[str]:
        """Trace ids of the resident requests, slot order — stamped on
        the batch-level decode/verify spans so a per-request exemplar's
        ``trace_id`` joins to the specific steps that served it, not
        just the whole-lifetime ``serve.request`` span."""
        return [self._slots[i].request.trace_id
                for i in range(self.dcfg.max_batch)
                if self._active[i] and self._slots[i] is not None
                and self._slots[i].request.trace_id is not None]

    def _record_occupancy(self) -> None:
        """Serving gauges on the current registry (the scope seam:
        ``with MetricsScope(reg):`` around the serve loop routes them)."""
        _metrics.set_gauge("apex_serve_queue_depth",
                           len(self.queue) + len(self.be_queue),
                           help="requests waiting for a slot+pages")
        _metrics.set_gauge("apex_serve_lane_queue_depth", len(self.queue),
                           help="waiting requests, by lane",
                           lane="interactive")
        _metrics.set_gauge("apex_serve_lane_queue_depth",
                           len(self.be_queue),
                           help="waiting requests, by lane",
                           lane="best_effort")
        _metrics.set_gauge("apex_serve_active_slots", self.num_active,
                           help="resident decoding sequences")
        _metrics.set_gauge("apex_serve_free_pages",
                           self.allocator.free_pages,
                           help="allocatable KV pages")

    # ------------------------------------------------------------ build
    def _build_steps(self) -> None:
        d = self.dcfg
        if d.draft_len > 0:
            self._verify = make_verify_step(self.config, d)
            self._decode = None
        else:
            self._decode = make_decode_step(self.config, d)
            self._verify = None
        if d.prefill_chunk is not None:
            self._chunk = make_prefill_chunk(self.config, d)
            self._sample_head = make_sample_head(self.config, d)
            self._prefill = None
        else:
            self._prefill = make_prefill(self.config, d)
            self._chunk = None
            self._sample_head = None

    def decode_cache_size(self) -> int:
        """Compiled-variant count of the decode-family step (the verify
        step when speculation is on) — the compile-once pin (1 after
        any number of steps at any occupancy/length/draft-hit mix)."""
        step = self._verify if self.dcfg.draft_len > 0 else self._decode
        return step._cache_size()

    def _call(self, attr: str, *args):
        """Run a compiled step; on a deferred kernel-compile failure,
        attribute it to the registry, rebuild the steps ONCE (the new
        trace lowers the fallback impls), and retry."""
        try:
            return getattr(self, attr)(*args)
        except Exception as exc:  # noqa: BLE001 — attribution decides
            from apex_tpu.resilience.fallback import trip_from_exception

            tripped = trip_from_exception(exc)
            if not tripped or self._rebuilt_once:
                raise
            self._rebuilt_once = True
            self.stats["step_rebuilds"] += 1
            log_structured(
                _logger, logging.WARNING, "inference.step_rebuilt",
                tripped=tripped, error=f"{type(exc).__name__}: {exc}")
            self._build_steps()
            return getattr(self, attr)(*args)

    # ------------------------------------------------------------ seeds
    def _seed_at(self, slot: int, draw: int) -> int:
        return (self.dcfg.base_seed
                + slot * 0x9E3779B9 + draw * 0x85EBCA6B) & _MASK32

    def _seed(self, slot: int) -> int:
        d = int(self._draws[slot])
        self._draws[slot] += 1
        return self._seed_at(slot, d)

    # ---------------------------------------------------------- requests
    def submit(self, request: Request) -> None:
        """Queue a request (FIFO within its lane).  Requests that can
        NEVER fit the static shapes fail here, loudly, instead of
        wedging the queue head forever."""
        if self._draining:
            raise RuntimeError(
                "scheduler is draining (begin_drain) — submit to "
                "another replica")
        if request.lane not in LANES:
            raise ValueError(
                f"unknown lane {request.lane!r}; lanes are {LANES}")
        plen = len(request.prompt)
        if plen < 1:
            raise ValueError("empty prompt")
        if self.dcfg.prefill_chunk is None \
                and plen > self.dcfg.max_prompt_len:
            raise ValueError(
                f"prompt ({plen} tokens) exceeds max_prompt_len "
                f"({self.dcfg.max_prompt_len}) — set prefill_chunk to "
                f"admit long prompts as chunks")
        if request.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.config.position_embedding_type == "learned" \
                and plen + request.max_new_tokens > self.config.max_seq_len:
            raise ValueError(
                f"prompt + max_new_tokens ({plen} + "
                f"{request.max_new_tokens}) exceeds the learned position "
                f"table ({self.config.max_seq_len})")
        need = self._total_pages(request)
        P = self.dcfg.cache.pages_per_seq
        if need > P:
            raise ValueError(
                f"request needs {need} pages; page tables hold {P} "
                f"(pages_per_seq) — raise pages_per_seq or shorten the "
                f"request")
        if need > self.allocator.num_pages - 1:
            raise ValueError(
                f"request needs {need} pages; the pool only has "
                f"{self.allocator.num_pages - 1} allocatable")
        if request.trace_id is None:
            request.trace_id = _tracing.new_trace_id()
        self._submit_times[request.rid] = self._time()
        (self.queue if request.lane == "interactive"
         else self.be_queue).append(request)
        self._record_occupancy()

    def cancel(self, rid: int) -> Optional[Request]:
        """Remove a still-QUEUED request (either lane) and return it;
        None when ``rid`` is resident or unknown — a decoding sequence
        is not cancellable mid-step, the caller suppresses its output
        instead (the frontend's hedge-loser path)."""
        for q in (self.queue, self.be_queue):
            for req in q:
                if req.rid == rid:
                    q.remove(req)
                    self._submit_times.pop(rid, None)
                    self._carry.pop(rid, None)
                    _metrics.inc("apex_serve_cancelled_total",
                                 help="queued requests cancelled "
                                      "before admission")
                    self._record_occupancy()
                    return req
        return None

    @property
    def draining(self) -> bool:
        return self._draining

    def drained(self) -> bool:
        """True once a draining scheduler has no residents left — the
        planned-restart point where killing the replica drops nothing."""
        return self._draining and all(s is None for s in self._slots)

    def begin_drain(self) -> List[ManifestEntry]:
        """Planned-restart entry: stop admitting (``submit`` raises,
        ``_admit`` is a no-op), hand back the queued requests as a
        manifest (they would otherwise wait forever), and let the
        residents finish through the ordinary step/evict path.  The
        caller re-routes the returned entries and polls :meth:`drained`
        before recycling the process."""
        self._draining = True
        manifest = [m for m in self.drain_manifest()
                    if m.phase == "queued"]
        for m in manifest:
            self._submit_times.pop(m.rid, None)
            self._carry.pop(m.rid, None)
        self.queue.clear()
        self.be_queue.clear()
        log_structured(
            _logger, logging.INFO, "serve.drain_begun",
            requeued=len(manifest), residents=self.num_active)
        self._record_occupancy()
        return manifest

    def _epoch(self, mono: float) -> float:
        """Epoch timestamp of the monotonic instant ``mono`` (the
        retro-emitted spans' clock: both endpoints are measured on
        ``self._time``, Chrome trace events want wall time)."""
        return time.time() - (self._time() - mono)

    def _total_pages(self, req: Request) -> int:
        """Worst-case page-table footprint: prompt + generation budget,
        plus the speculative write window (draft k/v land up to
        ``draft_len`` positions past the accepted stream and must never
        spill into an unreserved — garbage — table entry)."""
        return pages_needed(
            len(req.prompt) + req.max_new_tokens + self.dcfg.draft_len,
            self.dcfg.cache.page_size)

    @property
    def num_active(self) -> int:
        return int(self._active.sum())

    def idle(self) -> bool:
        return (not self.queue and not self.be_queue
                and all(s is None for s in self._slots))

    # ------------------------------------------------------------- admit
    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self._slots):
            if s is None:
                return i
        return None

    def _plan(self, req: Request):
        """(total_pages, match, need_fresh) for admitting ``req`` NOW —
        recomputed on every attempt (the trie and pool move under us)."""
        total = self._total_pages(req)
        match = (self.prefix.match(req.prompt) if self.prefix is not None
                 else PrefixMatch((), None, 0))
        return total, match, total - match.num_full

    def _admit(self) -> int:
        if self._draining:
            return 0
        admitted = self._admit_from(self.queue, can_preempt=True)
        if not self.queue:
            # best-effort fills leftover capacity only while no
            # interactive request waits (the lane priority contract)
            admitted += self._admit_from(self.be_queue, can_preempt=False)
        return admitted

    def _admit_from(self, queue: deque, can_preempt: bool) -> int:
        admitted = 0
        while queue:
            req = queue[0]
            slot = self._free_slot()
            total, match, need_fresh = self._plan(req)
            if slot is None or not self.allocator.can_allocate(need_fresh):
                if slot is not None and self.prefix is not None and \
                        self.prefix.release(
                            need_fresh - self.allocator.free_pages):
                    continue  # trie refs dropped — re-plan and retry
                if can_preempt and self._preempt_one():
                    continue  # a best-effort resident yielded — retry
                break  # FIFO: the head blocks, nothing overtakes it
            queue.popleft()
            self._admit_into(slot, req, total, match, need_fresh)
            admitted += 1
        return admitted

    def _admit_into(self, slot: int, req: Request, total: int,
                    match: PrefixMatch, need_fresh: int) -> None:
        t0 = self._time()
        submitted = self._submit_times.pop(req.rid, t0)
        _metrics.observe("apex_serve_admission_wait_seconds",
                         t0 - submitted,
                         help="submit -> slot+pages reserved",
                         exemplar={"trace_id": req.trace_id,
                                   "rid": req.rid},
                         lane=req.lane)
        tracer = _tracing.get_tracer()
        if tracer is not None:
            # both endpoints are known only now — retro-emit the wait
            tracer.emit("serve.admission_wait", self._epoch(submitted),
                        t0 - submitted, rid=req.rid,
                        trace_id=req.trace_id, lane=req.lane)
        fresh = self.allocator.allocate(need_fresh)
        assert fresh is not None  # _admit_from checked can_allocate
        if match.num_full:
            self.allocator.share(match.full_pages)
            self.stats["shared_full_pages"] += match.num_full
        table: List[int] = list(match.full_pages)
        it = iter(fresh)
        cow_reserve = None
        if match.tail_page is not None:
            self.allocator.share([match.tail_page])
            self.stats["shared_tail_pages"] += 1
            table.append(match.tail_page)
            cow_reserve = next(it)  # the tail's COW budget, held aside
        table.extend(it)
        P = self.dcfg.cache.pages_per_seq
        row = np.zeros((P,), np.int32)
        row[:len(table)] = table
        self._page_tables[slot] = row
        plen = len(req.prompt)
        self._admit_counter += 1
        s = _Slot(request=req, pages=table, generated=[],
                  token_times=[], submit_time=t0,
                  admit_seq=self._admit_counter, submitted_at=submitted,
                  shared_len=match.shared_len, cow_reserve=cow_reserve)
        self._slots[slot] = s
        self.stats["admitted"] += 1
        if self.dcfg.prefill_chunk is not None:
            # chunked admission: compute starts past the shared prefix
            # (fully-cached prompt → one recompute pass over the last
            # position, no writes), one chunk per scheduler step
            s.chunk_next = (match.shared_len if match.shared_len < plen
                            else plen - 1)
            return
        prompt = np.zeros((1, self.dcfg.max_prompt_len), np.int32)
        prompt[0, :plen] = req.prompt
        with _tracing.span("serve.prefill", rid=req.rid,
                           trace_id=req.trace_id, lane=req.lane,
                           prompt_len=plen,
                           shared_len=match.shared_len):
            self.pools, first = self._call(
                "_prefill", self.params, self.pools,
                jnp.asarray(prompt), jnp.int32(plen),
                jnp.int32(match.shared_len), jnp.asarray(row),
                jnp.uint32(self._seed(slot)))
        self.stats["prefills"] += 1
        self._start_decoding(slot, int(first), submitted)

    def _start_decoding(self, slot: int, first: int,
                        submitted: float) -> None:
        """Common prefill epilogue (classic and chunked): record the
        first token, index the prompt's full pages into the prefix
        trie, arm the slot for decode, and evict degenerate (1-token /
        instant-eos) generations immediately."""
        s = self._slots[slot]
        req = s.request
        t_first = self._time()
        _metrics.observe("apex_serve_ttft_seconds", t_first - submitted,
                         help="submit -> first token (prefill incl. queue)",
                         exemplar={"trace_id": req.trace_id,
                                   "rid": req.rid},
                         lane=req.lane)
        if self._anomaly is not None:
            self._anomaly.observe("ttft", t_first - submitted,
                                  lane=req.lane)
        s.generated.append(first)
        s.token_times.append(t_first)
        s.chunk_next = None
        if self.prefix is not None:
            # full pages quiesce the moment the prompt is cached; the
            # (mutable) tail page waits for eviction
            self.prefix.register(req.prompt, [int(p) for p in s.pages])
        if self.dcfg.draft_len > 0:
            s.proposer = NGramProposer(self.dcfg.draft_len,
                                       self.dcfg.ngram_max,
                                       self.dcfg.ngram_min)
            s.proposer.extend(list(req.prompt) + [first])
        self._positions[slot] = len(req.prompt)  # where `first` caches
        self._tokens[slot] = first
        self._active[slot] = True
        if (req.max_new_tokens == 1
                or (req.eos_id is not None and first == req.eos_id)):
            self._evict(slot)

    # --------------------------------------------------------- preemption
    def _preempt_one(self) -> bool:
        """Evict the YOUNGEST best-effort resident (decoding or still
        chunk-prefilling) through the ordinary evict→recycle path and
        requeue its continuation at its lane's head.  Returns whether a
        victim yielded."""
        cands = [i for i, s in enumerate(self._slots)
                 if s is not None and s.request.lane == "best_effort"]
        if not cands:
            return False
        victim = max(cands, key=lambda i: self._slots[i].admit_seq)
        s = self._slots[victim]
        req = s.request
        c = self._carry.get(req.rid)
        if c is None:
            c = _Carry(prompt=list(req.prompt), tokens=[], times=[],
                       submit_time=s.submit_time)
            self._carry[req.rid] = c
        c.preemptions += 1
        remaining = req.max_new_tokens - len(s.generated)
        cont_prompt = list(req.prompt) + list(s.generated)
        can_continue = (
            s.chunk_next is None and s.generated and remaining >= 1
            and (self.dcfg.prefill_chunk is not None
                 or len(cont_prompt) <= self.dcfg.max_prompt_len))
        if can_continue:
            c.tokens.extend(s.generated)
            c.times.extend(s.token_times)
            cont = Request(rid=req.rid, prompt=cont_prompt,
                           max_new_tokens=remaining, eos_id=req.eos_id,
                           lane=req.lane, trace_id=req.trace_id)
        else:  # restart this leg (its partial work is dropped)
            cont = Request(rid=req.rid, prompt=list(req.prompt),
                           max_new_tokens=req.max_new_tokens,
                           eos_id=req.eos_id, lane=req.lane,
                           trace_id=req.trace_id)
        self._release_slot(victim)
        self.stats["preemptions"] += 1
        _metrics.inc("apex_serve_preemptions_total",
                     help="best-effort residents evicted for the "
                          "interactive lane")
        log_structured(
            _logger, logging.INFO, "serve.preempted", rid=req.rid,
            generated=len(s.generated), requeued_prompt=len(cont.prompt))
        self._submit_times[req.rid] = self._time()
        self.be_queue.appendleft(cont)
        return True

    def _release_slot(self, slot: int) -> None:
        """Return a slot's pages (and unused COW reserve) to the
        allocator and clear its static-shape arrays."""
        s = self._slots[slot]
        self.allocator.free(s.pages)
        if s.cow_reserve is not None:
            self.allocator.free([s.cow_reserve])
        self._slots[slot] = None
        self._active[slot] = False
        self._page_tables[slot] = 0
        self._positions[slot] = 0
        self._tokens[slot] = 0

    # ------------------------------------------------------------- evict
    def _evict(self, slot: int) -> None:
        s = self._slots[slot]
        if self.prefix is not None and s.chunk_next is None:
            # the tail page is quiesced now — index it (full pages
            # re-index as a no-op walk, repairing released chains)
            self.prefix.register(
                s.request.prompt,
                [int(p) for p in self._page_tables[slot]], tail=True)
        c = self._carry.pop(s.request.rid, None)
        prompt = c.prompt if c is not None else list(s.request.prompt)
        tokens = (list(c.tokens) if c is not None else []) \
            + list(s.generated)
        times = (list(c.times) if c is not None else []) \
            + list(s.token_times)
        submit = c.submit_time if c is not None else s.submit_time
        self._release_slot(slot)
        finish = self._time()
        self.completed.append(Completion(
            rid=s.request.rid, prompt=prompt, tokens=tokens,
            submit_time=submit, finish_time=finish,
            token_times=times, lane=s.request.lane,
            preemptions=c.preemptions if c is not None else 0,
            trace_id=s.request.trace_id))
        tracer = _tracing.get_tracer()
        if tracer is not None:
            # the whole-lifetime span (admit-time submit -> eviction):
            # what the TTFT-exemplar trace_id joins to
            tracer.emit(
                "serve.request", self._epoch(submit), finish - submit,
                rid=s.request.rid, trace_id=s.request.trace_id,
                lane=s.request.lane, tokens=len(tokens),
                ttft_s=round(times[0] - submit, 6) if times else None,
                preemptions=c.preemptions if c is not None else 0)
        self.stats["evicted"] += 1
        _metrics.inc("apex_serve_completions_total",
                     help="finished generations")
        _metrics.inc("apex_serve_generated_tokens_total", len(tokens),
                     help="tokens served")
        self._record_occupancy()

    # ----------------------------------------------------- chunked prefill
    def _advance_chunks(self) -> bool:
        """One prefill chunk per still-prefilling slot: the chunk's k/v
        scatter into the reserved pages through the multi-position
        decode forward (shared-prefix positions skip both compute and
        writes), and the final chunk's last hidden state feeds the
        sampling head for the first token."""
        progressed = False
        C = self.dcfg.prefill_chunk
        for i, s in enumerate(self._slots):
            if s is None or s.chunk_next is None:
                continue
            plen = len(s.request.prompt)
            start = s.chunk_next
            n_valid = min(C, plen - start)
            tok = np.zeros((C,), np.int32)
            tok[:n_valid] = s.request.prompt[start:start + n_valid]
            with _tracing.span("serve.prefill_chunk", rid=s.request.rid,
                               trace_id=s.request.trace_id,
                               lane=s.request.lane, chunk_start=start,
                               chunk_tokens=n_valid):
                self.pools, h_last = self._call(
                    "_chunk", self.params, self.pools, jnp.asarray(tok),
                    jnp.int32(start), jnp.int32(n_valid),
                    jnp.int32(s.shared_len),
                    jnp.asarray(self._page_tables[i]))
            self.stats["chunk_steps"] += 1
            s.chunk_next = start + n_valid
            progressed = True
            if s.chunk_next >= plen:
                first = int(self._call(
                    "_sample_head", self.params, h_last,
                    jnp.uint32(self._seed(i))))
                self.stats["prefills"] += 1
                self._start_decoding(i, first, s.submitted_at)
        return progressed

    # ------------------------------------------------------------- COW
    def _cow_for_writes(self, width: int) -> None:
        """Copy-on-write pass before a decode/verify step: any page the
        step's write window (``positions .. positions + width - 1``)
        touches with refcount > 1 is copied into the slot's reserve and
        the table repointed — shared pages are never written through."""
        if self.prefix is None:
            return  # no sharing → no page can ever hold refcount > 1
        ps = self.dcfg.cache.page_size
        P = self.dcfg.cache.pages_per_seq
        for i in range(self.dcfg.max_batch):
            if not self._active[i]:
                continue
            p0 = int(self._positions[i])
            first_ix = p0 // ps
            last_ix = min((p0 + width - 1) // ps, P - 1)
            for ix in range(first_ix, last_ix + 1):
                page = int(self._page_tables[i, ix])
                if page == GARBAGE_PAGE \
                        or self.allocator.refcount(page) <= 1:
                    continue
                s = self._slots[i]
                if s.cow_reserve is None:
                    raise RuntimeError(
                        f"slot {i}: divergent write into shared page "
                        f"{page} with no COW reserve — the admission "
                        f"plan must reserve one page per shared tail")
                new = s.cow_reserve
                s.cow_reserve = None
                self.pools = copy_page(self.pools, page, new)
                self.allocator.free([page])  # drop this slot's share
                self._page_tables[i, ix] = new
                s.pages[ix] = new
                self.stats["cow_copies"] += 1
                _metrics.inc("apex_serve_cow_copies_total",
                             help="shared pages copied before a "
                                  "divergent write")

    # -------------------------------------------------------------- step
    def step(self) -> bool:
        """Admit waiting requests (both lanes), advance chunked
        prefills by one chunk each, then advance every active sequence
        — one token (plain decode) or up to ``draft_len + 1`` tokens
        (speculative verify).  Returns True when any work happened."""
        if self._watchdog is not None:
            # the first interval covers the prefill/decode jit compiles
            # (the trainer loop's compile-grace pattern); steady state
            # uses the watchdog's own deadline
            self._watchdog.beat(
                self.stats["decode_steps"],
                deadline=(self._watchdog.first_deadline_sec
                          if not self._beaten else None))
            self._beaten = True
        monkey = active_monkey()
        if monkey is not None:
            # deterministic wedged-decode-step fault: the sleep holds
            # THIS step past the watchdog deadline, exactly how a dead
            # tunnel presents (plan key: decode steps taken so far)
            monkey.maybe_wedge_step(self.stats["decode_steps"])
        admitted = self._admit()
        progressed = False
        if self.dcfg.prefill_chunk is not None:
            progressed = self._advance_chunks()
        if not self._active.any():
            return admitted > 0 or progressed
        if self.dcfg.draft_len > 0:
            self._step_verify()
        else:
            self._step_decode()
        return True

    def _step_decode(self) -> None:
        """The plain one-token decode step (PR 9 semantics, plus the
        COW pass and per-lane latency labels)."""
        B = self.dcfg.max_batch
        self._cow_for_writes(width=1)
        seeds = np.zeros((B,), np.uint32)
        for i in range(B):
            if self._active[i]:
                seeds[i] = self._seed(i)
        # attrs (slot scan, active count) are only worth computing when
        # a tracer is installed — this is the highest-frequency span in
        # the serving path and the off case must stay near-zero
        attrs = (dict(decode_step=self.stats["decode_steps"],
                      active=int(self._active.sum()),
                      trace_ids=self._active_trace_ids())
                 if _tracing.enabled() else {})
        with _tracing.span("serve.decode_step", **attrs):
            self.pools, next_tokens = self._call(
                "_decode", self.params, self.pools,
                jnp.asarray(self._tokens), jnp.asarray(self._positions),
                jnp.asarray(self._active), jnp.asarray(self._page_tables),
                jnp.asarray(seeds))
            next_tokens = np.asarray(next_tokens)
        now = self._time()
        self.stats["decode_steps"] += 1
        self._record_occupancy()
        for i in range(B):
            if not self._active[i]:
                continue
            s = self._slots[i]
            tok = int(next_tokens[i])
            _metrics.observe("apex_serve_inter_token_seconds",
                             now - s.token_times[-1],
                             help="previous token -> this token",
                             exemplar={"trace_id": s.request.trace_id,
                                       "rid": s.request.rid},
                             lane=s.request.lane)
            if self._anomaly is not None:
                self._anomaly.observe("inter_token",
                                      now - s.token_times[-1],
                                      lane=s.request.lane)
            s.generated.append(tok)
            s.token_times.append(now)
            self._tokens[i] = tok
            self._positions[i] += 1
            if (len(s.generated) >= s.request.max_new_tokens
                    or (s.request.eos_id is not None
                        and tok == s.request.eos_id)):
                self._evict(i)

    def _step_verify(self) -> None:
        """The speculative step: draft, verify all ``draft_len + 1``
        positions in ONE batched pass, accept the longest matching
        prefix per slot.  Emissions spend the same (slot, draw) seeds
        as the plain decode path — the token stream is bitwise the
        non-speculative stream, delivered faster."""
        B = self.dcfg.max_batch
        W = self.dcfg.draft_len + 1
        self._cow_for_writes(width=W)
        tokmat = np.zeros((B, W), np.int32)
        seeds = np.zeros((B, W), np.uint32)
        for i in range(B):
            if not self._active[i]:
                continue
            tokmat[i, 0] = self._tokens[i]
            drafts = self._slots[i].proposer.propose()
            if drafts:
                k = min(len(drafts), W - 1)
                tokmat[i, 1:1 + k] = drafts[:k]
            d0 = int(self._draws[i])
            for j in range(W):
                seeds[i, j] = self._seed_at(i, d0 + j)
        # the verify span is ended by hand so the spec ACCEPT counts —
        # known only after the host accepts per slot — ride its attrs
        verify_attrs = (dict(decode_step=self.stats["decode_steps"],
                             active=int(self._active.sum()),
                             draft_len=W - 1,
                             trace_ids=self._active_trace_ids())
                        if _tracing.enabled() else {})
        verify_span = _tracing.span("serve.verify_step", **verify_attrs)
        emitted_before = self.stats["spec_emitted"]
        try:
            self.pools, sampled = self._call(
                "_verify", self.params, self.pools,
                jnp.asarray(tokmat), jnp.asarray(self._positions),
                jnp.asarray(self._active), jnp.asarray(self._page_tables),
                jnp.asarray(seeds))
            sampled = np.asarray(sampled)
            now = self._time()
            self.stats["decode_steps"] += 1
            self.stats["spec_steps"] += 1
            self._record_occupancy()
            for i in range(B):
                if not self._active[i]:
                    continue
                s = self._slots[i]
                emit = accepted_tokens(tokmat[i], sampled[i])
                out: List[int] = []
                for tok in emit:  # clamp to the generation budget / eos
                    out.append(tok)
                    if s.request.eos_id is not None \
                            and tok == s.request.eos_id:
                        break
                    if len(s.generated) + len(out) \
                            >= s.request.max_new_tokens:
                        break
                self._draws[i] += len(out)  # one draw per emission
                for tok in out:
                    _metrics.observe(
                        "apex_serve_inter_token_seconds",
                        now - s.token_times[-1],
                        help="previous token -> this token",
                        exemplar={"trace_id": s.request.trace_id,
                                  "rid": s.request.rid},
                        lane=s.request.lane)
                    if self._anomaly is not None:
                        self._anomaly.observe("inter_token",
                                              now - s.token_times[-1],
                                              lane=s.request.lane)
                    s.generated.append(tok)
                    s.token_times.append(now)
                s.proposer.extend(out)
                self.stats["spec_emitted"] += len(out)
                _metrics.inc("apex_serve_spec_emitted_total", len(out),
                             help="tokens emitted by verify steps")
                self._tokens[i] = out[-1]
                self._positions[i] += len(out)
                if (len(s.generated) >= s.request.max_new_tokens
                        or (s.request.eos_id is not None
                            and out[-1] == s.request.eos_id)):
                    self._evict(i)
        except BaseException:
            verify_span.set(error=True)
            raise
        finally:
            # the accept loop can raise too — the span must never leak
            # open (it would render as a phantom wedged verify step in
            # every later export and flight-recorder dump)
            verify_span.end(
                emitted=self.stats["spec_emitted"] - emitted_before)

    def run_until_drained(self, max_steps: int = 10_000) -> List[Completion]:
        """Drive ``step()`` until queues and slots are empty (the
        test/driver convenience loop)."""
        for _ in range(max_steps):
            if self.idle():
                return self.completed
            self.step()
        raise RuntimeError(
            f"serve loop not drained after {max_steps} steps "
            f"(queue={len(self.queue) + len(self.be_queue)}, "
            f"active={self.num_active})")
