"""Continuous-batching scheduler: admit, decode, evict — between steps.

The serving loop's control plane (Orca-style continuous batching): the
jitted decode step always runs at the STATIC ``max_batch`` shape, and
this scheduler fills its slots —

- **admit**: between decode steps, queued requests move into free slots
  strictly FIFO.  A request is admitted only when a slot is free AND
  the page allocator can reserve its WORST-CASE page count
  (``ceil((prompt_len + max_new_tokens) / page_size)``), so a resident
  sequence can never hit a mid-generation allocation failure and the
  queue head can never be overtaken (no starvation: when the head does
  not fit, nothing behind it is considered).
- **prefill**: an admitted prompt runs through the training forward at
  ONE static padded shape (``DecodeConfig.max_prompt_len``), its
  per-layer k/v scatter into the reserved pages, and the first
  generated token is sampled from the last prompt position.
- **decode**: one fused step advances every active slot; inactive
  slots ride along masked.
- **evict**: finished sequences (max_new reached, or ``eos_id``) free
  their pages back to the allocator — the next ``step()`` can admit
  into them.

The scheduler is time-agnostic (drivers decide when to ``submit``;
tests replay seeded traces step-by-step, the load-generator example
submits on wall-clock Poisson arrivals) and deterministic: sampling
seeds derive from ``(base_seed, slot, per-slot draw counter)``, so the
same trace of submits produces the same tokens.

Kernel resilience: trace-time kernel failures already degrade through
the fallback registry inside the step build; a DEFERRED jit-compile
failure surfaces on the first call, is attributed via
``resilience.fallback.trip_from_exception``, and the steps are rebuilt
once — the fresh trace lowers the XLA reference and the server keeps
serving (the same recovery ``examples/gpt/pretrain_gpt.py`` wires for
training).

Wedge resilience: a ``watchdog=`` (:class:`apex_tpu.resilience
.StepWatchdog`) gets a heartbeat per scheduler step; a decode step that
never returns (dead tunnel, hung collective) fires it — the scheduler's
``on_wedge`` hook logs every queued and in-flight request id
(``serve.step_wedged`` — the requeue manifest for the layer above) and
records ``apex_serve_wedges_total``, then the watchdog drains and exits
75 so a :class:`~apex_tpu.resilience.supervisor.Supervisor` restarts
the server (``serve_gpt.py --supervise --watchdog-secs``).
"""

import dataclasses
import logging
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

import jax.numpy as jnp

from apex_tpu.inference.decode import (
    DecodeConfig, make_decode_step, make_prefill,
)
from apex_tpu.inference.kv_cache import (
    PageAllocator, alloc_pools, pages_needed,
)
from apex_tpu.models.gpt import GPTConfig
from apex_tpu.observability import metrics as _metrics
from apex_tpu.resilience.chaos import active_monkey
from apex_tpu.utils.logging import get_logger, log_structured

__all__ = ["Request", "Completion", "ContinuousBatchingScheduler"]

_logger = get_logger("apex_tpu.inference")

_MASK32 = (1 << 32) - 1


@dataclasses.dataclass
class Request:
    """One generation request: ``prompt`` token ids, ``max_new_tokens``
    to generate, optional ``eos_id`` early stop."""

    rid: int
    prompt: List[int]
    max_new_tokens: int
    eos_id: Optional[int] = None


@dataclasses.dataclass
class Completion:
    """A finished request with its wall-clock trace: ``token_times[i]``
    is when ``tokens[i]`` became available (``token_times[0]`` is the
    prefill / time-to-first-token)."""

    rid: int
    prompt: List[int]
    tokens: List[int]
    submit_time: float
    finish_time: float
    token_times: List[float]


@dataclasses.dataclass
class _Slot:
    request: Request
    pages: List[int]
    generated: List[int]
    token_times: List[float]
    submit_time: float


class ContinuousBatchingScheduler:
    """The serve loop's control plane: FIFO admission into freed KV
    pages between decode steps, static-shape slot management, eviction
    with page recycling, deterministic per-slot sampling seeds, and
    degrade-once step rebuild on deferred kernel failures (see the
    module docstring for the full semantics)."""

    def __init__(self, params, config: GPTConfig, dcfg: DecodeConfig,
                 time_fn=time.monotonic, watchdog=None):
        cache = dcfg.cache
        if config.moe:
            raise NotImplementedError("MoE decode is not wired")
        if dcfg.max_prompt_len > config.max_seq_len \
                and config.position_embedding_type == "learned":
            raise ValueError(
                f"max_prompt_len ({dcfg.max_prompt_len}) exceeds the "
                f"learned position table ({config.max_seq_len})")
        self.params = params
        self.config = config
        self.dcfg = dcfg
        self._time = time_fn
        tp_local_kv = config.kv_heads  # single-process serving: tp=1
        self.pools = alloc_pools(config.num_layers, tp_local_kv,
                                 config.head_dim, cache)
        self.allocator = PageAllocator(cache.num_pages)
        self.queue: deque = deque()
        B, P = dcfg.max_batch, cache.pages_per_seq
        self._slots: List[Optional[_Slot]] = [None] * B
        self._page_tables = np.zeros((B, P), np.int32)
        self._positions = np.zeros((B,), np.int32)
        self._tokens = np.zeros((B,), np.int32)
        self._active = np.zeros((B,), bool)
        self._draws = np.zeros((B,), np.int64)
        self.completed: List[Completion] = []
        self.stats: Dict[str, int] = {
            "admitted": 0, "evicted": 0, "decode_steps": 0,
            "prefills": 0, "step_rebuilds": 0,
        }
        self._rebuilt_once = False
        #: true submit wall-time per queued rid (Completion.submit_time
        #: is the ADMIT time for driver compatibility; the metrics
        #: histograms — admission wait, TTFT — need the real submit)
        self._submit_times: Dict[int, float] = {}
        self._watchdog = watchdog
        self._beaten = False
        if watchdog is not None:
            # chain, don't clobber: the driver may have wired its own
            # pre-exit hook (the trainer's goodput finalize pattern)
            prev = watchdog.on_wedge

            def hook(info, _prev=prev):
                if _prev is not None:
                    _prev(info)
                self._on_wedge(info)

            watchdog.on_wedge = hook
        self._build_steps()

    def _on_wedge(self, info) -> None:
        """Watchdog pre-exit hook: one structured record naming every
        queued and in-flight request id — the requeue manifest a
        frontend replays after the supervisor restarts the engine —
        plus the wedge counter.  Runs on the watchdog thread; reads of
        the slot arrays are racy-but-safe (the decode thread is by
        definition wedged)."""
        queued = [r.rid for r in list(self.queue)]
        inflight = [s.request.rid for s in self._slots if s is not None]
        # EVERY id, untruncated: this record IS the requeue manifest —
        # a frontend replaying it cannot recover ids a cap dropped.
        # One long line once per process death is the cheap side of
        # that trade (the wedge exits the process right after this).
        log_structured(
            _logger, logging.ERROR, "serve.step_wedged",
            decode_step=self.stats["decode_steps"],
            queued_rids=queued, inflight_rids=inflight,
            queued=len(queued), inflight=len(inflight),
            elapsed_s=info.get("elapsed_s"))
        _metrics.inc("apex_serve_wedges_total",
                     help="decode steps the watchdog declared wedged")

    def _record_occupancy(self) -> None:
        """Serving gauges on the current registry (the scope seam:
        ``with MetricsScope(reg):`` around the serve loop routes them)."""
        _metrics.set_gauge("apex_serve_queue_depth", len(self.queue),
                           help="requests waiting for a slot+pages")
        _metrics.set_gauge("apex_serve_active_slots", self.num_active,
                           help="resident decoding sequences")
        _metrics.set_gauge("apex_serve_free_pages",
                           self.allocator.free_pages,
                           help="allocatable KV pages")

    # ------------------------------------------------------------ build
    def _build_steps(self) -> None:
        self._decode = make_decode_step(self.config, self.dcfg)
        self._prefill = make_prefill(self.config, self.dcfg)

    def decode_cache_size(self) -> int:
        """Compiled-variant count of the decode step — the
        compile-once pin (1 after any number of steps at any
        occupancy/length mix)."""
        return self._decode._cache_size()

    def _call(self, attr: str, *args):
        """Run a compiled step; on a deferred kernel-compile failure,
        attribute it to the registry, rebuild both steps ONCE (the new
        trace lowers the fallback impls), and retry."""
        try:
            return getattr(self, attr)(*args)
        except Exception as exc:  # noqa: BLE001 — attribution decides
            from apex_tpu.resilience.fallback import trip_from_exception

            tripped = trip_from_exception(exc)
            if not tripped or self._rebuilt_once:
                raise
            self._rebuilt_once = True
            self.stats["step_rebuilds"] += 1
            log_structured(
                _logger, logging.WARNING, "inference.step_rebuilt",
                tripped=tripped, error=f"{type(exc).__name__}: {exc}")
            self._build_steps()
            return getattr(self, attr)(*args)

    # ------------------------------------------------------------ seeds
    def _seed(self, slot: int) -> int:
        d = int(self._draws[slot])
        self._draws[slot] += 1
        s = (self.dcfg.base_seed
             + slot * 0x9E3779B9 + d * 0x85EBCA6B) & _MASK32
        return s

    # ---------------------------------------------------------- requests
    def submit(self, request: Request) -> None:
        """Queue a request (FIFO).  Requests that can NEVER fit the
        static shapes fail here, loudly, instead of wedging the queue
        head forever."""
        plen = len(request.prompt)
        if plen < 1:
            raise ValueError("empty prompt")
        if plen > self.dcfg.max_prompt_len:
            raise ValueError(
                f"prompt ({plen} tokens) exceeds max_prompt_len "
                f"({self.dcfg.max_prompt_len})")
        if request.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        need = pages_needed(plen + request.max_new_tokens,
                            self.dcfg.cache.page_size)
        P = self.dcfg.cache.pages_per_seq
        if need > P:
            raise ValueError(
                f"request needs {need} pages; page tables hold {P} "
                f"(pages_per_seq) — raise pages_per_seq or shorten the "
                f"request")
        if need > self.allocator.num_pages - 1:
            raise ValueError(
                f"request needs {need} pages; the pool only has "
                f"{self.allocator.num_pages - 1} allocatable")
        self._submit_times[request.rid] = self._time()
        self.queue.append(request)
        self._record_occupancy()

    @property
    def num_active(self) -> int:
        return int(self._active.sum())

    def idle(self) -> bool:
        return not self.queue and not self._active.any()

    # ------------------------------------------------------------- admit
    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self._slots):
            if s is None:
                return i
        return None

    def _admit(self) -> int:
        admitted = 0
        while self.queue:
            req = self.queue[0]
            slot = self._free_slot()
            need = pages_needed(len(req.prompt) + req.max_new_tokens,
                                self.dcfg.cache.page_size)
            if slot is None or not self.allocator.can_allocate(need):
                break  # FIFO: the head blocks, nothing overtakes it
            self.queue.popleft()
            pages = self.allocator.allocate(need)
            self._admit_into(slot, req, pages)
            admitted += 1
        return admitted

    def _admit_into(self, slot: int, req: Request, pages: List[int]) -> None:
        t0 = self._time()
        submitted = self._submit_times.pop(req.rid, t0)
        _metrics.observe("apex_serve_admission_wait_seconds",
                         t0 - submitted,
                         help="submit -> slot+pages reserved")
        plen = len(req.prompt)
        P = self.dcfg.cache.pages_per_seq
        row = np.zeros((P,), np.int32)
        row[: len(pages)] = pages
        prompt = np.zeros((1, self.dcfg.max_prompt_len), np.int32)
        prompt[0, :plen] = req.prompt
        self.pools, first = self._call(
            "_prefill", self.params, self.pools,
            jnp.asarray(prompt), jnp.int32(plen), jnp.asarray(row),
            jnp.uint32(self._seed(slot)))
        first = int(first)
        t_first = self._time()
        _metrics.observe("apex_serve_ttft_seconds", t_first - submitted,
                         help="submit -> first token (prefill incl. queue)")
        self._slots[slot] = _Slot(request=req, pages=pages,
                                  generated=[first],
                                  token_times=[t_first],
                                  submit_time=t0)
        self._page_tables[slot] = row
        self._positions[slot] = plen  # where `first` will be cached
        self._tokens[slot] = first
        self._active[slot] = True
        self.stats["admitted"] += 1
        self.stats["prefills"] += 1
        if (req.max_new_tokens == 1
                or (req.eos_id is not None and first == req.eos_id)):
            self._evict(slot)

    # ------------------------------------------------------------- evict
    def _evict(self, slot: int) -> None:
        s = self._slots[slot]
        self.allocator.free(s.pages)
        self.completed.append(Completion(
            rid=s.request.rid, prompt=list(s.request.prompt),
            tokens=list(s.generated), submit_time=s.submit_time,
            finish_time=self._time(), token_times=list(s.token_times)))
        self._slots[slot] = None
        self._active[slot] = False
        self._page_tables[slot] = 0
        self._positions[slot] = 0
        self._tokens[slot] = 0
        self.stats["evicted"] += 1
        _metrics.inc("apex_serve_completions_total",
                     help="finished generations")
        _metrics.inc("apex_serve_generated_tokens_total", len(s.generated),
                     help="tokens served")
        self._record_occupancy()

    # -------------------------------------------------------------- step
    def step(self) -> bool:
        """Admit waiting requests, then advance every active sequence
        one token.  Returns True when any work (admission or decode)
        happened."""
        if self._watchdog is not None:
            # the first interval covers the prefill/decode jit compiles
            # (the trainer loop's compile-grace pattern); steady state
            # uses the watchdog's own deadline
            self._watchdog.beat(
                self.stats["decode_steps"],
                deadline=(self._watchdog.first_deadline_sec
                          if not self._beaten else None))
            self._beaten = True
        monkey = active_monkey()
        if monkey is not None:
            # deterministic wedged-decode-step fault: the sleep holds
            # THIS step past the watchdog deadline, exactly how a dead
            # tunnel presents (plan key: decode steps taken so far)
            monkey.maybe_wedge_step(self.stats["decode_steps"])
        admitted = self._admit()
        if not self._active.any():
            return admitted > 0
        B = self.dcfg.max_batch
        seeds = np.zeros((B,), np.uint32)
        for i in range(B):
            if self._active[i]:
                seeds[i] = self._seed(i)
        self.pools, next_tokens = self._call(
            "_decode", self.params, self.pools,
            jnp.asarray(self._tokens), jnp.asarray(self._positions),
            jnp.asarray(self._active), jnp.asarray(self._page_tables),
            jnp.asarray(seeds))
        next_tokens = np.asarray(next_tokens)
        now = self._time()
        self.stats["decode_steps"] += 1
        self._record_occupancy()
        for i in range(B):
            if not self._active[i]:
                continue
            s = self._slots[i]
            tok = int(next_tokens[i])
            _metrics.observe("apex_serve_inter_token_seconds",
                             now - s.token_times[-1],
                             help="previous token -> this token")
            s.generated.append(tok)
            s.token_times.append(now)
            self._tokens[i] = tok
            self._positions[i] += 1
            if (len(s.generated) >= s.request.max_new_tokens
                    or (s.request.eos_id is not None
                        and tok == s.request.eos_id)):
                self._evict(i)
        return True

    def run_until_drained(self, max_steps: int = 10_000) -> List[Completion]:
        """Drive ``step()`` until queue and slots are empty (the
        test/driver convenience loop)."""
        for _ in range(max_steps):
            if self.idle():
                return self.completed
            self.step()
        raise RuntimeError(
            f"serve loop not drained after {max_steps} steps "
            f"(queue={len(self.queue)}, active={self.num_active})")
