"""Speculative decode: n-gram (prompt-lookup) drafting + acceptance.

The draft side of the serving engine's speculative path: a model-free
proposer guesses the next ``k`` tokens of each resident sequence from
its OWN context (prompt + generated so far), and ONE batched verify
step (:func:`apex_tpu.inference.decode.make_verify_step`) scores all
``k + 1`` positions through the paged attention kernel — the
fused-verification framing of "LLM Inference Acceleration via
Efficient Operation Fusion" (arxiv 2502.17728) with zero extra model:
the draft is a dictionary lookup, so every accepted draft token is a
decode step the MXU never ran.

**Prompt-lookup drafting** (:class:`NGramProposer`): find the most
recent PRIOR occurrence of the context's trailing n-gram (n swept
``ngram_max .. ngram_min``) and propose the ``k`` tokens that followed
it.  Great on the workloads speculation is for — extraction,
summarization-with-quotes, code echoing its own identifiers, any
self-repetitive text; near-useless on high-entropy free generation,
where the engine gracefully pays one (cheap) wasted verify column.

**Acceptance** (:func:`accepted_tokens`) is the longest-matching-
prefix rule: the verify step returns the sampling head's token at
every position; draft column ``j`` survives iff it equals the head's
emission at column ``j - 1``, and the first mismatch position's own
head token is emitted as the (always-correct) bonus.  Every consumed
emission is therefore conditioned on a verified-correct prefix AND
spends the same per-(slot, draw) seed the plain decode step would
have — the emitted stream equals the non-speculative stream bitwise,
greedy and sampled alike.  A draft can only add tokens, never change
them.
"""

from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["NGramProposer", "accepted_tokens"]


class NGramProposer:
    """Per-sequence prompt-lookup draft source.

    Keeps the sequence's full token context plus an incrementally
    maintained index of every n-gram's two most recent end positions
    (for each n in ``[ngram_min, ngram_max]``) — ``propose`` is O(1)
    per n, ``extend`` is O(tokens * n-grams).  The two-deep history
    matters: the context's own trailing n-gram is always the MOST
    recent occurrence of itself, so the draft continuation comes from
    the one before it.
    """

    def __init__(self, draft_len: int, ngram_max: int = 3,
                 ngram_min: int = 1):
        if draft_len < 1:
            raise ValueError(f"draft_len must be >= 1 (got {draft_len})")
        if not (1 <= ngram_min <= ngram_max):
            raise ValueError(f"need 1 <= ngram_min <= ngram_max, got "
                             f"({ngram_min}, {ngram_max})")
        self.draft_len = int(draft_len)
        self.ngram_max = int(ngram_max)
        self.ngram_min = int(ngram_min)
        self._tokens: List[int] = []
        #: gram -> end position (exclusive) of its latest occurrence
        self._latest: Dict[Tuple[int, ...], int] = {}
        #: gram -> end position of the occurrence BEFORE the latest
        self._prior: Dict[Tuple[int, ...], int] = {}

    def __len__(self) -> int:
        return len(self._tokens)

    def extend(self, tokens: Sequence[int]) -> None:
        """Append emitted (or prompt) tokens, indexing every trailing
        n-gram they complete."""
        for t in tokens:
            self._tokens.append(int(t))
            end = len(self._tokens)
            for n in range(self.ngram_min, self.ngram_max + 1):
                if end < n:
                    break
                gram = tuple(self._tokens[end - n:end])
                old = self._latest.get(gram)
                if old is not None:
                    self._prior[gram] = old
                self._latest[gram] = end

    def propose(self) -> List[int]:
        """Up to ``draft_len`` draft tokens (possibly empty: no prior
        occurrence of any trailing n-gram).  Longest n wins — a longer
        matched context is a stronger continuation signal."""
        end = len(self._tokens)
        for n in range(self.ngram_max, self.ngram_min - 1, -1):
            if end < n:
                continue
            gram = tuple(self._tokens[end - n:end])
            pos = self._latest.get(gram)
            if pos == end:  # the trailing gram itself — use the prior one
                pos = self._prior.get(gram)
            if pos is None or pos >= end:
                continue
            return self._tokens[pos:pos + self.draft_len]
        return []


def accepted_tokens(drafted: Sequence[int], sampled: Sequence[int],
                    ) -> List[int]:
    """The emissions one verify step yields for one slot.

    ``drafted``: the verify step's input row ``[current, d1 .. dk]``;
    ``sampled``: its output row (the sampling head's token at each
    verified position).  Emission ``j`` is ``sampled[j]``; it is
    consumed only while every draft before it matched — draft
    ``drafted[j]`` survives iff it equals ``sampled[j - 1]`` — so the
    first mismatch contributes its own (correct) head token and stops.
    Always emits at least one token; at most ``len(drafted)``.
    """
    emit = [int(sampled[0])]
    for j in range(1, len(drafted)):
        if int(drafted[j]) != int(sampled[j - 1]):
            break
        emit.append(int(sampled[j]))
    return emit
