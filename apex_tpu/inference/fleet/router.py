"""Health-gated routing with prefix affinity and graceful brownout.

Placement, in decision order:

1. **Health gate** — only ``serving`` replicas are candidates (warm
   replicas are still paying compiles, draining ones refuse submits,
   dead ones are corpses).  No candidate at all is an
   :class:`Overloaded` with ``reason="no_serving_replica"``.
2. **Brownout ladder** — overload degrades EXPLICITLY instead of
   letting queues collapse into an SLO breach for everyone:
   fleet-wide queued depth ``>= reject_queue_depth`` rejects every
   admission (``reason="overloaded"``); depth
   ``>= be_shed_queue_depth`` sheds only best-effort admissions
   (``reason="brownout_shed"``) so the interactive lane keeps its
   TTFT.  Both carry ``retry_after_s`` — a typed backpressure signal,
   not a timeout.  Replays and hedges bypass the ladder
   (``bypass_admission=True``): the fleet already owes those tokens.
3. **Prefix affinity** — the prompt is matched against each
   candidate's prefix trie (read-only); if the best match reaches
   ``affinity_min_tokens``, the best-matching replicas are preferred
   (shared pages turn the re-prefill into a near-no-op — this is also
   what makes replay-after-death cheap on a replica that served the
   original prompt's twin).
4. **Lane-aware least-loaded** — among the remaining candidates, pick
   the lowest ``(own-lane queue depth, total queue depth, residents,
   anomaly alerts, replica id)``; the id tail makes ties
   deterministic.
"""

import dataclasses
from typing import FrozenSet, List, Optional, Sequence

from apex_tpu.inference.fleet.replica import LocalReplica
from apex_tpu.inference.scheduler import Request

__all__ = ["Overloaded", "Router", "RouterConfig"]


class Overloaded(RuntimeError):
    """Typed admission rejection: the caller should retry after
    ``retry_after_s`` (or downgrade its ask).  ``reason`` is one of
    ``brownout_shed`` (best-effort shed while interactive still
    admits), ``overloaded`` (every lane rejected), or
    ``no_serving_replica`` (the fleet has no healthy capacity)."""

    def __init__(self, reason: str, lane: str, retry_after_s: float):
        self.reason = str(reason)
        self.lane = str(lane)
        self.retry_after_s = float(retry_after_s)
        super().__init__(
            f"admission rejected ({reason}, lane={lane}): retry after "
            f"{retry_after_s:g}s")


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """The routing/brownout knobs (see docs/inference.md for the
    table).  ``hedge_after_s`` is the interactive-lane straggler
    deadline: a request with NO token past it gets its one hedged
    retry on another replica."""

    affinity_min_tokens: int = 16
    be_shed_queue_depth: int = 8
    reject_queue_depth: int = 16
    retry_after_s: float = 0.5
    hedge_after_s: float = 5.0


class Router:
    """Stateless placement over a replica list — all state it reads
    lives in the replicas (queues, tries, anomaly counts), so the
    frontend can call it per admission without bookkeeping."""

    def __init__(self, config: Optional[RouterConfig] = None):
        self.config = config or RouterConfig()

    @staticmethod
    def _serving(replicas: Sequence[LocalReplica],
                 exclude: FrozenSet[str]) -> List[LocalReplica]:
        return [r for r in replicas
                if r.state == "serving" and r.replica_id not in exclude]

    @staticmethod
    def fleet_queue_depth(replicas: Sequence[LocalReplica]) -> int:
        """Total queued (not yet admitted) requests across the live
        fleet — the brownout ladder's pressure signal."""
        return sum(r.queue_depth() for r in replicas
                   if r.state in ("serving", "warm", "draining"))

    def pick(self, request: Request,
             replicas: Sequence[LocalReplica], *,
             bypass_admission: bool = False,
             exclude: FrozenSet[str] = frozenset()) -> LocalReplica:
        """Choose the replica for ``request`` (raises
        :class:`Overloaded`; never returns a non-serving replica).
        ``bypass_admission`` skips the brownout ladder — replays and
        hedges are already-accepted work.  ``exclude`` bars replicas
        (a hedge must not land on the straggling primary)."""
        cfg = self.config
        serving = self._serving(replicas, exclude)
        if not serving:
            raise Overloaded("no_serving_replica", request.lane,
                             cfg.retry_after_s)
        if not bypass_admission:
            depth = self.fleet_queue_depth(replicas)
            if depth >= cfg.reject_queue_depth:
                raise Overloaded("overloaded", request.lane,
                                 cfg.retry_after_s)
            if depth >= cfg.be_shed_queue_depth \
                    and request.lane == "best_effort":
                raise Overloaded("brownout_shed", request.lane,
                                 cfg.retry_after_s)
        affinities = {r.replica_id: r.prefix_affinity(request.prompt)
                      for r in serving}
        best = max(affinities.values())
        cands = (serving if best < cfg.affinity_min_tokens
                 else [r for r in serving
                       if affinities[r.replica_id] == best])
        return min(cands, key=lambda r: self._load_key(r, request.lane))

    @staticmethod
    def _load_key(r: LocalReplica, lane: str):
        load = r.load()
        return (r.queue_depth(lane), r.queue_depth(), load["active"],
                load["alerts"], r.replica_id)
