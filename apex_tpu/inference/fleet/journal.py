"""Request journal — the frontend's replay source of last resort.

Every request the fleet accepts is journaled BEFORE it reaches a
replica: the original request (prompt, budget, lane, trace id), which
replica owns it, and — updated as the frontend polls replica progress —
every token already streamed to the caller.  On a replica wedge the
richer ``serve.step_wedged`` manifest drives replay; on a hard kill
(SIGKILL, OOM — no manifest, no goodbye) this journal is the ONLY
record of what the caller was owed, and the splice invariant below is
what makes the replayed stream gapless and duplicate-free.

The splice invariant
--------------------
A request may be served by several LEGS (original admission, replays,
a hedge) across several replicas.  Per entry:

- ``emitted`` is the tokens already streamed to the caller, in order —
  the single source of truth for "what the caller has seen".
- ``leg_prefix`` is the frozen copy of ``emitted`` taken when the
  CURRENT leg was submitted; the leg's continuation prompt is
  ``request.prompt + leg_prefix``, so every token the leg produces is
  a position ``>= len(leg_prefix)`` of the caller's stream.
- :meth:`JournalEntry.splice` maps a leg-relative token list back to
  stream positions (``leg_prefix + leg_tokens``) and appends only the
  tokens past ``len(emitted)`` — re-polling, a replay that regenerates
  a few already-seen tokens, or a hedge racing the primary can never
  emit a duplicate, and a leg that is AHEAD of the journal (the wedge
  manifest captures tokens the frontend never polled) streams exactly
  the missing tail.

With greedy decoding a continuation leg's tokens are bitwise the
tokens the dead leg would have produced (argmax is independent of
batch composition), so the spliced stream is token-identical to an
unkilled run — the acceptance bar of the fleet chaos tests.
"""

import dataclasses
from typing import Dict, List, Optional, Sequence

from apex_tpu.inference.scheduler import Request

__all__ = ["FleetCompletion", "JournalEntry", "RequestJournal"]


@dataclasses.dataclass
class FleetCompletion:
    """A finished request as the CALLER saw it: the original prompt,
    the full spliced token stream (across every leg), and the fleet's
    cost columns — how many replay legs (``replays``) and whether a
    hedge copy ran (``hedged``).  ``replica_id`` is the replica that
    emitted the final token."""

    rid: int
    prompt: List[int]
    tokens: List[int]
    submit_time: float
    finish_time: float
    token_times: List[float]
    lane: str = "interactive"
    replica_id: str = ""
    replays: int = 0
    hedged: bool = False
    trace_id: Optional[str] = None


@dataclasses.dataclass
class JournalEntry:
    """One accepted request's replay state (see the module docstring
    for the ``emitted`` / ``leg_prefix`` splice invariant)."""

    request: Request               # the ORIGINAL request, prompt copied
    submit_time: float
    owner: str                     # replica currently serving it
    leg_prefix: List[int]          # emitted snapshot at current leg start
    emitted: List[int]             # tokens streamed to the caller
    token_times: List[float]
    replays: int = 0
    hedge_owner: Optional[str] = None  # live hedge copy's replica
    hedged: bool = False               # a hedge ever ran
    done: bool = False

    def splice(self, leg_tokens: Sequence[int],
               leg_times: Optional[Sequence[float]] = None,
               now: float = 0.0) -> List[int]:
        """Merge a leg-relative token list into the caller's stream:
        append (and return) only the tokens past what was already
        emitted.  ``leg_times`` aligns per-token times when the leg
        reports them (a drained ``Completion``); manifest/poll sources
        stamp ``now``."""
        total = list(self.leg_prefix) + list(leg_tokens)
        new = total[len(self.emitted):]
        if not new:
            return []
        start = len(self.emitted) - len(self.leg_prefix)
        for j, tok in enumerate(new):
            self.emitted.append(int(tok))
            self.token_times.append(
                float(leg_times[start + j]) if leg_times is not None
                else float(now))
        return new

    def finished(self) -> bool:
        """The caller's stream is complete: budget exhausted or the
        last emitted token is the eos — checked at every splice so a
        request that FINISHED in the very step its replica died is
        finalized from the journal instead of replayed past its end."""
        req = self.request
        if len(self.emitted) >= req.max_new_tokens:
            return True
        return (req.eos_id is not None and bool(self.emitted)
                and self.emitted[-1] == req.eos_id)

    def remaining(self) -> int:
        return self.request.max_new_tokens - len(self.emitted)


class RequestJournal:
    """rid -> :class:`JournalEntry`, insertion-ordered.  Entries stay
    after completion (``done=True``) so a late duplicate — a suppressed
    hedge loser's eviction, a replayed completion landing after the
    journal already finalized — is recognized and dropped."""

    def __init__(self):
        self._entries: Dict[int, JournalEntry] = {}

    def __len__(self) -> int:
        return len(self._entries)

    def add(self, request: Request, owner: str,
            submit_time: float) -> JournalEntry:
        if request.rid in self._entries \
                and not self._entries[request.rid].done:
            raise ValueError(
                f"rid {request.rid} is already journaled and unfinished")
        req = dataclasses.replace(request, prompt=list(request.prompt))
        entry = JournalEntry(
            request=req, submit_time=submit_time, owner=owner,
            leg_prefix=[], emitted=[], token_times=[])
        self._entries[request.rid] = entry
        return entry

    def get(self, rid: int) -> Optional[JournalEntry]:
        return self._entries.get(rid)

    def unfinished(self) -> List[JournalEntry]:
        return [e for e in self._entries.values() if not e.done]

    def owned_by(self, replica_id: str) -> List[JournalEntry]:
        """Unfinished entries whose primary OR hedge leg runs on
        ``replica_id`` — the set a replica death orphans."""
        return [e for e in self._entries.values() if not e.done
                and (e.owner == replica_id
                     or e.hedge_owner == replica_id)]
