"""The fleet frontend: replica failure and overload, made invisible.

One :class:`FleetFrontend` owns N replicas (:mod:`.replica`), a router
(:mod:`.router`), and a request journal (:mod:`.journal`).  Callers
``submit`` requests and read ``completed``; everything between — which
replica serves, a replica dying mid-stream, a straggler getting
hedged, a planned drain — is this module's problem:

- **submit** routes through the health gate + brownout ladder, assigns
  the trace id (ONE id for the request's whole life, every leg on
  every replica stamps it), journals, and hands the request to the
  chosen replica.
- **step** advances every live replica and, per replica: polls its
  ``drain_manifest()`` to splice newly-generated tokens into the
  journal (the caller-visible stream), drains its ``completed`` list,
  and converts its death into replays.  A wedge
  (:class:`~.replica.ReplicaWedged`, exit-75 shape) replays from the
  ``serve.step_wedged`` MANIFEST — richer than the journal, it carries
  tokens the frontend never got to poll; a kill
  (:class:`~.replica.ReplicaKilled`, exit-137 shape) replays from the
  JOURNAL — the manifest died with the process.  Either way the
  continuation request is ``prompt + emitted`` with the remaining
  budget, routed to a healthy replica with admission bypassed, and the
  journal's splice invariant guarantees the caller's stream is gapless
  and duplicate-free — with greedy decoding, bitwise the unkilled
  stream.
- **hedging**: an interactive request with NO token past
  ``hedge_after_s`` gets its ONE hedged copy on another serving
  replica; the first leg to produce a token wins, the loser is
  cancelled if still queued or its output suppressed if resident
  (greedy decode makes either copy's tokens identical, so the race is
  benign by construction).
- **uniformity**: the fleet decision surface (router config, replica
  roster, per-replica scheduler-config digests) registers under
  ``serve.fleet_config`` in the PR 16 seam — ``check_uniform()``
  catches a fleet whose processes disagree about the fleet.
"""

import dataclasses
import logging
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

from apex_tpu.inference.fleet.journal import (
    FleetCompletion, JournalEntry, RequestJournal,
)
from apex_tpu.inference.fleet.replica import (
    LocalReplica, ReplicaKilled, ReplicaWedged,
)
from apex_tpu.inference.fleet.router import Overloaded, Router, RouterConfig
from apex_tpu.inference.scheduler import ManifestEntry, Request
from apex_tpu.observability import metrics as _metrics
from apex_tpu.observability import tracing as _tracing
from apex_tpu.resilience.uniformity import register_uniform
from apex_tpu.utils.logging import get_logger, log_structured

__all__ = ["FleetFrontend"]

_logger = get_logger("apex_tpu.inference")


class FleetFrontend:
    """Multi-replica serving frontend (see the module docstring).

    ``auto_restart`` (default True) relaunches dead replicas and
    retires-then-relaunches drained ones inside :meth:`step` — the
    in-process supervisor role; pass False to drive restarts by hand
    (the drain-then-restart test does)."""

    def __init__(self, replicas: Sequence[LocalReplica], *,
                 router: Optional[Router] = None,
                 config: Optional[RouterConfig] = None,
                 time_fn=time.monotonic, auto_restart: bool = True):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        ids = [r.replica_id for r in replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {ids}")
        self.replicas: Dict[str, LocalReplica] = {
            r.replica_id: r for r in replicas}
        self.router = router or Router(config)
        self.journal = RequestJournal()
        self.completed: List[FleetCompletion] = []
        self._time = time_fn
        self.auto_restart = bool(auto_restart)
        #: (replica_id, rid) legs whose output must be dropped — the
        #: resident hedge losers a scheduler cannot cancel mid-flight
        self._suppressed: Set[Tuple[str, int]] = set()
        self.stats: Dict[str, int] = {
            "accepted": 0, "rejected": 0, "replays": 0, "hedges": 0,
            "restarts": 0, "replica_deaths": 0,
        }
        register_uniform("serve.fleet_config", self._uniform_view)

    def _uniform_view(self) -> dict:
        """The fleet decision surface for ``check_uniform``: in a
        multi-process fleet every frontend must agree on the roster,
        the routing knobs, and each replica's scheduler config — a
        divergent replica serves from a DIFFERENT compiled program and
        replay-splicing onto it breaks the bitwise contract."""
        return {
            "router": dataclasses.asdict(self.router.config),
            "replicas": sorted(self.replicas),
            "config_digests": {
                rid: r.config_digest
                for rid, r in sorted(self.replicas.items())},
        }

    # ---------------------------------------------------------- launch
    def start(self) -> "FleetFrontend":
        """Start every replica and take each one's first (empty) step
        so the fleet opens at ``serving`` — without this, the first
        caller would be rejected by the health gate for no reason a
        caller can act on."""
        for r in self.replicas.values():
            if r.state == "dead" and r.sched is None and r.restarts == 0:
                r.start()
            r.step()
        return self

    # ---------------------------------------------------------- submit
    def submit(self, request: Request, *,
               replica_id: Optional[str] = None) -> str:
        """Accept (journal + place) one request; returns the chosen
        replica id.  Raises :class:`~.router.Overloaded` when the
        brownout ladder rejects — typed backpressure the caller can
        honor.  ``replica_id`` pins placement (tests, affinity
        experiments) past the router but not past the journal."""
        if replica_id is not None:
            target = self.replicas[replica_id]
        else:
            try:
                target = self.router.pick(
                    request, list(self.replicas.values()))
            except Overloaded as exc:
                self.stats["rejected"] += 1
                _metrics.inc("apex_fleet_rejections_total",
                             help="admissions rejected, by brownout "
                                  "reason and lane",
                             reason=exc.reason, lane=exc.lane)
                log_structured(_logger, logging.WARNING,
                               "fleet.rejected", rid=request.rid,
                               lane=request.lane, reason=exc.reason,
                               retry_after_s=exc.retry_after_s)
                raise
        if request.trace_id is None:
            # assigned HERE, not in the scheduler: the id must span
            # every leg on every replica
            request.trace_id = _tracing.new_trace_id()
        entry = self.journal.add(request, target.replica_id,
                                 self._time())
        self.stats["accepted"] += 1
        _metrics.inc("apex_fleet_accepted_total",
                     help="requests accepted into the fleet",
                     lane=request.lane)
        target.submit(dataclasses.replace(
            request, prompt=list(request.prompt)))
        return entry.owner

    # ------------------------------------------------------------ step
    def step(self) -> bool:
        """Advance the fleet by one scheduler step per live replica,
        absorbing deaths into replays (see the module docstring).
        Returns True when any replica did work."""
        worked = False
        for r in list(self.replicas.values()):
            if r.state == "dead":
                if self.auto_restart:
                    self._restart(r)
                continue
            try:
                worked = r.step() or worked
            except ReplicaWedged as exc:
                self._on_replica_dead(r, exc.manifest, "wedge")
                continue
            except ReplicaKilled:
                self._on_replica_dead(r, None, "kill")
                continue
            self._poll(r)
            self._drain_completions(r)
            if r.drained():
                r.retire()
                if self.auto_restart:
                    self._restart(r)
        self._maybe_hedge()
        return worked

    def _restart(self, r: LocalReplica) -> None:
        r.restart()
        r.step()  # pay the warm->serving promotion step
        self.stats["restarts"] += 1

    def run_until_drained(self, max_steps: int = 10_000
                          ) -> List[FleetCompletion]:
        """Drive :meth:`step` until every journaled request finished
        (the test/bench convenience loop)."""
        for _ in range(max_steps):
            if not self.journal.unfinished():
                return self.completed
            self.step()
        pending = [e.request.rid for e in self.journal.unfinished()]
        raise RuntimeError(
            f"fleet not drained after {max_steps} steps "
            f"(pending rids: {pending})")

    # ------------------------------------------------------- progress
    def _leg_of(self, r: LocalReplica,
                entry: JournalEntry) -> bool:
        """Does ``r`` currently run a leg of ``entry``?"""
        return entry.owner == r.replica_id \
            or entry.hedge_owner == r.replica_id

    def _poll(self, r: LocalReplica) -> None:
        """Splice the replica's in-progress tokens into the journal —
        the 'tokens emitted so far' the ISSUE's replay contract needs,
        refreshed every step so a kill loses at most one step's worth
        (regenerated bitwise by the continuation leg)."""
        now = self._time()
        for m in r.sched.drain_manifest():
            entry = self.journal.get(m.rid)
            if entry is None or entry.done \
                    or (r.replica_id, m.rid) in self._suppressed \
                    or not self._leg_of(r, entry):
                continue
            new = entry.splice(m.emitted, now=now)
            if new:
                self._leg_won(entry, r.replica_id)
                if entry.finished():
                    self._finalize(entry)

    def _drain_completions(self, r: LocalReplica) -> None:
        comps, r.sched.completed = r.sched.completed, []
        for c in comps:
            if (r.replica_id, c.rid) in self._suppressed:
                self._suppressed.discard((r.replica_id, c.rid))
                continue
            entry = self.journal.get(c.rid)
            if entry is None or entry.done or not self._leg_of(r, entry):
                continue
            entry.splice(c.tokens, leg_times=c.token_times)
            self._leg_won(entry, r.replica_id)
            self._finalize(entry)

    def _leg_won(self, entry: JournalEntry, replica_id: str) -> None:
        """First token decides a pending hedge race: ``replica_id``
        becomes the owner, the loser's copy is cancelled if still
        queued or suppressed if resident."""
        if entry.hedge_owner is None:
            return
        loser_id = (entry.hedge_owner if replica_id == entry.owner
                    else entry.owner)
        entry.owner = replica_id
        entry.hedge_owner = None
        loser = self.replicas.get(loser_id)
        if loser is not None and loser.sched is not None:
            if loser.sched.cancel(entry.request.rid) is None:
                self._suppressed.add((loser_id, entry.request.rid))
        log_structured(_logger, logging.INFO, "fleet.hedge_resolved",
                       rid=entry.request.rid, winner=replica_id,
                       loser=loser_id)

    def _finalize(self, entry: JournalEntry) -> None:
        entry.done = True
        finish = (entry.token_times[-1] if entry.token_times
                  else self._time())
        self.completed.append(FleetCompletion(
            rid=entry.request.rid, prompt=list(entry.request.prompt),
            tokens=list(entry.emitted),
            submit_time=entry.submit_time, finish_time=finish,
            token_times=list(entry.token_times),
            lane=entry.request.lane, replica_id=entry.owner,
            replays=entry.replays, hedged=entry.hedged,
            trace_id=entry.request.trace_id))
        _metrics.inc("apex_fleet_completions_total",
                     help="requests completed by the fleet",
                     lane=entry.request.lane)

    # --------------------------------------------------------- failure
    def _on_replica_dead(self, r: LocalReplica,
                         manifest: Optional[List[ManifestEntry]],
                         cause: str) -> None:
        """Turn a replica death into replays: splice what the manifest
        preserved (wedge) or what the journal last polled (kill), then
        resubmit every unfinished tail to a healthy replica."""
        self.stats["replica_deaths"] += 1
        self._suppressed = {(rep, rid) for rep, rid in self._suppressed
                            if rep != r.replica_id}
        by_rid = {m.rid: m for m in (manifest or [])}
        for entry in self.journal.owned_by(r.replica_id):
            if entry.hedge_owner == r.replica_id:
                # the hedge copy died with the replica; the primary
                # leg is untouched — just re-arm nothing (one hedge
                # per request is the bound)
                entry.hedge_owner = None
                continue
            m = by_rid.get(entry.request.rid)
            if m is not None:
                entry.splice(m.emitted, now=self._time())
            if entry.finished():
                # died in the same step the stream completed — the
                # journal/manifest already holds every owed token
                self._finalize(entry)
                continue
            hedge = self.replicas.get(entry.hedge_owner or "")
            if hedge is not None and hedge.state != "dead":
                # a live hedge leg IS the replay — promote it
                entry.owner, entry.hedge_owner = entry.hedge_owner, None
                continue
            entry.hedge_owner = None
            self._replay(entry, from_replica=r.replica_id, cause=cause)
        if self.auto_restart:
            self._restart(r)

    def _replay(self, entry: JournalEntry, *, from_replica: str,
                cause: str) -> None:
        """Resubmit the unfinished tail: continuation prompt is
        ``original prompt + emitted`` (prefix sharing makes the
        re-prefill cheap on a replica that served the twin), budget is
        what remains, trace id is THE SAME — the spans join."""
        req = entry.request
        t0 = self._time()
        cont = Request(
            rid=req.rid, prompt=list(req.prompt) + list(entry.emitted),
            max_new_tokens=entry.remaining(), eos_id=req.eos_id,
            lane=req.lane, trace_id=req.trace_id)
        target = self.router.pick(cont, list(self.replicas.values()),
                                  bypass_admission=True,
                                  exclude=frozenset({from_replica}))
        entry.owner = target.replica_id
        entry.leg_prefix = list(entry.emitted)
        entry.replays += 1
        self.stats["replays"] += 1
        target.submit(cont)
        # detection -> resubmission gap, measured from the last token
        # the caller saw (the stream's visible stall)
        stalled_since = (entry.token_times[-1] if entry.token_times
                         else entry.submit_time)
        _metrics.inc("apex_fleet_replays_total",
                     help="unfinished requests resubmitted after a "
                          "replica death, by cause", cause=cause)
        _metrics.observe("apex_fleet_replay_latency_seconds",
                         self._time() - stalled_since,
                         help="last streamed token -> continuation "
                              "resubmitted",
                         exemplar={"trace_id": req.trace_id,
                                   "rid": req.rid})
        tracer = _tracing.get_tracer()
        if tracer is not None:
            tracer.emit("fleet.replay", time.time(),
                        self._time() - t0, rid=req.rid,
                        trace_id=req.trace_id, cause=cause,
                        from_replica=from_replica,
                        to_replica=target.replica_id,
                        spliced_tokens=len(entry.emitted),
                        remaining=entry.remaining())
        log_structured(_logger, logging.WARNING, "fleet.replayed",
                       rid=req.rid, cause=cause,
                       from_replica=from_replica,
                       to_replica=target.replica_id,
                       spliced_tokens=len(entry.emitted),
                       remaining=entry.remaining())

    # --------------------------------------------------------- hedging
    def _maybe_hedge(self) -> None:
        """One bounded hedged retry for interactive stragglers: a
        request with NO token ``hedge_after_s`` past submit gets a
        copy on another serving replica.  Never more than one hedge
        per request (``hedged`` latches), never for requests already
        streaming (splicing two divergent mid-streams is not a thing
        the journal should ever have to referee — pre-first-token the
        copies are interchangeable)."""
        cfg = self.router.config
        if cfg.hedge_after_s <= 0:
            return
        now = self._time()
        for entry in self.journal.unfinished():
            if (entry.hedged or entry.emitted
                    or entry.request.lane != "interactive"
                    or now - entry.submit_time < cfg.hedge_after_s):
                continue
            req = entry.request
            copy = Request(rid=req.rid, prompt=list(req.prompt),
                           max_new_tokens=req.max_new_tokens,
                           eos_id=req.eos_id, lane=req.lane,
                           trace_id=req.trace_id)
            try:
                target = self.router.pick(
                    copy, list(self.replicas.values()),
                    bypass_admission=True,
                    exclude=frozenset({entry.owner}))
            except Overloaded:
                continue  # nowhere to hedge — keep waiting
            entry.hedged = True
            entry.hedge_owner = target.replica_id
            self.stats["hedges"] += 1
            target.submit(copy)
            _metrics.inc("apex_fleet_hedges_total",
                         help="interactive stragglers hedged to a "
                              "second replica")
            log_structured(_logger, logging.INFO, "fleet.hedged",
                           rid=req.rid, primary=entry.owner,
                           hedge=target.replica_id,
                           waited_s=round(now - entry.submit_time, 6))

    # -------------------------------------------------------- draining
    def drain_replica(self, replica_id: str) -> int:
        """Planned restart, zero drops: stop the replica admitting,
        re-route its QUEUED requests (admission bypassed — they were
        already accepted), and leave residents finishing in place.
        Returns the number of requests re-routed."""
        r = self.replicas[replica_id]
        manifest = r.begin_drain()
        moved = 0
        for m in manifest:
            entry = self.journal.get(m.rid)
            if entry is None or entry.done:
                continue
            if entry.hedge_owner == replica_id:
                entry.hedge_owner = None  # drop the queued hedge copy
                continue
            entry.splice(m.emitted, now=self._time())
            if entry.finished():
                self._finalize(entry)
                continue
            self._replay(entry, from_replica=replica_id, cause="drain")
            moved += 1
        log_structured(_logger, logging.INFO, "fleet.drain_started",
                       replica=replica_id, rerouted=moved,
                       residents=0 if r.sched is None
                       else r.sched.num_active)
        return moved
