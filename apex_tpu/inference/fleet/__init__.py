"""apex_tpu.inference.fleet — fault-tolerant multi-replica serving.

The frontend half of the serving resilience story: PR 11 gave replicas
a watchdog that emits the ``serve.step_wedged`` requeue manifest and a
supervisor that restarts them, and this package is the layer that
actually CONSUMES those signals, so a replica death is an absorbed
event instead of N dropped streams:

- :mod:`~apex_tpu.inference.fleet.replica` — replica lifecycle
  (starting → warm → serving → draining → dead) with heartbeats and
  per-replica state gauges; :class:`LocalReplica` is the in-process
  incarnation the tests and bench drive.
- :mod:`~apex_tpu.inference.fleet.journal` — the request journal and
  the splice invariant that makes multi-leg streams gapless and
  duplicate-free (bitwise the unkilled stream under greedy decoding).
- :mod:`~apex_tpu.inference.fleet.router` — health-gated placement:
  prefix-affinity first, lane-aware least-loaded fallback, and the
  graceful-brownout ladder (shed best-effort, then typed
  :class:`Overloaded` rejections with retry-after).
- :mod:`~apex_tpu.inference.fleet.frontend` — the
  :class:`FleetFrontend` tying it together: replay-on-failure (wedge →
  manifest, kill → journal), one bounded hedged retry for interactive
  stragglers, drain-then-restart with zero drops, and the
  ``serve.fleet_config`` uniformity registration.

See docs/inference.md ("Serving fleet") for health-state semantics,
the replay contract, and the knob table; ``tests/test_fleet.py`` holds
the chaos matrix (kill-137 / wedge-75 / brownout / drain-restart).
"""

from apex_tpu.inference.fleet.frontend import FleetFrontend
from apex_tpu.inference.fleet.journal import (
    FleetCompletion, JournalEntry, RequestJournal,
)
from apex_tpu.inference.fleet.replica import (
    LocalReplica, REPLICA_STATES, ReplicaKilled, ReplicaWedged,
)
from apex_tpu.inference.fleet.router import Overloaded, Router, RouterConfig

__all__ = [
    "FleetCompletion", "FleetFrontend", "JournalEntry", "LocalReplica",
    "Overloaded", "REPLICA_STATES", "ReplicaKilled", "ReplicaWedged",
    "RequestJournal", "Router", "RouterConfig",
]
