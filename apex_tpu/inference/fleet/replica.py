"""One serving replica: a scheduler behind a health state machine.

State machine (``REPLICA_STATES``, in lifecycle order)::

    starting -> warm -> serving -> draining -> dead -> (restart) -> warm

- **starting**: the build function is running (pools allocated, steps
  traced).  Not routable.
- **warm**: built, no step taken yet — the first step pays the jit
  compiles.  Not routable: admitting traffic here would eat the
  compile wait inside a caller's TTFT.
- **serving**: at least one step completed; the router admits.
- **draining**: planned restart — :meth:`LocalReplica.begin_drain`
  stops admission (the scheduler refuses ``submit``), hands back the
  queued requests for re-routing, and residents finish through the
  ordinary step/evict path; :meth:`LocalReplica.drained` flags when
  the process can be recycled with nothing dropped.
- **dead**: killed (exit-137 shape), wedged (exit-75 shape), or
  drained-and-retired.  :meth:`LocalReplica.restart` rebuilds — the
  supervised-child analogue — and the step counter does NOT reset, so
  a chaos plan keyed on replica steps fires once, not once per life.

:class:`LocalReplica` is the in-process incarnation (one scheduler per
replica object, same process) that the fleet tests and the bench drive
— the same frontend logic applies unchanged when each replica is a
supervised ``serve_gpt.py --replica-id`` child, because every
interaction goes through the scheduler's public seams (``submit`` /
``step`` / ``drain_manifest`` / ``completed``) plus the two fault
signals a process boundary also delivers (died-hard, wedged-with-
manifest).  Chaos faults are checked at the top of :meth:`step`, where
a real kill/wedge would land (mid-step-dispatch), and are re-raised as
:class:`ReplicaKilled` / :class:`ReplicaWedged` for the frontend —
which plays the supervisor here, the one place deliberately allowed to
absorb a replica's ``SystemExit``.
"""

import dataclasses
import logging
import time
from typing import Callable, List, Optional

from apex_tpu.inference.scheduler import (
    ContinuousBatchingScheduler, ManifestEntry, Request,
)
from apex_tpu.observability import metrics as _metrics
from apex_tpu.resilience.chaos import ChaosReplicaKilled, active_monkey
from apex_tpu.resilience.elastic import EXIT_KILLED, EXIT_WEDGED
from apex_tpu.resilience.uniformity import uniform_digest
from apex_tpu.utils.logging import get_logger, log_structured

__all__ = ["LocalReplica", "REPLICA_STATES", "ReplicaKilled",
           "ReplicaWedged"]

_logger = get_logger("apex_tpu.inference")

#: lifecycle order; the gauge ``apex_fleet_replica_state{replica=}``
#: reports the index into this tuple
REPLICA_STATES = ("starting", "warm", "serving", "draining", "dead")


class ReplicaKilled(RuntimeError):
    """A replica died HARD mid-step (SIGKILL shape, exit 137): no
    drain, no manifest — the frontend's journal is the only replay
    source."""

    def __init__(self, replica_id: str, step: int):
        self.replica_id = str(replica_id)
        self.step = int(step)
        self.exit_code = EXIT_KILLED
        super().__init__(
            f"replica {replica_id!r} killed at replica step {step} "
            f"(exit {EXIT_KILLED})")


class ReplicaWedged(RuntimeError):
    """A replica's decode step wedged (watchdog shape, exit 75): the
    ``serve.step_wedged`` record fired and ``manifest`` carries the
    scheduler's structured requeue manifest — the richer replay source
    (it includes tokens the frontend never got to poll)."""

    def __init__(self, replica_id: str, step: int,
                 manifest: List[ManifestEntry]):
        self.replica_id = str(replica_id)
        self.step = int(step)
        self.manifest = list(manifest)
        self.exit_code = EXIT_WEDGED
        super().__init__(
            f"replica {replica_id!r} wedged at replica step {step} "
            f"(exit {EXIT_WEDGED}; manifest carries "
            f"{len(manifest)} unfinished request(s))")


class LocalReplica:
    """One in-process serving replica: ``build_fn()`` constructs its
    scheduler (so each replica owns its pools/allocator/trie), the
    state machine above gates routability, and every step beats
    ``last_beat`` — the heartbeat a health check reads."""

    def __init__(self, replica_id: str,
                 build_fn: Callable[[], ContinuousBatchingScheduler],
                 *, time_fn=time.monotonic):
        self.replica_id = str(replica_id)
        self._build = build_fn
        self._time = time_fn
        self.sched: Optional[ContinuousBatchingScheduler] = None
        self.state = "dead"            # not started yet
        self.config_digest: Optional[str] = None
        self.last_beat: Optional[float] = None
        self.restarts = 0
        #: monotonic across restarts (supervisor-attempt semantics) —
        #: a chaos plan keyed on replica steps cannot re-fire after
        #: the restart it caused
        self.steps_total = 0

    # ------------------------------------------------------- lifecycle
    def _set_state(self, state: str) -> None:
        assert state in REPLICA_STATES
        self.state = state
        _metrics.set_gauge(
            "apex_fleet_replica_state",
            float(REPLICA_STATES.index(state)),
            help="replica lifecycle state (index into "
                 "starting/warm/serving/draining/dead)",
            replica=self.replica_id)
        log_structured(_logger, logging.INFO, "fleet.replica_state",
                       replica=self.replica_id, state=state,
                       step=self.steps_total)

    def start(self) -> "LocalReplica":
        """Build the scheduler: ``starting`` while the build runs,
        ``warm`` after — the first :meth:`step` promotes to
        ``serving``."""
        self._set_state("starting")
        self.sched = self._build()
        self.config_digest = uniform_digest({
            "decode": dataclasses.asdict(self.sched.dcfg),
            "model": dataclasses.asdict(self.sched.config),
        })
        self._set_state("warm")
        return self

    def restart(self) -> "LocalReplica":
        """Rebuild after a death — the supervised-relaunch analogue.
        ``steps_total`` carries over (see the class docstring)."""
        if self.state != "dead":
            raise RuntimeError(
                f"replica {self.replica_id!r} is {self.state}, not dead")
        self.restarts += 1
        _metrics.inc("apex_fleet_replica_restarts_total",
                     help="replica rebuilds after a death",
                     replica=self.replica_id)
        return self.start()

    def mark_dead(self, cause: str) -> None:
        """Record the death and DISCARD the scheduler — a killed
        process keeps no state, and keeping the object would tempt the
        frontend into reading a corpse instead of its journal."""
        self.sched = None
        _metrics.inc("apex_fleet_replica_deaths_total",
                     help="replica deaths, by cause",
                     replica=self.replica_id, cause=cause)
        log_structured(_logger, logging.WARNING, "fleet.replica_dead",
                       replica=self.replica_id, cause=cause,
                       step=self.steps_total)
        self._set_state("dead")

    # --------------------------------------------------------- serving
    def submit(self, request: Request) -> None:
        if self.state not in ("serving", "warm"):
            raise RuntimeError(
                f"replica {self.replica_id!r} is {self.state} — the "
                f"router must not admit here")
        self.sched.submit(request)

    def step(self) -> bool:
        """One scheduler step, with the chaos fault checks at the top
        — where a real SIGKILL or dead tunnel would land, i.e. before
        any of this step's work becomes visible."""
        if self.state in ("dead", "starting") or self.sched is None:
            return False
        step = self.steps_total
        monkey = active_monkey()
        if monkey is not None:
            if monkey.maybe_wedge_replica(self.replica_id, step):
                # the exit-75 path: the watchdog hook fires the
                # serve.step_wedged record (manifest included), then
                # the process dies — modeled by discarding the
                # scheduler after capturing its manifest
                manifest = self.sched.drain_manifest()
                self.sched._on_wedge({"elapsed_s": None})
                self.steps_total += 1
                self.mark_dead("wedge")
                raise ReplicaWedged(self.replica_id, step, manifest)
            try:
                monkey.maybe_kill_replica(self.replica_id, step)
            except ChaosReplicaKilled as exc:
                # deliberate SystemExit absorption: this layer IS the
                # supervisor for in-process replicas (the documented
                # chaos-consumer role) — exit-137 means no manifest
                self.steps_total += 1
                self.mark_dead("kill")
                raise ReplicaKilled(self.replica_id, step) from exc
        worked = self.sched.step()
        self.steps_total += 1
        self.last_beat = self._time()
        if self.state == "warm":
            # first completed step: the jit compiles are paid — open
            # for traffic
            self._set_state("serving")
        return worked

    def kill(self) -> None:
        """Direct in-process SIGKILL analogue (tests, bench): die hard
        right now, no manifest."""
        self.mark_dead("kill")

    # -------------------------------------------------------- draining
    def begin_drain(self) -> List[ManifestEntry]:
        """Planned restart: stop admitting, return the queued requests
        (as a manifest, for the frontend to re-route), let residents
        finish.  The replica keeps stepping while ``draining``."""
        if self.state != "serving":
            raise RuntimeError(
                f"replica {self.replica_id!r} is {self.state}; only a "
                f"serving replica drains")
        manifest = self.sched.begin_drain()
        self._set_state("draining")
        return manifest

    def drained(self) -> bool:
        return (self.state == "draining" and self.sched is not None
                and self.sched.drained())

    def retire(self) -> None:
        """Complete a drain: the residents are gone, recycle the
        process (``dead``, restartable) with nothing dropped."""
        if not self.drained():
            raise RuntimeError(
                f"replica {self.replica_id!r} still holds residents "
                f"(or is not draining) — poll drained() first")
        self.mark_dead("drain")

    # ------------------------------------------------- router inputs
    def queue_depth(self, lane: Optional[str] = None) -> int:
        if self.sched is None:
            return 0
        if lane == "interactive":
            return len(self.sched.queue)
        if lane == "best_effort":
            return len(self.sched.be_queue)
        return len(self.sched.queue) + len(self.sched.be_queue)

    def load(self) -> dict:
        """The router's ranking inputs, one snapshot."""
        s = self.sched
        return {
            "active": 0 if s is None else s.num_active,
            "queued_interactive": 0 if s is None else len(s.queue),
            "queued_best_effort": 0 if s is None else len(s.be_queue),
            "alerts": 0 if s is None or s._anomaly is None
            else sum(s._anomaly.counts().values()),
        }

    def prefix_affinity(self, prompt: List[int]) -> int:
        """Tokens of ``prompt`` this replica's prefix trie already
        holds — the router's affinity signal.  ``match`` is read-only
        (no refcounts taken), so probing N replicas is free."""
        if self.sched is None or self.sched.prefix is None:
            return 0
        return self.sched.prefix.match(list(prompt)).shared_len
