"""Paged KV cache: fixed-size pages in a preallocated pool.

The serving-side memory manager (the vLLM PagedAttention layout,
recast for TPU static shapes): the KV cache for ALL resident sequences
lives in ONE preallocated pool per layer —
``(num_layers, num_pages, page_size, kv_heads, head_dim)`` for each of
k and v — and every sequence owns a *page table*: a fixed-width row of
page ids mapping its logical positions ``[p * page_size, (p+1) *
page_size)`` onto pool pages.  Sequences of wildly different lengths
pack the pool densely, admission/eviction recycles pages between
decode steps, and the decode step's SHAPES never change (the pool, the
(max_batch, pages_per_seq) page-table block, the per-slot scalars), so
it compiles exactly once.

Storage dtype is configurable (bf16 default — halves the pool bytes;
the attention kernels widen the page reads at the seam, the APX306
contract).

Page id 0 is the **garbage page**: :class:`PageAllocator` never hands
it out, and every masked write (inactive slot, padded prompt tail) is
routed there instead of being predicated out — the scatter stays a
dense static-shape op and can never corrupt a live sequence's page.
Every page-table read is clamped into the pool (the APX107 contract:
a stale or corrupt table entry reads/writes garbage, never wraps).

Device-side helpers here are pure functions on the pool arrays (jit
inside the decode/prefill steps); the allocator and page tables are
host-side bookkeeping owned by the scheduler.
"""

import dataclasses
from collections import deque
from typing import Any, Dict, List, Optional

import jax.numpy as jnp

__all__ = [
    "GARBAGE_PAGE", "KVCacheConfig", "PageAllocator", "alloc_pools",
    "copy_page", "pages_needed", "write_decode_kv", "write_prompt_kv",
]

#: page id 0 — reserved, never allocated; the destination of every
#: masked (inactive / padded) cache write
GARBAGE_PAGE = 0


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    """Static shape of the pool (all fields bake into the compiled
    steps).

    ``num_pages`` includes the reserved garbage page, so the usable
    capacity is ``num_pages - 1`` pages.  ``pages_per_seq`` is the
    page-table width: the longest supportable sequence is
    ``pages_per_seq * page_size`` positions.
    """

    num_pages: int = 128
    page_size: int = 16
    pages_per_seq: int = 16
    dtype: Any = jnp.bfloat16

    def __post_init__(self):
        if self.num_pages < 2:
            raise ValueError("num_pages must be >= 2: page 0 is the "
                             "reserved garbage page")
        if self.page_size < 1 or self.pages_per_seq < 1:
            raise ValueError("page_size and pages_per_seq must be >= 1")

    @property
    def max_len(self) -> int:
        return self.pages_per_seq * self.page_size


def pages_needed(total_positions: int, page_size: int) -> int:
    """Pages to reserve for a sequence that will cache
    ``total_positions`` tokens (admission reserves the WORST case —
    prompt + max_new_tokens — so a mid-generation allocation failure
    cannot exist and FIFO admission cannot starve)."""
    return -(-int(total_positions) // int(page_size))


def alloc_pools(num_layers: int, kv_heads: int, head_dim: int,
                cfg: KVCacheConfig) -> Dict[str, jnp.ndarray]:
    """Zero-initialized k/v pools:
    ``(L, num_pages, page_size, kv_heads, head_dim)`` each, in the
    storage dtype.  Donated through the decode/prefill jits — the pool
    is updated in place across the whole serve loop."""
    shape = (num_layers, cfg.num_pages, cfg.page_size, kv_heads, head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


class PageAllocator:
    """Host-side refcounted free list over the pool's pages (page 0
    reserved).

    FIFO recycling: freed pages go to the back of the free list, so a
    use-after-free bug surfaces as stale-but-old data (maximally
    distinguishable) rather than freshly-written lookalike values.

    Refcounts (the prefix-sharing substrate): :meth:`allocate` hands a
    page out at refcount 1, :meth:`share` takes an extra reference on a
    LIVE page (a second sequence — or the prefix trie — mapping the
    same physical page), and :meth:`free` drops one reference, only
    recycling the page when the count reaches zero.  A page with
    refcount > 1 must never be written in place — the scheduler
    copy-on-writes it (:func:`copy_page`) before the first divergent
    write.  The garbage page is outside the scheme entirely: its
    refcount is pinned 0 and it can be neither allocated, shared, nor
    freed.
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 reserved)")
        self.num_pages = int(num_pages)
        self._free = deque(range(1, self.num_pages))
        self._refs: Dict[int, int] = {}

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def live_pages(self) -> int:
        """Pages currently allocated (refcount >= 1)."""
        return len(self._refs)

    def refcount(self, page: int) -> int:
        """References held on ``page`` (0 = free; the garbage page is
        always 0 — it is never allocated)."""
        return self._refs.get(int(page), 0)

    def can_allocate(self, n: int) -> bool:
        return n <= len(self._free)

    def allocate(self, n: int) -> Optional[List[int]]:
        """``n`` pages at refcount 1 each, or None (never a partial
        grab) when the pool cannot cover the request."""
        if n > len(self._free):
            return None
        pages = [self._free.popleft() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        return pages

    def share(self, pages) -> None:
        """Take one extra reference on each (live) page — a sequence or
        the prefix trie mapping an already-resident physical page."""
        for p in pages:
            p = int(p)
            if p == GARBAGE_PAGE:
                raise ValueError("page 0 is reserved and never shared")
            if p not in self._refs:
                raise ValueError(f"share of free page {p} — only live "
                                 f"(allocated) pages can gain references")
            self._refs[p] += 1

    def free(self, pages) -> None:
        """Drop one reference per page; a page recycles to the free
        list only when its last reference is dropped."""
        for p in pages:
            p = int(p)
            if p == GARBAGE_PAGE:
                raise ValueError("page 0 is reserved and never allocated")
            if not (0 < p < self.num_pages):
                raise ValueError(f"page id {p} outside pool "
                                 f"[1, {self.num_pages})")
            if p not in self._refs:
                raise ValueError(f"double free of page {p}")
            self._refs[p] -= 1
            if self._refs[p] == 0:
                del self._refs[p]
                self._free.append(p)


# ----------------------------------------------------------- device writes
def copy_page(pools, src: int, dst: int):
    """Copy-on-write seam: duplicate pool page ``src`` into ``dst``
    across every layer of both pools.

    ``src``/``dst`` are HOST ints handed out by :class:`PageAllocator`
    (``dst`` freshly allocated, refcount 1) — the scheduler calls this
    once, before the first divergent write to a shared (refcount > 1)
    page, then repoints the writing sequence's page table at ``dst``.
    Neither side may be the reserved garbage page.
    """
    src, dst = int(src), int(dst)
    num_pages = pools["k"].shape[1]
    for p in (src, dst):
        if not (GARBAGE_PAGE < p < num_pages):
            raise ValueError(
                f"copy_page({src}, {dst}): page {p} outside the "
                f"allocatable pool (1, {num_pages})")
    if src == dst:
        raise ValueError(f"copy_page: src == dst == {src}")
    return {"k": pools["k"].at[:, dst].set(pools["k"][:, src]),
            "v": pools["v"].at[:, dst].set(pools["v"][:, src])}


def write_decode_kv(k_pool, v_pool, k_new, v_new, page_tables, positions,
                    active):
    """Scatter one decode step's k/v into a layer's pools.

    ``k_pool``/``v_pool``: (num_pages, page_size, H_kv, D);
    ``k_new``/``v_new``: (B, H_kv, D) the current tokens' heads;
    ``page_tables``: (B, P) int32; ``positions``: (B,) the tokens'
    0-based positions; ``active``: (B,) bool — the WRITE mask (a
    multi-position verify/chunk caller may pass a narrower mask than
    slot liveness, e.g. to leave shared prefix pages untouched).
    Inactive rows write the garbage page; all page-table reads are
    clamped (APX107).
    """
    num_pages, page_size = k_pool.shape[0], k_pool.shape[1]
    P = page_tables.shape[1]
    page_ix = jnp.clip(positions // page_size, 0, P - 1)
    rows = jnp.take_along_axis(page_tables, page_ix[:, None], axis=1)[:, 0]
    dest = jnp.where(active, jnp.clip(rows, 0, num_pages - 1), GARBAGE_PAGE)
    slot = jnp.where(active, positions % page_size, 0)
    k_pool = k_pool.at[dest, slot].set(k_new.astype(k_pool.dtype))
    v_pool = v_pool.at[dest, slot].set(v_new.astype(v_pool.dtype))
    return k_pool, v_pool


def write_prompt_kv(k_pool, v_pool, k_stack, v_stack, page_table_row,
                    prompt_len, start=0):
    """Scatter a prefilled prompt's k/v into ALL layers' pools at once.

    ``k_pool``/``v_pool``: (L, num_pages, page_size, H_kv, D);
    ``k_stack``/``v_stack``: (L, S, H_kv, D) the training forward's
    per-layer post-RoPE keys/values for the (padded) prompt;
    ``page_table_row``: (P,) the sequence's page table;
    ``prompt_len``: scalar int32 — positions >= it (the pad tail)
    write the garbage page.  ``start``: scalar int32 — positions < it
    ALSO write the garbage page: the prefix-sharing window (those
    positions' k/v already live in shared pool pages, which must not be
    rewritten through this sequence's table).
    """
    num_pages, page_size = k_pool.shape[1], k_pool.shape[2]
    P = page_table_row.shape[0]
    S = k_stack.shape[1]
    s = jnp.arange(S, dtype=jnp.int32)
    page_ix = jnp.clip(s // page_size, 0, P - 1)
    rows = jnp.take(page_table_row, page_ix)
    valid = (s >= start) & (s < prompt_len)
    dest = jnp.where(valid, jnp.clip(rows, 0, num_pages - 1), GARBAGE_PAGE)
    slot = jnp.where(valid, s % page_size, 0)
    k_pool = k_pool.at[:, dest, slot].set(k_stack.astype(k_pool.dtype))
    v_pool = v_pool.at[:, dest, slot].set(v_stack.astype(v_pool.dtype))
    return k_pool, v_pool
