"""Fused single-token decode step and the prefill step.

``make_decode_step`` builds ONE jitted function that advances every
resident sequence by one token: embedding lookup, all transformer
blocks (QKV projection, RoPE at each sequence's own position, paged
single-query attention, MLP — the block code shared with training via
:func:`apex_tpu.models.gpt.forward_decode`), and the fused sampling
head (logits → temperature/top-k → token in one kernel,
:mod:`apex_tpu.ops.decode_sampling_pallas` — the full-vocab fp32
softmax never reaches HBM).

Compile-once discipline: every input shape is static — the KV pools,
the (max_batch, pages_per_seq) page-table block, the per-slot scalar
arrays — and occupancy/length live in DATA (``active``, ``positions``),
so the step traces exactly once and serves every batch occupancy and
cache length from that one executable
(tests/test_lowered_invariants.py pins the trace count and that the
lowering has zero host transfers).  The pools donate: the caller
rebinds them every step, and XLA updates the cache in place instead of
holding two pool copies live.

``make_prefill`` runs an admitted sequence's prompt through the
EXISTING training forward (``gpt_forward(return_kv=True)``) at one
static padded shape, scatters the captured per-layer k/v into the
sequence's pages, and samples the first generated token from the last
prompt position's hidden state.
"""

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from apex_tpu.inference.kv_cache import KVCacheConfig, write_prompt_kv
from apex_tpu.models.gpt import GPTConfig, forward_decode, gpt_forward
from apex_tpu.ops.decode_sampling_pallas import fused_sample

__all__ = ["DecodeConfig", "make_decode_step", "make_prefill"]


@dataclasses.dataclass(frozen=True)
class DecodeConfig:
    """Static serving configuration — everything here bakes into the
    compiled steps (thread impl choices HERE, never via env vars:
    the APX101/102 contract).

    ``max_batch``: decode-slot count (the step's batch dimension).
    ``max_prompt_len``: the prefill pad length (one prefill compile).
    ``temperature``/``top_k``: the sampling head; ``temperature=0`` is
    greedy argmax and ignores ``top_k``.
    ``attn_impl``/``sample_impl``: "auto" | "pallas" | "interpret" |
    "xla" for the decode-attention and sampling kernels (chosen
    impls degrade once through ``resilience.fallback``).
    ``sample_dot_dtype``: MXU dot dtype of the sampling head (None =
    the fused-CE default, bf16; tests pass fp32 for exact parity).
    """

    cache: KVCacheConfig = dataclasses.field(default_factory=KVCacheConfig)
    max_batch: int = 8
    max_prompt_len: int = 128
    temperature: float = 1.0
    top_k: int = 0
    attn_impl: str = "auto"
    sample_impl: str = "auto"
    sample_dot_dtype: Any = None
    base_seed: int = 0

    def __post_init__(self):
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.temperature < 0.0:
            raise ValueError(
                f"temperature must be >= 0 (got {self.temperature}); "
                "0 means greedy")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0 (got {self.top_k})")


def make_decode_step(config: GPTConfig, dcfg: DecodeConfig,
                     return_logits: bool = False):
    """Build the jitted one-token-per-sequence decode step.

    Returns ``step(params, pools, tokens, positions, active,
    page_tables, seeds) -> (pools, next_tokens)`` with

    - ``pools``: the ``{"k", "v"}`` page pools (DONATED — rebind on
      every call);
    - ``tokens``/``positions``/``active``: (B,) current token ids,
      their positions, slot liveness; inactive slots are fully masked
      (their cache writes land on the garbage page, their sampled
      token is meaningless);
    - ``page_tables``: (B, P) int32; ``seeds``: (B,) uint32 per-slot
      sampling counters.

    With ``return_logits=True`` the step instead returns
    ``(pools, logits)`` — the fp32 full-vocab head exactly as the
    training forward computes it — for the prefill↔decode parity band;
    serving never materializes those logits.
    """
    def step(params, pools, tokens, positions, active, page_tables, seeds):
        hidden, pools = forward_decode(
            params, tokens, positions, active, pools, page_tables,
            config, attn_impl=dcfg.attn_impl)
        if return_logits:
            logits = jnp.matmul(hidden.astype(jnp.float32),
                                params["embed"].T.astype(jnp.float32))
            return pools, logits
        next_tokens = fused_sample(
            hidden, params["embed"], seeds,
            temperature=dcfg.temperature, top_k=dcfg.top_k,
            impl=dcfg.sample_impl, dot_dtype=dcfg.sample_dot_dtype)
        return pools, next_tokens

    return jax.jit(step, donate_argnums=(1,))


def make_prefill(config: GPTConfig, dcfg: DecodeConfig):
    """Build the jitted prompt-prefill step (one static padded shape).

    Returns ``prefill(params, pools, prompt, prompt_len,
    page_table_row, seed) -> (pools, first_token)`` where ``prompt``
    is (1, max_prompt_len) int32 (zero-padded past ``prompt_len``; the
    padded tail's k/v go to the garbage page and its causal rows are
    never read), ``page_table_row`` is the admitted sequence's (P,)
    table, and ``first_token`` is sampled from the LAST prompt
    position's hidden state with the same sampling head as decode.
    Pools donate, as in the decode step.
    """
    S = dcfg.max_prompt_len

    def prefill(params, pools, prompt, prompt_len, page_table_row, seed):
        hidden, kv = gpt_forward(params, prompt, config,
                                 return_hidden=True, return_kv=True)
        k_stack, v_stack = kv  # (L, 1, KVH, S, hd)
        ks = k_stack[:, 0].transpose(0, 2, 1, 3)  # (L, S, KVH, hd)
        vs = v_stack[:, 0].transpose(0, 2, 1, 3)
        kp, vp = write_prompt_kv(pools["k"], pools["v"], ks, vs,
                                 page_table_row, prompt_len)
        h_last = hidden[jnp.clip(prompt_len - 1, 0, S - 1), 0]  # (H,)
        first = fused_sample(
            h_last[None], params["embed"], seed[None],
            temperature=dcfg.temperature, top_k=dcfg.top_k,
            impl=dcfg.sample_impl, dot_dtype=dcfg.sample_dot_dtype)
        return {"k": kp, "v": vp}, first[0]

    return jax.jit(prefill, donate_argnums=(1,))
