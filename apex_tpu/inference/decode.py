"""Fused single-token decode step and the prefill step.

``make_decode_step`` builds ONE jitted function that advances every
resident sequence by one token: embedding lookup, all transformer
blocks (QKV projection, RoPE at each sequence's own position, paged
single-query attention, MLP — the block code shared with training via
:func:`apex_tpu.models.gpt.forward_decode`), and the fused sampling
head (logits → temperature/top-k → token in one kernel,
:mod:`apex_tpu.ops.decode_sampling_pallas` — the full-vocab fp32
softmax never reaches HBM).

Compile-once discipline: every input shape is static — the KV pools,
the (max_batch, pages_per_seq) page-table block, the per-slot scalar
arrays — and occupancy/length live in DATA (``active``, ``positions``),
so the step traces exactly once and serves every batch occupancy and
cache length from that one executable
(tests/test_lowered_invariants.py pins the trace count and that the
lowering has zero host transfers).  The pools donate: the caller
rebinds them every step, and XLA updates the cache in place instead of
holding two pool copies live.

``make_prefill`` runs an admitted sequence's prompt through the
EXISTING training forward (``gpt_forward(return_kv=True)``) at one
static padded shape, scatters the captured per-layer k/v into the
sequence's pages, and samples the first generated token from the last
prompt position's hidden state.
"""

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.inference.kv_cache import KVCacheConfig, write_prompt_kv
from apex_tpu.models.gpt import GPTConfig, forward_decode, gpt_forward
from apex_tpu.ops.decode_sampling_pallas import fused_sample

__all__ = [
    "DecodeConfig", "make_decode_step", "make_prefill",
    "make_prefill_chunk", "make_sample_head", "make_verify_step",
]


@dataclasses.dataclass(frozen=True)
class DecodeConfig:
    """Static serving configuration — everything here bakes into the
    compiled steps (thread impl choices HERE, never via env vars:
    the APX101/102 contract).

    ``max_batch``: decode-slot count (the step's batch dimension).
    ``max_prompt_len``: the prefill pad length (one prefill compile).
    ``temperature``/``top_k``: the sampling head; ``temperature=0`` is
    greedy argmax and ignores ``top_k``.
    ``attn_impl``/``sample_impl``: "auto" | "pallas" | "interpret" |
    "xla" for the decode-attention and sampling kernels (chosen
    impls degrade once through ``resilience.fallback``).
    ``sample_dot_dtype``: MXU dot dtype of the sampling head (None =
    the fused-CE default, bf16; tests pass fp32 for exact parity).

    Serving-v2 knobs (all default OFF — the PR 9 engine unchanged):
    ``draft_len`` k > 0 enables speculative decode (n-gram drafts of up
    to k tokens verified per step through the ``k + 1``-wide verify
    step); ``ngram_max``/``ngram_min`` bound the prompt-lookup n-gram
    sweep.  ``prefill_chunk`` C enables chunked prefill: prompts admit
    as C-token chunks interleaved with decode steps (ONE chunk compile
    per C, any prompt length up to the page-table capacity).
    ``prefix_sharing`` dedupes identical prompt-prefix pages through
    the refcounted trie (:mod:`apex_tpu.inference.prefix`) with
    copy-on-write on first divergence.
    """

    cache: KVCacheConfig = dataclasses.field(default_factory=KVCacheConfig)
    max_batch: int = 8
    max_prompt_len: int = 128
    temperature: float = 1.0
    top_k: int = 0
    attn_impl: str = "auto"
    sample_impl: str = "auto"
    sample_dot_dtype: Any = None
    base_seed: int = 0
    draft_len: int = 0
    ngram_max: int = 3
    ngram_min: int = 1
    prefill_chunk: Optional[int] = None
    prefix_sharing: bool = False

    def __post_init__(self):
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.temperature < 0.0:
            raise ValueError(
                f"temperature must be >= 0 (got {self.temperature}); "
                "0 means greedy")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0 (got {self.top_k})")
        if self.draft_len < 0:
            raise ValueError(f"draft_len must be >= 0 (got "
                             f"{self.draft_len}); 0 disables speculation")
        if not (1 <= self.ngram_min <= self.ngram_max):
            raise ValueError(
                f"need 1 <= ngram_min <= ngram_max, got "
                f"({self.ngram_min}, {self.ngram_max})")
        if self.prefill_chunk is not None and self.prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1 (got "
                             f"{self.prefill_chunk}); None disables it")


def make_decode_step(config: GPTConfig, dcfg: DecodeConfig,
                     return_logits: bool = False):
    """Build the jitted one-token-per-sequence decode step.

    Returns ``step(params, pools, tokens, positions, active,
    page_tables, seeds) -> (pools, next_tokens)`` with

    - ``pools``: the ``{"k", "v"}`` page pools (DONATED — rebind on
      every call);
    - ``tokens``/``positions``/``active``: (B,) current token ids,
      their positions, slot liveness; inactive slots are fully masked
      (their cache writes land on the garbage page, their sampled
      token is meaningless);
    - ``page_tables``: (B, P) int32; ``seeds``: (B,) uint32 per-slot
      sampling counters.

    With ``return_logits=True`` the step instead returns
    ``(pools, logits)`` — the fp32 full-vocab head exactly as the
    training forward computes it — for the prefill↔decode parity band;
    serving never materializes those logits.
    """
    def step(params, pools, tokens, positions, active, page_tables, seeds):
        hidden, pools = forward_decode(
            params, tokens, positions, active, pools, page_tables,
            config, attn_impl=dcfg.attn_impl)
        if return_logits:
            logits = jnp.matmul(hidden.astype(jnp.float32),
                                params["embed"].T.astype(jnp.float32))
            return pools, logits
        next_tokens = fused_sample(
            hidden, params["embed"], seeds,
            temperature=dcfg.temperature, top_k=dcfg.top_k,
            impl=dcfg.sample_impl, dot_dtype=dcfg.sample_dot_dtype)
        return pools, next_tokens

    return jax.jit(step, donate_argnums=(1,))


def make_verify_step(config: GPTConfig, dcfg: DecodeConfig):
    """Build the jitted speculative VERIFY step — the decode step grown
    to ``W = draft_len + 1`` positions per slot, still compile-once.

    Returns ``verify(params, pools, tokens, positions, active,
    page_tables, seeds) -> (pools, sampled)`` where ``tokens`` is
    (B, W) int32 — column 0 the slot's current token (exactly the
    decode step's ``tokens``), columns 1..k its n-gram drafts —
    ``positions``/``active`` are (B,) as in the decode step, ``seeds``
    is (B, W) uint32 (one per prospective emission: the slot's NEXT W
    draw counters), and ``sampled`` is (B, W): the sampling head's
    token at every verified position.

    One batched pass scores all B*W positions through the paged
    attention kernel (each layer scatters the W rows' k/v, then every
    row attends under its own causal length — the fused-verification
    framing of arxiv 2502.17728) and ONE fused-sampling launch draws
    all W prospective tokens per slot.  The host accepts the longest
    prefix where ``sampled[:, j-1] == tokens[:, j]``
    (:func:`apex_tpu.inference.spec.accepted_tokens`); since
    ``sampled[i, j]`` is conditioned on a verified-correct prefix
    whenever it is consumed, the emitted stream is the NON-speculative
    stream — bitwise, including under temperature sampling (each
    emission spends the same (slot, draw) seed the plain decode step
    would).  A missed draft costs nothing extra: column 0 always
    yields the standard-path token.
    """
    W = dcfg.draft_len + 1

    def verify(params, pools, tokens, positions, active, page_tables,
               seeds):
        B = tokens.shape[0]
        off = jnp.arange(W, dtype=jnp.int32)
        pos_f = (positions.astype(jnp.int32)[:, None]
                 + off[None, :]).reshape(B * W)
        hidden, pools = forward_decode(
            params, tokens.reshape(B * W), pos_f,
            jnp.repeat(active, W), pools, page_tables, config,
            attn_impl=dcfg.attn_impl, verify_width=W)
        sampled = fused_sample(
            hidden, params["embed"], seeds.reshape(B * W),
            temperature=dcfg.temperature, top_k=dcfg.top_k,
            impl=dcfg.sample_impl, dot_dtype=dcfg.sample_dot_dtype)
        return pools, sampled.reshape(B, W)

    return jax.jit(verify, donate_argnums=(1,))


def make_prefill(config: GPTConfig, dcfg: DecodeConfig):
    """Build the jitted prompt-prefill step (one static padded shape).

    Returns ``prefill(params, pools, prompt, prompt_len, start,
    page_table_row, seed) -> (pools, first_token)`` where ``prompt``
    is (1, max_prompt_len) int32 (zero-padded past ``prompt_len``; the
    padded tail's k/v go to the garbage page and its causal rows are
    never read), ``start`` is the prefix-sharing write window (k/v for
    positions < ``start`` already live in shared pool pages and are
    NOT rewritten; 0 = unshared), ``page_table_row`` is the admitted
    sequence's (P,) table, and ``first_token`` is sampled from the
    LAST prompt position's hidden state with the same sampling head as
    decode.  Pools donate, as in the decode step.
    """
    S = dcfg.max_prompt_len

    def prefill(params, pools, prompt, prompt_len, start, page_table_row,
                seed):
        hidden, kv = gpt_forward(params, prompt, config,
                                 return_hidden=True, return_kv=True)
        k_stack, v_stack = kv  # (L, 1, KVH, S, hd)
        ks = k_stack[:, 0].transpose(0, 2, 1, 3)  # (L, S, KVH, hd)
        vs = v_stack[:, 0].transpose(0, 2, 1, 3)
        kp, vp = write_prompt_kv(pools["k"], pools["v"], ks, vs,
                                 page_table_row, prompt_len, start=start)
        h_last = hidden[jnp.clip(prompt_len - 1, 0, S - 1), 0]  # (H,)
        first = fused_sample(
            h_last[None], params["embed"], seed[None],
            temperature=dcfg.temperature, top_k=dcfg.top_k,
            impl=dcfg.sample_impl, dot_dtype=dcfg.sample_dot_dtype)
        return {"k": kp, "v": vp}, first[0]

    return jax.jit(prefill, donate_argnums=(1,))


def make_prefill_chunk(config: GPTConfig, dcfg: DecodeConfig):
    """Build the jitted chunked-prefill step: ONE compile per chunk
    size serves every prompt length.

    Returns ``chunk(params, pools, tokens, start_pos, valid,
    write_start, page_table_row) -> (pools, h_last)`` processing
    ``tokens`` (C,) — the prompt slice at absolute positions
    ``start_pos .. start_pos + C - 1``, of which the first ``valid``
    are real (the final chunk pads) — through the multi-position
    decode forward: each layer scatters the chunk's k/v into the
    sequence's pages, then every position attends causally over the
    WHOLE cached prefix (earlier chunks included) plus its intra-chunk
    predecessors.  ``write_start``: absolute positions below it skip
    the k/v scatter (shared-prefix pages, or a pure recompute pass
    over fully-cached positions).  ``h_last`` is the last valid
    position's pre-head hidden state — the sampling input once the
    final chunk lands (:func:`make_sample_head`).  Pools donate.

    Prompt length never touches a traced shape: arbitrarily long
    prompts are ``ceil(plen / C)`` calls of this one executable,
    interleavable with decode steps (the TTFT fix for resident
    streams).
    """
    C = int(dcfg.prefill_chunk)

    def chunk(params, pools, tokens, start_pos, valid, write_start,
              page_table_row):
        off = jnp.arange(C, dtype=jnp.int32)
        pos = start_pos.astype(jnp.int32) + off
        act = off < valid
        wmask = act & (pos >= write_start)
        hidden, pools = forward_decode(
            params, tokens, pos, act, pools, page_table_row[None],
            config, attn_impl=dcfg.attn_impl, verify_width=C,
            write_mask=wmask)
        h_last = hidden[jnp.clip(valid - 1, 0, C - 1)]
        return pools, h_last

    return jax.jit(chunk, donate_argnums=(1,))


def make_sample_head(config: GPTConfig, dcfg: DecodeConfig):
    """The standalone jitted sampling head — hidden (H,) + seed →
    token — used once per chunked admission (the final chunk returns
    ``h_last``; sampling stays OUT of the chunk step so intermediate
    chunks never pay the vocab matmul)."""
    del config  # the head is fully described by dcfg + params

    def head(params, hidden, seed):
        tok = fused_sample(
            hidden[None], params["embed"], seed[None],
            temperature=dcfg.temperature, top_k=dcfg.top_k,
            impl=dcfg.sample_impl, dot_dtype=dcfg.sample_dot_dtype)
        return tok[0]

    return jax.jit(head)
