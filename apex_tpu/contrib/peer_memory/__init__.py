"""Peer-memory halo exchange (reference: ``apex/contrib/peer_memory`` —
CUDA-IPC peer pools + ``peer_halo_exchanger_1d``).

On TPU, neighbor transfers are ``ppermute`` over ICI — there is no
user-managed peer memory; the halo exchange lives in
:mod:`apex_tpu.contrib.bottleneck`.  Re-exported here for discovery.
"""

from apex_tpu.contrib.bottleneck.halo_exchangers import (
    HaloExchanger as PeerHaloExchanger1d,
    halo_exchange_1d,
)


class PeerMemoryPool:
    """No TPU analog: ICI transfers need no pinned peer pools.  Raises
    with guidance (reference peer_memory.py:5)."""

    def __init__(self, *a, **kw):
        raise NotImplementedError(
            "TPU has no peer-memory pools; use "
            "apex_tpu.contrib.bottleneck.halo_exchange_1d (ppermute over ICI)"
        )


__all__ = ["PeerHaloExchanger1d", "halo_exchange_1d", "PeerMemoryPool"]
