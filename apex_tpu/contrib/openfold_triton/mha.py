"""OpenFold fused MHA: attention with mask + trained pair bias.

Reference: ``apex/contrib/openfold_triton/mha.py`` —
``FusedAttenionCoreFunc.forward(q, k, v, mask=None, bias=None, inf=…)``
(:133) with Triton kernels ``_attention_bias``/``_attention_no_bias``
(:400,:438), plus the ``CanSchTriMHA`` shape gate (:36) and
enable/disable switches (:20-33).

TPU form: the blockwise-scan flash path with the additive bias folded
into the online softmax (``attn_bias`` in
:func:`apex_tpu.ops.attention.flash_attention`).  The pair bias is
differentiable — its cotangent is dS reduced over broadcast dims —
because OpenFold trains it (it comes from the pair representation).
The shape gate collapses to "always" (no Triton block constraints).
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.attention import flash_attention

_enabled = True


def is_enabled() -> bool:
    return _enabled


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def CanSchTriMHA(in_shape, has_bias=True, inf=1e9, training=True) -> bool:
    """Reference :36 gates on Triton tile shapes; the scan path handles
    any shape, so the gate only reflects the enable switch."""
    return _enabled


def attention_core(
    q,
    k,
    v,
    mask: Optional[jnp.ndarray] = None,
    bias: Optional[jnp.ndarray] = None,
    inf: float = 1e9,
):
    """(…, H, S, D) attention with optional mask and pair bias
    (reference ``FusedAttenionCoreFunc`` :133).

    ``mask``: broadcastable to the (…, H, Sq, Sk) scores; nonzero/True =
    keep, 0/False = masked with ``-inf`` (OpenFold convention).
    ``bias``: additive score bias broadcastable the same way (trained).
    Leading dims beyond 4 are flattened into the batch.
    """
    lead = q.shape[:-3]
    B = 1
    for d in lead:
        B *= d
    H, Sq, D = q.shape[-3:]
    Sk = k.shape[-2]
    q4 = q.reshape(B, H, Sq, D)
    k4 = k.reshape(B, H, Sk, D)
    v4 = v.reshape(B, H, Sk, D)

    def to4(t):
        return jnp.broadcast_to(t, (*lead, H, Sq, Sk)).reshape(B, H, Sq, Sk)

    attn_bias = None
    if bias is not None:
        attn_bias = to4(bias.astype(jnp.float32))
    if mask is not None:
        mask_bias = to4(jnp.where(mask.astype(bool), 0.0, -float(inf)).astype(jnp.float32))
        attn_bias = mask_bias if attn_bias is None else attn_bias + mask_bias

    out = flash_attention(
        q4, k4, v4, causal=False, attn_bias=attn_bias, impl="scan"
    )
    return out.reshape(*lead, H, Sq, D)


def AttnTri(q, k, v, mask=None, bias=None, inf=1e9):
    """Reference ``AttnTri = FusedAttenionCoreFunc.apply`` (mha.py:397) —
    positional alias of :func:`attention_core` (the fused/flash path)."""
    return attention_core(q, k, v, mask=mask, bias=bias, inf=inf)


@partial(jax.jit, static_argnames=("inf",))
def AttnBiasJIT(query, key, value, mask, bias, inf):
    """Reference ``torch.compile(_attention_bias)`` (mha.py:472): the
    jitted composite with a trained pair bias — XLA fuses the
    scale/mask/bias/softmax chain; (mask - 1)·inf reproduces the
    OpenFold logit-mask convention exactly."""
    scaling = 1.0 / (query.shape[-1] ** 0.5)
    a = jnp.matmul(query * scaling, jnp.swapaxes(key, -2, -1))
    a = a + (mask.astype(a.dtype) - 1.0) * inf
    if bias is not None:
        a = a + bias.astype(a.dtype)
    a = jax.nn.softmax(a.astype(jnp.float32), axis=-1).astype(query.dtype)
    return jnp.matmul(a, value)


def AttnNoBiasJIT(query, key, value, mask, inf):
    """Reference ``torch.compile(_attention_no_bias)`` (mha.py:473)."""
    return AttnBiasJIT(query, key, value, mask, None, inf)
