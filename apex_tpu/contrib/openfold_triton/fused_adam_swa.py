"""Fused AdamW + stochastic weight averaging.

Reference: ``apex/contrib/openfold_triton/fused_adam_swa.py`` — one
kernel applying the AdamW update and folding the result into an SWA
(exponential/equal-average) copy, used by OpenFold training.

TPU: one jit region over :class:`apex_tpu.optimizers.FusedAdam` plus the
SWA blend; the SWA params live in the optimizer state.
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers.fused_adam import AdamState, FusedAdam


class AdamSWAState(NamedTuple):
    adam: AdamState
    swa_params: Any
    n_averaged: jnp.ndarray  # i32


class FusedAdamSWA(FusedAdam):
    """AdamW whose update also maintains an SWA average.

    ``swa_decay_rate``: EMA coefficient; ``None`` = equal average
    (reference swa_decay semantics).
    """

    #: the fused amp tail (update_scaled) would apply the inherited
    #: Adam step but never this class's SWA blend / n_averaged count —
    #: train-step builders must use the explicit ``update`` path
    supports_update_scaled = False

    def __init__(self, *args, swa_decay_rate: Optional[float] = None, **kw):
        super().__init__(*args, **kw)
        self.swa_decay_rate = swa_decay_rate

    def init(self, params) -> AdamSWAState:
        return AdamSWAState(
            adam=super().init(params),
            # copy=True: astype on fp32 leaves returns the SAME buffer,
            # and an swa copy aliasing its param crashes donated steps
            # with "donate the same buffer twice" (cf. base.make_master)
            swa_params=jax.tree.map(
                lambda p: jnp.array(p, jnp.float32, copy=True), params),
            n_averaged=jnp.int32(0),
        )

    def update(self, grads, state: AdamSWAState, params, grads_finite=None, lr=None):
        new_params, adam_state = super().update(
            grads, state.adam, params, grads_finite=grads_finite, lr=lr
        )
        # Overflow-skipped steps (grads_finite=False) leave params
        # untouched; they must not be counted as SWA samples either.
        took_step = (
            jnp.bool_(True) if grads_finite is None else jnp.asarray(grads_finite)
        )
        n = state.n_averaged + took_step.astype(jnp.int32)
        if self.swa_decay_rate is None:
            w = 1.0 / jnp.maximum(n, 1).astype(jnp.float32)  # equal average
        else:
            w = 1.0 - self.swa_decay_rate
        w = jnp.where(took_step, w, 0.0)
        swa = jax.tree.map(
            lambda s, p: s + w * (p.astype(jnp.float32) - s), state.swa_params, new_params
        )
        return new_params, AdamSWAState(adam=adam_state, swa_params=swa, n_averaged=n)
