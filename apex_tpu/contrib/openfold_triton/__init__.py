"""OpenFold kernels (reference: ``apex/contrib/openfold_triton`` —
Triton LayerNorm fwd/bwd with autotune, MHA, fused Adam+SWA).

TPU mapping: the Triton LayerNorm is the Pallas fused norm
(:mod:`apex_tpu.ops.layer_norm_pallas`); Triton MHA is
:func:`apex_tpu.ops.attention.flash_attention`; the autotune-cache
broadcast machinery has no analog (XLA/Mosaic compile deterministically
per shape).  The genuinely distinct piece — fused AdamW + stochastic
weight averaging — is implemented here.
"""

from apex_tpu.contrib.openfold_triton.fused_adam_swa import AdamSWAState, FusedAdamSWA
from apex_tpu.contrib.openfold_triton.mha import (
    CanSchTriMHA,
    attention_core,
    disable,
    enable,
    is_enabled,
)
from apex_tpu.normalization import FusedLayerNorm as LayerNormSmallShapeOptImpl

__all__ = [
    "FusedAdamSWA",
    "AdamSWAState",
    "LayerNormSmallShapeOptImpl",
    "attention_core",
    "CanSchTriMHA",
    "enable",
    "disable",
    "is_enabled",
]
