"""OpenFold kernels (reference: ``apex/contrib/openfold_triton`` —
Triton LayerNorm fwd/bwd with autotune, MHA, fused Adam+SWA).

TPU mapping: the Triton LayerNorm is the Pallas fused norm
(:mod:`apex_tpu.ops.layer_norm_pallas`); Triton MHA is
:func:`apex_tpu.ops.attention.flash_attention`; the autotune-cache
broadcast machinery has no analog (XLA/Mosaic compile deterministically
per shape).  The genuinely distinct piece — fused AdamW + stochastic
weight averaging — is implemented here.
"""

from apex_tpu.contrib.openfold_triton.fused_adam_swa import AdamSWAState, FusedAdamSWA
from apex_tpu.contrib.openfold_triton.mha import (
    AttnBiasJIT,
    AttnNoBiasJIT,
    AttnTri,
    CanSchTriMHA,
    attention_core,
    disable,
    enable,
    is_enabled,
)
from apex_tpu.normalization import FusedLayerNorm as LayerNormSmallShapeOptImpl

def sync_triton_auto_tune_cache_across_gpus() -> None:
    """Reference __init__.py:97 broadcasts the Triton autotune cache from
    rank 0 so every GPU skips re-tuning.  XLA/Mosaic kernels compile
    deterministically per shape (the compilation cache is content-
    addressed), so there is nothing to synchronize; kept for API parity."""


__all__ = [
    "FusedAdamSWA",
    "AttnTri",
    "AttnBiasJIT",
    "AttnNoBiasJIT",
    "sync_triton_auto_tune_cache_across_gpus",
    "AdamSWAState",
    "LayerNormSmallShapeOptImpl",
    "attention_core",
    "CanSchTriMHA",
    "enable",
    "disable",
    "is_enabled",
]
