from apex_tpu.contrib.sparsity.asp import ASP, compute_sparse_masks, m4n2_mask

__all__ = ["ASP", "compute_sparse_masks", "m4n2_mask"]
