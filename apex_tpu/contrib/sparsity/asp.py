"""ASP — automatic 2:4 structured sparsity.

Reference: ``apex/contrib/sparsity/asp.py:28``
(``ASP.prune_trained_model``: compute 2-of-4 magnitude masks for eligible
weights, register pruning hooks) and the channel-permutation search
(``permutation_lib.py``) that improves mask quality.

TPU notes: 2:4 sparse *execution* is an NVIDIA Ampere tensor-core
feature with no TPU analog — the MXU runs dense.  What transfers is the
*algorithm*: mask computation, masked training (weights multiplied by a
static mask each step so pruned weights stay zero through optimizer
updates), and mask persistence.  That is exactly the part apex implements
in Python; the CUDA here is only the permutation search, replaced by a
greedy JAX implementation.
"""

from typing import Callable

import jax
import jax.numpy as jnp


def m4n2_mask(w: jnp.ndarray) -> jnp.ndarray:
    """2-of-4 magnitude mask along the last dim (reference
    sparse_masklib.py m4n2_1d): in every group of 4, keep the 2 largest
    |w|."""
    orig_shape = w.shape
    n = orig_shape[-1]
    if n % 4 != 0:
        raise ValueError(f"last dim ({n}) must be divisible by 4 for 2:4 sparsity")
    g = jnp.abs(w.reshape(-1, 4))
    # rank positions within each group of 4; keep top-2
    order = jnp.argsort(g, axis=-1)  # ascending
    mask = jnp.zeros_like(g, dtype=bool)
    rows = jnp.arange(g.shape[0])
    mask = mask.at[rows, order[:, 3]].set(True)
    mask = mask.at[rows, order[:, 2]].set(True)
    return mask.reshape(orig_shape)


def _eligible(path: str, w) -> bool:
    """Prune 2D+ weights, skip norms/biases/embeddings (reference
    asp.py eligibility rules)."""
    p = path.lower()
    if w.ndim < 2:
        return False
    if any(k in p for k in ("norm", "bn", "bias", "embed")):
        return False
    return w.shape[-1] % 4 == 0


def compute_sparse_masks(params, eligible: Callable = _eligible,
                         permutation_search: bool = False):
    """Boolean mask pytree (True = keep); ineligible leaves get None.

    ``permutation_search=True`` runs the greedy channel-permutation
    search per eligible weight (reference ``permutation_lib.py``) and
    returns masks that retain at least as much magnitude as the naive
    2:4 masks — the accuracy-preserving half of ASP."""
    flat = jax.tree_util.tree_flatten_with_path(params)
    masks = []
    for kp, w in flat[0]:
        path = jax.tree_util.keystr(kp)
        if not eligible(path, w):
            masks.append(None)
        elif permutation_search:
            from apex_tpu.contrib.sparsity.permutation_lib import (
                permuted_m4n2_mask,
                search_channel_permutation,
            )

            perm, _, _ = search_channel_permutation(w)
            masks.append(permuted_m4n2_mask(w, perm))
        else:
            masks.append(m4n2_mask(w))
    return jax.tree_util.tree_unflatten(flat[1], masks)


def apply_masks(params, masks):
    return jax.tree.map(
        lambda w, m: w if m is None else w * m.astype(w.dtype),
        params,
        masks,
        is_leaf=lambda x: x is None,
    )


class ASP:
    """Functional ASP workflow (reference asp.py):

        masks = ASP.compute_sparse_masks(params)      # once, post-training
        params = ASP.prune_trained_model(params, masks)
        # during sparse finetuning, after every optimizer step:
        params = ASP.apply_masks(params, masks)
    """

    compute_sparse_masks = staticmethod(compute_sparse_masks)
    apply_masks = staticmethod(apply_masks)

    @staticmethod
    def prune_trained_model(params, masks=None):
        if masks is None:
            masks = compute_sparse_masks(params)
        return apply_masks(params, masks), masks
