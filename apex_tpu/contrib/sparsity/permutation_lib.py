"""Channel-permutation search for 2:4 structured sparsity.

Reference: ``apex/contrib/sparsity/permutation_lib.py`` +
``permutation_search_kernels/channel_swap.py`` — the accuracy-preserving
half of ASP: permuting a weight's input channels before applying the
2-of-4 magnitude mask regroups correlated channels so the mask retains
more magnitude.  The reference searches with CUDA kernels over a torch
fx graph and physically permutes the model (compensating in neighbor
layers).

TPU redesign: the MXU executes dense, so the 2:4 pattern never needs to
be *physically* contiguous — what transfers is mask quality.  The search
therefore stays functional: find a permutation ``perm`` maximizing the
magnitude retained by a 2:4 mask on ``w[:, perm]``, then map the mask
back to the original column order (``mask = mask_perm[:, argsort(perm)]``).
Weights never move, neighbors never compensate, and the masked model is
numerically identical to the physically-permuted one the reference
builds.

Search = the reference's greedy channel-swap strategy
(``channel_swap.py``: build the improvement map for all column pairs,
apply the best positive swap, repeat until convergence), with the
improvement map computed as one vectorized JAX evaluation over all
(column, column) pairs instead of a CUDA kernel per stripe pair.
"""

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def sum_after_2_to_4(m: jnp.ndarray) -> jnp.ndarray:
    """Magnitude retained by a 2-of-4 mask along the last dim
    (reference permutation_utilities.py ``sum_after_2_to_4``)."""
    g = jnp.abs(m.reshape(*m.shape[:-1], m.shape[-1] // 4, 4))
    return jnp.sum(jnp.sort(g, axis=-1)[..., 2:])


def _stripe_mags(m: jnp.ndarray) -> jnp.ndarray:
    """Per-stripe retained magnitude: (C/4,) for m (R, C)."""
    R, C = m.shape
    g = jnp.abs(m.reshape(R, C // 4, 4))
    return jnp.sum(jnp.sort(g, axis=-1)[..., 2:], axis=(0, 2))


@partial(jax.jit, static_argnames=())
def _swap_improvements(m: jnp.ndarray) -> jnp.ndarray:
    """(C, C) matrix of retained-magnitude improvement for swapping
    columns a and b (0 where a, b share a stripe — a no-op for the mask).

    Vectorized form of the reference's swap map
    (channel_swap.py ``compute_swap_map``): for the pair (a, b), only
    stripes a//4 and b//4 change; evaluate both 4-wide stripes with the
    swapped column patched in.
    """
    R, C = m.shape
    S = C // 4
    base = _stripe_mags(m)  # (S,)

    stripes = m.reshape(R, S, 4)

    def one_pair(a, b):
        sa, ia = a // 4, a % 4
        sb, ib = b // 4, b % 4
        col_a = m[:, a]
        col_b = m[:, b]
        new_sa = jax.lax.dynamic_update_index_in_dim(
            stripes[:, sa, :], col_b, ia, axis=1
        )
        new_sb = jax.lax.dynamic_update_index_in_dim(
            stripes[:, sb, :], col_a, ib, axis=1
        )
        mag = lambda s: jnp.sum(jnp.sort(jnp.abs(s), axis=-1)[..., 2:])
        improvement = mag(new_sa) + mag(new_sb) - base[sa] - base[sb]
        return jnp.where(sa == sb, 0.0, improvement)

    cols = jnp.arange(C)
    return jax.vmap(lambda a: jax.vmap(lambda b: one_pair(a, b))(cols))(cols)


def search_channel_permutation(
    w, max_swaps: int = 0, tol: float = 1e-6
) -> Tuple[np.ndarray, float, float]:
    """Greedy channel-swap search (reference channel_swap.py).

    ``w``: (..., C) weight, pruned along the last dim; leading dims are
    flattened into rows.  Returns ``(perm, base_mag, best_mag)`` with
    ``sum_after_2_to_4(w[..., perm]) == best_mag >= base_mag``.

    ``max_swaps`` bounds the greedy iterations (0 = until convergence,
    capped at 4·C — each swap must improve, so convergence is
    guaranteed; the cap is a safety net against fp ties).
    """
    m = np.asarray(w, np.float32).reshape(-1, w.shape[-1])
    C = m.shape[1]
    if C % 4:
        raise ValueError(f"channel count {C} must be divisible by 4")
    perm = np.arange(C)
    base = float(sum_after_2_to_4(jnp.asarray(m)))
    limit = max_swaps if max_swaps > 0 else 4 * C

    cur = m.copy()
    for _ in range(limit):
        imp = np.asarray(_swap_improvements(jnp.asarray(cur)))
        a, b = np.unravel_index(np.argmax(imp), imp.shape)
        if imp[a, b] <= tol:
            break
        cur[:, [a, b]] = cur[:, [b, a]]
        perm[[a, b]] = perm[[b, a]]
    best = float(sum_after_2_to_4(jnp.asarray(cur)))
    return perm, base, best


def permuted_m4n2_mask(w: jnp.ndarray, perm) -> jnp.ndarray:
    """2-of-4 mask computed under ``perm`` and mapped back to the
    original column order.  The mask is 2:4-structured in the permuted
    domain (what sparse hardware would need) and strictly retains at
    least as much magnitude as the naive mask in the original domain."""
    from apex_tpu.contrib.sparsity.asp import m4n2_mask

    perm = jnp.asarray(perm)
    inv = jnp.argsort(perm)
    mask_perm = m4n2_mask(w[..., perm])
    return mask_perm[..., inv]
