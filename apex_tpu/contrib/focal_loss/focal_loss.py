"""Fused focal loss for detection.

Reference: ``apex/contrib/focal_loss/focal_loss.py:6``
(``focal_loss_cuda`` ext): sigmoid focal loss over anchor
classification logits with one-hot targets, normalized by
``num_positives_sum``.

FL(p_t) = -alpha_t (1-p_t)^gamma log(p_t), computed in one fusion.
"""

import jax
import jax.numpy as jnp


def focal_loss(
    cls_output,
    cls_targets_at_level,
    num_positives_sum,
    num_real_classes: int,
    alpha: float = 0.25,
    gamma: float = 2.0,
    label_smoothing: float = 0.0,
):
    """cls_output (..., C) raw logits; targets integer class ids with
    -1 = ignore, 0 = background (reference semantics: one-hot of id-1
    over num_real_classes)."""
    t = cls_targets_at_level
    C = num_real_classes
    onehot = jax.nn.one_hot(t - 1, C, dtype=jnp.float32)  # -1/0 → all-zero rows
    valid = (t >= 0).astype(jnp.float32)[..., None]
    if label_smoothing > 0:
        onehot = onehot * (1.0 - label_smoothing) + label_smoothing / C

    x = cls_output.astype(jnp.float32)
    p = jax.nn.sigmoid(x)
    ce = jnp.logaddexp(0.0, x) - x * onehot  # BCE-with-logits
    p_t = p * onehot + (1 - p) * (1 - onehot)
    alpha_t = alpha * onehot + (1 - alpha) * (1 - onehot)
    loss = alpha_t * jnp.power(1 - p_t, gamma) * ce * valid
    return jnp.sum(loss) / jnp.maximum(num_positives_sum, 1.0)
