"""Optional extensions (reference: ``apex/contrib``, SURVEY §2.3).

Each subpackage is independent, mirroring the reference's layout:
``optimizers`` (ZeRO DistributedFusedAdam/LAMB), ``xentropy``,
``clip_grad``, ``focal_loss``, ``group_norm``, ``layer_norm``,
``index_mul_2d``, ``fmha``, ``multihead_attn``, ``sparsity``,
``transducer``, ``conv_bias_relu``.
"""

_SUBS = (
    "optimizers",
    "xentropy",
    "clip_grad",
    "focal_loss",
    "group_norm",
    "groupbn",
    "cudnn_gbn",
    "layer_norm",
    "index_mul_2d",
    "fmha",
    "multihead_attn",
    "sparsity",
    "transducer",
    "conv_bias_relu",
    "bottleneck",
    "peer_memory",
    "openfold_triton",
)


def __getattr__(name):
    if name in _SUBS:
        import importlib

        mod = importlib.import_module(f"apex_tpu.contrib.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'apex_tpu.contrib' has no attribute {name!r}")
