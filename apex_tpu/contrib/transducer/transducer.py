"""RNN-T transducer joint + loss.

Reference: ``apex/contrib/transducer/transducer.py:5,68``
(``TransducerJoint``: fused broadcast-add of encoder/predictor features;
``TransducerLoss``: fused RNN-T alpha/beta forward-backward).

TPU: the joint is one broadcast fusion.  The loss runs the alpha
recursion in log space with ``lax.scan`` over time — static shapes, VPU
logaddexp — and gets its gradient by autodiff through the scan (the
reference hand-codes beta; autodiff of the forward DP is mathematically
identical).
"""


import jax
import jax.numpy as jnp

NEG_INF = -1e30


class TransducerJoint:
    """f (B, T, H) ⊕ g (B, U, H) → (B, T, U, H) broadcast-add joint
    (reference transducer.py:5).

    ``relu``/``dropout`` fuse into the same XLA kernel as the add
    (reference opt=1 fused epilogues).  ``pack_output`` in the reference
    removes don't-care (t ≥ f_len or u ≥ g_len) entries into a ragged
    buffer; ragged layouts are hostile to XLA, so the equivalent here is
    zero-masking those entries in place when ``f_len``/``g_len`` are
    given — downstream loss math ignores them either way.  Dropout needs
    an explicit ``key`` (functional RNG).
    """

    def __init__(self, pack_output: bool = False, relu: bool = False,
                 dropout: bool = False, dropout_prob: float = 0.0, **_opt_knobs):
        self.pack_output = pack_output
        self.relu = relu
        self.dropout = dropout
        self.dropout_prob = dropout_prob

    def __call__(self, f, g, f_len=None, g_len=None, key=None):
        out = f[:, :, None, :] + g[:, None, :, :]
        if self.relu:
            out = jax.nn.relu(out)
        if self.dropout and self.dropout_prob > 0.0:
            if key is None:
                raise ValueError("dropout=True needs key= (functional RNG)")
            keep = jax.random.bernoulli(key, 1.0 - self.dropout_prob, out.shape)
            out = jnp.where(keep, out / (1.0 - self.dropout_prob), 0.0)
        if self.pack_output and (f_len is not None or g_len is not None):
            B, T, U, _ = out.shape
            valid = jnp.ones((B, T, U), bool)
            if f_len is not None:
                valid &= jnp.arange(T)[None, :, None] < f_len[:, None, None]
            if g_len is not None:
                valid &= jnp.arange(U)[None, None, :] < g_len[:, None, None]
            out = jnp.where(valid[..., None], out, 0.0)
        return out


def transducer_loss(logits, targets, f_len, y_len, blank_idx: int = 0):
    """RNN-T negative log likelihood.

    logits (B, T, U, V) — U = max_target_len + 1; targets (B, U-1);
    f_len (B,) valid time steps; y_len (B,) valid target lengths.
    """
    B, T, U, V = logits.shape
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)

    # per-(t,u): probability of blank and of the correct next label
    blank_lp = logp[..., blank_idx]  # (B, T, U)
    tgt = jnp.pad(targets, ((0, 0), (0, 1)), constant_values=0)  # (B, U)
    label_lp = jnp.take_along_axis(logp, tgt[:, None, :, None], axis=-1)[..., 0]  # (B,T,U)

    # alpha DP: scan over time; within a step, scan over u
    # α(0,0)=0; α(t,u) = logaddexp(α(t-1,u) + blank(t-1,u),
    #                               α(t,u-1) + label(t,u-1))
    def time_step(alpha_prev, inputs):
        blank_t1, label_t = inputs  # blank at t-1 (B,U), label at t (B,U)
        from_top = alpha_prev + blank_t1  # emit blank, advance time

        def u_step(carry, x):
            ft, lab = x  # from_top (B,), label(t, u-1) (B,)
            a = jnp.logaddexp(ft, carry + lab)
            return a, a

        # u=0 can only come from the top
        a0 = from_top[:, 0]
        _, rest = jax.lax.scan(
            u_step, a0, (from_top[:, 1:].T, label_t[:, :-1].T)
        )
        alpha = jnp.concatenate([a0[:, None], rest.T], axis=1)
        return alpha, alpha

    alpha0_row = jnp.concatenate(
        [jnp.zeros((B, 1)), jnp.full((B, U - 1), NEG_INF)], axis=1
    )

    # first row (t=0): only label transitions
    def u0_step(carry, lab):
        a = carry + lab
        return a, a

    _, rest0 = jax.lax.scan(u0_step, jnp.zeros((B,)), label_lp[:, 0, :-1].T)
    alpha_t0 = jnp.concatenate([jnp.zeros((B, 1)), rest0.T], axis=1)

    blanks = jnp.moveaxis(blank_lp[:, :-1, :], 1, 0)  # (T-1, B, U)
    labels = jnp.moveaxis(label_lp[:, 1:, :], 1, 0)  # (T-1, B, U)
    _, alphas = jax.lax.scan(time_step, alpha_t0, (blanks, labels))
    alphas = jnp.concatenate([alpha_t0[None], alphas], axis=0)  # (T, B, U)

    # final: α(f_len-1, y_len) + blank(f_len-1, y_len)
    t_idx = jnp.clip(f_len - 1, 0, T - 1)
    u_idx = jnp.clip(y_len, 0, U - 1)
    b_idx = jnp.arange(B)
    final_alpha = alphas[t_idx, b_idx, u_idx]
    final_blank = blank_lp[b_idx, t_idx, u_idx]
    return -(final_alpha + final_blank)


class TransducerLoss:
    """Callable parity with reference TransducerLoss (transducer.py:68)."""

    def __init__(self, fuse_softmax_backward: bool = True, packed_input: bool = False):
        pass

    def __call__(self, logits, targets, f_len, y_len, blank_idx: int = 0, **kw):
        return transducer_loss(logits, targets, f_len, y_len, blank_idx)
