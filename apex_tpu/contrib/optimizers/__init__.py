from apex_tpu.contrib.optimizers.distributed_fused_adam import (
    DistributedFusedAdam,
    DistributedFusedAdamState,
)
from apex_tpu.contrib.optimizers.distributed_fused_lamb import DistributedFusedLAMB

__all__ = [
    "DistributedFusedAdam",
    "DistributedFusedAdamState",
    "DistributedFusedLAMB",
]
