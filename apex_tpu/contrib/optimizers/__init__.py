"""ZeRO distributed optimizers + the reference's deprecated re-exports.

Reference ``apex/contrib/optimizers/__init__.py`` also exports legacy
``FP16_Optimizer`` / ``FusedAdam`` / ``FusedLAMB`` shims (deprecated
there in favor of ``apex.optimizers`` / ``apex.amp``); here they alias
the maintained implementations and warn once.
"""

from apex_tpu.contrib.optimizers.distributed_fused_adam import (
    DistributedFusedAdam,
    DistributedFusedAdamState,
)
from apex_tpu.contrib.optimizers.distributed_fused_lamb import DistributedFusedLAMB

__all__ = [
    "DistributedFusedAdam",
    "DistributedFusedAdamState",
    "DistributedFusedLAMB",
]


def __getattr__(name):
    _legacy = {
        "FusedAdam": ("apex_tpu.optimizers", "FusedAdam"),
        "FusedLAMB": ("apex_tpu.optimizers", "FusedLAMB"),
        "FP16_Optimizer": ("apex_tpu.fp16_utils", "FP16_Optimizer"),
    }
    if name in _legacy:
        import importlib

        from apex_tpu import deprecated_warning

        deprecated_warning(
            f"apex_tpu.contrib.optimizers.{name} is deprecated (as in the "
            f"reference); use {_legacy[name][0]}.{name}."
        )
        mod, attr = _legacy[name]
        return getattr(importlib.import_module(mod), attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


# NOTE: the legacy names are intentionally NOT in __all__ — the reference
# shims warn on *use*, and a star-import must not trigger the warnings.
