"""Quantized gradient synchronization on the bucket plan: int8/fp8
wire traffic with shared per-block scales and error-feedback residuals.

Reference: ``apex/contrib/optimizers/distributed_fused_adam.py``
reserves fp8 gradient buffers with per-bucket amax scaling
(``grad_sync_dtype=torch.float8_*`` + ``_fp8_scale``/amax history);
ground papers: "DynamiQ: Accelerating Gradient Synchronization using
Compressed Multi-hop All-reduce" (PAPERS.md, arXiv 2602.08923 —
quantize at the collective, carry the quantization error forward) and
the ZeRO basis arXiv 2004.13336 whose per-bucket reduce-scatters make
the wire format pluggable here.

The TPU-shaped scheme (what makes a REAL ``reduce_scatter`` with an
int8/fp8 operand element type numerically safe — the sum happens on
the wire, in the wire dtype):

- **Shared per-block scales.**  Each bucket splits into fixed
  :data:`QBLOCK`-element blocks.  Every rank computes its local amax
  per block; one small fp32 ``psum`` (the only full-precision
  collective, ~``4/QBLOCK`` of the payload bytes) yields the SUM of
  amaxes, and the shared scale is ``s = Σ_r amax_r / qmax``.  Each
  rank additionally clips its quantized block to
  ``±⌊qmax · amax_r / Σ amax_r⌋``, so the dp-sum of everyone's
  quantized values is bounded by ``qmax`` **by construction** — int8
  accumulation cannot wrap at any world size (integer adds are exact
  and every partial sum obeys the same bound).  fp8 wire dtypes halve
  ``qmax`` as headroom for the per-add rounding of float8
  accumulation.
- **Error-feedback residuals.**  Quantization error does not average
  out: without feedback the bias accumulates in the trajectory.  Each
  rank keeps ``residual = h - dequantize(quantize(h))`` as RESIDENT
  per-bucket optimizer state (stored in the bucket's storage dtype,
  donated through jit like m/v) and adds it back into the next step's
  gradient before quantizing — the one sharded grad read.  The
  telescoping identity ``Σ_steps transmitted = Σ_steps grads −
  final_residual`` holds exactly on exactly-representable inputs
  (``tests/test_distributed_optimizers.py`` pins it bitwise).
- **Dequantize into fp32.**  The owner shard dequantizes with its
  slice of the shared scale vector and the optimizer math proceeds in
  fp32 exactly as for the wide wire dtypes (LAMB's trust-ratio segment
  sums read the dequantized fp32 shard, unchanged).

Scales must stay fp32 (a half-precision scale re-quantizes the
quantizer) and residuals must match the bucket storage dtype — the
static analyzer's APX305 pins both at the source level.
"""

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers import bucketing

__all__ = [
    "QBLOCK", "QSpec", "qspec_of", "is_quantized", "block_scales",
    "quantize", "dequantize", "quantized_reduce_scatter",
    "quantized_pmean", "quantized_pmean_bucket", "grad_sync_bytes",
]

#: Elements per scale block.  Divides every bucket's dp shard: bucket
#: totals are padded to (sublane × 128)-tile × world multiples and the
#: smallest tile (fp32) is 1024 elements.  4 B of fp32 scale per 1024
#: payload elements keeps the scale vector at ~0.4% of an int8 wire.
QBLOCK = 1024


@dataclasses.dataclass(frozen=True)
class QSpec:
    """One quantized wire format: its dtype name, the effective clip
    bound ``qmax`` (fp8 formats carry a 2x margin under their finite
    max as headroom for float accumulation rounding inside the
    reduce), and whether rounding is to-integer."""

    name: str
    qmax: float
    is_int: bool

    @property
    def wire_dtype(self):
        return jnp.dtype(self.name)


_QSPECS = {
    "int8": QSpec("int8", 127.0, True),
    # e4m3 max finite 448, e5m2 max finite 57344; half of each leaves
    # headroom so the in-reduce float8 rounding cannot overflow (e4m3
    # has no inf — an overflow saturates to nan and poisons the shard)
    "float8_e4m3fn": QSpec("float8_e4m3fn", 224.0, False),
    "float8_e5m2": QSpec("float8_e5m2", 28672.0, False),
}


def qspec_of(dtype) -> Optional[QSpec]:
    """The :class:`QSpec` for a quantized wire dtype, None for wide
    (fp32/bf16/fp16) sync dtypes."""
    if dtype is None:
        return None
    return _QSPECS.get(jnp.dtype(dtype).name)


def is_quantized(dtype) -> bool:
    return qspec_of(dtype) is not None


def block_scales(h, axis_name: str, spec: QSpec,
                 block: int = QBLOCK) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``(scales, bounds)`` for one bucket, both fp32 of length
    ``len(h)//block``:

    - ``scales[b] = Σ_ranks amax_r[b] / qmax`` — SHARED across ranks
      (one small fp32 psum), chosen so the wire-dtype SUM of every
      rank's quantized block is bounded by ``qmax``;
    - ``bounds[b] = qmax · amax_r[b] / Σ amax_r[b]`` — this rank's
      per-block clip, whose dp-sum is ≤ ``qmax`` by construction.

    An all-zero block gets scale 1 and bound 0 (quantizes to exact
    zeros).  Non-finite amaxes propagate — the caller's finite vote on
    the PRE-quantization values gates the commit."""
    a_loc = jnp.max(jnp.abs(h.reshape(-1, block)), axis=1)
    a_sum = jax.lax.psum(a_loc, axis_name)
    denom = jnp.where(a_sum > 0, a_sum, 1.0)
    scales = jnp.where(a_sum > 0, a_sum / spec.qmax, 1.0)
    bounds = spec.qmax * (a_loc / denom)
    return scales, bounds


def quantize(h, scales, bounds, spec: QSpec, block: int = QBLOCK):
    """One bucket to the wire dtype: divide by the shared per-block
    scale, round (int wires; fp8 rounds in the cast), clip to this
    rank's bound so the cross-rank sum stays in range."""
    y = h.reshape(-1, block) / scales[:, None]
    if spec.is_int:
        b = jnp.floor(bounds)[:, None]
        q = jnp.clip(jnp.round(y), -b, b)
    else:
        b = bounds[:, None]
        q = jnp.clip(y, -b, b)
    return q.reshape(-1).astype(spec.wire_dtype)


def dequantize(q, scales, block: int = QBLOCK) -> jnp.ndarray:
    """Wire values back to fp32: per-block multiply by the (fp32)
    scale slice covering ``q``'s position."""
    return (q.astype(jnp.float32).reshape(-1, block)
            * scales[:, None]).reshape(-1)


def _check_block(n: int, block: int, world: int) -> None:
    if n % (block * max(world, 1)):
        raise ValueError(
            f"bucket of {n} elements does not split into {block}-element "
            f"scale blocks per {world}-way shard — bucket totals must be "
            "padded with bucketing.padded_total(shard_pad=world)")


def quantized_reduce_scatter(h, axis_name: str, spec: QSpec, rank, world,
                             block: int = QBLOCK):
    """The quantized grad sync of one bucket: returns
    ``(sum_shard_f32, residual_f32)`` where ``sum_shard_f32`` is this
    rank's 1/world shard of the dp-SUM of every rank's ``h`` (to the
    wire precision) and ``residual_f32 = h − dequantize(quantize(h))``
    is the local quantization error to carry into the next step.

    The payload crosses the wire in ``spec.wire_dtype`` — the lowering
    shows a ``reduce_scatter`` with an int8/fp8 operand element type —
    plus the fp32 scale psum from :func:`block_scales`."""
    _check_block(h.shape[0], block, world)
    scales, bounds = block_scales(h, axis_name, spec, block)
    q = quantize(h, scales, bounds, spec, block)
    residual = h - dequantize(q, scales, block)
    q_shard = jax.lax.psum_scatter(q, axis_name, scatter_dimension=0,
                                   tiled=True)
    nb_shard = (h.shape[0] // block) // world
    s_shard = jax.lax.dynamic_slice_in_dim(scales, rank * nb_shard, nb_shard)
    return dequantize(q_shard, s_shard, block), residual


def quantized_pmean(grads, axis_name: str, spec: QSpec, world: int,
                    block: int = QBLOCK):
    """Quantized gradient all-reduce for the REPLICATED data-parallel
    path (non-ZeRO): pack the grad tree into bucket-plan buckets,
    quantized reduce-scatter + all-gather — both collectives on the
    wire dtype (the gathered SUM is still bounded by ``qmax``, so the
    gather needs no re-quantization) — dequantize with the shared
    scales, divide by ``world``, unpack to storage dtypes.

    Stateless: the replicated step has no optimizer-state channel, so
    there is NO error-feedback residual here — per-step quantization
    error is unbiased-ish but uncompensated.  ZeRO
    (``DistributedFusedAdam(grad_sync_dtype=...)``) is the compressed
    path with feedback; this serves plain-DP runs that want the wire
    cut and accept the looser numerics."""
    plan = bucketing.plan_of(grads, shard_pad=world)
    leaves = jax.tree.leaves(grads)
    out = [quantized_pmean_bucket(bucketing.pack_bucket(b, leaves,
                                                        jnp.float32),
                                  axis_name, spec, world, block)
           for b in plan.buckets]
    return bucketing.unpack(plan, out)


def quantized_pmean_bucket(h, axis_name: str, spec: QSpec, world: int,
                           block: int = QBLOCK) -> jnp.ndarray:
    """One packed fp32 bucket's quantized all-reduce — the per-bucket
    body of :func:`quantized_pmean`, exposed on its own so the
    backward-overlapped train step (``make_train_step(overlap_grad_sync
    =True)``) can issue each bucket's collective the moment its
    cotangents materialize instead of after the whole backward."""
    _check_block(h.shape[0], block, world)
    scales, bounds = block_scales(h, axis_name, spec, block)
    q = quantize(h, scales, bounds, spec, block)
    q_shard = jax.lax.psum_scatter(q, axis_name, scatter_dimension=0,
                                   tiled=True)
    q_full = jax.lax.all_gather(q_shard, axis_name, axis=0, tiled=True)
    return dequantize(q_full, scales, block) * (1.0 / world)


def grad_sync_bytes(total: int, sync_dtype, block: int = QBLOCK,
                    hier=None, flat_hop: str = "dp"):
    """PER-HOP ``{hop: {"payload": bytes, "scales": bytes}}`` one
    bucket's grad sync puts on the wire per step (per rank: what this
    rank contributes to each hop's collective).  The scale-vector bytes
    of the quantized wires (the fp32 per-block amax psum) are EXPLICIT
    per hop — never folded into a payload approximation — so the
    bench's ``wire_bytes_per_step`` ratios (≈2x int8 vs bf16, ≈4x vs
    fp32, the ``1/dp_inner`` cross-slice cut) are exact.

    - flat (``hier=None``): one hop keyed ``flat_hop`` with the full
      ``total``-element payload in the sync dtype;
    - hierarchical (``hier`` a :class:`~apex_tpu.contrib.optimizers
      ._hierarchical_sync.HierarchicalSyncPlan`): the fast inner hop
      carries the full bucket and each slower hop the chunk already
      scattered by every faster hop — ALL at the wire dtype, each with
      its own per-hop-sized scale vector, so the slow-hop bytes are
      exactly ``1/prod(faster sizes)`` of the flat plan's at equal wire
      dtype (two-level: ``1/dp_inner`` cross-slice; three-level
      additionally ``1/(dp_in * dp_out)`` cross-DCN)."""
    spec = qspec_of(sync_dtype)
    item = (spec.wire_dtype.itemsize if spec is not None
            else jnp.dtype(sync_dtype).itemsize)
    f32 = jnp.dtype(jnp.float32).itemsize

    def hop(n):
        return {"payload": n * item,
                "scales": (n // block) * f32 if spec is not None else 0}

    if hier is None:
        return {flat_hop: hop(total)}
    out, n = {}, total
    for axis, size in zip(reversed(hier.hop_axes),
                          reversed(hier.hop_sizes)):  # fast -> slow
        out[axis] = hop(n)
        n //= max(size, 1)
    return out
