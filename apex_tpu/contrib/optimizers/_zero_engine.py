"""The ZeRO bucket engine: resident dp-sharded optimizer state on the
:class:`~apex_tpu.optimizers.bucketing.BucketPlan` layout.

Reference: ``apex/contrib/optimizers/distributed_fused_adam.py`` (3,078
LoC) — ``ParameterFragment``/``StateBucket`` fragment maps, fixed-size
buckets, reduce-scatter grad sync overlapped with backward, all-gather
param sync optionally overlapped with forward, optimizer state sharded
over the distributed process group.

TPU shape of that machinery (this module):

- **the bucket plan IS the fragment map**: params flatten (in
  ``tree_flatten`` order) into dtype-homogeneous 1-D buckets, split by
  ``bucket_cap_mb`` at leaf granularity and padded so each bucket slices
  into ``dp`` tile-aligned shards (``bucketing.plan_of(cap_bytes=...,
  shard_pad=dp)``);
- **state is resident as the local 1/dp shard of each bucket**: m/v
  (and the fp32 master or the uint16 param remainders) are per-bucket
  flat arrays sharded over ``(model axes…, dp)`` — no per-step tree
  flatten, no whole-tree fp32 concat, and the buffers donate through
  ``jax.jit`` like any other state leaf;
- **grad sync is one ``psum_scatter`` per bucket in
  ``grad_sync_dtype``** (storage dtype for half buckets by default — a
  bf16 bucket's gradient crosses the wire in bf16, half the traffic of
  the old monolithic fp32 concat), so XLA's latency-hiding scheduler
  can overlap each bucket's collective with the remaining backward and
  with other buckets' math; ``grad_sync_dtype`` of ``int8`` /
  ``float8_e4m3fn`` / ``float8_e5m2`` engages the QUANTIZED wire
  (:mod:`apex_tpu.contrib.optimizers._quantized_sync`): shared
  per-block fp32 scales from an amax psum, the narrow payload
  reduce-scattered in the wire dtype, and the per-rank quantization
  error carried as a resident error-feedback residual bucket (stored
  in the bucket's storage dtype, donated through jit like m/v);
- **param sync is one ``all_gather`` per bucket in
  ``param_sync_dtype``**; with ``overlap_param_sync`` the gather runs
  on the pre-commit update (before the cross-rank finite vote
  completes) and the commit is predicated per leaf afterwards, so the
  gather is not serialized behind the vote's collectives;
- **``dp_axes=(outer, inner)`` makes both syncs topology-aware**
  (:mod:`apex_tpu.contrib.optimizers._hierarchical_sync`): per bucket
  the grad sync becomes a TWO-HOP reduce-scatter — intra-slice on the
  fast inner axis, cross-slice on the slow outer axis at the same
  wire dtype (quantized wires requantize the partial sums against
  fresh outer-shared scales and fold the requantization error into
  the same residual channel) — and the param gathers mirror in
  reverse.  Shard ownership keeps the FLAT chunk-per-rank layout and
  the one ``bucketing.padded_total`` formula, so checkpoints reshard
  across flat <-> hierarchical worlds unchanged; cross-slice wire
  bytes drop by exactly ``1/dp_inner`` (per-hop accounting in
  :meth:`ZeroOptimizerBase.wire_bytes_per_step`).

Fail-fast contract: the collectives live INSIDE the optimizer, so this
engine never routes through the per-process
:mod:`apex_tpu.resilience.fallback` registry — a per-process degrade
would lower divergent SPMD programs (mismatched collective counts
deadlock the pod device-side, the exact hazard ``registry_engaged``
documents).  An engine failure surfaces loudly and ``--auto-resume``
restarts the job.
"""

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.contrib.optimizers import _hierarchical_sync as hs
from apex_tpu.contrib.optimizers import _quantized_sync as qs
from apex_tpu.observability import stepstats as _stepstats
from apex_tpu.optimizers import bucketing
from apex_tpu.optimizers.base import bias_corrections
from apex_tpu.transformer.parallel_state import DATA_AXIS

Tree = Any

#: Wide sync dtypes: the wire carries the values themselves.
_SUPPORTED_SYNC = ("float32", "bfloat16", "float16")

#: Quantized wire dtypes (grad sync ONLY): shared per-block fp32
#: scales + error-feedback residuals (``_quantized_sync``).  int8 is
#: the only legal integer — wider ints have no scaled-sum story and
#: narrower ones no wire support.
_QUANTIZED_GRAD_SYNC = ("int8", "float8_e4m3fn", "float8_e5m2")


def resolve_sync_dtype(value, knob: str):
    """Validate a ``grad_sync_dtype``/``param_sync_dtype`` knob; None
    means the per-bucket default (the bucket's storage dtype for half
    buckets, fp32 otherwise).  ``grad_sync_dtype`` additionally accepts
    the quantized wire dtypes ``int8``/``float8_e4m3fn``/
    ``float8_e5m2``; ``param_sync_dtype`` never does."""
    if value is None:
        return None
    dt = jnp.dtype(value)
    if dt.name in _SUPPORTED_SYNC:
        return dt
    if dt.name in _QUANTIZED_GRAD_SYNC:
        if knob == "grad_sync_dtype":
            return dt
        raise ValueError(
            f"{knob}={dt.name!r}: quantized sync is gradient-only — a "
            "param all-gather has no error-feedback channel (a gather "
            "is not a sum: each step's quantization error would land in "
            "the params with no residual to carry it to the next step); "
            f"pass one of {_SUPPORTED_SYNC} or None")
    raise ValueError(
        f"{knob}={dt.name!r} is not supported: pass one of "
        f"{_SUPPORTED_SYNC}, None (per-bucket default: the bucket's "
        "storage dtype for bf16/fp16 buckets, float32 otherwise), or — "
        f"for grad_sync_dtype only — a quantized wire dtype "
        f"{_QUANTIZED_GRAD_SYNC} (int8 is the only supported integer; "
        "per-block fp32 scales + error-feedback residuals ride the "
        "bucket plan)")


def _spec_dim_axes(entry) -> Tuple[str, ...]:
    return tuple(ax for ax in (entry if isinstance(entry, tuple) else (entry,))
                 if ax is not None)


def local_leaf_info(params, param_specs, axis_sizes, zero_axis):
    """Per-leaf LOCAL shard shapes when ``params`` are sharded over
    model-parallel mesh axes per ``param_specs``, plus the sorted model
    axes and — per leaf — the replication factor a psum over those axes
    over-counts it by (1 for fully sharded leaves).  ``zero_axis`` may
    be one axis name or the hierarchical ``(outer, inner)`` pair.
    Raises if a param is sharded over any ZeRO axis itself, or if any
    sharded DIMENSION is indivisible (floor division would silently
    misalign the flat layout)."""
    zero_axes = set(zero_axis) if isinstance(zero_axis, (tuple, list)) \
        else {zero_axis}
    leaves, treedef = jax.tree.flatten(params)
    spec_leaves = treedef.flatten_up_to(param_specs)
    used_axes: List[str] = []
    leaf_axes = []
    local_shapes = []
    for leaf, spec in zip(leaves, spec_leaves):
        shape = list(leaf.shape)
        axes_here = set()
        for dim, entry in enumerate(tuple(spec)):
            dim_axes = _spec_dim_axes(entry)
            if not dim_axes:
                continue
            for ax in dim_axes:
                if ax in zero_axes:
                    raise ValueError(
                        f"params must not be sharded over the ZeRO axis {ax!r}")
            shard = int(np.prod([axis_sizes[ax] for ax in dim_axes]))
            # per-DIMENSION check: a divisible total with an indivisible
            # sharded dim (e.g. (13, 5) split 5-way on dim 0) still
            # pads/misaligns the flat layout
            if leaf.shape[dim] % shard != 0:
                raise ValueError(
                    f"param dim {dim} of shape {leaf.shape} is not divisible "
                    f"by mesh axes {dim_axes!r} (total size {shard}); the "
                    "flat ZeRO layout would silently misalign")
            shape[dim] //= shard
            for ax in dim_axes:
                axes_here.add(ax)
                if ax not in used_axes:
                    used_axes.append(ax)
        leaf_axes.append(axes_here)
        local_shapes.append(tuple(shape))
    model_axes = tuple(sorted(used_axes))
    repl = [
        int(np.prod([axis_sizes[ax] for ax in model_axes if ax not in s]
                    or [1]))
        for s in leaf_axes
    ]
    return local_shapes, model_axes, repl


def _leaf_shard_np(leaf, spec, combo: Dict[str, int], axis_sizes):
    """The numpy block of ``leaf`` that mesh-rank ``combo`` holds under
    ``spec`` — jax shards each dim into row-major blocks, multi-axis
    dims major-to-minor left to right, which this mirrors exactly."""
    x = np.asarray(leaf)
    for dim, entry in enumerate(tuple(spec)):
        dim_axes = _spec_dim_axes(entry)
        if not dim_axes:
            continue
        n_shards = int(np.prod([axis_sizes[ax] for ax in dim_axes]))
        size = x.shape[dim] // n_shards
        idx = 0
        for ax in dim_axes:
            idx = idx * axis_sizes[ax] + combo[ax]
        x = np.take(x, range(idx * size, (idx + 1) * size), axis=dim)
    return x


class ZeroOptimizerBase:
    """Shared constructor plumbing + the bucket-shard machinery for the
    ZeRO optimizers.  Subclasses implement ``_shard_update`` (the
    per-shard math, reusing the per-leaf oracle's expression trees) and
    their state NamedTuple."""

    #: ``update_scaled`` covers the full step: the gpt step builders
    #: fold unscale/clip/finite-vote into the sharded grad read.
    supports_update_scaled = True

    def __init__(
        self,
        lr: float,
        weight_decay: float,
        axis_name: str = DATA_AXIS,
        grad_average: bool = True,
        overlap_grad_sync: bool = True,
        overlap_param_sync: bool = False,
        bucket_cap_mb: float = 100.0,
        grad_sync_dtype=None,
        param_sync_dtype=None,
        store_param_remainders: bool = False,
        dtype=jnp.float32,
        dp_axes: Optional[Sequence[str]] = None,
        process_group=None,
        distributed_process_group=None,
        redundant_process_group=None,
    ):
        self.lr = lr
        self.weight_decay = weight_decay
        self.axis_name = axis_name
        # hierarchical (slow, ..., fast) dp split: grad sync becomes
        # the multi-hop reduce-scatter of _hierarchical_sync —
        # intra-slice on the fast inner axis first, each slower axis
        # (cross-slice dp_out, cross-pod dcn) on the shrinking chunk at
        # the same wire dtype — param sync the mirrored gathers.  The
        # HierarchicalSyncPlan itself is built at init (it needs the
        # axis sizes); ownership keeps the FLAT chunk-per-rank layout,
        # so checkpoints reshard flat <-> two-level <-> three-level
        # unchanged.
        if dp_axes is not None:
            dp_axes = tuple(dp_axes)
            if not (2 <= len(dp_axes) <= 3) \
                    or len(set(dp_axes)) != len(dp_axes) \
                    or not all(isinstance(a, str) for a in dp_axes):
                raise ValueError(
                    f"dp_axes must be two or three distinct mesh axis "
                    f"names ordered slow to fast — (outer, inner) or "
                    f"(dcn, dp_out, dp_in) — got {dp_axes!r}")
        self.dp_axes = dp_axes
        self._hier_plan: Optional[hs.HierarchicalSyncPlan] = None
        self.grad_average = grad_average
        # per-bucket collectives are independently schedulable by
        # construction — overlap_grad_sync here is the reference's knob
        # for its side-stream engine and stays structural (recorded for
        # parity); the REAL backward-overlap seam is the step builder's
        # default-off ``make_train_step(overlap_grad_sync=True)``,
        # which issues each bucket's wire (``bucket_grad_wire``) inside
        # the backward and hands the engine pre-scattered shards via
        # ``presynced=``.  overlap_param_sync is real: True gathers the
        # PRE-commit update so the all-gather is not serialized behind
        # the finite vote (per-leaf predicated select afterwards).
        self.overlap_grad_sync = overlap_grad_sync
        self.overlap_param_sync = overlap_param_sync
        if bucket_cap_mb is not None and bucket_cap_mb <= 0:
            raise ValueError(f"bucket_cap_mb must be positive, got {bucket_cap_mb}")
        self.bucket_cap_mb = bucket_cap_mb
        self._cap_bytes = (None if bucket_cap_mb is None
                           else int(bucket_cap_mb * 2 ** 20))
        self.grad_sync_dtype = resolve_sync_dtype(grad_sync_dtype,
                                                  "grad_sync_dtype")
        self.param_sync_dtype = resolve_sync_dtype(param_sync_dtype,
                                                   "param_sync_dtype")
        # halve master-weight memory for bf16 params: store only the 16
        # mantissa bits the bf16 param is missing (reference
        # ``store_param_remainders``); param sync gathers bf16
        self.store_param_remainders = store_param_remainders
        if store_param_remainders and self.param_sync_dtype not in (
                None, jnp.dtype(jnp.bfloat16)):
            raise ValueError(
                "store_param_remainders gathers the master's bf16 high "
                "half; param_sync_dtype must be None or bfloat16, got "
                f"{self.param_sync_dtype.name!r}")

    # ------------------------------------------------------------- plan
    def _plan_of_local(self, params) -> bucketing.BucketPlan:
        """The plan over the LOCAL (model-sharded) param leaves — inside
        shard_map the traced leaves already have local shapes, so this
        is the same cached object ``init`` built."""
        world = getattr(self, "_world", None)
        if world is None:
            raise ValueError("call init() before update: the bucket plan "
                             "and dp shard layout live on the optimizer")
        return bucketing.plan_of(params, cap_bytes=self._cap_bytes,
                                 shard_pad=world)

    def _grad_dtype(self, bucket) -> jnp.dtype:
        if self.grad_sync_dtype is not None:
            return self.grad_sync_dtype
        dt = jnp.dtype(bucket.dtype)
        return dt if dt in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)) \
            else jnp.dtype(jnp.float32)

    @property
    def _quantized(self) -> bool:
        """True when grad sync runs the quantized wire (int8/fp8) —
        the optimizer then carries error-feedback residual buckets."""
        return qs.is_quantized(self.grad_sync_dtype)

    @property
    def _dp_sync_axes(self):
        """The axis-name argument dp-wide scalar collectives (finite
        pmin, clip psum) take: the flat axis name, or the hierarchical
        ``(outer, inner)`` tuple — one collective over the product
        group either way."""
        return self.dp_axes if self.dp_axes is not None else self.axis_name

    @property
    def hier_plan(self) -> Optional[hs.HierarchicalSyncPlan]:
        """The :class:`~apex_tpu.contrib.optimizers._hierarchical_sync
        .HierarchicalSyncPlan` built at ``init`` (None on flat dp)."""
        return self._hier_plan

    def _param_dtype(self, bucket) -> jnp.dtype:
        if self.param_sync_dtype is not None:
            return self.param_sync_dtype
        return jnp.dtype(bucket.dtype)

    # ------------------------------------------------------------- init
    def _init_plan(self, params, world_size, param_specs, axis_sizes):
        if world_size is None:
            raise ValueError("pass world_size= (the dp axis size)")
        self._world = int(world_size)
        if self.dp_axes is not None:
            self._hier_plan = hs.hierarchical_plan(
                self.dp_axes, axis_sizes,
                grad_wire_dtype=self.grad_sync_dtype,
                param_wire_dtype=self.param_sync_dtype)
            if self._hier_plan.world != self._world:
                raise ValueError(
                    f"dp_axes={self.dp_axes!r} sizes "
                    f"{self._hier_plan.hop_sizes} multiply to "
                    f"{self._hier_plan.world}, but world_size="
                    f"{self._world}: the hierarchical split must cover "
                    "exactly the flat dp world (same 1/dp shards, same "
                    "padded_total formula)")
        if param_specs is not None:
            if axis_sizes is None:
                raise ValueError("param_specs requires axis_sizes")
            local_shapes, self._model_axes, self._leaf_repl = \
                local_leaf_info(params, param_specs, axis_sizes,
                                self.dp_axes or self.axis_name)
        else:
            local_shapes = [tuple(l.shape) for l in jax.tree.leaves(params)]
            self._model_axes, self._leaf_repl = (), None
        self._axis_sizes = dict(axis_sizes or {})
        self._model_mult = int(np.prod(
            [self._axis_sizes[ax] for ax in self._model_axes] or [1]))
        leaves, treedef = jax.tree.flatten(params)
        if self._leaf_repl is None:
            self._leaf_repl = [1] * len(leaves)
        if self.store_param_remainders:
            bad = [l.dtype for l in leaves if l.dtype != jnp.bfloat16]
            if bad:
                raise ValueError(
                    f"store_param_remainders requires bf16 params (got "
                    f"{bad[:3]}): the master's high 16 bits must BE the "
                    "param")
        self._plan = bucketing.plan_of_shapes(
            treedef,
            [(s, jnp.dtype(l.dtype).name) for s, l in zip(local_shapes, leaves)],
            cap_bytes=self._cap_bytes, shard_pad=self._world)
        self._param_spec_leaves = (
            treedef.flatten_up_to(param_specs) if param_specs is not None
            else None)
        # record-only uniformity seam: the bucket plan IS the step's
        # collective schedule (one reduce_scatter/all_gather pair per
        # bucket), so a per-process plan difference — divergent
        # cap_bytes from env, divergent world, divergent leaf shapes —
        # wedges the pod; check_uniform() names this tag instead
        from apex_tpu.resilience.uniformity import assert_uniform
        assert_uniform("zero.bucket_plan", self.plan_fingerprint())
        return self._plan

    def plan_fingerprint(self) -> dict:
        """The rank-uniformity identity of the sharding layout: every
        input that shapes the lowered collective schedule (bucket
        count/sizes/dtypes, the dp world, the hierarchical split) in a
        digestable dict — what ``assert_uniform('zero.bucket_plan')``
        records and what tests pin across processes."""
        plan = self._require_plan()
        hier = self._hier_plan
        return {
            "world": self._world,
            "cap_bytes": self._cap_bytes,
            "model_mult": self._model_mult,
            "hier": None if hier is None else
                [list(hier.shard_axes), *hier.hop_sizes],
            "buckets": [[b.dtype, b.size, b.total, len(b.leaves)]
                        for b in plan.buckets],
        }

    def _zero_slot(self, dtype=jnp.float32) -> Tuple[jnp.ndarray, ...]:
        """One zeroed state slot: a flat (model_mult · bucket_total,)
        array per bucket, to be sharded over (model axes…, dp)."""
        return tuple(jnp.zeros((self._model_mult * b.total,), dtype)
                     for b in self._plan.buckets)

    def _residual_slot(self) -> Tuple[jnp.ndarray, ...]:
        """The error-feedback residuals for quantized grad sync — or
        the empty tuple on wide wires (the residual field stays in the
        state NamedTuple with zero leaves, so specs/donation/pytree
        plumbing need no special case).

        Residuals are PER-RANK FULL-BUCKET (each rank quantizes the
        whole local gradient it contributes, so its error covers every
        element — the 1-bit-Adam/EF-SGD shape), stored in the bucket's
        STORAGE dtype: globally (model_mult · dp · total,) sharded over
        (model axes…, dp), i.e. each rank resides its own (total,)
        error vector — bucket-sized like one grad copy, not
        state-sized."""
        if not self._quantized:
            return ()
        return tuple(
            jnp.zeros((self._model_mult * self._world * b.total,),
                      jnp.dtype(b.dtype))
            for b in self._plan.buckets)

    def _master_slot(self, params) -> Tuple[jnp.ndarray, ...]:
        """The resident master: fp32 pack of every mesh rank's local
        leaf shards, model-major per bucket (the layout
        ``P((*model_axes, dp))`` slices back into exactly each rank's
        shard), or zeroed uint16 remainders (zero remainder ≡ the fp32
        extension of the bf16 param — no lazy init needed)."""
        if self.store_param_remainders:
            return self._zero_slot(jnp.uint16)
        plan = self._plan
        leaves = jax.tree.leaves(params)
        if self._param_spec_leaves is None:
            return tuple(jnp.asarray(a) for a in
                         bucketing.pack(plan, params, dtype=jnp.float32))
        combos = [dict(zip(self._model_axes, c)) for c in np.ndindex(
            *[self._axis_sizes[ax] for ax in self._model_axes])] or [{}]
        out = []
        for b in plan.buckets:
            segs = []
            for cmap in combos:
                parts = [
                    _leaf_shard_np(leaves[bl.leaf_id],
                                   self._param_spec_leaves[bl.leaf_id],
                                   cmap, self._axis_sizes)
                    .astype(np.float32).reshape(-1)
                    for bl in b.leaves
                ]
                seg = np.concatenate(parts) if parts else np.zeros(0, np.float32)
                segs.append(np.pad(seg, (0, b.total - seg.size)))
            out.append(jnp.asarray(np.concatenate(segs)))
        return tuple(out)

    def _flat_spec(self):
        from jax.sharding import PartitionSpec as P

        axes = getattr(self, "_model_axes", ())
        # hierarchical shard ownership: (inner, outer) partition order
        # places flat chunk i*dp_outer + o on mesh rank (o, i) — the
        # chunk the two-hop scatter delivers there, and the SAME global
        # chunk-per-rank layout the flat plan has
        dp = self._hier_plan.shard_axes if self._hier_plan is not None \
            else (self.axis_name,)
        flat = P((*axes, *dp)) if (axes or self._hier_plan is not None) \
            else P(self.axis_name)
        return tuple(flat for _ in self._require_plan().buckets)

    @property
    def world_size(self) -> Optional[int]:
        """The dp world this optimizer's plan/state were built for
        (None before ``init``).  The elastic controller
        (:mod:`apex_tpu.resilience.elastic`) compares this against the
        LIVE world before resharding a checkpoint — a mismatch at
        restore time means ``init`` ran for the wrong mesh and the
        bucket plan would disagree with the resharded state at first
        trace."""
        return getattr(self, "_world", None)

    def _require_plan(self) -> bucketing.BucketPlan:
        plan = getattr(self, "_plan", None)
        if plan is None:
            raise ValueError("call init() first: the shard layout (bucket "
                             "plan / total_numel) lives on the optimizer")
        return plan

    def state_partition_spec(self):
        """The shard_map / pjit PartitionSpec tree for the state: each
        bucket's flat array sharded jointly over (model axes…, dp) —
        model-major, matching the layout ``init`` builds.  The residual
        field shares the flat spec (its global arrays are dp-times
        longer, each rank residing its full-bucket error vector) and is
        the empty tuple on wide wires."""
        from jax.sharding import PartitionSpec as P

        flat = self._flat_spec()
        fields = {"step": P()}
        for f in [f for f in self._STATE_CLS._fields if f != "step"]:
            fields[f] = flat
        if "residual" in self._STATE_CLS._fields and not self._quantized:
            fields["residual"] = ()
        return self._STATE_CLS(**fields)

    # ---------------------------------------------------------- prepare
    def _check_state_shards(self, plan, slot, world, name):
        if len(slot) != len(plan.buckets):
            raise ValueError(
                f"optimizer state has {len(slot)} {name} buckets but the "
                f"param tree plans {len(plan.buckets)} (bucket_cap_mb or "
                "the param tree changed since this state was created — "
                "reshard it with load_sharded_state_dicts)")
        for arr, b in zip(slot, plan.buckets):
            if arr.shape[0] != b.total // world:
                raise ValueError(
                    f"{name} bucket shard has {arr.shape[0]} elements; the "
                    f"plan expects {b.total // world} (= {b.total}/dp={world})"
                    " — state saved at a different dp world size must be "
                    "resharded with load_sharded_state_dicts")

    def _check_master_precision(self, master_slot):
        """A state restored from a checkpoint saved in the OTHER master
        precision must fail with this message at trace time, never a
        shape/NoneType crash deep in the math: the bit patterns cannot
        be value-converted silently (uint16 remainders are mantissa
        bits, not numbers)."""
        want = jnp.dtype(jnp.uint16 if self.store_param_remainders
                         else jnp.float32)
        for arr in master_slot:
            if arr.dtype != want:
                have_kind = ("remainder_u16" if arr.dtype == jnp.uint16
                             else str(arr.dtype))
                raise ValueError(
                    f"master-precision mismatch: optimizer state holds "
                    f"{have_kind} master shards but this optimizer runs "
                    f"with store_param_remainders="
                    f"{self.store_param_remainders} (expects {want.name}); "
                    "a checkpoint saved in the other master precision "
                    "cannot be value-converted silently — construct the "
                    "optimizer with the matching store_param_remainders")

    def _pack_bucket(self, leaves, bucket, dtype, scale=None):
        """One bucket's concat in ``dtype`` (the grad read / the bf16
        param read of remainder mode) — per-BUCKET and in the sync
        dtype, never a whole-tree fp32 flatten."""
        return bucketing.pack_bucket(bucket, leaves, dtype, scale=scale)

    def _check_residual_state(self, plan, residuals) -> None:
        """The error-feedback residuals must exist exactly when the
        wire is quantized — a compressed checkpoint restored into an
        uncompressed optimizer (or vice versa) fails HERE, at trace
        time with the knob named, mirroring the remainder-master
        check."""
        n = len(residuals) if residuals is not None else 0
        if not self._quantized:
            if n:
                raise ValueError(
                    "optimizer state carries error-feedback residual "
                    "buckets but this optimizer's grad_sync_dtype="
                    f"{getattr(self.grad_sync_dtype, 'name', None)!r} is "
                    "not quantized: a compressed (int8/fp8) checkpoint "
                    "cannot be value-converted silently — construct the "
                    "optimizer with the matching grad_sync_dtype")
            return
        if n != len(plan.buckets):
            raise ValueError(
                f"grad_sync_dtype={self.grad_sync_dtype.name!r} needs one "
                f"error-feedback residual per bucket ({len(plan.buckets)}), "
                f"state has {n}: this state was saved by an uncompressed "
                "run (or a different bucket layout) — resume with the "
                "matching grad_sync_dtype or reshard with "
                "load_sharded_state_dicts")
        for arr, b in zip(residuals, plan.buckets):
            if arr.shape[0] != b.total:
                raise ValueError(
                    f"residual bucket holds {arr.shape[0]} elements; each "
                    f"rank resides its FULL local bucket ({b.total}) — "
                    "state saved at another world size must be resharded "
                    "with load_sharded_state_dicts")
            if arr.dtype != jnp.dtype(b.dtype):
                raise ValueError(
                    f"residual bucket dtype {arr.dtype} must match the "
                    f"bucket storage dtype {b.dtype} (the APX305 "
                    "contract: a narrower residual re-quantizes the "
                    "feedback)")

    def _dp_rank_world(self):
        """``(rank, world)`` of this shard_map instance on the dp
        group: flat ``axis_index``/``axis_size``, or the hierarchical
        Horner rank over the hop axes (fast-major — the SAME global
        chunk-per-rank layout the flat plan has, at any hop depth)."""
        hier = self._hier_plan
        if hier is not None:
            world = 1
            for s in hier.traced_sizes():
                world = world * s
            return hier.zero_rank(), world
        ax = self._dp_sync_axes
        return jax.lax.axis_index(ax), jax.lax.axis_size(ax)

    def bucket_grad_wire(self, b, leaves, scale=None, residual=None):
        """ONE bucket's gradient wire — the factored per-bucket body of
        :meth:`_prepare_grads`, public so the backward-overlapped step
        builders (``make_train_step(overlap_grad_sync=True)``) can
        issue it INSIDE the backward as soon as this bucket's leaf
        cotangents materialize, then hand the engine the results via
        ``_prepare_grads(presynced=...)``.

        ``leaves`` is the flat leaf list in plan order — only the
        entries named by ``b.leaves[*].leaf_id`` are read, so a caller
        mid-backward may pass a partially-filled list.  ``residual`` is
        this bucket's error-feedback state (required exactly when the
        wire is quantized).  Returns ``(g32_shard, new_residual,
        pre_wire)`` — the fp32 1/dp shard of the synced grad, and on
        quantized wires the UNCOMMITTED refreshed residual plus the
        fp32 pre-quantization bucket for the caller's finite vote
        (both ``None`` on wide wires).

        The ops and their order inside one bucket are IDENTICAL to the
        unoverlapped path — overlap only moves whole-bucket wires
        earlier in the trace, so fp32 results stay bitwise equal."""
        ax = self._dp_sync_axes
        hier = self._hier_plan
        rank, world = self._dp_rank_world()
        sdt = self._grad_dtype(b)
        spec = qs.qspec_of(sdt)
        if spec is not None:
            # quantized wire: unscale BEFORE quantizing (the residual
            # must be in loss-scale-free units — a scaler backoff
            # between steps must not re-weight carried error), add the
            # residual, quantize against the shared per-block scales,
            # reduce-scatter int8/fp8
            if residual is None:
                raise ValueError(
                    "bucket_grad_wire on a quantized wire needs this "
                    "bucket's error-feedback residual (state.residual[bi])")
            h = self._pack_bucket(
                leaves, b, jnp.float32,
                scale=(1.0 / scale) if scale is not None else None)
            h = h + residual.astype(jnp.float32)
            if hier is not None:
                g_sum, res_new = hs.quantized_multi_hop_reduce_scatter(
                    h, hier, spec)
            else:
                g_sum, res_new = qs.quantized_reduce_scatter(
                    h, ax, spec, rank, world)
            g32 = g_sum / world if self.grad_average else g_sum
            return g32, res_new.astype(jnp.dtype(b.dtype)), h
        # fp16 sync pre-divides (the reference's predivide: the
        # world-sized sum would overflow fp16's range); fp32/bf16
        # sync post-divides in fp32 — same association the
        # replicated path's psum-then-pmean takes, so ZeRO vs
        # replicated trajectories agree to the grad's own rounding
        predivide = (self.grad_average
                     and sdt == jnp.dtype(jnp.float16))
        bucket = self._pack_bucket(
            leaves, b, sdt, scale=(1.0 / world) if predivide else None)
        # ZeRO grad sync: each rank owns 1/dp of the dp-SUM — the
        # one collective read of this bucket's gradient (plain hops
        # fast-to-slow on a hierarchical mesh, same wire dtype each)
        if hier is not None:
            g_loc = hs.multi_hop_reduce_scatter(bucket, hier)
        else:
            g_loc = jax.lax.psum_scatter(bucket, ax,
                                         scatter_dimension=0,
                                         tiled=True)
        g32 = g_loc.astype(jnp.float32)
        if self.grad_average and not predivide:
            g32 = g32 / world
        if scale is not None:
            # loss-scale unscale AFTER the sync, in fp32: half-dtype
            # wires carry the scaled grads (no underflow), the math
            # sees unscaled fp32
            g32 = g32 * (1.0 / scale)
        return g32, None, None

    def _prepare_grads(self, plan, grads, scale, clip_norm, finite_sync,
                       want_finite, grads_finite, sumsq_reduce,
                       residuals=None, presynced=None):
        """The sharded grad read: per-bucket reduce-scatter in
        ``grad_sync_dtype`` (grad-average pre-division folded in — the
        reference's predivide, overflow-safe for large worlds), fp32
        unscale on the 1/dp shard, the all-finite vote, and the
        global-l2 clip with per-leaf Σx² recovered from the shards via
        the plan's static segment map.

        With a quantized wire the same single read additionally folds
        the error-feedback residual add (``h = g/scale + residual``),
        the shared-scale quantization, and the residual refresh — the
        wire carries int8/fp8 plus the small fp32 scale psum, and the
        UNSCALED error lives in the residual so loss-scale changes
        between steps cannot change its units.  Returns
        ``(g32_shards, new_residuals, pred, rank, world)`` —
        ``new_residuals`` is ``()`` on wide wires, UNCOMMITTED (the
        caller predicates it on the finite vote: a skipped step leaves
        residuals untouched).

        With ``dp_axes=(outer, inner)`` every dp collective here is the
        TWO-HOP form (:mod:`~apex_tpu.contrib.optimizers
        ._hierarchical_sync`): reduce-scatter intra-slice on the fast
        inner axis, then cross-slice on the slow outer axis at the same
        wire dtype — on quantized wires the partial sums requantize
        against fresh outer-shared scales and the requantization error
        folds into the SAME residual channel.

        ``presynced=(g_shards, new_residuals, pre_wire)`` is the
        backward-overlap handoff: the step builder already issued every
        bucket's wire (:meth:`bucket_grad_wire` inside the backward, in
        reverse-backward bucket order), so the wire loop is skipped and
        everything AFTER it — the finite vote, the clip, the telemetry
        — runs here unchanged on identical values (``grads`` may be
        ``None`` then)."""
        ax = self._dp_sync_axes
        rank, world = self._dp_rank_world()
        self._check_residual_state(plan, residuals)
        if presynced is not None:
            pre_g, pre_res, pre_h = presynced
            if len(pre_g) != len(plan.buckets):
                raise ValueError(
                    f"presynced carries {len(pre_g)} bucket shards; the "
                    f"plan has {len(plan.buckets)} buckets")
            g_shards = list(pre_g)
            new_residuals = [r for r in pre_res if r is not None]
            pre_wire = [h for h in pre_h if h is not None]
        else:
            leaves = jax.tree.leaves(grads)
            if len(leaves) != plan.n_leaves:
                raise ValueError(f"grad tree has {len(leaves)} leaves; plan "
                                 f"expects {plan.n_leaves}")
            g_shards = []
            new_residuals = []
            pre_wire = []  # fp32 pre-quantization buckets, for the vote
            for bi, b in enumerate(plan.buckets):
                g32, res_new, h = self.bucket_grad_wire(
                    b, leaves, scale=scale,
                    residual=residuals[bi] if self._quantized else None)
                g_shards.append(g32)
                if res_new is not None:
                    new_residuals.append(res_new)
                if h is not None:
                    # a non-finite grad quantizes to garbage the wire
                    # may MASK (nan -> int8 is finite): vote on the
                    # pre-quantization values, not just the shards
                    pre_wire.append(h)

        pred = grads_finite
        if want_finite:
            from apex_tpu.amp.scaler import all_finite

            finite = all_finite(list(g_shards) + pre_wire)
            if finite_sync is not None:
                # the caller's vote MUST include the ZeRO axis: shards
                # are dp-disjoint, so ranks can disagree (the gpt step
                # builders append dp to sync_axes for ZeRO optimizers)
                finite = finite_sync(finite)
            else:
                finite = jax.lax.pmin(finite.astype(jnp.int32),
                                      ax).astype(jnp.bool_)
            pred = finite

        if clip_norm is not None:
            from apex_tpu.optimizers.base import _clip_coef

            leaf_sq = self._per_leaf_sumsq(plan, g_shards, rank, world)
            leaf_sq = jax.lax.psum(leaf_sq, ax)  # assemble dp-disjoint shards
            total_sq = (sumsq_reduce([leaf_sq[i] for i in range(plan.n_leaves)])
                        if sumsq_reduce is not None else jnp.sum(leaf_sq))
            # the telemetry seam reuses the clip's globally agreed norm
            # (the observability.stepstats no-new-HBM-pass contract)
            _stepstats.offer("grad_norm", jnp.sqrt(total_sq))
            # ONE clip expression (torch semantics) with the replicated
            # engine — the two trajectories must not drift
            coef = _clip_coef(jnp.sqrt(total_sq), clip_norm)
            g_shards = [g * coef for g in g_shards]
        else:
            # no clip to reuse: the shared rank-local fold — no dp psum
            # (the stat must add zero collectives), so this is this
            # rank's 1/dp-shard norm, documented
            _stepstats.offer_local_grad_norm(g_shards)
        return g_shards, tuple(new_residuals), pred, rank, world

    def _commit_residuals(self, new_residuals, old_residuals, pred):
        """The residual commit, predicated like every other state slot:
        a skipped (non-finite) step leaves the carried error untouched
        — a nan must never poison the feedback channel."""
        if not self._quantized:
            return ()
        return tuple(self._select(pred, list(new_residuals),
                                  list(old_residuals)))

    def _per_leaf_sumsq(self, plan, shards, rank, world):
        """Per-ORIGINAL-leaf Σx² of per-bucket 1/dp shards, via the
        static segment map sliced to this rank's window (a dp shard
        does not align to leaf boundaries) — LOCAL partial sums; psum
        over dp (and model axes, per caller semantics) completes them."""
        out = jnp.zeros((plan.n_leaves,), jnp.float32)
        for bi, b in enumerate(plan.buckets):
            ids = jnp.asarray(bucketing.seg_ids(plan, b))
            shard = b.total // world
            ids_loc = jax.lax.dynamic_slice_in_dim(ids, rank * shard, shard)
            out = out + jax.ops.segment_sum(
                jnp.square(shards[bi]), ids_loc,
                num_segments=plan.n_leaves + 1)[:plan.n_leaves]
        return out

    def _owned_param_shards(self, plan, params, rank, world):
        """The rank's bf16 param shard per bucket (remainder mode's
        master reconstruction input): per-BUCKET bf16 concat + dynamic
        slice — bf16 traffic only, no fp32 up-cast."""
        leaves = jax.tree.leaves(params)
        out = []
        for b in plan.buckets:
            bucket = self._pack_bucket(leaves, b, jnp.bfloat16)
            shard = b.total // world
            out.append(jax.lax.dynamic_slice_in_dim(bucket, rank * shard,
                                                    shard))
        return out

    # ------------------------------------------------------------- emit
    def _emit_params(self, plan, shard_out, params, pred):
        """ZeRO param sync: one ``all_gather`` per bucket in
        ``param_sync_dtype``, sliced back into the leaf tree through the
        plan's offset table (static slices — never a whole-tree
        concat/flatten).

        ``shard_out`` is the UNCOMMITTED updated shard per bucket when
        ``overlap_param_sync`` (the gather starts without waiting for
        the finite vote; ``pred`` then selects per leaf against the old
        params), else the committed shard (``pred`` None here).

        On a hierarchical mesh the gather MIRRORS the two-hop scatter:
        outer (slow) hop first — the slice-shared shard, ``1/dp_inner``
        of the bucket crossing slices — then the inner (fast) hop."""
        ax = self.axis_name
        hier = self._hier_plan
        leaves = jax.tree.leaves(params)
        new_leaves: List[Optional[jnp.ndarray]] = [None] * plan.n_leaves
        for bi, b in enumerate(plan.buckets):
            shard = shard_out[bi].astype(self._param_dtype(b))
            if hier is not None:
                full = hs.two_hop_all_gather(shard, hier)
            else:
                full = jax.lax.all_gather(shard, ax, axis=0, tiled=True)
            for bl in b.leaves:
                leaf = jax.lax.slice(
                    full, (bl.offset,), (bl.offset + bl.size,)
                ).reshape(bl.shape).astype(leaves[bl.leaf_id].dtype)
                if pred is not None:
                    leaf = jnp.where(jnp.asarray(pred), leaf,
                                     leaves[bl.leaf_id])
                new_leaves[bl.leaf_id] = leaf
        return jax.tree.unflatten(plan.treedef, new_leaves)

    @staticmethod
    def _select(pred, new, old):
        if pred is None:
            return list(new)
        p = jnp.asarray(pred)
        return [jnp.where(p, n, o) for n, o in zip(new, old)]

    def _bias_corrections(self, step):
        return bias_corrections(step, self.bias_correction,
                                self.beta1, self.beta2)

    # ------------------------------------------------------- public API
    def update(self, grads, state, params, grads_finite=None, lr=None,
               clip_norm=None, sumsq_reduce=None, presynced=None):
        """One ZeRO step inside shard_map.  ``grads`` are this rank's
        LOCAL grads (the optimizer's reduce-scatter IS the dp gradient
        sync); ``grads_finite`` (already agreed across every axis)
        predicates the commit; ``clip_norm`` folds a global-l2 clip
        (torch semantics) into the sharded grad read with
        ``sumsq_reduce`` supplying the model-axes Σx² agreement.
        ``presynced`` hands over wires already issued inside the
        backward (:meth:`bucket_grad_wire`); ``grads`` may be None."""
        p, s, _ = self._zero_step(grads, state, params,
                                  grads_finite=grads_finite, lr=lr,
                                  clip_norm=clip_norm,
                                  sumsq_reduce=sumsq_reduce,
                                  want_finite=False, presynced=presynced)
        return p, s

    def update_scaled(self, grads, state, params, scale=None,
                      clip_norm=None, finite_sync=None, lr=None,
                      sumsq_reduce=None, presynced=None):
        """The fused amp step on the sharded grad read: per-bucket
        reduce-scatter, fp32 unscale of the 1/dp shard, the all-finite
        vote (``finite_sync`` must agree it over the model axes AND
        dp), optional global-l2 clip, predicated commit.  Returns
        ``(new_params, new_state, all_finite)``.  ``presynced`` hands
        over wires already issued inside the backward
        (:meth:`bucket_grad_wire`); ``grads``/``scale`` consumed there."""
        return self._zero_step(grads, state, params, scale=scale,
                               clip_norm=clip_norm, finite_sync=finite_sync,
                               lr=lr, sumsq_reduce=sumsq_reduce,
                               want_finite=True, presynced=presynced)

    def step(self, grads, state, params, **kw):
        return self.update(grads, state, params, **kw)

    def _zero_step(self, grads, state, params, grads_finite=None, lr=None,
                   scale=None, clip_norm=None, finite_sync=None,
                   sumsq_reduce=None, want_finite=False, presynced=None):
        raise NotImplementedError  # pragma: no cover - abstract

    # ----------------------------------------------------- state dicts
    #: v3 adds the error-feedback residual buckets (full local bucket
    #: per rank, storage dtype) + ``residual_kind`` metadata.  v2
    #: (pre-quantization) checkpoints still load — into uncompressed
    #: optimizers only.
    SHARD_FORMAT = "apex_tpu_zero2_v3"
    _READ_FORMATS = ("apex_tpu_zero2_v2", "apex_tpu_zero2_v3")

    @property
    def _master_kind(self) -> str:
        return "remainder_u16" if self.store_param_remainders else "fp32"

    @property
    def _residual_kind(self) -> str:
        """``"ef"`` when the quantized wire carries error-feedback
        residual state, ``"none"`` otherwise — the save/restore
        compatibility key (mirrors ``master_kind``)."""
        return "ef" if self._quantized else "none"

    def _check_residual_kind(self, d) -> None:
        kind = d.get("residual_kind")
        if kind is None:  # v2 checkpoints never carried residuals
            kind = "none"
        if kind != self._residual_kind:
            have = ("a compressed (error-feedback) checkpoint"
                    if kind == "ef" else "an uncompressed checkpoint")
            raise ValueError(
                f"checkpoint residual_kind {kind!r} does not match this "
                f"optimizer's ({self._residual_kind!r}): {have} cannot "
                "restore into an optimizer whose grad_sync_dtype="
                f"{getattr(self.grad_sync_dtype, 'name', None)!r} — "
                "construct the optimizer with the matching "
                "grad_sync_dtype (quantized <-> not is a state-layout "
                "change, like store_param_remainders)")

    def _check_master_kind(self, d):
        """A store_param_remainders mismatch between save and load would
        value-convert master bit patterns silently — refuse instead."""
        kind = d.get("master_kind")
        if kind is None:  # pre-remainder checkpoints were always fp32
            kind = "fp32"
        if kind != self._master_kind:
            raise ValueError(
                f"checkpoint master_kind {kind!r} does not match this "
                f"optimizer's ({self._master_kind!r}): set "
                f"store_param_remainders={kind == 'remainder_u16'}")

    def _bucket_meta(self):
        plan = self._require_plan()
        return [{"dtype": b.dtype, "size": b.size, "total": b.total}
                for b in plan.buckets]

    def wire_bytes_per_step(self) -> Dict[str, Any]:
        """Static per-step wire accounting off the bucket plan — what
        the ``zero_gpt124`` bench reports per sync mode:

        - ``grad_payload``: Σ bucket totals × the grad wire itemsize
          (1 B for int8/fp8), summed over every hop;
        - ``grad_scales``: the quantized wires' fp32 per-block scale
          psums (0 on wide wires), one per hop — counted so the
          reported cut is honest (int8 ≈ 2x vs bf16, ≈ 4x vs fp32,
          minus ~0.4% scales);
        - ``grad_sync`` = payload + scales; ``param_sync``: the
          all-gather payload in ``param_sync_dtype``; ``total``;
        - ``hops``: the PER-HOP split ``{axis: {grad_payload,
          grad_scales, grad_sync, param_sync, total}}`` — one entry
          (the flat dp axis) on a flat plan, ``{inner, outer}`` axes on
          a hierarchical one.  The slow (outer/cross-slice) hop's entry
          is the bench's ``cross_slice_wire_cut`` numerator input:
          exactly ``1/dp_inner`` of the flat plan's bytes at equal wire
          dtype, scales included."""
        plan = self._require_plan()
        hier = self._hier_plan
        hops: Dict[str, Dict[str, int]] = {}

        def add(hop, key, n):
            d = hops.setdefault(hop, {"grad_payload": 0, "grad_scales": 0,
                                      "param_sync": 0})
            d[key] += n

        for b in plan.buckets:
            for hop, hb in qs.grad_sync_bytes(
                    b.total, self._grad_dtype(b), hier=hier,
                    flat_hop=self.axis_name).items():
                add(hop, "grad_payload", hb["payload"])
                add(hop, "grad_scales", hb["scales"])
            p_item = self._param_dtype(b).itemsize
            if hier is not None:
                # mirrored gathers: the fast hop reassembles the full
                # bucket, each slower hop moves the chunk already
                # scattered by every faster hop (three-level: the dcn
                # hop carries exactly 1/(dp_in*dp_out) of the bucket)
                n = b.total
                for axis, size in zip(reversed(hier.hop_axes),
                                      reversed(hier.hop_sizes)):
                    add(axis, "param_sync", n * p_item)
                    n //= max(size, 1)
            else:
                add(self.axis_name, "param_sync", b.total * p_item)

        for d in hops.values():
            d["grad_sync"] = d["grad_payload"] + d["grad_scales"]
            d["total"] = d["grad_sync"] + d["param_sync"]
        out: Dict[str, Any] = {
            k: sum(d[k] for d in hops.values())
            for k in ("grad_payload", "grad_scales", "grad_sync",
                      "param_sync", "total")}
        out["hops"] = hops
        return out

    def sync_plan_hops(self):
        """Per-``(bucket, hop)`` wire records — the trace-side spelling
        of :meth:`wire_bytes_per_step` (``tracing.emit_sync_plan``
        emits one ``zero_sync.bucket<k>.hop_<axis>`` marker per record,
        so span duration ÷ hop bytes bounds the per-hop achieved
        bandwidth).  One record per bucket on a flat plan, two (inner,
        outer) on a hierarchical one."""
        plan = self._require_plan()
        hier = self._hier_plan
        out = []
        for i, b in enumerate(plan.buckets):
            hop_bytes = qs.grad_sync_bytes(
                b.total, self._grad_dtype(b), hier=hier,
                flat_hop=self.axis_name)
            for hop, hb in hop_bytes.items():
                out.append({
                    "bucket": i, "hop": hop,
                    "bucket_dtype": str(jnp.dtype(b.dtype)),
                    "wire_dtype": str(jnp.dtype(self._grad_dtype(b))),
                    "payload_bytes": int(hb["payload"]),
                    "scale_bytes": int(hb["scales"]),
                })
        return out

    def _state_arrays(self, state) -> Dict[str, Sequence]:
        """name -> per-bucket arrays, in the subclass's field order."""
        return {f: getattr(state, f) for f in state._fields if f != "step"}

    def state_dict(self, state):
        """Whole-state dict (the reference's ``gather_on_root=True``
        mode, distributed_fused_adam.py:2527).  For the per-rank
        protocol use :meth:`sharded_state_dict`."""
        d = {
            "format": self.SHARD_FORMAT,
            "step": int(state.step),
            "master_kind": self._master_kind,
            "residual_kind": self._residual_kind,
            "buckets": self._bucket_meta(),
        }
        for name, slot in self._state_arrays(state).items():
            d[name] = [np.asarray(a) for a in slot]
        return d

    #: the state NamedTuple class (subclasses set it)
    _STATE_CLS = None

    def load_state_dict(self, d):
        fmt = d.get("format")
        fmt = np.asarray(fmt).item() if isinstance(fmt, np.ndarray) else fmt
        if fmt not in self._READ_FORMATS:
            # a pre-bucket (v1 flat-array) dict would otherwise iterate
            # its flat slot into thousands of 0-d scalars and fail later
            # with a misleading bucket-layout error
            raise ValueError(
                f"unrecognized state_dict format {fmt!r}: this optimizer "
                f"reads {self._READ_FORMATS} (per-bucket arrays); "
                "pre-bucket-plan (flat v1) checkpoints cannot be loaded")
        self._check_master_kind(d)
        self._check_residual_kind(d)
        fields = {"step": jnp.int32(d["step"])}
        for f in [f for f in self._STATE_CLS._fields if f != "step"]:
            # ONLY residual may be absent (v2 dicts predate it; empty
            # on wide wires) — a missing m/v/master slot is corruption
            # and must stay a loud KeyError here, not a misleading
            # bucket-layout error at first trace
            src = d.get(f, ()) if f == "residual" else d[f]
            fields[f] = tuple(jnp.asarray(a) for a in src)
        return self._STATE_CLS(**fields)

    def sharded_state_dict(self, state, rank: int, world_size: int):
        """Per-rank shard of the state + the layout metadata needed to
        reshard on load (reference ``state_dict(gather_on_root=False)``,
        distributed_fused_adam.py:2527; redistribution :2959).  Each
        bucket's piece is ``(model_mult, shard)`` — the model segments
        kept separate so a dp=4 save reshard-loads at dp=2 without
        scrambling the model-major layout."""
        plan = self._require_plan()
        if world_size != self._world:
            raise ValueError(
                f"state was built for dp={self._world}; sharded_state_dict "
                f"slices that layout (got world_size={world_size})")
        d = {
            "format": self.SHARD_FORMAT,
            "master_kind": self._master_kind,
            "residual_kind": self._residual_kind,
            "rank": int(rank),
            "world_size": int(world_size),
            "model_mult": self._model_mult,
            "step": int(state.step),
            "buckets": self._bucket_meta(),
            "total_numel": int(sum(b.size for b in plan.buckets)),
        }
        for name, slot in self._state_arrays(state).items():
            pieces = []
            for arr, b in zip(slot, plan.buckets):
                if name == "residual":
                    # each rank resides its FULL local bucket: the
                    # global layout is (model_mult, world, total) and
                    # rank r's piece is the (model_mult, total) block
                    a = np.asarray(arr).reshape(
                        self._model_mult, world_size, b.total)
                    pieces.append(a[:, rank, :].copy())
                    continue
                shard = b.total // world_size
                a = np.asarray(arr).reshape(self._model_mult, b.total)
                pieces.append(a[:, rank * shard:(rank + 1) * shard].copy())
            d[name] = pieces
        return d

    #: sentinel: "caller did not say" (None is a meaningful value — an
    #: uncompressed optimizer)
    _UNSPECIFIED = object()

    @classmethod
    def load_sharded_state_dicts(cls, shards, world_size: int,
                                 store_param_remainders: Optional[bool] = None,
                                 grad_sync_dtype=_UNSPECIFIED):
        """Reassemble a full state from per-rank shard dicts and reshard
        it for ``world_size`` ranks (which may differ from the saved
        world — save at dp=4, load at dp=2): per bucket and per model
        segment, concat the saved dp slices, trim to the payload, and
        re-pad with the plan's own formula
        (:func:`bucketing.padded_total`) for the new world.

        Error-feedback residuals (quantized grad sync, format v3)
        reshard with the SAME pad formula: at the saved world size each
        rank's full-bucket residual round-trips bitwise; at a different
        world size the per-rank errors are summed into the new rank
        0's residual (zeros elsewhere) — what the optimizer trajectory
        sees is ``Σ_r (g_r + residual_r)``, so the sum-collapse
        preserves the carried error exactly while the per-rank
        attribution (which no longer exists) is dropped.

        Pass ``grad_sync_dtype=`` to assert the target optimizer's wire
        up front (mirrors ``store_param_remainders``): a compressed
        checkpoint refuses to reshard for an uncompressed optimizer and
        vice versa."""
        def _py(v):
            """io round-trips scalars/strings as 0-d numpy arrays —
            coerce metadata back to python before comparisons."""
            v = np.asarray(v).item() if isinstance(v, np.ndarray) else v
            return v

        skip = set(cls._STATE_CLS._fields) | {"buckets"}
        shards = [{k: _py(v) if k not in skip else v
                   for k, v in d.items()} for d in shards]
        for d in shards:
            d["buckets"] = [{k: _py(v) for k, v in bm.items()}
                            for bm in d["buckets"]]
        shards = sorted(shards, key=lambda d: d["rank"])
        if not shards:
            raise ValueError("no shards given")
        meta = shards[0]
        if meta.get("format") not in cls._READ_FORMATS:
            raise ValueError(
                f"unrecognized shard format {meta.get('format')!r} (pre-"
                f"bucket-plan checkpoints cannot be resharded by this "
                "version)")
        saved_world = meta["world_size"]
        if [d["rank"] for d in shards] != list(range(saved_world)):
            raise ValueError(
                f"incomplete shard set: got ranks {[d['rank'] for d in shards]}, "
                f"saved world size is {saved_world}")
        for d in shards:
            for key in ("model_mult", "total_numel", "step", "world_size"):
                if d[key] != meta[key]:
                    raise ValueError(f"shard {d['rank']} disagrees on {key}")
            for kind_key, default in (("master_kind", "fp32"),
                                      ("residual_kind", "none")):
                if d.get(kind_key, default) != meta.get(kind_key, default):
                    raise ValueError(
                        f"shard {d['rank']} disagrees on {kind_key}")
        if store_param_remainders is not None:
            want = "remainder_u16" if store_param_remainders else "fp32"
            got = meta.get("master_kind", "fp32")
            if got != want:
                raise ValueError(
                    f"checkpoint master_kind {got!r} does not match "
                    f"store_param_remainders={store_param_remainders}")
        res_kind = meta.get("residual_kind", "none")
        if grad_sync_dtype is not cls._UNSPECIFIED:
            resolved = resolve_sync_dtype(grad_sync_dtype, "grad_sync_dtype")
            want_kind = "ef" if qs.is_quantized(resolved) else "none"
            if res_kind != want_kind:
                raise ValueError(
                    f"checkpoint residual_kind {res_kind!r} does not match "
                    f"grad_sync_dtype={getattr(resolved, 'name', None)!r}: "
                    "compressed (error-feedback) and uncompressed states "
                    "cannot be value-converted silently")

        mm = meta["model_mult"]
        buckets = meta["buckets"]
        fields = {"step": jnp.int32(meta["step"])}
        state_cls = cls._STATE_CLS
        for name in [f for f in state_cls._fields if f != "step"]:
            if name == "residual":
                fields[name] = cls._reshard_residuals(
                    shards, meta, world_size) if res_kind == "ef" else ()
                continue
            out = []
            for bi, bm in enumerate(buckets):
                # (model_mult, saved_total) from the saved dp slices
                full = np.concatenate([d[name][bi] for d in shards], axis=1)
                payload = full[:, :bm["size"]]
                new_total = bucketing.padded_total(
                    bm["size"], bm["dtype"], world_size)
                padded = np.zeros((mm, new_total), payload.dtype)
                padded[:, :bm["size"]] = payload
                out.append(jnp.asarray(padded.reshape(-1)))
            fields[name] = tuple(out)
        return state_cls(**fields)

    @classmethod
    def _reshard_residuals(cls, shards, meta, world_size: int):
        """Residual buckets for the new world (see
        :meth:`load_sharded_state_dicts`): bitwise per-rank restore at
        the saved world, trajectory-sum-preserving collapse onto the
        new rank 0 otherwise.  Pads with the ONE
        :func:`bucketing.padded_total` formula."""
        mm = meta["model_mult"]
        saved_world = meta["world_size"]
        out = []
        for bi, bm in enumerate(meta["buckets"]):
            pieces = [np.asarray(d["residual"][bi]) for d in shards]
            new_total = bucketing.padded_total(
                bm["size"], bm["dtype"], world_size)
            new = np.zeros((mm, world_size, new_total), pieces[0].dtype)
            if world_size == saved_world:
                for r, piece in enumerate(pieces):
                    new[:, r, :bm["size"]] = piece[:, :bm["size"]]
            else:
                summed = sum(p[:, :bm["size"]].astype(np.float32)
                             for p in pieces)
                new[:, 0, :bm["size"]] = summed.astype(pieces[0].dtype)
            out.append(jnp.asarray(new.reshape(-1)))
        return tuple(out)
