"""DistributedFusedAdam — ZeRO-2 optimizer-state sharding over ``dp``.

Reference: ``apex/contrib/optimizers/distributed_fused_adam.py:266``
(3,078 LoC): params flattened into fixed-size buckets; optimizer state
sharded over the process grid; reduce-scatter grad sync overlapped with
backward; all-gather param sync optionally overlapped with forward
(``ParameterFragment``/``StateBucket`` dataclasses :370-504, ``step``
:2158).

TPU-native collapse of that machinery:

- the *bucketing* (fixed-size flat buffers, fragment maps) exists to
  batch NCCL calls and kernel launches; XLA needs neither — one
  ``psum_scatter`` on the concatenated grads and one ``all_gather`` on
  the updated flat params, with overlap scheduled by the compiler;
- the *sharding grid* (distributed_process_group × redundant_process_
  group) is the ``dp`` mesh axis (a redundant axis would map to a
  second mesh axis with ``psum`` — multi-slice DCN deployments);
- optimizer state (m, v, fp32 master) lives ONLY for the local 1/dp
  shard — the ZeRO-2 memory saving;
- Adam math is exactly :class:`apex_tpu.optimizers.FusedAdam`'s
  (AdamFunctor numerics), applied to the local shard, step predicated on
  the synced finite flag.

Use inside ``shard_map`` with params replicated over ``dp``.
"""

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.transformer.parallel_state import DATA_AXIS


class DistributedFusedAdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg: jnp.ndarray  # (local_shard,) fp32
    exp_avg_sq: jnp.ndarray  # (local_shard,) fp32
    # fp32 master of owned params — or, with store_param_remainders, the
    # low 16 bits (uint16) the bf16 param is missing
    master_shard: jnp.ndarray


def _master_from_remainder(p_f32, rem_u16):
    """Exact fp32 master = (bf16 param bits << 16) | remainder.

    ``p_f32`` is the f32 *extension* of the bf16 param, whose low 16
    mantissa bits are zero by construction — OR-ing in the remainder
    reconstructs the master bit-exactly (reference
    distributed_fused_adam.py ``store_param_remainders``)."""
    bits = jax.lax.bitcast_convert_type(p_f32, jnp.uint32)
    return jax.lax.bitcast_convert_type(bits | rem_u16.astype(jnp.uint32), jnp.float32)


def _split_master(master_f32):
    """(bf16 param, uint16 remainder): the bf16 the model sees is the
    master's high 16 bits.

    Truncation is THIS repo's convention (chosen so reconstruction is a
    plain bitwise OR).  The reference instead stores signed int16
    remainders and rounds the bf16 to nearest
    (multi_tensor_distopt_adam_kernel.cu:295-312), so remainder-mode
    bf16 params here can differ by up to 1 ulp (toward zero) from both
    the reference and this repo's fp32-master mode (which RNE-casts).
    The fp32 master — what the optimizer actually integrates — is
    bit-exact either way."""
    bits = jax.lax.bitcast_convert_type(master_f32, jnp.uint32)
    rem = (bits & jnp.uint32(0xFFFF)).astype(jnp.uint16)
    p_bf16 = jax.lax.bitcast_convert_type((bits >> 16).astype(jnp.uint16), jnp.bfloat16)
    return p_bf16, rem


def _flatten(tree):
    leaves = jax.tree.leaves(tree)
    flat = jnp.concatenate([l.reshape(-1).astype(jnp.float32) for l in leaves])
    return flat


def local_total_and_axes(params, param_specs, axis_sizes, zero_axis):
    """(local_total_numel, model_axes, leaf_repl): per-device param
    count when ``params`` are sharded over model-parallel mesh axes per
    ``param_specs``, the sorted tuple of those axes, and — per leaf —
    the replication factor a psum over ``model_axes`` over-counts it by
    (1 for fully sharded leaves).  Raises if any param is sharded over
    the ZeRO axis itself."""
    total = 0
    used_axes = []
    leaves, treedef = jax.tree.flatten(params)
    spec_leaves = treedef.flatten_up_to(param_specs)
    leaf_axes = []
    for leaf, spec in zip(leaves, spec_leaves):
        n = int(np.prod(leaf.shape))
        axes_here = set()
        for dim, entry in enumerate(tuple(spec)):
            dim_axes = tuple(
                ax for ax in (entry if isinstance(entry, tuple) else (entry,))
                if ax is not None
            )
            if not dim_axes:
                continue
            for ax in dim_axes:
                if ax == zero_axis:
                    raise ValueError(
                        f"params must not be sharded over the ZeRO axis {ax!r}"
                    )
            shard = int(np.prod([axis_sizes[ax] for ax in dim_axes]))
            # the check must be per-DIMENSION: a divisible total with an
            # indivisible sharded dim (e.g. (13, 5) split 5-way on dim 0)
            # still pads/misaligns the flat layout
            if leaf.shape[dim] % shard != 0:
                raise ValueError(
                    f"param dim {dim} of shape {leaf.shape} is not divisible "
                    f"by mesh axes {dim_axes!r} (total size {shard}); the "
                    "flat ZeRO layout would silently misalign"
                )
            n //= shard
            for ax in dim_axes:
                axes_here.add(ax)
                if ax not in used_axes:
                    used_axes.append(ax)
        leaf_axes.append(axes_here)
        total += n
    model_axes = tuple(sorted(used_axes))
    # replication factor per leaf: a psum over model_axes counts a leaf
    # replicated over an axis once PER rank of that axis — norm math
    # must divide its contribution back out
    repl = [
        int(np.prod([axis_sizes[ax] for ax in model_axes if ax not in s] or [1]))
        for s in leaf_axes
    ]
    return total, model_axes, repl


def _unflatten_into(tree, flat):
    leaves, treedef = jax.tree.flatten(tree)
    out = []
    off = 0
    for l in leaves:
        n = int(np.prod(l.shape))
        out.append(flat[off : off + n].reshape(l.shape).astype(l.dtype))
        off += n
    return jax.tree.unflatten(treedef, out)


class DistributedFusedAdam:
    """ZeRO-2 AdamW with the reference's constructor vocabulary."""

    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        adam_w_mode: bool = True,
        weight_decay: float = 0.0,
        axis_name: str = DATA_AXIS,
        grad_average: bool = True,
        # accepted-for-parity knobs (overlap is XLA's):
        overlap_grad_sync: bool = True,
        overlap_param_sync: bool = False,
        bucket_cap_mb: float = 100.0,
        dtype=jnp.float32,
        grad_sync_dtype=None,
        param_sync_dtype=None,
        process_group=None,
        distributed_process_group=None,
        redundant_process_group=None,
        store_param_remainders: bool = False,
    ):
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self.axis_name = axis_name
        self.grad_average = grad_average
        # halve master-weight memory for bf16 params: store only the 16
        # mantissa bits the bf16 param is missing (reference
        # ``store_param_remainders``); param sync also all-gathers bf16
        # instead of fp32 (half the traffic)
        self.store_param_remainders = store_param_remainders

    # -------------------------------------------------------------- helpers
    def _total_and_pad(self, params):
        total = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
        return total

    def init(self, params, world_size: Optional[int] = None, param_specs=None,
             axis_sizes=None) -> DistributedFusedAdamState:
        """Build the GLOBAL flat state: arrays of shape (padded_total,),
        to be sharded over ``dp`` via :meth:`state_partition_spec` so
        each rank holds its 1/dp shard (the ZeRO memory saving comes
        from the sharding, stated explicitly rather than via per-device
        local arrays).  The fp32 master is lazily sliced from params on
        the first update (step==0).

        **Composition with tensor parallelism**: when ``params`` are
        themselves sharded over model-parallel mesh axes, pass
        ``param_specs`` (the PartitionSpec tree used for the params) and
        ``axis_sizes`` (mapping axis name → mesh size).  The state is
        then sized for the *local* param shard and additionally sharded
        over those model axes — each (tp, dp) device holds the dp-shard
        of the optimizer state for its tp-slice of the params.
        """
        if world_size is None:
            raise ValueError("pass world_size= (the dp axis size)")
        self._model_axes: Tuple[str, ...] = ()
        model_mult = 1
        if param_specs is not None:
            if axis_sizes is None:
                raise ValueError("param_specs requires axis_sizes")
            total, self._model_axes, _ = local_total_and_axes(
                params, param_specs, axis_sizes, self.axis_name
            )
            for ax in self._model_axes:
                model_mult *= axis_sizes[ax]
        else:
            total = self._total_and_pad(params)
        padded = ((total + world_size - 1) // world_size) * world_size
        self._total = total
        self._padded = padded
        self._world = world_size
        if self.store_param_remainders:
            bad = [
                l.dtype for l in jax.tree.leaves(params) if l.dtype != jnp.bfloat16
            ]
            if bad:
                raise ValueError(
                    f"store_param_remainders requires bf16 params (got {bad[:3]}): "
                    "the master's high 16 bits must BE the param"
                )
        zeros = jnp.zeros((model_mult * padded,), jnp.float32)
        master0 = (
            jnp.zeros((model_mult * padded,), jnp.uint16)
            if self.store_param_remainders
            else zeros
        )
        return DistributedFusedAdamState(
            step=jnp.int32(0), exp_avg=zeros, exp_avg_sq=zeros, master_shard=master0
        )

    def state_partition_spec(self):
        """The shard_map / pjit PartitionSpec tree for the state.  With
        model-parallel composition (``init(param_specs=...)``) the flat
        axis is sharded jointly over (model axes..., dp) — model-major,
        matching the layout :meth:`init` builds."""
        from jax.sharding import PartitionSpec as P

        axes = getattr(self, "_model_axes", ())
        flat = P((*axes, self.axis_name)) if axes else P(self.axis_name)
        return DistributedFusedAdamState(
            step=P(), exp_avg=flat, exp_avg_sq=flat, master_shard=flat,
        )

    def update(self, grads, state: DistributedFusedAdamState, params, grads_finite=None, lr=None):
        """One ZeRO-2 step (inside shard_map, params/grads replicated or
        dp-identical).  Returns (new_params, new_state)."""
        lr = self.lr if lr is None else lr
        ax = self.axis_name
        world = jax.lax.axis_size(ax)
        rank = jax.lax.axis_index(ax)
        b1, b2, eps, wd = self.beta1, self.beta2, self.eps, self.weight_decay

        flat_g = _flatten(grads)
        total = flat_g.shape[0]
        padded = ((total + world - 1) // world * world) if total % world else total
        if padded != total:
            flat_g = jnp.pad(flat_g, (0, padded - total))
        shard = padded // world

        # ZeRO grad sync: reduce-scatter — each rank owns one shard
        g_local = jax.lax.psum_scatter(flat_g, ax, scatter_dimension=0, tiled=True)
        if self.grad_average:
            g_local = g_local / world

        flat_p = _flatten(params)
        if padded != total:
            flat_p = jnp.pad(flat_p, (0, padded - total))
        p_owned = jax.lax.dynamic_slice_in_dim(flat_p, rank * shard, shard)
        if self.store_param_remainders:
            # master ≡ (bf16 param bits | stored remainder); zero
            # remainders (fresh state) reconstruct exactly the fp32
            # extension of the params — no separate lazy-init needed
            master = _master_from_remainder(p_owned, state.master_shard)
        else:
            # lazily materialize the fp32 master shard from params on step 0
            master = jnp.where(state.step == 0, p_owned, state.master_shard)

        step = state.step + (
            jnp.asarray(grads_finite).astype(jnp.int32) if grads_finite is not None else 1
        )
        t = step.astype(jnp.float32)
        if self.bias_correction:
            bc1 = 1.0 - jnp.power(b1, t)
            bc2 = 1.0 - jnp.power(b2, t)
        else:
            bc1 = bc2 = jnp.float32(1.0)

        g = g_local
        if not self.adam_w_mode:
            g = g + wd * master
        m_new = b1 * state.exp_avg + (1.0 - b1) * g
        v_new = b2 * state.exp_avg_sq + (1.0 - b2) * g * g
        update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
        if self.adam_w_mode:
            update = update + wd * master
        master_new = master - lr * update

        if grads_finite is not None:
            pred = jnp.asarray(grads_finite)
            m_new = jnp.where(pred, m_new, state.exp_avg)
            v_new = jnp.where(pred, v_new, state.exp_avg_sq)
            master_new = jnp.where(pred, master_new, master)

        if self.store_param_remainders:
            # param = master's high bits (truncation); sync bf16 — half
            # the all-gather traffic of the fp32 path
            p_bf16, rem_new = _split_master(master_new)
            flat_new = jax.lax.all_gather(p_bf16, ax, axis=0, tiled=True)
            new_params = _unflatten_into(params, flat_new[:total])
            return new_params, DistributedFusedAdamState(
                step=step, exp_avg=m_new, exp_avg_sq=v_new, master_shard=rem_new
            )

        # ZeRO param sync: all-gather the updated shards
        flat_new = jax.lax.all_gather(master_new, ax, axis=0, tiled=True)
        new_params = _unflatten_into(params, flat_new[:total])

        return new_params, DistributedFusedAdamState(
            step=step, exp_avg=m_new, exp_avg_sq=v_new, master_shard=master_new
        )

    # ----------------------------------------------------- state dict parity
    SHARD_FORMAT = "apex_tpu_zero2_v1"

    @property
    def _master_kind(self) -> str:
        return "remainder_u16" if self.store_param_remainders else "fp32"

    def _check_master_kind(self, d):
        """A store_param_remainders mismatch between save and load would
        value-convert master bit patterns silently — refuse instead."""
        kind = d.get("master_kind")
        if kind is None:  # pre-remainder checkpoints were always fp32
            kind = "fp32"
        if kind != self._master_kind:
            raise ValueError(
                f"checkpoint master_kind {kind!r} does not match this "
                f"optimizer's ({self._master_kind!r}): set "
                f"store_param_remainders={kind == 'remainder_u16'}"
            )

    def state_dict(self, state: DistributedFusedAdamState):
        """Whole-state dict (the reference's ``gather_on_root=True`` mode,
        distributed_fused_adam.py:2527).  For the per-rank protocol use
        :meth:`sharded_state_dict`."""
        return {
            "step": int(state.step),
            "master_kind": self._master_kind,
            "exp_avg": np.asarray(state.exp_avg),
            "exp_avg_sq": np.asarray(state.exp_avg_sq),
            "master_shard": np.asarray(state.master_shard),
        }

    def load_state_dict(self, d) -> DistributedFusedAdamState:
        self._check_master_kind(d)
        return DistributedFusedAdamState(
            step=jnp.int32(d["step"]),
            exp_avg=jnp.asarray(d["exp_avg"]),
            exp_avg_sq=jnp.asarray(d["exp_avg_sq"]),
            master_shard=jnp.asarray(d["master_shard"]),
        )

    def sharded_state_dict(self, state: DistributedFusedAdamState, rank: int,
                           world_size: int, total_numel: Optional[int] = None):
        """Per-rank shard of the state + the layout metadata needed to
        reshard on load (reference ``state_dict(gather_on_root=False)``
        saves each rank's fragments, distributed_fused_adam.py:2527;
        ``load_state_dict`` redistributes them :2959).

        ``total_numel`` is the UNPADDED parameter count; defaults to the
        value recorded by :meth:`init`.  It is what lets a checkpoint
        saved at dp=4 be re-padded for dp=2.
        """
        if total_numel is None:
            total_numel = getattr(self, "_total", None)
        if total_numel is None:
            raise ValueError(
                "pass total_numel= (or call init() first): resharding needs "
                "the unpadded parameter count"
            )
        padded = int(state.exp_avg.shape[0])
        if padded % world_size:
            raise ValueError(f"state length {padded} not divisible by world {world_size}")
        shard = padded // world_size
        sl = slice(rank * shard, (rank + 1) * shard)
        return {
            "format": self.SHARD_FORMAT,
            "master_kind": self._master_kind,
            "rank": int(rank),
            "world_size": int(world_size),
            "padded_total": padded,
            "shard_numel": shard,
            "total_numel": int(total_numel),
            "step": int(state.step),
            "exp_avg": np.asarray(state.exp_avg[sl]),
            "exp_avg_sq": np.asarray(state.exp_avg_sq[sl]),
            "master_shard": np.asarray(state.master_shard[sl]),
        }

    @classmethod
    def load_sharded_state_dicts(cls, shards, world_size: int,
                                 store_param_remainders: Optional[bool] = None
                                 ) -> DistributedFusedAdamState:
        """Reassemble a full state from per-rank shard dicts and reshard
        it for ``world_size`` ranks (which may differ from the saved
        world size — save at dp=4, load at dp=2).

        ``shards``: the complete set of shard dicts from one checkpoint,
        any order.  Returns the global flat state padded for the NEW
        world size; shard it with :meth:`state_partition_spec` as usual.
        """
        shards = sorted(shards, key=lambda d: d["rank"])
        if not shards:
            raise ValueError("no shards given")
        meta = shards[0]
        if meta.get("format") != cls.SHARD_FORMAT:
            raise ValueError(f"unrecognized shard format {meta.get('format')!r}")
        saved_world = meta["world_size"]
        if [d["rank"] for d in shards] != list(range(saved_world)):
            raise ValueError(
                f"incomplete shard set: got ranks {[d['rank'] for d in shards]}, "
                f"saved world size is {saved_world}"
            )
        for d in shards:
            for key in ("padded_total", "total_numel", "step", "world_size"):
                if d[key] != meta[key]:
                    raise ValueError(f"shard {d['rank']} disagrees on {key}")
            if d.get("master_kind", "fp32") != meta.get("master_kind", "fp32"):
                raise ValueError(f"shard {d['rank']} disagrees on master_kind")
        if store_param_remainders is not None:
            want = "remainder_u16" if store_param_remainders else "fp32"
            got = meta.get("master_kind", "fp32")
            if got != want:
                raise ValueError(
                    f"checkpoint master_kind {got!r} does not match "
                    f"store_param_remainders={store_param_remainders}"
                )

        total = meta["total_numel"]
        new_padded = ((total + world_size - 1) // world_size) * world_size

        def reassemble(key):
            full = np.concatenate([d[key] for d in shards])[:total]
            # dtype preserved: fp32 masters stay fp32, uint16 remainders
            # (store_param_remainders) stay uint16
            return jnp.asarray(np.pad(full, (0, new_padded - total)))

        return DistributedFusedAdamState(
            step=jnp.int32(meta["step"]),
            exp_avg=reassemble("exp_avg"),
            exp_avg_sq=reassemble("exp_avg_sq"),
            master_shard=reassemble("master_shard"),
        )
