"""DistributedFusedAdam — ZeRO optimizer-state sharding over ``dp`` on
the resident bucket plan.

Reference: ``apex/contrib/optimizers/distributed_fused_adam.py:266``
(3,078 LoC): params flattened into fixed-size buckets
(``ParameterFragment``/``StateBucket`` :370-504), optimizer state
sharded over the process grid, reduce-scatter grad sync overlapped with
backward, all-gather param sync optionally overlapped with forward
(``step`` :2158).

This port runs on :mod:`apex_tpu.contrib.optimizers._zero_engine`:

- optimizer state (m, v, fp32-master-or-remainder) lives permanently as
  the local 1/dp shard of each dtype bucket — the ZeRO memory saving,
  with no per-step tree flatten and no fp32 up-cast of bf16 traffic;
- grads are reduce-scattered **per bucket** in ``grad_sync_dtype``
  (storage dtype for half buckets by default) so XLA's latency-hiding
  scheduler can overlap each bucket's collective with the remaining
  backward; ``bucket_cap_mb`` splits oversized dtype buckets into
  collective-sized chunks;
- updated param shards are all-gathered per bucket in
  ``param_sync_dtype``; ``overlap_param_sync`` gathers the pre-commit
  update so the gather is not serialized behind the finite vote;
- the Adam math on each shard is exactly
  :func:`apex_tpu.optimizers.fused_adam.adam_math` — the per-leaf
  :class:`~apex_tpu.optimizers.FusedAdam` is the numerics oracle and
  the fp32 trajectories are bit-exact (``tests/
  test_distributed_optimizers.py`` pins it).

Use inside ``shard_map`` with params replicated over ``dp`` (the *sharding
grid* of the reference — distributed_process_group × redundant_process_
group — is the ``dp`` mesh axis; a redundant axis would map to a second
mesh axis in multi-slice DCN deployments).
"""

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.contrib.optimizers._zero_engine import (
    ZeroOptimizerBase,
    local_leaf_info,
)
from apex_tpu.optimizers.base import predicate_step
from apex_tpu.optimizers.fused_adam import adam_math
from apex_tpu.transformer.parallel_state import DATA_AXIS

__all__ = ["DistributedFusedAdam", "DistributedFusedAdamState",
           "local_total_and_axes"]


class DistributedFusedAdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Tuple[jnp.ndarray, ...]      # per-bucket fp32 dp shards
    exp_avg_sq: Tuple[jnp.ndarray, ...]   # per-bucket fp32 dp shards
    # fp32 master of owned params — or, with store_param_remainders, the
    # low 16 bits (uint16) the bf16 param is missing — per bucket
    master_shard: Tuple[jnp.ndarray, ...]
    # quantized grad sync only: per-bucket error-feedback residuals in
    # the bucket storage dtype, each rank residing its FULL local
    # bucket's quantization error; () on wide wires
    residual: Tuple[jnp.ndarray, ...] = ()


def _master_from_remainder(p_f32, rem_u16):
    """Exact fp32 master = (bf16 param bits << 16) | remainder.

    ``p_f32`` is the f32 *extension* of the bf16 param, whose low 16
    mantissa bits are zero by construction — OR-ing in the remainder
    reconstructs the master bit-exactly (reference
    distributed_fused_adam.py ``store_param_remainders``)."""
    bits = jax.lax.bitcast_convert_type(p_f32, jnp.uint32)
    return jax.lax.bitcast_convert_type(bits | rem_u16.astype(jnp.uint32), jnp.float32)


def _split_master(master_f32):
    """(bf16 param, uint16 remainder): the bf16 the model sees is the
    master's high 16 bits.

    Truncation is THIS repo's convention (chosen so reconstruction is a
    plain bitwise OR).  The reference instead stores signed int16
    remainders and rounds the bf16 to nearest
    (multi_tensor_distopt_adam_kernel.cu:295-312), so remainder-mode
    bf16 params here can differ by up to 1 ulp (toward zero) from both
    the reference and this repo's fp32-master mode (which RNE-casts).
    The fp32 master — what the optimizer actually integrates — is
    bit-exact either way."""
    bits = jax.lax.bitcast_convert_type(master_f32, jnp.uint32)
    rem = (bits & jnp.uint32(0xFFFF)).astype(jnp.uint16)
    p_bf16 = jax.lax.bitcast_convert_type((bits >> 16).astype(jnp.uint16), jnp.bfloat16)
    return p_bf16, rem


def local_total_and_axes(params, param_specs, axis_sizes, zero_axis):
    """(local_total_numel, model_axes, leaf_repl) — the flat summary of
    :func:`~apex_tpu.contrib.optimizers._zero_engine.local_leaf_info`,
    kept for callers that only need sizes (DistributedFusedLAMB's old
    API, tests)."""
    shapes, model_axes, repl = local_leaf_info(
        params, param_specs, axis_sizes, zero_axis)
    total = sum(int(np.prod(s)) if s else 1 for s in shapes)
    return total, model_axes, repl


class DistributedFusedAdam(ZeroOptimizerBase):
    """ZeRO AdamW with the reference's constructor vocabulary, on the
    resident sharded bucket engine."""

    _STATE_CLS = DistributedFusedAdamState

    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        adam_w_mode: bool = True,
        weight_decay: float = 0.0,
        axis_name: str = DATA_AXIS,
        grad_average: bool = True,
        overlap_grad_sync: bool = True,
        overlap_param_sync: bool = False,
        bucket_cap_mb: float = 100.0,
        dtype=jnp.float32,
        grad_sync_dtype=None,
        param_sync_dtype=None,
        process_group=None,
        distributed_process_group=None,
        redundant_process_group=None,
        store_param_remainders: bool = False,
        dp_axes=None,
    ):
        super().__init__(
            lr, weight_decay, axis_name=axis_name, grad_average=grad_average,
            overlap_grad_sync=overlap_grad_sync,
            overlap_param_sync=overlap_param_sync,
            bucket_cap_mb=bucket_cap_mb, grad_sync_dtype=grad_sync_dtype,
            param_sync_dtype=param_sync_dtype,
            store_param_remainders=store_param_remainders, dtype=dtype,
            dp_axes=dp_axes,
            process_group=process_group,
            distributed_process_group=distributed_process_group,
            redundant_process_group=redundant_process_group,
        )
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode

    # -------------------------------------------------------------- init
    def init(self, params, world_size: Optional[int] = None, param_specs=None,
             axis_sizes=None) -> DistributedFusedAdamState:
        """Build the GLOBAL per-bucket flat state, to be sharded via
        :meth:`state_partition_spec` so each rank holds its 1/dp shard
        of every bucket (the ZeRO memory saving stated explicitly
        through the sharding).  The fp32 master is packed from the
        params at init (resident — the step never re-flattens params);
        with ``store_param_remainders`` the zeroed uint16 remainders
        already reconstruct exactly the fp32 extension of the bf16
        params.

        **Composition with model parallelism**: when ``params`` are
        themselves sharded over model-parallel mesh axes, pass
        ``param_specs`` (their PartitionSpec tree) and ``axis_sizes``
        (axis name → mesh size).  The plan is then built over the LOCAL
        leaf shards and the state additionally shards over those axes —
        each (tp, dp) device holds the dp-shard of the optimizer state
        for its tp-slice of the params."""
        self._init_plan(params, world_size, param_specs, axis_sizes)
        m = self._zero_slot()
        v = self._zero_slot()
        return DistributedFusedAdamState(
            step=jnp.int32(0), exp_avg=m, exp_avg_sq=v,
            master_shard=self._master_slot(params),
            residual=self._residual_slot())

    # -------------------------------------------------------------- step
    def _zero_step(self, grads, state: DistributedFusedAdamState, params,
                   grads_finite=None, lr=None, scale=None, clip_norm=None,
                   finite_sync=None, sumsq_reduce=None, want_finite=False,
                   presynced=None):
        lr = self.lr if lr is None else lr
        wd = self.weight_decay
        plan = self._plan_of_local(params)
        self._check_master_precision(state.master_shard)

        g_shards, res_new, pred, rank, world = self._prepare_grads(
            plan, grads, scale, clip_norm, finite_sync, want_finite,
            grads_finite, sumsq_reduce, residuals=state.residual,
            presynced=presynced)
        self._check_state_shards(plan, state.exp_avg, world, "exp_avg")

        if self.store_param_remainders:
            # master ≡ (bf16 param bits | stored remainder); the bf16
            # param shard is this rank's slice of the per-bucket bf16
            # pack — bf16 traffic, no fp32 concat
            p_owned = self._owned_param_shards(plan, params, rank, world)
            master = [_master_from_remainder(p.astype(jnp.float32), rem)
                      for p, rem in zip(p_owned, state.master_shard)]
        else:
            master = list(state.master_shard)

        step = predicate_step(pred, state.step)
        bc1, bc2 = self._bias_corrections(step)

        new_p, new_m, new_v = [], [], []
        for bi in range(len(plan.buckets)):
            p_out, m_out, v_out = adam_math(
                g_shards[bi], master[bi], state.exp_avg[bi],
                state.exp_avg_sq[bi], wd, lr, bc1, bc2,
                beta1=self.beta1, beta2=self.beta2, eps=self.eps,
                adam_w_mode=self.adam_w_mode)
            new_p.append(p_out)
            new_m.append(m_out)
            new_v.append(v_out)

        new_m = self._select(pred, new_m, state.exp_avg)
        new_v = self._select(pred, new_v, state.exp_avg_sq)
        master_committed = self._select(pred, new_p, master)
        res_committed = self._commit_residuals(res_new, state.residual, pred)

        if self.store_param_remainders:
            if self.overlap_param_sync and pred is not None:
                # gather the PRE-commit bf16 halves — the all-gather
                # need not wait for the finite vote's collectives; the
                # commit happens per leaf against the old params
                gather_src = [_split_master(p)[0] for p in new_p]
                new_params = self._emit_params(plan, gather_src, params, pred)
            else:
                gather_src = [_split_master(p)[0] for p in master_committed]
                new_params = self._emit_params(plan, gather_src, params, None)
            rem_new = tuple(_split_master(p)[1] for p in master_committed)
            return new_params, DistributedFusedAdamState(
                step, tuple(new_m), tuple(new_v), rem_new,
                res_committed), pred

        if self.overlap_param_sync and pred is not None:
            new_params = self._emit_params(plan, new_p, params, pred)
        else:
            new_params = self._emit_params(plan, master_committed, params,
                                           None)
        return new_params, DistributedFusedAdamState(
            step, tuple(new_m), tuple(new_v), tuple(master_committed),
            res_committed), pred
