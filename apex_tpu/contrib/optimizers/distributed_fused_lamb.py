"""DistributedFusedLAMB — ZeRO-sharded LAMB (BERT-style large batch) on
the resident bucket engine.

Reference: ``apex/contrib/optimizers/distributed_fused_lamb.py:1061``
(ZeRO grid + two-stage LAMB with global grad norm and per-tensor trust
ratios).

LAMB's trust ratio is per-TENSOR, so unlike Adam the shard math cannot
ignore tensor boundaries.  On the bucket plan the fix is cheap: the
per-leaf ‖p‖²/‖u‖² sums are recovered from the dp shards through the
plan's static segment map (one ``segment_sum`` per bucket) and completed
by a psum over dp — with model-sharded params additionally psummed over
the model axes with tp-REPLICATED leaves counted once (per-shard norms
would silently change the numerics; the reference's DistributedFusedLAMB
is pure-dp and never faces this).  The trust ratios then broadcast back
onto each rank's shard as one static-repeats gather, so the all-gather
stays a pure param sync exactly like Adam's — stage 2 adds zero
collective traffic beyond the two batched norm psums.

Stage-1/stage-2 per-element math is
:func:`apex_tpu.optimizers.fused_lamb.lamb_stage1_math` /
:func:`~apex_tpu.optimizers.fused_lamb.lamb_trust_ratio` — the per-leaf
:class:`~apex_tpu.optimizers.FusedLAMB` is the numerics oracle.
"""

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.contrib.optimizers._zero_engine import ZeroOptimizerBase
from apex_tpu.optimizers import bucketing
from apex_tpu.optimizers.base import predicate_step
from apex_tpu.optimizers.fused_lamb import (
    lamb_grad_clip,
    lamb_stage1_math,
    lamb_trust_ratio,
)
from apex_tpu.transformer.parallel_state import DATA_AXIS

__all__ = ["DistributedFusedLAMB", "DistributedFusedLAMBState"]


class DistributedFusedLAMBState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Tuple[jnp.ndarray, ...]
    exp_avg_sq: Tuple[jnp.ndarray, ...]
    master_shard: Tuple[jnp.ndarray, ...]
    # quantized grad sync only: per-bucket error-feedback residuals
    # (see DistributedFusedAdamState.residual); () on wide wires
    residual: Tuple[jnp.ndarray, ...] = ()


class DistributedFusedLAMB(ZeroOptimizerBase):

    _STATE_CLS = DistributedFusedLAMBState

    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        max_grad_norm: float = 1.0,
        adam_w_mode: bool = True,
        grad_averaging: bool = True,
        use_nvlamb: bool = False,
        axis_name: str = DATA_AXIS,
        overlap_grad_sync: bool = True,
        overlap_param_sync: bool = False,
        bucket_cap_mb: float = 100.0,
        grad_sync_dtype=None,
        param_sync_dtype=None,
        dp_axes=None,
        **parity_kwargs,
    ):
        super().__init__(
            lr, weight_decay, axis_name=axis_name,
            grad_average=grad_averaging,
            overlap_grad_sync=overlap_grad_sync,
            overlap_param_sync=overlap_param_sync,
            bucket_cap_mb=bucket_cap_mb, grad_sync_dtype=grad_sync_dtype,
            param_sync_dtype=param_sync_dtype, dp_axes=dp_axes,
        )
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.max_grad_norm = max_grad_norm
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.use_nvlamb = use_nvlamb

    def init(self, params, world_size: Optional[int] = None, param_specs=None,
             axis_sizes=None) -> DistributedFusedLAMBState:
        """GLOBAL per-bucket flat state — shard with
        :meth:`state_partition_spec` (see DistributedFusedAdam.init).
        The fp32 master packs from the params at init (resident)."""
        self._init_plan(params, world_size, param_specs, axis_sizes)
        return DistributedFusedLAMBState(
            step=jnp.int32(0), exp_avg=self._zero_slot(),
            exp_avg_sq=self._zero_slot(),
            master_shard=self._master_slot(params),
            residual=self._residual_slot())

    def _global_leaf_sumsq(self, plan, shards, rank, world):
        """GLOBAL per-leaf Σx² from per-bucket dp shards: segment sums,
        psum over dp (shards are disjoint), then — with model-sharded
        params — psum over the model axes dividing out each leaf's
        replication factor so tp-replicated leaves count once, not once
        per rank."""
        leaf_sq = jax.lax.psum(
            self._per_leaf_sumsq(plan, shards, rank, world),
            self._dp_sync_axes)
        if self._model_axes:
            repl = jnp.asarray(self._leaf_repl, jnp.float32)
            leaf_sq = jax.lax.psum(leaf_sq / repl, self._model_axes)
        return leaf_sq

    def _zero_step(self, grads, state: DistributedFusedLAMBState, params,
                   grads_finite=None, lr=None, scale=None, clip_norm=None,
                   finite_sync=None, sumsq_reduce=None, want_finite=False,
                   presynced=None):
        lr = self.lr if lr is None else lr
        wd = self.weight_decay
        plan = self._plan_of_local(params)
        self._check_master_precision(state.master_shard)

        g_shards, res_new, pred, rank, world = self._prepare_grads(
            plan, grads, scale, clip_norm, finite_sync, want_finite,
            grads_finite, sumsq_reduce, residuals=state.residual,
            presynced=presynced)
        self._check_state_shards(plan, state.exp_avg, world, "exp_avg")

        # LAMB's own global grad-norm clip on the dp-AVERAGED grad
        # (fused_lamb.py:121-136) — per-leaf sums recovered from the
        # scattered shards (DEQUANTIZED fp32 on a compressed wire: the
        # trust-ratio segment sums never see the int8/fp8 payload), so
        # the dp reduction stays a reduce-scatter
        gn_sq = jnp.sum(self._global_leaf_sumsq(plan, g_shards, rank, world))
        clip = lamb_grad_clip(jnp.sqrt(gn_sq), self.max_grad_norm)

        master = list(state.master_shard)
        step = predicate_step(pred, state.step)
        bc1, bc2 = self._bias_corrections(step)

        # stage 1: one fused pass per bucket shard
        u_b, new_m, new_v = [], [], []
        for bi in range(len(plan.buckets)):
            u, m_out, v_out = lamb_stage1_math(
                g_shards[bi] / clip, master[bi], state.exp_avg[bi],
                state.exp_avg_sq[bi], wd, bc1, bc2,
                beta1=self.beta1, beta2=self.beta2, eps=self.eps,
                adam_w_mode=self.adam_w_mode,
                grad_averaging=self.grad_averaging)
            u_b.append(u)
            new_m.append(m_out)
            new_v.append(v_out)

        # stage 2: GLOBAL per-tensor trust ratios from the shards —
        # both norm families in two batched psums, never 2·L scalar
        # collectives
        apply_ratio = self.use_nvlamb or wd != 0.0
        if apply_ratio:
            p_sq = self._global_leaf_sumsq(plan, master, rank, world)
            u_sq = self._global_leaf_sumsq(plan, u_b, rank, world)
            ratios = [
                lamb_trust_ratio(lr, jnp.sqrt(p_sq[i]), jnp.sqrt(u_sq[i]),
                                 apply_ratio=True)
                for i in range(plan.n_leaves)
            ]
        else:
            ratios = [jnp.asarray(lr, jnp.float32)] * plan.n_leaves

        new_p = []
        for bi, b in enumerate(plan.buckets):
            shard = b.total // world
            ratio_b = bucketing.seg_broadcast(b, ratios)
            ratio_shard = jax.lax.dynamic_slice_in_dim(
                ratio_b, rank * shard, shard)
            new_p.append(master[bi] - ratio_shard * u_b[bi])

        new_m = self._select(pred, new_m, state.exp_avg)
        new_v = self._select(pred, new_v, state.exp_avg_sq)
        master_committed = self._select(pred, new_p, master)
        res_committed = self._commit_residuals(res_new, state.residual, pred)

        if self.overlap_param_sync and pred is not None:
            new_params = self._emit_params(plan, new_p, params, pred)
        else:
            new_params = self._emit_params(plan, master_committed, params,
                                           None)
        return new_params, DistributedFusedLAMBState(
            step, tuple(new_m), tuple(new_v), tuple(master_committed),
            res_committed), pred
