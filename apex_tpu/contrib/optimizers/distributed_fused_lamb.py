"""DistributedFusedLAMB — ZeRO-sharded LAMB (BERT-style large batch).

Reference: ``apex/contrib/optimizers/distributed_fused_lamb.py:1061``
(ZeRO grid + two-stage LAMB with global grad norm and per-tensor trust
ratios).

LAMB's trust ratio is per-TENSOR, so unlike Adam the flat-shard trick
can't ignore tensor boundaries.  TPU design: grads reduce-scatter over
``dp`` per-tensor is wasteful for many small tensors; instead this
implementation keeps the *moments* sharded (ZeRO-2 memory) by
flattening, but computes stage-2 norms per tensor on the gathered
update — the all_gather needed for param sync anyway supplies the
update vector, so the extra cost is one pass of per-tensor reductions.
"""

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.contrib.optimizers.distributed_fused_adam import (
    _flatten,
    local_total_and_axes,
)
from apex_tpu.transformer.parallel_state import DATA_AXIS


class DistributedFusedLAMBState(NamedTuple):
    step: jnp.ndarray
    exp_avg: jnp.ndarray
    exp_avg_sq: jnp.ndarray
    master_shard: jnp.ndarray


class DistributedFusedLAMB:
    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        max_grad_norm: float = 1.0,
        adam_w_mode: bool = True,
        grad_averaging: bool = True,
        use_nvlamb: bool = False,
        axis_name: str = DATA_AXIS,
        **parity_kwargs,
    ):
        self.lr = lr
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.max_grad_norm = max_grad_norm
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.use_nvlamb = use_nvlamb
        self.axis_name = axis_name

    def init(self, params, world_size: Optional[int] = None, param_specs=None,
             axis_sizes=None) -> DistributedFusedLAMBState:
        """GLOBAL flat state (padded_total,) — shard over dp with
        :meth:`state_partition_spec` (see DistributedFusedAdam.init).

        **Composition with tensor parallelism**: pass ``param_specs`` +
        ``axis_sizes`` exactly as for DistributedFusedAdam.  LAMB's
        stage-2 trust ratios need GLOBAL per-tensor norms, so with
        model-sharded params the per-tensor ‖p‖/‖u‖ sums are psum'd over
        the model axes before the ratio — per-shard norms would silently
        change the numerics (the reference's DistributedFusedLAMB is
        pure-dp and never faces this)."""
        if world_size is None:
            raise ValueError("pass world_size= (the dp axis size)")
        self._model_axes = ()
        self._leaf_repl = None
        if param_specs is not None:
            if axis_sizes is None:
                raise ValueError("param_specs requires axis_sizes")
            total, self._model_axes, self._leaf_repl = local_total_and_axes(
                params, param_specs, axis_sizes, self.axis_name
            )
        else:
            total = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
        model_mult = 1
        for ax in self._model_axes:
            model_mult *= axis_sizes[ax]
        padded = ((total + world_size - 1) // world_size) * world_size
        zeros = jnp.zeros((model_mult * padded,), jnp.float32)
        return DistributedFusedLAMBState(
            step=jnp.int32(0), exp_avg=zeros, exp_avg_sq=zeros, master_shard=zeros
        )

    def state_partition_spec(self):
        from jax.sharding import PartitionSpec as P

        axes = getattr(self, "_model_axes", ())
        flat = P((*axes, self.axis_name)) if axes else P(self.axis_name)
        return DistributedFusedLAMBState(
            step=P(), exp_avg=flat, exp_avg_sq=flat, master_shard=flat,
        )

    def update(self, grads, state, params, grads_finite=None, lr=None):
        lr = self.lr if lr is None else lr
        ax = self.axis_name
        world = jax.lax.axis_size(ax)
        rank = jax.lax.axis_index(ax)
        b1, b2, eps, wd = self.beta1, self.beta2, self.eps, self.weight_decay
        b3 = (1.0 - b1) if self.grad_averaging else 1.0

        flat_g = _flatten(grads)
        total = flat_g.shape[0]
        padded = ((total + world - 1) // world * world) if total % world else total
        if padded != total:
            flat_g = jnp.pad(flat_g, (0, padded - total))
        shard = padded // world

        g_local = jax.lax.psum_scatter(flat_g, ax, scatter_dimension=0, tiled=True)
        if self.grad_averaging:
            g_local = g_local / world

        # global grad norm on the dp-AVERAGED grad (fused_lamb.py:121-136).
        # Per-leaf sums are recovered from the scattered shard via a
        # static segment map (leaf boundaries in the flat layout), so
        # the dp reduction stays a reduce-scatter; with model-sharded
        # params the norm additionally psums over the model axes with
        # tp-REPLICATED leaves counted once, not once per rank.
        model_axes = getattr(self, "_model_axes", ())
        leaves_g = jax.tree.leaves(grads)
        L = len(leaves_g)
        seg_ids = np.repeat(
            np.arange(L), [int(np.prod(g.shape)) for g in leaves_g]
        )
        seg_ids = np.pad(seg_ids, (0, padded - total), constant_values=L)
        seg_local = jax.lax.dynamic_slice_in_dim(
            jnp.asarray(seg_ids), rank * shard, shard
        )
        leaf_sq_local = jax.ops.segment_sum(
            jnp.square(g_local), seg_local, num_segments=L + 1
        )[:L]
        leaf_sq = jax.lax.psum(leaf_sq_local, ax)  # ||avg grad leaf||², per leaf
        if model_axes:
            repl = jnp.asarray(self._leaf_repl, jnp.float32)
            gn_sq = jax.lax.psum(jnp.sum(leaf_sq / repl), model_axes)
        else:
            gn_sq = jnp.sum(leaf_sq)
        global_norm = jnp.sqrt(gn_sq)
        clip = jnp.where(
            global_norm > self.max_grad_norm, global_norm / self.max_grad_norm, jnp.float32(1.0)
        )

        flat_p = _flatten(params)
        if padded != total:
            flat_p = jnp.pad(flat_p, (0, padded - total))
        p_owned = jax.lax.dynamic_slice_in_dim(flat_p, rank * shard, shard)
        master = jnp.where(state.step == 0, p_owned, state.master_shard)

        step = state.step + (
            jnp.asarray(grads_finite).astype(jnp.int32) if grads_finite is not None else 1
        )
        t = step.astype(jnp.float32)
        if self.bias_correction:
            bc1 = 1.0 - jnp.power(b1, t)
            bc2 = 1.0 - jnp.power(b2, t)
        else:
            bc1 = bc2 = jnp.float32(1.0)

        g = g_local / clip
        if not self.adam_w_mode:
            g = g + wd * master
        m_new = b1 * state.exp_avg + b3 * g
        v_new = b2 * state.exp_avg_sq + (1.0 - b2) * g * g
        u_local = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
        if self.adam_w_mode:
            u_local = u_local + wd * master

        # gather update + params for per-tensor trust ratios (stage 2)
        flat_u = jax.lax.all_gather(u_local, ax, axis=0, tiled=True)[:total]
        flat_pm = jax.lax.all_gather(master, ax, axis=0, tiled=True)[:total]

        leaves, treedef = jax.tree.flatten(params)
        if self.use_nvlamb or wd != 0.0:
            # all per-tensor ‖p‖²/‖u‖² in ONE batched psum over the
            # model axes (not 2·L scalar collectives)
            sums = []
            off = 0
            for p in leaves:
                n = int(np.prod(p.shape))
                sums.append(jnp.sum(jnp.square(flat_pm[off : off + n])))
                sums.append(jnp.sum(jnp.square(flat_u[off : off + n])))
                off += n
            sums = jnp.stack(sums).reshape(len(leaves), 2)
            if model_axes:  # GLOBAL per-tensor norms across tp shards;
                # replicated leaves counted once, not once per rank
                repl2 = jnp.asarray(self._leaf_repl, jnp.float32)[:, None]
                sums = jax.lax.psum(sums, model_axes) / repl2
            p_norms = jnp.sqrt(sums[:, 0])
            u_norms = jnp.sqrt(sums[:, 1])
        new_leaves = []
        off = 0
        for i, p in enumerate(leaves):
            n = int(np.prod(p.shape))
            u_t = flat_u[off : off + n]
            p_t = flat_pm[off : off + n]
            if self.use_nvlamb or wd != 0.0:
                ratio = jnp.where(
                    (p_norms[i] != 0.0) & (u_norms[i] != 0.0),
                    lr * (p_norms[i] / u_norms[i]), lr,
                )
            else:
                ratio = lr
            new_leaves.append((p_t - ratio * u_t).reshape(p.shape).astype(p.dtype))
            off += n
        new_params = jax.tree.unflatten(treedef, new_leaves)

        # refresh the owned master shard from the new params
        flat_new = _flatten(new_params)
        if padded != total:
            flat_new = jnp.pad(flat_new, (0, padded - total))
        master_new = jax.lax.dynamic_slice_in_dim(flat_new, rank * shard, shard)

        if grads_finite is not None:
            pred = jnp.asarray(grads_finite)
            m_new = jnp.where(pred, m_new, state.exp_avg)
            v_new = jnp.where(pred, v_new, state.exp_avg_sq)
            master_new = jnp.where(pred, master_new, master)
            new_params = jax.tree.map(
                lambda n, o: jnp.where(pred, n, o.astype(n.dtype)), new_params, params
            )

        return new_params, DistributedFusedLAMBState(
            step=step, exp_avg=m_new, exp_avg_sq=v_new, master_shard=master_new
        )
