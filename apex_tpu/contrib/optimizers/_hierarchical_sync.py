"""Topology-aware hierarchical gradient sync: multi-hop reduce-scatter
over a ``(slow, ..., fast)`` data-parallel axis split.

Ground paper: "DynamiQ: Accelerating Gradient Synchronization using
Compressed Multi-hop All-reduce" (PAPERS.md, arXiv 2602.08923) — at pod
scale the dp world spans interconnects with very different bandwidth
(ICI within a slice, DCN across slices), and a flat collective pays the
slow hop at the FULL payload.  The multi-hop form reduces intra-slice
first on the fast axis, so each slower hop only ever carries the
already-scattered chunk — and, on a compressed wire, stays at the
compressed dtype by requantizing the partial sums with fresh shared
scales and feeding the requantization error back into the resident
error-feedback residual channel (PR 6's machinery, reused).

The topology contract a :class:`HierarchicalSyncPlan` describes:

- ``hop_axes``: the dp world is the mesh product of the named axes,
  ordered SLOW to FAST — the two-level ``(dp_out, dp_in)`` split of
  PR 12, or the seeded three-level ``(dcn, dp_out, dp_in)`` topology
  where DCN crosses pods, ``dp_out`` crosses slices, and ``dp_in`` is
  intra-slice ICI.  Every grad-sync hop runs at the same wire dtype
  (the compressed dtype never widens on a slow hop — that is the
  point); the per-hop dtypes are recorded on the plan for the wire
  accounting.
- **shard ownership is unchanged vs the flat plan**: the multi-hop
  scatter (fastest axis first on the full bucket, each slower axis on
  the shrinking chunk) lands flat chunk
  ``r = (... (i_fast * s_next + i_next) ...) * s_slow + i_slow`` on the
  mesh rank with those indices, which is exactly the resident shard
  ``P((..., fast, ..., slow))`` assigns that rank.  Bucket totals use
  the ONE :func:`~apex_tpu.optimizers.bucketing.padded_total` formula
  with ``shard_pad = prod(sizes)``, so elastic checkpoints reshard
  across flat <-> two-level <-> three-level worlds with no special
  case.
- **param sync mirrors in reverse**: all-gather the updated shard over
  the SLOWEST axis first (cross-pod traffic is the smallest chunk),
  finishing on the fast axis.

Quantized wire (int8/fp8), per bucket and per hop ``j`` (fast first):

1. shared per-block scales from an amax psum over THIS hop's axis only,
   quantize the current fp32 partial (``h = g/scale + residual`` on the
   first hop), reduce-scatter the int8/fp8 payload over the axis;
2. the hop's quantization error ``cur - deq(q_j)`` covers the current
   chunk and is FOLDED into the residual at that chunk's positions;
   dequantize the received shard into fp32 partial sums for the next
   (slower) hop, which REQUANTIZES with fresh shared scales.

The telescoping identity is preserved exactly at every depth: with each
rank's new residual carrying every hop's folded error, the transmitted
total per step is ``sum_r h_r - sum_r residual_r`` — what PR 6's error
feedback needs — so the crafted dyadic-scale test pins the multi-hop
chain bitwise (``tests/test_distributed_optimizers.py``).

When extra hops LOSE: each hop adds a (small) scale psum and a fresh
quantization, so for tiny buckets — where the fp32 scale vector
(~``4/QBLOCK`` of the payload) and the per-hop latency dominate — or
for meshes whose interconnect is flat (fast size 1), the flat plan is
the better choice.  The win scales with the fast sizes: cross-slice
bytes drop by exactly ``1/dp_in`` and cross-DCN bytes by
``1/(dp_in * dp_out)`` (scales included — the per-hop accounting in
:func:`~apex_tpu.contrib.optimizers._quantized_sync.grad_sync_bytes`
is exact, not a payload approximation).
"""

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.contrib.optimizers import _quantized_sync as qs

__all__ = [
    "HierarchicalSyncPlan", "hierarchical_plan",
    "multi_hop_reduce_scatter", "multi_hop_all_gather",
    "quantized_multi_hop_reduce_scatter", "quantized_multi_hop_pmean",
    "quantized_multi_hop_pmean_bucket",
    "two_hop_reduce_scatter", "two_hop_all_gather",
    "quantized_two_hop_reduce_scatter", "quantized_two_hop_pmean",
]


@dataclasses.dataclass(frozen=True)
class HierarchicalSyncPlan:
    """The ``(slow, ..., fast)`` dp split one ZeRO optimizer syncs over.

    ``hop_axes`` orders the mesh axes SLOW to FAST — two-level
    ``(dp_out, dp_in)`` (outer cross-slice DCN, inner intra-slice ICI)
    or three-level ``(dcn, dp_out, dp_in)``; ``hop_sizes`` are the mesh
    extents the plan was built for (the traced step re-reads them from
    the live mesh via ``lax.axis_size`` — a mismatch fails the
    state-shard check exactly like a flat world mismatch).
    ``grad_wire_dtype``/``param_wire_dtype`` record the per-hop wire
    dtypes for the accounting: every grad hop carries the SAME dtype (a
    compressed wire stays compressed on the slow hops), ``None`` means
    the per-bucket storage default."""

    hop_axes: Tuple[str, ...]
    hop_sizes: Tuple[int, ...]
    grad_wire_dtype: Optional[str] = None
    param_wire_dtype: Optional[str] = None

    def __post_init__(self):
        axes, sizes = tuple(self.hop_axes), tuple(self.hop_sizes)
        object.__setattr__(self, "hop_axes", axes)
        object.__setattr__(self, "hop_sizes", sizes)
        if len(set(axes)) != len(axes):
            raise ValueError(
                f"hierarchical dp axes must be DISTINCT mesh axes, got "
                f"{axes!r}")
        if not 2 <= len(axes) <= 3 or len(sizes) != len(axes):
            raise ValueError(
                f"hierarchical dp takes two or three (axis, size) hops, "
                f"got axes={axes!r} sizes={sizes!r}")
        if any(s < 1 for s in sizes):
            raise ValueError(f"axis sizes must be >= 1, got {sizes!r}")

    # ------------------------------------------- two-level spellings
    @property
    def outer_axis(self) -> str:
        """The SLOWEST hop's axis (the two-level ``dp_out``)."""
        return self.hop_axes[0]

    @property
    def inner_axis(self) -> str:
        """The FASTEST hop's axis (the two-level ``dp_in``)."""
        return self.hop_axes[-1]

    @property
    def outer_size(self) -> int:
        return self.hop_sizes[0]

    @property
    def inner_size(self) -> int:
        return self.hop_sizes[-1]

    @property
    def axes(self) -> Tuple[str, ...]:
        """``(slow, ..., fast)`` — the step builder's dp_axis spelling."""
        return self.hop_axes

    @property
    def world(self) -> int:
        w = 1
        for s in self.hop_sizes:
            w *= s
        return w

    @property
    def shard_axes(self) -> Tuple[str, ...]:
        """PartitionSpec order for the resident 1/dp shards: fast-major
        ``(fast, ..., slow)`` places the flat chunk the multi-hop
        scatter delivers on exactly the rank that owns it (two-level:
        chunk ``i * dp_outer + o`` on mesh rank ``(o, i)``)."""
        return tuple(reversed(self.hop_axes))

    def zero_rank(self):
        """This rank's FLAT dp rank (traced): the index of the bucket
        chunk the multi-hop scatter lands here.  Matches the flat
        plan's chunk-per-rank layout, so checkpoints reshard flat <->
        hierarchical through the one ``padded_total`` formula."""
        rank = None
        for ax in reversed(self.hop_axes):  # fast -> slow
            idx = jax.lax.axis_index(ax)
            if rank is None:
                rank = idx
            else:
                rank = rank * jax.lax.axis_size(ax) + idx
        return rank

    def traced_sizes(self) -> Tuple[int, ...]:
        """Hop-ordered ``(slow, ..., fast)`` extents of the LIVE mesh
        (static ints at trace time inside shard_map)."""
        return tuple(jax.lax.axis_size(ax) for ax in self.hop_axes)


def hierarchical_plan(dp_axes, axis_sizes, grad_wire_dtype=None,
                      param_wire_dtype=None) -> HierarchicalSyncPlan:
    """Build the plan from the optimizer's ``dp_axes=(slow, ..., fast)``
    knob plus the ``axis_sizes`` mapping ``init`` already takes."""
    axes = tuple(dp_axes)
    if not (2 <= len(axes) <= 3) or \
            not all(isinstance(a, str) for a in axes):
        raise ValueError(
            f"dp_axes must be two or three mesh axis names ordered slow "
            f"to fast — (outer, inner) or (dcn, dp_out, dp_in) — got "
            f"{dp_axes!r}")
    missing = [a for a in axes if a not in (axis_sizes or {})]
    if missing:
        raise ValueError(
            f"hierarchical dp needs axis_sizes for every dp axis; missing "
            f"{missing} (pass axis_sizes={{axis: size, ...}} covering "
            f"{axes!r} to init)")
    def _name(dt):
        return None if dt is None else jnp.dtype(dt).name
    return HierarchicalSyncPlan(
        hop_axes=axes,
        hop_sizes=tuple(int(axis_sizes[a]) for a in axes),
        grad_wire_dtype=_name(grad_wire_dtype),
        param_wire_dtype=_name(param_wire_dtype))


# ----------------------------------------------------------- wide wire
def multi_hop_reduce_scatter(bucket, plan: HierarchicalSyncPlan):
    """The unquantized multi-hop grad sync of one bucket (already in
    the wire dtype, fp16 predivide folded by the caller): reduce-scatter
    intra-slice on the fast axis first, then each slower axis on the
    shrinking chunk — the slowest hop moves ``1/prod(faster sizes)`` of
    the bucket.  Returns this rank's flat 1/dp chunk of the dp-wide
    SUM."""
    for ax in reversed(plan.hop_axes):  # fast -> slow
        bucket = jax.lax.psum_scatter(bucket, ax, scatter_dimension=0,
                                      tiled=True)
    return bucket


def multi_hop_all_gather(shard, plan: HierarchicalSyncPlan):
    """The mirrored param sync: gather the updated shard over the
    SLOWEST axis first (the pod-shared shard — cross-pod traffic is the
    smallest chunk), finishing on the fast axis.  Inverts the multi-hop
    scatter's chunk order exactly, so the bucket reassembles in flat
    layout."""
    for ax in plan.hop_axes:  # slow -> fast
        shard = jax.lax.all_gather(shard, ax, axis=0, tiled=True)
    return shard


# ------------------------------------------------------ quantized wire
def _check_hier_blocks(n: int, plan: HierarchicalSyncPlan,
                       block: int) -> None:
    length = n
    for size in reversed(plan.hop_sizes):  # fast -> slow
        if length % (block * size):
            raise ValueError(
                f"bucket of {n} elements does not split into "
                f"{block}-element scale blocks per {plan.hop_sizes} "
                "hierarchical shard — bucket totals must be padded with "
                "bucketing.padded_total(shard_pad=prod(dp sizes))")
        length //= size


def quantized_multi_hop_reduce_scatter(h, plan: HierarchicalSyncPlan,
                                       spec: qs.QSpec,
                                       block: int = qs.QBLOCK):
    """The compressed multi-hop grad sync of one bucket: returns
    ``(sum_shard_f32, residual_f32)`` where ``sum_shard_f32`` is this
    rank's flat 1/dp chunk of the dp-SUM (to the wire precision of
    EVERY hop) and ``residual_f32`` is the full-local-bucket error to
    carry: the hop-1 quantization error everywhere, PLUS each slower
    hop's REQUANTIZATION error folded in at this rank's shrinking chunk
    positions.

    Summed over ranks the new residuals satisfy
    ``sum_r transmitted = sum_r h_r - sum_r residual_r`` exactly — the
    same telescoping identity as the flat wire at any hop depth, so the
    resident error-feedback channel needs no layout change."""
    sizes = plan.traced_sizes()  # slow -> fast
    n = h.shape[0]
    _check_hier_blocks(n, plan, block)

    cur = h            # this hop's fp32 input (partial sums after hop 1)
    length = n         # its static length
    off = 0            # this rank's chunk offset within the local bucket
    residual = None
    for depth, ax in enumerate(reversed(plan.hop_axes)):  # fast -> slow
        # shared scales from THIS axis's amax psum only; each slower hop
        # requantizes the partial sums with fresh scales, keeping the
        # wire dtype end to end
        s_j, b_j = qs.block_scales(cur, ax, spec, block)
        q_j = qs.quantize(cur, s_j, b_j, spec, block)
        r_j = cur - qs.dequantize(q_j, s_j, block)
        if residual is None:
            residual = r_j
        else:
            # fold the requantization error into the residual at this
            # rank's current chunk positions: sum_r residual_r picks up
            # every hop's error exactly once — the telescoping identity
            prev = jax.lax.dynamic_slice_in_dim(residual, off, length)
            residual = jax.lax.dynamic_update_slice_in_dim(
                residual, prev + r_j, off, 0)
        q_shard = jax.lax.psum_scatter(q_j, ax, scatter_dimension=0,
                                       tiled=True)
        idx = jax.lax.axis_index(ax)
        length //= sizes[len(sizes) - 1 - depth]
        nb = length // block
        s_shard = jax.lax.dynamic_slice_in_dim(s_j, idx * nb, nb)
        # fp32 partial sums of this chunk: input to the next hop (or the
        # final dp-sum shard on the last hop)
        cur = qs.dequantize(q_shard, s_shard, block)
        off = off + idx * length
    return cur, residual


def quantized_multi_hop_pmean(grads, plan: HierarchicalSyncPlan,
                              spec: qs.QSpec, block: int = qs.QBLOCK):
    """Hierarchical quantized gradient all-reduce for the REPLICATED
    data-parallel path (the ``make_train_step(grad_sync_dtype=...)``
    knob over a multi-axis dp mesh): the multi-hop reduce-scatter
    above, then the MIRRORED gathers — every payload hop at the wire
    dtype (the gathered partial sums are bounded by ``qmax`` per hop),
    plus the small fp32 last-hop scale gather the dequantize needs
    (last-hop scales are chunk-local: shared over the slowest axis,
    distinct per faster-axis rank).

    Stateless like :func:`~apex_tpu.contrib.optimizers._quantized_sync
    .quantized_pmean`: no optimizer-state channel means no
    error-feedback residual — ZeRO with ``dp_axes=`` is the compressed
    hierarchical path WITH feedback."""
    from apex_tpu.optimizers import bucketing

    sizes = plan.traced_sizes()
    world = 1
    for s in sizes:
        world *= s
    tree_plan = bucketing.plan_of(grads, shard_pad=world)
    leaves = jax.tree.leaves(grads)
    out = [quantized_multi_hop_pmean_bucket(
        bucketing.pack_bucket(b, leaves, jnp.float32), plan, spec, block)
        for b in tree_plan.buckets]
    return bucketing.unpack(tree_plan, out)


def quantized_multi_hop_pmean_bucket(h, plan: HierarchicalSyncPlan,
                                     spec: qs.QSpec,
                                     block: int = qs.QBLOCK):
    """One packed fp32 bucket's hierarchical quantized all-reduce — the
    per-bucket body of :func:`quantized_multi_hop_pmean`, exposed so
    the backward-overlapped train step can sync each bucket as its
    cotangents materialize."""
    sizes = plan.traced_sizes()
    world = 1
    for s in sizes:
        world *= s
    _check_hier_blocks(h.shape[0], plan, block)
    length = h.shape[0]
    cur, q_shard, s_last = h, None, None
    for depth, ax in enumerate(reversed(plan.hop_axes)):  # fast->slow
        s_j, b_j = qs.block_scales(cur, ax, spec, block)
        q_j = qs.quantize(cur, s_j, b_j, spec, block)
        q_shard = jax.lax.psum_scatter(q_j, ax, scatter_dimension=0,
                                       tiled=True)
        s_last = s_j
        if depth + 1 == len(plan.hop_axes):
            break
        idx = jax.lax.axis_index(ax)
        length //= sizes[len(sizes) - 1 - depth]
        nb = length // block
        s_shard = jax.lax.dynamic_slice_in_dim(s_j, idx * nb, nb)
        cur = qs.dequantize(q_shard, s_shard, block)
    # mirrored gathers, payload still on the wire dtype; the fp32
    # last-hop scale vector rides the fast hops (~4/QBLOCK overhead)
    q_full = q_shard
    for ax in plan.hop_axes:  # slow -> fast
        q_full = jax.lax.all_gather(q_full, ax, axis=0, tiled=True)
    s_full = s_last
    for ax in plan.hop_axes[1:]:  # every axis the scales differ on
        s_full = jax.lax.all_gather(s_full, ax, axis=0, tiled=True)
    return qs.dequantize(q_full, s_full, block) * (1.0 / world)


# Two-level names, kept as the public spelling PR 12 shipped — they run
# the generalized multi-hop loops (a two-entry plan lowers the exact
# same op sequence as the original two-hop code).
two_hop_reduce_scatter = multi_hop_reduce_scatter
two_hop_all_gather = multi_hop_all_gather
quantized_two_hop_reduce_scatter = quantized_multi_hop_reduce_scatter
quantized_two_hop_pmean = quantized_multi_hop_pmean
