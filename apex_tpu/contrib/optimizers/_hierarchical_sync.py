"""Topology-aware hierarchical gradient sync: multi-hop reduce-scatter
over a ``(fast, slow)`` data-parallel axis split.

Ground paper: "DynamiQ: Accelerating Gradient Synchronization using
Compressed Multi-hop All-reduce" (PAPERS.md, arXiv 2602.08923) — at pod
scale the dp world spans interconnects with very different bandwidth
(ICI within a slice, DCN across slices), and a flat collective pays the
slow hop at the FULL payload.  The multi-hop form reduces intra-slice
first on the fast axis, so the cross-slice hop only ever carries the
already-scattered ``1/dp_inner`` chunk — and, on a compressed wire,
stays at the compressed dtype by requantizing the partial sums with
fresh shared scales and feeding the requantization error back into the
resident error-feedback residual channel (PR 6's machinery, reused).

The topology contract a :class:`HierarchicalSyncPlan` describes:

- ``(outer_axis, inner_axis)``: the dp world is the mesh product
  ``dp_outer x dp_inner``, ``inner`` fast (intra-slice), ``outer`` slow
  (cross-slice).  Both grad-sync hops run at the same wire dtype (the
  compressed dtype never widens on the slow hop — that is the point);
  the per-hop dtypes are recorded on the plan for the wire accounting.
- **shard ownership is unchanged vs the flat plan**: the two-hop
  scatter (inner tile ``i``, then outer sub-tile ``o``) lands flat
  chunk ``r = i * dp_outer + o`` on mesh rank ``(o, i)``, which is
  exactly the resident shard ``P((..., inner_axis, outer_axis))``
  assigns that rank.  Bucket totals use the ONE
  :func:`~apex_tpu.optimizers.bucketing.padded_total` formula with
  ``shard_pad = dp_outer * dp_inner``, so elastic checkpoints reshard
  across flat <-> hierarchical worlds with no special case.
- **param sync mirrors in reverse**: all-gather the updated shard over
  ``outer`` first (the slice-shared shard — cross-slice traffic is
  ``1/dp_inner`` of the bucket), then over ``inner``.

Quantized wire (int8/fp8), per bucket:

1. hop 1 (fast): shared per-block scales from an amax psum over
   ``inner`` ONLY, quantize ``h = g/scale + residual``, reduce-scatter
   the int8/fp8 payload over ``inner``; the hop-1 quantization error
   ``h - deq(q1)`` covers the full local bucket.
2. hop 2 (slow): dequantize the received chunk into fp32 partial sums,
   REQUANTIZE with fresh per-block shared scales (amax psum over
   ``outer`` ONLY), reduce-scatter over ``outer`` still at the wire
   dtype; the requantization error ``p - deq(q2)`` covers this rank's
   ``1/dp_inner`` chunk and is FOLDED into the same residual at the
   chunk's positions.

The telescoping identity is preserved exactly: with every rank's new
residual ``res1 + scatter(res2)``, the transmitted total per step is
``sum_r h_r - sum_r residual_r`` — what PR 6's error feedback needs —
so the crafted dyadic-scale test pins the two-hop chain bitwise
(``tests/test_distributed_optimizers.py``).

When two hops LOSE: a second hop adds a second (small) scale psum and a
second quantization, so for tiny buckets — where the fp32 scale vector
(~``4/QBLOCK`` of the payload) and the per-hop latency dominate — or
for meshes whose interconnect is flat (``dp_inner = 1``), the flat plan
is the better choice.  The win scales with ``dp_inner``: cross-slice
bytes drop by exactly ``1/dp_inner`` (scales included — the per-hop
accounting in :func:`~apex_tpu.contrib.optimizers._quantized_sync
.grad_sync_bytes` is exact, not a payload approximation).
"""

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.contrib.optimizers import _quantized_sync as qs

__all__ = [
    "HierarchicalSyncPlan", "hierarchical_plan",
    "two_hop_reduce_scatter", "two_hop_all_gather",
    "quantized_two_hop_reduce_scatter", "quantized_two_hop_pmean",
]


@dataclasses.dataclass(frozen=True)
class HierarchicalSyncPlan:
    """The ``(outer, inner)`` dp split one ZeRO optimizer syncs over.

    ``outer_axis`` is the SLOW hop (cross-slice, e.g. DCN), ``inner_axis``
    the FAST hop (intra-slice ICI); sizes are the mesh extents the plan
    was built for (the traced step re-reads them from the live mesh via
    ``lax.axis_size`` — a mismatch fails the state-shard check exactly
    like a flat world mismatch).  ``grad_wire_dtype``/``param_wire_dtype``
    record the per-hop wire dtypes for the accounting: both grad hops
    carry the SAME dtype (a compressed wire stays compressed on the slow
    hop), ``None`` means the per-bucket storage default."""

    outer_axis: str
    inner_axis: str
    outer_size: int
    inner_size: int
    grad_wire_dtype: Optional[str] = None
    param_wire_dtype: Optional[str] = None

    def __post_init__(self):
        if self.outer_axis == self.inner_axis:
            raise ValueError(
                f"hierarchical dp axes must be two DISTINCT mesh axes, got "
                f"({self.outer_axis!r}, {self.inner_axis!r})")
        if self.outer_size < 1 or self.inner_size < 1:
            raise ValueError(
                f"axis sizes must be >= 1, got outer={self.outer_size}, "
                f"inner={self.inner_size}")

    @property
    def axes(self) -> Tuple[str, str]:
        """``(outer, inner)`` — the step builder's dp_axis spelling."""
        return (self.outer_axis, self.inner_axis)

    @property
    def world(self) -> int:
        return self.outer_size * self.inner_size

    @property
    def shard_axes(self) -> Tuple[str, str]:
        """PartitionSpec order for the resident 1/dp shards: inner-major
        ``(inner, outer)`` places flat chunk ``i * dp_outer + o`` on mesh
        rank ``(o, i)`` — the chunk the two-hop scatter delivers there."""
        return (self.inner_axis, self.outer_axis)

    def zero_rank(self):
        """This rank's FLAT dp rank (traced): the index of the bucket
        chunk the two-hop scatter lands here.  Matches the flat plan's
        chunk-per-rank layout, so checkpoints reshard flat <->
        hierarchical through the one ``padded_total`` formula."""
        i = jax.lax.axis_index(self.inner_axis)
        o = jax.lax.axis_index(self.outer_axis)
        return i * jax.lax.axis_size(self.outer_axis) + o

    def traced_sizes(self) -> Tuple[int, int]:
        """``(outer, inner)`` extents of the LIVE mesh (static ints at
        trace time inside shard_map)."""
        return (jax.lax.axis_size(self.outer_axis),
                jax.lax.axis_size(self.inner_axis))


def hierarchical_plan(dp_axes, axis_sizes, grad_wire_dtype=None,
                      param_wire_dtype=None) -> HierarchicalSyncPlan:
    """Build the plan from the optimizer's ``dp_axes=(outer, inner)``
    knob plus the ``axis_sizes`` mapping ``init`` already takes."""
    axes = tuple(dp_axes)
    if len(axes) != 2 or not all(isinstance(a, str) for a in axes):
        raise ValueError(
            f"dp_axes must be two mesh axis names (outer, inner), got "
            f"{dp_axes!r}")
    missing = [a for a in axes if a not in (axis_sizes or {})]
    if missing:
        raise ValueError(
            f"hierarchical dp needs axis_sizes for both dp axes; missing "
            f"{missing} (pass axis_sizes={{{axes[0]!r}: outer, "
            f"{axes[1]!r}: inner, ...}} to init)")
    def _name(dt):
        return None if dt is None else jnp.dtype(dt).name
    return HierarchicalSyncPlan(
        outer_axis=axes[0], inner_axis=axes[1],
        outer_size=int(axis_sizes[axes[0]]),
        inner_size=int(axis_sizes[axes[1]]),
        grad_wire_dtype=_name(grad_wire_dtype),
        param_wire_dtype=_name(param_wire_dtype))


# ----------------------------------------------------------- wide wire
def two_hop_reduce_scatter(bucket, plan: HierarchicalSyncPlan):
    """The unquantized two-hop grad sync of one bucket (already in the
    wire dtype, fp16 predivide folded by the caller): reduce-scatter
    intra-slice on the fast axis, then cross-slice on the slow axis —
    the slow hop moves ``1/dp_inner`` of the bucket.  Returns this
    rank's flat 1/dp chunk of the dp-wide SUM."""
    a = jax.lax.psum_scatter(bucket, plan.inner_axis, scatter_dimension=0,
                             tiled=True)
    return jax.lax.psum_scatter(a, plan.outer_axis, scatter_dimension=0,
                                tiled=True)


def two_hop_all_gather(shard, plan: HierarchicalSyncPlan):
    """The mirrored param sync: gather the updated shard over the SLOW
    axis first (the slice-shared shard — cross-slice traffic is the
    ``1/dp_inner`` chunk), then over the fast axis.  Inverts the
    two-hop scatter's chunk order exactly, so the bucket reassembles in
    flat layout."""
    chunk = jax.lax.all_gather(shard, plan.outer_axis, axis=0, tiled=True)
    return jax.lax.all_gather(chunk, plan.inner_axis, axis=0, tiled=True)


# ------------------------------------------------------ quantized wire
def _check_hier_blocks(n: int, plan: HierarchicalSyncPlan,
                       block: int) -> None:
    if n % (block * plan.inner_size) or \
            (n // plan.inner_size) % (block * max(plan.outer_size, 1)):
        raise ValueError(
            f"bucket of {n} elements does not split into {block}-element "
            f"scale blocks per ({plan.outer_size}, {plan.inner_size}) "
            "hierarchical shard — bucket totals must be padded with "
            "bucketing.padded_total(shard_pad=dp_outer*dp_inner)")


def quantized_two_hop_reduce_scatter(h, plan: HierarchicalSyncPlan,
                                     spec: qs.QSpec, block: int = qs.QBLOCK):
    """The compressed two-hop grad sync of one bucket: returns
    ``(sum_shard_f32, residual_f32)`` where ``sum_shard_f32`` is this
    rank's flat 1/dp chunk of the dp-SUM (to the wire precision of BOTH
    hops) and ``residual_f32`` is the full-local-bucket error to carry:
    the hop-1 quantization error everywhere, PLUS the hop-2
    requantization error folded in at this rank's ``1/dp_inner`` chunk.

    Summed over ranks the new residuals satisfy
    ``sum_r transmitted = sum_r h_r - sum_r residual_r`` exactly — the
    same telescoping identity as the flat wire, so the resident
    error-feedback channel needs no layout change."""
    outer_sz, inner_sz = plan.traced_sizes()
    n = h.shape[0]
    _check_hier_blocks(n, plan, block)

    # hop 1 (fast, intra-slice): shared scales from the INNER amax psum
    s1, b1 = qs.block_scales(h, plan.inner_axis, spec, block)
    q1 = qs.quantize(h, s1, b1, spec, block)
    res1 = h - qs.dequantize(q1, s1, block)
    q1_shard = jax.lax.psum_scatter(q1, plan.inner_axis,
                                    scatter_dimension=0, tiled=True)
    i = jax.lax.axis_index(plan.inner_axis)
    chunk = n // inner_sz
    nb1 = chunk // block
    s1_shard = jax.lax.dynamic_slice_in_dim(s1, i * nb1, nb1)
    # fp32 partial sums of this slice: chunk i of sum_{inner} h
    p = qs.dequantize(q1_shard, s1_shard, block)

    # hop 2 (slow, cross-slice): REQUANTIZE the partial sums with fresh
    # shared scales from the OUTER amax psum only, keep the wire dtype
    s2, b2 = qs.block_scales(p, plan.outer_axis, spec, block)
    q2 = qs.quantize(p, s2, b2, spec, block)
    res2 = p - qs.dequantize(q2, s2, block)
    q2_shard = jax.lax.psum_scatter(q2, plan.outer_axis,
                                    scatter_dimension=0, tiled=True)
    o = jax.lax.axis_index(plan.outer_axis)
    sub = chunk // outer_sz
    nb2 = sub // block
    s2_shard = jax.lax.dynamic_slice_in_dim(s2, o * nb2, nb2)
    g_shard = qs.dequantize(q2_shard, s2_shard, block)

    # fold the requantization error into the residual at this rank's
    # chunk positions: sum_r residual_r = sum res1 + sum res2, exactly
    # the error the next step's feedback must replay
    r1_chunk = jax.lax.dynamic_slice_in_dim(res1, i * chunk, chunk)
    residual = jax.lax.dynamic_update_slice_in_dim(
        res1, r1_chunk + res2, i * chunk, 0)
    return g_shard, residual


def quantized_two_hop_pmean(grads, plan: HierarchicalSyncPlan,
                            spec: qs.QSpec, block: int = qs.QBLOCK):
    """Hierarchical quantized gradient all-reduce for the REPLICATED
    data-parallel path (the ``make_train_step(grad_sync_dtype=...)``
    knob over a ``(dp_out, dp_in)`` mesh): the two-hop reduce-scatter
    above, then the MIRRORED gathers — every payload hop at the wire
    dtype (the gathered partial sums are bounded by ``qmax`` per hop),
    plus the small fp32 hop-2 scale gather the dequantize needs (hop-2
    scales are chunk-local: shared over ``outer``, distinct per
    ``inner`` rank).

    Stateless like :func:`~apex_tpu.contrib.optimizers._quantized_sync
    .quantized_pmean`: no optimizer-state channel means no
    error-feedback residual — ZeRO with ``dp_axes=`` is the compressed
    hierarchical path WITH feedback."""
    from apex_tpu.optimizers import bucketing

    outer_sz, inner_sz = plan.traced_sizes()
    world = outer_sz * inner_sz
    tree_plan = bucketing.plan_of(grads, shard_pad=world)
    leaves = jax.tree.leaves(grads)
    out = []
    for b in tree_plan.buckets:
        h = bucketing.pack_bucket(b, leaves, jnp.float32)
        _check_hier_blocks(h.shape[0], plan, block)
        s1, b1 = qs.block_scales(h, plan.inner_axis, spec, block)
        q1 = qs.quantize(h, s1, b1, spec, block)
        q1_shard = jax.lax.psum_scatter(q1, plan.inner_axis,
                                        scatter_dimension=0, tiled=True)
        i = jax.lax.axis_index(plan.inner_axis)
        chunk = h.shape[0] // inner_sz
        nb1 = chunk // block
        s1_shard = jax.lax.dynamic_slice_in_dim(s1, i * nb1, nb1)
        p = qs.dequantize(q1_shard, s1_shard, block)
        s2, b2 = qs.block_scales(p, plan.outer_axis, spec, block)
        q2 = qs.quantize(p, s2, b2, spec, block)
        q2_shard = jax.lax.psum_scatter(q2, plan.outer_axis,
                                        scatter_dimension=0, tiled=True)
        # mirrored gathers, payload still on the wire dtype; the fp32
        # hop-2 scale vector rides the fast hop (~4/QBLOCK overhead)
        q2_chunk = jax.lax.all_gather(q2_shard, plan.outer_axis, axis=0,
                                      tiled=True)
        q_full = jax.lax.all_gather(q2_chunk, plan.inner_axis, axis=0,
                                    tiled=True)
        s2_full = jax.lax.all_gather(s2, plan.inner_axis, axis=0,
                                     tiled=True)
        out.append(qs.dequantize(q_full, s2_full, block) * (1.0 / world))
    return bucketing.unpack(tree_plan, out)


