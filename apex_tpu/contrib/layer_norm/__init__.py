"""Fast LayerNorm (reference: ``apex/contrib/layer_norm/layer_norm.py:8``
— tuned persistent kernels for specific hidden sizes).  The fused norm
covers all sizes on TPU; re-exported under the contrib name."""

from apex_tpu.normalization import FusedLayerNorm as FastLayerNorm
from apex_tpu.normalization import fused_layer_norm_affine as fast_layer_norm

__all__ = ["FastLayerNorm", "fast_layer_norm"]
