from apex_tpu.contrib.multihead_attn.self_multihead_attn import (
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
)

__all__ = ["SelfMultiheadAttn", "EncdecMultiheadAttn"]
