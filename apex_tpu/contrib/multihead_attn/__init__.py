from apex_tpu.contrib.multihead_attn.mask_softmax_dropout_func import (
    fast_mask_softmax_dropout_func,
)
from apex_tpu.contrib.multihead_attn.self_multihead_attn import (
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
)

__all__ = [
    "SelfMultiheadAttn",
    "EncdecMultiheadAttn",
    "fast_mask_softmax_dropout_func",
]
