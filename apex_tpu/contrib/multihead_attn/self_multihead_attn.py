"""Fast multihead attention modules.

Reference: ``apex/contrib/multihead_attn`` — ``SelfMultiheadAttn`` /
``EncdecMultiheadAttn`` with fused QKV GEMM + softmax + dropout + output
projection, optional pre-LN + residual-add fusion
(``fast_*_norm_add_func.py``).

TPU: one jit region — QKV projection dots hit the MXU, the attention
core is flash attention, and the norm/residual variants fuse
automatically.  Layout matches the reference: inputs ``(seq, batch,
hidden)``.
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.normalization import fused_layer_norm_affine
from apex_tpu.ops.attention import flash_attention


class SelfMultiheadAttn(nn.Module):
    """Parity with ``SelfMultiheadAttn(hidden, heads, dropout, bias,
    include_norm_add, impl)``."""

    hidden_size: int
    num_heads: int
    dropout: float = 0.0
    use_bias: bool = True
    include_norm_add: bool = False
    impl: str = "fast"
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, query, key_padding_mask=None, *, causal: bool = False, train: bool = True):
        """``key_padding_mask``: (B, S) with True/1 = PAD (torch
        convention, reference self_multihead_attn.py:144); padded keys
        are excluded from the softmax via the flash kernel's mask."""
        S, B, H = query.shape
        nh = self.num_heads
        hd = H // nh

        residual = query
        if self.include_norm_add:
            ln_w = self.param("lyr_nrm_gamma_weights", nn.initializers.ones, (H,), jnp.float32)
            ln_b = self.param("lyr_nrm_beta_weights", nn.initializers.zeros, (H,), jnp.float32)
            query = fused_layer_norm_affine(query, ln_w, ln_b, (H,), 1e-5)

        w_qkv = self.param(
            "input_weights", nn.initializers.lecun_normal(), (3 * H, H), self.param_dtype
        )
        b_qkv = (
            self.param("input_biases", nn.initializers.zeros, (3 * H,), self.param_dtype)
            if self.use_bias
            else None
        )
        w_out = self.param(
            "output_weights", nn.initializers.lecun_normal(), (H, H), self.param_dtype
        )
        b_out = (
            self.param("output_biases", nn.initializers.zeros, (H,), self.param_dtype)
            if self.use_bias
            else None
        )

        qkv = jnp.matmul(query, w_qkv.T.astype(query.dtype))
        if b_qkv is not None:
            qkv = qkv + b_qkv.astype(qkv.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):  # (S,B,H) → (B,nh,S,hd)
            return t.reshape(S, B, nh, hd).transpose(1, 2, 0, 3)

        kv_mask = None if key_padding_mask is None else ~key_padding_mask.astype(bool)
        ctx = flash_attention(heads(q), heads(k), heads(v), causal=causal,
                              kv_mask=kv_mask)
        ctx = ctx.transpose(2, 0, 1, 3).reshape(S, B, H)

        if train and self.dropout > 0:
            ctx = nn.Dropout(rate=self.dropout, deterministic=False)(ctx)

        out = jnp.matmul(ctx, w_out.T.astype(ctx.dtype))
        if b_out is not None:
            out = out + b_out.astype(out.dtype)
        if self.include_norm_add:
            out = out + residual.astype(out.dtype)
        return out


class EncdecMultiheadAttn(nn.Module):
    """Cross attention: q from decoder, k/v from encoder (reference
    encdec_multihead_attn.py — incl. ``bias`` and ``include_norm_add``
    pre-LN + residual-add fusion, encdec_multihead_attn.py:27-63)."""

    hidden_size: int
    num_heads: int
    dropout: float = 0.0
    use_bias: bool = False
    include_norm_add: bool = False
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, query, key, key_padding_mask=None, *, train: bool = True):
        """``key_padding_mask``: (B, Sk), True/1 = PAD (torch
        convention) — masks encoder keys (reference
        encdec_multihead_attn.py)."""
        S, B, H = query.shape
        Sk = key.shape[0]
        nh = self.num_heads
        hd = H // nh

        residual = query
        if self.include_norm_add:
            ln_w = self.param("lyr_nrm_gamma_weights", nn.initializers.ones, (H,), jnp.float32)
            ln_b = self.param("lyr_nrm_beta_weights", nn.initializers.zeros, (H,), jnp.float32)
            query = fused_layer_norm_affine(query, ln_w, ln_b, (H,), 1e-5)

        w_q = self.param("q_weights", nn.initializers.lecun_normal(), (H, H), self.param_dtype)
        w_kv = self.param("kv_weights", nn.initializers.lecun_normal(), (2 * H, H), self.param_dtype)
        w_out = self.param("output_weights", nn.initializers.lecun_normal(), (H, H), self.param_dtype)

        q = jnp.matmul(query, w_q.T.astype(query.dtype))
        kv = jnp.matmul(key, w_kv.T.astype(key.dtype))
        if self.use_bias:
            b_q = self.param("q_biases", nn.initializers.zeros, (H,), self.param_dtype)
            b_kv = self.param("kv_biases", nn.initializers.zeros, (2 * H,), self.param_dtype)
            q = q + b_q.astype(q.dtype)
            kv = kv + b_kv.astype(kv.dtype)
        k, v = jnp.split(kv, 2, axis=-1)

        def heads(t, s):
            return t.reshape(s, B, nh, hd).transpose(1, 2, 0, 3)

        kv_mask = None if key_padding_mask is None else ~key_padding_mask.astype(bool)
        ctx = flash_attention(heads(q, S), heads(k, Sk), heads(v, Sk), causal=False,
                              kv_mask=kv_mask)
        ctx = ctx.transpose(2, 0, 1, 3).reshape(S, B, H)
        if train and self.dropout > 0:
            ctx = nn.Dropout(rate=self.dropout, deterministic=False)(ctx)
        out = jnp.matmul(ctx, w_out.T.astype(ctx.dtype))
        if self.use_bias:
            b_out = self.param("output_biases", nn.initializers.zeros, (H,), self.param_dtype)
            out = out + b_out.astype(out.dtype)
        if self.include_norm_add:
            out = out + residual.astype(out.dtype)
        return out
