"""Fused ResNet bottleneck block + spatial-parallel variant.

Reference: ``apex/contrib/bottleneck/bottleneck.py`` — ``Bottleneck``
(cuDNN-v8 fused conv+frozen-BN+ReLU chain for Mask-RCNN-style training
where BN is frozen and folded into per-channel scale/bias) and
``SpatialBottleneck`` (same block with the H dimension sharded across
GPUs, exchanging 1-row halos before each 3x3 conv via
``halo_exchangers.py:11-127``).

TPU-native: NHWC convs (XLA fuses the scale/bias/ReLU epilogues into the
convolution, which is what the cuDNN-frontend graph does by hand), bf16
compute with fp32 folded-BN parameters, and the spatial variant rides
:func:`~apex_tpu.contrib.bottleneck.halo_exchangers.halo_exchange_1d`
(one ppermute pair) instead of CUDA-IPC peer memory.
"""

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.contrib.bottleneck.halo_exchangers import halo_exchange_1d


class FrozenScaleBias(nn.Module):
    """Folded frozen BatchNorm: per-channel ``y = x*scale + bias``
    (reference folds frozen-BN running stats into conv epilogues)."""

    features: int

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (self.features,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (self.features,), jnp.float32)
        return (x.astype(jnp.float32) * scale + bias).astype(x.dtype)


class Bottleneck(nn.Module):
    """Fused 1x1 → 3x3 → 1x1 bottleneck with frozen-BN epilogues
    (reference contrib/bottleneck/bottleneck.py ``Bottleneck``).

    NHWC input.  ``use_cudnn``/``explicit_nhwc`` flags from the reference
    are layout/backend toggles with no TPU meaning and are accepted as
    no-ops for signature parity.
    """

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    stride: int = 1
    dtype: Any = jnp.bfloat16
    use_cudnn: bool = False  # parity no-op
    explicit_nhwc: bool = True  # parity no-op (NHWC is the only layout)

    @nn.compact
    def __call__(self, x):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32)
        y = conv(self.bottleneck_channels, (1, 1))(x)
        y = FrozenScaleBias(self.bottleneck_channels)(y)
        y = nn.relu(y)
        y = conv(
            self.bottleneck_channels, (3, 3), strides=(self.stride, self.stride)
        )(y)
        y = FrozenScaleBias(self.bottleneck_channels)(y)
        y = nn.relu(y)
        y = conv(self.out_channels, (1, 1))(y)
        y = FrozenScaleBias(self.out_channels)(y)
        if self.stride != 1 or self.in_channels != self.out_channels:
            residual = conv(
                self.out_channels, (1, 1), strides=(self.stride, self.stride)
            )(x)
            residual = FrozenScaleBias(self.out_channels)(residual)
        else:
            residual = x
        return nn.relu(y + residual.astype(y.dtype))


class SpatialBottleneck(nn.Module):
    """Bottleneck with H sharded over a mesh axis (reference
    ``SpatialBottleneck``): halo-exchange one row with ring neighbors
    before the 3x3 conv, convolve VALID over the padded rows.

    Call inside ``shard_map`` with the input's H dimension split along
    ``axis_name``.  Only stride 1 is supported for the spatial conv, as
    in the reference's Mask-RCNN deployment.
    """

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    stride: int = 1
    axis_name: str = "spatial"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        if self.stride != 1:
            raise NotImplementedError("spatial halo exchange requires stride 1")
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32)
        y = conv(self.bottleneck_channels, (1, 1))(x)
        y = FrozenScaleBias(self.bottleneck_channels)(y)
        y = nn.relu(y)
        # 3x3 over halo-padded local shard: pad W with zeros (SAME), H by
        # neighbor exchange, then convolve VALID so output H == local H.
        y = halo_exchange_1d(y, halo=1, axis_name=self.axis_name, spatial_axis=1)
        y = jnp.pad(y, ((0, 0), (0, 0), (1, 1), (0, 0)))
        y = conv(self.bottleneck_channels, (3, 3), padding="VALID")(y)
        y = FrozenScaleBias(self.bottleneck_channels)(y)
        y = nn.relu(y)
        y = conv(self.out_channels, (1, 1))(y)
        y = FrozenScaleBias(self.out_channels)(y)
        if self.in_channels != self.out_channels:
            residual = conv(self.out_channels, (1, 1))(x)
            residual = FrozenScaleBias(self.out_channels)(residual)
        else:
            residual = x
        return nn.relu(y + residual.astype(y.dtype))
