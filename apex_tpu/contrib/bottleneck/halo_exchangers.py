"""Spatial-parallel halo exchange.

Reference: ``apex/contrib/bottleneck/halo_exchangers.py:11-127``
(``HaloExchangerAllGather``/``SendRecv``/``Peer``) +
``peer_memory/peer_halo_exchanger_1d.py`` — CNNs with the spatial (H)
dimension split across GPUs exchange boundary rows with neighbors via
NCCL p2p or CUDA-IPC peer memory.

TPU: neighbor exchange over a mesh axis is one ``ppermute`` pair riding
ICI neighbor links — the exact communication pattern peer memory
emulates on NVLink.  Edge ranks keep zero halos (same as the
reference's non-periodic boundary handling).
"""

from typing import Tuple

import jax
import jax.numpy as jnp


def halo_exchange_1d(x, halo: int, axis_name: str, spatial_axis: int = 1):
    """Exchange ``halo`` rows with ring neighbors along ``spatial_axis``.

    x: local NHWC shard (split along H).  Returns x padded with the
    received halos: shape grows by 2*halo along ``spatial_axis``.
    """
    n = jax.lax.axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)

    top = jax.lax.slice_in_dim(x, 0, halo, axis=spatial_axis)
    bot = jax.lax.slice_in_dim(x, x.shape[spatial_axis] - halo, x.shape[spatial_axis], axis=spatial_axis)

    fwd = [(i, (i + 1) % n) for i in range(n)]
    bwd = [(i, (i - 1) % n) for i in range(n)]
    from_above = jax.lax.ppermute(bot, axis_name, fwd)  # neighbor above's bottom rows
    from_below = jax.lax.ppermute(top, axis_name, bwd)  # neighbor below's top rows

    # zero halos at the non-periodic boundary (reference edge handling)
    from_above = jnp.where(rank == 0, jnp.zeros_like(from_above), from_above)
    from_below = jnp.where(rank == n - 1, jnp.zeros_like(from_below), from_below)
    return jnp.concatenate([from_above, x, from_below], axis=spatial_axis)


class HaloExchanger:
    """Object parity with the reference exchangers; one implementation
    (ppermute) covers AllGather/SendRecv/Peer — they differ only in the
    NCCL/IPC transport."""

    def __init__(self, axis_name: str, halo: int = 1, spatial_axis: int = 1):
        self.axis_name = axis_name
        self.halo = halo
        self.spatial_axis = spatial_axis

    def __call__(self, x):
        return halo_exchange_1d(x, self.halo, self.axis_name, self.spatial_axis)


# Reference class names (halo_exchangers.py:11-127).  On GPU these pick a
# transport (NCCL allgather vs send/recv vs CUDA-IPC peer memory); on TPU
# every neighbor exchange is the same ppermute over ICI, so they are one
# implementation under three names.
class HaloExchangerAllGather(HaloExchanger):
    pass


class HaloExchangerSendRecv(HaloExchanger):
    pass


class HaloExchangerPeer(HaloExchanger):
    def __init__(self, axis_name: str, halo: int = 1, spatial_axis: int = 1, peer_pool=None):
        # peer_pool (a PeerMemoryPool on GPU) has no TPU role; accepted
        # for signature parity.
        super().__init__(axis_name, halo, spatial_axis)
