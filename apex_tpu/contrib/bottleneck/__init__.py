from apex_tpu.contrib.bottleneck.bottleneck import (
    Bottleneck,
    FrozenScaleBias,
    SpatialBottleneck,
)
from apex_tpu.contrib.bottleneck.halo_exchangers import (
    HaloExchanger,
    HaloExchangerAllGather,
    HaloExchangerPeer,
    HaloExchangerSendRecv,
    halo_exchange_1d,
)

__all__ = [
    "Bottleneck",
    "FrozenScaleBias",
    "SpatialBottleneck",
    "HaloExchanger",
    "HaloExchangerAllGather",
    "HaloExchangerPeer",
    "HaloExchangerSendRecv",
    "halo_exchange_1d",
]
