from apex_tpu.contrib.bottleneck.halo_exchangers import (
    HaloExchanger,
    halo_exchange_1d,
)

__all__ = ["HaloExchanger", "halo_exchange_1d"]
