"""Fused Conv+Bias(+Mask)+ReLU.

Reference: ``apex/contrib/conv_bias_relu/conv_bias_relu.py:12-78``
(cuDNN-frontend fused graphs).  XLA fuses the conv epilogue natively;
these are the callable composites with the reference's names.  NHWC
layout (TPU conv layout); weights (KH, KW, Cin, Cout).
"""

import jax
import jax.numpy as jnp


def _conv(x, w, stride, padding):
    return jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def ConvBias(x, weight, bias, stride: int = 1, padding="SAME"):
    return _conv(x, weight, stride, padding) + bias


def ConvBiasReLU(x, weight, bias, stride: int = 1, padding="SAME"):
    return jax.nn.relu(ConvBias(x, weight, bias, stride, padding))


def ConvBiasMaskReLU(x, weight, bias, mask, stride: int = 1, padding="SAME"):
    return jax.nn.relu(ConvBias(x, weight, bias, stride, padding) * mask)


def ConvFrozenScaleBiasReLU(x, weight, scale, bias, stride: int = 1, padding="SAME"):
    """relu(conv(x, w) * scale + bias) with scale/bias treated as frozen
    (no gradients — reference backward returns None for them,
    conv_bias_relu.py:96): the folded-BatchNorm inference fusion."""
    scale = jax.lax.stop_gradient(scale)
    bias = jax.lax.stop_gradient(bias)
    return jax.nn.relu(_conv(x, weight, stride, padding) * scale + bias)
