"""Group BatchNorm via cuDNN v8 (reference: ``apex/contrib/cudnn_gbn``).
TPU: same as :mod:`apex_tpu.contrib.groupbn` — SyncBatchNorm over a
subgroup mesh axis."""

from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC, GroupBatchNorm2d

GroupBatchNorm = GroupBatchNorm2d

__all__ = ["GroupBatchNorm", "GroupBatchNorm2d", "BatchNorm2d_NHWC"]
