"""Fused indexed multiply (OpenFold hot op).

Reference: ``apex/contrib/index_mul_2d`` — ``out[idx] = in1[idx] * in2``
fwd/bwd fused kernels.  One XLA gather+multiply fusion here; autodiff
produces the fused scatter backward.
"""

import jax.numpy as jnp


def index_mul_2d(in1, in2, idx):
    """in1 (N, D), idx (K,), in2 (K, D) → (K, D) = in1[idx] * in2."""
    return jnp.take(in1, idx, axis=0) * in2
