"""NHWC group BatchNorm with fused residual-add + ReLU.

Reference: ``apex/contrib/groupbn/batch_norm.py`` —
``BatchNorm2d_NHWC(num_features, fuse_relu, bn_group, ...)`` (:101) over
the ``bnp`` CUDA kernels (``bn_NHWC_impl`` :7, ``bn_addrelu_NHWC_impl``
:53): NHWC batchnorm whose statistics sync across a ``bn_group``-sized
subgroup of GPUs, with the residual add and ReLU fused into the BN
kernel (``forward(x, z)``).  Also the surface of
``apex/contrib/cudnn_gbn/batch_norm.py`` (``GroupBatchNorm2d``).

TPU form: one flax module.  NHWC is already the TPU-native conv layout;
the Welford/merge kernels collapse to f32 moment math + ``pmean`` with
``axis_index_groups`` partitioning the dp axis into ``bn_group``-sized
blocks (the ``create_syncbn_process_group`` pattern); add+ReLU fuse into
the same XLA fusion as the normalization, and the ReLU backward masking
falls out of autodiff.
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import DATA_AXIS


def _group_partition(world: int, bn_group: int):
    """[[0..g-1], [g..2g-1], ...] — the subgroup layout of
    ``create_syncbn_process_group`` (apex/parallel/__init__.py:60)."""
    if world % bn_group:
        raise ValueError(f"bn_group {bn_group} must divide world size {world}")
    return [list(range(i, i + bn_group)) for i in range(0, world, bn_group)]


class BatchNorm2d_NHWC(nn.Module):
    """NHWC BN; ``__call__(x, z=None)`` fuses ``relu(bn(x) + z)`` when
    ``fuse_relu`` (reference :196 ``forward(x, z)``)."""

    num_features: int
    eps: float = 1e-5
    momentum: float = 0.1
    fuse_relu: bool = False
    bn_group: int = 1
    axis_name: Optional[str] = DATA_AXIS

    @nn.compact
    def __call__(self, x, z=None, use_running_average: bool = False):
        C = self.num_features
        scale = self.param("scale", nn.initializers.ones, (C,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (C,), jnp.float32)
        ra_mean = self.variable("batch_stats", "running_mean",
                                lambda: jnp.zeros((C,), jnp.float32))
        ra_var = self.variable("batch_stats", "running_var",
                               lambda: jnp.ones((C,), jnp.float32))

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=(0, 1, 2))
            sq = jnp.mean(xf * xf, axis=(0, 1, 2))
            if (self.axis_name is not None and self.bn_group > 1
                    and not self.is_initializing()):
                world = jax.lax.axis_size(self.axis_name)
                groups = _group_partition(world, self.bn_group)
                mean = jax.lax.pmean(mean, self.axis_name, axis_index_groups=groups)
                sq = jax.lax.pmean(sq, self.axis_name, axis_index_groups=groups)
            var = sq - mean * mean
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = (1 - m) * ra_mean.value + m * mean
                ra_var.value = (1 - m) * ra_var.value + m * var

        inv = jax.lax.rsqrt(var + self.eps)
        y = (x.astype(jnp.float32) - mean) * inv * scale + bias
        if z is not None:
            y = y + z.astype(jnp.float32)
        if self.fuse_relu:
            y = jax.nn.relu(y)
        return y.astype(x.dtype)


class GroupBatchNorm2d(BatchNorm2d_NHWC):
    """cudnn_gbn surface (apex/contrib/cudnn_gbn/batch_norm.py:44) —
    identical semantics, ``group_size`` vocabulary."""
