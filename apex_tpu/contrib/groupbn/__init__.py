"""Group BatchNorm (reference: ``apex/contrib/groupbn`` and
``apex/contrib/cudnn_gbn`` — NHWC BN with stats synced across a GPU
subgroup).  On TPU this is :class:`apex_tpu.parallel.SyncBatchNorm` with
``channel_last=True`` and the axis restricted to the subgroup mesh axis;
re-exported under the contrib names."""

from functools import partial

from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm

BatchNorm2d_NHWC = partial(SyncBatchNorm, channel_last=True)
GroupBatchNorm2d = partial(SyncBatchNorm, channel_last=True)

__all__ = ["BatchNorm2d_NHWC", "GroupBatchNorm2d"]
