"""Group BatchNorm (reference: ``apex/contrib/groupbn`` and
``apex/contrib/cudnn_gbn`` — NHWC BN with stats synced across a GPU
subgroup, fused residual-add + ReLU)."""

from apex_tpu.contrib.groupbn.batch_norm import BatchNorm2d_NHWC, GroupBatchNorm2d

__all__ = ["BatchNorm2d_NHWC", "GroupBatchNorm2d"]
