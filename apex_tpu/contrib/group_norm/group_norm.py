"""NHWC GroupNorm with optional fused SiLU.

Reference: ``apex/contrib/group_norm/group_norm.py:44-127`` over NHWC
one-pass/two-pass CUDA kernels (diffusion workloads).  NHWC is the TPU
conv layout already; stats in fp32; SiLU fuses into the same pass.
"""


import flax.linen as nn
import jax
import jax.numpy as jnp


def group_norm_nhwc(x, num_groups: int, weight=None, bias=None, eps: float = 1e-5, act: str = ""):
    """x (N, H, W, C); groups over C.  act in {"", "silu"}."""
    N, H, W, C = x.shape
    G = num_groups
    xf = x.astype(jnp.float32).reshape(N, H, W, G, C // G)
    mean = jnp.mean(xf, axis=(1, 2, 4), keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=(1, 2, 4), keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    y = y.reshape(N, H, W, C)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    if act == "silu":
        y = y * jax.nn.sigmoid(y)
    return y.astype(x.dtype)


# alias matching the reference extension's entry point name
cuda_group_norm_nhwc_forward = group_norm_nhwc


class GroupNorm(nn.Module):
    """Module parity with ``apex.contrib.group_norm.GroupNorm``."""

    num_groups: int
    num_channels: int
    eps: float = 1e-5
    affine: bool = True
    act: str = ""

    @nn.compact
    def __call__(self, x):
        w = b = None
        if self.affine:
            w = self.param("weight", nn.initializers.ones, (self.num_channels,), jnp.float32)
            b = self.param("bias", nn.initializers.zeros, (self.num_channels,), jnp.float32)
        return group_norm_nhwc(x, self.num_groups, w, b, self.eps, self.act)
