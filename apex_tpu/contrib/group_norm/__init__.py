from apex_tpu.contrib.group_norm.group_norm import GroupNorm, cuda_group_norm_nhwc_forward

__all__ = ["GroupNorm", "cuda_group_norm_nhwc_forward"]
