from apex_tpu.contrib.fmha.fmha import fmha

__all__ = ["fmha"]
