from apex_tpu.contrib.fmha.fmha import fmha, fmha_varlen

__all__ = ["fmha", "fmha_varlen"]
