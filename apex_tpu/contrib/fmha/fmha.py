"""FMHA — fused multi-head attention (BERT-style, padding masks).

Reference: ``apex/contrib/fmha/fmha.py:33-60`` (``fmhalib``): flash-style
fused attention for seqlen ≤ 512 with varlen/padding support via
cu_seqlens.

TPU form: :func:`apex_tpu.ops.attention.flash_attention` with the
key-padding mask folded into the flash kernel's online softmax (no 512
limit, no dense S×S score matrix for padded batches).  Interface takes a
dense padded batch + boolean key-padding mask instead of packed
cu_seqlens: packed ragged layouts are hostile to XLA's static shapes,
while a dense mask rides the same blockwise kernel at full speed.
"""

from typing import Optional

import jax.numpy as jnp

from apex_tpu.ops.attention import flash_attention


def fmha_varlen(qkv_packed, cu_seqlens, max_s: int, causal: bool = False,
                softmax_scale=None):
    """Packed-varlen interface matching the reference call shape
    (``FMHAFun(qkv, cu_seqlens, p_dropout, max_s, ...)``,
    ``apex/contrib/fmha/fmha.py:33-60``).

    ``qkv_packed``: (total_tokens, 3, H, D) — all sequences concatenated;
    ``cu_seqlens``: (B+1,) int32 cumulative sequence starts;
    ``max_s``: static max sequence length (the dense padding width).

    The packed layout is unpacked to a dense (B, max_s) batch + validity
    mask (static shapes for XLA), run through the masked flash kernel,
    and repacked — same numerics as the reference's ragged kernel, and
    the pack/unpack gathers fuse into the surrounding program.
    """
    B = cu_seqlens.shape[0] - 1
    total = qkv_packed.shape[0]
    seqlens = cu_seqlens[1:] - cu_seqlens[:-1]  # (B,)

    pos = jnp.arange(max_s)
    idx = cu_seqlens[:-1, None] + pos[None, :]           # (B, max_s)
    valid = pos[None, :] < seqlens[:, None]              # (B, max_s)
    dense = jnp.take(qkv_packed, jnp.clip(idx, 0, total - 1), axis=0)
    dense = jnp.where(valid[..., None, None, None], dense, 0)

    out_dense = fmha(dense, key_padding_mask=valid, causal=causal,
                     softmax_scale=softmax_scale)       # (B, max_s, H, D)

    # repack: token t belongs to sequence b(t), offset t - cu_seqlens[b]
    t = jnp.arange(total)
    b_of_t = jnp.searchsorted(cu_seqlens, t, side="right") - 1
    i_of_t = t - jnp.take(cu_seqlens, b_of_t)
    return out_dense[b_of_t, i_of_t]


def fmha(qkv, key_padding_mask: Optional[jnp.ndarray] = None, causal: bool = False, softmax_scale=None):
    """qkv: (B, S, 3, H, D) packed as in the reference; returns (B, S, H, D).

    ``key_padding_mask``: (B, S) bool, True = valid token.  Padded keys
    are excluded from every row's softmax inside the flash kernel, and
    padded query rows are zeroed on the way out (matching the packed
    varlen semantics of the reference, where padding positions simply
    don't exist in the output).
    """
    q = qkv[:, :, 0].transpose(0, 2, 1, 3)  # (B,H,S,D)
    k = qkv[:, :, 1].transpose(0, 2, 1, 3)
    v = qkv[:, :, 2].transpose(0, 2, 1, 3)
    out = flash_attention(q, k, v, causal=causal, softmax_scale=softmax_scale,
                          kv_mask=key_padding_mask)
    if key_padding_mask is not None:
        out = out * key_padding_mask[:, None, :, None].astype(out.dtype)
    return out.transpose(0, 2, 1, 3)  # (B,S,H,D)
