"""FMHA — fused multi-head attention (BERT-style, padding masks).

Reference: ``apex/contrib/fmha/fmha.py:33-60`` (``fmhalib``): flash-style
fused attention for seqlen ≤ 512 with varlen/padding support via
cu_seqlens.

TPU form: :func:`apex_tpu.ops.attention.flash_attention` with a padding
mask folded in (no 512 limit).  Interface takes a dense padded batch +
boolean key-padding mask instead of packed cu_seqlens (packed layouts
are hostile to static shapes; padded+masked is the XLA idiom).
"""

from typing import Optional

import jax.numpy as jnp

from apex_tpu.ops.attention import NEG_INF, flash_attention, mha_reference


def fmha(qkv, key_padding_mask: Optional[jnp.ndarray] = None, causal: bool = False, softmax_scale=None):
    """qkv: (B, S, 3, H, D) packed as in the reference; returns (B, S, H, D).

    ``key_padding_mask``: (B, S) bool, True = valid token.
    """
    q = qkv[:, :, 0].transpose(0, 2, 1, 3)  # (B,H,S,D)
    k = qkv[:, :, 1].transpose(0, 2, 1, 3)
    v = qkv[:, :, 2].transpose(0, 2, 1, 3)
    if key_padding_mask is not None:
        # fold padding into k by pushing masked keys to -inf via a large
        # negative bias on their scores: implemented by zeroing v and
        # biasing k is fragile — instead mask scores through an additive
        # trick: set masked k rows to a huge negative value in the first
        # dim won't work either.  Use the dense path when padding masks
        # are present (seqlens here are ≤512-class workloads).
        s_mask = ~key_padding_mask[:, None, None, :]  # (B,1,1,S) True=masked
        import jax

        scale = softmax_scale if softmax_scale is not None else 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
        if causal:
            S = s.shape[-1]
            s = jnp.where(jnp.tril(jnp.ones((S, S), bool)), s, NEG_INF)
        s = jnp.where(s_mask, NEG_INF, s)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(qkv.dtype)
    else:
        out = flash_attention(q, k, v, causal=causal, softmax_scale=softmax_scale)
    return out.transpose(0, 2, 1, 3)  # (B,S,H,D)
