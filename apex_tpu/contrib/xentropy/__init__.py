from apex_tpu.contrib.xentropy.softmax_xentropy import SoftmaxCrossEntropyLoss, softmax_xentropy

__all__ = ["SoftmaxCrossEntropyLoss", "softmax_xentropy"]
