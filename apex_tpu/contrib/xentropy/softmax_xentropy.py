"""Fused softmax cross entropy with label smoothing.

Reference: ``apex/contrib/xentropy/softmax_xentropy.py:6``
(``SoftmaxCrossEntropyLoss``) over ``apex/contrib/csrc/xentropy`` — a
fused kernel computing loss and saving the softmax for backward.

TPU: one fusion; ``custom_vjp`` saves the (log-)softmax so backward is a
single fused ``softmax - smoothed_onehot`` pass, exactly the kernel's
residual strategy.
"""

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def softmax_xentropy(logits, labels, smoothing: float = 0.0, half_to_float: bool = False):
    """Per-sample loss; logits (N, C), labels (N,).

    With smoothing s: loss = (1-s)*nll(target) + s*mean_c(nll(c)).
    """
    loss, _ = _fwd_math(logits, labels, smoothing)
    return loss


def _fwd_math(logits, labels, smoothing):
    x = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(x, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
    if smoothing > 0:
        smooth_loss = -jnp.mean(logp, axis=-1)
        loss = (1.0 - smoothing) * nll + smoothing * smooth_loss
    else:
        loss = nll
    return loss, logp


def _xent_fwd(logits, labels, smoothing, half_to_float):
    loss, logp = _fwd_math(logits, labels, smoothing)
    dtype_token = jnp.zeros((0,), logits.dtype)  # carries the input dtype
    return loss, (logp, labels, dtype_token)


def _xent_bwd(smoothing, half_to_float, res, g):
    logp, labels, dtype_token = res
    dt = dtype_token.dtype
    n, c = logp.shape
    softmax = jnp.exp(logp)
    onehot = jax.nn.one_hot(labels, c, dtype=jnp.float32)
    grad = softmax - (1.0 - smoothing) * onehot - smoothing / c
    grad = grad * g[:, None]
    return grad.astype(dt), None


softmax_xentropy.defvjp(_xent_fwd, _xent_bwd)


class SoftmaxCrossEntropyLoss:
    """Class-form parity with the reference's autograd Function wrapper."""

    @staticmethod
    def apply(logits, labels, smoothing=0.0, padding_idx=0, half_to_float=False):
        return softmax_xentropy(logits, labels, smoothing, half_to_float)
