"""Fused gradient clipping.

Reference: ``apex/contrib/clip_grad/clip_grad.py:16`` —
``clip_grad_norm_`` via ``multi_tensor_l2norm`` + ``multi_tensor_scale``.

Functional: returns ``(clipped_grads, total_norm)`` instead of mutating.
Supports ``norm_type`` 2.0 and inf like the reference.
"""


import jax
import jax.numpy as jnp

from apex_tpu.ops.multi_tensor import multi_tensor_l2norm


def clip_grad_norm_(grads, max_norm: float, norm_type: float = 2.0, error_if_nonfinite: bool = False):
    leaves = jax.tree.leaves(grads)
    if not leaves:
        return grads, jnp.float32(0.0)
    if norm_type == 2.0:
        total_norm = multi_tensor_l2norm(grads)
    elif norm_type in (float("inf"), jnp.inf):
        total_norm = jnp.max(jnp.stack([jnp.max(jnp.abs(g.astype(jnp.float32))) for g in leaves]))
    else:
        total_norm = jnp.power(
            jnp.stack(
                [jnp.sum(jnp.power(jnp.abs(g.astype(jnp.float32)), norm_type)) for g in leaves]
            ).sum(),
            1.0 / norm_type,
        )
    # torch semantics: clip_coef = max_norm / (total_norm + 1e-6), applied only when < 1
    clip_coef = jnp.minimum(max_norm / (total_norm + 1e-6), 1.0)
    clipped = jax.tree.map(lambda g: (g.astype(jnp.float32) * clip_coef).astype(g.dtype), grads)
    return clipped, total_norm
