"""LARC — layerwise adaptive rate control.

Reference: ``apex/parallel/LARC.py:5-107``: wraps any optimizer; before
``step`` it rescales each param's grad so the effective lr is
``min(lr, trust_coefficient * ||p|| / (||g|| + wd*||p|| + eps))`` (clip
mode) or the adaptive lr outright (scale mode).  Weight decay is folded
into the grad when active (LARC.py:98-104).
"""


import jax
import jax.numpy as jnp


class LARC:
    """Wrap an apex_tpu optimizer: ``LARC(FusedSGD(...))``.

    Matches reference semantics: per-tensor adaptive lr computed in fp32;
    params with zero norm (or zero grad norm) keep the base lr.
    """

    def __init__(self, optimizer, trust_coefficient: float = 0.02, clip: bool = True, eps: float = 1e-8):
        self.optim = optimizer
        self.trust_coefficient = trust_coefficient
        self.clip = clip
        self.eps = eps

    def init(self, params):
        return self.optim.init(params)

    def update(self, grads, state, params, lr=None, **kw):
        base_lr = self.optim.lr if lr is None else lr
        wd = self.optim.weight_decay

        def adjust(g, p):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            p_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
            g_norm = jnp.sqrt(jnp.sum(jnp.square(g32)))
            adaptive_lr = (
                self.trust_coefficient * p_norm / (g_norm + p_norm * wd + self.eps)
            )
            if self.clip:
                # reference LARC.py:92: adaptive_lr = min(adaptive_lr/lr, 1)
                scale = jnp.minimum(adaptive_lr / base_lr, 1.0)
            else:
                scale = adaptive_lr
            # zero-norm params are left completely untouched (LARC.py:89)
            ok = (p_norm != 0) & (g_norm != 0)
            g_out = jnp.where(ok, (g32 + wd * p32) * scale, g32)
            return g_out

        adj = jax.tree.map(adjust, grads, params)
        # the inner optimizer must not re-apply weight decay (LARC.py:98-104
        # zeroes group wd); emulate by a wd=0 shadow for the inner update.
        saved_wd = self.optim.weight_decay
        try:
            self.optim.weight_decay = 0.0
            return self.optim.update(adj, state, params, lr=base_lr, **kw)
        finally:
            self.optim.weight_decay = saved_wd
